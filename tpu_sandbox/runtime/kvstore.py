"""Python face of the native TCP KV store (rendezvous/coordination).

Role parity with torch's TCPStore behind env:// rendezvous (reference
test_init.py:76-91, SURVEY §2.3): rank-0 hosts the store, every rank
connects, and coordination primitives (key exchange, counters, barriers)
build on set/get/add. The production JAX path uses the coordinator service
in runtime.bootstrap; this store serves framework-level coordination and
the multi-process CPU test strategy.
"""

from __future__ import annotations

import ctypes
import os
import random
import threading
import time
import weakref


def _env_token() -> str | None:
    """Default shared-secret for both ends: set TPU_SANDBOX_KV_TOKEN on
    every host of a cross-host job and servers require it, clients send it
    — respawned workers inherit the auth story through the environment
    with no extra flag plumbing."""
    return os.environ.get("TPU_SANDBOX_KV_TOKEN") or None


def _backoff_delays(timeout: float, *, base: float = 0.02, cap: float = 0.5):
    """Jittered exponential backoff delays, exhausted at a hard deadline.

    Yields the next sleep until ``timeout`` seconds (monotonic) have
    elapsed since the first ``next()``; the generator then ends, which is
    the caller's signal to give up. Each delay is the exponential envelope
    scaled by a uniform factor in [0.5, 1.5) — when an elastic restart
    relaunches a whole gang at once, unjittered clients hammer the
    listening socket in lockstep — and the final sleep is clamped so no
    caller oversleeps its own deadline."""
    deadline = time.monotonic() + timeout
    delay = base
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return
        yield min(delay * (0.5 + random.random()), remaining)
        delay = min(delay * 2, cap)


def _lib() -> ctypes.CDLL:
    global _cached
    try:
        return _cached
    except NameError:
        pass
    from tpu_sandbox.native import load_library

    lib = load_library("kvstore")
    lib.kv_server_start.restype = ctypes.c_void_p
    lib.kv_server_start.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_char_p,
    ]
    lib.kv_server_port.restype = ctypes.c_int
    lib.kv_server_port.argtypes = [ctypes.c_void_p]
    lib.kv_server_stop.restype = None
    lib.kv_server_stop.argtypes = [ctypes.c_void_p]
    lib.kv_connect.restype = ctypes.c_int
    lib.kv_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.kv_request.restype = ctypes.c_int64
    lib.kv_request.argtypes = [
        ctypes.c_int, ctypes.c_char, ctypes.c_char_p, ctypes.c_int64,
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.kv_close.restype = None
    lib.kv_close.argtypes = [ctypes.c_int]
    _cached = lib
    return lib


# Every live KVServer registers here so the test suite can detect servers a
# test forgot to stop (the C++ accept/worker threads are invisible to Python's
# threading.enumerate, so a leak check needs this explicit registry). WeakSet:
# the registry must not keep abandoned servers alive on its own.
_live_servers: "weakref.WeakSet[KVServer]" = weakref.WeakSet()


def live_servers() -> list["KVServer"]:
    """Servers constructed but not yet stop()ed (GC'd ones drop out)."""
    return [s for s in _live_servers if s._handle]


class KVServer:
    """In-process store server (rank 0 runs one). port=0 -> OS-assigned.

    ``bind`` defaults to loopback — the single-host topology needs nothing
    more, and an open port with no auth is not a default anyone should
    inherit. Cross-host deployment: ``bind="0.0.0.0"`` plus a shared-secret
    ``token`` (default: the TPU_SANDBOX_KV_TOKEN env var), which every
    connection must present in an opening hello frame before any store op
    is served. Auth without transport encryption: the token gates access,
    it does not hide traffic from the network path — run on a trusted
    fabric (DCN) or tunnel."""

    def __init__(self, port: int = 0, *, bind: str = "127.0.0.1",
                 token: str | None = None):
        if token is None:
            token = _env_token()
        self._lib = _lib()
        self._handle = self._lib.kv_server_start(
            bind.encode(), port, (token or "").encode()
        )
        if not self._handle:
            raise RuntimeError(
                f"kv_server_start failed on {bind}:{port}"
            )
        self.port = self._lib.kv_server_port(self._handle)
        self.bind = bind
        self.token = token
        _live_servers.add(self)

    def stop(self) -> None:
        if self._handle:
            self._lib.kv_server_stop(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class KVClient:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        connect_timeout: float = 10.0,
        token: str | None = None,
    ):
        """Connect with bounded retry: worker processes race the rank-0
        server's listen() (an elastic restart relaunches everyone at once),
        so a refused connection within ``connect_timeout`` seconds is
        "server not up yet", not an error. ``connect_timeout=0`` restores
        the old single-attempt behavior.

        ``token`` (default: the TPU_SANDBOX_KV_TOKEN env var) is sent as
        the opening hello frame of every connection — required by servers
        started with a token, a no-op against servers without one."""
        if token is None:
            token = _env_token()
        self._lib = _lib()
        self.host, self.port = host, port
        self.token = token
        self.connect_timeout = connect_timeout
        retries = _backoff_delays(connect_timeout)
        while True:
            self._fd = self._lib.kv_connect(host.encode(), port)
            if self._fd >= 0:
                break
            delay = next(retries, None)
            if delay is None:
                raise ConnectionError(
                    f"kv_connect {host}:{port} failed "
                    f"(retried for {connect_timeout}s)"
                )
            time.sleep(delay)
        self._hello()
        # one request-response in flight per connection: the wire protocol is
        # length-prefixed with no framing recovery, so concurrent callers
        # (e.g. a Heartbeat thread sharing the owner's client) must serialize
        self._mu = threading.Lock()

    def _hello(self) -> None:
        """Authenticate this connection (first frame, before any store op).
        Raw kv_request on purpose: runs inside _reconnect, which executes
        under _request's lock — re-entering _request would deadlock."""
        if not self.token:
            return
        tok = self.token.encode()
        out = ctypes.create_string_buffer(8)
        n = self._lib.kv_request(self._fd, b"H", tok, len(tok), b"", 0, out, 8)
        if n < 0:
            self._lib.kv_close(self._fd)
            self._fd = -1
            raise ConnectionError(
                f"kv auth to {self.host}:{self.port} failed — token "
                "rejected (TPU_SANDBOX_KV_TOKEN mismatch?)"
            )

    # Idempotent reads may be transparently retried on a fresh connection
    # after a transient socket error: re-running them cannot change store
    # state. Writes (set/add/delete/...) stay single-shot and fail loud —
    # a retried add() would double-count and a retried set() could resurrect
    # a key someone deleted in between.
    _RETRYABLE_OPS = frozenset({"G", "T", "L"})
    _READ_RETRIES = 5
    _RETRY_BASE_DELAY = 0.05

    def _reconnect(self) -> None:
        """Drop the (presumed broken) connection and dial again, bounded by
        the client's original connect_timeout."""
        if self._fd >= 0:
            self._lib.kv_close(self._fd)
            self._fd = -1
        retries = _backoff_delays(max(self.connect_timeout, 1.0))
        while True:
            self._fd = self._lib.kv_connect(self.host.encode(), self.port)
            if self._fd >= 0:
                self._hello()
                return
            delay = next(retries, None)
            if delay is None:
                raise ConnectionError(
                    f"kv reconnect {self.host}:{self.port} failed"
                )
            time.sleep(delay)

    def _request(
        self, op: str, key: str, val: bytes = b"", cap: int = 1 << 20
    ) -> bytes | None:
        out = ctypes.create_string_buffer(cap)
        attempts = self._READ_RETRIES if op in self._RETRYABLE_OPS else 1
        with self._mu:
            for attempt in range(attempts):
                n = self._lib.kv_request(
                    self._fd, op.encode(), key.encode(), len(key.encode()),
                    val, len(val), out, cap,
                )
                if n == -2:
                    return None  # try-get: key missing
                if n >= 0:
                    return out.raw[:n]
                # n < 0: request failed (dead socket, server restarting).
                # For idempotent reads, back off with jitter and try again
                # on a fresh connection — a leader failover must not kill
                # every agent mid-poll over one dropped packet.
                if attempt + 1 >= attempts:
                    break
                time.sleep(
                    self._RETRY_BASE_DELAY * (2**attempt)
                    * (0.5 + random.random())
                )
                try:
                    self._reconnect()
                except ConnectionError:
                    break  # nothing is listening; fail below
        raise RuntimeError(f"kv {op} {key!r} failed")

    def set(self, key: str, value: bytes | str) -> None:
        if isinstance(value, str):
            value = value.encode()
        self._request("S", key, value)

    def set_ttl(self, key: str, value: bytes | str, ttl: float) -> None:
        """Set with a server-side time-to-live: the key reads as missing
        (and is purged) once ``ttl`` seconds pass. The hygiene primitive
        for claim keys — a crashed generation's shard-done/fault claims
        must not satisfy (or pollute) a later generation forever."""
        if isinstance(value, str):
            value = value.encode()
        if ttl <= 0:
            raise ValueError(f"ttl must be > 0, got {ttl}")
        self._request("X", key, f"{ttl}\n".encode() + value)

    def get(self, key: str) -> bytes:
        """Blocks until the key exists (TCPStore wait-get semantics)."""
        return self._request("G", key)

    def clone(self) -> "KVClient":
        """A fresh connection to the same store. Background users (e.g. a
        Heartbeat) should run on a clone: a blocking ``get`` holds this
        connection's request lock for its whole server-side wait."""
        return KVClient(self.host, self.port, token=self.token)

    def try_get(self, key: str) -> bytes | None:
        """Non-blocking get: ``None`` when the key does not exist (the poll
        primitive failure detection needs — a blocking get can't observe
        'rank never wrote its heartbeat')."""
        return self._request("T", key)

    def add(self, key: str, delta: int = 1) -> int:
        """Atomic fetch-add on a decimal counter; returns the new value."""
        return int(self._request("A", key, str(delta).encode()))

    def delete(self, key: str) -> None:
        self._request("D", key)

    def keys(self, prefix: str = "") -> list[str]:
        """All live keys starting with ``prefix`` (sorted; expired TTL keys
        excluded). Empty prefix lists the whole store."""
        raw = self._request("L", prefix)
        return [k.decode() for k in raw.split(b"\n") if k] if raw else []

    def delete_prefix(self, prefix: str) -> int:
        """Delete every key starting with ``prefix``; returns how many went.
        Refuses the empty prefix — 'wipe the whole store' should never be
        one typo away from 'clean my namespace'."""
        if not prefix:
            raise ValueError("delete_prefix needs a non-empty prefix")
        return int(self._request("P", prefix))

    def barrier(self, world_size: int, key: str = "barrier") -> None:
        """All ``world_size`` callers block until everyone arrived."""
        arrived = self.add(f"{key}/count", 1)
        if arrived == world_size:
            self.set(f"{key}/done", b"1")
        self.get(f"{key}/done")  # blocks until released

    def close(self) -> None:
        if self._fd >= 0:
            self._lib.kv_close(self._fd)
            self._fd = -1

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# Per-job namespacing
# ---------------------------------------------------------------------------

ENV_JOB_ID = "TPU_SANDBOX_JOB_ID"

# The "default job" (empty/absent/"default" job id) maps to the empty
# namespace: its keys are the historical bare forms (leader/*, budget/*,
# gen/*, job/done), so every single-job deployment — and every pre-cluster
# test — keeps its exact KV schema.
DEFAULT_JOB = "default"


def job_namespace(job_id: str | None) -> str:
    """The key prefix a job's runtime keys live under.

    Empty string for the default job (bare-prefix backward-compat alias);
    ``job/<id>/`` otherwise. Job ids may not contain '/' or whitespace —
    namespace sweeps (``delete_prefix("job/<id>/")``) must never be able
    to reach into a sibling job's keys via a crafted id."""
    if not job_id or job_id == DEFAULT_JOB:
        return ""
    if any(c in job_id for c in "/ \t\n\r"):
        raise ValueError(f"invalid job id {job_id!r}: '/' and whitespace "
                         "are reserved (namespace sweeps must stay scoped)")
    return f"job/{job_id}/"


def for_job(kv: "KVClient | NamespacedKV", job_id: str | None):
    """A view of ``kv`` scoped to one job's namespace.

    The default job gets the client back unchanged (bitwise-identical key
    schema to the pre-cluster runtime); any other id gets a
    ``NamespacedKV`` that prepends ``job/<id>/`` to every key. Layering a
    namespace on an already-namespaced view is a programming error."""
    ns = job_namespace(job_id)
    if not ns:
        return kv
    if isinstance(kv, NamespacedKV):
        raise ValueError("refusing to nest job namespaces: "
                         f"{kv.prefix!r} + {ns!r}")
    return NamespacedKV(kv, ns)


class NamespacedKV:
    """A KVClient view that prepends a fixed prefix to every key.

    This is the whole multi-tenant isolation story at the storage layer:
    two jobs sharing one store each hold a view under ``job/<id>/``, so
    their elections, budgets, generations, heartbeats, and fault claims
    land in disjoint key ranges — no coordination code above this layer
    needs to know other jobs exist. ``keys()`` strips the prefix on the
    way out so callers see the same relative names they wrote."""

    def __init__(self, client: KVClient, prefix: str):
        if not prefix:
            raise ValueError("NamespacedKV needs a non-empty prefix "
                             "(use the raw client for the default job)")
        self._kv = client
        self.prefix = prefix

    @property
    def host(self) -> str:
        return self._kv.host

    @property
    def port(self) -> int:
        return self._kv.port

    @property
    def token(self) -> str | None:
        return self._kv.token

    @property
    def raw(self) -> KVClient:
        """The underlying un-namespaced client (cluster-level callers
        only — e.g. the scheduler reading its own sched/* plane while
        holding a job view)."""
        return self._kv

    def set(self, key: str, value: bytes | str) -> None:
        self._kv.set(self.prefix + key, value)

    def set_ttl(self, key: str, value: bytes | str, ttl: float) -> None:
        self._kv.set_ttl(self.prefix + key, value, ttl)

    def get(self, key: str) -> bytes:
        return self._kv.get(self.prefix + key)

    def try_get(self, key: str) -> bytes | None:
        return self._kv.try_get(self.prefix + key)

    def add(self, key: str, delta: int = 1) -> int:
        return self._kv.add(self.prefix + key, delta)

    def delete(self, key: str) -> None:
        self._kv.delete(self.prefix + key)

    def keys(self, prefix: str = "") -> list[str]:
        full = self._kv.keys(self.prefix + prefix)
        return [k[len(self.prefix):] for k in full]

    def delete_prefix(self, prefix: str = "") -> int:
        # Empty relative prefix is legal here — it means "sweep my whole
        # namespace", which is exactly the scoped cleanup the scheduler
        # runs when a job ends; the store-wide wipe stays impossible
        # because self.prefix is never empty.
        return self._kv.delete_prefix(self.prefix + prefix)

    def barrier(self, world_size: int, key: str = "barrier") -> None:
        arrived = self.add(f"{key}/count", 1)
        if arrived == world_size:
            self.set(f"{key}/done", b"1")
        self.get(f"{key}/done")

    def clone(self) -> "NamespacedKV":
        return NamespacedKV(self._kv.clone(), self.prefix)

    def close(self) -> None:
        self._kv.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
