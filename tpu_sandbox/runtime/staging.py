"""Chunk-streamed array staging: load ``.npz`` members without the
whole-file host copy.

``np.load`` on an npz materialises each member by reading the full
compressed stream into one bytes object and then copying it into the
array — two transient copies of every shard on the host, which is what
made deploy weight swaps and MPMD recovery reads spike resident memory
by the checkpoint size. :func:`stream_load_npz` parses the npy header of
each member itself and ``readinto``-s the payload directly into a
preallocated array in bounded chunks, so peak staging overhead is one
chunk (default 4 MiB) regardless of shard size. Works for stored and
deflated members alike (the zip extension file decompresses into the
chunk window).

Bitwise contract: the bytes that land in the array are exactly the bytes
``np.load`` would have produced — tests assert equality array-for-array
— so checksum verification (``verify_step_dir``) and the bitwise swap /
recovery parity gates are unaffected by the staging path.
"""

from __future__ import annotations

import zipfile

import numpy as np
from numpy.lib import format as npformat

DEFAULT_CHUNK = 4 << 20


def _stream_member(f, *, chunk_bytes: int, name: str) -> np.ndarray:
    """Parse one npy stream and fill a preallocated array in chunks."""
    version = npformat.read_magic(f)
    shape, fortran, dtype = npformat._read_array_header(f, version)
    if dtype.hasobject:
        raise ValueError(
            f"{name}: object arrays need pickling; refusing (the staging "
            "path is for raw numeric checkpoints)")
    count = int(np.prod(shape, dtype=np.int64))
    arr = np.empty(count, dtype=dtype)
    buf = memoryview(arr).cast("B") if count else memoryview(b"")
    total = arr.nbytes
    off = 0
    while off < total:
        n = f.readinto(buf[off:off + chunk_bytes])
        if not n:
            raise ValueError(
                f"{name}: truncated npy payload ({off} of {total} bytes)")
        off += n
    if fortran:
        arr.shape = shape[::-1]
        return arr.transpose()
    arr.shape = shape
    return arr


def stream_load_npz(path, *, chunk_bytes: int = DEFAULT_CHUNK,
                    only=None) -> dict[str, np.ndarray]:
    """Load an npz into ``{name: array}`` with chunked staging.

    ``only`` restricts loading to a set of member names (a partial
    restore never stages shards it will drop). ``allow_pickle`` is
    permanently off, same trust posture as every other load in the
    repo.
    """
    if chunk_bytes < 1:
        raise ValueError(f"chunk_bytes must be >= 1, got {chunk_bytes}")
    out: dict[str, np.ndarray] = {}
    with zipfile.ZipFile(path, "r") as zf:
        for info in zf.infolist():
            if not info.filename.endswith(".npy"):
                continue
            key = info.filename[:-len(".npy")]
            if only is not None and key not in only:
                continue
            with zf.open(info, "r") as f:
                out[key] = _stream_member(f, chunk_bytes=chunk_bytes,
                                          name=info.filename)
    return out
