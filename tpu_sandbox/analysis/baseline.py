"""Baseline suppression for graftlint findings.

``analysis/baseline.toml`` holds the accepted findings so the gate starts
green and *ratchets*: new findings fail, removing code removes its
suppression pressure, and ``--update-baseline`` re-emits the file.

Format — a TOML subset (the file stays valid TOML for external tooling),
parsed here with a ~40-line reader because the pinned interpreter is
Python 3.10 (no ``tomllib``) and the container can't grow dependencies:

    [[suppress]]
    rule = "GL-R304"
    file = "tpu_sandbox/runtime/host_agent.py"
    match = "kv.get(k_teardown"
    reason = "why this is accepted"

``match`` is a substring of the finding's source snippet, so suppressions
survive line-number churn; ``file`` is the exact repo-relative path. An
entry with no ``match`` suppresses every finding of that rule in that
file. Unused entries are reported so stale suppressions get deleted.
"""

from __future__ import annotations

import dataclasses

from tpu_sandbox.analysis.findings import Finding


@dataclasses.dataclass(frozen=True)
class Suppression:
    rule: str
    file: str
    match: str = ""
    reason: str = ""


class BaselineError(ValueError):
    pass


def _parse_value(raw: str, lineno: int) -> str:
    raw = raw.strip()
    if len(raw) >= 2 and raw[0] == '"' and raw[-1] == '"':
        body = raw[1:-1]
        out = []
        i = 0
        while i < len(body):
            c = body[i]
            if c == "\\" and i + 1 < len(body):
                nxt = body[i + 1]
                out.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\"}
                           .get(nxt, nxt))
                i += 2
            else:
                out.append(c)
                i += 1
        return "".join(out)
    raise BaselineError(
        f"baseline line {lineno}: expected a double-quoted string, got "
        f"{raw!r}"
    )


def parse_baseline(text: str) -> list[Suppression]:
    entries: list[dict[str, str]] = []
    current: dict[str, str] | None = None
    for lineno, line in enumerate(text.splitlines(), start=1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        if stripped == "[[suppress]]":
            current = {}
            entries.append(current)
            continue
        if stripped.startswith("["):
            raise BaselineError(
                f"baseline line {lineno}: only [[suppress]] tables are "
                f"supported, got {stripped!r}"
            )
        if "=" not in stripped:
            raise BaselineError(
                f"baseline line {lineno}: expected 'key = \"value\"'"
            )
        if current is None:
            raise BaselineError(
                f"baseline line {lineno}: key outside a [[suppress]] table"
            )
        key, _, raw = stripped.partition("=")
        key = key.strip()
        if key not in ("rule", "file", "match", "reason"):
            raise BaselineError(
                f"baseline line {lineno}: unknown key {key!r}"
            )
        current[key] = _parse_value(raw, lineno)
    out = []
    for i, e in enumerate(entries):
        if "rule" not in e or "file" not in e:
            raise BaselineError(
                f"baseline entry #{i + 1} is missing 'rule' or 'file'"
            )
        out.append(Suppression(
            rule=e["rule"], file=e["file"],
            match=e.get("match", ""), reason=e.get("reason", ""),
        ))
    return out


def load_baseline(path: str) -> list[Suppression]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return parse_baseline(f.read())
    except FileNotFoundError:
        return []


def _matches(s: Suppression, f: Finding) -> bool:
    if s.rule != f.rule or s.file != f.file:
        return False
    if s.match:
        return s.match in f.snippet or s.match in f.message
    return True


def apply_baseline(
    findings: list[Finding], suppressions: list[Suppression],
) -> tuple[list[Finding], list[Finding], list[Suppression]]:
    """-> (kept, suppressed, unused suppressions)."""
    kept: list[Finding] = []
    suppressed: list[Finding] = []
    used = [False] * len(suppressions)
    for f in findings:
        hit = False
        for i, s in enumerate(suppressions):
            if _matches(s, f):
                used[i] = True
                hit = True
        (suppressed if hit else kept).append(f)
    unused = [s for s, u in zip(suppressions, used) if not u]
    return kept, suppressed, unused


def _toml_str(s: str) -> str:
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def render_baseline(findings: list[Finding], *, reason: str = "") -> str:
    """Emit a baseline file suppressing exactly ``findings``."""
    lines = [
        "# graftlint accepted-findings baseline.",
        "# Each [[suppress]] entry silences matching findings; 'match' is a",
        "# substring of the offending source line so entries survive line",
        "# churn. Regenerate with: python tools/graftlint.py "
        "--update-baseline",
        "",
    ]
    seen: set[tuple[str, str, str]] = set()
    for f in findings:
        match = f.snippet[:80] if f.snippet else ""
        key = (f.rule, f.file, match)
        if key in seen:
            continue
        seen.add(key)
        lines.append("[[suppress]]")
        lines.append(f"rule = {_toml_str(f.rule)}")
        lines.append(f"file = {_toml_str(f.file)}")
        if match:
            lines.append(f"match = {_toml_str(match)}")
        lines.append(f"reason = {_toml_str(reason or 'TRIAGE: ' + f.message)}")
        lines.append("")
    return "\n".join(lines)
