"""Shared finding model + rule catalog for the graftlint passes.

Every pass emits :class:`Finding` rows — file:line, a stable rule id, a
one-line message, and a fix hint — so the CLI, the tier-1 gate, and the
baseline suppressor all speak one format. Rule ids are grouped by pass:

- ``GL-C1xx``  Pass 1: collective consistency (AST, SPMD-divergence class)
- ``GL-H2xx``  Pass 2: jaxpr / chipless AOT HLO step lint
- ``GL-R3xx``  Pass 3: control-plane lint (AST over runtime/ + serve/)
- ``GL-O4xx``  Pass 3 observability rules (span/recorder discipline)
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit. ``snippet`` is the stripped source line (or a short
    machine summary for compile-level findings) — the baseline matches on
    it so suppressions survive line-number churn."""

    rule: str
    file: str        # repo-relative path, or "<step:NAME>" for compile lint
    line: int        # 1-based; 0 for compile-level findings
    message: str
    hint: str = ""
    snippet: str = ""

    def format(self) -> str:
        loc = f"{self.file}:{self.line}" if self.line else self.file
        out = f"{loc}: {self.rule}: {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out


#: rule id -> (title, default fix hint)
RULES: dict[str, tuple[str, str]] = {
    # -- Pass 1: collective consistency --------------------------------------
    "GL-C101": (
        "collective under a rank-conditioned branch",
        "hoist the collective out of the rank-conditional (all ranks must "
        "reach every collective in the same order) or guard the whole "
        "function, not the call",
    ),
    "GL-C102": (
        "collective after a rank-conditioned early exit",
        "a rank that returns/raises early never reaches the collective the "
        "others are blocked in; make the exit unconditional or move it "
        "after the last collective",
    ),
    "GL-C103": (
        "collective-bearing call under a rank-conditioned branch",
        "the callee's collective sequence diverges across ranks through "
        "this call site; hoist the call or strip the callee's collectives",
    ),
    # -- Pass 2: step-function jaxpr / HLO lint ------------------------------
    "GL-H201": (
        "missing input donation on TrainState buffers",
        "pass donate=True (donate_argnums=(0,)) so XLA aliases the old "
        "state's buffers into the new state instead of holding both live",
    ),
    "GL-H202": (
        "bf16->fp32 upcast inside the step",
        "a large convert_element_type to f32 doubles that buffer's HBM "
        "footprint; keep the tensor in bf16 or upcast per-block",
    ),
    "GL-H203": (
        "host transfer inside the step",
        "callbacks/infeed/outfeed serialize the step on host round-trips; "
        "move the host work outside the jit or behind io_callback batching",
    ),
    "GL-H204": (
        "grad-sync collectives all scheduled after the last backward op",
        "overlap_grad_sync is on but XLA issued no all-reduce before the "
        "last backward compute: nothing can hide under compute — check "
        "bucket_mb and the latency-hiding compiler flags",
    ),
    "GL-H205": (
        "int8 block padding waste above threshold",
        "block/axis alignment padding dominates the int8 wire payload; "
        "lower CompressedAllReduce.block or fuse small leaves into buckets",
    ),
    # -- Pass 3: control-plane lint ------------------------------------------
    "GL-R301": (
        "KV add() claim without generation/term scoping",
        "an unscoped add()-wins claim stays claimed across generations "
        "(double-charge / never-again-charge); scope the key with the "
        "generation, term, or another per-round discriminator",
    ),
    "GL-R302": (
        "heartbeat stamp compared against the local clock",
        "cross-host clock skew makes wall-stamp arithmetic read as death "
        "(or mask one); track when the observer last saw the stamp CHANGE "
        "and bound that local age instead (see runtime/watchdog.Watchdog)",
    ),
    "GL-R303": (
        "thread started without daemon=True",
        "non-daemon threads trip the conftest leak check and outlive "
        "crashed owners; pass daemon=True (or set .daemon before start())",
    ),
    "GL-R304": (
        "blocking KV read inside a leader-action critical section",
        "a blocking get() can park the leader past its lease TTL (a peer "
        "takes over while this one still thinks it leads); use try_get() "
        "and re-observe next tick",
    ),
    "GL-R305": (
        "Python loop dispatching a multi-device jitted fn per iteration",
        "each dispatch of a collective-bearing jit is a cross-device "
        "rendezvous; a Python-speed storm of them interleaves across "
        "ranks and deadlocks XLA:CPU gangs — batch the loop into the "
        "program (lax.scan / fori_loop) or hoist the dispatch out",
    ),
    "GL-R306": (
        "unbounded in-memory request queue",
        "a producer-facing queue appended to with no capacity comparison "
        "and no shed path turns overload into unbounded memory growth and "
        "unbounded tail latency; bound the queue and shed with an explicit "
        "verdict (see serve/engine.ContinuousEngine.submit)",
    ),
    # -- Pass 3: observability discipline ------------------------------------
    "GL-O401": (
        "span begun without a guaranteed close",
        "a leaked open span never emits its record and the request "
        "silently vanishes from the merged timeline; use `with "
        "rec.span(...)`, or assign `sp = rec.begin_span(...)` and follow "
        "it IMMEDIATELY with try/finally sp.close()",
    ),
    "GL-O402": (
        "metric name is not a static snake.dotted literal",
        "a dynamic metric name (f-string, concatenation, variable) mints "
        "one series per distinct value — unbounded cardinality that "
        "bloats every registry snapshot, OP_METRICS scrape, and tsdb "
        "flush, and breaks alert rules keyed on the name; use a static "
        "'component.metric' literal and carry the bounded dimension in "
        "labels= (see obs/metrics.py)",
    ),
    "GL-O403": (
        "span name is minted at runtime",
        "a span/instant name built with %, .format(), concatenation, or "
        "a bare variable has unbounded cardinality — the critical-path "
        "analyzer, waterfalls, and trace-diff gating all aggregate by "
        "span name and fragment across it; use a static literal, or the "
        "sanctioned f'family:{value}' shape (static family prefix ending "
        "in ':') which downstream aggregation keys on, with the value "
        "drawn from a bounded set",
    ),
}


def make_finding(rule: str, file: str, line: int, message: str,
                 snippet: str = "", hint: str | None = None) -> Finding:
    if rule not in RULES:
        raise ValueError(f"unknown rule id {rule!r}")
    return Finding(
        rule=rule, file=file, line=line, message=message,
        hint=RULES[rule][1] if hint is None else hint,
        snippet=snippet,
    )
