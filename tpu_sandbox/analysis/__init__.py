"""graftlint: static analysis for the distributed-training stack.

Three passes over three failure planes (see ``tools/graftlint.py`` for
the CLI and ``analysis/baseline.toml`` for the ratchet):

- Pass 1 (:mod:`.collective_pass`) — AST collective-consistency: the
  SPMD-divergence deadlock class (rules GL-C1xx).
- Pass 2 (:mod:`.hlo_pass`) — jaxpr + chipless AOT HLO lint of the real
  step functions: donation, upcasts, host transfers, overlap schedule,
  int8 padding (rules GL-H2xx).
- Pass 3 (:mod:`.control_pass`) — control-plane AST lint over
  ``runtime/``: claim scoping, clock-skew stamp math, thread hygiene,
  leader-section blocking reads (rules GL-R3xx).

Import note: only :mod:`.hlo_pass`'s driver needs jax; the AST passes
and the baseline machinery are stdlib-only so the tier-1 gate can run
them in-process.
"""

from tpu_sandbox.analysis.baseline import (
    BaselineError,
    Suppression,
    apply_baseline,
    load_baseline,
    parse_baseline,
    render_baseline,
)
from tpu_sandbox.analysis.collective_pass import run_collective_pass
from tpu_sandbox.analysis.control_pass import run_control_pass
from tpu_sandbox.analysis.findings import RULES, Finding, make_finding

__all__ = [
    "Finding",
    "RULES",
    "make_finding",
    "run_collective_pass",
    "run_control_pass",
    "Suppression",
    "BaselineError",
    "parse_baseline",
    "load_baseline",
    "apply_baseline",
    "render_baseline",
]
