"""Pass 3: control-plane lint over ``runtime/``, ``serve/``,
``gateway/``, ``obs/`` and ``deploy/`` (AST).

Nine rules distilled from this repo's own elastic-runtime and serving
incident history:

- **GL-R301** — ``kv.add(key, 1) == 1`` claims whose key carries no
  generation/term/round discriminator. An unscoped claim-once key stays
  claimed forever: budgets double-charge on the first race and then
  never charge again. Key helpers (module functions / methods that
  return f-strings, e.g. ``k_charge_claim(gen)``) are resolved so a
  scoped helper call counts as scoped.
- **GL-R302** — arithmetic mixing ``time.time()`` with a value read from
  the KV store (a remote wall-clock stamp). Cross-host skew makes that
  difference meaningless; the watchdog idiom is to track when the local
  observer last saw the stamp *change* and bound that local age.
- **GL-R303** — ``threading.Thread(...)`` without ``daemon=True`` (and
  no ``x.daemon = True`` before ``x.start()`` in the same function).
  Non-daemon threads outlive crashed owners and trip the conftest
  ``_no_resource_leaks`` check.
- **GL-R304** — blocking ``kv.get(...)`` reachable from a leader-action
  method (``_leader*`` roots; the ``self.``-call graph spans same-module
  base classes, so a helper one inheritance edge away is still seen). A
  blocking read can park the leader past its lease TTL; leader ticks
  must use ``try_get`` and re-observe next tick.
- **GL-R305** — a Python ``for``/``while`` loop dispatching a
  *multi-device* jitted computation (one whose body runs a collective,
  or a ``shard_map``) per iteration. Every dispatch is a fresh
  cross-device rendezvous; on XLA:CPU a storm of them interleaves
  across ranks until two ranks wait in different rendezvous and the
  job deadlocks (the ROADMAP launch-storm carry-over). Batch the loop
  into the program (``lax.scan``/``fori_loop``) or hoist the dispatch
  out of the loop.
- **GL-R306** — ``.append()`` onto a queue-ish attribute (``queue``,
  ``waiting``, ``pending``, ``backlog``, ``inbox``, ``mailbox``) in a
  function with no capacity comparison on that queue and no shed/drop
  path. An unbounded producer-facing queue converts overload into
  unbounded memory growth and unbounded tail latency; the fix is a
  bounded queue that sheds with an explicit verdict (the
  ``serve/engine.ContinuousEngine.submit`` idiom). ``appendleft`` is
  deliberately exempt: requeueing already-admitted work (preemption)
  adds nothing the queue has not already accepted.
- **GL-O401** — a span begun with ``begin_span()`` whose ``close()`` is
  not guaranteed on every path. The sanctioned forms are ``with
  rec.span(...)`` or ``sp = rec.begin_span(...)`` followed
  *immediately* by a ``try`` whose ``finally`` calls ``sp.close()``.
  Anything looser (a bare call whose handle is discarded, work between
  the begin and the ``try``, a close only on the happy path) can leak
  the span: a leaked open span never emits its record, so the request
  silently vanishes from the merged timeline — the observability
  equivalent of a lost verdict.
- **GL-O402** — a ``counter()``/``gauge()``/``histogram()`` call on a
  metrics registry whose name argument is not a static ``snake.dotted``
  string literal. A dynamic name (f-string, concatenation, variable)
  mints one series per distinct value: unbounded cardinality in every
  snapshot, scrape, and tsdb flush, and nothing stable for alert rules
  to key on. Bounded dimensions belong in ``labels=``.
- **GL-O403** — a ``span()``/``begin_span()``/``complete()``/
  ``instant()`` call on a recorder whose name argument is minted at
  runtime (``%``, ``.format()``, concatenation, a bare variable, or an
  f-string with no static family prefix). Span names are the
  aggregation key for the critical-path analyzer, waterfalls, and
  trace-diff gating — unbounded names fragment every one of them. The
  one sanctioned dynamic shape is ``f"family:{value}"`` with a static
  family prefix ending in ``:`` (``door:{reason}``, ``shed:{reason}``,
  ``fault:{action}``): downstream aggregation keys on the family, and
  the tail must come from a bounded set. Request-sized dimensions
  (rid, step) belong in ``args=``.
"""

from __future__ import annotations

import ast
import os
import re

from tpu_sandbox.analysis.findings import Finding, make_finding

#: identifiers that count as a per-round discriminator inside a claim key
SCOPE_TOKENS = frozenset({
    "gen", "generation", "term", "index", "idx", "step", "epoch",
    "attempt", "round", "fault", "token", "nonce", "seq", "rid",
})

#: attribute names that mark a receiver as "the KV client"
KV_RECEIVERS = frozenset({"kv", "client", "store", "_kv", "_client", "_store"})

#: attribute names that mark an in-memory collection as a request queue
QUEUE_NAMES = frozenset({
    "queue", "waiting", "pending", "backlog", "inbox", "mailbox",
})

#: call-name substrings that mark a function as overload-aware — it has
#: somewhere to put work it refuses (shed verdicts, drop/evict paths)
SHED_MARKERS = ("shed", "drop", "reject", "evict")

#: instrument factories on a metrics registry (GL-O402)
METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})

#: the sanctioned metric-name shape: lowercase snake segments joined by
#: dots, at least two segments ("component.metric")
METRIC_NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")

#: span/event emitters on a recorder (GL-O403). ``metric`` is excluded:
#: the tsdb flusher relays registry names already policed by GL-O402
SPAN_EMITTERS = frozenset({"span", "begin_span", "complete", "instant"})

#: a static span name: lowercase snake/dotted segments, optionally
#: colon-joined into a family ("claim", "door:no_replicas", "swap:pause")
SPAN_NAME_RE = re.compile(
    r"^[a-z0-9_]+(\.[a-z0-9_]+)*(:[a-z0-9_]+(\.[a-z0-9_]+)*)*$")

#: the static family prefix an f-string span name must open with to be
#: sanctioned: f"door:{reason}" aggregates as "door"
SPAN_FAMILY_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)*:$")


#: nested scopes a statement walk must not descend into — each is
#: linted as its own function/class
_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def _stmt_lists(fn: ast.AST):
    """Yield every statement sequence under ``fn`` (bodies, else/finally
    arms, except handlers, match cases) without descending into nested
    function/class scopes."""
    stack: list[ast.AST] = [fn]
    while stack:
        cur = stack.pop()
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(cur, field, None)
            if isinstance(stmts, list):
                yield stmts
                stack.extend(
                    s for s in stmts if not isinstance(s, _SCOPE_NODES))
        stack.extend(getattr(cur, "handlers", ()))
        stack.extend(getattr(cur, "cases", ()))


def _is_queueish(name: str | None) -> bool:
    if name is None:
        return False
    low = name.lstrip("_").lower()
    return low in QUEUE_NAMES or any(
        low.endswith("_" + q) for q in QUEUE_NAMES)


def _final_attr(node: ast.AST) -> str | None:
    """``self.kv`` -> 'kv', ``agent.client`` -> 'client', ``kv`` -> 'kv'."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_kv_receiver(node: ast.AST) -> bool:
    name = _final_attr(node)
    return name is not None and name in KV_RECEIVERS


def _fstring_idents(node: ast.JoinedStr) -> set[str]:
    idents: set[str] = set()
    for part in node.values:
        if isinstance(part, ast.FormattedValue):
            for sub in ast.walk(part.value):
                if isinstance(sub, ast.Name):
                    idents.add(sub.id)
                elif isinstance(sub, ast.Attribute):
                    idents.add(sub.attr)
    return idents


def _has_scope(idents: set[str]) -> bool:
    return any(
        tok in SCOPE_TOKENS or any(tok.startswith(s) or tok.endswith(s)
                                   for s in ("gen", "term", "idx"))
        for tok in {i.lower() for i in idents}
    )


class _KeyHelperIndex:
    """Module functions / methods whose body ``return``s a string key.

    Maps bare helper name -> (set of identifiers interpolated into the
    returned f-string, unioned with the helper's own parameter names when
    they feed the f-string). A helper returning a constant string maps to
    an empty set — calling it for a claim is as unscoped as the literal.
    """

    def __init__(self, tree: ast.Module):
        self.scopes: dict[str, set[str] | None] = {}
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            returned = self._returned_key_idents(node)
            if returned is not None:
                self.scopes[node.name] = returned

    @staticmethod
    def _returned_key_idents(fn: ast.AST) -> set[str] | None:
        """None if the function doesn't look like a key helper; else the
        identifier set interpolated into its returned string."""
        idents: set[str] | None = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                if isinstance(node.value, ast.JoinedStr):
                    found = _fstring_idents(node.value)
                elif isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    found = set()
                else:
                    continue
                idents = found if idents is None else (idents | found)
        return idents


class _FnLinter:
    def __init__(self, path: str, lines: list[str], helpers: _KeyHelperIndex,
                 findings: list[Finding]):
        self.path = path
        self.lines = lines
        self.helpers = helpers
        self.findings = findings

    def _snippet(self, node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        return self.lines[ln - 1].strip() if 0 < ln <= len(self.lines) else ""

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(make_finding(
            rule, self.path, getattr(node, "lineno", 0), message,
            snippet=self._snippet(node),
        ))

    # -- GL-R301 -------------------------------------------------------------

    def _key_scope(self, key: ast.AST) -> bool | None:
        """True = scoped, False = provably unscoped, None = unknown."""
        if isinstance(key, ast.JoinedStr):
            return _has_scope(_fstring_idents(key))
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            return False
        if isinstance(key, ast.Call):
            name = _final_attr(key.func)
            if name in self.helpers.scopes:
                helper_idents = self.helpers.scopes[name]
                # identifiers interpolated by the helper + what the call
                # site passes in (k_claim(gen) scopes even if the helper
                # names its parameter differently)
                site_idents: set[str] = set()
                for arg in key.args:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name):
                            site_idents.add(sub.id)
                        elif isinstance(sub, ast.Attribute):
                            site_idents.add(sub.attr)
                return _has_scope(helper_idents | site_idents)
            return None
        if isinstance(key, ast.BinOp):  # "prefix/" + str(gen) style
            idents = {
                sub.id for sub in ast.walk(key) if isinstance(sub, ast.Name)
            } | {
                sub.attr for sub in ast.walk(key)
                if isinstance(sub, ast.Attribute)
            }
            return _has_scope(idents)
        return None  # bare Name / subscript: key built elsewhere — skip

    def _check_claim(self, node: ast.Compare) -> None:
        """``X.add(key, ..) == 1`` / ``!= 1`` with an unscoped key."""
        sides = [node.left] + list(node.comparators)
        call = next(
            (s for s in sides
             if isinstance(s, ast.Call)
             and isinstance(s.func, ast.Attribute)
             and s.func.attr == "add"
             and _is_kv_receiver(s.func.value)),
            None,
        )
        if call is None or not call.args:
            return
        one = any(
            isinstance(s, ast.Constant) and s.value == 1
            for s in sides if s is not call
        )
        if not one or not isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
            return
        if self._key_scope(call.args[0]) is False:
            self._emit(
                "GL-R301", node,
                "add()-wins claim key carries no generation/term scope — "
                "it stays claimed across rounds",
            )

    # -- GL-R302 -------------------------------------------------------------

    @staticmethod
    def _is_time_call(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("time", "monotonic")
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "time"
        )

    def _taint_kv_reads(self, fn: ast.AST) -> set[str]:
        """Names assigned (transitively through float()/decode()/…) from a
        kv-ish ``.get``/``.try_get`` in this function."""
        tainted: set[str] = set()

        def expr_tainted(expr: ast.AST) -> bool:
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return True
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in ("get", "try_get") \
                        and _is_kv_receiver(sub.func.value):
                    return True
            return False

        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and expr_tainted(node.value):
                    # only plain-name (or tuple-of-name) targets taint:
                    # `obj[k] = (stamp, now)` must not taint `obj` or `k`
                    for tgt in node.targets:
                        names = [tgt] if isinstance(tgt, ast.Name) else (
                            tgt.elts if isinstance(tgt, (ast.Tuple, ast.List))
                            else []
                        )
                        for sub in names:
                            if isinstance(sub, ast.Name) \
                                    and sub.id not in tainted:
                                tainted.add(sub.id)
                                changed = True
        return tainted

    def _check_stamp_math(self, fn: ast.AST) -> None:
        tainted = self._taint_kv_reads(fn)

        def side_is_now(expr: ast.AST) -> bool:
            if self._is_time_call(expr):
                return True
            return isinstance(expr, ast.Name) and expr.id in ("now", "t_now")

        def side_is_stamp(expr: ast.AST) -> bool:
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Name) and sub.id in tainted:
                    return True
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr in ("get", "try_get") \
                        and _is_kv_receiver(sub.func.value):
                    return True
            return False

        for node in ast.walk(fn):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
                pairs = ((node.left, node.right), (node.right, node.left))
                if any(side_is_now(a) and side_is_stamp(b)
                       for a, b in pairs):
                    self._emit(
                        "GL-R302", node,
                        "local clock minus a KV-read stamp: cross-host "
                        "skew corrupts this age",
                    )

    # -- GL-R303 -------------------------------------------------------------

    def _check_threads(self, fn: ast.AST) -> None:
        daemon_set: set[str] = set()   # names with `.daemon = True` later
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.targets[0], ast.Attribute) \
                    and node.targets[0].attr == "daemon" \
                    and isinstance(node.value, ast.Constant) \
                    and node.value.value is True:
                tgt = node.targets[0].value
                name = _final_attr(tgt)
                if name:
                    daemon_set.add(name)
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and _final_attr(node.func) == "Thread"):
                continue
            daemon_kw = next(
                (kw for kw in node.keywords if kw.arg == "daemon"), None,
            )
            if daemon_kw is not None:
                if not (isinstance(daemon_kw.value, ast.Constant)
                        and daemon_kw.value.value is True):
                    self._emit(
                        "GL-R303", node,
                        "Thread created with daemon != True",
                    )
                continue
            # no daemon kwarg: accept `x = Thread(...)` + `x.daemon = True`
            assigned = self._assigned_name(fn, node)
            if assigned is not None and assigned in daemon_set:
                continue
            self._emit(
                "GL-R303", node,
                "Thread created without daemon=True (leaks past the "
                "conftest check, outlives crashed owners)",
            )

    @staticmethod
    def _assigned_name(fn: ast.AST, call: ast.Call) -> str | None:
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and node.value is call:
                name = _final_attr(node.targets[0])
                if name:
                    return name
        return None

    # -- GL-R306 -------------------------------------------------------------

    def _check_unbounded_queues(self, fn: ast.AST) -> None:
        """``.append()`` onto a queue-ish attribute in a function with no
        capacity comparison on that queue and no shed/drop call.

        ``appendleft`` (requeue of already-admitted work) is exempt, and
        a ``len(<queue>)`` that appears inside any comparison counts as
        the capacity check even when it guards a different branch — this
        is a lint heuristic, not a proof."""
        appends: list[tuple[ast.Call, str]] = []
        len_compared: set[str] = set()
        sheds = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Compare):
                for side in [node.left] + list(node.comparators):
                    for sub in ast.walk(side):
                        if isinstance(sub, ast.Call) \
                                and _final_attr(sub.func) == "len" \
                                and sub.args:
                            qn = _final_attr(sub.args[0])
                            if qn is not None:
                                len_compared.add(qn)
                continue
            if not isinstance(node, ast.Call):
                continue
            name = _final_attr(node.func)
            if name is None:
                continue
            if name == "append" and isinstance(node.func, ast.Attribute):
                qname = _final_attr(node.func.value)
                if _is_queueish(qname):
                    appends.append((node, qname))
            elif any(m in name.lower() for m in SHED_MARKERS):
                sheds = True
        if sheds:
            return
        for node, qname in appends:
            if qname in len_compared:
                continue
            self._emit(
                "GL-R306", node,
                f"append to '{qname}' with no capacity check and no shed "
                f"path — overload grows this queue without bound",
            )

    # -- GL-O401 -------------------------------------------------------------

    @staticmethod
    def _is_begin_span(expr: ast.AST) -> bool:
        return isinstance(expr, ast.Call) \
            and _final_attr(expr.func) == "begin_span"

    @staticmethod
    def _finally_closes(tryst: ast.Try, name: str) -> bool:
        for stmt in tryst.finalbody:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr == "close" \
                        and _final_attr(sub.func.value) == name:
                    return True
        return False

    def _check_span_leaks(self, fn: ast.AST) -> None:
        """``begin_span()`` must be the sanctioned shape: the handle
        assigned, then IMMEDIATELY a ``try`` whose ``finally`` closes
        it. A discarded handle, or any statement between the begin and
        the ``try``, is a path on which the span never emits — it
        silently vanishes from the merged timeline. (``with
        rec.span(...)`` compiles to this shape inside the recorder and
        is the preferred spelling.)"""
        for stmts in _stmt_lists(fn):
            for i, stmt in enumerate(stmts):
                if isinstance(stmt, ast.Expr) \
                        and self._is_begin_span(stmt.value):
                    self._emit(
                        "GL-O401", stmt,
                        "begin_span() handle discarded — nothing can "
                        "ever close this span",
                    )
                    continue
                if not (isinstance(stmt, ast.Assign)
                        and self._is_begin_span(stmt.value)):
                    continue
                name = _final_attr(stmt.targets[0])
                nxt = stmts[i + 1] if i + 1 < len(stmts) else None
                if name is not None and isinstance(nxt, ast.Try) \
                        and self._finally_closes(nxt, name):
                    continue
                self._emit(
                    "GL-O401", stmt,
                    f"span '{name}' begun without an immediate "
                    f"try/finally close — an exception before close() "
                    f"leaks it from the timeline",
                )

    # -- GL-O402 -------------------------------------------------------------

    @staticmethod
    def _is_registry_receiver(node: ast.AST) -> bool:
        """``get_registry().x``, ``reg.x``, ``self.registry.x`` — anything
        that reads as "the metrics registry". Instrument calls on other
        objects are out of scope."""
        if isinstance(node, ast.Call):
            return _final_attr(node.func) == "get_registry"
        name = _final_attr(node)
        if name is None:
            return False
        low = name.lstrip("_").lower()
        return low == "reg" or "registry" in low

    def _check_metric_names(self, fn: ast.AST) -> None:
        """Instrument names must be static ``snake.dotted`` literals; a
        name built at runtime mints a series per distinct value."""
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in METRIC_FACTORIES
                    and self._is_registry_receiver(node.func.value)):
                continue
            name_arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"),
                None)
            if name_arg is None:
                continue
            if isinstance(name_arg, ast.Constant) \
                    and isinstance(name_arg.value, str) \
                    and METRIC_NAME_RE.match(name_arg.value):
                continue
            self._emit(
                "GL-O402", node,
                f"{node.func.attr}() name is not a static snake.dotted "
                f"literal — a dynamic name mints one series per distinct "
                f"value (put bounded dimensions in labels=)",
            )

    # -- GL-O403 -------------------------------------------------------------

    @staticmethod
    def _is_recorder_receiver(node: ast.AST) -> bool:
        """``get_recorder().x``, ``rec.x``, ``self._recorder.x`` —
        anything that reads as "the recorder". Same-named methods on
        other objects (a checkpoint's ``complete``, say) are out of
        scope."""
        if isinstance(node, ast.Call):
            return _final_attr(node.func) == "get_recorder"
        name = _final_attr(node)
        if name is None:
            return False
        low = name.lstrip("_").lower()
        return low == "rec" or "recorder" in low

    @staticmethod
    def _span_name_ok(name_arg: ast.AST) -> bool:
        if isinstance(name_arg, ast.Constant):
            return isinstance(name_arg.value, str) \
                and bool(SPAN_NAME_RE.match(name_arg.value))
        if isinstance(name_arg, ast.JoinedStr) and name_arg.values:
            head = name_arg.values[0]
            return isinstance(head, ast.Constant) \
                and isinstance(head.value, str) \
                and bool(SPAN_FAMILY_RE.match(head.value))
        return False

    def _check_span_names(self, fn: ast.AST) -> None:
        """Span names must be static literals (or family-prefixed
        f-strings); everything downstream aggregates by span name."""
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in SPAN_EMITTERS
                    and self._is_recorder_receiver(node.func.value)):
                continue
            name_arg = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"),
                None)
            if name_arg is None or self._span_name_ok(name_arg):
                continue
            self._emit(
                "GL-O403", node,
                f"{node.func.attr}() span name is minted at runtime — "
                f"trace aggregation keys on span names; use a static "
                f"literal or f\"family:{{value}}\" with a static family "
                f"prefix, and put request-sized dimensions in args=",
            )

    # -- GL-R304 (per-class, run separately) ---------------------------------

    def run_common(self, fn: ast.AST) -> None:
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue
            if isinstance(node, ast.Compare):
                self._check_claim(node)
        self._check_stamp_math(fn)
        self._check_threads(fn)
        self._check_unbounded_queues(fn)
        self._check_span_leaks(fn)
        self._check_metric_names(fn)
        self._check_span_names(fn)


def _base_label(expr: ast.AST) -> str | None:
    """Trailing name of a base-class expression (``Base``, ``mod.Base``)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def _class_method_table(
    cls: ast.ClassDef, class_map: dict[str, ast.ClassDef],
    _seen: set[str] | None = None,
) -> dict[str, ast.AST]:
    """The class's effective method table: own methods plus same-module
    base methods (own overrides win; bases merge left-to-right, nearest
    definition first — the static shadow of the MRO). A ``_leader*`` tick
    that calls ``self._lookup()`` defined on a mixin is exactly as
    blocking as one defined inline, so GL-R304 must see through the
    inheritance edge."""
    seen = set() if _seen is None else _seen
    if cls.name in seen:  # cycle guard: malformed code must not recurse
        return {}
    seen.add(cls.name)
    table: dict[str, ast.AST] = {
        n.name: n for n in cls.body
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    }
    for base in cls.bases:
        bname = _base_label(base)
        if bname in class_map:
            for name, fn in _class_method_table(
                    class_map[bname], class_map, seen).items():
                table.setdefault(name, fn)
    return table


def _leader_reachable(
    cls: ast.ClassDef, class_map: dict[str, ast.ClassDef],
) -> tuple[set[str], dict[str, ast.AST]]:
    """(method names reachable from ``_leader*`` roots via ``self._x()``,
    the class's merged method table)."""
    methods = _class_method_table(cls, class_map)
    calls: dict[str, set[str]] = {}
    for name, fn in methods.items():
        out: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id == "self" \
                    and node.func.attr in methods:
                out.add(node.func.attr)
        calls[name] = out
    reachable = {n for n in methods if n.startswith("_leader")}
    frontier = list(reachable)
    while frontier:
        cur = frontier.pop()
        for callee in calls.get(cur, ()):
            if callee not in reachable:
                reachable.add(callee)
                frontier.append(callee)
    return reachable, methods


def _check_leader_blocking_reads(
    cls: ast.ClassDef, class_map: dict[str, ast.ClassDef],
    path: str, lines: list[str], findings: list[Finding],
    reported: set[int],
) -> None:
    """``reported`` dedupes by method node identity across classes: a
    base method reached from two subclasses is one finding, attributed to
    the first reaching class."""
    reachable, methods = _leader_reachable(cls, class_map)
    if not reachable:
        return
    ordered = sorted(
        ((n, methods[n]) for n in reachable),
        key=lambda item: getattr(item[1], "lineno", 0),
    )
    for method_name, node in ordered:
        if id(node) in reported:
            continue
        hit = False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "get" \
                    and _is_kv_receiver(sub.func.value):
                hit = True
                ln = getattr(sub, "lineno", 0)
                snippet = lines[ln - 1].strip() \
                    if 0 < ln <= len(lines) else ""
                findings.append(make_finding(
                    "GL-R304", path, ln,
                    f"blocking kv.get() inside leader-reachable "
                    f"'{cls.name}.{method_name}' can outlast the lease TTL",
                    snippet=snippet,
                ))
        if hit:
            reported.add(id(node))


# -- GL-R305 (module-level) --------------------------------------------------

#: cross-device rendezvous primitives — a jit whose trace hits one of
#: these runs on every device of the mesh, so each dispatch is a
#: collective rendezvous (shard_map-wrapped fns are multi-device by
#: construction)
_COLLECTIVES = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter",
    "all_gather", "ppermute", "pshuffle", "all_to_all",
})


def _calls_collective(fn: ast.AST,
                      external_coll: frozenset = frozenset()) -> bool:
    """``external_coll``: names imported from other modules whose bodies
    (transitively) issue collectives — xmodule.CrossIndex resolves them,
    so a jitted wrapper around an imported sync helper still counts."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = _final_attr(node.func)
            if name in _COLLECTIVES or name == "shard_map" \
                    or (isinstance(node.func, ast.Name)
                        and name in external_coll):
                return True
    return False


def _is_jit_expr(node: ast.AST) -> bool:
    """``jax.jit`` / ``jit`` (bare or decorator), incl. the
    ``partial(jax.jit, ...)`` decorator form."""
    if isinstance(node, ast.Call):
        fname = _final_attr(node.func)
        if fname == "partial" and node.args:
            return _is_jit_expr(node.args[0])
        return fname == "jit"
    return _final_attr(node) == "jit"


def _wrapped_is_multi_device(arg: ast.AST, coll_fns: set[str]) -> bool:
    """Does ``jax.jit(<arg>)`` trace a collective? ``<arg>`` is a known
    collective-calling function name, a lambda with a collective, or a
    ``shard_map(...)`` expression."""
    if isinstance(arg, ast.Name):
        return arg.id in coll_fns
    if isinstance(arg, ast.Lambda):
        return _calls_collective(arg, frozenset(coll_fns))
    if isinstance(arg, ast.Call):
        if _final_attr(arg.func) == "shard_map":
            return True
        if _final_attr(arg.func) == "partial" and arg.args:
            return _wrapped_is_multi_device(arg.args[0], coll_fns)
    return False


def _multi_device_jits(
    tree: ast.Module, external_coll: frozenset = frozenset(),
) -> tuple[set[str], set[str], set[ast.AST]]:
    """(names bound to multi-device jitted callables, names of functions
    that call collectives, jit-decorated defs).

    The last set matters for scoping: a loop *inside* a jitted function
    is traced into one program (one dispatch), so it is exempt.
    ``external_coll`` (from-imported collective-bearing functions, per
    xmodule.CrossIndex) count as collective-calling directly — a
    ``jax.jit(imported_sync)`` is exactly as multi-device as a local one.
    """
    coll_fns = {
        node.name for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and _calls_collective(node, external_coll)
    } | set(external_coll)
    jitted: set[str] = set()
    traced_defs: set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(d) for d in node.decorator_list):
                traced_defs.add(node)
                if node.name in coll_fns:
                    jitted.add(node.name)
        elif isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and _is_jit_expr(node.value.func) \
                and node.value.args \
                and _wrapped_is_multi_device(node.value.args[0], coll_fns):
            name = _final_attr(node.targets[0])
            if name:
                jitted.add(name)
    return jitted, coll_fns, traced_defs


def _loops_outside_traced(tree: ast.Module, traced_defs: set[ast.AST]):
    """Yield every For/While whose dispatches happen at Python speed —
    i.e. not inside a jit-decorated function body."""
    def visit(node):
        for child in ast.iter_child_nodes(node):
            if child in traced_defs:
                continue
            if isinstance(child, (ast.For, ast.AsyncFor, ast.While)):
                yield child
            yield from visit(child)
    yield from visit(tree)


def _check_launch_storms(
    tree: ast.Module, path: str, lines: list[str],
    findings: list[Finding], external_coll: frozenset = frozenset(),
) -> None:
    jitted, coll_fns, traced_defs = _multi_device_jits(tree, external_coll)
    if not jitted and not coll_fns:
        return
    for loop in _loops_outside_traced(tree, traced_defs):
        bodies = list(loop.body) + list(loop.orelse)
        if isinstance(loop, ast.While):
            bodies.append(loop.test)
        for part in bodies:
            for node in ast.walk(part):
                if not isinstance(node, ast.Call):
                    continue
                name = _final_attr(node.func)
                dispatches = name in jitted
                if not dispatches and isinstance(node.func, ast.Call):
                    # inline form: jax.jit(f)(x) inside the loop — a
                    # storm AND a retrace per iteration
                    call = node.func
                    dispatches = bool(
                        _is_jit_expr(call.func) and call.args
                        and _wrapped_is_multi_device(call.args[0],
                                                     coll_fns)
                    )
                if dispatches:
                    ln = getattr(node, "lineno", 0)
                    snippet = lines[ln - 1].strip() \
                        if 0 < ln <= len(lines) else ""
                    findings.append(make_finding(
                        "GL-R305", path, ln,
                        "Python loop dispatches a multi-device jitted "
                        "computation per iteration — each dispatch is a "
                        "collective rendezvous; the resulting launch "
                        "storm deadlocks XLA:CPU gangs",
                        snippet=snippet,
                    ))


def lint_source(source: str, path: str, *,
                external_coll: frozenset = frozenset()) -> list[Finding]:
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [make_finding(
            "GL-R303", path, e.lineno or 0,
            f"unparseable module skipped ({e.msg})",
            hint="fix the syntax error so the pass can see this file",
        )]
    lines = source.splitlines()
    helpers = _KeyHelperIndex(tree)
    findings: list[Finding] = []
    linter = _FnLinter(path, lines, helpers, findings)
    class_map = {
        node.name: node for node in ast.walk(tree)
        if isinstance(node, ast.ClassDef)
    }
    reported: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            linter.run_common(node)
        elif isinstance(node, ast.ClassDef):
            _check_leader_blocking_reads(node, class_map, path, lines,
                                         findings, reported)
    _check_launch_storms(tree, path, lines, findings, external_coll)
    return findings


def run_control_pass(
    root: str, *, paths: list[str] | None = None,
) -> list[Finding]:
    """Lint ``runtime/`` + ``serve/`` + ``gateway/`` + ``obs/`` (or
    explicit ``paths``); labels are root-relative. The whole tree under
    ``root`` is indexed first (xmodule.CrossIndex) so GL-R305 sees
    collective-bearing functions imported from modules outside the
    linted set — e.g. a jitted wrapper in ``runtime/`` around a sync
    helper defined in ``parallel/``."""
    from tpu_sandbox.analysis import xmodule
    from tpu_sandbox.analysis.collective_pass import iter_py_files

    if paths is None:
        paths = []
        for pkg in ("runtime", "serve", "gateway", "obs", "deploy"):
            pkg_dir = os.path.join(root, "tpu_sandbox", pkg)
            if os.path.isdir(pkg_dir):
                for fn in sorted(os.listdir(pkg_dir)):
                    if fn.endswith(".py"):
                        paths.append(os.path.join(pkg_dir, fn))
    # index every module the linted files could import from: the whole
    # tree (minus fixture corpora) plus the explicit paths themselves
    index_paths = set(iter_py_files(root, {"tests", "related"}))
    index_paths.update(paths)
    sources: dict[str, str] = {}
    for p in index_paths:
        try:
            with open(p, "r", encoding="utf-8") as f:
                sources[p] = f.read()
        except OSError:
            continue
    cross = xmodule.CrossIndex(root, sources)
    findings: list[Finding] = []
    for p in paths:
        rel = os.path.relpath(p, root)
        src = sources.get(p)
        if src is None:
            continue
        findings.extend(lint_source(
            src, rel,
            external_coll=frozenset(cross.imported_coll_fns(p))))
    return findings
