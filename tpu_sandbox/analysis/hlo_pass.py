"""Pass 2: step-function jaxpr + chipless AOT HLO lint.

Two layers, split so the cheap one is always available:

- **Pure functions** (``lint_jaxpr``, ``lint_hlo_text``,
  ``lint_schedule``, ``lint_int8_padding``) take already-built artifacts
  and emit findings. They import nothing heavy — the fixture tests drive
  them directly.
- **The driver** (:func:`run_hlo_pass`) builds the real engines chipless
  and feeds them through: it traces ``DataParallel`` (plain, ZeRO, and
  the int8-grad-compress / bucketed-overlap flag variants),
  ``PjitEngine``, ``PipelineParallel``, ``SeqParallel``, and the serve
  decode + bucketed-prefill steps to jaxprs on CPU
  devices, then AOT-compiles the DP/ZeRO steps against a multi-chip v5e
  topology (``tools/aot_v5e.make_topology``) to verify input donation
  from XLA's own ``memory_analysis`` and to check the overlapped
  grad-sync schedule via ``tools/hlo_schedule.schedule_report``.

The driver mutates process env (``make_topology`` forces compiled
Pallas kernels) — run it in a dedicated process (the ``graftlint`` CLI),
never inside a long-lived pytest process. AOT tools are single-process:
do not run two at once.

Donation is checked on the AOT TPU path only: the CPU backend does not
implement buffer donation (aliasing always reports 0 there), so a CPU
"check" would flag every engine. ``memory_analysis().alias_size_in_bytes``
vs ``output_size_in_bytes`` is the signal — parsing the
``input_output_alias={...}`` header breaks on nested braces.
"""

from __future__ import annotations

import os
import sys

from tpu_sandbox.analysis.findings import Finding, make_finding

#: convert_element_type upcasts smaller than this many elements are noise
#: (scalar losses, iteration counters); above it the fp32 copy of a bf16
#: tensor is a real HBM cost.
UPCAST_MIN_ELEMENTS = 4096

#: jaxpr primitives that round-trip through the host inside the step
HOST_TRANSFER_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "outside_call",
    "infeed", "outfeed", "host_callback_call",
})

#: int8 wire overhead (scales + alignment padding) above this fraction of
#: the all-in total means padding dominates the compression win.
INT8_OVERHEAD_THRESHOLD = 0.25

#: donated-aliasing below this fraction of output bytes counts as missing
#: (the non-aliasable remainder — the scalar loss — is well under 1%).
DONATION_MIN_FRACTION = 0.5


# --------------------------------------------------------------------------
# pure lints (no jax import; fixture tests call these directly)
# --------------------------------------------------------------------------

def _iter_eqns(jaxpr):
    """Yield every eqn in a (Closed)Jaxpr, recursing through call/scan/
    cond/shard_map sub-jaxprs found in eqn params."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)  # ClosedJaxpr -> Jaxpr
    for eqn in inner.eqns:
        yield eqn
        for val in eqn.params.values():
            stack = [val]
            while stack:
                v = stack.pop()
                if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
                    yield from _iter_eqns(v)
                elif isinstance(v, (list, tuple)):
                    stack.extend(v)


def lint_jaxpr(jaxpr, label: str) -> list[Finding]:
    """Lint one traced step jaxpr. ``label`` names the step (e.g. 'dp');
    findings carry ``file="<step:label>"`` and line 0."""
    file = f"<step:{label}>"
    findings: list[Finding] = []
    import numpy as np  # ubiquitous; fine even in the "pure" layer

    for eqn in _iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name == "convert_element_type":
            new = eqn.params.get("new_dtype")
            if new is None or "float32" not in str(new):
                continue
            aval = eqn.invars[0].aval
            src = str(getattr(aval, "dtype", ""))
            n = int(np.prod(getattr(aval, "shape", ()) or (1,)))
            if src == "bfloat16" and n >= UPCAST_MIN_ELEMENTS:
                findings.append(make_finding(
                    "GL-H202", file, 0,
                    f"bf16->f32 convert of {n} elements "
                    f"(shape {tuple(aval.shape)}) inside the step",
                    snippet=f"convert_element_type {tuple(aval.shape)} "
                            f"bf16->f32",
                ))
        elif name in HOST_TRANSFER_PRIMITIVES:
            findings.append(make_finding(
                "GL-H203", file, 0,
                f"host-transfer primitive '{name}' inside the step",
                snippet=f"primitive {name}",
            ))
    return findings


def lint_hlo_text(hlo_text: str, label: str) -> list[Finding]:
    """Host-transfer + large-upcast scan over optimized HLO text (the
    post-fusion complement of the jaxpr walk)."""
    import re

    file = f"<step:{label}>"
    findings: list[Finding] = []
    host_marks = ("SendToHost", "RecvFromHost", "custom_call_target=\"tpu_"
                  "host", "infeed(", "outfeed(")
    upcast = re.compile(r"=\s*f32\[([\d,]*)\][^ ]*\s+convert\(\s*%?\S*bf16")
    for i, line in enumerate(hlo_text.splitlines(), start=1):
        if any(m in line for m in host_marks):
            findings.append(make_finding(
                "GL-H203", file, 0,
                f"host transfer op in optimized HLO (module line {i})",
                snippet=line.strip()[:120],
            ))
            continue
        m = upcast.search(line)
        if m:
            dims = [int(d) for d in m.group(1).split(",") if d]
            n = 1
            for d in dims:
                n *= d
            if n >= UPCAST_MIN_ELEMENTS:
                findings.append(make_finding(
                    "GL-H202", file, 0,
                    f"bf16->f32 convert of {n} elements survived into "
                    f"optimized HLO (module line {i})",
                    snippet=line.strip()[:120],
                ))
    return findings


def lint_donation(label: str, *, donate_requested: bool, alias_bytes: int,
                  output_bytes: int) -> tuple[list[Finding], dict]:
    """GL-H201 verdict from XLA's memory-analysis numbers. Returns
    ``(findings, report_entry)``; the driver feeds real compiles through
    here, the fixture tests feed synthetic numbers."""
    frac = alias_bytes / output_bytes if output_bytes else 0.0
    entry = {
        "donate_requested": donate_requested,
        "alias_bytes": int(alias_bytes),
        "output_bytes": int(output_bytes),
        "alias_fraction": round(frac, 4),
        "donation": "verified" if frac >= DONATION_MIN_FRACTION
        else "missing",
    }
    if frac < DONATION_MIN_FRACTION:
        return [make_finding(
            "GL-H201", f"<step:{label}>", 0,
            f"step compiled with donate={donate_requested} but XLA aliased "
            f"only {int(alias_bytes)}/{int(output_bytes)} output bytes — "
            "TrainState buffers are not donated",
            snippet=f"alias_fraction={frac:.4f}",
        )], entry
    return [], entry


def lint_schedule(report: dict, label: str, *, overlap: bool) -> list[Finding]:
    """GL-H204 from a ``tools/hlo_schedule.schedule_report`` dict: overlap
    was requested but every grad all-reduce issues after the last backward
    compute op — nothing can hide under compute."""
    if not overlap:
        return []
    issues = report.get("all_reduce_issues_before_last_bwd_compute", 0)
    n_coll = report.get("collective_count", 0)
    if n_coll and not issues:
        return [make_finding(
            "GL-H204", f"<step:{label}>", 0,
            f"overlap_grad_sync requested but 0 of {n_coll} collectives "
            "issue before the last backward compute op",
            snippet=f"all_reduce_issues_before_last_bwd_compute=0 "
                    f"collective_count={n_coll}",
        )]
    return []


def lint_int8_padding(leaf_sizes, size: int, *, block: int = 256,
                      label: str = "dp",
                      threshold: float = INT8_OVERHEAD_THRESHOLD,
                      compress=None) -> tuple[list[Finding], dict]:
    """GL-H205 from the analytic wire model: fraction of the int8 all-in
    wire bytes that is scales + block/axis alignment padding. Returns
    ``(findings, wire_report)``."""
    if compress is None:
        from tpu_sandbox.parallel.collectives import CompressedAllReduce
        compress = CompressedAllReduce(mode="int8", block=block)
    wire = compress.wire_bytes(list(leaf_sizes), size)
    frac = wire["overhead"] / wire["total"] if wire["total"] else 0.0
    wire = dict(wire, overhead_fraction=round(frac, 4), world=size,
                block=block)
    if frac > threshold:
        return [make_finding(
            "GL-H205", f"<step:{label}>", 0,
            f"int8 wire overhead (scales+padding) is {frac:.0%} of total "
            f"({wire['overhead']}/{wire['total']} bytes) at world={size}, "
            f"block={block}",
            snippet=f"int8 overhead_fraction={frac:.4f}",
        )], wire
    return [], wire


# --------------------------------------------------------------------------
# driver: build the real engines chipless and lint them
# --------------------------------------------------------------------------

def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _tools_on_path() -> None:
    tools = os.path.join(_repo_root(), "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)


def _trace_targets(steps) -> tuple[list[Finding], dict]:
    """Jaxpr-lint the requested engines on CPU devices (needs 8; the CLI
    sets XLA_FLAGS=--xla_force_host_platform_device_count=8 pre-import)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from tpu_sandbox.models import ConvNet
    from tpu_sandbox.train import TrainState

    findings: list[Finding] = []
    report: dict = {}
    devices = np.array(jax.devices()[:8])
    if devices.size < 8:
        report["jaxpr"] = {"status": "skipped",
                           "reason": f"only {devices.size} devices"}
        return findings, report

    model = ConvNet(use_bn=False)
    tx = optax.sgd(1e-2, momentum=0.9)
    state = jax.eval_shape(lambda: TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx,
    ))
    imgs = jax.ShapeDtypeStruct((64, 28, 28, 1), jnp.float32)
    labs = jax.ShapeDtypeStruct((64,), jnp.int32)
    mesh = Mesh(devices, ("data",))

    def trace(label, fn, *args):
        try:
            jaxpr = fn.trace(*args).jaxpr
        except Exception as e:
            report[label] = {"status": "trace-failed", "error": str(e)[:200]}
            return
        fnd = lint_jaxpr(jaxpr, label)
        findings.extend(fnd)
        report[label] = {"status": "traced", "findings": len(fnd)}

    from tpu_sandbox.parallel import DataParallel, PjitEngine

    if "dp" in steps:
        dp = DataParallel(model, tx, mesh)
        trace("dp", dp._compile_for(state), state, imgs, labs)
    if "zero" in steps:
        dpz = DataParallel(model, tx, mesh, zero=True)
        trace("zero", dpz._compile_for(state), state, imgs, labs)
    if "pjit" in steps:
        eng = PjitEngine(model, tx, mesh)
        trace("pjit", eng._build(state), state, imgs, labs)
    if "pipeline" in steps:
        from tpu_sandbox.models.transformer import TransformerConfig
        from tpu_sandbox.parallel import PipelineParallel

        cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=4, d_ff=64, max_len=64)
        mesh_pp = Mesh(devices.reshape(2, 4), ("data", "pipe"))
        pp = PipelineParallel(cfg, tx, mesh_pp, microbatches=2)
        pstate = jax.eval_shape(
            pp.init_state, jax.random.key(0),
            jnp.zeros((4, 64), jnp.int32),
        )
        toks = jax.ShapeDtypeStruct((4, 64), jnp.int32)
        trace("pipeline", pp._compile_for(pstate), pstate, toks, toks)
    # engine-flag variants: the same DP step graph is a different graph
    # under grad compression / bucketed overlap, and each has had its own
    # regression history — lint them as first-class steps
    if "dp-int8" in steps:
        dpc = DataParallel(model, tx, mesh, grad_compress="int8")
        trace("dp-int8", dpc._compile_for(state), state, imgs, labs)
    if "dp-overlap" in steps:
        dpo = DataParallel(model, tx, mesh, overlap_grad_sync=True)
        trace("dp-overlap", dpo._compile_for(state), state, imgs, labs)
    if "sp" in steps:
        from tpu_sandbox.models.transformer import TransformerConfig
        from tpu_sandbox.models.transformer import TransformerLM
        from tpu_sandbox.parallel import SeqParallel

        cfg_sp = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                   n_layers=2, d_ff=64, max_len=64)
        mesh_sp = Mesh(devices.reshape(2, 4), ("data", "sp"))
        sp = SeqParallel(
            lambda attn: TransformerLM(cfg_sp, attention_fn=attn),
            tx, mesh_sp)
        sstate = jax.eval_shape(
            sp.init_state, jax.random.key(0),
            jnp.zeros((2, 64), jnp.int32),
        )
        stoks = jax.ShapeDtypeStruct((2, 64), jnp.int32)
        trace("sp", sp._jitted, sstate, stoks, stoks, stoks)
    if "decode" in steps:
        from tpu_sandbox.models.transformer import TransformerConfig
        from tpu_sandbox.models.transformer import TransformerLM
        from tpu_sandbox.serve.cache import CacheConfig
        from tpu_sandbox.serve.decode import make_decode_fn, page_shapes

        cfg_d = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                  n_layers=2, d_ff=64, max_len=64)
        ccfg = CacheConfig(num_blocks=16, block_size=8,
                           max_blocks_per_seq=4)
        dparams = jax.eval_shape(
            lambda: TransformerLM(cfg_d).init(
                jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"])
        kd, vd = page_shapes(cfg_d, ccfg, jnp.float32)
        trace("decode", make_decode_fn(cfg_d, ccfg, 2),
              dparams, kd, vd,
              jax.ShapeDtypeStruct((2, 1), jnp.int32),
              jax.ShapeDtypeStruct((2,), jnp.int32),
              jax.ShapeDtypeStruct((2, ccfg.max_blocks_per_seq), jnp.int32))
    if "prefill" in steps:
        from tpu_sandbox.models.transformer import TransformerConfig
        from tpu_sandbox.models.transformer import TransformerLM
        from tpu_sandbox.serve.cache import CacheConfig
        from tpu_sandbox.serve.decode import make_prefill_fn, page_shapes

        cfg_p = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                  n_layers=2, d_ff=64, max_len=64)
        pcfg = CacheConfig(num_blocks=16, block_size=8,
                           max_blocks_per_seq=4)
        pparams = jax.eval_shape(
            lambda: TransformerLM(cfg_p).init(
                jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"])
        kp, vp = page_shapes(cfg_p, pcfg, jnp.float32)
        # one trace per bucket length: each bucket is its own static-shape
        # program in the serve AOT set, and padding scatters through the
        # null block have their own upcast/host-transfer surface
        for bucket in (8, 16):
            trace("prefill" if bucket == 8 else f"prefill-b{bucket}",
                  make_prefill_fn(cfg_p, pcfg),
                  pparams, kp, vp,
                  jax.ShapeDtypeStruct((1, bucket), jnp.int32),
                  jax.ShapeDtypeStruct((bucket,), jnp.int32),
                  jax.ShapeDtypeStruct((), jnp.int32))
    # second-wave engines (VERDICT: the lint only covers what it traces):
    # FSDP-as-specs, the full Megatron TP ruleset, expert parallelism, and
    # the per-stage MPMD programs each have collective/donation surfaces
    # the first-wave steps never exercise
    if "fsdp" in steps:
        engf = PjitEngine(model, tx, mesh, fsdp_axis="data")
        trace("fsdp", engf._build(state), state, imgs, labs)
    if "tp" in steps:
        from tpu_sandbox.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )
        from tpu_sandbox.parallel.pjit_engine import megatron_rules

        # every megatron-ruled dim divisible by the 4-way model axis
        cfg_tp = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                                   n_layers=2, d_ff=64, max_len=64)
        mesh_tp = Mesh(devices.reshape(2, 4), ("data", "model"))
        lm_tp = TransformerLM(cfg_tp)
        engt = PjitEngine(lm_tp, tx, mesh_tp, task="lm",
                          rules=megatron_rules("model"))
        tstate = jax.eval_shape(lambda: TrainState.create(
            lm_tp, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx))
        ttoks = jax.ShapeDtypeStruct((8, 16), jnp.int32)
        trace("tp", engt._build(tstate), tstate, ttoks, ttoks)
    if "ep" in steps:
        from jax.sharding import PartitionSpec as P

        from tpu_sandbox.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )

        cfg_ep = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                   n_layers=1, d_ff=64, max_len=64,
                                   n_experts=4, capacity_factor=2.0)
        mesh_ep = Mesh(devices.reshape(2, 4), ("data", "expert"))
        lm_ep = TransformerLM(cfg_ep)
        enge = PjitEngine(lm_ep, tx, mesh_ep, task="lm",
                          rules=[(r"w_(up|down)", P("expert", None, None))])
        estate = jax.eval_shape(lambda: TrainState.create(
            lm_ep, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx))
        etoks = jax.ShapeDtypeStruct((8, 16), jnp.int32)
        trace("ep", enge._build(estate), estate, etoks, etoks)
    if "mpmd" in steps:
        from tpu_sandbox.models.transformer import (
            TransformerConfig,
            TransformerLM,
        )
        from tpu_sandbox.mpmd.program import StageProgram, stage_params

        cfg_m = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                  n_layers=4, d_ff=64, max_len=64)
        # stage_params slices concrete leaves; a tiny real init is cheap
        flat_m = jax.tree.map(np.asarray, TransformerLM(cfg_m).init(
            jax.random.key(0), jnp.zeros((1, 16), jnp.int32))["params"])
        for s in (0, 1):
            prog = StageProgram(cfg_m, tx, s, 2, 2)
            absp = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                stage_params(flat_m, s, 2))
            if prog.is_first:
                x = jax.ShapeDtypeStruct((4, 16), jnp.int32)
            else:
                x = jax.ShapeDtypeStruct((4, 16, cfg_m.d_model), cfg_m.dtype)
            if prog.is_last:
                trace(f"mpmd-s{s}-loss_grad", prog.loss_grad, absp, x,
                      jax.ShapeDtypeStruct((4, 16), jnp.int32))
            else:
                trace(f"mpmd-s{s}-fwd", prog.fwd, absp, x)
                g = jax.eval_shape(prog.fwd, absp, x)
                trace(f"mpmd-s{s}-bwd", prog.bwd, absp, x, g)
    return findings, report


def _aot_targets(steps, *, topology: str, chips, overlap_check: bool,
                 int8_check: bool) -> tuple[list[Finding], dict]:
    """Donation + schedule + padding lint against a chipless v5e topology."""
    _tools_on_path()
    from aot_v5e import make_topology
    from hlo_schedule import build_overlapped_hlo, schedule_report

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from tpu_sandbox.models import ConvNet
    from tpu_sandbox.parallel import DataParallel
    from tpu_sandbox.train import TrainState

    findings: list[Finding] = []
    report: dict = {}
    topo = make_topology(topology, tuple(chips))
    devices = np.array(topo.devices)
    world = devices.size
    mesh = Mesh(devices, ("data",))

    model = ConvNet(use_bn=False)
    tx = optax.sgd(1e-2, momentum=0.9)
    state = jax.eval_shape(lambda: TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx,
    ))
    imgs = jax.ShapeDtypeStruct((world * 8, 28, 28, 1), jnp.float32)
    labs = jax.ShapeDtypeStruct((world * 8,), jnp.int32)

    def check_donation(label: str, engine) -> None:
        compiled = engine.lower_step(state, imgs, labs).compile()
        ma = compiled.memory_analysis()
        alias = getattr(ma, "alias_size_in_bytes", None)
        out = getattr(ma, "output_size_in_bytes", 0)
        if alias is None:
            report[label] = {"donation": "unknown",
                             "reason": "no alias_size_in_bytes"}
            return
        fnd, report[label] = lint_donation(
            label, donate_requested=engine._donate,
            alias_bytes=int(alias), output_bytes=int(out),
        )
        findings.extend(fnd)
        findings.extend(lint_hlo_text(compiled.as_text(), label))

    if "dp" in steps:
        check_donation("dp", DataParallel(model, tx, mesh))
    if "zero" in steps:
        check_donation("zero", DataParallel(model, tx, mesh, zero=True))

    if overlap_check:
        text = build_overlapped_hlo(devices, bucket_mb=0.02, overlap=True)
        sched = schedule_report(text)
        findings.extend(lint_schedule(sched, "dp-overlap", overlap=True))
        report["overlap_schedule"] = {
            "collective_count": sched["collective_count"],
            "issues_before_last_bwd":
                sched["all_reduce_issues_before_last_bwd_compute"],
            "exposed_comm_fraction": sched["exposed_comm_fraction"],
        }

    if int8_check:
        leaf_sizes = [
            int(np.prod(l.shape)) for l in jax.tree.leaves(state.params)
        ]
        fnd, wire = lint_int8_padding(leaf_sizes, world, label="dp")
        findings.extend(fnd)
        report["int8_wire"] = wire
    return findings, report


def run_hlo_pass(
    *,
    steps=("dp", "zero", "pjit", "pipeline", "dp-int8", "dp-overlap",
           "sp", "decode", "prefill", "fsdp", "tp", "ep", "mpmd"),
    aot: bool = True,
    topology: str = "v5e:2x2x1",
    chips=(2, 2, 1),
    overlap_check: bool = True,
    int8_check: bool = True,
) -> tuple[list[Finding], dict]:
    """Full Pass 2. Returns ``(findings, report)``; ``report`` carries the
    per-step donation/trace status the acceptance gate prints. With
    ``aot=False`` only the CPU jaxpr layer runs (donation is then
    'skipped', never 'missing' — CPU can't witness aliasing)."""
    findings, report = _trace_targets(steps)
    if aot:
        try:
            aot_findings, aot_report = _aot_targets(
                steps, topology=topology, chips=chips,
                overlap_check=overlap_check, int8_check=int8_check,
            )
            findings.extend(aot_findings)
            report["aot"] = aot_report
        except Exception as e:
            report["aot"] = {"status": "skipped",
                             "reason": f"{type(e).__name__}: {e}"[:300]}
    else:
        report["aot"] = {"status": "skipped", "reason": "aot disabled"}
    return findings, report
