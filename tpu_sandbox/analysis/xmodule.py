"""Cross-module call-graph resolution for the AST lint passes.

Pass 1 and GL-R305 used to summarize one module at a time, so a
collective hidden one ``import`` away was invisible: a rank-guarded call
to ``helpers.sync_all()`` linted clean even though ``sync_all``'s body
issues a ``pmean`` — exactly the divergence class the pass exists to
catch (the PR-6 carry-over). This module closes that hole without
importing any scanned code: it parses the whole file set, records each
module's import aliases, and runs the "bears a collective" fixed point
*globally*, so bearing propagates through ``from mod import helper`` and
``import mod`` / ``mod.helper()`` edges of any depth.

Scope, deliberately narrow (a lint heuristic, not an import system):

- ``import pkg.mod as m`` + ``m.f()``, ``from pkg.mod import f [as g]``,
  and multi-dotted receivers over plain name chains (``import pkg.mod``
  + ``pkg.mod.f()``, ``import pkg.mod as m`` + ``m.sub.f()``) resolve by
  longest alias prefix; ``from mod import *`` and receivers rooted at
  anything but a name do not — unresolvable edges stay silent, never
  noisy.
- Relative imports resolve against the importing module's package
  (``from .helpers import f`` inside ``pkg/mod.py`` targets
  ``pkg.helpers``).
- Only module-level functions travel across module edges; classes and
  methods resolve within their own module as before.
"""

from __future__ import annotations

import ast
import os


def module_name(path: str, root: str) -> str:
    """Dotted module name of ``path`` relative to ``root``:
    ``<root>/tpu_sandbox/parallel/collectives.py`` ->
    ``tpu_sandbox.parallel.collectives``. A package ``__init__.py`` names
    the package itself."""
    rel = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    rel = rel[:-3] if rel.endswith(".py") else rel
    parts = [p for p in rel.split(os.sep) if p not in (".", "")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def import_aliases(tree: ast.Module,
                   modname: str = "") -> dict[str, tuple[str, str | None]]:
    """Local alias -> (target module, remote name | None). ``None`` as
    the remote name marks a module alias (``import helpers [as h]``);
    a string marks a from-import of one name."""
    aliases: dict[str, tuple[str, str | None]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname is not None:
                    aliases[a.asname] = (a.name, None)
                else:
                    # `import a.b` binds `a` at runtime, but the only
                    # receiver shape that reaches a.b's functions is the
                    # full dotted path `a.b.f()` — key the alias by the
                    # dotted name; _external_bearing matches receivers
                    # by longest alias prefix
                    aliases[a.name] = (a.name, None)
        elif isinstance(node, ast.ImportFrom):
            target = node.module or ""
            if node.level:
                parts = modname.split(".") if modname else []
                # level 1 = this module's package, each extra level one up
                base = parts[:-node.level] if node.level <= len(parts) else []
                target = ".".join(base + ([target] if target else []))
            if not target:
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = (target, a.name)
    return aliases


class CrossIndex:
    """Per-module function indexes wired together across import edges.

    Built from ``{path: source}``; unparseable files drop out (the pass
    reports them through its own syntax-error finding). After
    construction every module's ``_FunctionIndex.bearing`` reflects the
    *global* fixed point, and its ``external`` hook answers for direct
    call sites whose target lives in another scanned module — so the
    per-module linters need no further changes."""

    def __init__(self, root: str, sources: dict[str, str]):
        # local import: collective_pass imports this module at top level
        from tpu_sandbox.analysis.collective_pass import _FunctionIndex

        self._by_path: dict[str, str] = {}
        self.indexes: dict[str, object] = {}
        self.aliases: dict[str, dict[str, tuple[str, str | None]]] = {}
        for path, src in sources.items():
            mod = module_name(path, root)
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue
            self._by_path[path] = mod
            self.indexes[mod] = _FunctionIndex(tree)
            self.aliases[mod] = import_aliases(tree, mod)
        self._propagate()
        for mod, idx in self.indexes.items():
            idx.external = self._resolver_for(mod)

    # -- querying -------------------------------------------------------------

    def index_for(self, path: str):
        """The (externally-wired) _FunctionIndex for ``path``, or None if
        the file failed to parse."""
        mod = self._by_path.get(path)
        return None if mod is None else self.indexes.get(mod)

    def imported_coll_fns(self, path: str) -> set[str]:
        """Local alias names in ``path`` that are from-imports of
        collective-bearing module-level functions elsewhere in the
        scanned set — what GL-R305 unions into its ``coll_fns``."""
        mod = self._by_path.get(path)
        if mod is None:
            return set()
        out = set()
        for alias, (tmod, tname) in self.aliases.get(mod, {}).items():
            if tname is not None and self._target_bearing(tmod, tname):
                out.add(alias)
        return out

    # -- resolution -----------------------------------------------------------

    def _target_bearing(self, tmod: str, tname: str) -> bool:
        idx = self.indexes.get(tmod)
        return idx is not None and bool(idx.bearing.get(tname, False))

    def _external_bearing(self, mod: str, recv: str | None,
                          name: str) -> bool:
        """Does a call ``recv.name()`` / ``name()`` from ``mod`` reach a
        collective-bearing function in another scanned module?"""
        amap = self.aliases.get(mod, {})
        if recv is not None:
            # longest alias prefix wins: `pkg.mod.f()` resolves through
            # `import pkg.mod` (alias key 'pkg.mod'); `m.sub.f()` through
            # `import pkg.mod as m` (alias 'm' + remainder '.sub')
            parts = recv.split(".")
            for cut in range(len(parts), 0, -1):
                tgt = amap.get(".".join(parts[:cut]))
                if tgt is None:
                    continue
                # module alias only: `obj.f()` on a from-imported object
                # is an ordinary method call, not a cross-module edge
                if tgt[1] is not None:
                    return False
                return self._target_bearing(
                    ".".join([tgt[0], *parts[cut:]]), name)
            return False
        tgt = amap.get(name)
        if tgt is not None and tgt[1] is not None:
            return self._target_bearing(tgt[0], tgt[1])
        return False

    def _resolver_for(self, mod: str):
        def resolve(recv: str | None, name: str) -> bool:
            return self._external_bearing(mod, recv, name)
        return resolve

    def _propagate(self) -> None:
        """Global bearing fixed point: local edges re-walk (already at
        their local fixed point, so they converge immediately) and
        import edges join the graph."""
        changed = True
        while changed:
            changed = False
            for mod, idx in self.indexes.items():
                for key, (cls, _has, calls) in idx.facts.items():
                    if idx.bearing.get(key, False):
                        continue
                    for via_self, recv, name in calls:
                        local = idx.resolve(name, cls, via_self)
                        if local:
                            hit = any(idx.bearing.get(t, False)
                                      for t in local)
                        else:
                            hit = (not via_self) and self._external_bearing(
                                mod, recv, name)
                        if hit:
                            idx.bearing[key] = True
                            changed = True
                            break
