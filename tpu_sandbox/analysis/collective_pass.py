"""Pass 1: static collective-consistency lint (the SPMD-divergence class).

A collective reached under a rank-, coords-, or process_index-conditioned
branch (or after a rank-conditioned early return) diverges the collective
sequence across ranks: the guarded ranks issue it, the others don't, and
the job hangs in the fabric with no error. Every multi-host framework has
this failure class; this pass catches it at parse time.

Mechanics (pure ``ast``, no imports of the scanned code):

- Collective call sites are recognized by *name*: ``lax.pmean``/``psum``/
  ``psum_scatter``/``all_gather``/``ppermute``/``all_to_all``, the
  :class:`~tpu_sandbox.parallel.collectives.CollectiveGroup` method
  surface, and the bucketed/compressed sync entry points
  (``sync_buckets``, ``pmean_tree``, ``int8_block_pmean``).
- Rank-likeness of a condition is a token scan of the test expression:
  identifiers/attributes such as ``rank``, ``process_index``, ``coords``,
  or calls to ``lax.axis_index`` / ``jax.process_index``.
- Each function gets a summary — "does it (transitively, through direct
  same-module calls) always issue a collective?" — propagated to a fixed
  point, so a call to a collective-bearing helper under a rank branch is
  flagged (GL-C103) exactly like a literal collective (GL-C101).
  ``lax.cond`` branches with a rank-like predicate are checked the same
  way (both branch callables must have the SAME collective footprint).
"""

from __future__ import annotations

import ast
import os

from tpu_sandbox.analysis.findings import Finding, make_finding

#: Call names that ARE collectives (jax.lax spellings + this repo's
#: CollectiveGroup methods + the bucketed/compressed sync entry points).
COLLECTIVE_NAMES = frozenset({
    "pmean", "psum", "psum_scatter", "pmax", "pmin",
    "all_gather", "ppermute", "all_to_all", "pshuffle",
    "all_reduce", "reduce_scatter", "broadcast", "shift",
    "compressed_all_reduce",
    "sync_buckets", "pmean_tree", "int8_block_pmean",
})

#: Identifier / attribute tokens that mark a condition as rank-derived.
RANK_TOKENS = frozenset({
    "rank", "local_rank", "ranks", "process_index", "process_id",
    "proc_id", "coords", "coord", "axis_index", "device_index",
    "is_leader", "agent_id",
})

_EXCLUDE_DIRS = {
    "__pycache__", ".git", ".pytest_cache", "build", "dist",
    ".eggs", "node_modules",
}


def _call_name(func: ast.AST) -> str | None:
    """Trailing name of a call target: ``lax.pmean`` -> 'pmean',
    ``group.all_reduce`` -> 'all_reduce', ``sync_buckets`` -> itself."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _is_rank_like(test: ast.AST) -> bool:
    for node in ast.walk(test):
        if isinstance(node, ast.Name) and node.id in RANK_TOKENS:
            return True
        if isinstance(node, ast.Attribute) and node.attr in RANK_TOKENS:
            return True
    return False


def _cond_desc(test: ast.AST) -> str:
    try:
        s = ast.unparse(test)
    except Exception:  # pragma: no cover - unparse is total on py>=3.9
        s = "<condition>"
    return s if len(s) <= 60 else s[:57] + "..."


def _via_self(func: ast.AST) -> bool:
    """Is this call target ``self.<something>``? Those resolve through the
    enclosing class's method table, never by bare-name coincidence."""
    return (isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name) and func.value.id == "self")


def _recv_name(func: ast.AST) -> str | None:
    """Dotted receiver of an attribute call: ``helpers.sync()`` ->
    'helpers', ``pkg.mod.fn()`` -> 'pkg.mod'. None for bare names,
    anything rooted at ``self``, and non-name roots (call results,
    subscripts) — only a plain name chain can be an imported-module
    path, which xmodule resolves by longest alias prefix."""
    if not isinstance(func, ast.Attribute):
        return None
    parts: list[str] = []
    node = func.value
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name) or node.id == "self":
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _base_name(expr: ast.AST) -> str | None:
    """Trailing name of a base-class expression (``Mixin``, ``mod.Mixin``)."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


class _FunctionIndex:
    """Per-module function table + transitive "bears a collective" summary.

    Keys are bare names for module-level functions and ``Class.method``
    for methods. ``self.foo()`` call sites resolve through the enclosing
    class's method table — own methods first, then same-module bases
    (BFS) — so two classes with a same-named method never shadow each
    other (the bug this replaces: the first ``_sync`` in the file used to
    win the bare-name slot and answer for every class). Plain-name calls
    resolve to the module-level function when one exists, else any-match
    across same-named methods (the conservative choice for ``obj.foo()``
    where ``obj``'s class is unknown). Nested defs index under their own
    name (closures calling helpers defined alongside them still resolve).
    """

    def __init__(self, tree: ast.Module):
        self.functions: dict[str, ast.AST] = {}
        #: every function exactly once: (key, enclosing class | None, node)
        self.entries: list[tuple[str, str | None, ast.AST]] = []
        self._bare: dict[str, list[str]] = {}
        self._bases: dict[str, list[str]] = {}
        #: cross-module hook, wired by xmodule.CrossIndex: callable
        #: (recv, name) -> bool answering "does this call reach a
        #: collective-bearing function in ANOTHER scanned module?"
        self.external = None
        self._collect(tree, None)
        self.bearing = self._summarize()

    def _collect(self, node: ast.AST, cls: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                key = f"{cls}.{child.name}" if cls else child.name
                if key in self.functions:  # redefinition / nested twin
                    n = 2
                    while f"{key}#{n}" in self.functions:
                        n += 1
                    key = f"{key}#{n}"
                self.functions[key] = child
                self.entries.append((key, cls, child))
                self._bare.setdefault(child.name, []).append(key)
                self._collect(child, cls)
            elif isinstance(child, ast.ClassDef):
                self._bases[child.name] = [
                    b for b in map(_base_name, child.bases) if b
                ]
                self._collect(child, child.name)
            else:
                self._collect(child, cls)

    def resolve(self, name: str, cls: str | None,
                via_self: bool) -> list[str]:
        """Candidate table keys a call to ``name`` may reach from a
        function whose enclosing class is ``cls``."""
        if via_self:
            seen: set[str] = set()
            queue = [cls] if cls else []
            while queue:
                c = queue.pop(0)
                if c in seen:
                    continue
                seen.add(c)
                key = f"{c}.{name}"
                if key in self.functions:
                    return [key]  # nearest definition wins, like the MRO
                queue.extend(self._bases.get(c, []))
            return []  # not in this module's hierarchy: unknowable
        if name in self.functions:
            return [name]
        return list(self._bare.get(name, []))

    def _direct_facts(self, fn: ast.AST) -> tuple[bool, set]:
        """(has a literal collective, (via_self, recv, name) of calls it
        makes) — counting only this function's own body, not nested
        defs. ``recv`` is the dotted name-chain attribute receiver (the
        only shape that can be an imported-module path), else None."""
        has = False
        calls: set[tuple[bool, str | None, str]] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                continue  # nested defs summarize separately
            if isinstance(node, ast.Call):
                name = _call_name(node.func)
                if name in COLLECTIVE_NAMES:
                    has = True
                elif name:
                    calls.add((_via_self(node.func),
                               _recv_name(node.func), name))
        return has, calls

    def _summarize(self) -> dict[str, bool]:
        #: kept on the instance: xmodule.CrossIndex re-walks these same
        #: edges for the global (cross-module) fixed point
        self.facts: dict[str, tuple] = {}
        for key, cls, fn in self.entries:
            has, calls = self._direct_facts(fn)
            self.facts[key] = (cls, has, calls)
        bearing = {key: has for key, (_, has, _) in self.facts.items()}
        changed = True
        while changed:  # fixed point over the (acyclic-enough) call graph
            changed = False
            for key, (cls, _, calls) in self.facts.items():
                if bearing[key]:
                    continue
                for via_self, _recv, name in calls:
                    if any(bearing.get(t, False)
                           for t in self.resolve(name, cls, via_self)):
                        bearing[key] = True
                        changed = True
                        break
        return bearing

    def bears_collective(self, name: str | None, *, cls: str | None = None,
                         via_self: bool = False,
                         recv: str | None = None) -> bool:
        if not name:
            return False
        candidates = self.resolve(name, cls, via_self)
        if candidates:
            return any(self.bearing.get(k, False) for k in candidates)
        # nothing local answers for this name: in a cross-module run the
        # call may target an imported function (never for self.-calls —
        # those stay inside the class hierarchy by construction)
        if self.external is not None and not via_self:
            return self.external(recv, name)
        return False


class _FunctionLinter(ast.NodeVisitor):
    """Walks ONE function body tracking rank-conditioned context and
    rank-conditioned early exits; nested defs are linted independently."""

    def __init__(self, path: str, lines: list[str], index: _FunctionIndex,
                 findings: list[Finding], cls: str | None = None):
        self.path = path
        self.lines = lines
        self.index = index
        self.findings = findings
        self.cls = cls  # enclosing class: scopes self.-call resolution
        self._rank_depth = 0          # inside how many rank-like branches
        self._divergent_exit: tuple[int, str] | None = None  # (line, cond)
        self._rank_names: set[str] = set()  # names assigned from axis_index

    def lint_function(self, fn: ast.AST) -> None:
        """Entry point: prescan for rank-derived names, then lint."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                derived = any(
                    isinstance(sub, ast.Call)
                    and _call_name(sub.func) in (
                        "axis_index", "process_index", "axis_index_groups",
                    )
                    for sub in ast.walk(node.value)
                )
                if derived:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self._rank_names.add(tgt.id)
        self.lint_body(fn.body)

    def _is_rank(self, test: ast.AST) -> bool:
        if _is_rank_like(test):
            return True
        return any(
            isinstance(sub, ast.Name) and sub.id in self._rank_names
            for sub in ast.walk(test)
        )

    # -- helpers -------------------------------------------------------------

    def _snippet(self, node: ast.AST) -> str:
        ln = getattr(node, "lineno", 0)
        return self.lines[ln - 1].strip() if 0 < ln <= len(self.lines) else ""

    def _emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(make_finding(
            rule, self.path, getattr(node, "lineno", 0), message,
            snippet=self._snippet(node),
        ))

    def _check_call(self, node: ast.Call) -> None:
        name = _call_name(node.func)
        if name in COLLECTIVE_NAMES:
            if self._rank_depth:
                self._emit(
                    "GL-C101", node,
                    f"collective '{name}' is reached only under a "
                    "rank-conditioned branch",
                )
            elif self._divergent_exit is not None:
                ln, cond = self._divergent_exit
                self._emit(
                    "GL-C102", node,
                    f"collective '{name}' sits after the rank-conditioned "
                    f"early exit at line {ln} (if {cond}: ...)",
                )
        elif self.index.bears_collective(name, cls=self.cls,
                                         via_self=_via_self(node.func),
                                         recv=_recv_name(node.func)):
            if self._rank_depth:
                self._emit(
                    "GL-C103", node,
                    f"call to '{name}' (whose body issues collectives) is "
                    "reached only under a rank-conditioned branch",
                )
            elif self._divergent_exit is not None:
                ln, cond = self._divergent_exit
                self._emit(
                    "GL-C102", node,
                    f"call to collective-bearing '{name}' sits after the "
                    f"rank-conditioned early exit at line {ln} "
                    f"(if {cond}: ...)",
                )
        if name == "cond" and len(node.args) >= 2 \
                and self._is_rank(node.args[0]):
            # lax.cond with a rank-dependent predicate: a collective inside
            # either branch executes on a data-dependent subset of ranks
            for branch in node.args[1:3]:
                self._branch_collectives(branch, node)

    def _branch_collectives(self, branch: ast.AST, site: ast.Call) -> None:
        if isinstance(branch, ast.Lambda):
            for sub in ast.walk(branch.body):
                if isinstance(sub, ast.Call):
                    name = _call_name(sub.func)
                    if name in COLLECTIVE_NAMES or \
                            self.index.bears_collective(
                                name, cls=self.cls,
                                via_self=_via_self(sub.func),
                                recv=_recv_name(sub.func)):
                        self._emit(
                            "GL-C101", site,
                            f"lax.cond on a rank-derived predicate runs "
                            f"collective-bearing '{name}' in one branch only",
                        )
                        return
        elif isinstance(branch, (ast.Name, ast.Attribute)):
            name = branch.id if isinstance(branch, ast.Name) else branch.attr
            ref_self = (isinstance(branch, ast.Attribute)
                        and isinstance(branch.value, ast.Name)
                        and branch.value.id == "self")
            ref_recv = _recv_name(branch) \
                if isinstance(branch, ast.Attribute) else None
            if self.index.bears_collective(name, cls=self.cls,
                                           via_self=ref_self,
                                           recv=ref_recv):
                self._emit(
                    "GL-C103", site,
                    f"lax.cond on a rank-derived predicate calls "
                    f"collective-bearing '{name}' in one branch only",
                )

    @staticmethod
    def _exits(body: list[ast.stmt]) -> bool:
        """Does this branch body end the surrounding control flow?"""
        return any(
            isinstance(s, (ast.Return, ast.Raise, ast.Break, ast.Continue))
            for s in body
        )

    # -- statement walk ------------------------------------------------------

    def lint_body(self, body: list[ast.stmt]) -> None:
        for stmt in body:
            self._lint_stmt(stmt)

    def _lint_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are linted as their own functions
        if isinstance(stmt, (ast.If, ast.While)):
            rank_like = self._is_rank(stmt.test)
            self._scan_exprs(stmt.test)
            if rank_like:
                self._rank_depth += 1
            self.lint_body(stmt.body)
            if isinstance(stmt, ast.If):
                # the else-branch of `if rank...` is just as conditioned
                self.lint_body(stmt.orelse)
            if rank_like:
                self._rank_depth -= 1
                if isinstance(stmt, ast.If) and self._divergent_exit is None \
                        and (self._exits(stmt.body)
                             or self._exits(stmt.orelse)):
                    self._divergent_exit = (
                        stmt.lineno, _cond_desc(stmt.test)
                    )
            elif isinstance(stmt, ast.While):
                pass
            if isinstance(stmt, ast.While):
                self.lint_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_exprs(stmt.iter)
            self.lint_body(stmt.body)
            self.lint_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_exprs(item.context_expr)
            self.lint_body(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self.lint_body(stmt.body)
            for h in stmt.handlers:
                self.lint_body(h.body)
            self.lint_body(stmt.orelse)
            self.lint_body(stmt.finalbody)
            return
        # plain statement: scan every expression inside it
        self._scan_exprs(stmt)

    def _scan_exprs(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(sub, ast.Call):
                self._check_call(sub)
            elif isinstance(sub, ast.IfExp) and self._is_rank(sub.test):
                for branch in (sub.body, sub.orelse):
                    for c in ast.walk(branch):
                        if isinstance(c, ast.Call):
                            name = _call_name(c.func)
                            if name in COLLECTIVE_NAMES or \
                                    self.index.bears_collective(
                                        name, cls=self.cls,
                                        via_self=_via_self(c.func),
                                        recv=_recv_name(c.func)):
                                self._emit(
                                    "GL-C101", sub,
                                    f"collective-bearing '{name}' inside a "
                                    "rank-conditioned ternary",
                                )


def lint_source(source: str, path: str, *,
                index: _FunctionIndex | None = None) -> list[Finding]:
    """Lint one module's source text; ``path`` labels the findings.
    ``index`` lets a whole-tree run pass the module's cross-module-wired
    _FunctionIndex (xmodule.CrossIndex) instead of a fresh local one."""
    if index is None:
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            return [make_finding(
                "GL-C101", path, e.lineno or 0,
                f"unparseable module skipped ({e.msg})",
                hint="fix the syntax error so the pass can see this file",
            )]
        index = _FunctionIndex(tree)
    lines = source.splitlines()
    findings: list[Finding] = []
    for _key, cls, fn in index.entries:
        linter = _FunctionLinter(path, lines, index, findings, cls)
        linter.lint_function(fn)
    return findings


def iter_py_files(root: str, exclude_dirs: set[str] | None = None):
    exclude = _EXCLUDE_DIRS | (exclude_dirs or set())
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(d for d in dirnames if d not in exclude)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def run_collective_pass(
    root: str,
    *,
    paths: list[str] | None = None,
    exclude_dirs: set[str] | None = None,
) -> list[Finding]:
    """Lint every Python file under ``root`` (or just ``paths``); findings
    carry root-relative file labels. ``tests`` is excluded by default —
    fixture corpora deliberately violate the rules. The whole file set is
    indexed together (xmodule.CrossIndex) before any file is linted, so
    collective-bearing calls hidden behind an import resolve."""
    from tpu_sandbox.analysis import xmodule

    if paths is None:
        exclude = (exclude_dirs or set()) | {"tests", "related"}
        paths = list(iter_py_files(root, exclude))
    sources: dict[str, str] = {}
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as f:
                sources[p] = f.read()
        except OSError:
            continue
    cross = xmodule.CrossIndex(root, sources)
    findings: list[Finding] = []
    for p, src in sources.items():
        rel = os.path.relpath(p, root)
        findings.extend(lint_source(src, rel, index=cross.index_for(p)))
    return findings
