"""Rank-strided dataset sharding with torch-DistributedSampler parity.

The reference shards data with torch.utils.data.DistributedSampler
(mnist_distributed.py:73-75): pad the index list by wrapping to a multiple
of ``num_replicas``, then rank r takes indices[r::num_replicas]. The
shuffle stream is seeded ``seed + epoch``; the reference never calls
``set_epoch`` so every epoch reuses the epoch-0 order (SURVEY §2.1 C14 —
a quirk we preserve by defaulting epoch=0).
"""

from __future__ import annotations

import math

import numpy as np


class DistributedSampler:
    """Yields rank ``rank``'s shard of ``range(num_samples)``.

    Structure-compatible with torch's sampler: equal shard sizes via
    wrap-padding, rank-strided subsampling, seed+epoch shuffling.
    """

    def __init__(
        self,
        num_samples: int,
        num_replicas: int,
        rank: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
    ):
        if not 0 <= rank < num_replicas:
            raise ValueError(
                f"rank {rank} out of range for num_replicas={num_replicas}"
            )
        if num_samples <= 0:
            raise ValueError(f"num_samples must be positive, got {num_samples}")
        self.num_samples = num_samples
        self.num_replicas = num_replicas
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.per_replica = math.ceil(num_samples / num_replicas)
        self.total_size = self.per_replica * num_replicas

    def __len__(self) -> int:
        return self.per_replica

    def indices(self, epoch: int = 0) -> np.ndarray:
        """This rank's index shard for ``epoch`` (len == per_replica)."""
        if self.shuffle:
            order = np.random.default_rng(self.seed + epoch).permutation(
                self.num_samples
            )
        else:
            order = np.arange(self.num_samples)
        pad = self.total_size - self.num_samples
        if pad:
            # torch parity: indices += indices[:padding_size] (wrap, not repeat-last)
            reps = math.ceil(self.total_size / self.num_samples)
            order = np.tile(order, reps)[: self.total_size]
        return order[self.rank : self.total_size : self.num_replicas]
