"""Data layer: IDX MNIST reader, synthetic fallback, sharded sampling,
Python and native (C++) batch loaders.

Parity targets: torchvision MNIST + Resize (reference mnist_onegpu.py:51-54),
torch DataLoader (mnist_onegpu.py:55-59), DistributedSampler
(mnist_distributed.py:73-75). The 28->3000 resize is NOT here — it runs
on-device inside the train step.
"""

from tpu_sandbox.data.loader import (
    BatchLoader,
    PrefetchLoader,
    ShardedBatchLoader,
)
from tpu_sandbox.data.mnist import load_mnist, normalize, synthetic_mnist
from tpu_sandbox.data.sampler import DistributedSampler

__all__ = [
    "BatchLoader",
    "DistributedSampler",
    "PrefetchLoader",
    "ShardedBatchLoader",
    "load_mnist",
    "normalize",
    "synthetic_mnist",
]
