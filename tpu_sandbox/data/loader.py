"""Host-side batch iteration: BatchLoader (single replica) and
ShardedBatchLoader (all replicas' shards concatenated per step).

Role parity: torch DataLoader as used by the reference — shuffle=True
single-device (mnist_onegpu.py:55-59), shuffle=False + DistributedSampler
under DDP (mnist_distributed.py:76-81). One process drives all TPU ranks,
so the DDP-side loader yields the *global* batch: rank r's per-step slice
occupies rows [r*bs, (r+1)*bs) and equals exactly what rank r's own
DistributedSampler would have yielded — the DataParallel engine then
shards those rows onto the 'data' mesh axis.
"""

from __future__ import annotations

import math
import queue
import threading

import numpy as np

from tpu_sandbox.data.sampler import DistributedSampler


class BatchLoader:
    """Minibatch iterator over in-memory arrays.

    ``shuffle`` uses a ``seed + epoch`` stream (call ``set_epoch``);
    ``sampler`` restricts iteration to a DistributedSampler shard. The two
    are mutually exclusive, like torch's DataLoader.
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        *,
        shuffle: bool = False,
        seed: int = 0,
        drop_last: bool = False,
        sampler: DistributedSampler | None = None,
    ):
        if shuffle and sampler is not None:
            raise ValueError("shuffle and sampler are mutually exclusive")
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.images = images
        self.labels = labels
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.sampler = sampler
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _indices(self) -> np.ndarray:
        if self.sampler is not None:
            return self.sampler.indices(self.epoch)
        if self.shuffle:
            return np.random.default_rng(self.seed + self.epoch).permutation(
                len(self.images)
            )
        return np.arange(len(self.images))

    def _num_selected(self) -> int:
        return (
            self.sampler.per_replica if self.sampler is not None else len(self.images)
        )

    def __len__(self) -> int:
        n = self._num_selected()
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)

    def __iter__(self):
        idx = self._indices()
        if self.drop_last:
            idx = idx[: (len(idx) // self.batch_size) * self.batch_size]
        for start in range(0, len(idx), self.batch_size):
            sel = idx[start : start + self.batch_size]
            yield self.images[sel], self.labels[sel]


class ShardedBatchLoader:
    """Global-batch iterator for single-process data parallelism.

    Each step yields arrays of ``num_replicas * batch_size`` rows; rows
    [r*bs, (r+1)*bs) are rank r's DistributedSampler shard in order, so the
    stream is bit-identical to ``num_replicas`` independent per-rank loaders
    (asserted in tests/test_data_parallel.py). Shards stay equal-sized at
    the tail by wrap-padding each rank's index list to a batch multiple —
    the DP engine needs uniform shard shapes for one jit'd step.
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        num_replicas: int,
        *,
        shuffle: bool = True,
        seed: int = 0,
    ):
        self.images = images
        self.labels = labels
        self.batch_size = batch_size
        self.num_replicas = num_replicas
        self.samplers = [
            DistributedSampler(
                len(images), num_replicas, r, shuffle=shuffle, seed=seed
            )
            for r in range(num_replicas)
        ]
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def __len__(self) -> int:
        return math.ceil(self.samplers[0].per_replica / self.batch_size)

    def __iter__(self):
        steps = len(self)
        padded = steps * self.batch_size
        per_rank = []
        for s in self.samplers:
            idx = s.indices(self.epoch)
            if len(idx) < padded:  # wrap-pad so every step has full shards
                reps = math.ceil(padded / len(idx))
                idx = np.tile(idx, reps)[:padded]
            per_rank.append(idx)
        for step in range(steps):
            sel = np.concatenate(
                [idx[step * self.batch_size : (step + 1) * self.batch_size]
                 for idx in per_rank]
            )
            yield self.images[sel], self.labels[sel]


class PrefetchLoader:
    """Double-buffered background prefetch over any loader.

    While the device runs step N, a daemon thread assembles (and, when
    ``stage`` is given, device-places) batch N+1 — the input pipeline's
    half of the overlapped step. ``depth=2`` is classic double buffering:
    one batch in flight on device, one staged behind it; deeper queues buy
    nothing once the producer keeps one step ahead, and would hold that
    many extra batches in memory.

    ``stage``: optional ``(images, labels) -> staged_batch`` callable run
    in the producer thread — pass an engine's ``shard_batch`` so the
    host→device transfer itself overlaps the previous step's compute
    instead of serializing in front of it.

    Determinism contract (elastic resume depends on it): a single producer
    feeding a FIFO queue yields exactly the wrapped loader's batches in
    exactly its order, and ``set_epoch``/``__len__`` delegate — so the
    (epoch, offset) metadata the resumable loop checkpoints means the same
    thing with or without the prefetcher. The producer is a daemon thread,
    stopped and joined when iteration ends for ANY reason (exhaustion,
    preemption raising out of the loop, a consumer break).
    """

    def __init__(self, loader, *, depth: int = 2, stage=None):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self.loader = loader
        self.depth = depth
        self.stage = stage

    def set_epoch(self, epoch: int) -> None:
        self.loader.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loader)

    def __iter__(self):
        q: queue.Queue = queue.Queue(self.depth)
        stop = threading.Event()

        def put(item) -> bool:
            # bounded put that a consumer-side stop can always unstick
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for batch in self.loader:
                    if self.stage is not None:
                        batch = self.stage(*batch)
                    if not put(("batch", batch)):
                        return
                put(("done", None))
            except BaseException as e:  # re-raised on the consumer side
                put(("error", e))

        t = threading.Thread(
            target=produce, name="prefetch-loader", daemon=True
        )
        t.start()
        try:
            while True:
                kind, payload = q.get()
                if kind == "done":
                    return
                if kind == "error":
                    raise payload
                yield payload
        finally:
            stop.set()
            t.join(timeout=5.0)
