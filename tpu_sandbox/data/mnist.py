"""MNIST data source: pure-NumPy IDX reader + deterministic synthetic fallback.

Role parity: the reference pulls MNIST through torchvision
(mnist_onegpu.py:51-54, mnist_distributed.py:69-72) and resizes 28->3000 per
image on the host with PIL. Here the host only ever handles raw 28x28 bytes;
the 3000x3000 upsample happens on device inside the jit'd train step
(tpu_sandbox/train/trainer.py), because a host-side resize would starve the
TPU (180 MB/step H2D vs 4 KB/step).

With zero network egress the reference's download step
(mnist_onegpu.py:92-95) cannot be reproduced, so ``synthetic_mnist`` provides
a deterministic, class-separable stand-in: 10 fixed random prototypes plus
per-image noise. Same shapes, same dtypes, learnable by the ConvNet.
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path

import numpy as np

_FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _read_idx(path: Path) -> np.ndarray:
    """Read one IDX file (raw or .gz): >HBB magic, big-endian u32 dims, data."""
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        if zero != 0 or dtype_code != 0x08:
            raise ValueError(f"unsupported IDX header in {path}: "
                             f"magic={zero}, dtype=0x{dtype_code:02x}")
        shape = struct.unpack(f">{ndim}I", f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(shape)


def _find(data_dir: Path, stem: str) -> Path | None:
    for sub in ("", "MNIST/raw"):
        for suffix in ("", ".gz"):
            p = data_dir / sub / (stem + suffix)
            if p.exists():
                return p
    return None


def load_mnist(split: str, data_dir=None) -> tuple[np.ndarray, np.ndarray]:
    """Load MNIST IDX files -> (uint8 images [N,28,28], uint8 labels [N]).

    ``data_dir`` defaults to ``$MNIST_DIR`` or ``./data``. Accepts raw or
    gzipped files, flat or in torchvision's ``MNIST/raw`` layout.
    """
    if split not in _FILES:
        raise ValueError(f"unknown split {split!r}; expected 'train' or 'test'")
    data_dir = Path(data_dir or os.environ.get("MNIST_DIR", "data"))
    image_stem, label_stem = _FILES[split]
    image_path = _find(data_dir, image_stem)
    label_path = _find(data_dir, label_stem)
    if image_path is None or label_path is None:
        raise FileNotFoundError(
            f"MNIST IDX files for split {split!r} not found under {data_dir}; "
            "download them there or fall back to synthetic_mnist()"
        )
    return _read_idx(image_path), _read_idx(label_path)


def synthetic_mnist(n: int = 60000, seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """Deterministic synthetic MNIST: (uint8 [n,28,28], uint8 labels [n]).

    Ten fixed class prototypes — Gaussian blobs at class-specific positions,
    MNIST-like smooth strokes rather than white noise — plus per-image
    Gaussian noise. Prototype geometry is independent of ``seed`` so class
    identity is stable across calls. Smoothness matters: full-field random
    prototypes make the first BN+SGD steps overshoot, which would break the
    loss-decrease assertions in tests; blobs keep early gradients tame.
    """
    yy, xx = np.mgrid[0:28, 0:28].astype(np.float32)
    protos = []
    for c in range(10):
        cy = 6 + 4 * (c // 4) + 3 * ((c * 7) % 3)
        cx = 5 + 6 * (c % 4)
        protos.append(
            220.0 * np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2) / (2 * 3.0**2)))
        )
    protos = np.stack(protos)
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.uint8)
    noise = rng.normal(0.0, 15.0, size=(n, 28, 28)).astype(np.float32)
    images = np.clip(protos[labels] + noise, 0, 255).astype(np.uint8)
    return images, labels


def normalize(images: np.ndarray) -> np.ndarray:
    """uint8 [N,H,W] -> float32 [N,H,W,1] in [0,1] (ToTensor semantics,
    reference mnist_onegpu.py:54)."""
    return (images.astype(np.float32) / 255.0)[..., None]
