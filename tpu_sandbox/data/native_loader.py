"""ctypes front-end for the C++ prefetching data loader
(tpu_sandbox/native/src/dataloader.cpp).

Role parity: torch's C++ DataLoader machinery behind the reference's
``DataLoader(..., num_workers=0, pin_memory=True)`` (mnist_onegpu.py:55-59).
The native side does the per-batch host work — gather rows by index,
uint8 -> float32/255 (ToTensor semantics) — on a worker pool with a bounded
in-order prefetch ring, off the Python thread.

Index order (shuffle / sampler / epoch) is computed in NumPy with exactly
the same streams as the Python ``BatchLoader``, so the two loaders are
drop-in interchangeable batch-for-batch (asserted in tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import math

import numpy as np

from tpu_sandbox.data.sampler import DistributedSampler

_lib = None


def _load():
    global _lib
    if _lib is None:
        from tpu_sandbox.native.build import load_library

        lib = load_library("dataloader")
        lib.loader_create.restype = ctypes.c_void_p
        lib.loader_create.argtypes = [
            ctypes.c_void_p,  # images (uint8*)
            ctypes.c_void_p,  # labels (uint8*)
            ctypes.c_int64,   # n
            ctypes.c_int64,   # item_len
            ctypes.c_int64,   # batch
            ctypes.c_void_p,  # indices (int64*)
            ctypes.c_int64,   # n_indices
            ctypes.c_int,     # threads
            ctypes.c_int,     # prefetch
        ]
        lib.loader_next.restype = ctypes.c_int64
        lib.loader_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.loader_num_batches.restype = ctypes.c_int64
        lib.loader_num_batches.argtypes = [ctypes.c_void_p]
        lib.loader_destroy.restype = None
        lib.loader_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


class NativeBatchLoader:
    """Iterates (float32 [b,H,W,1] normalized images, int32 [b] labels).

    Takes *raw uint8* images/labels (the C side owns the normalize); a new
    native loader (fresh prefetch ring) is created per epoch iteration.
    """

    def __init__(
        self,
        images: np.ndarray,
        labels: np.ndarray,
        batch_size: int,
        *,
        shuffle: bool = False,
        seed: int = 0,
        sampler: DistributedSampler | None = None,
        threads: int = 2,
        prefetch: int = 4,
        drop_last: bool = False,
    ):
        if images.dtype != np.uint8 or labels.dtype != np.uint8:
            raise TypeError(
                "NativeBatchLoader requires raw uint8 images and labels "
                f"(got {images.dtype}/{labels.dtype}); it normalizes in C++"
            )
        if shuffle and sampler is not None:
            raise ValueError("shuffle and sampler are mutually exclusive")
        if batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got {batch_size}")
        self.images = np.ascontiguousarray(images)
        self.labels = np.ascontiguousarray(labels)
        self.item_shape = images.shape[1:]
        self.item_len = int(np.prod(self.item_shape))
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.sampler = sampler
        self.threads = threads
        self.prefetch = prefetch
        self.drop_last = drop_last
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _indices(self) -> np.ndarray:
        if self.sampler is not None:
            idx = self.sampler.indices(self.epoch).astype(np.int64)
        elif self.shuffle:
            idx = (
                np.random.default_rng(self.seed + self.epoch)
                .permutation(len(self.images))
                .astype(np.int64)
            )
        else:
            idx = np.arange(len(self.images), dtype=np.int64)
        if self.drop_last:
            idx = idx[: len(idx) - len(idx) % self.batch_size]
        return idx

    def __len__(self) -> int:
        n = self.sampler.per_replica if self.sampler is not None else len(self.images)
        if self.drop_last:
            return n // self.batch_size
        return math.ceil(n / self.batch_size)

    def __iter__(self):
        lib = _load()
        idx = np.ascontiguousarray(self._indices())
        if len(idx) == 0:
            return  # drop_last on a tiny dataset: zero batches, like BatchLoader
        handle = lib.loader_create(
            self.images.ctypes.data,
            self.labels.ctypes.data,
            len(self.images),
            self.item_len,
            self.batch_size,
            idx.ctypes.data,
            len(idx),
            self.threads,
            self.prefetch,
        )
        if not handle:
            raise RuntimeError("native loader_create failed (bad indices/args)")
        out_images = np.empty((self.batch_size, self.item_len), dtype=np.float32)
        out_labels = np.empty((self.batch_size,), dtype=np.int32)
        try:
            while True:
                count = lib.loader_next(
                    handle, out_images.ctypes.data, out_labels.ctypes.data
                )
                if count == 0:
                    break
                batch = out_images[:count].reshape(count, *self.item_shape)[..., None]
                yield batch.copy(), out_labels[:count].copy()
        finally:
            lib.loader_destroy(handle)
