from tpu_sandbox.native.build import load_library  # noqa: F401
