// Native data pipeline: multithreaded batch gather + normalize + prefetch.
//
// Role parity: the reference leans on torch's C++ DataLoader machinery
// (reference mnist_onegpu.py:55-59 — though it ran num_workers=0, the
// loader itself is C++) and torchvision's per-image host transforms. Here
// the host-side work per batch is: gather rows by index, convert uint8 ->
// float32/255 (ToTensor semantics). This library does that off the Python
// thread with a worker pool and a bounded in-order prefetch ring, so the
// accelerator never waits on the GIL.
//
// C ABI (ctypes-friendly); see tpu_sandbox/data/native_loader.py.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Slot {
  std::vector<float> images;
  std::vector<int32_t> labels;
  int64_t count = 0;     // rows in this batch
  int64_t expected = 0;  // the only job id allowed to write this slot next
  bool ready = false;
};

struct Loader {
  const uint8_t* images;   // [n, item_len] row-major, borrowed
  const uint8_t* labels;   // [n], borrowed
  int64_t item_len;
  int64_t batch;
  std::vector<int64_t> indices;
  int64_t n_batches;

  std::vector<Slot> ring;
  std::atomic<int64_t> next_job{0};
  int64_t next_out = 0;

  std::mutex mu;
  std::condition_variable cv_ready;   // consumer waits for slot ready
  std::condition_variable cv_free;    // workers wait for slot freed
  bool stopping = false;

  std::vector<std::thread> workers;

  void worker() {
    for (;;) {
      int64_t job = next_job.fetch_add(1);
      if (job >= n_batches) return;
      int64_t slot_idx = job % (int64_t)ring.size();
      Slot& slot = ring[slot_idx];
      {
        std::unique_lock<std::mutex> lk(mu);
        // wait for our turn on this slot: drained AND this job is next in
        // its rotation (two jobs ring-distance apart must not both write
        // after a single drain)
        cv_free.wait(lk, [&] {
          return stopping || (!slot.ready && slot.expected == job);
        });
        if (stopping) return;
      }
      int64_t start = job * batch;
      int64_t count = std::min(batch, (int64_t)indices.size() - start);
      slot.count = count;
      float* out = slot.images.data();
      for (int64_t r = 0; r < count; ++r) {
        const uint8_t* src = images + indices[start + r] * item_len;
        float* dst = out + r * item_len;
        for (int64_t i = 0; i < item_len; ++i) dst[i] = src[i] * (1.0f / 255.0f);
        slot.labels[(size_t)r] = labels[indices[start + r]];
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        slot.ready = true;
      }
      cv_ready.notify_all();
    }
  }
};

}  // namespace

extern "C" {

Loader* loader_create(const uint8_t* images, const uint8_t* labels, int64_t n,
                      int64_t item_len, int64_t batch, const int64_t* indices,
                      int64_t n_indices, int threads, int prefetch) {
  if (!images || !labels || batch <= 0 || n_indices <= 0 || n <= 0) return nullptr;
  for (int64_t i = 0; i < n_indices; ++i)
    if (indices[i] < 0 || indices[i] >= n) return nullptr;
  auto* ld = new Loader();
  ld->images = images;
  ld->labels = labels;
  ld->item_len = item_len;
  ld->batch = batch;
  ld->indices.assign(indices, indices + n_indices);
  ld->n_batches = (n_indices + batch - 1) / batch;
  int slots = std::max(2, prefetch);
  ld->ring.resize(slots);
  for (int i = 0; i < slots; ++i) {
    ld->ring[i].images.resize((size_t)batch * item_len);
    ld->ring[i].labels.resize((size_t)batch);
    ld->ring[i].expected = i;
  }
  int nthreads = std::max(1, threads);
  for (int t = 0; t < nthreads; ++t)
    ld->workers.emplace_back([ld] { ld->worker(); });
  return ld;
}

// Copies the next batch (in order) into out_images/out_labels.
// Returns the row count, or 0 when the epoch is exhausted.
int64_t loader_next(Loader* ld, float* out_images, int32_t* out_labels) {
  if (!ld || ld->next_out >= ld->n_batches) return 0;
  int64_t slot_idx = ld->next_out % (int64_t)ld->ring.size();
  Slot& slot = ld->ring[slot_idx];
  {
    std::unique_lock<std::mutex> lk(ld->mu);
    ld->cv_ready.wait(lk, [&] { return slot.ready; });
  }
  int64_t count = slot.count;
  std::memcpy(out_images, slot.images.data(),
              (size_t)count * ld->item_len * sizeof(float));
  std::memcpy(out_labels, slot.labels.data(), (size_t)count * sizeof(int32_t));
  {
    std::lock_guard<std::mutex> lk(ld->mu);
    slot.ready = false;
    slot.expected += (int64_t)ld->ring.size();
  }
  ld->cv_free.notify_all();
  ld->next_out++;
  return count;
}

int64_t loader_num_batches(Loader* ld) { return ld ? ld->n_batches : 0; }

void loader_destroy(Loader* ld) {
  if (!ld) return;
  {
    std::lock_guard<std::mutex> lk(ld->mu);
    ld->stopping = true;
    ld->next_job.store(ld->n_batches);
  }
  ld->cv_free.notify_all();
  for (auto& t : ld->workers) t.join();
  delete ld;
}

}  // extern "C"
