// Native rendezvous KV store — the TCPStore of this framework.
//
// Role parity: the reference's process-group bootstrap rides torch's C++
// TCPStore (env:// rendezvous behind MASTER_ADDR/MASTER_PORT,
// reference test_init.py:76-91; SURVEY §2.3). JAX's coordinator service
// covers the production path; this in-tree store covers the same role for
// framework-level coordination: rank discovery, key exchange, barriers —
// usable from multi-process CPU tests exactly like the reference's
// gloo-on-localhost strategy.
//
// Design: one server (thread-per-connection, in-memory map, blocking waits
// via condition_variable), tiny length-prefixed protocol:
//   request : op u8 | keylen u32 | key | vallen u32 | val
//   response: status u8 | vallen u32 | val
//   ops     : 'S' set, 'G' get (blocks until key exists), 'T' try-get
//             (non-blocking; status 2 when the key is missing), 'A' atomic
//             add (value is decimal i64; returns new value), 'D' delete,
//             'L' list keys with prefix (key = prefix; returns keys joined
//             by '\n'), 'P' delete every key with prefix (returns count),
//             'X' set with TTL (value = "<ttl-seconds>\n<payload>"; the key
//             expires lazily — purged on the next request after its
//             deadline, and treated as missing by G/T/L once expired).
//             TTL/prefix ops exist for coordination hygiene: claim keys
//             (fault claims, checkpoint shard-done claims) must not
//             accumulate across supervisor generations on a long-lived
//             server, nor alias a later generation's claims.
//             'H' hello (key = shared-secret token): when the server was
//             started with a token, this must be the FIRST frame on every
//             connection — wrong/missing token gets status 1 and the
//             socket closed. On a token-less server 'H' is a no-op, so
//             clients send it unconditionally whenever they hold a token.
// C ABI at the bottom; Python wrapper in tpu_sandbox/runtime/kvstore.py.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Server {
  int listen_fd = -1;
  int port = 0;
  std::string token;  // empty = no authentication (loopback deployments)
  std::map<std::string, std::string> data;
  std::map<std::string, Clock::time_point> expiry;  // keys set with TTL
  std::mutex mu;
  std::condition_variable cv;
  std::vector<std::thread> conns;
  std::vector<int> conn_fds;
  std::thread acceptor;
  std::mutex conns_mu;
  bool stopping = false;
};

bool read_exact(int fd, void* buf, size_t n) {
  auto* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  auto* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= (size_t)r;
  }
  return true;
}

bool read_blob(int fd, std::string& out) {
  uint32_t len;
  if (!read_exact(fd, &len, 4)) return false;
  len = ntohl(len);
  if (len > (64u << 20)) return false;  // 64MB sanity cap
  out.resize(len);
  return len == 0 || read_exact(fd, out.data(), len);
}

bool write_response(int fd, uint8_t status, const std::string& val) {
  uint32_t len = htonl((uint32_t)val.size());
  return write_exact(fd, &status, 1) && write_exact(fd, &len, 4) &&
         (val.empty() || write_exact(fd, val.data(), val.size()));
}

// Lazily drop expired keys. Caller holds srv->mu.
void purge_expired(Server* srv) {
  auto now = Clock::now();
  for (auto it = srv->expiry.begin(); it != srv->expiry.end();) {
    if (it->second <= now) {
      srv->data.erase(it->first);
      it = srv->expiry.erase(it);
    } else {
      ++it;
    }
  }
}

// Key present and not past its TTL deadline. Caller holds srv->mu.
bool key_alive(Server* srv, const std::string& key) {
  if (!srv->data.count(key)) return false;
  auto it = srv->expiry.find(key);
  return it == srv->expiry.end() || it->second > Clock::now();
}

// Shared-secret handshake: when the server carries a token, the FIRST
// frame of every connection must be op 'H' with key == token. Constant
// framing (same request shape as every other op) keeps the client code
// one line; a wrong/missing token gets one error response and the socket
// closed before any store op is served.
bool authenticate(Server* srv, int fd) {
  if (srv->token.empty()) return true;
  uint8_t op;
  std::string key, val;
  if (!read_exact(fd, &op, 1) || !read_blob(fd, key) || !read_blob(fd, val))
    return false;
  if (op != 'H' || key != srv->token) {
    write_response(fd, 1, "auth required");
    return false;
  }
  return write_response(fd, 0, "");
}

void serve_loop(Server* srv, int fd);

void serve_conn(Server* srv, int fd) {
  if (authenticate(srv, fd)) serve_loop(srv, fd);
  {
    // deregister before closing: fd numbers get reused, and a stale entry
    // in conn_fds would make stop() shutdown() an unrelated future socket
    std::lock_guard<std::mutex> lk(srv->conns_mu);
    auto& v = srv->conn_fds;
    v.erase(std::remove(v.begin(), v.end(), fd), v.end());
  }
  ::close(fd);
}

void serve_loop(Server* srv, int fd) {
  for (;;) {
    uint8_t op;
    if (!read_exact(fd, &op, 1)) break;
    std::string key, val;
    if (!read_blob(fd, key) || !read_blob(fd, val)) break;
    if (op == 'H') {
      // hello to an unauthenticated server (client env carries a token the
      // server doesn't): harmless no-op, keeps client setup unconditional
      if (!write_response(fd, 0, "")) break;
    } else if (op == 'S') {
      {
        std::lock_guard<std::mutex> lk(srv->mu);
        purge_expired(srv);
        srv->data[key] = val;
        srv->expiry.erase(key);  // a plain set clears any previous TTL
      }
      srv->cv.notify_all();
      if (!write_response(fd, 0, "")) break;
    } else if (op == 'X') {
      // value = "<ttl-seconds>\n<payload>"
      size_t nl = val.find('\n');
      if (nl == std::string::npos) {
        write_response(fd, 1, "bad ttl");
        break;
      }
      double ttl = std::strtod(val.substr(0, nl).c_str(), nullptr);
      {
        std::lock_guard<std::mutex> lk(srv->mu);
        purge_expired(srv);
        srv->data[key] = val.substr(nl + 1);
        srv->expiry[key] =
            Clock::now() + std::chrono::duration_cast<Clock::duration>(
                               std::chrono::duration<double>(ttl));
      }
      srv->cv.notify_all();
      if (!write_response(fd, 0, "")) break;
    } else if (op == 'G') {
      std::string out;
      {
        std::unique_lock<std::mutex> lk(srv->mu);
        srv->cv.wait(lk, [&] {
          return srv->stopping || key_alive(srv, key);
        });
        if (srv->stopping) break;
        out = srv->data[key];
      }
      if (!write_response(fd, 0, out)) break;
    } else if (op == 'T') {
      std::string out;
      bool found;
      {
        std::lock_guard<std::mutex> lk(srv->mu);
        purge_expired(srv);
        found = key_alive(srv, key);
        if (found) out = srv->data[key];
      }
      if (!write_response(fd, found ? 0 : 2, out)) break;
    } else if (op == 'L') {
      // key = prefix; newline-joined matches (keys never contain '\n' in
      // this framework's usage — they are path-like ASCII identifiers)
      std::string out;
      {
        std::lock_guard<std::mutex> lk(srv->mu);
        purge_expired(srv);
        for (auto it = srv->data.lower_bound(key);
             it != srv->data.end() && it->first.compare(0, key.size(), key) == 0;
             ++it) {
          if (!out.empty()) out += '\n';
          out += it->first;
        }
      }
      if (!write_response(fd, 0, out)) break;
    } else if (op == 'P') {
      int64_t count = 0;
      {
        std::lock_guard<std::mutex> lk(srv->mu);
        auto it = srv->data.lower_bound(key);
        while (it != srv->data.end() &&
               it->first.compare(0, key.size(), key) == 0) {
          srv->expiry.erase(it->first);
          it = srv->data.erase(it);
          ++count;
        }
      }
      if (!write_response(fd, 0, std::to_string(count))) break;
    } else if (op == 'A') {
      int64_t delta = std::strtoll(val.c_str(), nullptr, 10);
      int64_t now;
      {
        std::lock_guard<std::mutex> lk(srv->mu);
        purge_expired(srv);
        int64_t cur = 0;
        auto it = srv->data.find(key);
        if (it != srv->data.end())
          cur = std::strtoll(it->second.c_str(), nullptr, 10);
        now = cur + delta;
        srv->data[key] = std::to_string(now);
        srv->expiry.erase(key);  // counters do not expire
      }
      srv->cv.notify_all();
      if (!write_response(fd, 0, std::to_string(now))) break;
    } else if (op == 'D') {
      {
        std::lock_guard<std::mutex> lk(srv->mu);
        srv->data.erase(key);
        srv->expiry.erase(key);
      }
      if (!write_response(fd, 0, "")) break;
    } else {
      write_response(fd, 1, "bad op");
      break;
    }
  }
}

}  // namespace

extern "C" {

// bind_addr: dotted-quad listen address — nullptr/"" means loopback (the
// safe single-host default); "0.0.0.0" opens the store to the network for
// real cross-host deployment, which is what token (nullptr/"" = no auth)
// exists for: every connection must then open with the shared secret.
Server* kv_server_start(const char* bind_addr, int port, const char* token) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  if (bind_addr == nullptr || bind_addr[0] == '\0') {
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  } else if (::inet_pton(AF_INET, bind_addr, &addr.sin_addr) != 1) {
    ::close(fd);
    return nullptr;
  }
  addr.sin_port = htons((uint16_t)port);
  if (::bind(fd, (sockaddr*)&addr, sizeof(addr)) != 0 || ::listen(fd, 64) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, (sockaddr*)&addr, &alen);

  auto* srv = new Server();
  srv->listen_fd = fd;
  srv->port = ntohs(addr.sin_port);
  if (token != nullptr) srv->token = token;
  srv->acceptor = std::thread([srv] {
    for (;;) {
      int cfd = ::accept(srv->listen_fd, nullptr, nullptr);
      if (cfd < 0) return;  // listen socket closed -> shutdown
      // Request/response over multi-write() framing: without TCP_NODELAY,
      // Nagle + delayed ACK turns every round trip into ~40-90ms, which an
      // election or heartbeat loop pays per KV op.
      int one = 1;
      ::setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(srv->conns_mu);
      if (srv->stopping) {
        ::close(cfd);
        return;
      }
      srv->conn_fds.push_back(cfd);
      srv->conns.emplace_back([srv, cfd] { serve_conn(srv, cfd); });
    }
  });
  return srv;
}

int kv_server_port(Server* srv) { return srv ? srv->port : -1; }

void kv_server_stop(Server* srv) {
  if (!srv) return;
  {
    std::lock_guard<std::mutex> lk(srv->mu);
    std::lock_guard<std::mutex> lk2(srv->conns_mu);
    srv->stopping = true;
  }
  srv->cv.notify_all();
  ::shutdown(srv->listen_fd, SHUT_RDWR);
  ::close(srv->listen_fd);
  srv->acceptor.join();
  {
    // unblock conn threads parked in read() on still-open client sockets —
    // without this, stop() deadlocks whenever a client outlives the server
    std::lock_guard<std::mutex> lk(srv->conns_mu);
    for (int fd : srv->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : srv->conns) t.join();
  delete srv;
}

int kv_connect(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      ::connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

static bool send_req(int fd, char op, const char* key, int64_t klen,
                     const char* val, int64_t vlen) {
  uint8_t opb = (uint8_t)op;
  uint32_t kl = htonl((uint32_t)klen), vl = htonl((uint32_t)vlen);
  return write_exact(fd, &opb, 1) && write_exact(fd, &kl, 4) &&
         (klen == 0 || write_exact(fd, key, (size_t)klen)) &&
         write_exact(fd, &vl, 4) && (vlen == 0 || write_exact(fd, val, (size_t)vlen));
}

// Returns value length (copied into out, up to out_cap), -2 when the
// server reports key-missing (try-get), or -1 on error.
int64_t kv_request(int fd, char op, const char* key, int64_t klen,
                   const char* val, int64_t vlen, char* out, int64_t out_cap) {
  if (!send_req(fd, op, key, klen, val, vlen)) return -1;
  uint8_t status;
  if (!read_exact(fd, &status, 1)) return -1;
  std::string resp;
  if (!read_blob(fd, resp)) return -1;
  if (status == 2) return -2;
  if (status != 0) return -1;
  int64_t n = (int64_t)resp.size();
  if (out && out_cap > 0) std::memcpy(out, resp.data(), (size_t)std::min(n, out_cap));
  return n;
}

void kv_close(int fd) { ::close(fd); }

}  // extern "C"
