"""Build-on-first-use for the in-tree C++ runtime components.

The reference's native layer ships precompiled inside torch wheels; here
the sources live in tpu_sandbox/native/src/ and compile once per machine
into native/lib/ (g++ -O3 -shared -fPIC). No pybind11 — plain C ABIs
loaded with ctypes.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from pathlib import Path

_ROOT = Path(__file__).parent
_SRC = _ROOT / "src"
_LIB = _ROOT / "lib"


class NativeBuildError(RuntimeError):
    pass


def build_library(name: str, *, force: bool = False) -> Path:
    """Compile src/<name>.cpp -> lib/<name>.so if missing/stale; return path."""
    src = _SRC / f"{name}.cpp"
    if not src.exists():
        raise NativeBuildError(f"no such native source: {src}")
    _LIB.mkdir(exist_ok=True)
    out = _LIB / f"{name}.so"
    if not force and out.exists() and out.stat().st_mtime >= src.stat().st_mtime:
        return out
    # build to a temp file then atomic-rename: concurrent builders race safely
    with tempfile.NamedTemporaryFile(
        dir=_LIB, suffix=".so.tmp", delete=False
    ) as tmp:
        tmp_path = Path(tmp.name)
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        str(src), "-o", str(tmp_path),
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        tmp_path.unlink(missing_ok=True)
        raise NativeBuildError(
            f"g++ failed for {name}:\n{proc.stderr[-2000:]}"
        )
    os.replace(tmp_path, out)
    return out


def load_library(name: str) -> ctypes.CDLL:
    return ctypes.CDLL(str(build_library(name)))
