"""The gateway process: one socket front door for the replica fleet.

``Gateway`` terminates client TCP connections (wire protocol in
``gateway/wire.py``), decides routing and admission per request, and
talks to the serve plane through the same KV keys replicas use — it is a
*client* of the serve protocol, not a new authority. Every correctness
property (claim-once verdicts, lease scavenging, drain/requeue) is
enforced by that protocol underneath; the gateway only decides *where*
work lands and *whether* it is worth landing at all.

Per admitted request:

1. hash the prompt's full blocks (``serve/cache.chain_digest``) with the
   fleet's block size;
2. match against the replica digests cached from ``serve/load/<tag>``
   reports; route to the deepest resident-prefix match via that replica's
   targeted queue (``serve/tq/<tag>/``), falling back to least-loaded,
   falling back to the shared queue when no report is fresh;
3. before enqueueing, run the admission policy (SLO feasibility by
   default). A door shed claims ``serve/done/<rid>`` and writes an
   explicit SHED verdict — the audit invariant "every rid gets exactly
   one terminal verdict" holds no matter where the shed happens.

Load reports are cached with *local* staleness: the gateway stamps
``time.monotonic()`` when a report's bytes change and ages against that
stamp — never wall-clock arithmetic against the replica's own clock
(cross-host skew; GL-R302). A report the KV TTL already expired drops
out of the table entirely on the next refresh.

The server is a plain asyncio loop on a daemon thread: the KV round
trips it performs per request are sub-millisecond against the local
store, so handlers call them inline; only verdict *waits* yield the loop
(``asyncio.sleep`` polling), keeping every other connection live while
one blocks on a slow decode.

**HA**: any number of gateways may front the same store — all shared
state (load reports, verdict slots, claim markers) already lives in the
KV store, and claim-once ``serve/done/<rid>`` arbitration makes
concurrent door sheds, hedges, and clears race-safe by construction.
Each gateway registers a TTL'd ``gateway/hb/<id>`` lease so clients and
the chaos harness can discover the live set
(:func:`live_gateway_endpoints`); a SIGKILLed gateway simply drops off
that list when its lease lapses, and every request it routed is still
claimable, scavengable, and verdict-bearing without it. Requests are
stamped with the routing gateway's id (``write_request(..., gw=...)``)
so replicas can attribute claims per gateway — the chaos claim audit's
evidence that a killed gateway's in-flight work was finished by the
fleet, not lost.
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import os
import random
import signal
import socket
import ssl
import sys
import threading
import time
import weakref
from dataclasses import dataclass, field

from tpu_sandbox.gateway import wire
from tpu_sandbox.gateway import routing
from tpu_sandbox.gateway.fleet import DEFAULT_FLEET, FleetSpec, fleet_kv
from tpu_sandbox.obs import get_recorder, get_registry
from tpu_sandbox.obs.health import active_subjects
from tpu_sandbox.runtime.kvstore import KVClient
from tpu_sandbox.runtime.supervisor import ENV_KV_PORT
from tpu_sandbox.deploy.registry import read_shares
from tpu_sandbox.serve.cache import chain_digest
from tpu_sandbox.serve.replica import (enqueue, enqueue_to, k_done, k_lease,
                                       k_pin, k_req, k_result, write_request)

#: rid -> routed-replica memory per fleet, for hedge target exclusion; a
#: bounded ring — forgetting an old route only costs hedge precision
ROUTE_MEMORY = 4096

_LIVE_GATEWAYS: "weakref.WeakSet[Gateway]" = weakref.WeakSet()


def live_gateways() -> list["Gateway"]:
    """Gateways constructed but not yet closed — the conftest leak check."""
    return [g for g in _LIVE_GATEWAYS if not g.closed]


def k_gateway_hb(gateway_id: str) -> str:
    """The gateway's TTL'd liveness lease: value JSON {host, port, wall}."""
    return f"gateway/hb/{gateway_id}"


def live_gateway_endpoints(kv) -> list[tuple[str, str, int]]:
    """(gateway_id, host, port) for every gateway whose heartbeat lease is
    still live, sorted by id — the discovery surface a failover client or
    the chaos harness reads instead of a static endpoint list. A SIGKILLed
    gateway drops off when its lease TTL lapses; nothing deletes it."""
    out = []
    for key in kv.keys("gateway/hb/"):
        raw = kv.try_get(key)
        if raw is None:
            continue  # lapsed between list and read
        body = json.loads(raw)
        out.append((key[len("gateway/hb/"):],
                    str(body["host"]), int(body["port"])))
    return sorted(out)


@dataclass
class GatewayStats:
    connections: int = 0
    requests: int = 0
    admitted: int = 0
    shed_door: int = 0
    routed_prefix: int = 0      # targeted, with a resident-prefix match
    routed_balance: int = 0     # targeted, least-loaded fallback
    routed_shared: int = 0      # no fresh report anywhere: shared queue
    hedges: int = 0
    clears: int = 0
    auth_failures: int = 0
    protocol_errors: int = 0
    tls_handshake_failures: int = 0


@dataclass
class _ReplicaEntry:
    """One replica's last-seen load report plus the local change stamp."""

    raw: bytes
    report: dict
    changed_at: float  # time.monotonic() when ``raw`` last changed


@dataclass
class _FleetState:
    spec: FleetSpec
    kv: object  # fleet-scoped KV view, used only on the gateway thread
    replicas: dict = field(default_factory=dict)   # tag -> _ReplicaEntry
    inflight: dict = field(default_factory=dict)   # tag -> routed-unreported
    routes: dict = field(default_factory=dict)     # rid -> tag (bounded)
    last_refresh: float = -1e9
    # replica tags under an active health-plane replica_burn alert:
    # excluded from targeted routing until the alert's TTL expires
    unhealthy: frozenset = frozenset()
    # live canary traffic shares {version: share} from the deploy
    # controller (deploy/shares/<fleet>), None outside a canary phase
    shares: dict | None = None

    def note_route(self, rid: str, tag: str) -> None:
        self.routes.pop(rid, None)
        self.routes[rid] = tag
        while len(self.routes) > ROUTE_MEMORY:
            self.routes.pop(next(iter(self.routes)))


class Gateway:
    """Accepts client connections, routes requests across the fleet(s).

    One instance owns one listening socket, one KV connection (a clone of
    the one passed in — the gateway thread must not share a socket with
    the caller), and one routing table per fleet. ``start()`` returns
    once the port is bound; ``close()`` is idempotent and joins the
    thread."""

    def __init__(self, kv: KVClient, fleets: list[FleetSpec] | None = None,
                 *, host: str = "127.0.0.1", port: int = 0,
                 token: str | None = None, admission: str = "feasible",
                 policy: str = "prefix", policy_seed: int = 0,
                 max_report_age_s: float = 5.0,
                 refresh_min_s: float = 0.02, wait_cap_s: float = 60.0,
                 gateway_id: str | None = None, tls=None,
                 hb_ttl: float = 3.0):
        specs = fleets or [FleetSpec(name=DEFAULT_FLEET)]
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate fleet names: {names}")
        if admission not in ("feasible", "occupancy", "none"):
            raise ValueError(f"unknown admission mode {admission!r}")
        if policy not in ("prefix", "random"):
            raise ValueError(f"unknown routing policy {policy!r}")
        self._kv = kv.clone()
        self._fleets = {
            s.name: _FleetState(spec=s, kv=fleet_kv(self._kv, s.name))
            for s in specs
        }
        self._host = host
        self._requested_port = port
        self._token = token
        self.admission = admission
        # 'prefix' is the product; 'random' is the control arm the bench
        # measures the TTFT win against (uniform over fresh views)
        self.policy = policy
        self._rng = random.Random(policy_seed)
        self.max_report_age_s = max_report_age_s
        self.refresh_min_s = refresh_min_s
        self.wait_cap_s = wait_cap_s
        # the HA identity: stamped into every routed request (gw field)
        # and onto the gateway/hb/<id> liveness lease. The pid-derived
        # default is unique enough for ad-hoc runs; HA fleets and chaos
        # campaigns pass stable explicit ids.
        self.gateway_id = gateway_id or f"gw-{os.getpid()}"
        self._tls = tls  # ssl.SSLContext for the listener, or None
        self.hb_ttl = hb_ttl
        self.stats = GatewayStats()
        self.port: int | None = None
        self.closed = False
        self.killed = False
        self._thread: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._stop: asyncio.Event | None = None
        self._writers: set = set()   # open connections, for abrupt kill()
        self._started = threading.Event()
        self._startup_error: BaseException | None = None
        _LIVE_GATEWAYS.add(self)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "Gateway":
        self._thread = threading.Thread(
            target=self._thread_main, name="gateway", daemon=True)
        self._thread.start()
        if not self._started.wait(10.0):
            raise RuntimeError("gateway did not start within 10s")
        if self._startup_error is not None:
            raise RuntimeError("gateway failed to start") \
                from self._startup_error
        return self

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if self._loop is not None and self._thread is not None \
                and self._thread.is_alive() and self._stop is not None:
            with contextlib.suppress(RuntimeError):  # loop already gone
                self._loop.call_soon_threadsafe(self._stop.set)
            self._thread.join(timeout=5.0)
        if not self.killed:
            # a clean shutdown retires its lease immediately; a kill()
            # leaves it to lapse, exactly like a SIGKILLed process would
            with contextlib.suppress(ConnectionError, OSError):
                self._kv.delete(k_gateway_hb(self.gateway_id))
        self._kv.close()

    def kill(self) -> None:
        """Die abruptly: drop every open connection mid-whatever, stop
        answering, leave the heartbeat lease to TTL out — the in-process
        stand-in for SIGKILL that chaos campaigns fire. Unlike
        :meth:`close`, nothing is flushed or retired; clients see a
        mid-frame EOF and must fail over."""
        if self.closed:
            return
        self.killed = True
        if self._loop is not None and self._thread is not None \
                and self._thread.is_alive() and self._stop is not None:
            def _abort() -> None:
                for w in list(self._writers):
                    with contextlib.suppress(Exception):
                        transport = w.transport
                        if transport is not None:
                            transport.abort()
                self._stop.set()
            with contextlib.suppress(RuntimeError):
                self._loop.call_soon_threadsafe(_abort)
        self.close()

    def __enter__(self) -> "Gateway":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def _thread_main(self) -> None:
        try:
            asyncio.run(self._amain())
        except BaseException as e:  # surface bind errors to start()
            self._startup_error = e
        finally:
            self._started.set()

    async def _amain(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        # TLS handshake failures (plaintext probes, wrong-CA alerts, bad
        # protocol versions) never reach _handle, and asyncio's sslproto
        # only debug-logs them (SSLError is an OSError). The one hook that
        # sees every failed handshake is the SSLObject the context builds —
        # install a counting subclass bound to this gateway's stats. The
        # context must therefore not be shared across gateways.
        if self._tls is not None:
            stats = self.stats

            class _CountingSSLObject(ssl.SSLObject):
                def do_handshake(sslobj) -> None:
                    try:
                        super().do_handshake()
                    except (ssl.SSLWantReadError, ssl.SSLWantWriteError):
                        raise  # handshake still in progress, not a failure
                    except Exception:
                        stats.tls_handshake_failures += 1
                        raise

            self._tls.sslobject_class = _CountingSSLObject
        server = await asyncio.start_server(
            self._handle, self._host, self._requested_port,
            ssl=self._tls,
            ssl_handshake_timeout=5.0 if self._tls is not None else None)
        self.port = server.sockets[0].getsockname()[1]
        hb = asyncio.ensure_future(self._heartbeat())
        self._started.set()
        try:
            async with server:
                await self._stop.wait()
        finally:
            hb.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await hb
        # asyncio.run's shutdown cancels any still-open connection handlers

    async def _heartbeat(self) -> None:
        """Refresh the gateway/hb/<id> liveness lease on a half-TTL
        cadence. The lease is discovery, not authority: losing it (or the
        whole gateway) costs clients a failover, never a request."""
        body = json.dumps({"host": self._host, "port": self.port,
                           "wall": time.time()})
        while True:
            self._kv.set_ttl(k_gateway_hb(self.gateway_id), body,
                             self.hb_ttl)
            await asyncio.sleep(self.hb_ttl / 2)

    # -- connection handling -------------------------------------------------

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        self.stats.connections += 1
        self._writers.add(writer)
        sock = writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        authed = self._token is None
        try:
            while True:
                op, payload = await wire.read_frame(reader)
                if op == wire.OP_HELLO:
                    authed = await self._hello(writer, payload)
                    if not authed:
                        return
                    continue
                if not authed:
                    # any op before a good hello is an auth failure, even a
                    # well-formed one — close, never serve
                    self.stats.auth_failures += 1
                    await wire.write_response(
                        writer, wire.ST_AUTH, {"error": "hello required"})
                    return
                if op not in wire.KNOWN_OPS:
                    raise wire.ProtocolError(f"unknown op {op}")
                status, resp = await self._dispatch(op,
                                                   wire.decode_body(payload))
                await wire.write_response(writer, status, resp)
        except asyncio.IncompleteReadError as e:
            # bare EOF between frames is a clean disconnect; EOF mid-frame
            # is a protocol violation (truncated frame)
            if e.partial:
                self.stats.protocol_errors += 1
        except wire.ProtocolError:
            self.stats.protocol_errors += 1
        except (ConnectionError, OSError):
            pass  # peer vanished; nothing to answer
        finally:
            # a request exists only once its 'S' frame fully dispatched, so
            # closing here never strands one — it just ends the conversation
            self._writers.discard(writer)
            writer.close()
            with contextlib.suppress(ConnectionError, OSError):
                await writer.wait_closed()

    async def _hello(self, writer: asyncio.StreamWriter,
                     payload: bytes) -> bool:
        body = wire.decode_body(payload) if payload else {}
        if self._token is None or body.get("token") == self._token:
            await wire.write_response(writer, wire.ST_OK, {})
            return True
        self.stats.auth_failures += 1
        await wire.write_response(
            writer, wire.ST_AUTH, {"error": "bad token"})
        return False

    async def _dispatch(self, op: int, body: dict) -> tuple[int, dict]:
        if op == wire.OP_STATS:
            return wire.ST_OK, self._stats_body()
        if op == wire.OP_METRICS:
            return wire.ST_OK, self._metrics_body()
        try:
            fleet = self._fleet_of(body)
        except KeyError as e:
            return wire.ST_ERR, {"error": str(e)}
        try:
            if op == wire.OP_SUBMIT:
                return self._submit(fleet, body)
            if op == wire.OP_WAIT:
                return await self._wait(fleet, body)
            if op == wire.OP_TRY:
                return self._try(fleet, body)
            if op == wire.OP_HEDGE:
                return self._hedge(fleet, body)
            return self._clear(fleet, body)
        except (KeyError, TypeError, ValueError) as e:
            # a malformed *body* (missing rid, bad types) fails the one
            # request, not the connection — the framing was fine
            return wire.ST_ERR, {"error": f"{type(e).__name__}: {e}"}

    def _fleet_of(self, body: dict) -> _FleetState:
        name = body.get("fleet", DEFAULT_FLEET)
        state = self._fleets.get(name)
        if state is None:
            raise KeyError(f"unknown fleet {name!r} "
                           f"(serving: {sorted(self._fleets)})")
        return state

    # -- routing table -------------------------------------------------------

    def _refresh(self, fleet: _FleetState) -> None:
        """Re-read ``serve/load/`` if the cache is older than the refresh
        floor. A report whose bytes changed gets a new local change stamp
        and resets the routed-but-unreported count (the replica has since
        told us what it actually sees); a report the TTL expired drops its
        replica from the table."""
        if time.monotonic() - fleet.last_refresh < self.refresh_min_s:
            return
        fleet.last_refresh = time.monotonic()
        seen = set()
        for key in fleet.kv.keys("serve/load/"):
            raw = fleet.kv.try_get(key)
            if raw is None:
                continue  # expired between list and read
            tag = key[len("serve/load/"):]
            seen.add(tag)
            entry = fleet.replicas.get(tag)
            if entry is None or entry.raw != raw:
                fleet.replicas[tag] = _ReplicaEntry(
                    raw=raw, report=json.loads(raw),
                    changed_at=time.monotonic())
                fleet.inflight[tag] = 0
        for tag in [t for t in fleet.replicas if t not in seen]:
            del fleet.replicas[tag]
            fleet.inflight.pop(tag, None)
        # the health plane's verdict rides the same refresh cadence: a
        # replica with an active per-replica burn alert keeps reporting
        # (it is alive) but is excluded from targeted routing until the
        # alert's TTL lapses
        fleet.unhealthy = frozenset(
            active_subjects(fleet.kv, "replica_burn"))
        # canary traffic shares live at the store ROOT (the deploy plane
        # spans fleets), keyed by the fleet's name
        fleet.shares = read_shares(self._kv, fleet.spec.name)

    def _views(self, fleet: _FleetState) -> list[routing.ReplicaView]:
        now = time.monotonic()
        return [
            routing.parse_report(
                tag, entry.report, age_s=now - entry.changed_at,
                pending_local=fleet.inflight.get(tag, 0))
            for tag, entry in sorted(fleet.replicas.items())
        ]

    # -- ops -----------------------------------------------------------------

    def _submit(self, fleet: _FleetState, body: dict) -> tuple[int, dict]:
        self.stats.requests += 1
        rid = body["rid"]
        prompt = [int(t) for t in body["prompt"]]
        max_new = int(body["max_new_tokens"])
        deadline_s = body.get("deadline_s")
        if deadline_s is not None:
            deadline_s = float(deadline_s)
        rec = get_recorder()
        t_route = time.monotonic()
        self._refresh(fleet)
        chain = chain_digest(prompt, fleet.spec.block_size)
        # workload-trace riders on the route span: enough to replay this
        # request against a twin (obs/workload.py) without the payload
        route_args = {"rid": rid, "plen": len(prompt),
                      "chain": str(chain[-1]) if chain else "",
                      "fleet": fleet.spec.name or "default"}
        if deadline_s is not None:
            route_args["deadline_s"] = round(deadline_s, 6)
        views = routing.fresh(self._views(fleet), self.max_report_age_s)
        if fleet.shares:
            # canary split: draw a version by share, route within the
            # replicas acked at that version. No fresh replica at the
            # drawn version yet (swap mid-ack) -> route over everyone;
            # the version pin at claim keeps correctness regardless —
            # shares are a traffic split, never a correctness gate.
            drawn = routing.pick_by_share(fleet.shares, self._rng.random())
            if drawn is not None:
                pinned = routing.pin_version(views, drawn)
                if pinned:
                    views = pinned
        if self.policy == "random":
            healthy = [v for v in views if v.tag not in fleet.unhealthy]
            choice = None
            if healthy:
                v = healthy[self._rng.randrange(len(healthy))]
                choice = (v, routing.match_depth(chain, v))
        else:
            choice = routing.choose(chain, views, exclude=fleet.unhealthy)
        if choice is None:
            if deadline_s is not None and self.admission == "feasible":
                # a deadline-carrying request against a fleet with ZERO
                # fresh reports cannot have its feasibility estimated —
                # and a dead fleet would let it rot until the client's
                # whole retry budget burned. Fast-fail at the door with
                # the same claim-once verdict slot as door:infeasible.
                route_ctx = rec.complete(
                    "route", t_route, parent=body.get("tc"),
                    args={**route_args, "routed": "none"})
                with rec.span("door:no_replicas", parent=route_ctx,
                              args={"rid": rid}):
                    self._door_shed(fleet, rid, "no_replicas", 0.0)
                return wire.ST_OK, {"admitted": False,
                                    "reason": "no_replicas",
                                    "estimate_s": 0.0, "replica": ""}
            # no deadline to defend (or admission is not feasibility-
            # based): admit to the shared queue — a warming-up fleet will
            # claim it, and engine-side guardrails still apply
            route_ctx = rec.complete("route", t_route, parent=body.get("tc"),
                                     args={**route_args, "routed": "shared"})
            with rec.span("enqueue", parent=route_ctx,
                          args={"rid": rid}) as sp:
                self._enqueue_request(fleet, body, rid, prompt, max_new,
                                      deadline_s, target=None, tc=sp.ctx)
            self.stats.routed_shared += 1
            self.stats.admitted += 1
            return wire.ST_OK, {"admitted": True, "replica": "",
                                "depth": 0, "routed": "shared"}
        view, depth = choice
        ok, reason, est = routing.admit(
            view, mode=self.admission,
            service_rate_rps=fleet.spec.service_rate_rps,
            deadline_s=deadline_s,
            occupancy_bound=fleet.spec.occupancy_bound)
        route_ctx = rec.complete("route", t_route, parent=body.get("tc"),
                                 args={**route_args, "replica": view.tag})
        if not ok:
            # the trace's terminal span for a door shed: door:<reason>
            with rec.span(f"door:{reason}", parent=route_ctx,
                          args={"rid": rid}):
                self._door_shed(fleet, rid, reason, est)
            return wire.ST_OK, {"admitted": False, "reason": reason,
                                "estimate_s": round(est, 6),
                                "replica": view.tag}
        with rec.span("enqueue", parent=route_ctx,
                      args={"rid": rid, "target": view.tag}) as sp:
            self._enqueue_request(fleet, body, rid, prompt, max_new,
                                  deadline_s, target=view.tag, tc=sp.ctx)
        if depth > 0:
            self.stats.routed_prefix += 1
        else:
            self.stats.routed_balance += 1
        self.stats.admitted += 1
        return wire.ST_OK, {"admitted": True, "replica": view.tag,
                            "depth": depth, "estimate_s": round(est, 6),
                            "routed": "prefix" if depth else "balance"}

    def _enqueue_request(self, fleet: _FleetState, body: dict, rid: str,
                         prompt: list[int], max_new: int,
                         deadline_s: float | None,
                         target: str | None, tc=None) -> None:
        write_request(
            fleet.kv, rid, prompt, max_new,
            deadline_unix=None if deadline_s is None
            else time.time() + deadline_s,
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            seed=int(body.get("seed", 0)),
            tc=None if tc is None else tc.to_wire(),
            gw=self.gateway_id)
        if target is None:
            enqueue(fleet.kv, rid)
        else:
            enqueue_to(fleet.kv, target, rid)
            fleet.inflight[target] = fleet.inflight.get(target, 0) + 1
            fleet.note_route(rid, target)

    def _door_shed(self, fleet: _FleetState, rid: str, reason: str,
                   est: float) -> None:
        """Refuse at the door with the same claim-once verdict discipline
        replicas use: first publisher of serve/done/<rid> wins, so a
        door shed racing a retry's fresh execution still yields exactly
        one terminal verdict per rid."""
        self.stats.shed_door += 1
        get_registry().counter("gateway.shed.door",
                               labels={"reason": reason}).inc()
        if fleet.kv.add(k_done(rid)) == 1:
            fleet.kv.set(k_result(rid), json.dumps({
                "rid": rid, "verdict": "SHED", "reason": f"door:{reason}",
                "estimate_s": round(est, 6), "replica": "gateway"}))

    async def _wait(self, fleet: _FleetState,
                    body: dict) -> tuple[int, dict]:
        rid = body["rid"]
        timeout = min(float(body.get("timeout", 30.0)), self.wait_cap_s)
        deadline = time.monotonic() + timeout
        while True:
            raw = fleet.kv.try_get(k_result(rid))
            if raw is not None:
                return wire.ST_OK, json.loads(raw)
            if time.monotonic() >= deadline:
                return wire.ST_TIMEOUT, {"rid": rid, "timeout_s": timeout}
            await asyncio.sleep(0.01)

    def _try(self, fleet: _FleetState, body: dict) -> tuple[int, dict]:
        raw = fleet.kv.try_get(k_result(body["rid"]))
        if raw is None:
            return wire.ST_MISSING, {"rid": body["rid"]}
        return wire.ST_OK, json.loads(raw)

    def _hedge(self, fleet: _FleetState, body: dict) -> tuple[int, dict]:
        """Duplicate a verdictless, leaseless request onto the next-best
        replica, excluding wherever we routed it first (hedging onto the
        suspect straggler is no hedge at all). Claim-once verdicts make
        the duplicate harmless."""
        rid = body["rid"]
        if fleet.kv.try_get(k_result(rid)) is not None:
            return wire.ST_OK, {"hedged": False, "reason": "verdict"}
        if fleet.kv.try_get(k_lease(rid)) is not None:
            return wire.ST_OK, {"hedged": False, "reason": "lease"}
        raw = fleet.kv.try_get(k_req(rid))
        if raw is None:
            return wire.ST_MISSING, {"rid": rid}
        req = json.loads(raw)
        self._refresh(fleet)
        first = fleet.routes.get(rid, "")
        chain = chain_digest(req["prompt"], fleet.spec.block_size)
        views = routing.fresh(self._views(fleet), self.max_report_age_s)
        exclude = fleet.unhealthy | ({first} if first else set())
        choice = routing.choose(chain, views, exclude=frozenset(exclude))
        if choice is None:
            enqueue(fleet.kv, rid)
            replica = ""
        else:
            view, _depth = choice
            enqueue_to(fleet.kv, view.tag, rid)
            fleet.inflight[view.tag] = fleet.inflight.get(view.tag, 0) + 1
            replica = view.tag
        self.stats.hedges += 1
        return wire.ST_OK, {"hedged": True, "replica": replica}

    def _clear(self, fleet: _FleetState, body: dict) -> tuple[int, dict]:
        """Clear a terminal SHED verdict so a retry's fresh execution can
        publish — the socket form of ServeClient._retry's delete pair."""
        rid = body["rid"]
        fleet.kv.delete(k_result(rid))
        fleet.kv.delete(k_done(rid))
        # a retry is a NEW lifecycle: drop the weight-version pin so the
        # fresh execution pins whatever its claimer currently runs
        fleet.kv.delete(k_pin(rid))
        self.stats.clears += 1
        return wire.ST_OK, {"rid": rid}

    def _stats_body(self) -> dict:
        fleets = {}
        for name, fleet in self._fleets.items():
            self._refresh(fleet)
            fleets[name or "default"] = {
                "replicas": {
                    v.tag: {"queue_depth": v.queue_depth, "active": v.active,
                            "pending_local": v.pending_local,
                            "digest_len": len(v.digest),
                            "age_s": round(v.age_s, 3)}
                    for v in self._views(fleet)
                },
            }
        return {"stats": dict(self.stats.__dict__), "fleets": fleets,
                "admission": self.admission}

    def _metrics_body(self) -> dict:
        """The OP_METRICS scrape: this process's registry snapshot and
        recorder stats, plus each replica's recorder stats as last seen
        riding its TTL'd load report — one scrape sees whether ANY
        process in the fleet is silently dropping trace events."""
        replica_recorders = {}
        for name, fleet in self._fleets.items():
            self._refresh(fleet)
            for tag, entry in sorted(fleet.replicas.items()):
                stats = entry.report.get("recorder")
                if stats is not None:
                    replica_recorders[f"{name or 'default'}/{tag}"] = stats
        own = get_recorder().stats()
        return {"registry": get_registry().snapshot(),
                "recorder": own,
                "replica_recorders": replica_recorders,
                # fleet-wide drop total: the one number the
                # recorder_drops health rule and an operator both want
                "dropped_events": own["dropped"] + sum(
                    s.get("dropped", 0)
                    for s in replica_recorders.values())}


# -- gateway process main -----------------------------------------------------


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="serving gateway: routes client requests across the "
                    "replica fleet(s) behind one socket endpoint")
    p.add_argument("--kv-port", type=int,
                   default=int(os.environ.get(ENV_KV_PORT, "0")))
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--token",
                   default=os.environ.get("TPU_SANDBOX_GATEWAY_TOKEN"))
    p.add_argument("--admission", default="feasible",
                   choices=["feasible", "occupancy", "none"])
    p.add_argument("--policy", default="prefix",
                   choices=["prefix", "random"])
    p.add_argument("--fleets", default=None,
                   help="JSON list of FleetSpec kwargs; default is the "
                        "single bare-namespace fleet")
    p.add_argument("--gateway-id", default=None,
                   help="stable HA identity for the gateway/hb lease and "
                        "request stamping (default: gw-<pid>)")
    p.add_argument("--tls-cert", default=None,
                   help="server certificate PEM; with --tls-key, every "
                        "external connection must speak TLS")
    p.add_argument("--tls-key", default=None)
    args = p.parse_args(argv)
    if not args.kv_port:
        p.error(f"--kv-port or {ENV_KV_PORT} required")
    if bool(args.tls_cert) != bool(args.tls_key):
        p.error("--tls-cert and --tls-key go together")
    fleets = None
    if args.fleets:
        fleets = [FleetSpec(**f) for f in json.loads(args.fleets)]
    tls = None
    if args.tls_cert:
        tls = wire.make_server_ssl_context(args.tls_cert, args.tls_key)
    kv = KVClient(port=args.kv_port)
    gw = Gateway(kv, fleets, host=args.host, port=args.port,
                 token=args.token, admission=args.admission,
                 policy=args.policy, gateway_id=args.gateway_id, tls=tls)
    gw.start()
    print(f"[gateway] {gw.gateway_id} listening on {args.host}:{gw.port} "
          f"(admission={args.admission}, "
          f"tls={'on' if tls is not None else 'off'})", flush=True)
    stopped = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stopped.set())
    try:
        stopped.wait()
    finally:
        gw.close()
        kv.close()
        print(f"[gateway] closed: {gw.stats.__dict__}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
