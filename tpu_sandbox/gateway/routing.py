"""Routing + admission policy as pure functions: state in, decision out.

Nothing in this module touches a socket or the KV store — the gateway
assembles :class:`ReplicaView`\\ s from cached load reports and calls in;
tests drive the exact same code with hand-built views (the cluster-twin
discipline from ROADMAP item 6, applied from day one here).

Three decisions live here:

- **freshness** — a view is routable only while its load report is young.
  Age is LOCAL: the gateway stamps when it last saw a report's bytes
  change and bounds that local age (never wall-clock arithmetic against a
  remote stamp — cross-host skew makes that meaningless, and the KV TTL
  already drops dead replicas' reports entirely).
- **routing** — deepest resident-prefix match wins (the vLLM/SGLang
  production pattern): the request's chain hashes are matched against
  each replica's advertised digest, and the deepest hit minimizes cold
  prefill work. Ties, and requests with no resident prefix anywhere,
  fall back to least-loaded. Deterministic throughout (ties break on
  tag) so routing decisions are replayable from the report snapshot.
- **admission** — SLO feasibility: from a replica's queued work and a
  calibrated per-replica service rate, estimate when an admitted request
  would finish; if that already overruns the deadline, shed at the door
  with an explicit verdict instead of letting the request rot in a queue
  and be shed deep in the engine after burning its patience.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class ReplicaView:
    """One replica's routable state, assembled from its load report plus
    the gateway's local bookkeeping."""

    tag: str
    queue_depth: int = 0
    active: int = 0
    max_batch: int = 1
    free_block_frac: float = 1.0
    digest: frozenset = field(default_factory=frozenset)
    #: seconds since the gateway last saw this report's bytes CHANGE
    #: (local monotonic age, not remote wall arithmetic)
    age_s: float = 0.0
    #: requests this gateway routed here that the report predates
    pending_local: int = 0
    #: weight version the replica last acked (deploy rolling updates);
    #: 0 = boot weights / old-format report
    ver: int = 0

    @property
    def load(self) -> int:
        """Work in the replica's system as the gateway best knows it."""
        return self.queue_depth + self.active + self.pending_local


def parse_report(tag: str, report: dict, *, age_s: float,
                 pending_local: int = 0) -> ReplicaView:
    """Load-report JSON -> view. Missing fields degrade to a routable but
    unattractive default rather than erroring: an old-format replica is
    still a replica."""
    return ReplicaView(
        tag=tag,
        queue_depth=int(report.get("queue_depth", 0)),
        active=int(report.get("active", 0)),
        max_batch=int(report.get("max_batch", 1)),
        free_block_frac=float(report.get("free_block_frac", 1.0)),
        digest=frozenset(report.get("prefix_digest", ())),
        age_s=age_s,
        pending_local=pending_local,
        ver=int(report.get("ver", 0)),
    )


def fresh(views: list[ReplicaView], max_age_s: float) -> list[ReplicaView]:
    """Views whose reports are young enough to route on. A report past
    ``max_age_s`` describes a replica that existed, not one that does."""
    return [v for v in views if v.age_s <= max_age_s]


def match_depth(chain: list[str], view: ReplicaView) -> int:
    """How many leading full blocks of the request's prompt are resident
    on ``view``. A chain hash covers its whole prefix, so the DEEPEST
    digest member alone decides — intermediate misses (evicted mid-chain
    entries) don't shrink the answer the hash can still prove."""
    for depth in range(len(chain), 0, -1):
        if chain[depth - 1] in view.digest:
            return depth
    return 0


def least_loaded(views: list[ReplicaView]) -> ReplicaView:
    return min(views, key=lambda v: (v.load, v.tag))


def choose(chain: list[str], views: list[ReplicaView], *,
           exclude: frozenset = frozenset()) -> tuple[ReplicaView, int] | None:
    """Pick the routing target: deepest resident-prefix match, falling
    back to least-loaded when nothing is resident anywhere. Returns
    ``(view, match_depth)`` or None when no candidate remains (caller
    falls back to the shared queue). ``exclude`` removes tags — the
    hedge path must not duplicate onto the replica it is hedging."""
    views = [v for v in views if v.tag not in exclude]
    if not views:
        return None
    best = max(views, key=lambda v: (match_depth(chain, v), -v.load, v.tag))
    depth = match_depth(chain, best)
    if depth == 0:
        return least_loaded(views), 0
    return best, depth


def pick_by_share(shares: dict[int, float], draw: float) -> int | None:
    """Weighted draw over version-pinned traffic shares (the canary
    split): ``draw`` in [0, 1) lands in one version's normalized share
    band. Deterministic given the draw, ordered by version so the split
    is replayable. None when the shares carry no weight."""
    vers = sorted(v for v in shares if shares[v] > 0)
    total = sum(shares[v] for v in vers)
    if total <= 0:
        return None
    acc = 0.0
    for v in vers:
        acc += shares[v] / total
        if draw < acc:
            return v
    return vers[-1]


def pin_version(views: list[ReplicaView], ver: int) -> list[ReplicaView]:
    """Views currently running weight version ``ver`` — the canary split
    routes within this subset (caller falls back to all views when no
    fresh replica has acked ``ver`` yet)."""
    return [v for v in views if v.ver == int(ver)]


def estimate_completion_s(view: ReplicaView, service_rate_rps: float) -> float:
    """Seconds until a request admitted to ``view`` NOW would finish:
    everything already in its system plus this request, drained at the
    calibrated per-replica rate. Request-granularity M/D/1 — coarse on
    purpose; the calibration absorbs batching effects."""
    if service_rate_rps <= 0:
        raise ValueError(f"service rate must be > 0, got {service_rate_rps}")
    return (view.load + 1) / service_rate_rps


def feasible(view: ReplicaView, service_rate_rps: float,
             deadline_s: float | None) -> tuple[bool, float]:
    """(can this request make its deadline on this replica, estimate).
    No deadline means nothing to miss — always feasible."""
    est = estimate_completion_s(view, service_rate_rps)
    return (deadline_s is None or est <= deadline_s), est


def admit(view: ReplicaView, *, mode: str, service_rate_rps: float,
          deadline_s: float | None,
          occupancy_bound: int) -> tuple[bool, str, float]:
    """The door decision: (admit?, reason, estimate_s).

    - ``feasible``  — shed when the completion estimate overruns the
      deadline (reason ``infeasible``);
    - ``occupancy`` — the classic bound: shed when the replica's known
      queue already holds ``occupancy_bound`` requests (reason
      ``queue_full``), deadline ignored at the door;
    - ``none``      — always admit (the engine's own guardrails still
      apply downstream).
    """
    if mode == "feasible":
        ok, est = feasible(view, service_rate_rps, deadline_s)
        return ok, "" if ok else "infeasible", est
    est = estimate_completion_s(view, service_rate_rps)
    if mode == "occupancy":
        q = view.queue_depth + view.pending_local
        ok = q < occupancy_bound
        return ok, "" if ok else "queue_full", est
    if mode == "none":
        return True, "", est
    raise ValueError(f"unknown admission mode {mode!r}")
