"""``python -m tpu_sandbox.gateway`` — the gateway process entrypoint.

(`gateway/server.py` is imported by the package ``__init__``, so running
it via ``-m tpu_sandbox.gateway.server`` would execute it twice under
runpy; this shim is the canonical CLI.)
"""

import sys

from tpu_sandbox.gateway.server import main

if __name__ == "__main__":
    sys.exit(main())
