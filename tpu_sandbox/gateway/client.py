"""Socket-path serve client: ``ServeClient``'s API over the gateway wire.

``GatewayClient`` presents the same submit / result / retry / hedge
surface as ``serve/client.py::ServeClient`` but talks to a ``Gateway``
over TCP instead of to the KV store directly — the shape a real external
caller has, with no store credentials and no knowledge of the serve key
schema. Differences that exist because the door does:

- ``submit`` returns **False when the gateway sheds at the door**
  (infeasible deadline / full fleet). The verdict slot still holds an
  explicit SHED body, so ``result`` on a refused rid returns that verdict
  (or retries it, same as any other shed) rather than hanging.
- verdict waits are **server-side**: one 'W' frame parks on the gateway
  until the verdict lands or the bounded wait expires, instead of the
  client polling the store — clients pace retries/hedges between waits.
- retry and hedge go through the gateway ('C' clear + fresh 'S';
  'E' hedge), which re-routes with current fleet state — the retry of a
  shed request may land on a different replica than the original.

**Failover**: the client takes a gateway *list* (``endpoints``) and
treats every connection-shaped failure — connect refusal, mid-frame EOF,
hello timeout, TLS handshake that dies under it — as "this gateway is
gone, try the next", cycling with jittered backoff. Correctness across a
failover leans on the same store the gateways share: verdict slots,
claim markers, and queue entries all outlive any one gateway, so a
reissued 'W'/'T'/'C'/'E' is exactly the same operation against the same
state. The one op that is NOT blindly reissued is 'S': after a failover
mid-submit the client first polls the verdict slot ('T') on the new
gateway — a request whose verdict landed before the old gateway died is
returned, never re-executed. (A submit that died *before* the verdict is
reissued; re-enqueueing is harmless — replicas skip entries whose rid
already has a result, and claim-once publication arbitrates any race.)

**TLS**: pass ``tls=wire.make_client_ssl_context(ca_pem)`` and the
socket is wrapped before the first frame — the shared-secret hello rides
inside the encrypted channel. Auth rejection (ST_AUTH) is deterministic
and never fails over: every gateway shares the secret, so the next one
would only say no again.
"""

from __future__ import annotations

import random
import socket
import time
from dataclasses import dataclass, field

from tpu_sandbox.gateway import wire
from tpu_sandbox.obs import get_recorder, get_registry
from tpu_sandbox.serve.client import ClientStats, RetriesExhausted

__all__ = ["GatewayClient", "GatewayError", "GatewayAuthError",
           "RetriesExhausted"]


@dataclass
class _Pending:
    prompt: list[int]
    max_new_tokens: int
    deadline_s: float | None
    temperature: float
    top_k: int
    seed: int
    submitted_at: float = 0.0
    retries_left: int = 0
    hedged: bool = False
    # one entry per submit/retry: {submitted_at, shed_reason?, resolved_at?}
    attempts: list = field(default_factory=list)


class GatewayError(Exception):
    """The gateway answered ST_ERR — a request-level failure."""


class GatewayAuthError(GatewayError):
    """Hello refused: wrong or missing shared secret."""


class GatewayClient:
    """One caller's connection to the gateway fleet. Not thread-safe; make
    one per caller thread (they share the gateways, not this socket).

    ``port`` keeps the single-gateway call sites working; HA callers pass
    ``endpoints=[(host, port), ...]`` instead and the client fails over
    down the list (wrapping around, jittered backoff between full
    cycles). ``tls`` is an ``ssl.SSLContext`` from
    :func:`wire.make_client_ssl_context`, applied to every connection."""

    def __init__(self, port: int | None = None, *, host: str = "127.0.0.1",
                 token: str | None = None, fleet: str = "",
                 deadline_s: float | None = None, max_retries: int = 2,
                 hedge_after: float | None = None,
                 connect_timeout: float = 5.0,
                 endpoints: list[tuple[str, int]] | None = None,
                 tls=None, failover_cycles: int = 3,
                 backoff_base: float = 0.05, backoff_cap: float = 0.5):
        if endpoints is None:
            if port is None:
                raise ValueError("need port or endpoints")
            endpoints = [(host, int(port))]
        if not endpoints:
            raise ValueError("endpoints must not be empty")
        self.fleet = fleet
        self.deadline_s = deadline_s
        self.max_retries = max_retries
        self.hedge_after = hedge_after
        self.connect_timeout = connect_timeout
        self.failover_cycles = failover_cycles
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.stats = ClientStats()
        self._endpoints = [(str(h), int(p)) for h, p in endpoints]
        self._idx = 0  # endpoint currently connected (or next to try)
        self._tls = tls
        self._token = token
        self._rng = random.Random()  # backoff jitter only, never routing
        self._pending: dict[str, _Pending] = {}
        self._sock: socket.socket | None = None
        self._connect_any()

    @property
    def endpoint(self) -> tuple[str, int]:
        """The gateway this client is currently connected to."""
        return self._endpoints[self._idx]

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def __enter__(self) -> "GatewayClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- connection + failover -----------------------------------------------

    def _connect_one(self, host: str, port: int) -> socket.socket:
        """Connect + (optional) TLS wrap + hello, all under the connect
        timeout — a gateway that accepts but never answers hello is as
        dead as one that refuses the SYN."""
        sock = socket.create_connection((host, port),
                                       timeout=self.connect_timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            if self._tls is not None:
                sock = self._tls.wrap_socket(sock, server_hostname=host)
            if self._token is not None:
                wire.send_frame(sock, wire.OP_HELLO, {"token": self._token})
                status, body = wire.recv_response(sock)
                if status != wire.ST_OK:
                    raise GatewayAuthError(
                        body.get("error", "hello refused"))
            sock.settimeout(None)
            return sock
        except BaseException:
            sock.close()
            raise

    def _connect_any(self) -> None:
        """Walk the endpoint list from the current index until one
        connects; jittered backoff between full cycles. Auth rejection
        raises immediately (deterministic — the next gateway holds the
        same secret); only connection-shaped failures advance the walk."""
        last: Exception | None = None
        for cycle in range(self.failover_cycles):
            for _ in range(len(self._endpoints)):
                host, port = self._endpoints[self._idx]
                try:
                    self._sock = self._connect_one(host, port)
                    return
                except GatewayAuthError:
                    raise
                except (ConnectionError, TimeoutError, OSError) as e:
                    last = e
                    self._idx = (self._idx + 1) % len(self._endpoints)
            delay = min(self.backoff_cap,
                        self.backoff_base * (2 ** cycle))
            time.sleep(self._rng.uniform(0, delay))
        raise GatewayError(
            f"no gateway reachable (tried {self._endpoints} "
            f"x{self.failover_cycles} cycles)") from last

    def _failover(self) -> None:
        self.close()
        self._idx = (self._idx + 1) % len(self._endpoints)
        self.stats.failovers += 1
        get_registry().counter("client.failovers").inc()
        self._connect_any()

    def _call(self, op: int, body: dict) -> tuple[int, dict]:
        wire.send_frame(self._sock, op, dict(body, fleet=self.fleet))
        return wire.recv_response(self._sock)

    def _call_robust(self, op: int, body: dict) -> tuple[int, dict]:
        """One op, surviving gateway death: connection-shaped failures
        fail over and reissue. 'W'/'T'/'C'/'E' reissue verbatim (the
        store state they act on outlives the gateway); 'S' first re-polls
        the verdict slot so a request that already completed is never
        re-executed."""
        failed_over = False
        budget = self.failover_cycles * len(self._endpoints)
        while True:
            try:
                if failed_over and op == wire.OP_SUBMIT:
                    status, verdict = self._call(
                        wire.OP_TRY, {"rid": body["rid"]})
                    if status == wire.ST_OK:
                        # the old gateway died after the verdict landed;
                        # surface it as an admit — result() finds it
                        return wire.ST_OK, {
                            "admitted": True,
                            "replica": verdict.get("replica", ""),
                            "depth": 0, "routed": "failover"}
                return self._call(op, body)
            except (ConnectionError, TimeoutError, OSError) as e:
                failed_over = True
                budget -= 1
                if budget < 0:
                    # every reconnect succeeded but the op itself keeps
                    # dying mid-frame — stop chasing a flapping fleet
                    raise GatewayError(
                        f"op {op} kept failing across failovers") from e
                self._failover()

    def _checked(self, op: int, body: dict) -> tuple[int, dict]:
        status, resp = self._call_robust(op, body)
        if status == wire.ST_ERR:
            raise GatewayError(resp.get("error", "gateway error"))
        if status == wire.ST_AUTH:
            raise GatewayAuthError(resp.get("error", "auth required"))
        return status, resp

    # -- the ServeClient surface ---------------------------------------------

    def submit(self, rid: str, prompt, max_new_tokens: int, *,
               deadline_s: float | None = None, temperature: float = 0.0,
               top_k: int = 0, seed: int = 0) -> bool:
        """Route one request through the door. True when admitted; False
        when the gateway shed it there (its SHED verdict is already in
        place, and ``result`` will burn a retry on it like any shed)."""
        d = self.deadline_s if deadline_s is None else deadline_s
        p = _Pending(prompt=[int(t) for t in prompt],
                     max_new_tokens=int(max_new_tokens), deadline_s=d,
                     temperature=temperature, top_k=top_k, seed=seed,
                     submitted_at=time.time(),
                     retries_left=self.max_retries)
        p.attempts.append({"submitted_at": p.submitted_at})
        self._pending[rid] = p
        self.stats.submitted += 1
        return self._submit_body(rid, p)

    def _submit_body(self, rid: str, p: _Pending) -> bool:
        body = {"rid": rid, "prompt": p.prompt,
                "max_new_tokens": p.max_new_tokens}
        if p.deadline_s is not None:
            body["deadline_s"] = p.deadline_s
        if p.temperature > 0.0:
            body.update(temperature=p.temperature, top_k=p.top_k,
                        seed=p.seed)
        # the trace ROOT: every downstream span of this request chains
        # back to this submit via the tc carried in the wire frame
        with get_recorder().span("submit", args={"rid": rid}) as sp:
            if sp.ctx is not None:
                body["tc"] = sp.ctx.to_wire()
            _status, resp = self._checked(wire.OP_SUBMIT, body)
        return bool(resp.get("admitted"))

    def result(self, rid: str, timeout: float = 60.0) -> dict:
        """Block until ``rid`` has a terminal verdict, retrying sheds and
        hedging stragglers. Same contract as ``ServeClient.result``: the
        "ok" verdict is returned; a shed that outlives the retry budget
        raises :class:`RetriesExhausted` (a rid this client never
        submitted gets its SHED verdict back as data)."""
        p = self._pending.get(rid)
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"no verdict for {rid} within {timeout}s")
            # bounded server-side wait; short slices so hedge checks run
            slice_s = min(remaining,
                          0.25 if self.hedge_after is not None else 5.0)
            status, verdict = self._checked(
                wire.OP_WAIT, {"rid": rid, "timeout": slice_s})
            if status != wire.ST_OK:
                if p is not None:
                    self._maybe_hedge(rid, p)
                continue
            if verdict.get("verdict", "ok") != "SHED":
                self._pending.pop(rid, None)
                self.stats.completed += 1
                return verdict
            if p is None:
                self.stats.shed += 1
                return verdict
            if p.retries_left <= 0:
                self._pending.pop(rid, None)
                self.stats.shed += 1
                if p.attempts:
                    p.attempts[-1].update(
                        shed_reason=verdict.get("reason", ""),
                        resolved_at=time.time())
                raise RetriesExhausted(rid, verdict, p.attempts)
            self._retry(rid, p, verdict)

    def _retry(self, rid: str, p: _Pending,
               verdict: dict | None = None) -> None:
        p.retries_left -= 1
        if p.attempts:
            p.attempts[-1].update(
                shed_reason="" if verdict is None
                else verdict.get("reason", ""),
                resolved_at=time.time())
        p.submitted_at = time.time()
        p.attempts.append({"submitted_at": p.submitted_at})
        p.hedged = False
        self._checked(wire.OP_CLEAR, {"rid": rid})
        self._submit_body(rid, p)  # fresh deadline, fresh routing
        self.stats.retries += 1
        get_registry().counter("client.retries").inc()

    def _maybe_hedge(self, rid: str, p: _Pending) -> None:
        if p.hedged or self.hedge_after is None:
            return
        if time.time() - p.submitted_at < self.hedge_after:
            return
        status, resp = self._checked(wire.OP_HEDGE, {"rid": rid})
        # "already has a verdict/lease" answers are not hedges; only an
        # actual duplicate enqueue consumes this request's hedge
        if status == wire.ST_OK and resp.get("hedged"):
            p.hedged = True
            self.stats.hedges += 1
            get_registry().counter("client.hedges").inc()

    # -- extras ---------------------------------------------------------------

    def try_result(self, rid: str) -> dict | None:
        status, verdict = self._checked(wire.OP_TRY, {"rid": rid})
        return verdict if status == wire.ST_OK else None

    def gateway_stats(self) -> dict:
        _status, body = self._checked(wire.OP_STATS, {})
        return body

    def metrics(self) -> dict:
        """Live fleet metrics scrape: the gateway's registry snapshot,
        its recorder stats, and per-replica recorder stats riding the
        TTL'd load reports."""
        _status, body = self._checked(wire.OP_METRICS, {})
        return body
