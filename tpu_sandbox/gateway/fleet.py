"""Multi-model tenancy: several model fleets behind one gateway.

A *fleet* is one model configuration served by N replicas. Fleets share
one KV store and one host pool but never each other's keys: every fleet's
serve-protocol keys (queues, leases, verdicts, load reports) live under
``fleet/<name>/`` via the same :class:`NamespacedKV` mechanism that
isolates cluster jobs under ``job/<id>/``. The serve layer writes only
relative keys, so namespacing is free — a replica started with
``--fleet chat`` and a gateway routing fleet ``chat`` agree on the prefix
and everything below them is unchanged.

The host pool is divided by the scheduler's weighted fair share: each
fleet's replica jobs carry ``tenant=<fleet>`` and the fleet's ``share``,
so pool pressure between fleets resolves by accumulated normalized
service, not by who submitted first.

The default fleet (empty name) is the bare-prefix serve namespace —
single-fleet deployments keep the exact key schema the serve stack has
always had, bitwise.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from tpu_sandbox.runtime.kvstore import KVClient, NamespacedKV
from tpu_sandbox.runtime.scheduler import JobSpec

DEFAULT_FLEET = ""


def fleet_namespace(name: str) -> str:
    """Key prefix for one fleet: '' for the default, ``fleet/<name>/``
    otherwise. Same character discipline as job ids — '/' and whitespace
    are reserved so namespace sweeps can never cross fleets."""
    if not name:
        return ""
    if any(c in name for c in "/ \t\n\r"):
        raise ValueError(f"invalid fleet name {name!r}: '/' and whitespace "
                         "are reserved (namespace sweeps must stay scoped)")
    return f"fleet/{name}/"


def fleet_kv(kv: "KVClient | NamespacedKV", name: str):
    """A view of ``kv`` scoped to one fleet's serve namespace. The default
    fleet gets the client back unchanged; nesting views is a programming
    error (a fleet lives at the top of the store, not inside a job)."""
    ns = fleet_namespace(name)
    if not ns:
        return kv
    if isinstance(kv, NamespacedKV):
        raise ValueError("refusing to nest fleet namespaces: "
                         f"{kv.prefix!r} + {ns!r}")
    return NamespacedKV(kv, ns)


@dataclass(frozen=True)
class FleetSpec:
    """One model tier: its serve namespace, routing/admission calibration,
    and its claim on the shared host pool."""

    name: str = DEFAULT_FLEET
    #: allocator block size — the gateway must hash request chains with the
    #: SAME block size the fleet's replicas allocate with, or no digest
    #: entry can ever match
    block_size: int = 8
    #: calibrated per-replica service rate (requests/s) feeding the
    #: feasibility estimate; measure with a closed-loop run (bench does)
    service_rate_rps: float = 10.0
    #: occupancy-mode door bound (requests known queued on the replica)
    occupancy_bound: int = 8
    #: scheduler weighted-fair-share weight for this fleet's replica jobs
    share: float = 1.0
    priority: int = 0
    #: extra CLI args appended to every replica's serve command (model
    #: size, batch/cache shape — whatever distinguishes this tier)
    replica_args: list[str] = field(default_factory=list)

    def __post_init__(self):
        fleet_namespace(self.name)  # validate eagerly, not at first use

    def replica_job_specs(self, *, replicas: int,
                          base_priority: int = 0) -> list[JobSpec]:
        """Scheduler jobs for this fleet's replica gang: one single-host
        job per replica (replicas are independent failure domains; a gang
        of one preempts and requeues without dragging siblings down).
        Job ids are ``serve-<fleet>-<n>``; the fleet namespace rides in
        the environment, not the argv, so the template stays uniform."""
        name = self.name or "default"
        env = {"TPU_SANDBOX_FLEET": self.name} if self.name else {}
        return [
            JobSpec(
                job_id=f"serve-{name}-{i}",
                hosts=1,
                world_size=1,
                agent_argv=[
                    "python", "-m", "tpu_sandbox.serve.replica",
                    "--kv-port", "{kv_port}",
                    "--tag", f"{name}-{i}",
                    *self.replica_args,
                ],
                priority=base_priority + self.priority,
                env=env,
                tenant=f"fleet-{name}",
                share=self.share,
            )
            for i in range(replicas)
        ]
