"""Network serving gateway: the fleet's front door.

External clients connect here over TCP (``wire`` framing, shared-secret
hello) instead of holding KV-store credentials; the gateway routes each
request to the replica with the deepest resident prefix match
(``routing``), refuses requests that provably cannot make their deadline
(SLO-feasibility admission), and serves several model fleets from one
endpoint (``fleet`` namespacing). See gateway/server.py for the full
design narrative.
"""

from tpu_sandbox.gateway.client import (GatewayAuthError, GatewayClient,
                                        GatewayError)
from tpu_sandbox.gateway.fleet import (DEFAULT_FLEET, FleetSpec,
                                       fleet_kv, fleet_namespace)
from tpu_sandbox.gateway.routing import (ReplicaView, admit, choose,
                                         feasible, fresh, match_depth,
                                         parse_report)
from tpu_sandbox.gateway.server import (Gateway, GatewayStats,
                                        live_gateway_endpoints,
                                        live_gateways)
from tpu_sandbox.gateway.wire import (make_client_ssl_context,
                                      make_server_ssl_context)

__all__ = [
    "DEFAULT_FLEET",
    "FleetSpec",
    "Gateway",
    "GatewayAuthError",
    "GatewayClient",
    "GatewayError",
    "GatewayStats",
    "ReplicaView",
    "admit",
    "choose",
    "feasible",
    "fleet_kv",
    "fleet_namespace",
    "fresh",
    "live_gateway_endpoints",
    "live_gateways",
    "make_client_ssl_context",
    "make_server_ssl_context",
    "match_depth",
    "parse_report",
]
