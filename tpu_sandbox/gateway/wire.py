"""Gateway wire protocol: the native kvstore's framing idioms, one tier up.

Same shape as ``native/src/kvstore.cpp``'s protocol, deliberately — one
framing discipline across the whole system:

    request : op u8 | len u32 (network order) | payload
    response: status u8 | len u32 (network order) | payload

Payloads are JSON (the gateway speaks requests, not raw key bytes, so a
self-describing body beats the kvstore's key/val split). Ops:

    'H' hello   — payload = shared-secret token. When the gateway holds a
                  token this must be the FIRST frame on every connection;
                  wrong/missing token gets ST_AUTH and the socket closed.
                  On a token-less gateway 'H' is a no-op, so clients send
                  it unconditionally whenever they hold a token.
    'S' submit  — route + admit one request; responds with the admission
                  verdict (admitted + replica, or an explicit door shed).
    'W' wait    — block (server-side, bounded) for a terminal verdict.
    'T' try     — non-blocking verdict poll (ST_MISSING when none yet).
    'E' hedge   — duplicate a verdictless, leaseless request onto the
                  next-best replica (claim-once verdicts make races safe).
    'C' clear   — delete a SHED verdict + its claim marker so a retry's
                  fresh execution can publish (the client retry path).
    'L' stats   — gateway + per-fleet routing-table introspection.
    'M' metrics — live scrape of the obs metrics registry plus recorder
                  stats (gateway-local and per-replica via load reports).

Any protocol violation — oversized or truncated frame, undecodable JSON,
unknown op, auth failure — closes the connection; it never wedges the
accept loop or leaks a request (a request exists only after a fully
parsed, fully dispatched 'S').

TLS rides *under* this framing on the external wire: the gateway wraps
its listener in an ``ssl.SSLContext`` and the client wraps its socket
before the first frame, so the shared-secret hello (and everything after
it) is inside the encrypted channel. The framing code below is transport
agnostic — an ``ssl.SSLSocket`` and an ssl-wrapped asyncio stream expose
the same recv/readexactly surface — which is why the context builders
live here next to the protocol they protect. A plaintext client against
a TLS gateway fails the *handshake* (the server reads a frame header out
of the ClientHello bytes, or the client times out waiting for a
ServerHello that never parses); either way the connection dies before a
single op is interpreted.

Both ends set TCP_NODELAY: frames are small and latency is the product.
"""

from __future__ import annotations

import json
import socket
import ssl
import struct

#: one-frame cap, matching the kvstore's sanity cap in spirit; prompts are
#: token-id lists, so even huge requests are far below this
MAX_FRAME = 1 << 20

_HDR = struct.Struct("!BI")  # op/status u8 | length u32, network order

OP_HELLO = ord("H")
OP_SUBMIT = ord("S")
OP_WAIT = ord("W")
OP_TRY = ord("T")
OP_HEDGE = ord("E")
OP_CLEAR = ord("C")
OP_STATS = ord("L")
OP_METRICS = ord("M")

KNOWN_OPS = frozenset({OP_HELLO, OP_SUBMIT, OP_WAIT, OP_TRY, OP_HEDGE,
                       OP_CLEAR, OP_STATS, OP_METRICS})

ST_OK = 0
ST_ERR = 1
ST_MISSING = 2   # try/wait: no verdict yet
ST_TIMEOUT = 3   # wait: bounded server-side wait expired
ST_AUTH = 4      # hello rejected / required and absent


class ProtocolError(Exception):
    """The peer violated the framing contract; close the connection."""


# -- TLS contexts -------------------------------------------------------------


def make_server_ssl_context(certfile: str, keyfile: str) -> ssl.SSLContext:
    """The gateway's listener context: TLS 1.2+, server cert + key from
    committed PEM files (tests/fixtures/tls/ in the suite; an operator
    hands real paths in production). Client certs are not requested —
    the shared-secret hello inside the channel is the caller identity."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_cert_chain(certfile=certfile, keyfile=keyfile)
    return ctx


def make_client_ssl_context(cafile: str) -> ssl.SSLContext:
    """The client's context: verify the gateway against exactly the CA
    given (never the system trust store — a sandbox fleet's CA is
    private), hostname checking on."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.load_verify_locations(cafile=cafile)
    ctx.check_hostname = True
    ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def pack_frame(op: int, payload: bytes) -> bytes:
    if len(payload) > MAX_FRAME:
        raise ProtocolError(f"frame of {len(payload)} bytes exceeds cap")
    return _HDR.pack(op, len(payload)) + payload


def parse_header(header: bytes) -> tuple[int, int]:
    """(op_or_status, payload_length); oversized lengths are a protocol
    violation BEFORE any allocation — a hostile 4 GB length prefix must
    cost nothing."""
    if len(header) != _HDR.size:
        raise ProtocolError(f"short header: {len(header)} bytes")
    op, length = _HDR.unpack(header)
    if length > MAX_FRAME:
        raise ProtocolError(f"declared frame of {length} bytes exceeds cap")
    return op, length


def encode_body(body: dict) -> bytes:
    return json.dumps(body).encode()


def decode_body(payload: bytes) -> dict:
    try:
        body = json.loads(payload.decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"undecodable payload: {e}") from e
    if not isinstance(body, dict):
        raise ProtocolError("payload must be a JSON object")
    return body


# -- sync side (GatewayClient) ------------------------------------------------


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("gateway closed the connection")
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, op: int, body: dict) -> None:
    sock.sendall(pack_frame(op, encode_body(body)))


def recv_response(sock: socket.socket) -> tuple[int, dict]:
    status, length = parse_header(recv_exact(sock, _HDR.size))
    payload = recv_exact(sock, length) if length else b""
    return status, (decode_body(payload) if payload else {})


# -- async side (Gateway server) ----------------------------------------------


async def read_frame(reader) -> tuple[int, bytes]:
    """One request frame off an asyncio stream; raises ProtocolError on a
    hostile length prefix and IncompleteReadError on mid-frame EOF."""
    op, length = parse_header(await reader.readexactly(_HDR.size))
    payload = await reader.readexactly(length) if length else b""
    return op, payload


async def write_response(writer, status: int, body: dict | None) -> None:
    writer.write(pack_frame(status, encode_body(body) if body else b""))
    await writer.drain()
