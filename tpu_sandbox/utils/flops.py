"""Analytic FLOP accounting + per-chip peak table => MFU.

VERDICT r01 found the benchmark reported ~10x a v5e's bf16 peak because
nothing in the repo cross-checked achieved FLOP/s against the hardware
ceiling. This module is that cross-check: a hand-derived FLOP model for the
reference-parity ConvNet (reference mnist_onegpu.py:11-31 defines the
architecture; SURVEY §2.1 C11), a peak-FLOPs table keyed on
``jax.Device.device_kind``, and an MFU helper that flags physically
impossible numbers instead of publishing them.

Conventions (stated so the numbers are auditable):
- Model FLOPs count matmul/conv multiply-adds as 2 FLOPs; elementwise work
  (BN, ReLU, pooling, the on-device 28->3000 resize) is excluded — standard
  MFU accounting, which therefore *understates* utilization slightly.
- Training = forward + backward. Backward of a conv/matmul costs 2x its
  forward (grad wrt input + grad wrt weights), except the first conv, whose
  grad wrt the *input image* is never needed — we subtract that term rather
  than quoting the usual flat 3x.
- MFU is computed against the chip's *bf16 systolic-array peak* regardless
  of the run dtype; fp32 runs will show lower MFU by construction (TPUs
  have no faster fp32 path than bf16).
"""

from __future__ import annotations

from dataclasses import dataclass

# bf16 peak matmul TFLOP/s per chip, keyed by substrings of
# jax.Device.device_kind. Public figures (cloud.google.com/tpu docs):
#   v2 46, v3 123, v4 275, v5e 197, v5p 459, v6e (Trillium) 918.
# 'TPU v5 lite' is what jax reports for v5e; 'TPU v6 lite' for v6e.
PEAK_BF16_TFLOPS: dict[str, float] = {
    "TPU v6 lite": 918.0,
    "TPU v6": 918.0,
    "TPU v5p": 459.0,
    "TPU v5 lite": 197.0,
    "TPU v5": 197.0,
    "TPU v4": 275.0,
    "TPU v3": 123.0,
    "TPU v2": 46.0,
}


def device_peak_tflops(device_kind: str) -> float | None:
    """bf16 peak for a device_kind string, or None if unknown (e.g. 'cpu' —
    no published peak, MFU is then not computed rather than faked)."""
    for key in sorted(PEAK_BF16_TFLOPS, key=len, reverse=True):
        if key.lower() in device_kind.lower():
            return PEAK_BF16_TFLOPS[key]
    return None


def conv2d_flops(h: int, w: int, c_in: int, c_out: int, k: int) -> float:
    """'same'-padded stride-1 conv forward FLOPs at output h x w."""
    return 2.0 * h * w * c_out * k * k * c_in


@dataclass(frozen=True)
class ConvNetFlops:
    """Per-image FLOP breakdown for the parity ConvNet at a given input size.

    Architecture (models/convnet.py, mirroring reference mnist_onegpu.py:14-24):
    conv 1->16 k5 same; pool /2; conv 16->32 k5 same; pool /2; dense -> 10.
    """

    conv1: float
    conv2: float
    fc: float

    @property
    def forward(self) -> float:
        return self.conv1 + self.conv2 + self.fc

    @property
    def train(self) -> float:
        """fwd + bwd; conv1's grad-wrt-input term is excluded (the input is
        data, its gradient is never formed)."""
        return 3.0 * self.forward - self.conv1


def convnet_flops(image_size: int, num_classes: int = 10) -> ConvNetFlops:
    h = w = image_size
    conv1 = conv2d_flops(h, w, 1, 16, 5)
    conv2 = conv2d_flops(h // 2, w // 2, 16, 32, 5)
    features = 32 * (h // 4) * (w // 4)
    fc = 2.0 * features * num_classes
    return ConvNetFlops(conv1=conv1, conv2=conv2, fc=fc)


#: per-(output element) matmul contraction depths of the s2d-plan Pallas
#: kernels at the production geometry (H=W=image/4): EXECUTED flops per
#: custom call = 2 * B * H * W * _S2D_KERNEL_K[class]. conv taps run the
#: scattered 3x3 at the s2d channel widths (conv1: 16 in -> 256 out;
#: conv2: 64 in -> 128 out); the bn tails' matmuls are the pool
#: compaction/scatter selections (bn1: [64,256] sel; bn2: [32,128]).
_S2D_KERNEL_K = {
    "/conv1/": 9 * 16 * 256,   # in 16 (s2d image), out blk^2*f1 = 256
    "/conv2/": 9 * 64 * 128,   # in 4*f1 = 64 (pool1), out blk^2*f2 = 128
    "/bn1.fused/": 256 * 64,   # pool compaction/scatter selection matmuls
    "/bn2.fused/": 128 * 32,
}

#: the transposed plan's conv1 runs the sparse-tap union-tile kernel
#: since r04 (ops/pallas_conv5_t.py): K = 64 tap rows, not 9C = 144
_S2DT_OVERRIDES = {"/conv1/": 64 * 256}


def model_runs_sparse_conv1(model) -> bool:
    """Whether this model instance will EXECUTE the sparse-tap conv1
    kernel, accounting for both the ``sparse_conv1`` field and the
    TPU_SANDBOX_NO_SPARSE_CONV1 kill switch (read at trace time by
    models/convnet_s2d_t.py::_ConvT). The FLOP cross-check must key on
    this, never on the class name alone."""
    import os

    return (type(model).__name__ == "ConvNetS2DT"
            and getattr(model, "sparse_conv1", False)
            and os.environ.get("TPU_SANDBOX_NO_SPARSE_CONV1") != "1")


def s2d_custom_call_flops(hlo_text: str, batch: int, image_size: int,
                          plan: str = "s2dt",
                          sparse_conv1: bool | None = None) -> dict:
    """Analytic EXECUTED flops of the Pallas custom calls in a compiled
    s2d/s2dt train step, counted from the optimized HLO (VERDICT r03
    weak-7: XLA's cost analysis cannot see into custom calls, so
    ``flops_per_step_xla`` silently undercounts exactly when the
    production kernels are in play; composing it with this makes the
    cross-check real). Counts every custom-call line whose op_name names
    a model kernel; per-call flops are the kernel's one matmul over the
    full [B, H, W] geometry, which holds for fwd, dgrad, wgrad, and the
    tail kernels alike (same contraction per output element).

    ``sparse_conv1`` is the EXECUTED conv1 kernel choice, not the model
    class: ConvNetS2DT can run the scattered-3x3 conv1 (K = 9*16) via
    ``sparse_conv1=False`` or TPU_SANDBOX_NO_SPARSE_CONV1=1, in which
    case keying the K table on the class name would undercount every
    conv1 call by 2.25x while ``unmatched_pallas_calls`` stayed 0 —
    exactly the silent-wrong-cross-check this function exists to prevent
    (ADVICE r04 medium). Callers that know the model should pass
    ``model_runs_sparse_conv1(model)``; None falls back to the plan-name
    heuristic for HLO-only callers."""
    import re

    h = w = image_size // 4
    base = 2.0 * batch * h * w
    table = dict(_S2D_KERNEL_K)
    if sparse_conv1 is None:
        sparse_conv1 = "s2dt" in plan.lower()
    if sparse_conv1:
        table.update(_S2DT_OVERRIDES)
    per_class: dict[str, float] = {}
    count = unmatched = 0
    for line in hlo_text.splitlines():
        # a Pallas kernel instruction: `%name = <shape> custom-call(...)`
        # whose metadata path ends in .../pallas_call (plain XLA
        # gather/scatter ops under the same module paths must not count)
        if not re.search(r"= [^=]*custom-call\(", line):
            continue
        m = re.search(r'op_name="([^"]*)"', line)
        path = m.group(1) if m else ""
        if "/pallas_call" not in path:
            continue
        for tag, k in table.items():
            if tag in path:
                key = tag.strip("/")
                per_class[key] = per_class.get(key, 0.0) + base * k
                count += 1
                break
        else:
            unmatched += 1  # a Pallas call this table doesn't know
    return {
        "total": sum(per_class.values()),
        "per_class": per_class,
        "custom_calls_counted": count,
        "unmatched_pallas_calls": unmatched,
    }


def transformer_flops(
    n_layers: int, d_model: int, d_ff: int, seq: int, vocab: int
) -> dict[str, float]:
    """Per-token forward FLOPs for the TransformerLM (models/transformer.py):
    the standard 2*params matmul accounting + attention score/value terms."""
    per_layer = (
        2.0 * 4 * d_model * d_model  # qkv + out projections
        + 2.0 * 2 * d_model * d_ff  # mlp up + down
        + 2.0 * 2 * seq * d_model  # QK^T and PV, amortized per token
    )
    head = 2.0 * d_model * vocab
    fwd = n_layers * per_layer + head
    return {"forward": fwd, "train": 3.0 * fwd}


def mfu(flops_per_step: float, sec_per_step: float, device_kind: str,
        n_devices: int = 1) -> dict:
    """Achieved TFLOP/s + model-FLOPs utilization, with a sanity verdict.

    Returns achieved_tflops, peak_tflops (None if unknown chip), mfu (None
    if peak unknown), and plausible=False when mfu > 1 — the r01 failure
    mode this module exists to catch.
    """
    achieved = flops_per_step / sec_per_step / 1e12
    peak = device_peak_tflops(device_kind)
    total_peak = peak * n_devices if peak is not None else None
    util = achieved / total_peak if total_peak else None
    return {
        "achieved_tflops": achieved,
        "peak_tflops_bf16": total_peak,
        "mfu": util,
        "plausible": util is None or 0.0 < util <= 1.0,
    }
