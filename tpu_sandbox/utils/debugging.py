"""Numerical sanitizers — the framework's race-detector analogue.

The reference carries no sanitizers (SURVEY §5 "Race detection: Absent");
on TPU the failure mode that actually bites is numerical, not data races
(XLA programs are data-race-free by construction): a NaN/Inf born in one
step silently poisons the replicated params everywhere. These helpers make
that loud:

- ``finite_report`` / ``assert_finite`` — walk a pytree on host, name every
  leaf containing NaN/Inf by its tree path.
- ``guarded_step``  — wrap any engine's ``train_step``; checks the loss
  every step (cheap: one scalar sync) and, on trouble, re-checks the whole
  state to report exactly which params went bad and at which step.
- ``debug_nans``    — context manager for jax's compiled-code NaN checker
  (``jax_debug_nans``), which catches the *birth* of a NaN inside jit at
  ~2x compile cost — the bisection tool once guarded_step flags a step.
"""

from __future__ import annotations

from contextlib import contextmanager

import jax
import jax.numpy as jnp
import numpy as np

# module-level so jax.jit's identity-keyed cache hits after the first leaf
_count_nonfinite = jax.jit(lambda x: (~jnp.isfinite(x)).sum())


def _is_inexact(dtype) -> bool:
    """True for float/complex including the ML dtypes (bfloat16, float8_*),
    whose raw numpy kind is 'V' and would slip past a kind-based check."""
    return jnp.issubdtype(dtype, jnp.inexact)


class NonFiniteError(RuntimeError):
    def __init__(self, msg: str, bad_paths: list[str]):
        super().__init__(msg)
        self.bad_paths = bad_paths


def _path_str(path) -> str:
    return "/".join(
        str(getattr(p, "key", getattr(p, "idx", getattr(p, "name", p))))
        for p in path
    )


def finite_report(tree) -> list[str]:
    """Paths of leaves containing any NaN/Inf (device->host sync).

    Multihost-sharded ``jax.Array``s (not fully addressable — ``np.asarray``
    would raise) are checked with an on-device reduction instead; the
    reduced scalar is replicated, so every process reports consistently.
    """
    bad: list[str] = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        dtype = getattr(leaf, "dtype", None) or np.asarray(leaf).dtype
        if not _is_inexact(dtype):
            continue
        if isinstance(leaf, jax.Array):
            if leaf.is_fully_addressable:
                arr = np.asarray(leaf)
            else:
                n_bad = int(_count_nonfinite(leaf))
                if n_bad:
                    bad.append(
                        f"{_path_str(path)} ({n_bad}/{leaf.size} non-finite)"
                    )
                continue
        else:
            arr = np.asarray(leaf)
        if arr.dtype.kind not in "fc":  # ml_dtypes (bf16/fp8): kind 'V',
            arr = arr.astype(np.float32)  # no native np.isfinite; upcast is
            # exact for these narrow types. Real f/c dtypes are NOT cast:
            # float64 would overflow and complex would drop its imag part.
        if not np.isfinite(arr).all():
            n = int((~np.isfinite(arr)).sum())
            bad.append(f"{_path_str(path)} ({n}/{arr.size} non-finite)")
    return bad


def assert_finite(tree, name: str = "tree") -> None:
    bad = finite_report(tree)
    if bad:
        raise NonFiniteError(
            f"{name}: non-finite values in {len(bad)} leaves:\n  "
            + "\n  ".join(bad),
            bad,
        )


def guarded_step(step_fn, *, name: str = "train_step"):
    """Wrap ``step_fn(state, *batch) -> (state, loss)`` with per-step loss
    checks; on a non-finite loss, diagnose the returned state too so the
    error names the poisoned leaves.  Adds one scalar device->host sync per
    step — acceptable for debugging runs, not for benchmarking.
    """
    calls = {"n": 0}

    def wrapped(state, *args, **kwargs):
        new_state, loss = step_fn(state, *args, **kwargs)
        step = calls["n"]
        calls["n"] += 1
        loss_host = np.asarray(loss)
        if not np.isfinite(loss_host).all():
            detail = finite_report(new_state)
            raise NonFiniteError(
                f"{name}: non-finite loss {np.ravel(loss_host)[:4]} at step "
                f"{step}" + (f"; poisoned state leaves:\n  " +
                             "\n  ".join(detail) if detail else
                             " (state still finite — loss-only blowup)"),
                detail,
            )
        return new_state, loss

    return wrapped


@contextmanager
def debug_nans(enable: bool = True):
    """Scoped ``jax_debug_nans``: XLA re-runs each primitive de-optimized
    when an output is non-finite and raises at the birth site."""
    old = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enable)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", old)
