from tpu_sandbox.utils.cli import ensure_devices  # noqa: F401
