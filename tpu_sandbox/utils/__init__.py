from tpu_sandbox.utils.cli import ensure_devices  # noqa: F401
from tpu_sandbox.utils.debugging import (  # noqa: F401
    NonFiniteError,
    assert_finite,
    debug_nans,
    finite_report,
    guarded_step,
)
