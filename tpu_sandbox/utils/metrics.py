"""Metrics logging.

The reference logs with bare rank-gated prints (SURVEY §5 "metrics:
print() only"); the Trainer reproduces those lines verbatim for parity.
This module adds the structured side: a JSONL metrics writer (one record
per log event, greppable/plottable) and a rank-gated print helper.
"""

from __future__ import annotations

import json
import time
from pathlib import Path

import jax


def rank0_print(*args, **kwargs) -> None:
    """Print only on process 0 (the reference gates on gpu==0 / rank 0)."""
    if jax.process_index() == 0:
        print(*args, **kwargs)


class MetricsWriter:
    """Append-only JSONL metrics log: {"step": ..., "time": ..., **metrics}."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh = open(self.path, "a", buffering=1)

    def write(self, step: int, **metrics) -> None:
        record = {"step": int(step), "time": time.time()}
        for k, v in metrics.items():
            record[k] = float(v) if hasattr(v, "__float__") else v
        self._fh.write(json.dumps(record) + "\n")

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_metrics(path: str | Path) -> list[dict]:
    return [json.loads(line) for line in Path(path).read_text().splitlines() if line]
