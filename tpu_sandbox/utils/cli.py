"""Entry-script device bootstrapping.

The reference scripts fork one process per GPU rank (``mp.spawn``); here
"ranks" are devices of one process. When the user asks for more ranks than
the accelerator has (the common case on a 1-chip dev box), we fall back to
N virtual CPU devices — the same trick the reference pulls with
gloo-on-localhost (SURVEY §4), minus the processes. JAX keeps the CPU
client alongside the accelerator client, so no platform flip is needed;
``jax_num_cpu_devices`` just has to be set before any backend initializes,
which is why entry scripts call this first.
"""

from __future__ import annotations

import jax

_MAX_VIRTUAL = 64


def ensure_devices(n: int, force_cpu: bool = False) -> list:
    """Return ``n`` devices to act as ranks, virtualizing on CPU if needed.

    Preference order: real accelerator devices if there are enough of them;
    otherwise ``n`` virtual CPU devices. ``force_cpu`` skips the accelerator
    (useful for deterministic multi-rank demos on a 1-chip box).
    """
    if n < 1:
        raise ValueError(f"need at least 1 device, asked for {n}")
    try:
        # Pre-size the CPU client before any backend initializes so the
        # fallback exists. Harmless if real devices suffice.
        jax.config.update("jax_num_cpu_devices", min(max(n, 1), _MAX_VIRTUAL))
        if force_cpu:
            # Exclude the accelerator platform entirely: initializing it just
            # to ignore it can hang (and wastes its memory grant).
            jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backends already up; the current CPU client size is fixed
    if not force_cpu:
        if jax.device_count() >= n:
            return jax.devices()[:n]
    cpu = jax.devices("cpu")
    if len(cpu) < n:
        raise RuntimeError(
            f"wanted {n} ranks; have {jax.device_count()} "
            f"{jax.default_backend()} device(s) and {len(cpu)} CPU device(s), "
            "and the CPU client size is already fixed for this process"
        )
    return cpu[:n]
