"""Entry-script device bootstrapping.

The reference scripts fork one process per GPU rank (``mp.spawn``); here
"ranks" are devices of one process. When the user asks for more ranks than
the accelerator has (the common case on a 1-chip dev box), we fall back to
N virtual CPU devices — the same trick the reference pulls with
gloo-on-localhost (SURVEY §4), minus the processes. JAX keeps the CPU
client alongside the accelerator client, so no platform flip is needed;
``jax_num_cpu_devices`` just has to be set before any backend initializes,
which is why entry scripts call this first.
"""

from __future__ import annotations

import os

import jax

_MAX_VIRTUAL = 64


def add_checkpoint_cli(parser) -> None:
    """Register the checkpoint flag group shared by the entry scripts.

    One definition site keeps the launcher and its respawned workers
    agreeing on spelling — spawn/elastic passthrough re-parses these exact
    flags in the child process.
    """
    parser.add_argument("--ckpt-every", type=int, default=0, metavar="N",
                        help="with --ckpt-dir: also save every N steps")
    parser.add_argument("--ckpt-dir", type=str, default=None,
                        help="checkpoint directory (orbax/npz/sharded)")
    parser.add_argument("--resume", action="store_true",
                        help="restore the newest step from --ckpt-dir first")
    parser.add_argument("--ckpt-sharded", action="store_true",
                        help="with --elastic: every rank writes its own "
                             "shard + SHA-256, rank 0 seals the step with a "
                             "manifest (two-phase commit). Implied by --zero, "
                             "whose optimizer shards rank 0 alone cannot see")
    parser.add_argument("--ckpt-verify-interval", type=float, default=0.0,
                        metavar="SEC",
                        help="with sharded checkpoints: rank 0 re-hashes "
                             "older sealed steps every SEC seconds in the "
                             "background (0 = off)")
    parser.add_argument("--ckpt-compress", action="store_true",
                        help="with --ckpt-sharded: zlib-deflate each shard "
                             "file (np.savez_compressed); manifests record "
                             "on-disk AND raw sizes, checksums stay over "
                             "the bytes on disk")


def add_grad_compress_cli(parser, error_feedback: bool = True) -> None:
    """Register the gradient-compression flag group (same single-site
    contract as the checkpoint group: launchers and their respawned
    workers re-parse these exact flags)."""
    parser.add_argument("--grad-compress", choices=["none", "bf16", "int8"],
                        default="none",
                        help="compress the data-parallel gradient sync: "
                             "bf16 cast (2x wire payload reduction) or "
                             "int8 block-scaled two-shot exchange (~4x); "
                             "'none' is bitwise-identical to the "
                             "uncompressed path")
    if error_feedback:
        parser.add_argument("--no-error-feedback", action="store_true",
                            help="with --grad-compress int8: drop the "
                                 "error-feedback residual (saves one "
                                 "param-sized fp32 buffer per rank, loses "
                                 "the fp32-tracking convergence guarantee)")


def add_overlap_cli(parser, prefetch: bool = True) -> None:
    """Register the overlapped-step-pipeline flag group (same single-site
    contract as the checkpoint group: launchers and their respawned
    workers re-parse these exact flags). ``prefetch=False`` for entry
    scripts with synthetic in-memory streams and no Trainer loop."""
    parser.add_argument("--overlap-grad-sync", action="store_true",
                        help="bucket the gradient sync (DDP's reducer): one "
                             "independent collective per ~--bucket-mb flat "
                             "buffer so XLA's latency-hiding scheduler can "
                             "overlap all-reduces with remaining backward "
                             "compute; composes with --grad-compress "
                             "(per-bucket quantization + error feedback) "
                             "and --zero")
    parser.add_argument("--bucket-mb", type=float, default=25.0,
                        metavar="MB",
                        help="with --overlap-grad-sync: bucket size target "
                             "(default 25, PyTorch DDP's bucket_cap_mb)")
    if prefetch:
        parser.add_argument("--prefetch", action="store_true",
                            help="double-buffered background batch "
                                 "prefetch: a daemon thread assembles "
                                 "batch N+1 while step N runs (same "
                                 "batches, same order — resume parity is "
                                 "unchanged under --elastic)")


def add_elastic_cli(parser) -> None:
    """Register the elastic/agent flag group (same single-site contract as
    the checkpoint group: launchers, agents, and their respawned workers
    all re-parse these exact flags)."""
    parser.add_argument("--elastic", action="store_true",
                        help="run the multiprocess topology under elastic "
                             "supervision: crashed/preempted generations "
                             "are relaunched and resume from the newest "
                             "checkpoint with exact data order")
    parser.add_argument("--max-restarts", type=int, default=3,
                        help="with --elastic: charged restarts before "
                             "giving up (preemptions are free)")
    parser.add_argument("--agents", type=int, default=0, metavar="N",
                        help="with --elastic: cross-host mode — N per-host "
                             "agents (runtime/host_agent.py) coordinate "
                             "generations over the KV store with leader "
                             "election; 0 keeps the single-host supervisor. "
                             "World size need not divide by N (the leader "
                             "publishes a balanced rank-assignment table)")
    parser.add_argument("--job-id", type=str, default="", metavar="ID",
                        help="with --elastic: run under this job's KV "
                             "namespace (job/<ID>/...) so several jobs can "
                             "share one store without colliding; empty = "
                             "the bare default-job namespace")
    parser.add_argument("--priority", type=int, default=0,
                        help="with --pool: this job's scheduling priority "
                             "(higher wins; may preempt lower-priority "
                             "running jobs)")
    parser.add_argument("--pool", type=int, default=0, metavar="SLOTS",
                        help="with --elastic: multi-tenant cluster mode — "
                             "run runtime/scheduler.py over SLOTS host "
                             "slots and gang-schedule the demo job(s) "
                             "through its durable queue instead of "
                             "launching agents directly")
    parser.add_argument("--agent-id", type=int, default=None, metavar="ID",
                        help="run exactly ONE host agent (0..N-1) of an "
                             "--agents N job and exit with its verdict — "
                             "for launching each host's agent yourself; "
                             "needs --kv-port pointing at the job's store "
                             "(or --leader to host it here)")
    parser.add_argument("--leader", action="store_true",
                        help="with --agent-id: host the coordination KV "
                             "store inside this agent's process (start "
                             "this agent first; peers connect via "
                             "--kv-port). Binds loopback by default; pass "
                             "--kv-bind 0.0.0.0 (+ TPU_SANDBOX_KV_TOKEN) "
                             "for real cross-host deployment")
    parser.add_argument("--kv-bind", type=str, default="127.0.0.1",
                        metavar="ADDR",
                        help="with --leader: address the KV store listens "
                             "on (default loopback; 0.0.0.0 for cross-host "
                             "— set TPU_SANDBOX_KV_TOKEN on every host so "
                             "connections authenticate with the shared "
                             "secret)")


def _request_cpu_devices(n: int) -> None:
    """Ask for ``n`` virtual CPU devices, whatever this jax calls the knob.

    Newer jax exposes the ``jax_num_cpu_devices`` config; older releases
    only honor the XLA_FLAGS env var, which likewise must be set before
    the CPU backend initializes.
    """
    try:
        jax.config.update("jax_num_cpu_devices", n)
        return
    except AttributeError:
        pass
    flag = "--xla_force_host_platform_device_count"
    flags = [
        f for f in os.environ.get("XLA_FLAGS", "").split()
        if not f.startswith(flag + "=")
    ]
    flags.append(f"{flag}={n}")
    os.environ["XLA_FLAGS"] = " ".join(flags)


def configure_worker_cpu(n: int = 1) -> None:
    """Per-rank worker processes: exactly ``n`` (usually 1) CPU device(s),
    regardless of any XLA_FLAGS the parent process exported (tests run
    under a force-8-devices flag which workers must NOT inherit — a mesh
    of ``world_size`` processes x 8 devices each is not the topology).
    Must run before the first device query."""
    jax.config.update("jax_platforms", "cpu")
    try:
        # cross-process CPU collectives run over gloo; without this the CPU
        # backend refuses multiprocess computations outright
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass  # jax versions that dropped/renamed the knob enable it themselves
    _request_cpu_devices(n)


def ensure_devices(n: int, force_cpu: bool = False) -> list:
    """Return ``n`` devices to act as ranks, virtualizing on CPU if needed.

    Preference order: real accelerator devices if there are enough of them;
    otherwise ``n`` virtual CPU devices. ``force_cpu`` skips the accelerator
    (useful for deterministic multi-rank demos on a 1-chip box).
    """
    if n < 1:
        raise ValueError(f"need at least 1 device, asked for {n}")
    try:
        # Pre-size the CPU client before any backend initializes so the
        # fallback exists. Harmless if real devices suffice.
        _request_cpu_devices(min(max(n, 1), _MAX_VIRTUAL))
        if force_cpu:
            # Exclude the accelerator platform entirely: initializing it just
            # to ignore it can hang (and wastes its memory grant).
            jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backends already up; the current CPU client size is fixed
    if not force_cpu:
        if jax.device_count() >= n:
            return jax.devices()[:n]
    cpu = jax.devices("cpu")
    if len(cpu) < n:
        raise RuntimeError(
            f"wanted {n} ranks; have {jax.device_count()} "
            f"{jax.default_backend()} device(s) and {len(cpu)} CPU device(s), "
            "and the CPU client size is already fixed for this process"
        )
    return cpu[:n]
