"""Tracing / profiling utilities.

The reference's only instrumentation is a wall-clock print
(``datetime.now() - start``, mnist_onegpu.py:61,83-84 — kept verbatim by
train.Trainer). SURVEY §5 calls a real profiler "a free idiomatic add" on
TPU, so: ``trace()`` wraps ``jax.profiler`` (XLA/TPU timeline viewable in
TensorBoard/Perfetto) and ``StepTimer`` turns step wall-times into the
images/sec numbers BASELINE.md wants.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import jax


@contextlib.contextmanager
def trace(logdir: str, *, host_tracer_level: int = 2):
    """Capture an XLA profiler trace for the enclosed block."""
    try:
        jax.profiler.start_trace(logdir, host_tracer_level=host_tracer_level)
    except TypeError:  # newer jax: tracer options moved off start_trace
        jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Name a region so it shows up on the trace timeline."""
    with jax.profiler.TraceAnnotation(name):
        yield


def host_sync(x) -> float:
    """TRUE host-side synchronization: fetch a scalar derived from ``x``.

    On some platforms (the experimental 'axon' TPU tunnel among them)
    ``block_until_ready`` returns as soon as the dispatch is acknowledged —
    measured at ~0.02 ms for a 100 ms computation — so wall-clock timing
    around it reports physically impossible throughput (the r01 benchmark
    bug: ~10x chip peak, VERDICT.md weak #1). A device->host transfer of a
    value that data-depends on the computation cannot complete early on any
    platform; this is the only sync primitive benchmarks here may use.
    """
    import jax.numpy as jnp

    return float(jnp.ravel(x)[0])


def measure_per_step(run_steps, n: int) -> dict:
    """Fetch-synced *differential* step timing: per_step = (t(2n)-t(n)) / n.

    ``run_steps(k)`` must execute k steps whose final output data-depends on
    all k (e.g. a threaded train state) and return that output; we fetch a
    scalar from it (``host_sync``). Timing t(n) and t(2n) and differencing
    cancels the constant costs a single timed loop cannot escape — the
    host->device fetch round-trip (~80 ms through the axon tunnel) and any
    fixed dispatch overhead — leaving the marginal cost of one step.

    Both loops are warmed (compile + queue drain) before timing. Returns
    seconds per step plus the raw t(n)/t(2n) for the benchmark record.
    """
    host_sync(run_steps(n))  # warm: compile, stage, drain queue
    t0 = time.perf_counter()
    host_sync(run_steps(n))
    t_n = time.perf_counter() - t0
    t0 = time.perf_counter()
    host_sync(run_steps(2 * n))
    t_2n = time.perf_counter() - t0
    return {
        "sec_per_step": (t_2n - t_n) / n,
        "t_n_sec": t_n,
        "t_2n_sec": t_2n,
        "n": n,
        "timing_method": "fetch-synced differential (t(2n)-t(n))/n",
    }


def measure_per_step_repeated(run_steps, n: int, repeats: int = 3) -> dict:
    """``measure_per_step`` run ``repeats`` times: publishes the MIN (the
    least-contended sample — the honest kernel time under a shared,
    occasionally-hiccuping tunnel) plus every sample, so artifacts carry
    their own run-to-run spread (VERDICT r03 next-7: the same kernel
    differed 25-50% between single-shot r03 sweeps; single samples must
    not drive plan decisions)."""
    samples = [measure_per_step(run_steps, n) for _ in range(repeats)]
    times = [s["sec_per_step"] for s in samples]
    positive = [t for t in times if t > 0] or times
    best = samples[times.index(min(positive))]
    # spread is only a repeatability claim when EVERY repeat measured;
    # with noise-negative samples dropped it would report a lone noisy
    # sample as perfectly repeatable — publish None + the failure count
    all_ok = len(positive) == len(times) and min(positive) > 0
    spread = ((max(positive) - min(positive)) / min(positive)
              if all_ok else None)
    out = {
        **best,
        "sec_per_step": min(positive),
        "repeats": repeats,
        "sec_per_step_samples": [round(t, 6) for t in times],
        "spread_frac": round(spread, 3) if spread is not None else None,
        "timing_method": best["timing_method"] + f"; min of {repeats}",
    }
    bad = len(times) - len([t for t in times if t > 0])
    if bad:
        out["nonpositive_samples"] = bad
    return out


@dataclass
class StepTimer:
    """Throughput measurement: call start() once, tick(n_items) per step."""

    warmup: int = 1
    _steps: int = 0
    _items: int = 0
    _t0: float | None = None
    step_times: list = field(default_factory=list)

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def tick(self, n_items: int = 0) -> None:
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now
            return
        self._steps += 1
        if self._steps > self.warmup:
            self.step_times.append(now - self._t0)
            self._items += n_items
        self._t0 = now

    @property
    def seconds_per_step(self) -> float:
        if not self.step_times:
            return float("nan")
        return sum(self.step_times) / len(self.step_times)

    @property
    def items_per_second(self) -> float:
        total = sum(self.step_times)
        return self._items / total if total > 0 else float("nan")
