"""Tracing / profiling utilities.

The reference's only instrumentation is a wall-clock print
(``datetime.now() - start``, mnist_onegpu.py:61,83-84 — kept verbatim by
train.Trainer). SURVEY §5 calls a real profiler "a free idiomatic add" on
TPU, so: ``trace()`` wraps ``jax.profiler`` (XLA/TPU timeline viewable in
TensorBoard/Perfetto) and ``StepTimer`` turns step wall-times into the
images/sec numbers BASELINE.md wants.
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field

import jax


@contextlib.contextmanager
def trace(logdir: str, *, host_tracer_level: int = 2):
    """Capture an XLA profiler trace for the enclosed block."""
    try:
        jax.profiler.start_trace(logdir, host_tracer_level=host_tracer_level)
    except TypeError:  # newer jax: tracer options moved off start_trace
        jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def annotate(name: str):
    """Name a region so it shows up on the trace timeline."""
    with jax.profiler.TraceAnnotation(name):
        yield


@dataclass
class StepTimer:
    """Throughput measurement: call start() once, tick(n_items) per step."""

    warmup: int = 1
    _steps: int = 0
    _items: int = 0
    _t0: float | None = None
    step_times: list = field(default_factory=list)

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def tick(self, n_items: int = 0) -> None:
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now
            return
        self._steps += 1
        if self._steps > self.warmup:
            self.step_times.append(now - self._t0)
            self._items += n_items
        self._t0 = now

    @property
    def seconds_per_step(self) -> float:
        if not self.step_times:
            return float("nan")
        return sum(self.step_times) / len(self.step_times)

    @property
    def items_per_second(self) -> float:
        total = sum(self.step_times)
        return self._items / total if total > 0 else float("nan")
