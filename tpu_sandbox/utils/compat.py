"""Version compatibility shims for the jax API surface this repo targets.

The framework is written against the modern spellings (``jax.shard_map``
with ``check_vma``, ``pltpu.CompilerParams``); older installed jax
releases (0.4.x) ship the same functionality under earlier names
(``jax.experimental.shard_map.shard_map`` with ``check_rep``,
``pltpu.TPUCompilerParams``). Everything resolves here once so engine code
stays written in one idiom and the whole suite runs on either release.
"""

from __future__ import annotations

import jax


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions.

    On modern jax this is exactly ``jax.shard_map``; on 0.4.x it maps to
    ``jax.experimental.shard_map.shard_map``, translating ``check_vma``
    (the current name for the replication/varying-manual-axes check) to
    the old ``check_rep``.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def axis_size(axis_name):
    """``lax.axis_size`` across jax versions: inside shard_map/pmap bodies,
    the size of a mapped axis. Old releases lack the accessor; ``psum`` of
    the literal 1 is the classic spelling and constant-folds to the same
    static int."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def tpu_compiler_params(pltpu_module):
    """The pallas-TPU compiler-params class under its current or legacy
    name (``CompilerParams`` vs ``TPUCompilerParams``); the constructor
    fields used in this repo (``dimension_semantics``,
    ``vmem_limit_bytes``) exist under both."""
    cls = getattr(pltpu_module, "CompilerParams", None)
    if cls is None:
        cls = getattr(pltpu_module, "TPUCompilerParams")
    return cls
