"""Torch-replica twin of the reference ConvNet, for parity experiments.

The reference architecture is torch (mnist_onegpu.py:11-31); this framework
re-implements it in flax (models/convnet.py). To demonstrate end-to-end
loss-curve parity — not just per-op equality — this module builds the torch
model with weights COPIED from the flax params, so both frameworks start
from bit-identical init and can be trained on identical batches
(parity_run.py at the repo root records the experiment; tests/test_convnet.py
asserts it at short horizon).

Layout conversions: flax conv kernels are HWIO -> torch OIHW; the
framework's canonical fc row order is (h, c, w) (models/convnet.py)
while torch flattens NCHW as (c, h, w), so the fc weight is re-blocked
accordingly.
"""

from __future__ import annotations

import numpy as np


def torch_twin(torch, params, hw: int):
    """Torch replica of the reference stack (conv 1->16 k5 p2, BN, ReLU,
    pool /2; conv 16->32; fc -> 10) with weights copied from flax
    ``params``. ``hw`` = spatial size after the two pools (H/4 for square
    inputs)."""
    tnn = torch.nn

    class TorchNet(tnn.Module):
        def __init__(self):
            super().__init__()
            self.layer1 = tnn.Sequential(
                tnn.Conv2d(1, 16, 5, stride=1, padding=2),
                tnn.BatchNorm2d(16), tnn.ReLU(), tnn.MaxPool2d(2, 2))
            self.layer2 = tnn.Sequential(
                tnn.Conv2d(16, 32, 5, stride=1, padding=2),
                tnn.BatchNorm2d(32), tnn.ReLU(), tnn.MaxPool2d(2, 2))
            self.fc = tnn.Linear(32 * hw * hw, 10)

        def forward(self, x):
            x = self.layer2(self.layer1(x))
            return self.fc(x.reshape(x.shape[0], -1))

    tm = TorchNet()
    with torch.no_grad():
        for i, layer in enumerate([tm.layer1, tm.layer2], start=1):
            k = np.asarray(params[f"conv{i}"]["kernel"]).transpose(3, 2, 0, 1).copy()
            layer[0].weight.copy_(torch.from_numpy(k))
            layer[0].bias.copy_(torch.from_numpy(
                np.asarray(params[f"conv{i}"]["bias"]).copy()))
            layer[1].weight.copy_(torch.from_numpy(
                np.asarray(params[f"bn{i}"]["scale"]).copy()))
            layer[1].bias.copy_(torch.from_numpy(
                np.asarray(params[f"bn{i}"]["bias"]).copy()))
        fck = np.asarray(params["fc"]["kernel"])
        # ours: canonical (h, c, w) rows (models/convnet.py) -> torch:
        # NCHW flatten = (c, h, w) rows
        fck_chw = (fck.reshape(hw, 32, hw, 10)
                   .transpose(1, 0, 2, 3).reshape(32 * hw * hw, 10))
        tm.fc.weight.copy_(torch.from_numpy(fck_chw.T.copy()))
        tm.fc.bias.copy_(torch.from_numpy(np.asarray(params["fc"]["bias"]).copy()))
    return tm
