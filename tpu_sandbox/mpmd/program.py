"""Per-stage MPMD programs — separately compiled, bitwise-matching the
SPMD pipeline.

Each stage compiles ONLY its own program (the AOT receipt in
tools/aot_mpmd.py shows stage 0's executable carries the embedding table
and no head, the last stage's the reverse): forward for its layer slice,
a vjp-based backward fed by the downstream stage's shipped cotangent, and
a stage-local optimizer apply. The math is lifted from
``parallel/pipeline.py`` (same ``Block.apply`` scan, same fp32 layernorm,
same ``head_loss/M``), so the only parity question is accumulation order.

Bitwise discipline (held by tests/test_mpmd.py against the real SPMD
engine on a ``{'data': 1, 'pipe': S}`` mesh, where psum/pmean are
identities):

- The SPMD pipeline differentiates one ``lax.scan`` over ticks; scan's
  transpose accumulates each stage's parameter cotangent in REVERSE tick
  order, i.e. descending microbatch. So per-microbatch stage grads here
  are summed with a left fold in **descending** microbatch order —
  ``((0 + g[M-1]) + g[M-2]) + ... + g[0]`` — which reproduces the scan
  transpose add-for-add (``0 + g`` is bitwise ``g``).
- The loss scalar is accumulated ascending (forward tick order), like
  the scan carry. Trained *parameters* are bitwise across ≥20 steps for
  sgd and adam; the reported *loss* can differ from the fused SPMD
  program by ~1 ulp on some steps — XLA may group the cross-entropy mean
  reduction differently in the two compilations, and a reduce regrouping
  changes the forward value but not its gradient (the cotangent of a
  mean is uniform regardless of grouping). Params are the parity
  contract; losses are compared to 1e-6.
- optax's sgd/adam update leaf-wise, so the stage-local apply over a
  stage's param slice matches the SPMD whole-tree update exactly.
  (Global-norm-clipped transforms would couple stages and break this —
  callers wanting clipping must apply it per stage on both sides.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from tpu_sandbox.models.transformer import Block, TransformerConfig
from tpu_sandbox.ops.losses import cross_entropy_loss
from tpu_sandbox.parallel.pipeline import (
    _layernorm,
    merge_transformer_params,
    split_transformer_params,
)


def check_layer_split(n_layers: int, n_stages: int,
                      layer_split) -> list[int]:
    """Validate (or derive) the per-stage layer counts. ``None`` keeps
    the original contract: layers must divide evenly."""
    if layer_split is None:
        if n_layers % n_stages:
            raise ValueError(
                f"{n_layers} layers not divisible into {n_stages} stages "
                "(pass layer_split for an uneven pipeline)")
        return [n_layers // n_stages] * n_stages
    split = [int(x) for x in layer_split]
    if len(split) != n_stages:
        raise ValueError(
            f"layer_split {split} has {len(split)} entries for "
            f"{n_stages} stages")
    if any(x < 1 for x in split) or sum(split) != n_layers:
        raise ValueError(
            f"layer_split {split} must be positive and sum to {n_layers}")
    return split


def stage_params(flat_params: dict, stage: int, n_stages: int, *,
                 layer_split=None) -> dict:
    """Slice a full TransformerLM param tree to one stage's subtree:
    ``{"stages": [layers_of_stage, ...]}`` plus ``"pre"`` on stage 0 and
    ``"post"`` on the last stage — the same leaves the SPMD engine
    shards to that pipe rank, so checkpoints interchange leaf-for-leaf.
    ``layer_split`` gives each stage's layer count for uneven
    pipelines."""
    # n_stages=1 skips the splitter's own divisibility check — uneven
    # pipelines validate through check_layer_split instead
    pre, stacked, post = split_transformer_params(flat_params, 1)
    n_layers = jax.tree.leaves(stacked)[0].shape[0]
    split = check_layer_split(n_layers, n_stages, layer_split)
    lo = sum(split[:stage])
    hi = lo + split[stage]
    sliced = jax.tree.map(lambda x: np.asarray(x)[lo:hi], stacked)
    out = {"stages": sliced}
    if stage == 0:
        out["pre"] = jax.tree.map(np.asarray, pre)
    if stage == n_stages - 1:
        out["post"] = jax.tree.map(np.asarray, post)
    return out


def merge_stage_params(parts: list[dict]) -> dict:
    """Per-stage param subtrees (stage order) -> flat TransformerLM tree."""
    stacked = jax.tree.map(
        lambda *xs: np.concatenate([np.asarray(x) for x in xs]),
        *[p["stages"] for p in parts])
    return merge_transformer_params(
        jax.tree.map(np.asarray, parts[0]["pre"]), stacked,
        jax.tree.map(np.asarray, parts[-1]["post"]))


def tree_add(a, b):
    """Elementwise host add — the accumulation op of the scan transpose
    (IEEE fp32 add is the same bit pattern on host numpy and XLA:CPU)."""
    return jax.tree.map(lambda x, y: np.asarray(x) + np.asarray(y), a, b)


def tree_zeros_like(t):
    return jax.tree.map(lambda x: np.zeros_like(np.asarray(x)), t)


def accumulate_descending(grads_by_mb: dict):
    """Left-fold per-microbatch grads in descending microbatch order —
    the scan-transpose order (module docstring). ``grads_by_mb`` maps
    microbatch index -> grad tree and must be dense over [0, M)."""
    order = sorted(grads_by_mb, reverse=True)
    acc = tree_zeros_like(grads_by_mb[order[0]])
    for m in order:
        acc = tree_add(acc, grads_by_mb[m])
    return acc


class StageProgram:
    """Compiled step functions for one pipeline stage.

    ``device`` pins the stage to its own mesh: every jitted call runs
    where its (committed) params live, so N stages on one process give
    N separate single-device meshes each executing only its own
    executable — the CPU twin of one mesh per stage-gang.
    """

    def __init__(self, config: TransformerConfig,
                 tx: optax.GradientTransformation, stage: int,
                 n_stages: int, microbatches: int, *, device=None,
                 layer_split=None):
        self.layer_split = check_layer_split(config.n_layers, n_stages,
                                             layer_split)
        self.config = config
        self.tx = tx
        self.stage = stage
        self.n_stages = n_stages
        self.microbatches = microbatches
        self.device = device
        self.is_first = stage == 0
        self.is_last = stage == n_stages - 1
        self._block = Block(config, None)
        self._build()

    # -- the per-stage math (identical to parallel/pipeline.py) -------------

    def _stage_apply(self, sp, h):
        def one(hh, layer_params):
            return self._block.apply({"params": layer_params}, hh), None

        out, _ = lax.scan(one, h, sp)
        return out

    def _embed(self, pre, tokens):
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1]), tokens.shape)
        tok = pre["tok_emb"]["embedding"][tokens]
        pos = pre["pos_emb"]["embedding"][positions]
        return (tok + pos).astype(self.config.dtype)

    def _head_loss(self, post, h, targets):
        dt = self.config.dtype
        hn = _layernorm(h, post["ln_f"]).astype(dt)
        logits = (hn @ post["lm_head"]["kernel"].astype(dt)
                  + post["lm_head"]["bias"].astype(dt))
        return cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]), targets.reshape(-1))

    def _forward(self, params, x):
        h = self._embed(params["pre"], x) if self.is_first else x
        return self._stage_apply(params["stages"], h)

    # -- compiled entry points ----------------------------------------------

    def _build(self) -> None:
        M = self.microbatches

        def fwd(params, x):
            return self._forward(params, x)

        def bwd(params, x, g_out):
            # recompute-forward + transpose, exactly what the SPMD scan's
            # remat backward does for this tick
            if self.is_first:
                _, vjp = jax.vjp(lambda p: self._forward(p, x), params)
                return vjp(g_out)[0], None
            _, vjp = jax.vjp(self._forward, params, x)
            return vjp(g_out)

        def loss_grad(params, x, targets):
            def f(p, xx):
                out = self._forward(p, xx)  # reads pre/stages only
                return self._head_loss(p["post"], out, targets) / M

            lv, grads = jax.value_and_grad(f, argnums=(0, 1))(params, x)
            return lv, grads[0], grads[1]

        def apply_grads(params, opt_state, grads):
            updates, new_opt = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        # -- ZB-H1 backward split: grad-input (B) vs grad-weight (W) as
        # separate programs. B runs the cotangent chain layer by layer
        # and stashes each layer's (input, output-cotangent) pair; W is
        # then PURE weight-grad work from the stash — it never re-walks
        # the chain, which is what makes deferring it into the drain
        # bubble a win instead of a 2x backward. The split is exact
        # math against the fused backward but NOT bitwise: each
        # per-layer vjp compiles as its own XLA unit, whose reduction
        # grouping differs from the fused scan transpose by a few ulps
        # (parity held at 1e-6 loss / per-leaf allclose by
        # tests/test_mpmd_fastfabric.py). The bitwise contracts are
        # untouched where they bind: fused 1F1B vs SPMD, and ZB vs ZB —
        # replay after a fault re-runs the SAME split programs, so the
        # fault matrix still lands bitwise. Stage 0 is the exception to
        # the split: its weight grads need the internal chain anyway
        # (nothing upstream wants its g_in), so it keeps the
        # chain-walking W (``bwd_weight_chain``) and skips B entirely.

        def _fwd_collect(params, h0):
            # forward over the layer slice, stacking each layer's INPUT
            def one(h, lp):
                return self._block.apply({"params": lp}, h), h

            return lax.scan(one, h0, params["stages"])

        def _chain(params, hs, g_top):
            # reverse sweep: per-layer grad-input vjp, stacking each
            # layer's OUTPUT cotangent alongside its stashed input
            def one(g, xs):
                lp, h_in = xs
                _, vjp = jax.vjp(
                    lambda hh: self._block.apply({"params": lp}, hh), h_in)
                return vjp(g)[0], g

            return lax.scan(one, g_top, (params["stages"], hs),
                            reverse=True)

        def _weight_grads(params, stash):
            hs, gs = stash

            def one(c, xs):
                lp, h_in, g = xs
                _, vjp = jax.vjp(
                    lambda p: self._block.apply({"params": p}, h_in), lp)
                return c, vjp(g)[0]

            _, g_stages = lax.scan(one, 0, (params["stages"], hs, gs))
            return g_stages

        def bwd_input(params, x, g_out):
            _, hs = _fwd_collect(params, x)
            gx, gs = _chain(params, hs, g_out)
            return gx, (hs, gs)

        def bwd_weight(params, stash):
            return {"stages": _weight_grads(params, stash)}

        def bwd_weight_chain(params, x, g_out):
            # stage 0's W: the full vjp w.r.t. params (embed included) —
            # its chain feeds nothing upstream, so it rides inside W
            _, vjp = jax.vjp(lambda p: self._forward(p, x), params)
            return vjp(g_out)[0]

        def loss_bwd_input(params, x, targets):
            h_out, hs = _fwd_collect(params, x)
            lv, head_vjp = jax.vjp(
                lambda hh: self._head_loss(params["post"], hh, targets) / M,
                h_out)
            (g_top,) = head_vjp(jnp.ones_like(lv))
            gx, gs = _chain(params, hs, g_top)
            return lv, gx, (hs, gs, h_out)

        def loss_bwd_weight(params, targets, stash):
            hs, gs, h_out = stash
            g_post = jax.grad(
                lambda post: self._head_loss(post, h_out, targets) / M)(
                params["post"])
            return {"stages": _weight_grads(params, (hs, gs)),
                    "post": g_post}

        self.fwd = jax.jit(fwd)
        self.bwd = jax.jit(bwd)
        self.loss_grad = jax.jit(loss_grad)
        self.apply_grads = jax.jit(apply_grads)
        self.bwd_input = jax.jit(bwd_input)
        self.bwd_weight = jax.jit(bwd_weight)
        self.bwd_weight_chain = jax.jit(bwd_weight_chain)
        self.loss_bwd_input = jax.jit(loss_bwd_input)
        self.loss_bwd_weight = jax.jit(loss_bwd_weight)

    # -- placement ----------------------------------------------------------

    def place(self, tree):
        """Commit a pytree to this stage's device (jit dispatch follows
        committed operands, so the stage's programs execute on its mesh)."""
        if self.device is None:
            return tree
        return jax.device_put(tree, self.device)

    def init_opt_state(self, params):
        return self.place(self.tx.init(params))

    def lower_train_programs(self, params, sample_x, sample_targets=None,
                             *, zb: bool = False):
        """AOT-lower this stage's programs (fwd and, where they exist,
        bwd/loss_grad) without executing — the hook aot_mpmd.py and the
        graftlint HLO pass share. With ``zb`` the split ZB-H1 backward
        pair (grad-input / grad-weight) is lowered alongside, so the AOT
        receipt shows what each half's executable actually carries."""
        out = {}
        if self.is_last:
            out["loss_grad"] = self.loss_grad.lower(
                params, sample_x, sample_targets)
            if zb:
                out["loss_bwd_input"] = self.loss_bwd_input.lower(
                    params, sample_x, sample_targets)
                _, _, stash = jax.eval_shape(
                    self.loss_bwd_input, params, sample_x, sample_targets)
                out["loss_bwd_weight"] = self.loss_bwd_weight.lower(
                    params, sample_targets, stash)
        else:
            out["fwd"] = self.fwd.lower(params, sample_x)
            g = jax.eval_shape(self.fwd, params, sample_x)
            out["bwd"] = self.bwd.lower(params, sample_x, g)
            if zb:
                if self.is_first:
                    out["bwd_weight"] = self.bwd_weight_chain.lower(
                        params, sample_x, g)
                else:
                    out["bwd_input"] = self.bwd_input.lower(
                        params, sample_x, g)
                    _, stash = jax.eval_shape(
                        self.bwd_input, params, sample_x, g)
                    out["bwd_weight"] = self.bwd_weight.lower(
                        params, stash)
        return out
