"""Stage transport: device-buffer shipment of activations/grads between
meshes.

The first wire is the KV store (control plane) + host-RAM staging: a
producer stages its device buffer to host bytes, chunks them under the
store's read cap, and publishes a seq-numbered slot; the consumer blocks
on the slot's meta key, reassembles, and uploads to its own mesh. Slots
are *durable until acknowledged* — a stage that dies mid-step relaunches
from its checkpoint and replays, and every slot its peers already
produced is still there to re-read, so recovery never recomputes a
neighbor's work. The interface is deliberately narrow (put / get /
claim / release_step / stats) so a faster wire — real DCN send/recv, or
ICI once jax grows cross-mesh transfer — can replace this one without
touching the schedule or the per-stage programs.

Delivery discipline:

- **Produce once.** ``put`` claims the slot's commit counter with an
  atomic fetch-add; only the first claimant writes. A replaying stage
  (same step re-run after a crash) re-puts the same slot, loses the
  claim, sees the slot complete, and skips — so a slot's payload is
  written exactly once even when the producer runs the step twice.
  If the first claimant died *mid-write* (commit claimed, meta never
  landed), the replayer detects the incomplete slot and finishes it:
  replay is deterministic, so the bytes it writes are the bytes the
  dead writer would have written.
- **Claim-once consume.** ``claim`` is a per-generation fetch-add on
  the slot's claim counter: within one generation a slot feeds exactly
  one consumer op (the duplicate-delivery audit), while a relaunched
  generation claims afresh — replay re-reads are legitimate, double
  consumption inside a live schedule is a bug.
- **TTL hygiene.** Claim markers carry a TTL so a dead generation's
  claims cannot satisfy (or poison) a later one forever. Slot payloads
  are TTL'd only if asked — durability until ``release_step`` is what
  makes crash replay cheap.
- ``release_step`` garbage-collects every slot of an edge up to a step
  the whole pipeline has applied; the leader calls it once per step.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from tpu_sandbox.obs import get_recorder, get_registry

SLOT_PREFIX = "mpmd/slot"
CLAIM_PREFIX = "mpmd/claim"


def _account(stats: TransportStats) -> None:
    """Mirror per-transport stats into the process metrics registry so a
    live OP_METRICS scrape sees wire traffic without reaching into every
    Transport instance."""
    reg = get_registry()
    reg.gauge("transport.puts").set(stats.puts)
    reg.gauge("transport.gets").set(stats.gets)
    reg.gauge("transport.bytes_out").set(stats.bytes_out)
    reg.gauge("transport.bytes_in").set(stats.bytes_in)


def pack_arrays(arrays) -> tuple[dict, bytes]:
    """[arrays] -> (meta, payload). Raw little-endian bytes, no pickling:
    the payload crosses trust and process boundaries, and bitwise replay
    parity needs the exact bits, not a codec's idea of them."""
    meta_arrays = []
    parts = []
    for a in arrays:
        a = np.ascontiguousarray(np.asarray(a))
        meta_arrays.append({"shape": list(a.shape), "dtype": a.dtype.str})
        parts.append(a.tobytes())
    return {"arrays": meta_arrays}, b"".join(parts)


def unpack_arrays(meta: dict, payload: bytes) -> list[np.ndarray]:
    out = []
    off = 0
    for spec in meta["arrays"]:
        dt = np.dtype(spec["dtype"])
        n = int(np.prod(spec["shape"], dtype=np.int64)) * dt.itemsize
        out.append(
            np.frombuffer(payload[off:off + n], dt).reshape(spec["shape"]))
        off += n
    if off != len(payload):
        raise ValueError(
            f"payload is {len(payload)} bytes, meta describes {off}")
    return out


@dataclass
class TransportStats:
    """Wire accounting for the bench receipt. Latencies are whole-op wall
    times (staging + chunk puts / blocking wait + reassembly)."""

    puts: int = 0
    gets: int = 0
    bytes_out: int = 0
    bytes_in: int = 0
    put_seconds: float = 0.0
    get_seconds: float = 0.0
    get_wait_seconds: float = 0.0  # time blocked on a slot not yet produced

    def snapshot(self) -> dict:
        return {
            "puts": self.puts, "gets": self.gets,
            "bytes_out": self.bytes_out, "bytes_in": self.bytes_in,
            "put_seconds": round(self.put_seconds, 6),
            "get_seconds": round(self.get_seconds, 6),
            "get_wait_seconds": round(self.get_wait_seconds, 6),
        }


class Transport:
    """Interface contract; see the module docstring for the semantics."""

    stats: TransportStats

    def put(self, edge: str, step: int, mb: int, arrays) -> bool:
        """Publish a slot. True if this call won the produce claim, False
        when the slot was already complete (idempotent replay)."""
        raise NotImplementedError

    def get(self, edge: str, step: int, mb: int, *,
            timeout: float = 60.0) -> list[np.ndarray]:
        """Block until the slot exists; TimeoutError past ``timeout``."""
        raise NotImplementedError

    def poll(self, edge: str, step: int, mb: int) -> bool:
        raise NotImplementedError

    def claim(self, edge: str, step: int, mb: int, generation: int) -> bool:
        """Claim-once consume marker; True exactly once per generation."""
        raise NotImplementedError

    def release_step(self, edge: str, step: int) -> None:
        """Drop every slot of ``edge`` at ``step`` (pipeline has applied)."""
        raise NotImplementedError


class LocalTransport(Transport):
    """In-process transport over a dict + condition variable. Same
    produce-once/claim-once contract as the KV wire (a slot survives its
    producer; replays re-put idempotently), so the tier-1 schedule and
    recovery tests exercise the exact delivery discipline the distributed
    path relies on — without sockets."""

    def __init__(self):
        self._slots: dict[tuple, tuple[dict, bytes]] = {}
        self._commits: dict[tuple, int] = {}
        self._claims: dict[tuple, int] = {}
        self._cond = threading.Condition()
        self.stats = TransportStats()

    def put(self, edge, step, mb, arrays) -> bool:
        t0 = time.perf_counter()
        meta, payload = pack_arrays(arrays)
        key = (edge, step, mb)
        with self._cond:
            self._commits[key] = self._commits.get(key, 0) + 1
            first = self._commits[key] == 1
            if not first and key in self._slots:
                return False
            self._slots[key] = (meta, payload)
            self._cond.notify_all()
        self.stats.puts += 1
        self.stats.bytes_out += len(payload)
        self.stats.put_seconds += time.perf_counter() - t0
        _account(self.stats)
        get_recorder().instant(
            "slot:put", args={"edge": edge, "step": step, "mb": mb,
                              "bytes": len(payload), "first": first})
        return first

    def get(self, edge, step, mb, *, timeout: float = 60.0):
        t0 = time.perf_counter()
        key = (edge, step, mb)
        deadline = t0 + timeout
        with self._cond:
            while key not in self._slots:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise TimeoutError(f"transport slot {key} never arrived")
                self.stats.get_wait_seconds += min(remaining, 0.05)
                self._cond.wait(min(remaining, 0.05))
            meta, payload = self._slots[key]
        out = unpack_arrays(meta, payload)
        self.stats.gets += 1
        self.stats.bytes_in += len(payload)
        self.stats.get_seconds += time.perf_counter() - t0
        _account(self.stats)
        return out

    def poll(self, edge, step, mb) -> bool:
        with self._cond:
            return (edge, step, mb) in self._slots

    def claim(self, edge, step, mb, generation) -> bool:
        key = (edge, step, mb, generation)
        with self._cond:
            self._claims[key] = self._claims.get(key, 0) + 1
            won = self._claims[key] == 1
        if won:
            get_recorder().instant(
                "slot:claim", args={"edge": edge, "step": step, "mb": mb,
                                    "gen": generation})
        return won

    def release_step(self, edge, step) -> None:
        with self._cond:
            for key in [k for k in self._slots if k[0] == edge
                        and k[1] == step]:
                del self._slots[key]

    # -- audit (tier-1 delivery tests) --------------------------------------

    def audit(self) -> dict:
        """Counters for the zero-dup/zero-loss audit: commit attempts per
        slot and claims per (slot, generation)."""
        with self._cond:
            return {
                "commits": {"/".join(map(str, k)): v
                            for k, v in self._commits.items()},
                "claims": {"/".join(map(str, k)): v
                           for k, v in self._claims.items()},
            }


class KVTransport(Transport):
    """The KV-store wire. Chunked puts sized under the client's 1 MiB
    read cap; meta is written LAST so its presence is the slot-complete
    signal; commit/claim counters give produce-once / claim-once.

    ``kv`` may be namespaced or raw — stages of one pipeline must share
    the SAME namespace view (the transport plane is cross-job state when
    stages run as separate scheduler jobs, so it lives under a pipeline
    prefix, not under either job's ``job/<id>/``).
    """

    def __init__(self, kv, *, prefix: str = "", chunk_bytes: int = 256 << 10,
                 claim_ttl: float = 600.0, slot_ttl: float | None = None,
                 poll_interval: float = 0.005):
        if chunk_bytes < 1 or chunk_bytes > (1 << 20) - 4096:
            raise ValueError(
                f"chunk_bytes {chunk_bytes} must fit the KV read cap (1MiB)")
        self.kv = kv
        self.prefix = prefix.rstrip("/") + "/" if prefix else ""
        self.chunk_bytes = chunk_bytes
        self.claim_ttl = claim_ttl
        self.slot_ttl = slot_ttl
        self.poll_interval = poll_interval
        self.stats = TransportStats()

    def _slot(self, edge: str, step: int, mb: int) -> str:
        return f"{self.prefix}{SLOT_PREFIX}/{edge}/{step}/{mb}"

    def _set(self, key: str, val: bytes) -> None:
        if self.slot_ttl is not None:
            self.kv.set_ttl(key, val, self.slot_ttl)
        else:
            self.kv.set(key, val)

    def put(self, edge, step, mb, arrays) -> bool:
        t0 = time.perf_counter()
        meta, payload = pack_arrays(arrays)
        slot = self._slot(edge, step, mb)
        first = self.kv.add(f"{slot}/commit", 1) == 1
        if not first and self.kv.try_get(f"{slot}/meta") is not None:
            return False  # complete slot: replay no-op
        # not first but incomplete: the claimant died mid-write — finish
        # its slot (deterministic replay writes the identical bytes)
        nchunks = -(-len(payload) // self.chunk_bytes) if payload else 0
        for i in range(nchunks):
            self._set(f"{slot}/chunk/{i}",
                      payload[i * self.chunk_bytes:(i + 1) * self.chunk_bytes])
        meta = dict(meta, nchunks=nchunks, bytes=len(payload),
                    seq=(step, mb))
        self._set(f"{slot}/meta", json.dumps(meta).encode())
        self.stats.puts += 1
        self.stats.bytes_out += len(payload)
        self.stats.put_seconds += time.perf_counter() - t0
        _account(self.stats)
        get_recorder().instant(
            "slot:put", args={"edge": edge, "step": step, "mb": mb,
                              "bytes": len(payload), "first": first})
        return first

    def get(self, edge, step, mb, *, timeout: float = 60.0):
        t0 = time.perf_counter()
        slot = self._slot(edge, step, mb)
        deadline = t0 + timeout
        raw = self.kv.try_get(f"{slot}/meta")
        while raw is None:
            if time.perf_counter() >= deadline:
                raise TimeoutError(
                    f"transport slot {slot} never arrived ({timeout}s)")
            time.sleep(self.poll_interval)
            self.stats.get_wait_seconds += self.poll_interval
            raw = self.kv.try_get(f"{slot}/meta")
        meta = json.loads(raw)
        parts = []
        for i in range(meta["nchunks"]):
            chunk = self.kv.try_get(f"{slot}/chunk/{i}")
            if chunk is None:
                raise RuntimeError(
                    f"slot {slot} chunk {i} missing under a complete meta "
                    "(released early, or TTL expired mid-read)")
            parts.append(chunk)
        payload = b"".join(parts)
        if len(payload) != meta["bytes"]:
            raise RuntimeError(
                f"slot {slot}: reassembled {len(payload)} bytes, "
                f"meta says {meta['bytes']}")
        out = unpack_arrays(meta, payload)
        self.stats.gets += 1
        self.stats.bytes_in += len(payload)
        self.stats.get_seconds += time.perf_counter() - t0
        _account(self.stats)
        return out

    def poll(self, edge, step, mb) -> bool:
        return self.kv.try_get(f"{self._slot(edge, step, mb)}/meta") is not None

    def claim(self, edge, step, mb, generation) -> bool:
        key = (f"{self.prefix}{CLAIM_PREFIX}/{generation}/{edge}/{step}/{mb}")
        n = self.kv.add(key, 1)
        if n == 1:
            # fetch-add created a plain counter; re-arm it as TTL'd so a
            # dead generation's claims expire (value no longer needs to
            # count past "claimed at least twice" for the audit)
            self.kv.set_ttl(key, str(n), self.claim_ttl)
            get_recorder().instant(
                "slot:claim", args={"edge": edge, "step": step, "mb": mb,
                                    "gen": generation})
        return n == 1

    def release_step(self, edge, step) -> None:
        self.kv.delete_prefix(f"{self.prefix}{SLOT_PREFIX}/{edge}/{step}/")

    # -- audit --------------------------------------------------------------

    def audit(self) -> dict:
        """Commit counters per live slot and claim counters per generation
        (released slots drop out of ``commits``; claims persist until
        their TTL, which is what the post-mortem audit reads)."""
        commits, claims = {}, {}
        for key in self.kv.keys(f"{self.prefix}{SLOT_PREFIX}/"):
            if key.endswith("/commit"):
                commits[key[len(self.prefix) + len(SLOT_PREFIX) + 1:
                            -len("/commit")]] = int(self.kv.get(key))
        for key in self.kv.keys(f"{self.prefix}{CLAIM_PREFIX}/"):
            raw = self.kv.try_get(key)
            if raw is not None:
                claims[key[len(self.prefix) + len(CLAIM_PREFIX) + 1:]] = (
                    int(raw))
        return {"commits": commits, "claims": claims}


@dataclass
class EdgeNames:
    """The two directed edges between adjacent stages s and s+1."""

    stage: int
    act: str = field(init=False)   # activations s -> s+1
    grad: str = field(init=False)  # cotangents  s+1 -> s

    def __post_init__(self):
        self.act = f"act{self.stage}"
        self.grad = f"grad{self.stage}"
