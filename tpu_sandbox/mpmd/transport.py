"""Stage transport: device-buffer shipment of activations/grads between
meshes.

Three wires behind one narrow interface (put / get / poll / claim /
release_step / stats):

- :class:`KVTransport` — the KV store (control plane) + host staging: a
  producer stages its device buffer to host bytes, chunks them under the
  store's read cap, and publishes a seq-numbered slot; the consumer
  reads chunk-pipelined (each chunk is fetched as soon as it lands, not
  after the slot completes) and reassembles through memoryviews, so the
  only full-payload copy on the read side is the final join.
- :class:`LocalTransport` — the in-process host wire (dict + condvar),
  same delivery contract, no sockets. Tier-1's workhorse.
- :class:`DeviceTransport` — the fast path for stages colocated in one
  process on separate meshes: ``put`` hands the producer's device
  arrays straight to the consumer (which ``jax.device_put``-s them onto
  its own mesh), while a durable *journal* transport underneath records
  the same slot for recovery. The journal owns produce-once commits and
  claim-once consumption, so the fault matrix semantics are identical
  to the host wires — the device buffer is just a cache in front of it.

Slots are *durable until acknowledged* — a stage that dies mid-step
relaunches from its checkpoint and replays, and every slot its peers
already produced is still there to re-read, so recovery never recomputes
a neighbor's work. The interface is deliberately narrow so a faster wire
— real DCN send/recv, or ICI once jax grows cross-mesh transfer — can
replace these without touching the schedule or the per-stage programs.

Delivery discipline:

- **Produce once.** ``put`` claims the slot's commit counter with an
  atomic fetch-add; only the first claimant writes. A replaying stage
  (same step re-run after a crash) re-puts the same slot, loses the
  claim, sees the slot complete, and skips — so a slot's payload is
  written exactly once even when the producer runs the step twice.
  If the first claimant died *mid-write* (commit claimed, meta never
  landed), the replayer detects the incomplete slot and finishes it:
  replay is deterministic, so the bytes it writes are the bytes the
  dead writer would have written.
- **Claim-once consume.** ``claim`` is a per-generation fetch-add on
  the slot's claim counter: within one generation a slot feeds exactly
  one consumer op (the duplicate-delivery audit), while a relaunched
  generation claims afresh — replay re-reads are legitimate, double
  consumption inside a live schedule is a bug.
- **TTL hygiene.** Claim markers carry a TTL so a dead generation's
  claims cannot satisfy (or poison) a later one forever. Slot payloads
  are TTL'd only if asked — durability until ``release_step`` is what
  makes crash replay cheap.
- ``release_step`` garbage-collects every slot of an edge up to a step
  the whole pipeline has applied; the leader calls it once per step.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from tpu_sandbox.obs import get_recorder, get_registry

SLOT_PREFIX = "mpmd/slot"
CLAIM_PREFIX = "mpmd/claim"


def _account(stats: TransportStats) -> None:
    """Mirror per-transport stats into the process metrics registry so a
    live OP_METRICS scrape sees wire traffic without reaching into every
    Transport instance."""
    reg = get_registry()
    reg.gauge("transport.puts").set(stats.puts)
    reg.gauge("transport.gets").set(stats.gets)
    reg.gauge("transport.bytes_out").set(stats.bytes_out)
    reg.gauge("transport.bytes_in").set(stats.bytes_in)


def pack_views(arrays) -> tuple[dict, list[memoryview]]:
    """[arrays] -> (meta, per-array memoryviews). Raw little-endian
    bytes, no pickling: the payload crosses trust and process
    boundaries, and bitwise replay parity needs the exact bits, not a
    codec's idea of them. The views alias the (contiguous) host arrays —
    zero staging copies until bytes actually hit a wire."""
    meta_arrays = []
    views = []
    for a in arrays:
        a = np.asarray(a)
        shape = list(a.shape)  # before ascontiguousarray: it 1-d's 0-d
        if not a.flags["C_CONTIGUOUS"]:
            a = np.ascontiguousarray(a)
        meta_arrays.append({"shape": shape, "dtype": a.dtype.str})
        views.append(memoryview(a).cast("B") if a.nbytes
                     else memoryview(b""))
    return {"arrays": meta_arrays}, views


def pack_arrays(arrays) -> tuple[dict, bytes]:
    """[arrays] -> (meta, joined payload); the one-copy variant for
    wires that want a single buffer (LocalTransport's slot dict)."""
    meta, views = pack_views(arrays)
    return meta, b"".join(views)


def iter_chunks(views: list[memoryview], chunk_bytes: int):
    """Yield ``chunk_bytes``-sized bytes across the concatenation of
    ``views`` without ever materialising the joined payload — each chunk
    is assembled straight from the array views it overlaps."""
    pending: list[memoryview] = []
    size = 0
    for v in views:
        off = 0
        while off < len(v):
            take = min(chunk_bytes - size, len(v) - off)
            pending.append(v[off:off + take])
            size += take
            off += take
            if size == chunk_bytes:
                yield pending[0].tobytes() if len(pending) == 1 \
                    else b"".join(pending)
                pending, size = [], 0
    if size:
        yield pending[0].tobytes() if len(pending) == 1 \
            else b"".join(pending)


def unpack_arrays(meta: dict, payload) -> list[np.ndarray]:
    """(meta, payload bytes-like) -> [arrays]. Slices through a
    memoryview, so each array aliases the payload buffer instead of
    copying its range out (``bytes`` slicing copies; this path is the
    read side of every wire)."""
    view = memoryview(payload)
    out = []
    off = 0
    for spec in meta["arrays"]:
        dt = np.dtype(spec["dtype"])
        n = int(np.prod(spec["shape"], dtype=np.int64)) * dt.itemsize
        out.append(
            np.frombuffer(view[off:off + n], dt).reshape(spec["shape"]))
        off += n
    if off != len(view):
        raise ValueError(
            f"payload is {len(view)} bytes, meta describes {off}")
    return out


@dataclass
class TransportStats:
    """Wire accounting for the bench receipt. Latencies are whole-op wall
    times (staging + chunk puts / blocking wait + reassembly)."""

    puts: int = 0
    gets: int = 0
    bytes_out: int = 0
    bytes_in: int = 0
    put_seconds: float = 0.0
    get_seconds: float = 0.0       # retrieval work only (wait excluded)
    get_wait_seconds: float = 0.0  # time blocked on a slot not yet produced
    device_hits: int = 0           # gets served from the device buffer
    journal_fallbacks: int = 0     # gets that fell back to the journal

    def snapshot(self) -> dict:
        out = {
            "puts": self.puts, "gets": self.gets,
            "bytes_out": self.bytes_out, "bytes_in": self.bytes_in,
            "put_seconds": round(self.put_seconds, 6),
            "get_seconds": round(self.get_seconds, 6),
            "get_wait_seconds": round(self.get_wait_seconds, 6),
        }
        if self.device_hits or self.journal_fallbacks:
            out["device_hits"] = self.device_hits
            out["journal_fallbacks"] = self.journal_fallbacks
        return out


class Transport:
    """Interface contract; see the module docstring for the semantics."""

    stats: TransportStats

    def put(self, edge: str, step: int, mb: int, arrays) -> bool:
        """Publish a slot. True if this call won the produce claim, False
        when the slot was already complete (idempotent replay)."""
        raise NotImplementedError

    def get(self, edge: str, step: int, mb: int, *,
            timeout: float = 60.0) -> list[np.ndarray]:
        """Block until the slot exists; TimeoutError past ``timeout``."""
        raise NotImplementedError

    def poll(self, edge: str, step: int, mb: int) -> bool:
        raise NotImplementedError

    def claim(self, edge: str, step: int, mb: int, generation: int) -> bool:
        """Claim-once consume marker; True exactly once per generation."""
        raise NotImplementedError

    def release_step(self, edge: str, step: int) -> None:
        """Drop every slot of ``edge`` at ``step`` (pipeline has applied)."""
        raise NotImplementedError


class LocalTransport(Transport):
    """In-process transport over a dict + condition variable. Same
    produce-once/claim-once contract as the KV wire (a slot survives its
    producer; replays re-put idempotently), so the tier-1 schedule and
    recovery tests exercise the exact delivery discipline the distributed
    path relies on — without sockets."""

    def __init__(self):
        self._slots: dict[tuple, tuple[dict, bytes]] = {}
        self._commits: dict[tuple, int] = {}
        self._claims: dict[tuple, int] = {}
        self._cond = threading.Condition()
        self.stats = TransportStats()

    def put(self, edge, step, mb, arrays) -> bool:
        t0 = time.perf_counter()
        meta, payload = pack_arrays(arrays)
        key = (edge, step, mb)
        with self._cond:
            self._commits[key] = self._commits.get(key, 0) + 1
            first = self._commits[key] == 1
            if not first and key in self._slots:
                return False
            self._slots[key] = (meta, payload)
            self._cond.notify_all()
        self.stats.puts += 1
        self.stats.bytes_out += len(payload)
        self.stats.put_seconds += time.perf_counter() - t0
        _account(self.stats)
        get_recorder().instant(
            "slot:put", args={"edge": edge, "step": step, "mb": mb,
                              "bytes": len(payload), "first": first})
        return first

    def get(self, edge, step, mb, *, timeout: float = 60.0):
        t0 = time.perf_counter()
        t_mono = time.monotonic()
        key = (edge, step, mb)
        deadline = t0 + timeout
        waited = 0.0
        with self._cond:
            while key not in self._slots:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise TimeoutError(f"transport slot {key} never arrived")
                w0 = time.perf_counter()
                self._cond.wait(min(remaining, 0.05))
                waited += time.perf_counter() - w0
            meta, payload = self._slots[key]
        out = unpack_arrays(meta, payload)
        self.stats.gets += 1
        self.stats.bytes_in += len(payload)
        # blocked-on-producer time is the schedule's, not the wire's:
        # it lands in get_wait_seconds and the span starts after it, so
        # get_seconds / slot:get durs measure retrieval work only
        self.stats.get_wait_seconds += waited
        self.stats.get_seconds += time.perf_counter() - t0 - waited
        _account(self.stats)
        get_recorder().complete(
            "slot:get", t_mono + waited,
            args={"edge": edge, "step": step, "mb": mb,
                  "bytes": len(payload), "tier": "local"})
        return out

    def poll(self, edge, step, mb) -> bool:
        with self._cond:
            return (edge, step, mb) in self._slots

    def claim(self, edge, step, mb, generation) -> bool:
        key = (edge, step, mb, generation)
        with self._cond:
            self._claims[key] = self._claims.get(key, 0) + 1
            won = self._claims[key] == 1
        if won:
            get_recorder().instant(
                "slot:claim", args={"edge": edge, "step": step, "mb": mb,
                                    "gen": generation})
        return won

    def release_step(self, edge, step) -> None:
        with self._cond:
            for key in [k for k in self._slots if k[0] == edge
                        and k[1] == step]:
                del self._slots[key]

    # -- audit (tier-1 delivery tests) --------------------------------------

    def audit(self) -> dict:
        """Counters for the zero-dup/zero-loss audit: commit attempts per
        slot and claims per (slot, generation)."""
        with self._cond:
            return {
                "commits": {"/".join(map(str, k)): v
                            for k, v in self._commits.items()},
                "claims": {"/".join(map(str, k)): v
                           for k, v in self._claims.items()},
            }


class KVTransport(Transport):
    """The KV-store wire. Chunked puts sized under the client's 1 MiB
    read cap; meta is written LAST so its presence is the slot-complete
    signal; commit/claim counters give produce-once / claim-once.

    ``kv`` may be namespaced or raw — stages of one pipeline must share
    the SAME namespace view (the transport plane is cross-job state when
    stages run as separate scheduler jobs, so it lives under a pipeline
    prefix, not under either job's ``job/<id>/``).
    """

    def __init__(self, kv, *, prefix: str = "", chunk_bytes: int = 256 << 10,
                 claim_ttl: float = 600.0, slot_ttl: float | None = None,
                 poll_interval: float = 0.005):
        if chunk_bytes < 1 or chunk_bytes > (1 << 20) - 4096:
            raise ValueError(
                f"chunk_bytes {chunk_bytes} must fit the KV read cap (1MiB)")
        self.kv = kv
        self.prefix = prefix.rstrip("/") + "/" if prefix else ""
        self.chunk_bytes = chunk_bytes
        self.claim_ttl = claim_ttl
        self.slot_ttl = slot_ttl
        self.poll_interval = poll_interval
        self.stats = TransportStats()

    def _slot(self, edge: str, step: int, mb: int) -> str:
        return f"{self.prefix}{SLOT_PREFIX}/{edge}/{step}/{mb}"

    def _set(self, key: str, val: bytes) -> None:
        if self.slot_ttl is not None:
            self.kv.set_ttl(key, val, self.slot_ttl)
        else:
            self.kv.set(key, val)

    def put(self, edge, step, mb, arrays) -> bool:
        t0 = time.perf_counter()
        meta, views = pack_views(arrays)
        nbytes = sum(len(v) for v in views)
        slot = self._slot(edge, step, mb)
        first = self.kv.add(f"{slot}/commit", 1) == 1
        if not first and self.kv.try_get(f"{slot}/meta") is not None:
            return False  # complete slot: replay no-op
        # not first but incomplete: the claimant died mid-write — finish
        # its slot (deterministic replay writes the identical bytes).
        # Chunks stream straight off the array views (iter_chunks) — the
        # joined payload never exists on the put side.
        nchunks = 0
        for i, chunk in enumerate(iter_chunks(views, self.chunk_bytes)):
            self._set(f"{slot}/chunk/{i}", chunk)
            nchunks = i + 1
        meta = dict(meta, nchunks=nchunks, bytes=nbytes, seq=(step, mb))
        self._set(f"{slot}/meta", json.dumps(meta).encode())
        self.stats.puts += 1
        self.stats.bytes_out += nbytes
        self.stats.put_seconds += time.perf_counter() - t0
        _account(self.stats)
        get_recorder().instant(
            "slot:put", args={"edge": edge, "step": step, "mb": mb,
                              "bytes": nbytes, "first": first})
        return first

    def get(self, edge, step, mb, *, timeout: float = 60.0):
        """Chunk-pipelined read: chunks are written before the slot's
        meta, so the consumer fetches chunk ``i`` as soon as it appears
        and overlaps its reads with the producer's remaining writes —
        the wait for a slot "in flight" shrinks to the tail chunk plus
        meta instead of the whole staging pass."""
        t0 = time.perf_counter()
        t_mono = time.monotonic()
        slot = self._slot(edge, step, mb)
        deadline = t0 + timeout
        meta = None
        parts = []
        i = 0
        waited = 0.0
        while True:
            chunk = self.kv.try_get(f"{slot}/chunk/{i}")
            if chunk is not None:
                parts.append(chunk)
                i += 1
                continue
            if meta is None:
                raw = self.kv.try_get(f"{slot}/meta")
                if raw is not None:
                    meta = json.loads(raw)
                    # the producer may have landed chunk i AND the meta
                    # between our two probes — re-try the chunk before
                    # judging it missing
                    continue
            if meta is not None:
                if i >= meta["nchunks"]:
                    break
                # chunks land before meta, so a chunk probed AFTER the
                # meta was seen complete can only be missing if deleted
                raise RuntimeError(
                    f"slot {slot} chunk {i} missing under a complete meta "
                    "(released early, or TTL expired mid-read)")
            if time.perf_counter() >= deadline:
                raise TimeoutError(
                    f"transport slot {slot} never arrived ({timeout}s)")
            w0 = time.perf_counter()
            time.sleep(self.poll_interval)
            waited += time.perf_counter() - w0
        payload = parts[0] if len(parts) == 1 else b"".join(parts)
        if len(payload) != meta["bytes"]:
            raise RuntimeError(
                f"slot {slot}: reassembled {len(payload)} bytes, "
                f"meta says {meta['bytes']}")
        out = unpack_arrays(meta, payload)
        self.stats.gets += 1
        self.stats.bytes_in += len(payload)
        # sleeps waiting on the producer are the schedule's share; the
        # chunk fetches interleaved between them are the wire's
        self.stats.get_wait_seconds += waited
        self.stats.get_seconds += time.perf_counter() - t0 - waited
        _account(self.stats)
        get_recorder().complete(
            "slot:get", t_mono + waited,
            args={"edge": edge, "step": step, "mb": mb,
                  "bytes": len(payload), "tier": "kv"})
        return out

    def poll(self, edge, step, mb) -> bool:
        return self.kv.try_get(f"{self._slot(edge, step, mb)}/meta") is not None

    def claim(self, edge, step, mb, generation) -> bool:
        key = (f"{self.prefix}{CLAIM_PREFIX}/{generation}/{edge}/{step}/{mb}")
        n = self.kv.add(key, 1)
        if n == 1:
            # fetch-add created a plain counter; re-arm it as TTL'd so a
            # dead generation's claims expire (value no longer needs to
            # count past "claimed at least twice" for the audit)
            self.kv.set_ttl(key, str(n), self.claim_ttl)
            get_recorder().instant(
                "slot:claim", args={"edge": edge, "step": step, "mb": mb,
                                    "gen": generation})
        return n == 1

    def release_step(self, edge, step) -> None:
        self.kv.delete_prefix(f"{self.prefix}{SLOT_PREFIX}/{edge}/{step}/")

    # -- audit --------------------------------------------------------------

    def audit(self) -> dict:
        """Commit counters per live slot and claim counters per generation
        (released slots drop out of ``commits``; claims persist until
        their TTL, which is what the post-mortem audit reads)."""
        commits, claims = {}, {}
        for key in self.kv.keys(f"{self.prefix}{SLOT_PREFIX}/"):
            if key.endswith("/commit"):
                commits[key[len(self.prefix) + len(SLOT_PREFIX) + 1:
                            -len("/commit")]] = int(self.kv.get(key))
        for key in self.kv.keys(f"{self.prefix}{CLAIM_PREFIX}/"):
            raw = self.kv.try_get(key)
            if raw is not None:
                claims[key[len(self.prefix) + len(CLAIM_PREFIX) + 1:]] = (
                    int(raw))
        return {"commits": commits, "claims": claims}


class DeviceTransport(Transport):
    """The fast path for stages colocated in one process on separate
    meshes: ``put`` publishes the producer's device arrays as-is (no
    host staging on the data path — the consumer ``jax.device_put``-s
    them onto its own mesh), and a durable *journal* transport
    underneath records the identical slot bytes for recovery.

    Division of labour: the journal is authoritative for produce-once
    commits, claim-once consumption, and the post-mortem audit — this
    class adds only a device-buffer cache in front of it. The buffer is
    published before the journal write, so a consumer never waits on
    host staging; a ``get`` that finds no buffer (a transport rebuilt
    over a persistent journal after a driver crash) falls back to the
    journal's bytes, which deterministic replay guarantees are the bits
    the buffer held.
    """

    def __init__(self, journal: Transport | None = None):
        self.journal = LocalTransport() if journal is None else journal
        self._bufs: dict[tuple, list] = {}
        self._cond = threading.Condition()
        self.stats = TransportStats()

    @staticmethod
    def _nbytes(arrays) -> int:
        return sum(int(getattr(a, "nbytes", 0) or np.asarray(a).nbytes)
                   for a in arrays)

    def put(self, edge, step, mb, arrays) -> bool:
        t0 = time.perf_counter()
        arrays = list(arrays)
        key = (edge, step, mb)
        with self._cond:
            if key not in self._bufs:
                self._bufs[key] = arrays
                self._cond.notify_all()
        # the journal owns the produce-once verdict; a replayed put loses
        # the commit there and leaves the published buffer untouched
        first = self.journal.put(edge, step, mb, arrays)
        self.stats.puts += 1
        self.stats.bytes_out += self._nbytes(arrays)
        self.stats.put_seconds += time.perf_counter() - t0
        _account(self.stats)
        return first

    def get(self, edge, step, mb, *, timeout: float = 60.0):
        t0 = time.perf_counter()
        t_mono = time.monotonic()
        key = (edge, step, mb)
        deadline = t0 + timeout
        waited = 0.0
        with self._cond:
            while key not in self._bufs:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise TimeoutError(
                        f"transport slot {key} never arrived ({timeout}s)")
                if self.journal.poll(edge, step, mb):
                    break  # journal has it but no buffer: recovery read
                w0 = time.perf_counter()
                self._cond.wait(min(remaining, 0.01))
                waited += time.perf_counter() - w0
            arrays = self._bufs.get(key)
        if arrays is None:
            out = self.journal.get(
                edge, step, mb,
                timeout=max(0.001, deadline - time.perf_counter()))
            self.stats.journal_fallbacks += 1
            tier = "journal"
        else:
            out = list(arrays)
            self.stats.device_hits += 1
            tier = "device"
        nbytes = self._nbytes(out)
        self.stats.gets += 1
        self.stats.bytes_in += nbytes
        # same split as the staged tiers: blocked-on-producer time goes
        # to get_wait_seconds, get_seconds is the handoff itself
        self.stats.get_wait_seconds += waited
        self.stats.get_seconds += time.perf_counter() - t0 - waited
        _account(self.stats)
        get_recorder().complete(
            "slot:get", t_mono + waited,
            args={"edge": edge, "step": step, "mb": mb,
                  "bytes": nbytes, "tier": tier})
        return out

    def poll(self, edge, step, mb) -> bool:
        with self._cond:
            if (edge, step, mb) in self._bufs:
                return True
        return self.journal.poll(edge, step, mb)

    def claim(self, edge, step, mb, generation) -> bool:
        return self.journal.claim(edge, step, mb, generation)

    def release_step(self, edge, step) -> None:
        with self._cond:
            for key in [k for k in self._bufs if k[0] == edge
                        and k[1] == step]:
                del self._bufs[key]
        self.journal.release_step(edge, step)

    def audit(self) -> dict:
        return self.journal.audit()


@dataclass
class EdgeNames:
    """The two directed edges between adjacent stages s and s+1."""

    stage: int
    act: str = field(init=False)   # activations s -> s+1
    grad: str = field(init=False)  # cotangents  s+1 -> s

    def __post_init__(self):
        self.act = f"act{self.stage}"
        self.grad = f"grad{self.stage}"
