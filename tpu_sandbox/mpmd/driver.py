"""Stage workers and the in-process MPMD pipeline harness.

:class:`StageWorker` is the per-stage execution loop: it walks the
stage's 1F1B op list, pulling activations/cotangents off the transport
(claim-once), running the stage's compiled programs, shipping its own
outputs, and applying the stage-local optimizer once per step with the
descending-microbatch accumulation that keeps trained params bitwise
equal to the SPMD pipeline. The same loop body backs both deployment
shapes: :class:`MPMDPipeline` drives S workers on S single-device CPU
meshes with one thread per stage (the tier-1 twin), and
``mpmd/worker.py`` runs one worker per process under per-stage HostAgent
gangs with a :class:`~tpu_sandbox.mpmd.transport.KVTransport`.

Recovery model (the reason the transport is durable): a stage host that
dies mid-step is relaunched, restores params/opt from its own
single-writer :class:`~tpu_sandbox.train.checkpoint.HostCheckpoint`, and
replays from the checkpointed step + 1. Replay re-ships slots the dead
generation already produced (``put`` is an idempotent no-op on complete
slots) and re-consumes its inputs under a NEW claim generation, while
the surviving stages never rewind — the durable slots between the
checkpoint watermark and the frontier bridge the gap. Slots are only
garbage-collected (``release_step``) up to the minimum step every stage
has made durable, so a replayer always finds its inputs. Because every
F/B is a pure function of shipped values, the replayed lineage lands
bitwise on the unfaulted run's parameters.
"""

from __future__ import annotations

import threading
import time

import jax
import numpy as np

from tpu_sandbox.mpmd.program import (
    StageProgram,
    accumulate_descending,
    merge_stage_params,
    stage_params,
)
from tpu_sandbox.mpmd.schedule import bubble_fraction, ops_for
from tpu_sandbox.mpmd.transport import EdgeNames, LocalTransport
from tpu_sandbox.obs.metrics import get_registry
from tpu_sandbox.obs.record import get_recorder
from tpu_sandbox.train.checkpoint import HostCheckpoint


class StageKilled(RuntimeError):
    """In-process stand-in for a stage-host crash: raised by the
    ``fail_at`` hook mid-step, leaving half-shipped slots and an
    un-applied optimizer step behind — exactly the state a kill_agent
    fault leaves on the KV store in the process-level path."""


class StageWorker:
    """Executes one stage's schedule against a transport.

    ``generation`` is the claim-once namespace: a relaunched worker for
    the same stage MUST carry a higher generation so its replay can
    re-consume slots the dead lineage already claimed.
    """

    def __init__(self, program: StageProgram, params, opt_state, transport,
                 *, generation: int = 0, checkpoint: HostCheckpoint | None
                 = None, get_timeout: float = 60.0, kind: str = "1f1b"):
        self.program = program
        self.transport = transport
        self.generation = generation
        self.checkpoint = checkpoint
        self.get_timeout = get_timeout
        self.kind = kind
        self.params = program.place(params)
        self.opt_state = (program.init_opt_state(self.params)
                          if opt_state is None else program.place(opt_state))
        # host-side restore template (checkpoints are structure-checked)
        self._template = {
            "params": jax.tree.map(np.asarray, params),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
        }
        self.ops = ops_for(kind, program.stage, program.n_stages,
                           program.microbatches)
        s = program.stage
        self.act_in = EdgeNames(s - 1).act if not program.is_first else None
        self.act_out = EdgeNames(s).act if not program.is_last else None
        self.grad_in = EdgeNames(s).grad if not program.is_last else None
        self.grad_out = EdgeNames(s - 1).grad if not program.is_first else None
        self.next_step = 0
        self.losses: dict[int, float] = {}
        self.step_seconds: dict[int, float] = {}
        #: op -> list of measured compute seconds, one entry per executed
        #: op — the same durations the "stage:op" spans carry, kept
        #: in-memory so schedule.autotune_plan can read them without a
        #: trace round-trip
        self.op_seconds: dict[str, list[float]] = {}
        #: step -> measured bubble fraction (1 - compute/wall); the same
        #: number is published online as the ``mpmd.bubble_fraction``
        #: gauge and derivable offline from the stage:op/stage:step spans
        self.bubble_by_step: dict[int, float] = {}
        self.applied_steps: list[int] = []
        #: (step, op_index) at which to raise StageKilled — fault hook
        self.fail_at: tuple[int, int] | None = None
        #: optional callback run at every op boundary ``(step, op_index)``
        #: — the process worker hangs its fault-plan trigger and agent
        #: mailbox poll here, so agent faults land MID-shipment
        self.on_op = None

    # -- fault hook ----------------------------------------------------------

    def _maybe_fail(self, step: int, op_index: int) -> None:
        if self.fail_at is not None and self.fail_at == (step, op_index):
            self.fail_at = None
            raise StageKilled(
                f"stage {self.program.stage} killed at step {step} "
                f"op {op_index}")

    def _consume(self, edge: str, step: int, mb: int) -> None:
        if not self.transport.claim(edge, step, mb,
                                    generation=self.generation):
            raise RuntimeError(
                f"duplicate delivery: stage {self.program.stage} "
                f"generation {self.generation} already consumed "
                f"{edge}/{step}/{mb}")

    # -- one optimizer step --------------------------------------------------

    def run_step(self, step: int, *, tokens=None, targets=None) -> None:
        prog, tr = self.program, self.transport
        M = prog.microbatches
        if prog.is_first:
            if tokens is None:
                raise ValueError("stage 0 needs the token batch")
            tokens_mb = np.asarray(tokens).reshape(
                M, -1, np.shape(tokens)[-1])
        if prog.is_last:
            if targets is None:
                raise ValueError("last stage needs the target batch")
            targets_mb = np.asarray(targets).reshape(
                M, -1, np.shape(targets)[-1])
        stash: dict[int, object] = {}
        per_mb: dict[int, object] = {}
        loss = np.float32(0.0)
        # bubble accounting: "stage:wait" spans bracket the blocking
        # transport gets, "stage:op" spans bracket stage compute, and the
        # closing "stage:step" span carries the measured bubble — all
        # constant span names (GL-O403) with stage/step/mb riding args
        rec = get_recorder()
        s = prog.stage
        zb = self.kind == "zb_h1"
        compute_s = 0.0
        t0 = time.perf_counter()
        t_step = time.monotonic()

        def timed(op_name, mb, fn, *fn_args):
            # block_until_ready inside the timer: async dispatch would
            # otherwise book the compute under whatever forces it next,
            # and these durations feed schedule.autotune_plan
            nonlocal compute_s
            t_op = time.monotonic()
            out = jax.block_until_ready(fn(self.params, *fn_args))
            dt = time.monotonic() - t_op
            compute_s += dt
            self.op_seconds.setdefault(op_name, []).append(dt)
            rec.complete("stage:op", t_op,
                         args={"stage": s, "step": step,
                               "op": op_name, "mb": mb})
            return out

        def waited(edge, mb, op_name):
            t_wait = time.monotonic()
            self._consume(edge, step, mb)
            (v,) = tr.get(edge, step, mb, timeout=self.get_timeout)
            rec.complete("stage:wait", t_wait,
                         args={"stage": s, "step": step,
                               "op": op_name, "mb": mb})
            return prog.place(v)

        for idx, (op, m) in enumerate(self.ops):
            self._maybe_fail(step, idx)
            if self.on_op is not None:
                self.on_op(step, idx)
            if op == "F":
                if prog.is_first:
                    x = prog.place(np.asarray(tokens_mb[m]))
                else:
                    x = waited(self.act_in, m, "F")
                stash[m] = x
                if not prog.is_last:
                    h_out = timed("F", m, prog.fwd, x)
                    tr.put(self.act_out, step, m, [h_out])
            elif op == "B":
                if prog.is_last:
                    x = stash[m] if zb else stash.pop(m)
                    tg = prog.place(np.asarray(targets_mb[m]))
                    if zb:
                        lv, gh, st = timed("B", m, prog.loss_bwd_input, x, tg)
                        stash[m] = (tg, st)  # W: pure weight grads
                    else:
                        lv, gp, gh = timed("B", m, prog.loss_grad, x, tg)
                    # ship the upstream cotangent before anything else:
                    # the previous stage is waiting on it
                    tr.put(self.grad_out, step, m, [gh])
                    loss = loss + np.float32(lv)
                    if not zb:
                        per_mb[m] = jax.tree.map(np.asarray, gp)
                elif zb:
                    g = waited(self.grad_in, m, "B")
                    x = stash[m]
                    if not prog.is_first:
                        gx, st = timed("B", m, prog.bwd_input, x, g)
                        tr.put(self.grad_out, step, m, [gx])
                        stash[m] = st  # per-layer pairs, W is chain-free
                    else:
                        # stage 0's chain rides inside W (nothing
                        # upstream consumes its grad-input)
                        stash[m] = (x, g)
                else:
                    g = waited(self.grad_in, m, "B")
                    gp, gx = timed("B", m, prog.bwd, stash.pop(m), g)
                    if not prog.is_first:
                        tr.put(self.grad_out, step, m, [gx])
                    per_mb[m] = jax.tree.map(np.asarray, gp)
            else:  # "W": the deferred grad-weight pass (ZB-H1 only)
                if prog.is_last:
                    tg, st = stash.pop(m)
                    gp = timed("W", m, prog.loss_bwd_weight, tg, st)
                elif prog.is_first:
                    x, g = stash.pop(m)
                    gp = timed("W", m, prog.bwd_weight_chain, x, g)
                else:
                    gp = timed("W", m, prog.bwd_weight, stash.pop(m))
                per_mb[m] = jax.tree.map(np.asarray, gp)
        grads = accumulate_descending(per_mb)
        self.params, self.opt_state = timed(
            "A", -1, prog.apply_grads, self.opt_state, prog.place(grads))
        wall = time.perf_counter() - t0
        self.step_seconds[step] = wall
        bubble = max(0.0, 1.0 - compute_s / wall) if wall > 0 else 0.0
        self.bubble_by_step[step] = bubble
        get_registry().gauge("mpmd.bubble_fraction",
                             labels={"stage": str(s)}).set(round(bubble, 6))
        rec.complete("stage:step", t_step,
                     args={"stage": s, "step": step,
                           "bubble": round(bubble, 6)})
        if prog.is_last:
            self.losses[step] = float(loss)
        self.applied_steps.append(step)
        self.next_step = step + 1

    # -- durability ----------------------------------------------------------

    def host_state(self) -> dict:
        return {
            "params": jax.tree.map(np.asarray, self.params),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
        }

    def save_checkpoint(self, step: int) -> None:
        if self.checkpoint is not None:
            self.checkpoint.save(self.host_state(), step, epoch=0, offset=0)

    def restore_checkpoint(self) -> int | None:
        """Restore params/opt from the newest valid checkpoint; returns
        the restored step (``next_step`` becomes step + 1) or ``None``
        for a fresh start (``next_step`` 0)."""
        if self.checkpoint is None:
            return None
        out = self.checkpoint.restore(self._template)
        if out is None:
            self.next_step = 0
            return None
        state, meta = out
        self.params = self.program.place(state["params"])
        self.opt_state = self.program.place(state["opt_state"])
        self.next_step = int(meta["step"]) + 1
        return int(meta["step"])


class MPMDPipeline:
    """In-process MPMD harness: S stage workers, one per single-device
    CPU mesh, one thread each, over a shared transport.

    This is the tier-1 twin of the multi-process deployment: the same
    StageWorker loop, the same transport contract, the same recovery
    path — minus processes, agents and the scheduler. ``train`` runs
    the leader loop: launch stage threads, advance the release
    watermark (GC slots every stage has made durable), and — with
    ``recover=True`` — relaunch any stage that dies with
    :class:`StageKilled` from its checkpoint under a new claim
    generation.
    """

    def __init__(self, config, tx, *, n_stages: int = 2,
                 microbatches: int = 4, transport=None, devices=None,
                 ckpt_root=None, get_timeout: float = 60.0,
                 kind: str = "1f1b", layer_split=None):
        self.config = config
        self.tx = tx
        self.n_stages = n_stages
        self.microbatches = microbatches
        self.kind = kind
        self.layer_split = layer_split
        self.transport = LocalTransport() if transport is None else transport
        if devices is None:
            devs = jax.devices()
            devices = [devs[s % len(devs)] for s in range(n_stages)]
        self.devices = devices
        self.programs = [
            StageProgram(config, tx, s, n_stages, microbatches,
                         device=devices[s], layer_split=layer_split)
            for s in range(n_stages)
        ]
        self.ckpt_root = ckpt_root
        self.get_timeout = get_timeout
        self.workers: list[StageWorker] = []
        self._generations = [0] * n_stages
        self._released_through = -1

    # -- construction --------------------------------------------------------

    def _checkpoint_for(self, stage: int) -> HostCheckpoint | None:
        if self.ckpt_root is None:
            return None
        return HostCheckpoint(f"{self.ckpt_root}/stage-{stage}")

    def init_from_flat(self, flat_params: dict) -> None:
        """Build the stage workers from a full TransformerLM param tree
        (e.g. ``PipelineParallel.merged_params`` of the same init — the
        parity tests seed both engines identically this way)."""
        self.workers = [
            StageWorker(self.programs[s],
                        stage_params(flat_params, s, self.n_stages,
                                     layer_split=self.layer_split),
                        None, self.transport,
                        checkpoint=self._checkpoint_for(s),
                        get_timeout=self.get_timeout, kind=self.kind)
            for s in range(self.n_stages)
        ]

    def init(self, rng, sample_tokens) -> None:
        from tpu_sandbox.models.transformer import TransformerLM
        flat = TransformerLM(self.config).init(rng, sample_tokens)["params"]
        self.init_from_flat(jax.tree.map(np.asarray, flat))

    # -- recovery ------------------------------------------------------------

    def respawn_stage(self, stage: int) -> StageWorker:
        """Relaunch a dead stage: fresh worker, params restored from the
        stage's own checkpoint, claim generation bumped so replay can
        re-consume already-claimed slots."""
        old = self.workers[stage]
        self._generations[stage] += 1
        worker = StageWorker(
            old.program, old._template["params"],
            old._template["opt_state"], self.transport,
            generation=self._generations[stage],
            checkpoint=old.checkpoint, get_timeout=self.get_timeout,
            kind=old.kind)
        worker.restore_checkpoint()
        # carry the audit trail across the relaunch
        worker.applied_steps = list(old.applied_steps)
        worker.losses = dict(old.losses)
        worker.step_seconds = dict(old.step_seconds)
        self.workers[stage] = worker
        return worker

    # -- leader loop ---------------------------------------------------------

    def _stage_loop(self, stage: int, steps: int, tokens, targets,
                    done: list[int], errors: dict) -> None:
        worker = self.workers[stage]
        try:
            for step in range(worker.next_step, steps):
                worker.run_step(
                    step,
                    tokens=tokens if worker.program.is_first else None,
                    targets=targets if worker.program.is_last else None)
                worker.save_checkpoint(step)
                done[stage] = step
        except BaseException as e:  # noqa: BLE001 — reported to the leader
            errors[stage] = e

    def release_through(self, step: int) -> None:
        """GC every edge's slots up to ``step`` inclusive (leader calls
        this only once ALL stages have checkpointed past ``step`` — a
        replayer never rewinds below its own checkpoint, so these slots
        can no longer be re-read)."""
        for s in range(self._released_through + 1, step + 1):
            for edge in ([EdgeNames(i).act for i in range(self.n_stages - 1)]
                         + [EdgeNames(i).grad
                            for i in range(self.n_stages - 1)]):
                self.transport.release_step(edge, s)
        self._released_through = max(self._released_through, step)

    def train(self, steps: int, tokens, targets, *, recover: bool = False,
              release: bool = True) -> list[float]:
        """Run the pipeline to ``steps`` optimizer steps on a fixed
        batch; returns the per-step losses. With ``recover=True``,
        stages dying with StageKilled are respawned from checkpoint and
        the run continues to the same end state."""
        if not self.workers:
            raise RuntimeError("call init()/init_from_flat() first")
        done = [w.next_step - 1 for w in self.workers]
        errors: dict[int, BaseException] = {}

        def launch(stage: int) -> threading.Thread:
            t = threading.Thread(
                target=self._stage_loop,
                args=(stage, steps, tokens, targets, done, errors),
                name=f"mpmd-stage-{stage}", daemon=True)
            t.start()
            return t

        threads = {s: launch(s) for s in range(self.n_stages)}
        while threads:
            if release and self.ckpt_root is not None:
                watermark = min(done)
                if watermark > self._released_through:
                    self.release_through(watermark)
            for stage in list(threads):
                threads[stage].join(timeout=0.01)
                if threads[stage].is_alive():
                    continue
                del threads[stage]
                err = errors.pop(stage, None)
                if err is None:
                    continue
                if recover and isinstance(err, StageKilled):
                    worker = self.respawn_stage(stage)
                    done[stage] = worker.next_step - 1
                    threads[stage] = launch(stage)
                else:
                    # surviving threads exit via their get() timeouts
                    raise err
        if errors:
            raise next(iter(errors.values()))
        if release:
            self.release_through(steps - 1)
        last = self.workers[-1]
        return [last.losses[s] for s in sorted(last.losses)]

    # -- results / metrics ---------------------------------------------------

    def merged_params(self) -> dict:
        return merge_stage_params([
            jax.tree.map(np.asarray, w.params) for w in self.workers])

    def bubble_fraction(self) -> float:
        return bubble_fraction(self.n_stages, self.microbatches)

    def stage_step_seconds(self) -> list[dict[int, float]]:
        return [dict(w.step_seconds) for w in self.workers]

    def measured_op_costs(self) -> dict[int, dict[str, float]]:
        """Median measured compute seconds per (stage, op) — the input
        :func:`~tpu_sandbox.mpmd.schedule.autotune_plan` expects. Fused
        runs report F/B/A; ZB runs additionally report W."""
        out: dict[int, dict[str, float]] = {}
        for s, w in enumerate(self.workers):
            out[s] = {op: float(np.median(ts))
                      for op, ts in w.op_seconds.items() if ts}
        return out
