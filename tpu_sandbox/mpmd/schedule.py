"""Leader-driven global schedule: 1F1B op lists per stage.

The leader computes every stage's op list once and publishes it (KV for
the distributed path, direct handoff in-process); stages execute their
list mechanically — all cross-stage coordination is the transport's
blocking slot waits, so the schedule needs no per-tick control messages.

1F1B: stage ``i`` runs ``min(M, S - 1 - i)`` warmup forwards, then
alternates F/B until forwards are spent, then drains backwards. Same
bubble as GPipe — ``(S-1)/(M+S-1)`` — but in-flight activations are
bounded by S instead of M, which is what lets a stage stash at most
``S - i`` microbatch inputs regardless of M.

Values are schedule-independent: every F/B is a pure program on shipped
inputs, so any topological order of the dependency dag gives bitwise
identical grads. 1F1B is about memory and bubble, not numerics — which
is also why the recovery path may replay a step with a plain
F*-then-B* order and still land bitwise on the unfaulted state.
"""

from __future__ import annotations

import json


def one_f_one_b(stage: int, n_stages: int,
                microbatches: int) -> list[tuple[str, int]]:
    """The stage's op list: [("F", mb) | ("B", mb), ...]."""
    if not 0 <= stage < n_stages:
        raise ValueError(f"stage {stage} not in [0, {n_stages})")
    if microbatches < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")
    warmup = min(microbatches, n_stages - 1 - stage)
    ops: list[tuple[str, int]] = [("F", m) for m in range(warmup)]
    nf, nb = warmup, 0
    while nb < microbatches:
        if nf < microbatches:
            ops.append(("F", nf))
            nf += 1
        ops.append(("B", nb))
        nb += 1
    return ops


def max_in_flight(ops: list[tuple[str, int]]) -> int:
    """Peak number of microbatches forwarded but not yet backwarded —
    the stage's activation-stash bound (S - stage for 1F1B)."""
    live = peak = 0
    for op, _ in ops:
        live += 1 if op == "F" else -1
        peak = max(peak, live)
    return peak


def bubble_fraction(n_stages: int, microbatches: int) -> float:
    """(S-1)/(M+S-1): idle fraction of the synchronous schedule; same
    formula as ``PipelineParallel.bubble_fraction`` at v=1."""
    return (n_stages - 1) / (microbatches + n_stages - 1)


# -- leader publication (distributed path) ----------------------------------

def plan_key(prefix: str) -> str:
    return f"{prefix}/plan" if prefix else "mpmd/plan"


def publish_plan(kv, *, n_stages: int, microbatches: int, steps: int,
                 seed: int, prefix: str = "mpmd",
                 extra: dict | None = None) -> dict:
    """The leader's one-shot schedule publication: each stage reads its
    own op list and the run geometry from a single durable key, so a
    relaunched stage host rejoins the SAME global schedule (the plan,
    like the queue, outlives any process). ``extra`` rides along for
    run config the stages must agree on (model, optimizer, batch)."""
    plan = {
        "n_stages": n_stages,
        "microbatches": microbatches,
        "steps": steps,
        "seed": seed,
        "ops": {str(s): one_f_one_b(s, n_stages, microbatches)
                for s in range(n_stages)},
    }
    plan.update(extra or {})
    kv.set(plan_key(prefix), json.dumps(plan))
    return plan


def fetch_plan(kv, *, prefix: str = "mpmd", timeout: float = 60.0) -> dict:
    import time
    deadline = time.monotonic() + timeout
    raw = kv.try_get(plan_key(prefix))
    while raw is None:
        if time.monotonic() >= deadline:
            raise TimeoutError("no schedule plan published")
        time.sleep(0.01)
        raw = kv.try_get(plan_key(prefix))
    plan = json.loads(raw)
    plan["ops"] = {int(k): [tuple(op) for op in v]
                   for k, v in plan["ops"].items()}
    return plan
