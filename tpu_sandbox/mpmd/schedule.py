"""Leader-driven global schedules: 1F1B and ZB-H1 op lists per stage,
plus measurement-driven plan selection.

The leader computes every stage's op list once and publishes it (KV for
the distributed path, direct handoff in-process); stages execute their
list mechanically — all cross-stage coordination is the transport's
blocking slot waits, so the schedule needs no per-tick control messages.

1F1B: stage ``i`` runs ``min(M, S - 1 - i)`` warmup forwards, then
alternates F/B until forwards are spent, then drains backwards. Same
bubble as GPipe — ``(S-1)/(M+S-1)`` — but in-flight activations are
bounded by S instead of M, which is what lets a stage stash at most
``S - i`` microbatch inputs regardless of M.

ZB-H1 (arxiv 2401.10241, the memory-neutral variant): the backward is
split into B (grad-input — the upstream cotangent, all the downstream
stage is waiting for) and W (grad-weight — nobody waits for it until
the optimizer). Each stage holds ``min(M, S-1-stage)`` W passes in
reserve through the steady phase and spends one after each drain-phase
B, so the tail bubble of 1F1B — idle waits between late cotangents —
is filled with weight-grad work instead. Same activation stash bound
as 1F1B; the extra state is the per-reserved-W (input, cotangent)
pair.

Values are schedule-independent: every F/B/W is a pure program on
shipped inputs, so any topological order of the dependency dag gives
bitwise identical grads *for a fixed set of programs*. Reordering is
free; recompiling is not — the ZB split's per-layer vjps agree with
the fused backward only to float32 ulps (XLA groups reductions
differently across compilation units), so parity across schedule
KINDS is held at tolerance (1e-6 losses) while replay after a fault,
which re-runs the same programs in a different interleaving, still
lands bitwise on the unfaulted state.

``autotune_plan`` closes the measurement loop: the per-stage ``stage:op``
timings the driver records (the same numbers the flight-recorder spans
carry) feed a small dependency-exact simulator, and the plan — schedule
kind × microbatch count — with the best predicted step time wins.
"""

from __future__ import annotations

import json


def one_f_one_b(stage: int, n_stages: int,
                microbatches: int) -> list[tuple[str, int]]:
    """The stage's op list: [("F", mb) | ("B", mb), ...]."""
    if not 0 <= stage < n_stages:
        raise ValueError(f"stage {stage} not in [0, {n_stages})")
    if microbatches < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")
    warmup = min(microbatches, n_stages - 1 - stage)
    ops: list[tuple[str, int]] = [("F", m) for m in range(warmup)]
    nf, nb = warmup, 0
    while nb < microbatches:
        if nf < microbatches:
            ops.append(("F", nf))
            nf += 1
        ops.append(("B", nb))
        nb += 1
    return ops


def zb_h1(stage: int, n_stages: int,
          microbatches: int) -> list[tuple[str, int]]:
    """The stage's ZB-H1 op list: [("F", m) | ("B", m) | ("W", m), ...].

    B is grad-input only (ships the cotangent upstream), W is
    grad-weight. ``min(M, S-1-stage)`` W passes are deferred into the
    drain phase — one after each drain B, filling the wait for the next
    cotangent — and any excess W runs in the steady phase so the
    deferred-state bound matches 1F1B's stash bound."""
    if not 0 <= stage < n_stages:
        raise ValueError(f"stage {stage} not in [0, {n_stages})")
    if microbatches < 1:
        raise ValueError(f"microbatches must be >= 1, got {microbatches}")
    warmup = min(microbatches, n_stages - 1 - stage)
    ops: list[tuple[str, int]] = [("F", m) for m in range(warmup)]
    nf, nb = warmup, 0
    pending: list[int] = []
    while nb < microbatches:
        if nf < microbatches:
            ops.append(("F", nf))
            nf += 1
        ops.append(("B", nb))
        pending.append(nb)
        nb += 1
        if nf < microbatches:
            # steady: keep `warmup` weight passes in reserve for the
            # drain; run the excess now (bounds deferred state)
            while len(pending) > warmup:
                ops.append(("W", pending.pop(0)))
        elif pending:
            # drain: one reserved W after each B fills the gap while
            # the next cotangent is still in flight downstream
            ops.append(("W", pending.pop(0)))
    while pending:
        ops.append(("W", pending.pop(0)))
    return ops


SCHEDULE_KINDS = ("1f1b", "zb_h1")


def ops_for(kind: str, stage: int, n_stages: int,
            microbatches: int) -> list[tuple[str, int]]:
    if kind == "1f1b":
        return one_f_one_b(stage, n_stages, microbatches)
    if kind == "zb_h1":
        return zb_h1(stage, n_stages, microbatches)
    raise ValueError(f"unknown schedule kind {kind!r} "
                     f"(have {SCHEDULE_KINDS})")


def max_in_flight(ops: list[tuple[str, int]]) -> int:
    """Peak number of microbatches forwarded but not yet released —
    the stage's activation-stash bound (S - stage for 1F1B). Under a
    split backward the stash is held through B and released at W."""
    has_w = {m for op, m in ops if op == "W"}
    live = peak = 0
    for op, m in ops:
        if op == "F":
            live += 1
        elif (op == "W") or (op == "B" and m not in has_w):
            live -= 1
        peak = max(peak, live)
    return peak


def bubble_fraction(n_stages: int, microbatches: int) -> float:
    """(S-1)/(M+S-1): idle fraction of the synchronous schedule; same
    formula as ``PipelineParallel.bubble_fraction`` at v=1."""
    return (n_stages - 1) / (microbatches + n_stages - 1)


# -- measured schedules ------------------------------------------------------

def simulate_step(op_lists: dict[int, list[tuple[str, int]]],
                  op_costs: dict[int, dict[str, float]], *,
                  ship_s: float = 0.0) -> dict:
    """Dependency-exact step simulation: each stage executes its op list
    sequentially; F_m@s waits on F_m@(s-1), B_m@s waits on B_m@(s+1)
    (plus ``ship_s`` wire latency per hop), W is stage-local. Returns
    the predicted makespan and per-stage busy/bubble — the same
    ``1 - compute/wall`` gauge the driver measures online.

    ``op_costs[stage]`` maps op -> seconds, with "B" the grad-input
    cost, "W" grad-weight, and "A" the once-per-step optimizer apply.
    For fused-backward (1F1B) lists pass the fused cost as "B".
    """
    n_stages = len(op_lists)
    t = {s: 0.0 for s in range(n_stages)}
    busy = {s: 0.0 for s in range(n_stages)}
    fin: dict[tuple, float] = {}
    idx = {s: 0 for s in range(n_stages)}
    remaining = sum(len(v) for v in op_lists.values())
    while remaining:
        progressed = False
        for s in range(n_stages):
            ops = op_lists[s]
            while idx[s] < len(ops):
                op, m = ops[idx[s]]
                if op == "F" and s > 0:
                    ready = fin.get(("F", s - 1, m))
                    if ready is None:
                        break
                    ready += ship_s
                elif op == "B" and s < n_stages - 1:
                    ready = fin.get(("B", s + 1, m))
                    if ready is None:
                        break
                    ready += ship_s
                else:
                    ready = 0.0  # W, stage-0 F, last-stage B: no wait
                dur = float(op_costs[s].get(op, 0.0))
                t[s] = max(t[s], ready) + dur
                fin[(op, s, m)] = t[s]
                busy[s] += dur
                idx[s] += 1
                remaining -= 1
                progressed = True
        if not progressed:
            raise RuntimeError("op lists deadlock: unsatisfiable dependency")
    for s in range(n_stages):
        a = float(op_costs[s].get("A", 0.0))
        t[s] += a
        busy[s] += a
    makespan = max(t.values())
    bubbles = {s: (1.0 - busy[s] / makespan) if makespan > 0 else 0.0
               for s in range(n_stages)}
    return {
        "step_seconds": makespan,
        "busy_seconds": busy,
        "bubble_by_stage": bubbles,
        "bubble_mean": sum(bubbles.values()) / n_stages,
        "bubble_max": max(bubbles.values()),
    }


def autotune_plan(op_costs: dict[int, dict[str, float]], *, n_stages: int,
                  measured_microbatches: int,
                  candidates=(2, 4, 8, 16),
                  kinds=SCHEDULE_KINDS, ship_s: float = 0.0) -> dict:
    """Pick (schedule kind, microbatch count) from measured per-stage op
    timings. ``op_costs`` is per-op seconds at ``measured_microbatches``
    (e.g. the driver's recorded ``stage:op`` medians); candidate M
    rescales them by ``measured_microbatches / M`` — per-op work is
    linear in microbatch size at fixed global batch. Returns the winning
    plan plus every candidate's prediction, so the bench receipt shows
    the whole frontier, not just the argmin."""
    if not candidates:
        raise ValueError("no microbatch candidates")
    rows = []
    for kind in kinds:
        for m_count in candidates:
            scale = measured_microbatches / m_count
            costs = {}
            for s in range(n_stages):
                c = {k: float(v) * scale for k, v in op_costs[s].items()
                     if k != "A"}
                if kind == "1f1b":
                    # fused backward: one op paying both halves
                    c["B"] = c.get("B", 0.0) + c.get("W", 0.0)
                    c.pop("W", None)
                c["A"] = float(op_costs[s].get("A", 0.0))
                costs[s] = c
            ops = {s: ops_for(kind, s, n_stages, m_count)
                   for s in range(n_stages)}
            sim = simulate_step(ops, costs, ship_s=ship_s)
            rows.append({"kind": kind, "microbatches": m_count,
                         "predicted_step_s": round(sim["step_seconds"], 6),
                         "predicted_bubble": round(sim["bubble_mean"], 6)})
    best = min(rows, key=lambda r: (r["predicted_step_s"],
                                    r["predicted_bubble"]))
    return {"kind": best["kind"], "microbatches": best["microbatches"],
            "predicted": best, "candidates": rows}


# -- leader publication (distributed path) ----------------------------------

def plan_key(prefix: str) -> str:
    return f"{prefix}/plan" if prefix else "mpmd/plan"


def publish_plan(kv, *, n_stages: int, microbatches: int, steps: int,
                 seed: int, prefix: str = "mpmd", kind: str = "1f1b",
                 layer_split=None, extra: dict | None = None) -> dict:
    """The leader's one-shot schedule publication: each stage reads its
    own op list and the run geometry from a single durable key, so a
    relaunched stage host rejoins the SAME global schedule (the plan,
    like the queue, outlives any process). ``kind`` picks the schedule
    family, ``layer_split`` the (possibly uneven) per-stage layer
    counts; ``extra`` rides along for run config the stages must agree
    on (model, optimizer, batch)."""
    plan = {
        "n_stages": n_stages,
        "microbatches": microbatches,
        "steps": steps,
        "seed": seed,
        "kind": kind,
        "layer_split": list(layer_split) if layer_split else None,
        "ops": {str(s): ops_for(kind, s, n_stages, microbatches)
                for s in range(n_stages)},
    }
    plan.update(extra or {})
    kv.set(plan_key(prefix), json.dumps(plan))
    return plan


def fetch_plan(kv, *, prefix: str = "mpmd", timeout: float = 60.0) -> dict:
    import time
    deadline = time.monotonic() + timeout
    raw = kv.try_get(plan_key(prefix))
    while raw is None:
        if time.monotonic() >= deadline:
            raise TimeoutError("no schedule plan published")
        time.sleep(0.01)
        raw = kv.try_get(plan_key(prefix))
    plan = json.loads(raw)
    plan["ops"] = {int(k): [tuple(op) for op in v]
                   for k, v in plan["ops"].items()}
    plan.setdefault("kind", "1f1b")
    plan.setdefault("layer_split", None)
    return plan
