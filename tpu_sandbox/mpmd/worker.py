"""Process entry for one MPMD stage gang: ``python -m tpu_sandbox.mpmd.worker``.

Each pipeline stage is its own scheduler job (a co-gang member, see
``JobSpec.cogroup``): the scheduler spawns this module once per stage
with the standard agent argv placeholders, and the stages find each
other purely through the shared KV store —

- the stage-0 worker is the LEADER: it publishes the 1F1B plan (plus the
  model/optimizer/batch config every stage must agree on) to
  ``mpmd/<pipeline>/plan`` on the RAW store, and advances the slot-GC
  watermark as stages publish their checkpoint progress;
- every stage fetches the plan, derives the SAME full-model init from
  the plan seed (deterministic on CPU — no init shipping), slices its
  own stage subtree, and runs the :class:`StageWorker` loop over a
  :class:`KVTransport` rooted at ``mpmd/<pipeline>/``.

The transport prefix lives OUTSIDE the per-job namespaces on purpose:
the scheduler sweeps ``job/<id>/`` when each stage job finishes, and
cross-stage slots must outlive any single stage's job record.

Faults: the fault plan (env) fires at the MIDDLE of the step's op list —
half the step's slots shipped, the rest unproduced — and agent-targeted
actions (kill_agent / partition_host) are consumed from this agent's own
mailbox at every op boundary, so the death lands mid-shipment. A killed
worker exits nonzero; the scheduler's ``_respawn_dead_agents`` relaunches
the same argv, and the relaunch restores from its per-stage
HostCheckpoint, bumps the claim generation (``mpmd/<pipeline>/gen/<s>``),
and replays into the durable slots.

On completion each stage ships its final params over the transport
(edge ``final``) and posts its job verdict; the last stage also
publishes the per-step losses. The integration test asserts the merged
final params are bitwise identical to the unfaulted in-process run.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


def _build_tx(spec: dict):
    import optax

    name = spec.get("name", "sgd")
    lr = spec.get("lr", 0.1)
    if name == "sgd":
        return optax.sgd(lr)
    if name == "adam":
        return optax.adam(lr)
    raise ValueError(f"unknown optimizer {name!r}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("agent_id", type=int)
    p.add_argument("kv_port", type=int)
    p.add_argument("job_id")
    p.add_argument("--stage", type=int, required=True)
    p.add_argument("--pipeline", default="pipe0",
                   help="shared transport namespace: mpmd/<pipeline>/")
    p.add_argument("--ckpt-root", required=True)
    p.add_argument("--steps", type=int, default=0)
    p.add_argument("--n-stages", type=int, default=0)
    p.add_argument("--microbatches", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--schedule-kind", default="1f1b",
                   help="schedule family: 1f1b | zb_h1 (leader only)")
    p.add_argument("--layer-split", default="",
                   help="json list of per-stage layer counts for uneven "
                   "pipelines (leader only; others read the plan)")
    p.add_argument("--model", default="", help="TransformerConfig kwargs "
                   "json (leader only; others read the plan)")
    p.add_argument("--optimizer", default="",
                   help='{"name": "sgd"|"adam", "lr": ...} json')
    p.add_argument("--batch", default="", help="[batch, seqlen] json")
    p.add_argument("--get-timeout", type=float, default=120.0)
    args = p.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import jax
    import numpy as np

    # every stage derives the full-model init from the plan seed instead of
    # shipping it — that only works if all processes agree on the PRNG
    # implementation bit-for-bit, so pin it rather than inherit whatever
    # default the launching environment's jax happens to have
    jax.config.update("jax_threefry_partitionable", True)

    from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
    from tpu_sandbox.mpmd.driver import StageWorker
    from tpu_sandbox.mpmd.program import StageProgram, stage_params
    from tpu_sandbox.mpmd.schedule import fetch_plan, publish_plan
    from tpu_sandbox.mpmd.transport import EdgeNames, KVTransport
    from tpu_sandbox.runtime.faults import (
        FaultInjector,
        FaultPlan,
        agent_cmd_key,
    )
    from tpu_sandbox.runtime.kvstore import KVClient, for_job
    from tpu_sandbox.train.checkpoint import HostCheckpoint

    kv = KVClient(port=args.kv_port)
    jobkv = for_job(kv, args.job_id)
    prefix = f"mpmd/{args.pipeline}"
    stage = args.stage

    # -- heartbeat (pausable: partition_host silences it) --------------------
    partitioned = threading.Event()
    hb_stop = threading.Event()

    def beat():
        while not hb_stop.is_set():
            if not partitioned.is_set():
                jobkv.set_ttl(f"agent_hb/{args.agent_id}",
                              repr(time.time()), 5.0)
            hb_stop.wait(1.0)

    threading.Thread(target=beat, daemon=True).start()

    # -- leader publishes the plan; everyone fetches it ----------------------
    if stage == 0:
        publish_plan(
            kv, n_stages=args.n_stages, microbatches=args.microbatches,
            steps=args.steps, seed=args.seed, prefix=prefix,
            kind=args.schedule_kind,
            layer_split=(json.loads(args.layer_split)
                         if args.layer_split else None),
            extra={
                "model": json.loads(args.model or "{}"),
                "optimizer": json.loads(args.optimizer or "{}"),
                "batch": json.loads(args.batch or "[8, 16]"),
            })
    plan = fetch_plan(kv, prefix=prefix, timeout=args.get_timeout)
    n_stages, microbatches = plan["n_stages"], plan["microbatches"]
    kind, layer_split = plan["kind"], plan["layer_split"]

    config = TransformerConfig(**plan["model"])
    tx = _build_tx(plan["optimizer"])
    b, s = plan["batch"]
    rng = np.random.default_rng(plan["seed"])
    tokens = rng.integers(0, config.vocab_size, size=(b, s)).astype(np.int32)
    targets = ((tokens + 7) % config.vocab_size).astype(np.int32)

    # every stage derives the same init from the plan seed and keeps only
    # its own slice — deterministic, so nothing needs shipping
    flat = jax.tree.map(
        np.asarray,
        TransformerLM(config).init(jax.random.key(plan["seed"]),
                                   tokens)["params"])
    program = StageProgram(config, tx, stage, n_stages, microbatches,
                           layer_split=layer_split)
    transport = KVTransport(kv, prefix=f"{prefix}/")
    generation = kv.add(f"{prefix}/gen/{stage}", 1)
    worker = StageWorker(
        program,
        stage_params(flat, stage, n_stages, layer_split=layer_split),
        None, transport, generation=generation,
        checkpoint=HostCheckpoint(f"{args.ckpt_root}/stage-{stage}"),
        get_timeout=args.get_timeout, kind=kind)
    worker.restore_checkpoint()

    # -- fault plan + agent mailbox, polled at every op boundary -------------
    injector = FaultInjector(FaultPlan.from_env(), rank=stage, kv=jobkv,
                             agent_id=args.agent_id)
    mid_op = len(worker.ops) // 2

    def poll_mailbox():
        raw = jobkv.try_get(agent_cmd_key(args.agent_id))
        if raw is None:
            return
        jobkv.delete(agent_cmd_key(args.agent_id))
        cmd = json.loads(raw)
        if cmd["action"] == "kill_agent":
            os._exit(9)  # host death: no cleanup, no verdict
        elif cmd["action"] == "partition_host":
            dur = float(cmd.get("arg") or 3.0)
            partitioned.set()  # heartbeats stop; peers just see stall
            time.sleep(dur)
            partitioned.clear()

    def on_op(step, idx):
        if idx == mid_op:
            # step-boundary faults deliberately land MID-schedule: the
            # nastiest recovery point, with half the step's slots out
            injector.maybe_fire(step)
        poll_mailbox()

    worker.on_op = on_op

    # -- online bubble publication -------------------------------------------
    # run_step sets the mpmd.bubble_fraction gauge per step; flushing the
    # registry through the tsdb ring after every step makes it durable,
    # so the health plane can rule on it and fleetop renders it live
    from tpu_sandbox.obs.tsdb import TimeSeriesFlusher
    flusher = TimeSeriesFlusher(
        kv, proc=f"mpmd-{args.pipeline}-s{stage}".replace("/", "-"))

    # -- the training loop ---------------------------------------------------
    edges = ([EdgeNames(i).act for i in range(n_stages - 1)]
             + [EdgeNames(i).grad for i in range(n_stages - 1)])
    released = -1
    for step in range(worker.next_step, plan["steps"]):
        worker.run_step(
            step,
            tokens=tokens if program.is_first else None,
            targets=targets if program.is_last else None)
        flusher.flush()
        worker.save_checkpoint(step)
        kv.set(f"{prefix}/ckpt/{stage}", str(step))
        if program.is_last:
            # durable per-step loss: a relaunched worker's in-memory dict
            # only covers replayed steps (replays write identical values)
            kv.set(f"{prefix}/loss/{step}", repr(worker.losses[step]))
        if stage == 0:
            # leader-driven GC: drop slots every stage has made durable
            marks = [int(kv.try_get(f"{prefix}/ckpt/{s2}") or -1)
                     for s2 in range(n_stages)]
            watermark = min(marks)
            while released < watermark - 1:
                released += 1
                for edge in edges:
                    transport.release_step(edge, released)

    # -- results -------------------------------------------------------------
    leaves = jax.tree.leaves(worker.host_state()["params"])
    transport.put("final", 0, stage, [np.asarray(x) for x in leaves])
    if program.is_last:
        kv.set(f"{prefix}/losses", json.dumps(
            [float(kv.get(f"{prefix}/loss/{s2}"))
             for s2 in range(plan["steps"])]))

    if args.agent_id == 0:
        jobkv.set("job/done", json.dumps({
            "ok": True, "preempted": False,
            "reason": f"stage {stage} finished {plan['steps']} steps",
            "summary": "", "restarts": 0, "preemptions": 0,
            "generations": generation,
        }))
    hb_stop.set()
    kv.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
