"""MPMD cross-mesh pipeline: per-stage programs, stage transport, 1F1B.

The SPMD pipeline in ``parallel/pipeline.py`` is one compiled program on
one mesh. This package is the "many cooperating meshes" shape (ROADMAP
item 3, arxiv 2412.14374): each stage owns its own mesh and compiles only
its own program; activations and cotangents ship between stages over a
:class:`~tpu_sandbox.mpmd.transport.Transport`; a leader-published 1F1B
schedule coordinates microbatch dispatch. Trained parameters are bitwise
identical to the SPMD pipeline on the same model (see program.py for the
accumulation-order discipline that makes this hold).
"""

from tpu_sandbox.mpmd.transport import (  # noqa: F401
    KVTransport,
    LocalTransport,
    Transport,
    TransportStats,
    pack_arrays,
    unpack_arrays,
)
from tpu_sandbox.mpmd.program import (  # noqa: F401
    StageProgram,
    merge_stage_params,
    stage_params,
)
from tpu_sandbox.mpmd.schedule import (  # noqa: F401
    bubble_fraction,
    one_f_one_b,
)
from tpu_sandbox.mpmd.driver import MPMDPipeline, StageWorker  # noqa: F401
