"""The span/event recorder: per-process append-only JSONL, cheap enough
to leave on.

One :class:`Recorder` per process, obtained via :func:`get_recorder`. It
is **disabled unless** ``TPU_SANDBOX_TRACE_DIR`` is set in the
environment — every emit on a disabled recorder is a couple of attribute
reads, so instrumentation stays in the hot paths unconditionally.

Record forms (one JSON object per line, all timestamps are THIS
process's ``time.monotonic()`` seconds — never wall clock, never another
host's clock):

    {"ph":"P", ...}   preamble: proc name, pid, a coarse (mono, wall)
                      pair — the fallback clock anchor
    {"ph":"X", ...}   complete span: ts + dur, trace/span/parent ids
    {"ph":"i", ...}   instant event (fault injections, verdicts, job
                      lifecycle); flushed immediately so it survives a
                      SIGKILL issued on the next line
    {"ph":"C", ...}   clock-calibration sample: (kv-sequencer value,
                      mono midpoint, rtt, wall) — the collector derives
                      per-process offsets from these (see
                      ``obs/collect.py::clock_offsets``)
    {"ph":"m", ...}   metric sample: (series name, numeric value) —
                      rendered by the collector as a Chrome/Perfetto
                      counter track (``ph:"C"`` in the Chrome JSON; the
                      recorder's own "C" phase was already taken by
                      calibration) so time-series and spans share one
                      timeline

Causality is carried by :class:`TraceContext` — ``(trace_id, span_id)``
pairs serialized as ``{"t":…,"s":…}`` wherever a request body crosses a
process boundary (gateway wire frames, ``serve/req/<rid>`` bodies). A
disabled recorder *passes contexts through* unchanged, so one dark
process does not sever the chain between two instrumented ones.

Span discipline: ``with rec.span(name) as sp`` is the sanctioned form;
``begin_span`` exists for the rare span that cannot be a ``with`` block
and MUST be closed in a ``try/finally`` (graftlint GL-O401 polices
this — a leaked open span never emits and corrupts the merged timeline).
Spans whose start time predates the call (claim/admit/decode latencies
measured around existing control flow) use :meth:`Recorder.complete`,
which emits retrospectively and cannot leak.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass

ENV_TRACE_DIR = "TPU_SANDBOX_TRACE_DIR"
ENV_PROC_NAME = "TPU_SANDBOX_OBS_PROC"

#: the KV store's shared sequencer for clock calibration: every
#: ``kv.add`` on this key is serialized by the single-threaded server,
#: so the returned values give a TOTAL order across hosts that the
#: collector can pin each host's monotonic clock against
CLOCK_SEQ_KEY = "obs/clock/seq"


@dataclass(frozen=True)
class TraceContext:
    """One request's position in the causal chain: which trace it
    belongs to and which span is the current parent."""

    trace_id: str
    span_id: str

    def to_wire(self) -> dict:
        return {"t": self.trace_id, "s": self.span_id}

    @classmethod
    def from_wire(cls, obj) -> "TraceContext | None":
        """Tolerant decode: None, a wire dict, or an existing context.
        Anything malformed reads as 'no context' — tracing must never
        fail a request."""
        if obj is None:
            return None
        if isinstance(obj, TraceContext):
            return obj
        if isinstance(obj, dict) and "t" in obj and "s" in obj:
            return cls(trace_id=str(obj["t"]), span_id=str(obj["s"]))
        return None


class Span:
    """A live span handle. ``ctx`` is the context CHILDREN of this span
    should carry; on a disabled recorder it passes the parent through."""

    __slots__ = ("_rec", "name", "ctx", "parent", "args", "_t0", "_closed")

    def __init__(self, rec: "Recorder", name: str,
                 ctx: TraceContext | None, parent: TraceContext | None,
                 args: dict | None, t0: float | None):
        self._rec = rec
        self.name = name
        self.ctx = ctx
        self.parent = parent
        self.args = args if args is not None else {}
        self._t0 = t0
        self._closed = t0 is None  # disabled spans have nothing to emit

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        now = time.monotonic()
        self._rec._emit({
            "ph": "X", "name": self.name, "ts": self._t0,
            "dur": now - self._t0,
            "trace": None if self.ctx is None else self.ctx.trace_id,
            "span": None if self.ctx is None else self.ctx.span_id,
            "parent": None if self.parent is None else self.parent.span_id,
            "args": self.args,
        })


class Recorder:
    """Bounded-buffer JSONL event sink. Thread-safe; one per process.

    ``flush_every`` > 0 flushes the buffer to disk whenever it reaches
    that many records (and on every instant — instants mark faults and
    verdicts, which must survive an immediate SIGKILL). ``flush_every``
    == 0 means fully manual flushing, which is how the backpressure path
    is exercised: once the buffer holds ``max_buffered`` records, new
    ones are DROPPED and counted — the recorder prefers losing its own
    data to growing without bound inside a serving process. The drop
    count rides the engine load reports (satellite: a silently-dropping
    recorder is visible, not invisible)."""

    def __init__(self, path: str | None, *, proc: str | None = None,
                 flush_every: int = 64, max_buffered: int = 4096):
        self.path = path
        self.enabled = path is not None
        self.pid = os.getpid()
        self.proc = proc or os.environ.get(ENV_PROC_NAME) \
            or f"proc-{self.pid}"
        self.flush_every = flush_every
        self.max_buffered = max_buffered
        self.events = 0
        self.dropped = 0
        self._buf: list[dict] = []
        self._lock = threading.Lock()
        self._next_span = 0
        self._fh = None
        if self.enabled:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            self._fh = open(path, "a", encoding="utf-8")
            self._emit({"ph": "P", "mono": time.monotonic(),
                        "wall": time.time()}, flush=True)

    # -- emission ------------------------------------------------------------

    def _emit(self, rec: dict, *, flush: bool = False) -> None:
        if not self.enabled:
            return
        rec.setdefault("pid", self.pid)
        rec.setdefault("proc", self.proc)
        rec.setdefault("tid", threading.get_ident())
        with self._lock:
            if len(self._buf) >= self.max_buffered:
                self.dropped += 1
                return
            self._buf.append(rec)
            self.events += 1
            if flush or (self.flush_every
                         and len(self._buf) >= self.flush_every):
                self._flush_locked()

    def _flush_locked(self) -> None:
        if not self._buf or self._fh is None:
            return
        lines = "".join(json.dumps(r) + "\n" for r in self._buf)
        self._buf.clear()
        self._fh.write(lines)
        self._fh.flush()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            if self._fh is not None:
                self._fh.close()
                self._fh = None
        self.enabled = False

    def stats(self) -> dict:
        """The load-report rider: emitted vs dropped-on-backpressure."""
        return {"events": self.events, "dropped": self.dropped}

    # -- ids -----------------------------------------------------------------

    def _mint_span_id(self) -> str:
        self._next_span += 1
        return f"{self.pid:x}.{self._next_span}"

    def _mint_trace_id(self) -> str:
        return os.urandom(8).hex()

    def _child_ctx(self, parent: TraceContext | None) -> TraceContext:
        if parent is None:
            return TraceContext(self._mint_trace_id(), self._mint_span_id())
        return TraceContext(parent.trace_id, self._mint_span_id())

    # -- spans / events ------------------------------------------------------

    def begin_span(self, name: str, parent=None,
                   args: dict | None = None) -> Span:
        """Open a span the caller MUST close in a try/finally (GL-O401).
        Prefer ``with rec.span(...)``; use this only when the span's
        lifetime cannot be a lexical block."""
        parent = TraceContext.from_wire(parent)
        if not self.enabled:
            return Span(self, name, parent, parent, args, None)
        ctx = self._child_ctx(parent)
        return Span(self, name, ctx, parent, args, time.monotonic())

    @contextlib.contextmanager
    def span(self, name: str, parent=None, args: dict | None = None):
        """The sanctioned span form: closes on every path."""
        sp = self.begin_span(name, parent=parent, args=args)
        try:
            yield sp
        finally:
            sp.close()

    def complete(self, name: str, start_mono: float, parent=None,
                 args: dict | None = None) -> TraceContext | None:
        """Emit a span retrospectively: started at ``start_mono`` (this
        process's monotonic clock), ended now. Returns the context
        children should parent to (parent pass-through when disabled)."""
        parent = TraceContext.from_wire(parent)
        if not self.enabled:
            return parent
        ctx = self._child_ctx(parent)
        self._emit({
            "ph": "X", "name": name, "ts": start_mono,
            "dur": time.monotonic() - start_mono,
            "trace": ctx.trace_id, "span": ctx.span_id,
            "parent": None if parent is None else parent.span_id,
            "args": args or {},
        })
        return ctx

    def instant(self, name: str, parent=None,
                args: dict | None = None) -> TraceContext | None:
        """Point event — flushed immediately (auto-flush mode) so a
        fault injection's record survives the SIGKILL it announces."""
        parent = TraceContext.from_wire(parent)
        if not self.enabled:
            return parent
        ctx = self._child_ctx(parent)
        self._emit({
            "ph": "i", "name": name, "ts": time.monotonic(),
            "trace": ctx.trace_id, "span": ctx.span_id,
            "parent": None if parent is None else parent.span_id,
            "args": args or {},
        }, flush=bool(self.flush_every))
        return ctx

    def metric(self, name: str, value: float) -> None:
        """Sample a metric series onto the timeline. Buffered like spans
        (metrics are periodic, not fault markers — losing the tail on
        SIGKILL is acceptable); the collector turns these into Perfetto
        counter tracks."""
        if not self.enabled:
            return
        self._emit({"ph": "m", "name": name, "ts": time.monotonic(),
                    "value": float(value)})

    # -- clock calibration ---------------------------------------------------

    def calibrate(self, kv, rounds: int = 5) -> int:
        """Pin this process's monotonic clock against the KV server's
        shared sequencer. Each round brackets one ``kv.add`` round trip
        with monotonic reads; the sequencer value is a server-serialized
        total order, so the collector can (a) offset each process by its
        own (wall - mono) median and (b) enforce that calibration points
        appear in sequencer order on the merged timeline — no raw
        cross-host wall-clock arithmetic anywhere (GL-R302). Returns the
        last sequencer value observed (0 when disabled)."""
        if not self.enabled:
            return 0
        seq = 0
        for _ in range(rounds):
            m0 = time.monotonic()
            seq = kv.add(CLOCK_SEQ_KEY)
            m1 = time.monotonic()
            self._emit({
                "ph": "C", "seq": int(seq), "mono": (m0 + m1) / 2.0,
                "rtt": m1 - m0, "wall": time.time(),
            })
        self.flush()
        return int(seq)


# -- process-global recorder --------------------------------------------------

_RECORDER: Recorder | None = None
_RECORDER_LOCK = threading.Lock()


def get_recorder() -> Recorder:
    """The process-wide recorder, built once from the environment:
    enabled iff ``TPU_SANDBOX_TRACE_DIR`` is set (log file
    ``<dir>/<proc>-<pid>.jsonl``)."""
    global _RECORDER
    rec = _RECORDER
    if rec is not None:
        return rec
    with _RECORDER_LOCK:
        if _RECORDER is None:
            trace_dir = os.environ.get(ENV_TRACE_DIR)
            if trace_dir:
                proc = os.environ.get(ENV_PROC_NAME) \
                    or f"proc-{os.getpid()}"
                path = os.path.join(trace_dir, f"{proc}-{os.getpid()}.jsonl")
                _RECORDER = Recorder(path, proc=proc)
            else:
                _RECORDER = Recorder(None)
        return _RECORDER


def reset_recorder() -> None:
    """Close and forget the global recorder so the next
    :func:`get_recorder` re-reads the environment (tests / the obs
    bench flipping tracing on and off inside one process)."""
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is not None:
            _RECORDER.close()
        _RECORDER = None
