"""Durable KV-backed time series: the health plane's memory.

The metrics registry (:mod:`tpu_sandbox.obs.metrics`) is a point-in-time
scrape — ask it twice and you get two unrelated snapshots, and when the
process dies the history dies with it. The :class:`TimeSeriesFlusher`
gives every process a cheap way to leave a durable trail: each flush
diffs the registry against the previous flush and writes the touched
series into bucketed KV windows

    obs/ts/<proc>/<series>/<slot>     fine buckets (``bucket_s`` wide)
    obs/tsd/<proc>/<series>/<slot>    downsampled (``ds_factor`` × wider)

where ``slot = bucket % retention`` — a true ring: the key count per
series is bounded by the retention window and old slots are overwritten
on wrap. Every write also carries a TTL of one full retention window,
so a dead process's trail ages out instead of lingering forever. The
payload records the ABSOLUTE bucket index, so readers never confuse a
wrapped slot with a fresh one.

Per-kind semantics inside one bucket:

* **counters** flush as deltas (this bucket's increments, accumulated
  locally across flushes — the flusher is the sole writer of its own
  ``<proc>`` namespace, so overwriting the bucket with the running
  per-bucket total is safe);
* **gauges** are last-write-wins;
* **histograms** store the registry's cumulative digest
  (count/sum/min/max/mean/p50/p90/p99) — readers treat the latest
  bucket as "the distribution so far".

The flusher also publishes two synthetic series so the health plane can
watch the observability layer itself: ``obs.recorder.dropped`` (a
silently-dropping recorder is the observability layer lying) and
``obs.recorder.events``. When the process recorder is enabled, each
flush additionally emits ``"m"`` metric samples onto the trace log, so
``collect.to_chrome_trace`` renders the same series as Perfetto counter
tracks next to the spans.

Readers (:func:`read_series`, :func:`list_series`) work fleet-wide off
prefix scans; any process holding a ``KVClient`` can reconstruct any
other process's recent metric history — that is what the leader-elected
``HealthMonitor`` (:mod:`tpu_sandbox.obs.health`) and the ``fleetop``
console are built on.
"""

from __future__ import annotations

import json
import time

from .metrics import get_registry
from .record import get_recorder

#: fine-grained ring root (bucket_s-wide windows)
TS_PREFIX = "obs/ts/"
#: downsampled ring root (ds_factor * bucket_s-wide windows)
TSD_PREFIX = "obs/tsd/"


def series_base(series: str) -> str:
    """Strip the ``{k=v,...}`` label suffix: the aggregation name."""
    return series.split("{", 1)[0]


def _k(prefix: str, proc: str, series: str, slot: int) -> str:
    return f"{prefix}{proc}/{series}/{slot}"


class TimeSeriesFlusher:
    """Flush one process's registry into the durable ring.

    Call :meth:`flush` on whatever cadence the process already has (the
    replica worker rides its load-report interval; the bench rides the
    step loop). ``clock`` is injectable so tests can drive bucket
    boundaries with a stub clock.
    """

    def __init__(self, kv, proc: str, *, bucket_s: float = 1.0,
                 retention_buckets: int = 120, ds_factor: int = 10,
                 ds_retention_buckets: int | None = None,
                 registry=None, recorder=None, clock=time.time):
        proc = str(proc)
        if "/" in proc or not proc:
            raise ValueError(f"need a slash-free proc name, got {proc!r}")
        if ds_factor < 2:
            raise ValueError("ds_factor must be >= 2")
        self.kv = kv
        self.proc = proc
        self.bucket_s = float(bucket_s)
        self.retention_buckets = int(retention_buckets)
        self.ds_factor = int(ds_factor)
        self.ds_retention_buckets = int(
            ds_retention_buckets or retention_buckets)
        self.registry = registry if registry is not None else get_registry()
        self.recorder = recorder
        self.clock = clock
        self.flushes = 0
        self._prev_counters: dict[str, int] = {}
        # per-bucket local accumulation of counter deltas; pruned to the
        # current bucket after every flush
        self._acc: dict[int, dict[str, float]] = {}
        self._acc_ds: dict[int, dict[str, float]] = {}

    # -- flushing ------------------------------------------------------------

    def flush(self) -> int:
        """Diff the registry against the previous flush and write every
        live series into the current fine + coarse buckets. Returns the
        number of KV keys written."""
        snap = self.registry.snapshot()
        rec = self.recorder if self.recorder is not None else get_recorder()
        now = float(self.clock())
        bucket = int(now // self.bucket_s)
        dsb = bucket // self.ds_factor
        ttl = self.retention_buckets * self.bucket_s
        ds_ttl = self.ds_retention_buckets * self.ds_factor * self.bucket_s
        writes = 0

        # counters: accumulate this flush's deltas into the open buckets
        acc = self._acc.setdefault(bucket, {})
        acc_ds = self._acc_ds.setdefault(dsb, {})
        for name, val in snap["counters"].items():
            delta = val - self._prev_counters.get(name, 0)
            self._prev_counters[name] = val
            acc[name] = acc.get(name, 0) + delta
            acc_ds[name] = acc_ds.get(name, 0) + delta
        for name, total in acc.items():
            writes += self._write(TS_PREFIX, name, bucket,
                                  self.retention_buckets,
                                  {"kind": "counter", "v": total,
                                   "bucket": bucket, "wall": now}, ttl)
        for name, total in acc_ds.items():
            writes += self._write(TSD_PREFIX, name, dsb,
                                  self.ds_retention_buckets,
                                  {"kind": "counter", "v": total,
                                   "bucket": dsb, "wall": now}, ds_ttl)
        self._acc = {bucket: acc}
        self._acc_ds = {dsb: acc_ds}

        # gauges + synthetic recorder-health series: last write wins
        gauges = dict(snap["gauges"])
        stats = rec.stats()
        gauges["obs.recorder.dropped"] = float(stats["dropped"])
        gauges["obs.recorder.events"] = float(stats["events"])
        for name, val in gauges.items():
            body = {"kind": "gauge", "v": val, "bucket": bucket, "wall": now}
            writes += self._write(TS_PREFIX, name, bucket,
                                  self.retention_buckets, body, ttl)
            writes += self._write(
                TSD_PREFIX, name, dsb, self.ds_retention_buckets,
                {"kind": "gauge", "v": val, "bucket": dsb, "wall": now},
                ds_ttl)

        # histograms: cumulative digest, last write wins
        for name, digest in snap["histograms"].items():
            body = {"kind": "histogram", "v": digest,
                    "bucket": bucket, "wall": now}
            writes += self._write(TS_PREFIX, name, bucket,
                                  self.retention_buckets, body, ttl)
            writes += self._write(
                TSD_PREFIX, name, dsb, self.ds_retention_buckets,
                {"kind": "histogram", "v": digest, "bucket": dsb,
                 "wall": now}, ds_ttl)

        # mirror onto the trace timeline as Perfetto counter tracks
        if rec.enabled:
            for name, val in snap["counters"].items():
                rec.metric(name, val)
            for name, val in gauges.items():
                rec.metric(name, val)
            for name, digest in snap["histograms"].items():
                if digest.get("p99") is not None:
                    rec.metric(f"{name}.p99", digest["p99"])

        self.flushes += 1
        return writes

    def _write(self, prefix: str, series: str, bucket: int,
               retention: int, body: dict, ttl: float) -> int:
        slot = bucket % retention
        self.kv.set_ttl(_k(prefix, self.proc, series, slot),
                        json.dumps(body), ttl)
        return 1


# -- fleet-wide readers -------------------------------------------------------

def _parse(key: str, prefix: str):
    """``obs/ts/<proc>/<series>/<slot>`` → (proc, series, slot). The
    series may contain label braces but never slashes; proc and slot are
    the outermost segments."""
    parts = key[len(prefix):].split("/")
    if len(parts) < 3:
        return None
    try:
        slot = int(parts[-1])
    except ValueError:
        return None
    return parts[0], "/".join(parts[1:-1]), slot


def read_series(kv, name: str, *, proc: str | None = None,
                coarse: bool = False) -> list[dict]:
    """Every live point of every series whose base name is ``name``
    (label variants included), fleet-wide or for one process. Rows are
    ``{"proc", "series", "bucket", "kind", "v", "wall"}`` sorted by
    (bucket, proc, series); wrapped/expired slots never appear because
    the payload's absolute bucket is authoritative."""
    prefix = TSD_PREFIX if coarse else TS_PREFIX
    scan = prefix + (f"{proc}/" if proc else "")
    rows = []
    for key in kv.keys(scan):
        parsed = _parse(key, prefix)
        if parsed is None:
            continue
        kproc, series, _slot = parsed
        if series_base(series) != name:
            continue
        raw = kv.try_get(key)
        if raw is None:
            continue
        try:
            body = json.loads(raw)
        except ValueError:
            continue
        rows.append({"proc": kproc, "series": series, **body})
    rows.sort(key=lambda r: (r["bucket"], r["proc"], r["series"]))
    return rows


def list_series(kv, *, coarse: bool = False) -> list[tuple[str, str]]:
    """Sorted (proc, base-name) pairs currently live in the store."""
    prefix = TSD_PREFIX if coarse else TS_PREFIX
    seen = set()
    for key in kv.keys(prefix):
        parsed = _parse(key, prefix)
        if parsed is not None:
            seen.add((parsed[0], series_base(parsed[1])))
    return sorted(seen)


def window_sum(rows: list[dict], *, since_bucket: int,
               per_proc: bool = False):
    """Sum counter deltas from ``since_bucket`` onward: one float, or a
    per-proc dict. Gauge/histogram rows are ignored."""
    if per_proc:
        out: dict[str, float] = {}
        for r in rows:
            if r["kind"] == "counter" and r["bucket"] >= since_bucket:
                out[r["proc"]] = out.get(r["proc"], 0.0) + float(r["v"])
        return out
    return sum(float(r["v"]) for r in rows
               if r["kind"] == "counter" and r["bucket"] >= since_bucket)


def latest_value(rows: list[dict], *, proc: str | None = None,
                 field: str | None = None):
    """The newest gauge value or histogram-digest field across the
    rows (optionally restricted to one proc); None when absent."""
    best = None
    for r in rows:
        if proc is not None and r["proc"] != proc:
            continue
        if r["kind"] == "counter":
            continue
        if best is None or r["bucket"] >= best["bucket"]:
            best = r
    if best is None:
        return None
    if best["kind"] == "histogram":
        return (best["v"] or {}).get(field or "p99")
    return best["v"]
