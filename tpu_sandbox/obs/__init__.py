"""Flight recorder: causal tracing, fleet metrics, merged timelines.

Three pieces, deliberately decoupled:

- :mod:`tpu_sandbox.obs.record` — the in-process recorder. Append-only
  per-process JSONL, monotonic timestamps, propagated trace context.
  Off by default; exporting ``TPU_SANDBOX_TRACE_DIR`` turns it on for
  every process that inherits the env (agents, replicas, the gateway).
- :mod:`tpu_sandbox.obs.metrics` — counters / gauges / streaming-quantile
  histograms. Always on (an increment is nanoseconds); scraped live via
  the gateway's METRICS wire op.
- :mod:`tpu_sandbox.obs.collect` — the offline collector: merges per-host
  logs on a KV-sequencer-calibrated clock, emits Chrome trace-event JSON,
  per-request waterfalls, and last-N-seconds postmortem timelines
  (``tools/tracecat.py`` is the CLI).
"""

from tpu_sandbox.obs.record import (ENV_TRACE_DIR, Recorder, TraceContext,
                                    get_recorder, reset_recorder)
from tpu_sandbox.obs.metrics import MetricsRegistry, get_registry

__all__ = [
    "ENV_TRACE_DIR",
    "MetricsRegistry",
    "Recorder",
    "TraceContext",
    "get_recorder",
    "get_registry",
    "reset_recorder",
]
