"""Flight recorder + health plane: causal tracing, fleet metrics,
merged timelines, durable time series, and alerting wired into control.

Five pieces, deliberately decoupled:

- :mod:`tpu_sandbox.obs.record` — the in-process recorder. Append-only
  per-process JSONL, monotonic timestamps, propagated trace context.
  Off by default; exporting ``TPU_SANDBOX_TRACE_DIR`` turns it on for
  every process that inherits the env (agents, replicas, the gateway).
- :mod:`tpu_sandbox.obs.metrics` — counters / gauges / streaming-quantile
  histograms. Always on (an increment is nanoseconds); scraped live via
  the gateway's METRICS wire op. Bounded dimensions ride ``labels=``;
  names are static ``snake.dotted`` literals (graftlint GL-O402).
- :mod:`tpu_sandbox.obs.tsdb` — the durable KV-backed time-series ring:
  each process flushes its registry (counter deltas, gauges, histogram
  digests) into TTL'd per-bucket windows any process can read back.
- :mod:`tpu_sandbox.obs.health` — the leader-elected ``HealthMonitor``:
  multi-window SLO burn-rate rules and anomaly detectors over the tsdb
  and durable control-plane state, raising claim-once alerts that the
  gateway, autoscaler, and scheduler consume (``tools/fleetop.py`` is
  the ops console).
- :mod:`tpu_sandbox.obs.collect` — the offline collector: merges per-host
  logs on a KV-sequencer-calibrated clock, emits Chrome trace-event JSON
  (spans + metric counter tracks), per-request waterfalls, and
  last-N-seconds postmortem timelines (``tools/tracecat.py`` is the CLI).
- :mod:`tpu_sandbox.obs.critpath` — the trace analytics plane over the
  merged timeline: per-request causal critical paths attributed to named
  segments (>= 95% of wall, residue reported as ``unattributed``), the
  run-level where-time-goes profile, blame for every shed/late request,
  offline MPMD bubble accounting, and the profile compare engine behind
  ``tools/tracediff.py`` regression gating.
- :mod:`tpu_sandbox.obs.workload` — the canonical replayable workload
  trace exported from a merged run (arrival offsets, tenant, prefix
  chain, token counts, outcome), schema-versioned and byte-stable so a
  saved workload round-trips and diffs cleanly.
"""

from tpu_sandbox.obs.record import (ENV_TRACE_DIR, Recorder, TraceContext,
                                    get_recorder, reset_recorder)
from tpu_sandbox.obs.metrics import MetricsRegistry, get_registry
from tpu_sandbox.obs.tsdb import TimeSeriesFlusher, list_series, read_series

__all__ = [
    "ENV_TRACE_DIR",
    "MetricsRegistry",
    "Recorder",
    "TimeSeriesFlusher",
    "TraceContext",
    "get_recorder",
    "get_registry",
    "list_series",
    "read_series",
    "reset_recorder",
]
