"""Critical-path attribution over merged traces: where did the time go?

Input is the merged, clock-calibrated record list from
:mod:`tpu_sandbox.obs.collect` (each record carries ``uts``, unified
seconds). For every request chain this module:

1. finds the **terminal** record (the ``verdict`` instant, a
   ``door:*``/``shed:*`` terminal, or — for a chain that never finished —
   the latest record) and walks parent links back to the root, giving
   the causal critical path;
2. **sweeps** the request's wall-clock interval and attributes every
   elementary sub-interval to a named segment: the deepest covering span
   on the path (or a direct child of a path span — ``prefill`` refines
   ``admit``) wins; uncovered gaps are named by their causal neighbours
   (``enqueue`` → ``claim`` is ``queue_wait``, the targeted-queue wait),
   overlapped against process-level ``swap:pause`` spans (a weight swap
   stalls every resident request on that engine), and anything still
   unexplained lands in ``unattributed``. Attribution therefore sums to
   the wall-clock *exactly*; the contract (`coverage ≥ 0.95`) is on how
   little of it is ``unattributed``;
3. emits a **blame** segment per request — the largest attributed
   segment — so a SHED or deadline-missed request names the span that
   ate its budget.

Run-level aggregation (:func:`aggregate`) keeps per-request samples per
segment so :func:`compare_profiles` (the engine behind
``tools/tracediff.py``) can gate on a quantile-paired **median of
ratios** rather than means — one straggler request must not flag a
regression, and a real 20% decode slowdown must.

MPMD runs get the same treatment at stage granularity:
:func:`bubble_fractions` derives per-stage, per-step pipeline bubble
from the ``stage:op`` / ``stage:step`` spans that
:class:`tpu_sandbox.mpmd.driver.StageWorker` emits, independently of the
online ``mpmd.bubble_fraction`` gauge the worker publishes — the bench
cross-checks the two against the analytic ``(S-1)/(M+S-1)``.

:func:`publish_profile` pushes a profile's segment shares through the
tsdb ring (static gauge names, segment as a label — GL-O402/O403) so
``tools/fleetop.py`` can render a live where-time-goes panel.
"""

from __future__ import annotations

import json
import statistics

from tpu_sandbox.obs import tsdb
from tpu_sandbox.obs.metrics import MetricsRegistry
from tpu_sandbox.obs.record import Recorder

#: profile schema tag — bump on any change to the aggregate layout
PROFILE_SCHEMA = "tpu-sandbox.critpath/1"

#: span name (or ``family`` for ``family:<x>`` names) -> segment
SEGMENT_OF_SPAN = {
    "submit": "submit",         # client-side submit RPC round trip
    "route": "route",           # gateway routing decision
    "door": "door",             # terminal door shed (door:<reason>)
    "enqueue": "enqueue",       # KV queue write
    "claim": "claim",           # replica claim + request fetch
    "admit": "admit",           # engine admission bookkeeping
    "prefill": "prefill",       # prefill compute (child of admit)
    "decode": "decode",         # decode steps, admit -> retire
    "publish": "publish",       # verdict publish (KV write)
    "ship": "wire_ship",        # KV wire ship (disagg / remote cache)
    "swap": "swap_pause",       # swap:pause — weight-swap stall
}

#: (segment before, segment after) -> name for the uncovered gap between
GAP_SEGMENTS = {
    ("enqueue", "claim"): "queue_wait",      # targeted/shared queue wait
    ("submit", "claim"): "queue_wait",       # enqueue span lost/torn
    ("claim", "admit"): "engine_queue",      # engine waiting deque
    ("claim", "decode"): "engine_queue",
    ("claim", "shed"): "engine_queue",       # shed straight off the queue
    ("decode", "publish"): "publish_wait",   # retire -> publisher pump
    ("decode", "verdict"): "publish_wait",
    ("decode", "shed"): "publish_wait",
    ("publish", "verdict"): "publish_wait",
}

#: process-level spans that stall resident requests without being part
#: of any request's causal chain — matched into gaps by process key
STALL_SPANS = {"swap": "swap_pause"}

#: the coverage contract: at most 5% of a request's wall may stay
#: unattributed for the request to count as fully explained
COVERAGE_TARGET = 0.95


def _segment_of(name: str) -> str | None:
    """Map a span name to its segment; ``family:<value>`` names key on
    the family prefix (``door:infeasible`` -> ``door``)."""
    if name in SEGMENT_OF_SPAN:
        return SEGMENT_OF_SPAN[name]
    fam = name.split(":", 1)[0]
    return SEGMENT_OF_SPAN.get(fam)


def _family(name: str) -> str:
    return name.split(":", 1)[0]


def _end(r: dict) -> float:
    return float(r["uts"]) + float(r.get("dur", 0.0))


# -- per-request critical path ------------------------------------------------


def request_traces(merged: list[dict]) -> dict[str, str]:
    """rid -> trace id, discovered from the ``rid`` stamped into span
    args at submit time (first trace to mention a rid wins)."""
    out: dict[str, str] = {}
    for r in merged:
        rid = (r.get("args") or {}).get("rid")
        if rid is not None and r.get("trace") and rid not in out:
            out[rid] = r["trace"]
    return out


def _terminal(records: list[dict]) -> dict:
    """The record the path walk starts from: the chain's verdict instant
    if one landed, else a terminal door/shed record, else whatever
    happened last (an open request — still attributable up to its last
    observed event)."""
    for want in ("verdict", "door", "shed"):
        cands = [r for r in records if _family(r.get("name", "")) == want]
        if cands:
            return max(cands, key=_end)
    return max(records, key=_end)


def critical_path(records: list[dict]) -> list[dict]:
    """The causal chain from the terminal record back to the root,
    returned root-first. A dangling parent (torn log) truncates the walk
    there — the path is still valid from that point on."""
    if not records:
        return []
    by_span = {r["span"]: r for r in records if r.get("span")}
    path = []
    node = _terminal(records)
    seen = set()
    while node is not None and id(node) not in seen:
        seen.add(id(node))
        path.append(node)
        parent = node.get("parent")
        node = by_span.get(parent) if parent else None
    path.reverse()
    return path


def attribute_request(records: list[dict],
                      stalls: list[dict] | None = None) -> dict | None:
    """Attribute one request chain's wall-clock to named segments.

    ``records`` is every merged record of one trace; ``stalls`` is the
    run's process-level stall spans (``swap:pause``), matched into this
    request's gaps by process key. Returns the per-request attribution
    dict, or None for traces with no usable records."""
    spans = [r for r in records if r.get("ph") == "X"]
    if not records or not (spans or
                           any(r.get("ph") == "i" for r in records)):
        return None
    path = critical_path(records)
    if not path:
        return None
    path_ids = {r.get("span") for r in path if r.get("span")}
    # one level of refinement: a direct child of a path span carves its
    # parent's time into a finer segment (prefill inside admit)
    cover = list(path) + [
        r for r in spans
        if r.get("parent") in path_ids and r.get("span") not in path_ids]
    # depth orders nesting for deepest-wins; the path is causally ordered
    # already, refinement children sit one deeper than their parent
    depth = {id(r): i for i, r in enumerate(path)}
    for r in cover:
        if id(r) not in depth:
            depth[id(r)] = depth.get(
                id(next((p for p in path
                         if p.get("span") == r.get("parent")), path[-1])),
                len(path)) + 1

    t0 = min(float(r["uts"]) for r in path)
    t1 = max(_end(r) for r in path)
    wall = t1 - t0
    rid = next(((r.get("args") or {}).get("rid") for r in records
                if (r.get("args") or {}).get("rid") is not None), None)
    terminal = _terminal(records)
    term_name = terminal.get("name", "?")
    outcome = "ok"
    if _family(term_name) in ("door", "shed"):
        outcome = term_name
    elif term_name == "verdict":
        v = (terminal.get("args") or {}).get("verdict", "ok")
        outcome = "ok" if str(v).lower() == "ok" else f"shed:{v}"
    else:
        outcome = "open"

    segments: dict[str, float] = {}
    if wall <= 0.0:
        return {"rid": rid, "trace": records[0].get("trace"),
                "wall_s": 0.0, "segments": {}, "coverage": 1.0,
                "outcome": outcome, "blame": None, "procs": []}

    intervals = [(max(float(r["uts"]), t0), min(_end(r), t1), r)
                 for r in cover if r.get("ph") == "X"]
    intervals = [iv for iv in intervals if iv[1] > iv[0]]
    procs = sorted({r.get("pkey", "?") for r in cover})
    my_stalls = [(float(s["uts"]), _end(s), _segment_of(s.get("name", "")))
                 for s in (stalls or []) if s.get("pkey") in procs]

    bounds = sorted({t0, t1}
                    | {b for lo, hi, _ in intervals for b in (lo, hi)})
    for lo, hi in zip(bounds, bounds[1:]):
        if hi <= lo:
            continue
        covering = [r for ilo, ihi, r in intervals if ilo <= lo and ihi >= hi]
        if covering:
            winner = max(covering,
                         key=lambda r: (depth[id(r)], float(r["uts"])))
            seg = _segment_of(winner.get("name", "")) or "unattributed"
        else:
            prev = max((r for ilo, ihi, r in intervals if ihi <= lo),
                       key=lambda r: _end(r), default=None)
            nxt = min((r for ilo, ihi, r in intervals if ilo >= hi),
                      key=lambda r: float(r["uts"]), default=None)
            before = _segment_of(prev.get("name", "")) if prev else None
            after = _family(term_name) if nxt is None \
                else _segment_of(nxt.get("name", ""))
            seg = GAP_SEGMENTS.get((before, after))
            if seg is None:
                seg = "unattributed"
            if seg == "unattributed" or seg in ("queue_wait", "engine_queue",
                                                "publish_wait"):
                # a weight swap overlapping the gap explains (part of)
                # it; the unoverlapped remainder keeps the gap's name so
                # the pieces still sum to the wall exactly
                cursor = lo
                for slo, shi, sseg in sorted(my_stalls):
                    a, b = max(slo, cursor), min(shi, hi)
                    if b > a:
                        if a > cursor:
                            segments[seg] = segments.get(seg, 0.0) \
                                + (a - cursor)
                        segments[sseg] = segments.get(sseg, 0.0) + (b - a)
                        cursor = b
                if cursor > lo:
                    rem = hi - cursor
                    if rem > 0:
                        segments[seg] = segments.get(seg, 0.0) + rem
                    continue
        segments[seg] = segments.get(seg, 0.0) + (hi - lo)

    unattr = segments.get("unattributed", 0.0)
    coverage = 1.0 - unattr / wall
    attributed = {k: v for k, v in segments.items() if k != "unattributed"}
    blame = max(attributed, key=attributed.get) if attributed else None
    return {
        "rid": rid,
        "trace": records[0].get("trace"),
        "wall_s": wall,
        "segments": {k: segments[k] for k in sorted(segments)},
        "coverage": coverage,
        "outcome": outcome,
        "blame": blame,
        "procs": procs,
    }


def analyze(merged: list[dict]) -> dict:
    """Every request chain in a merged trace, attributed, plus the
    run-level profile. The unit tools/benches call."""
    from tpu_sandbox.obs.collect import trace_chains
    chains = trace_chains(merged)
    stalls = [r for r in merged
              if r.get("ph") == "X"
              and _family(r.get("name", "")) in STALL_SPANS]
    rid_to_trace = request_traces(merged)
    requests = []
    for rid, trace in sorted(rid_to_trace.items()):
        recs = chains.get(trace)
        if not recs:
            continue
        req = attribute_request(recs, stalls)
        if req is not None:
            requests.append(req)
    return {"requests": requests, "profile": aggregate(requests)}


# -- aggregation --------------------------------------------------------------


def aggregate(requests: list[dict]) -> dict:
    """Fold per-request attributions into the run profile: per-segment
    totals, shares, and the sorted per-request samples tracediff pairs
    by quantile; blame counts over non-ok requests; a per-proc segment
    breakdown (the fleet/stage view)."""
    segs: dict[str, list[float]] = {}
    by_proc: dict[str, dict[str, float]] = {}
    blames: dict[str, int] = {}
    walls = []
    n_ok = 0
    for req in requests:
        walls.append(req["wall_s"])
        if req["outcome"] == "ok":
            n_ok += 1
        elif req.get("blame"):
            blames[req["blame"]] = blames.get(req["blame"], 0) + 1
        for seg, s in req["segments"].items():
            segs.setdefault(seg, []).append(s)
        # charge the request's segments to its serving process (the
        # non-gateway, non-client proc if any — where claim/decode ran)
        serving = next(
            (p for p in req.get("procs", ())
             if not p.startswith(("gateway", "client", "bench", "test"))),
            req.get("procs", ["?"])[0] if req.get("procs") else "?")
        slot = by_proc.setdefault(serving, {})
        for seg, s in req["segments"].items():
            slot[seg] = slot.get(seg, 0.0) + s
    total_wall = sum(walls)
    segments = {}
    for seg in sorted(segs):
        samples = sorted(round(s, 9) for s in segs[seg])
        tot = sum(samples)
        segments[seg] = {
            "total_s": round(tot, 9),
            "share": round(tot / total_wall, 6) if total_wall else 0.0,
            "n": len(samples),
            "median_s": round(statistics.median(samples), 9),
            "samples": samples,
        }
    covs = [r["coverage"] for r in requests]
    return {
        "schema": PROFILE_SCHEMA,
        "requests": len(requests),
        "ok": n_ok,
        "wall_s_total": round(total_wall, 9),
        "wall_s_median": round(statistics.median(walls), 9) if walls else 0.0,
        "coverage_min": round(min(covs), 6) if covs else 1.0,
        "coverage_mean": round(sum(covs) / len(covs), 6) if covs else 1.0,
        "segments": segments,
        "blame": {k: blames[k] for k in sorted(blames)},
        "by_proc": {p: {k: round(v, 9) for k, v in sorted(d.items())}
                    for p, d in sorted(by_proc.items())},
    }


def format_profile(profile: dict) -> str:
    """The where-time-goes table, largest segment first."""
    lines = [f"critpath profile: {profile['requests']} requests "
             f"({profile['ok']} ok), wall "
             f"{profile['wall_s_total'] * 1e3:.1f}ms total, "
             f"coverage min {profile['coverage_min']:.1%} "
             f"mean {profile['coverage_mean']:.1%}"]
    segs = sorted(profile["segments"].items(),
                  key=lambda kv: -kv[1]["total_s"])
    for seg, s in segs:
        lines.append(f"  {seg:<14} {s['share']:>7.1%}  "
                     f"{s['total_s'] * 1e3:>10.2f}ms total  "
                     f"{s['median_s'] * 1e3:>9.3f}ms median  n={s['n']}")
    if profile.get("blame"):
        lines.append("  blame (non-ok requests): " + ", ".join(
            f"{seg}={n}" for seg, n in profile["blame"].items()))
    return "\n".join(lines)


# -- regression compare (the tracediff engine) --------------------------------


def compare_profiles(a: dict, b: dict, *, threshold: float = 0.10,
                     min_ms: float = 0.5, min_share: float = 0.01) -> dict:
    """Segment-by-segment compare of two run profiles, robust to
    stragglers: per segment the two runs' per-request samples are paired
    by quantile (both sorted, index-matched over the shorter run) and
    the **median of the pairwise ratios** is the segment's ratio. A
    segment regresses when that ratio exceeds ``1 + threshold`` AND the
    median grew by at least ``min_ms`` AND the segment carries at least
    ``min_share`` of either run's wall — the noise floor that keeps a
    2µs route jitter from failing a build."""
    rows = []
    regressions = []
    names = sorted(set(a["segments"]) | set(b["segments"]))
    for seg in names:
        sa = a["segments"].get(seg, {}).get("samples", [])
        sb = b["segments"].get(seg, {}).get("samples", [])
        share = max(a["segments"].get(seg, {}).get("share", 0.0),
                    b["segments"].get(seg, {}).get("share", 0.0))
        med_a = statistics.median(sa) if sa else 0.0
        med_b = statistics.median(sb) if sb else 0.0
        if sa and sb:
            n = min(len(sa), len(sb))
            qa = [sa[int(i * (len(sa) - 1) / max(1, n - 1))]
                  for i in range(n)] if n > 1 else [statistics.median(sa)]
            qb = [sb[int(i * (len(sb) - 1) / max(1, n - 1))]
                  for i in range(n)] if n > 1 else [statistics.median(sb)]
            ratios = sorted(y / x for x, y in zip(qa, qb) if x > 0)
            ratio = statistics.median(ratios) if ratios else None
        else:
            ratio = None
        grew_ms = (med_b - med_a) * 1e3
        regressed = (ratio is not None and ratio > 1.0 + threshold
                     and grew_ms >= min_ms and share >= min_share)
        row = {"segment": seg, "median_a_ms": round(med_a * 1e3, 4),
               "median_b_ms": round(med_b * 1e3, 4),
               "ratio": None if ratio is None else round(ratio, 4),
               "share": round(share, 4), "regressed": regressed}
        rows.append(row)
        if regressed:
            regressions.append(seg)
    wall_ratio = None
    if a.get("wall_s_median") and b.get("wall_s_median"):
        wall_ratio = round(b["wall_s_median"] / a["wall_s_median"], 4)
    return {"segments": rows, "regressions": regressions,
            "wall_ratio": wall_ratio,
            "threshold": threshold, "min_ms": min_ms,
            "min_share": min_share}


def format_compare(cmp: dict) -> str:
    lines = [f"{'segment':<14} {'a (ms)':>10} {'b (ms)':>10} "
             f"{'ratio':>7} {'share':>6}  verdict"]
    for row in cmp["segments"]:
        verdict = "REGRESSED" if row["regressed"] else (
            "-" if row["ratio"] is None else
            ("improved" if row["ratio"] < 0.97 else "ok"))
        lines.append(
            f"{row['segment']:<14} {row['median_a_ms']:>10.3f} "
            f"{row['median_b_ms']:>10.3f} "
            f"{row['ratio'] if row['ratio'] is not None else '-':>7} "
            f"{row['share']:>6.1%}  {verdict}")
    if cmp["wall_ratio"] is not None:
        lines.append(f"wall median ratio: {cmp['wall_ratio']}")
    lines.append(
        f"{len(cmp['regressions'])} regression(s)"
        + (f": {', '.join(cmp['regressions'])}" if cmp["regressions"]
           else "")
        + f"  (threshold {cmp['threshold']:.0%}, floor "
          f"{cmp['min_ms']}ms / {cmp['min_share']:.0%} share)")
    return "\n".join(lines)


def save_profile(profile: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(profile, fh, sort_keys=True, separators=(",", ":"))
        fh.write("\n")


def load_profile(path: str) -> dict:
    """A saved profile JSON, or a trace dir to analyze on the fly."""
    import os
    if os.path.isdir(path):
        from tpu_sandbox.obs.collect import load_merged
        return analyze(load_merged(path))["profile"]
    with open(path, "r", encoding="utf-8") as fh:
        profile = json.load(fh)
    if profile.get("schema") != PROFILE_SCHEMA:
        raise ValueError(
            f"unknown critpath profile schema {profile.get('schema')!r} "
            f"(want {PROFILE_SCHEMA})")
    return profile


# -- MPMD bubble accounting ---------------------------------------------------


def bubble_fractions(merged: list[dict]) -> dict:
    """Per-stage, per-step pipeline bubble derived offline from the
    stage-worker spans: a step's bubble is the fraction of its
    ``stage:step`` wall NOT covered by that stage's ``stage:op`` compute
    spans. This is the trace-side cross-check for the online
    ``mpmd.bubble_fraction`` gauge (same numerator, measured instead of
    reported) and for the analytic ``(S-1)/(M+S-1)``."""
    steps: dict[tuple[int, int], float] = {}
    compute: dict[tuple[int, int], float] = {}
    for r in merged:
        if r.get("ph") != "X":
            continue
        args = r.get("args") or {}
        if r.get("name") == "stage:step":
            key = (int(args.get("stage", -1)), int(args.get("step", -1)))
            steps[key] = steps.get(key, 0.0) + float(r.get("dur", 0.0))
        elif r.get("name") == "stage:op":
            key = (int(args.get("stage", -1)), int(args.get("step", -1)))
            compute[key] = compute.get(key, 0.0) + float(r.get("dur", 0.0))
    per_step = []
    per_stage: dict[int, list[float]] = {}
    for (stage, step), wall in sorted(steps.items()):
        if wall <= 0:
            continue
        bubble = max(0.0, 1.0 - compute.get((stage, step), 0.0) / wall)
        per_step.append({"stage": stage, "step": step,
                         "bubble": round(bubble, 6)})
        per_stage.setdefault(stage, []).append(bubble)
    stage_means = {s: round(sum(v) / len(v), 6)
                   for s, v in sorted(per_stage.items())}
    all_b = [row["bubble"] for row in per_step]
    return {
        "per_step": per_step,
        "per_stage": stage_means,
        "mean": round(sum(all_b) / len(all_b), 6) if all_b else None,
    }


# -- tsdb publication ---------------------------------------------------------


def publish_profile(kv, profile: dict, *, proc: str = "critpath",
                    top: int = 12) -> int:
    """Push a profile's segment breakdown through the tsdb ring so
    ``fleetop`` renders it live: static gauge names, the segment riding
    a bounded label (the segment vocabulary is the fixed set above).
    Returns the number of series written."""
    reg = MetricsRegistry()
    segs = sorted(profile["segments"].items(),
                  key=lambda kv_: -kv_[1]["total_s"])[:top]
    for seg, s in segs:
        reg.gauge("critpath.segment.share",
                  labels={"seg": seg}).set(s["share"])
        reg.gauge("critpath.segment.ms",
                  labels={"seg": seg}).set(s["median_s"] * 1e3)
    reg.gauge("critpath.coverage").set(profile["coverage_mean"])
    flusher = tsdb.TimeSeriesFlusher(
        kv, proc=proc, registry=reg, recorder=Recorder(None))
    return flusher.flush()
