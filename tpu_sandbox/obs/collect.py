"""The offline collector: merge per-process recorder logs onto one
clock, then render.

Input is a directory of ``*.jsonl`` files written by
:class:`tpu_sandbox.obs.record.Recorder` — one per process, each
timestamped with that process's OWN ``time.monotonic()``. Raw monotonic
clocks from different processes are mutually meaningless, so the merge
runs in two steps:

1. **Wall anchor** — each process's offset starts as the median
   ``wall - mono`` over its ``"C"`` calibration records (falling back to
   the ``"P"`` preamble pair when a process never calibrated).
2. **Sequencer repair** — calibration records carry the KV server's
   shared counter value (``kv.add`` is serialized by the single-threaded
   server, so sequencer order IS happened-before order). Walking the
   calibration points in sequencer order, any point whose unified time
   runs *backwards* relative to an earlier point bumps its process's
   offset forward by the deficit. NTP-grade skew that the wall anchor
   misses cannot reorder causally-related events after this pass.

Everything downstream — Chrome trace-event export, per-request
waterfalls, trace-chain validation, last-N-seconds postmortems — works
on the merged record list (each record gains ``"uts"``, the unified
timestamp in seconds).
"""

from __future__ import annotations

import json
import os
import statistics


# -- loading ------------------------------------------------------------------

def read_log(path: str, stats: dict | None = None) -> list[dict]:
    """Parse one recorder JSONL file. A torn final line (the process was
    SIGKILLed mid-write) is dropped, not fatal — postmortems read logs
    from processes that died badly. Pass a ``stats`` dict to have every
    skipped line counted under ``dropped_records``: a postmortem that
    silently loses records reads as "nothing happened here", which is
    exactly the wrong story to tell about a process that died mid-write."""
    records = []
    dropped = 0
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                dropped += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                dropped += 1
    if stats is not None:
        stats["dropped_records"] = stats.get("dropped_records", 0) + dropped
    return records


def load_dir(logdir: str,
             stats: dict | None = None) -> dict[str, list[dict]]:
    """Read every ``*.jsonl`` under ``logdir``, keyed by process key
    (``proc/pid`` — distinct even when two processes share a name).
    ``stats`` (optional) accumulates ``files`` and ``dropped_records``
    across the whole directory."""
    logs: dict[str, list[dict]] = {}
    for name in sorted(os.listdir(logdir)):
        if not name.endswith(".jsonl"):
            continue
        if stats is not None:
            stats["files"] = stats.get("files", 0) + 1
        for rec in read_log(os.path.join(logdir, name), stats):
            key = f"{rec.get('proc', '?')}/{rec.get('pid', 0)}"
            logs.setdefault(key, []).append(rec)
    return logs


# -- clock calibration --------------------------------------------------------

def clock_offsets(logs: dict[str, list[dict]]) -> dict[str, float]:
    """Per-process ``offset`` such that ``mono + offset`` is comparable
    across processes. Wall-anchored, then repaired against the KV
    sequencer's total order (see module docstring)."""
    offsets: dict[str, float] = {}
    for key, records in logs.items():
        deltas = [r["wall"] - r["mono"] for r in records
                  if r.get("ph") == "C"]
        if not deltas:
            deltas = [r["wall"] - r["mono"] for r in records
                      if r.get("ph") == "P"]
        offsets[key] = statistics.median(deltas) if deltas else 0.0

    # sequencer repair: unified time must be non-decreasing in seq order
    points = []
    for key, records in logs.items():
        for r in records:
            if r.get("ph") == "C":
                points.append((int(r["seq"]), key, float(r["mono"])))
    points.sort()
    high = None
    for _seq, key, mono in points:
        unified = mono + offsets[key]
        if high is not None and unified < high:
            offsets[key] += high - unified
            unified = high
        high = unified if high is None else max(high, unified)
    return offsets


# -- merging ------------------------------------------------------------------

def merge(logs: dict[str, list[dict]],
          offsets: dict[str, float] | None = None) -> list[dict]:
    """Flatten per-process logs into one list ordered by unified time.
    Each span/instant record gains ``uts`` (unified seconds) and
    ``pkey`` (its process key)."""
    if offsets is None:
        offsets = clock_offsets(logs)
    merged = []
    for key, records in logs.items():
        off = offsets.get(key, 0.0)
        for r in records:
            if r.get("ph") not in ("X", "i", "m"):
                continue
            out = dict(r)
            out["uts"] = float(r["ts"]) + off
            out["pkey"] = key
            merged.append(out)
    merged.sort(key=lambda r: (r["uts"], r.get("pkey", ""),
                               r.get("span") or ""))
    return merged


def load_merged(logdir: str, stats: dict | None = None) -> list[dict]:
    logs = load_dir(logdir, stats)
    return merge(logs, clock_offsets(logs))


# -- chrome trace-event export ------------------------------------------------

def to_chrome_trace(merged: list[dict]) -> dict:
    """Render merged records as Chrome/Perfetto trace-event JSON: one
    track (pid) per process, spans as ``"X"`` complete events, fault
    injections and other point records as ``"i"`` instants, metric
    samples (recorder phase ``"m"``) as ``"C"`` counter events — so
    Perfetto draws the time-series as counter tracks on the same
    timeline as the spans. Times are microseconds from the earliest
    record."""
    if not merged:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(r["uts"] for r in merged)
    pids: dict[str, int] = {}
    events = []
    for r in merged:
        pkey = r.get("pkey", "?")
        if pkey not in pids:
            pids[pkey] = len(pids) + 1
            events.append({"ph": "M", "name": "process_name",
                           "pid": pids[pkey], "tid": 0,
                           "args": {"name": pkey}})
        if r["ph"] == "m":
            events.append({
                "name": r.get("name", "?"), "ph": "C",
                "ts": (r["uts"] - base) * 1e6,
                "pid": pids[pkey], "tid": 0,
                "args": {"value": float(r.get("value", 0.0))},
            })
            continue
        ev = {
            "name": r.get("name", "?"),
            "ph": "X" if r["ph"] == "X" else "i",
            "ts": (r["uts"] - base) * 1e6,
            "pid": pids[pkey],
            "tid": r.get("tid", 0),
            "args": dict(r.get("args") or {}),
        }
        if r["ph"] == "X":
            ev["dur"] = float(r.get("dur", 0.0)) * 1e6
        else:
            ev["s"] = "p"  # process-scoped instant
        if r.get("trace"):
            ev["args"]["trace"] = r["trace"]
            ev["args"]["span"] = r.get("span")
            ev["args"]["parent"] = r.get("parent")
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- trace chains -------------------------------------------------------------

def trace_chains(merged: list[dict]) -> dict[str, list[dict]]:
    """Group span/instant records by trace id (records without a trace
    id — untraced internal activity — are excluded)."""
    chains: dict[str, list[dict]] = {}
    for r in merged:
        t = r.get("trace")
        if t:
            chains.setdefault(t, []).append(r)
    return chains


def chain_check(records: list[dict]) -> dict:
    """Validate one trace's causal integrity: how many roots it has and
    whether every non-root record's parent resolves to a span inside the
    same trace. A healthy request chain has exactly one root and no
    dangling parents."""
    span_ids = {r.get("span") for r in records if r.get("span")}
    roots = [r for r in records if not r.get("parent")]
    dangling = [r for r in records
                if r.get("parent") and r["parent"] not in span_ids]
    return {
        "roots": len(roots),
        "root_names": sorted(r.get("name", "?") for r in roots),
        "dangling": len(dangling),
        "names": sorted({r.get("name", "?") for r in records}),
        "connected": len(roots) == 1 and not dangling,
    }


# -- waterfalls ---------------------------------------------------------------

def _chain_depths(records: list[dict]) -> dict[str, int]:
    parent_of = {r.get("span"): r.get("parent") for r in records
                 if r.get("span")}
    depths: dict[str, int] = {}

    def depth(span_id, guard=0):
        if span_id in depths:
            return depths[span_id]
        p = parent_of.get(span_id)
        d = 0 if (p is None or p not in parent_of or guard > 64) \
            else depth(p, guard + 1) + 1
        depths[span_id] = d
        return d

    for sid in parent_of:
        depth(sid)
    return depths


def request_waterfall(merged: list[dict], *, rid: str | None = None,
                      trace: str | None = None) -> list[dict]:
    """One request's life as ordered rows: relative start, duration,
    depth in the causal chain, process, span name. Select by explicit
    trace id or by the ``rid`` stamped into span args at submit time."""
    if trace is None:
        if rid is None:
            raise ValueError("need rid or trace")
        for r in merged:
            if (r.get("args") or {}).get("rid") == rid and r.get("trace"):
                trace = r["trace"]
                break
        if trace is None:
            return []
    records = [r for r in merged if r.get("trace") == trace]
    if not records:
        return []
    depths = _chain_depths(records)
    span_ids = {r.get("span") for r in records if r.get("span")}
    base = min(r["uts"] for r in records)
    rows = []
    for r in records:
        rows.append({
            "t": r["uts"] - base,
            "dur": float(r.get("dur", 0.0)) if r["ph"] == "X" else 0.0,
            "depth": depths.get(r.get("span"), 0),
            "proc": r.get("pkey", "?"),
            "name": r.get("name", "?"),
            "ph": r["ph"],
            "span": r.get("span"),
            # a parent that never landed in the trace (the parent span
            # leaked, or its log tail was torn off with the process):
            # the row renders at depth 0 but says WHY, instead of
            # impersonating a root
            "orphan": bool(r.get("parent")) and r.get("parent")
            not in span_ids,
            "args": {k: v for k, v in (r.get("args") or {}).items()
                     if k != "rid"},
            "trace": trace,
        })
    rows.sort(key=lambda row: (row["t"], row["depth"]))
    return rows


def format_waterfall(rows: list[dict],
                     crit: set[str] | None = None) -> str:
    """Render waterfall rows; spans whose id is in ``crit`` (the
    critical-path span set from ``obs/critpath.py``) get a ``*`` prefix,
    orphaned rows an explicit ``[orphan]`` tag."""
    lines = []
    if rows:
        lines.append(f"trace {rows[0]['trace']}")
    for row in rows:
        mark = "·" if row["ph"] == "i" else \
            f"{row['dur'] * 1e3:8.3f}ms"
        indent = "  " * row["depth"]
        star = "*" if crit and row.get("span") in crit else " "
        orphan = "  [orphan]" if row.get("orphan") else ""
        lines.append(f"  +{row['t'] * 1e3:9.3f}ms {mark:>10} "
                     f"{star}{indent}{row['name']}  [{row['proc']}]"
                     f"{orphan}")
    return "\n".join(lines)


# -- postmortem ---------------------------------------------------------------

def last_window(merged: list[dict], seconds: float) -> list[dict]:
    """The final ``seconds`` of the merged timeline — measured back from
    the LAST record, not from now: the logs may be hours old by the time
    someone runs the postmortem."""
    if not merged:
        return []
    end = max(r["uts"] for r in merged)
    return [r for r in merged if r["uts"] >= end - seconds]


def format_timeline(records: list[dict]) -> str:
    """Causally-ordered text timeline for postmortems: one line per
    record, relative seconds, process, name, interesting args."""
    if not records:
        return "(no records in window)"
    base = min(r["uts"] for r in records)
    lines = []
    for r in records:
        args = r.get("args") or {}
        arg_s = " ".join(f"{k}={v}" for k, v in sorted(args.items()))
        kind = "|" if r["ph"] == "X" else "!"
        lines.append(f"+{r['uts'] - base:8.3f}s {kind} "
                     f"[{r.get('pkey', '?')}] {r.get('name', '?')}"
                     + (f"  {arg_s}" if arg_s else ""))
    return "\n".join(lines)
