"""The fleet metrics registry: counters, gauges, streaming-quantile
histograms.

Unlike the recorder (:mod:`tpu_sandbox.obs.record`), the registry is
ALWAYS on — an increment is a lock-guarded integer add, nanoseconds —
and absorbs the stats that used to live as ad-hoc attributes scattered
across the codebase: engine shed reasons, client retry/hedge counts,
transport put/claim audit, scheduler virtual-time per tenant. It is
scraped live through the gateway's ``OP_METRICS`` wire op
(``GatewayClient.metrics()``), which folds in the per-replica recorder
stats from the TTL'd load reports so one scrape sees the whole fleet.

Histograms keep exact count/sum/min/max plus a fixed-size reservoir
sample (deterministic seed — reproducible quantile estimates) so
``quantile(0.99)`` stays O(reservoir) regardless of observation count.

Metric NAMES are static ``snake.dotted`` literals — graftlint GL-O402
rejects f-strings and concatenation at registry call sites, because a
dynamic name mints a new series per distinct value and the time-series
store downstream would grow without bound. Bounded dimensions (shed
reason, tenant, replica tag) travel in ``labels=``, which become part
of the series key as ``name{k=v,...}`` with sorted label keys.
"""

from __future__ import annotations

import random
import threading


def series_key(name: str, labels: dict | None) -> str:
    """The registry/tsdb series key: ``name`` alone, or
    ``name{k=v,...}`` with label keys sorted so the same label set
    always produces the same series."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonically increasing integer."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n

    def snapshot(self):
        return self.value


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def snapshot(self):
        return self.value


class Histogram:
    """Streaming distribution: exact count/sum/min/max, quantiles from a
    bounded reservoir (Vitter's algorithm R with a fixed seed)."""

    __slots__ = ("name", "count", "total", "min", "max",
                 "_reservoir", "_cap", "_rng", "_lock")

    def __init__(self, name: str, reservoir: int = 512):
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self._reservoir: list[float] = []
        self._cap = reservoir
        self._rng = random.Random(0xB0B)
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.total += v
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
            if len(self._reservoir) < self._cap:
                self._reservoir.append(v)
            else:
                j = self._rng.randrange(self.count)
                if j < self._cap:
                    self._reservoir[j] = v

    def quantile(self, q: float) -> float | None:
        with self._lock:
            if not self._reservoir:
                return None
            s = sorted(self._reservoir)
        idx = min(len(s) - 1, max(0, int(q * (len(s) - 1) + 0.5)))
        return s[idx]

    def snapshot(self):
        with self._lock:
            mean = self.total / self.count if self.count else None
        return {"count": self.count, "sum": self.total,
                "min": self.min, "max": self.max, "mean": mean,
                "p50": self.quantile(0.5), "p90": self.quantile(0.9),
                "p99": self.quantile(0.99)}


class MetricsRegistry:
    """Get-or-create home for every metric in the process. ``snapshot()``
    is the scrape body: plain JSON-serializable dict keyed by kind."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str, labels: dict | None = None) -> Counter:
        key = series_key(name, labels)
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = Counter(key)
            return c

    def gauge(self, name: str, labels: dict | None = None) -> Gauge:
        key = series_key(name, labels)
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = Gauge(key)
            return g

    def histogram(self, name: str, reservoir: int = 512,
                  labels: dict | None = None) -> Histogram:
        key = series_key(name, labels)
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = self._histograms[key] = Histogram(key, reservoir)
            return h

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = dict(self._histograms)
        return {
            "counters": {k: c.snapshot() for k, c in sorted(counters.items())},
            "gauges": {k: g.snapshot() for k, g in sorted(gauges.items())},
            "histograms": {k: h.snapshot()
                           for k, h in sorted(histograms.items())},
        }

    def reset(self) -> None:
        """Drop every metric (tests / bench arm isolation)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
