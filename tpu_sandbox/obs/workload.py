"""The canonical replayable workload trace: what arrived, when, and how
it ended — the input half of a serving run, separated from how the fleet
handled it.

ROADMAP item 6's cluster twin replays these against a simulated fleet;
for that to mean anything the export must be (a) derivable from any
merged flight-recorder trace, (b) schema-versioned so a twin built next
quarter refuses a trace it cannot interpret, and (c) **canonical**: the
same logical workload always serializes to the same bytes, so traces
diff cleanly and a parse → re-export round trip is the identity.

One record per request:

- ``t_s``       arrival relative to the first request, seconds (6 dp)
- ``rid``       the request id
- ``tenant``    traffic owner — fleets are single-tenant today, so this
                carries the fleet label until a real tenancy axis lands
- ``fleet``     serving fleet label
- ``chain``     deepest prefix-chain block hash (the routing key — two
                requests sharing it share a cacheable prefix)
- ``prompt_tokens`` / ``decode_tokens``  size of the ask and the answer
- ``outcome``   ``ok`` | ``door:<reason>`` | ``shed:<reason>`` | ``open``
- ``deadline_s``  the SLO the client attached, when it attached one

All fields come from span args the gateway and engine already stamp
(``route`` carries ``plen``/``chain``/``fleet``/``deadline_s``;
``decode`` carries ``tokens``; the terminal verdict carries the
outcome), so export is a pure function of the merged record list.
"""

from __future__ import annotations

import json
import random

from tpu_sandbox.obs import critpath

#: bump on any field change; loaders hard-reject unknown versions
SCHEMA = "tpu-sandbox.workload/1"

_FIELDS = ("t_s", "rid", "tenant", "fleet", "chain",
           "prompt_tokens", "decode_tokens", "outcome", "deadline_s")


def from_trace(merged: list[dict], *, source: str = "") -> dict:
    """Derive the workload trace from a merged record list. Requests are
    ordered by (arrival, rid) — the replay order — so the export is
    deterministic for a given trace."""
    rid_to_trace = critpath.request_traces(merged)
    by_trace: dict[str, list[dict]] = {}
    for r in merged:
        t = r.get("trace")
        if t:
            by_trace.setdefault(t, []).append(r)
    rows = []
    t_first = None
    for rid, trace in rid_to_trace.items():
        recs = by_trace.get(trace, [])
        submit = next((r for r in recs if r.get("name") == "submit"), None)
        route = next((r for r in recs if r.get("name") == "route"), None)
        arrival = float((submit or (recs[0] if recs else {})).get("uts", 0.0))
        if t_first is None or arrival < t_first:
            t_first = arrival
        rargs = (route.get("args") or {}) if route else {}
        decode = next((r for r in recs if r.get("name") == "decode"), None)
        term = critpath._terminal(recs) if recs else {}
        outcome = "open"
        name = term.get("name", "")
        fam = critpath._family(name)
        if fam in ("door", "shed"):
            outcome = name
        elif name == "verdict":
            v = str((term.get("args") or {}).get("verdict", "ok"))
            outcome = "ok" if v.lower() == "ok" else f"shed:{v}"
        fleet = str(rargs.get("fleet", "default"))
        deadline = rargs.get("deadline_s")
        rows.append({
            "t_s": arrival,  # absolute for now; rebased below
            "rid": str(rid),
            "tenant": fleet,
            "fleet": fleet,
            "chain": str(rargs.get("chain", "")),
            "prompt_tokens": int(rargs.get("plen", 0)),
            "decode_tokens": int((decode.get("args") or {}).get("tokens", 0))
            if decode else 0,
            "outcome": outcome,
            "deadline_s": None if deadline is None
            else round(float(deadline), 6),
        })
    base = t_first or 0.0
    for row in rows:
        row["t_s"] = round(row["t_s"] - base, 6)
    rows.sort(key=lambda r: (r["t_s"], r["rid"]))
    return {"schema": SCHEMA, "source": source, "requests": rows}


def synthesize(seed: int, n: int, *, duration_s: float = 1.0,
               fleet: str = "", n_chains: int = 4,
               prompt_tokens: tuple[int, int] = (12, 48),
               decode_tokens: tuple[int, int] = (4, 16),
               deadline_s: float | None = None) -> dict:
    """A seeded canonical workload: ``n`` arrivals over ``duration_s``,
    each tagged with one of ``n_chains`` shared prefix chains (so a
    replayed fleet has real prefix-affinity structure to route on). Same
    seed, same trace, byte for byte — the chaos harness replays these
    against a live fleet and compares audits across runs, which only
    means something if the input side is pinned. Outcomes are ``open``:
    a synthesized trace records what arrives, not how a fleet will
    answer it."""
    rng = random.Random(seed)
    rows = []
    for i in range(n):
        rows.append({
            "t_s": round(rng.uniform(0.0, duration_s), 6),
            "rid": f"c{seed}-r{i:04d}",
            "tenant": fleet or "default",
            "fleet": fleet or "default",
            "chain": f"chain{rng.randrange(n_chains)}",
            "prompt_tokens": rng.randint(*prompt_tokens),
            "decode_tokens": rng.randint(*decode_tokens),
            "outcome": "open",
            "deadline_s": deadline_s,
        })
    rows.sort(key=lambda r: (r["t_s"], r["rid"]))
    # rebase so the first arrival is t=0, like a from_trace export
    base = rows[0]["t_s"] if rows else 0.0
    for row in rows:
        row["t_s"] = round(row["t_s"] - base, 6)
    return {"schema": SCHEMA, "source": f"synthesized:seed={seed}",
            "requests": rows}


def dumps(trace: dict) -> str:
    """Canonical bytes: sorted keys, compact separators, one trailing
    newline. ``loads(dumps(t))`` then ``dumps`` again is byte-identical
    — floats were already rounded at build time and JSON round-trips
    them exactly."""
    _validate(trace)
    return json.dumps(trace, sort_keys=True, separators=(",", ":")) + "\n"


def loads(text: str) -> dict:
    trace = json.loads(text)
    _validate(trace)
    return trace


def save(trace: dict, path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(dumps(trace))


def load(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as fh:
        return loads(fh.read())


def replay_order(trace: dict) -> list[dict]:
    """Requests in arrival order — what a twin feeds its open-loop
    driver. Already the storage order; re-sorted here so a hand-edited
    trace still replays correctly."""
    return sorted(trace["requests"], key=lambda r: (r["t_s"], r["rid"]))


def _validate(trace: dict) -> None:
    if not isinstance(trace, dict) or trace.get("schema") != SCHEMA:
        raise ValueError(
            f"unknown workload schema {trace.get('schema')!r} "
            f"(this reader understands {SCHEMA})")
    for i, row in enumerate(trace.get("requests", ())):
        missing = [f for f in _FIELDS if f not in row]
        if missing:
            raise ValueError(f"request[{i}] missing fields {missing}")
        if not isinstance(row["t_s"], (int, float)) or row["t_s"] < 0:
            raise ValueError(f"request[{i}] bad arrival {row['t_s']!r}")
