"""The health plane: declarative rules over durable time series,
anomaly detectors for the known policy pathologies, and claim-once
alerts that control planes consume.

PR 12 made the system legible; this module makes it *watched*. A
leader-elected :class:`HealthMonitor` (any number of candidates,
``LeaseElection`` on ``obs/health/leader``) evaluates two kinds of
checks on a fixed cadence:

* **rules** over the tsdb ring (:mod:`tpu_sandbox.obs.tsdb`):
  :class:`BurnRateRule` is the classic multi-window SLO burn — the
  bad-event fraction must exceed ``burn × budget`` in BOTH a short and
  a long window before it fires (fast detection without flapping on a
  single bad bucket); :class:`ThresholdRule` compares the newest gauge
  value or histogram-digest field (p99 TTFT vs the deadline, goodput
  vs calibrated capacity, recorder drops > 0) against a bound.
* **detectors** over durable control-plane state, one per named
  pathology: :class:`OscillationDetector` counts autoscale
  direction-flips in the event log; :class:`StarvationDetector` watches
  the scheduler's vtime ledger for a tenant whose service stalls while
  it still has queued work; :class:`CascadeDetector` diffs per-job
  preemption counts for preempt→requeue→preempt cycles.

Alert protocol — exactly-once through monitor failover:

1. the alert RECORD ``obs/alert/rec/<rule>/<subject>/<window_idx>`` is
   written with a plain idempotent ``set``: every monitor evaluating
   the same window writes byte-identical content, so a monitor killed
   mid-evaluation cannot lose or corrupt the record;
2. the one-time notification (registry counter + recorder instant) is
   gated by ``kv.add`` on the matching CLAIM key — exactly one monitor
   observes 1, no matter how many evaluate the window (GL-R301: the
   claim key carries ``window_idx`` as its scope discriminator);
3. the ACTIVE key ``obs/health/active/<rule>/<subject>`` is a TTL'd
   condition flag, refreshed every evaluation while the rule still
   fires. Control planes read ONLY this key: the gateway excludes
   replicas with an active ``replica_burn``, the autoscaler backs off
   on active ``autoscale_oscillation``, the scheduler stamps a
   ``starved`` job event on active ``tenant_starvation``. Recovery is
   TTL expiry — no delete ordering to race on.

Everything takes an injectable ``clock`` so the seeded-pathology tests
drive whole detection windows in microseconds.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass

from tpu_sandbox.runtime.election import LeaseElection

from . import tsdb
from .metrics import get_registry
from .record import get_recorder

K_ALERT_PREFIX = "obs/alert/rec/"
K_CLAIM_PREFIX = "obs/alert/claim/"
K_ACTIVE_PREFIX = "obs/health/active/"
LEADER_PREFIX = "obs/health/leader"


def k_alert_record(rule: str, subject: str, window_idx: int) -> str:
    return f"{K_ALERT_PREFIX}{rule}/{subject}/{window_idx}"


def k_alert_claim(rule: str, subject: str, window_idx: int) -> str:
    return f"{K_CLAIM_PREFIX}{rule}/{subject}/{window_idx}"


def k_active(rule: str, subject: str) -> str:
    return f"{K_ACTIVE_PREFIX}{rule}/{subject}"


def raise_alert(kv, rule: str, subject: str, window_idx: int,
                body: dict, *, active_ttl: float) -> bool:
    """The durable alert write: idempotent record, claim-once
    notification gate, TTL'd active flag — in that order, so a monitor
    killed between any two steps leaves a state a successor completes
    without double-firing. Returns True iff THIS caller won the claim
    (and therefore owns the one-time notification side effects)."""
    blob = json.dumps(body, sort_keys=True)
    kv.set(k_alert_record(rule, subject, window_idx), blob)
    claimed = kv.add(k_alert_claim(rule, subject, window_idx)) == 1
    kv.set_ttl(k_active(rule, subject), blob, active_ttl)
    return claimed


def alerts(kv, *, rule: str | None = None) -> list[dict]:
    """Every durable alert record (optionally one rule's), oldest
    first — the postmortem feed."""
    prefix = K_ALERT_PREFIX + (f"{rule}/" if rule else "")
    out = []
    for key in kv.keys(prefix):
        raw = kv.try_get(key)
        if raw is None:
            continue
        try:
            out.append(json.loads(raw))
        except ValueError:
            continue
    out.sort(key=lambda a: (a.get("wall", 0.0), a.get("rule", ""),
                            a.get("subject", "")))
    return out


def active_alerts(kv) -> list[dict]:
    """Currently-held alert conditions (TTL'd flags still live)."""
    out = []
    for key in kv.keys(K_ACTIVE_PREFIX):
        raw = kv.try_get(key)
        if raw is None:
            continue
        try:
            out.append(json.loads(raw))
        except ValueError:
            continue
    out.sort(key=lambda a: (a.get("rule", ""), a.get("subject", "")))
    return out


def active_subjects(kv, rule: str) -> set[str]:
    """The subjects currently flagged by ``rule`` — what control planes
    poll (replica tags for ``replica_burn``, tenants for
    ``tenant_starvation``, ``fleet`` for fleet-wide rules)."""
    prefix = f"{K_ACTIVE_PREFIX}{rule}/"
    return {key[len(prefix):] for key in kv.keys(prefix)}


# -- rules over the tsdb ------------------------------------------------------

@dataclass(frozen=True)
class BurnRateRule:
    """Multi-window SLO burn over two counter series: fire when
    ``bad / (bad + good)`` exceeds ``burn * budget`` in BOTH the short
    and the long trailing window. ``per_proc`` evaluates each producing
    process separately (per-replica burn); otherwise the subject is
    ``fleet``. Label variants of each series are summed."""

    name: str
    bad: str
    good: str
    budget: float
    burn: float = 4.0
    short_buckets: int = 3
    long_buckets: int = 12
    per_proc: bool = False

    def evaluate(self, kv, now_bucket: int) -> list[tuple[str, dict]]:
        bad_rows = tsdb.read_series(kv, self.bad)
        good_rows = tsdb.read_series(kv, self.good)
        if self.per_proc:
            procs = sorted({r["proc"] for r in bad_rows}
                           | {r["proc"] for r in good_rows})
            fired = []
            for p in procs:
                res = self._burn(bad_rows, good_rows, now_bucket, proc=p)
                if res is not None:
                    fired.append((p, res))
            return fired
        res = self._burn(bad_rows, good_rows, now_bucket, proc=None)
        return [] if res is None else [("fleet", res)]

    def _burn(self, bad_rows, good_rows, now_bucket, *, proc):
        def _sum(rows, buckets):
            since = now_bucket - buckets + 1
            return sum(float(r["v"]) for r in rows
                       if r["kind"] == "counter" and r["bucket"] >= since
                       and (proc is None or r["proc"] == proc))

        def _rate(buckets):
            b = _sum(bad_rows, buckets)
            tot = b + _sum(good_rows, buckets)
            return None if tot <= 0 else b / tot

        short, long = _rate(self.short_buckets), _rate(self.long_buckets)
        if short is None or long is None:
            return None  # no traffic in a window -> no verdict
        threshold = self.burn * self.budget
        if short >= threshold and long >= threshold:
            return {"short_rate": round(short, 6),
                    "long_rate": round(long, 6),
                    "budget": self.budget, "burn": self.burn}
        return None


@dataclass(frozen=True)
class ThresholdRule:
    """Compare the newest gauge value (or ``field`` of the newest
    histogram digest) against a bound. ``op`` is ``">"`` (alert when
    above, e.g. p99 TTFT vs deadline, recorder drops vs 0) or ``"<"``
    (alert when below, e.g. goodput vs calibrated capacity)."""

    name: str
    series: str
    threshold: float
    op: str = ">"
    field: str | None = None
    per_proc: bool = False

    def evaluate(self, kv, now_bucket: int) -> list[tuple[str, dict]]:
        del now_bucket  # thresholds read the latest point, not a window
        rows = tsdb.read_series(kv, self.series)
        subjects = sorted({r["proc"] for r in rows}) if self.per_proc \
            else [None]
        fired = []
        for p in subjects:
            v = tsdb.latest_value(rows, proc=p, field=self.field)
            if v is None:
                continue
            breached = v > self.threshold if self.op == ">" \
                else v < self.threshold
            if breached:
                fired.append((p if p is not None else "fleet",
                              {"value": v, "threshold": self.threshold,
                               "op": self.op, "series": self.series}))
        return fired


@dataclass(frozen=True)
class BaselineDeltaRule:
    """Compare one process's newest series value against the pooled
    baseline of a set of incumbent processes — the canary-analysis shape:
    the subject is the freshly-swapped replica, the baseline is everyone
    still on the incumbent version. Fires when the subject breaches
    ``baseline * threshold`` (``mode="ratio"``) or ``baseline +
    threshold`` (``mode="delta"``) in the direction of ``op``. Same
    no-verdict discipline as :class:`BurnRateRule`: a side with no data
    (no traffic yet, TTL'd rows expired) yields no verdict rather than a
    false one."""

    name: str
    series: str
    subject: str                # tsdb proc name of the canary
    baseline: tuple[str, ...]   # tsdb proc names of the incumbents
    threshold: float
    mode: str = "delta"         # "delta" | "ratio"
    op: str = ">"               # ">" fires above the bound, "<" below
    field: str | None = None    # histogram digest field (None -> gauge)

    def evaluate(self, kv, now_bucket: int) -> list[tuple[str, dict]]:
        del now_bucket  # like ThresholdRule: newest point, not a window
        rows = tsdb.read_series(kv, self.series)
        subject_v = tsdb.latest_value(rows, proc=self.subject,
                                      field=self.field)
        base_vals = [v for p in self.baseline
                     if (v := tsdb.latest_value(rows, proc=p,
                                                field=self.field))
                     is not None]
        if subject_v is None or not base_vals:
            return []  # no traffic on a side -> no verdict
        base = sum(base_vals) / len(base_vals)
        bound = base * self.threshold if self.mode == "ratio" \
            else base + self.threshold
        breached = subject_v > bound if self.op == ">" else subject_v < bound
        if not breached:
            return []
        return [(self.subject,
                 {"value": subject_v, "baseline": base, "bound": bound,
                  "mode": self.mode, "op": self.op, "series": self.series,
                  "n_baseline": len(base_vals)})]

    def has_data(self, kv) -> bool:
        """True when BOTH sides have live points — the controller counts
        a canary evaluation as evidence only when this holds."""
        rows = tsdb.read_series(kv, self.series)
        if tsdb.latest_value(rows, proc=self.subject,
                             field=self.field) is None:
            return False
        return any(tsdb.latest_value(rows, proc=p, field=self.field)
                   is not None for p in self.baseline)


def default_rules(*, ttft_deadline_s: float | None = None,
                  goodput_floor: float | None = None,
                  shed_budget: float = 0.05) -> list:
    """The stock SLO rule set: fleet and per-replica shed burn, recorder
    drop visibility, and (when bounds are given) p99-TTFT and goodput
    thresholds."""
    rules: list = [
        BurnRateRule(name="shed_burn", bad="engine.shed",
                     good="engine.done", budget=shed_budget),
        BurnRateRule(name="replica_burn", bad="engine.shed",
                     good="engine.done", budget=shed_budget,
                     per_proc=True),
        ThresholdRule(name="recorder_drops", series="obs.recorder.dropped",
                      threshold=0.0, op=">", per_proc=True),
    ]
    if ttft_deadline_s is not None:
        rules.append(ThresholdRule(name="ttft_slo", series="engine.ttft",
                                   threshold=ttft_deadline_s, op=">",
                                   field="p99"))
    if goodput_floor is not None:
        rules.append(ThresholdRule(name="goodput_floor",
                                   series="serve.goodput",
                                   threshold=goodput_floor, op="<"))
    return rules


# -- anomaly detectors over durable control-plane state -----------------------

class OscillationDetector:
    """Autoscale oscillation: the replica count sign-flipping inside a
    rolling window of evaluations. Reads the durable
    ``serve/autoscale/events/<n>`` log incrementally (the tail pointer
    is our cursor); ``min_replicas`` bootstrap events never count."""

    name = "autoscale_oscillation"

    def __init__(self, *, window_evals: int = 8, flip_threshold: int = 3):
        self.window_evals = int(window_evals)
        self.flip_threshold = int(flip_threshold)
        self._seen_tail = 0
        self._recent: deque[tuple[int, str]] = deque()
        self._evals = 0

    def observe(self, kv) -> list[tuple[str, dict]]:
        from tpu_sandbox.serve.autoscale import K_EVENT_TAIL, k_event

        self._evals += 1
        tail = int(kv.try_get(K_EVENT_TAIL) or b"0")
        for n in range(self._seen_tail, tail):
            raw = kv.try_get(k_event(n))
            if raw is None:
                continue
            ev = json.loads(raw)
            if ev.get("action") in ("scale_up", "scale_down") \
                    and ev.get("reason") != "min_replicas":
                self._recent.append((self._evals, ev["action"]))
        self._seen_tail = tail
        horizon = self._evals - self.window_evals
        while self._recent and self._recent[0][0] <= horizon:
            self._recent.popleft()
        actions = [a for _, a in self._recent]
        flips = sum(1 for prev, cur in zip(actions, actions[1:])
                    if prev != cur)
        if flips >= self.flip_threshold:
            return [("fleet", {"flips": flips,
                               "window_evals": self.window_evals,
                               "actions": actions})]
        return []


class StarvationDetector:
    """Tenant starvation: a tenant with queued work whose normalized
    vtime stops advancing while another tenant's does. Under weighted
    fair sharing every ACTIVE tenant's vtime advances at the same rate
    (the charge is ``hosts·dt/share``), so a starved tenant shows up as
    a per-window vtime delta at least ``ratio``× below the busiest
    tenant's — for ``consecutive`` evaluations, to ride out admission
    churn."""

    name = "tenant_starvation"

    def __init__(self, *, ratio: float = 5.0, consecutive: int = 2):
        self.ratio = float(ratio)
        self.consecutive = int(consecutive)
        self._prev: dict[str, float] | None = None
        self._streak: dict[str, int] = {}

    def observe(self, kv) -> list[tuple[str, dict]]:
        from tpu_sandbox.runtime.scheduler import (K_QUEUED_PREFIX,
                                                   K_VTIME_PREFIX)

        vt: dict[str, float] = {}
        for key in kv.keys(K_VTIME_PREFIX):
            raw = kv.try_get(key)
            if raw is None:
                continue
            try:
                vt[key[len(K_VTIME_PREFIX):]] = float(raw)
            except ValueError:
                continue
        queued: dict[str, int] = {}
        for key in kv.keys(K_QUEUED_PREFIX):
            raw = kv.try_get(key)
            if raw is None:
                continue
            try:
                queued[key[len(K_QUEUED_PREFIX):]] = int(raw)
            except ValueError:
                continue
        if self._prev is None:
            self._prev = vt
            return []
        deltas = {t: v - self._prev.get(t, v) for t, v in vt.items()}
        self._prev = vt
        peak = max(deltas.values(), default=0.0)
        fired = []
        for tenant in sorted(set(deltas) | set(queued)):
            d = deltas.get(tenant, 0.0)
            starving = (queued.get(tenant, 0) > 0 and peak > 0.0
                        and d * self.ratio <= peak)
            streak = self._streak.get(tenant, 0) + 1 if starving else 0
            self._streak[tenant] = streak
            if streak >= self.consecutive:
                fired.append((tenant, {"vtime_delta": d,
                                       "peak_delta": peak,
                                       "queued": queued.get(tenant, 0),
                                       "ratio": self.ratio}))
        return fired


class CascadeDetector:
    """Preemption cascade: one job accumulating preempt→requeue→preempt
    cycles faster than ``cycles`` per rolling window. The scheduler
    bumps a durable per-job counter at every ``preempt_sent``; we diff
    it per evaluation."""

    name = "preemption_cascade"

    def __init__(self, *, cycles: int = 3, window_evals: int = 8):
        self.cycles = int(cycles)
        self.window_evals = int(window_evals)
        self._prev: dict[str, int] = {}
        self._recent: dict[str, deque] = {}
        self._evals = 0

    def observe(self, kv) -> list[tuple[str, dict]]:
        from tpu_sandbox.runtime.scheduler import K_PREEMPTS_PREFIX

        self._evals += 1
        fired = []
        horizon = self._evals - self.window_evals
        counts: dict[str, int] = {}
        for key in kv.keys(K_PREEMPTS_PREFIX):
            raw = kv.try_get(key)
            if raw is None:
                continue
            try:
                counts[key[len(K_PREEMPTS_PREFIX):]] = int(raw)
            except ValueError:
                continue
        for job_id, c in counts.items():
            delta = c - self._prev.get(job_id, 0)
            self._prev[job_id] = c
            hist = self._recent.setdefault(job_id, deque())
            if delta > 0:
                hist.append((self._evals, delta))
        for job_id, hist in self._recent.items():
            while hist and hist[0][0] <= horizon:
                hist.popleft()
            in_window = sum(d for _, d in hist)
            if in_window >= self.cycles:
                fired.append((job_id, {"preemptions": in_window,
                                       "window_evals": self.window_evals}))
        return fired


def default_detectors() -> list:
    return [OscillationDetector(), StarvationDetector(), CascadeDetector()]


# -- the monitor --------------------------------------------------------------

class HealthMonitor:
    """Leader-elected evaluation loop. Run any number of candidates;
    :meth:`step` is a no-op (returns None) on non-leaders. On the
    leader it evaluates every rule and detector once and returns the
    list of alert bodies THIS monitor claimed (usually empty).

    Detector state is monitor-local; after a failover the successor
    rebuilds it within one window, which is why the acceptance bound is
    detection ≤ 2 evaluation windows."""

    def __init__(self, kv, member_id: str = "health-0", *,
                 window_s: float = 1.0, bucket_s: float = 1.0,
                 rules=None, detectors=None, election_ttl: float = 3.0,
                 active_windows: float = 3.0, clock=time.time):
        self.kv = kv
        self.window_s = float(window_s)
        self.bucket_s = float(bucket_s)
        self.rules = list(default_rules() if rules is None else rules)
        self.detectors = list(default_detectors() if detectors is None
                              else detectors)
        self.election = LeaseElection(kv, member_id, ttl=election_ttl,
                                      prefix=LEADER_PREFIX)
        self.active_ttl = float(active_windows) * self.window_s
        self.clock = clock
        self.evals = 0

    def step(self, *, candidate: bool = True) -> list[dict] | None:
        if not self.election.step(candidate=candidate):
            return None
        self.evals += 1
        now = float(self.clock())
        window_idx = int(now // self.window_s)
        now_bucket = int(now // self.bucket_s)
        claimed = []
        for rule in self.rules:
            for subject, payload in rule.evaluate(self.kv, now_bucket):
                body = self._fire(rule.name, subject, window_idx,
                                  payload, now)
                if body is not None:
                    claimed.append(body)
        for det in self.detectors:
            for subject, payload in det.observe(self.kv):
                body = self._fire(det.name, subject, window_idx,
                                  payload, now)
                if body is not None:
                    claimed.append(body)
        return claimed

    def resign(self) -> None:
        self.election.resign()

    def _fire(self, rule: str, subject: str, window_idx: int,
              payload: dict, now: float) -> dict | None:
        """Onset vs refresh: a condition already active just has its
        TTL flag renewed — new records (and notifications) happen only
        on a rising edge."""
        existing = self.kv.try_get(k_active(rule, subject))
        if existing is not None:
            self.kv.set_ttl(k_active(rule, subject), existing,
                            self.active_ttl)
            return None
        body = dict(payload)
        body.update(rule=rule, subject=subject,
                    window_idx=int(window_idx), wall=now)
        if raise_alert(self.kv, rule, subject, window_idx, body,
                       active_ttl=self.active_ttl):
            get_registry().counter("health.alerts",
                                   labels={"rule": rule}).inc()
            get_recorder().instant("health:alert",
                                   args={"rule": rule, "subject": subject})
            return body
        return None
