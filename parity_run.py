"""Recorded loss-curve parity experiment: this framework vs a torch replica.

VERDICT r01 "what's missing" #1: step-level unit parity existed, but no
END-TO-END loss curve of the reference experiment was ever recorded. This
script is that record. It trains the reference schedule (ConvNet, CE,
plain SGD — mnist_onegpu.py:34-84) twice from bit-identical init on
bit-identical batches:

  - the tpu_sandbox trainer (flax/optax, the framework under test), and
  - a torch replica with the weights copied over
    (tpu_sandbox/utils/parity.py),

and writes both loss curves to a JSONL file plus a summary line with the
maximum absolute and relative per-step deviation.

Data: the environment has zero network egress, so torchvision's MNIST
download (reference mnist_onegpu.py:92-95) cannot run; the deterministic
synthetic MNIST (tpu_sandbox/data/mnist.py::synthetic_mnist) stands in, and
``--data-dir`` accepts real IDX files wherever they can be staged. The
28x28 -> NxN resize is applied ONCE on the host with jax.image.resize and
the SAME resized arrays feed both frameworks: resize-kernel differences
between torchvision PIL and XLA are an input-pipeline property, not a
training-dynamics property, and this experiment isolates the latter.

Default config scales the reference experiment to CPU-feasible size
(128x128, bs=5, 400 steps); on a TPU with time to spare, pass
--image-size 3000 --steps 12000 for the full reference shape (the torch
side will be slow: it is the control, not the subject).

Usage::

    python parity_run.py --out parity_curves.jsonl
"""

import argparse
import json


def main():
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--image-size", type=int, default=128)
    p.add_argument("--batch-size", type=int, default=5)
    p.add_argument("--steps", type=int, default=400)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--log-every", type=int, default=20)
    p.add_argument("--n-data", type=int, default=2000)
    p.add_argument("--data-dir", type=str, default=None,
                   help="real MNIST IDX dir (falls back to synthetic)")
    p.add_argument("--out", type=str, default="parity_curves.jsonl")
    p.add_argument("--force-cpu", action="store_true")
    args = p.parse_args()

    from tpu_sandbox.utils.cli import ensure_devices

    if args.force_cpu:
        ensure_devices(1, force_cpu=True)

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    import torch

    from tpu_sandbox.data.mnist import load_mnist, normalize, synthetic_mnist
    from tpu_sandbox.models import ConvNet
    from tpu_sandbox.train import TrainState, make_train_step
    from tpu_sandbox.utils.parity import torch_twin

    try:
        images, labels = load_mnist("train", args.data_dir)
        source = "mnist-idx"
    except FileNotFoundError:
        images, labels = synthetic_mnist(n=args.n_data, seed=0)
        source = "synthetic"
    images = normalize(images[: args.n_data])
    labels = labels[: args.n_data].astype(np.int64)

    # one host-side resize feeds BOTH frameworks identical pixels
    n = args.image_size
    resized = np.asarray(
        jax.image.resize(
            jnp.asarray(images), (len(images), n, n, 1), method="bilinear"
        )
    )

    rng = np.random.default_rng(0)
    order = [rng.permutation(len(resized))[: args.batch_size]
             for _ in range(args.steps)]

    model = ConvNet()
    variables = model.init(
        jax.random.key(0), jnp.zeros((1, n, n, 1)), train=False
    )
    tm = torch_twin(torch, variables["params"], hw=n // 4)

    # --- framework under test -------------------------------------------
    tx = optax.sgd(args.lr)
    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, n, n, 1)), tx
    )
    state = state.replace(params=variables["params"],
                          batch_stats=variables["batch_stats"])
    step = make_train_step(model, tx, donate=False)
    jax_losses = []
    for i, sel in enumerate(order):
        state, loss = step(
            state, jnp.asarray(resized[sel]),
            jnp.asarray(labels[sel].astype(np.int32)),
        )
        jax_losses.append(float(loss))
        if (i + 1) % args.log_every == 0:
            print(f"[tpu_sandbox] Step [{i + 1}/{args.steps}], "
                  f"Loss: {jax_losses[-1]:.4f}", flush=True)

    # --- torch control ---------------------------------------------------
    tm.train()
    opt = torch.optim.SGD(tm.parameters(), lr=args.lr)
    crit = torch.nn.CrossEntropyLoss()
    torch_losses = []
    for i, sel in enumerate(order):
        opt.zero_grad()
        out = tm(torch.from_numpy(resized[sel].transpose(0, 3, 1, 2).copy()))
        loss = crit(out, torch.from_numpy(labels[sel]))
        loss.backward()
        opt.step()
        torch_losses.append(float(loss))
        if (i + 1) % args.log_every == 0:
            print(f"[torch-ref]   Step [{i + 1}/{args.steps}], "
                  f"Loss: {torch_losses[-1]:.4f}", flush=True)

    ja, ta = np.asarray(jax_losses), np.asarray(torch_losses)
    abs_dev = np.abs(ja - ta)
    rel_dev = abs_dev / np.maximum(np.abs(ta), 1e-8)
    summary = {
        "source": source,
        "image_size": n,
        "batch_size": args.batch_size,
        "steps": args.steps,
        "lr": args.lr,
        "final_loss_tpu_sandbox": round(float(ja[-1]), 6),
        "final_loss_torch": round(float(ta[-1]), 6),
        "max_abs_dev": round(float(abs_dev.max()), 6),
        "max_rel_dev": round(float(rel_dev.max()), 6),
        "mean_abs_dev": round(float(abs_dev.mean()), 6),
    }
    with open(args.out, "w") as f:
        for i, (jl, tl) in enumerate(zip(jax_losses, torch_losses)):
            f.write(json.dumps({"step": i + 1, "tpu_sandbox": round(jl, 6),
                                "torch": round(tl, 6)}) + "\n")
        f.write(json.dumps({"summary": summary}) + "\n")
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
