"""Per-op HBM traffic breakdown of the AOT-compiled ConvNet train step.

VERDICT r02 next-#3: after the s2d plan + fused tail, XLA's aggregate cost
analysis still charges ~5.45 GB/img (bs=16). This tool answers WHERE, from
the optimized HLO itself: every top-level instruction in the ENTRY
computation materializes its output once and reads its operands, so
(padded output bytes + padded operand bytes) per instruction is the
traffic model — the same accounting XLA's own `bytes accessed` uses,
but attributable to individual ops and op classes (conv fwd / dgrad /
wgrad, packed-form copies, Mosaic kernels, fusions).

Padded bytes honor the TPU tiling in the dump: layout T(8,128) pads the
two minor physical dims to (8·(32/bits), 128) — the [.,.,.,16]-lane
pathology this repo's s2d plan exists to kill shows up directly here.

Chipless (uses the local libtpu via jax.experimental.topologies, like
tools/aot_v5e.py — single-process: do not run two AOT tools at once).
Estimates, not measurements; the bench owns measured truth.

Usage: python tools/hlo_traffic.py [--plan s2d] [--batch 16] [--top 25]
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)            # import aot_v5e as a sibling
sys.path.insert(0, os.path.dirname(_HERE))  # import tpu_sandbox from the repo

# aot_v5e (and with it libtpu topologies) is imported lazily in main():
# the pure-text analyzers below (shape_bytes / collective_bytes) must be
# importable on CPU-only boxes — bench.py's grad-compress traffic metric
# runs them against a CPU SPMD compile.

_SHAPE = re.compile(r"(\w+)\[([\d,]*)\](?:\{([^}]*)\})?")
_BITS = {
    "pred": 8, "s8": 8, "u8": 8, "bf16": 16, "f16": 16, "s16": 16,
    "u16": 16, "f32": 32, "s32": 32, "u32": 32, "f64": 64, "s64": 64,
    "u64": 64,
}


def shape_bytes(text: str) -> int:
    """Sum padded bytes over every 'dtype[dims]{layout}' in text (handles
    tuple shapes by matching each element)."""
    total = 0
    for dt, dims_s, layout in _SHAPE.findall(text):
        if dt not in _BITS:
            continue  # e.g. token[], opaque
        bits = _BITS[dt]
        dims = [int(d) for d in dims_s.split(",") if d] or [1]
        perm_s = layout.split(":")[0] if layout else ""
        if perm_s and all(t.strip().isdigit() for t in perm_s.split(",")):
            # HLO layouts list dims MINOR-to-major; reverse for major-to-minor
            perm = [int(t) for t in perm_s.split(",")]
            phys = [dims[i] for i in reversed(perm)]
        else:
            phys = list(dims)
        if "T(" in (layout or "") and len(phys) >= 2:
            sub = 8 * (32 // bits)  # bf16: (16,128) second-level tiling
            phys[-2] = -(-phys[-2] // sub) * sub
            phys[-1] = -(-phys[-1] // 128) * 128
        elif "T(" in (layout or "") and len(phys) == 1:
            phys[-1] = -(-phys[-1] // 128) * 128
        n = 1
        for d in phys:
            n *= d
        total += n * bits // 8
    return total


#: Cross-replica collective opcodes (plus their async -start halves; the
#: -done halves carry no payload of their own and are skipped).
_COLLECTIVES = (
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute",
)

_INST = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.+?)\s+([a-z][\w\-]*)\((.*)$"
)


def collective_bytes(hlo_text: str) -> dict:
    """Per-participant payload bytes of the cross-replica collectives in an
    optimized HLO module, bucketed by opcode.

    Counts each collective instruction's OPERAND bytes — the data every
    participant contributes to the fabric per step (for all-gather that is
    the local shard, for all-reduce the full buffer; ring-algorithm wire
    amplification is deliberately not modeled, so ratios between compiles
    are like-for-like). Scans every computation, not just ENTRY: shard_map
    bodies compile to nested computations.

    Returns ``{"total": int, "by_opcode": {opcode: int}}``.
    """
    by_opcode: dict[str, int] = collections.defaultdict(int)
    for line in hlo_text.splitlines():
        m = _INST.match(line)
        if not m:
            continue
        _shape, opcode, rest = m.groups()
        base = opcode[:-6] if opcode.endswith("-start") else opcode
        if base not in _COLLECTIVES or opcode.endswith("-done"):
            continue
        # operand list ends at the first ')' (shapes carry no parens)
        by_opcode[base] += shape_bytes(rest.split(")")[0])
    return {"total": sum(by_opcode.values()), "by_opcode": dict(by_opcode)}


_OPNAME = re.compile(r'op_name="jit\(train_step\)/([^"]*)"')


def classify(opcode: str, line: str, out_bytes: int) -> str:
    """Attribute by the op's jaxpr provenance (metadata op_name): XLA:TPU
    wraps convolutions inside fusion instructions, so opcode alone cannot
    see them — but the metadata names the model op and whether it came
    from the forward (jvp) or backward (transpose(jvp)) pass."""
    m = _OPNAME.search(line)
    if m:
        path = m.group(1)
        bwd = "transpose(" in path
        if "fused_input_stage" in path:  # jvp(Model.fused_input_stage)/...
            return f"input-stage-{'bwd' if bwd else 'fwd'}"
        for tag in ("conv1", "conv2", "fc", "_resize", "bn1", "bn2"):
            if f"/{tag}/" in path or path.startswith(f"jvp(jit({tag}))"):
                if tag.startswith("conv") and bwd:
                    # wgrad writes a kernel-small buffer; dgrad an activation
                    kind = "wgrad" if out_bytes < (1 << 24) else "dgrad"
                    return f"{tag}-{kind}"
                return f"{tag}-{'bwd' if bwd else 'fwd'}"
        if "tpu_custom_call" in line:
            return "pallas-kernel"
        return ("optimizer/other-bwd" if bwd else "other-fwd")
    if opcode in ("copy", "copy-start", "copy-done", "transpose"):
        return "copy/transpose(no-provenance)"
    return opcode


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--plan", choices=["s2dt", "s2d", "plain"],
                   default="s2dt")
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--image-size", type=int, default=3000)
    p.add_argument("--top", type=int, default=25)
    p.add_argument("--hlo-file", default=None,
                   help="re-analyze an existing optimized-HLO dump instead "
                        "of recompiling (~5 min saved per iteration)")
    p.add_argument("--dump-hlo", default=None,
                   help="also write the optimized HLO text here")
    args = p.parse_args()

    if args.hlo_file:
        text = open(args.hlo_file).read()
    else:
        from aot_v5e import compile_step, make_topology

        topo = make_topology()
        compiled = compile_step(topo, args.plan, args.batch, args.image_size)
        text = compiled.as_text()
        if args.dump_hlo:
            open(args.dump_hlo, "w").write(text)

    # ENTRY computation only: fusions count once (their internals stay in
    # registers/VMEM); while/cond absent from this step.
    entry = text[text.index("ENTRY "):]
    shapes: dict[str, int] = {}
    rows = []
    inst = re.compile(
        r"^\s+(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(\(?[^=]+?\)?)\s+([\w\-]+)\((.*)",
        re.M,
    )
    for m in inst.finditer(entry):
        name, shape_s, opcode, rest = m.groups()
        out_b = shape_bytes(shape_s)
        shapes[name] = out_b
        if opcode in ("parameter", "constant", "get-tuple-element", "tuple",
                      "bitcast"):
            continue
        # operand list ends at the first ')'; tokens are matched with an
        # OPTIONAL '%' sigil (HLO dumps come both ways) and filtered
        # through the name table, so comment/keyword tokens count as 0
        operand_names = re.findall(r"%?([\w.\-]+)", rest.split(")")[0])
        in_b = sum(shapes.get(o, 0) for o in operand_names)
        rows.append({
            "op": name, "class": classify(opcode, m.group(0), out_b),
            "opcode": opcode, "write_mb": out_b / 1e6, "read_mb": in_b / 1e6,
        })

    per_img = args.batch
    by_class = collections.defaultdict(float)
    for r in rows:
        by_class[r["class"]] += r["write_mb"] + r["read_mb"]
    total = sum(by_class.values())
    print(json.dumps({
        "plan": args.plan, "batch": args.batch,
        "total_traffic_gb": round(total / 1e3, 2),
        "gb_per_img": round(total / 1e3 / per_img, 3),
        "by_class_gb": {k: round(v / 1e3, 2) for k, v in sorted(
            by_class.items(), key=lambda kv: -kv[1])},
        "source": "optimized-HLO padded-buffer accounting "
                  "(chipless AOT estimate, not a measurement)",
    }))
    for r in sorted(rows, key=lambda r: -(r["write_mb"] + r["read_mb"]))[
            : args.top]:
        r["write_mb"] = round(r["write_mb"], 1)
        r["read_mb"] = round(r["read_mb"], 1)
        print(json.dumps(r))


if __name__ == "__main__":
    main()
