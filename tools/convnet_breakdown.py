"""Per-stage timing breakdown of the 3000x3000 ConvNet train step.

Diagnoses WHERE the headline step time goes on the real chip (the r02
question: honest timing said ~0.41 s/step = 1.2% MFU, ~20x above the
bandwidth floor). Each stage runs as its own jitted fori_loop whose
iterations are data-chained through a scalar tap (`x0 + tap*eps`), so XLA
can neither hoist nor CSE the op, and timing is the same fetch-synced
differential as bench.py (utils/profiling.py::measure_per_step).

Known suspect (from the axon AOT allocator dump): activations shaped
[B, 3000, 3000, 16] are tiled T(8,128) with C=16 in the 128-lane minor dim
=> 8x padded bytes and lane-starved conv MACs. The NCHW variants and the
spatial-minor matmul formulation quantify what a layout change would buy.

Usage: python tools/convnet_breakdown.py [--batch 5] [--size 3000] [--n 3]
Prints one JSON line per stage: {"stage", "sec", "note"}.
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys

# runnable as `python tools/convnet_breakdown.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from tpu_sandbox.utils.profiling import measure_per_step


def chained(f, x0, n: int):
    """Time f applied to (a tap-perturbed copy of) x0, n-vs-2n differential.

    The tap (last element of f's output) feeds the next iteration's input,
    so the k applications form a serial data chain inside ONE compiled
    while_loop — no per-step dispatch through the tunnel, no hoisting.
    """

    @jax.jit
    def loop(x_init, k):
        def body(i, carry):
            x, acc = carry
            y = f(x)
            tap = jnp.ravel(y)[-1].astype(jnp.float32)
            return (x0 + (tap * 1e-30).astype(x0.dtype), acc + tap)

        _, acc = jax.lax.fori_loop(0, k, body, (x_init, jnp.float32(0)))
        return acc

    return measure_per_step(lambda k: loop(x0, k), n)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=5)
    p.add_argument("--size", type=int, default=3000)
    p.add_argument("--n", type=int, default=3)
    p.add_argument("--stages", default="",
                   help="comma-separated subset to run (default: all)")
    p.add_argument("--force-cpu", action="store_true",
                   help="flip jax to the CPU backend (env vars alone cannot "
                        "override the axon sitecustomize registration)")
    args = p.parse_args()
    if args.force_cpu:
        jax.config.update("jax_platforms", "cpu")
    b, hw, n = args.batch, args.size, args.n
    only = set(s for s in args.stages.split(",") if s)

    rng = np.random.default_rng(0)
    f32, bf16 = jnp.float32, jnp.bfloat16

    def arr(*shape, dtype=bf16):
        return jnp.asarray(rng.standard_normal(shape), dtype)

    x_raw = arr(b, 28, 28, 1)
    x_big = arr(b, hw, hw, 1)
    w1 = arr(5, 5, 1, 16)
    y1 = arr(b, hw, hw, 16)
    x2 = arr(b, hw // 2, hw // 2, 16)
    w2 = arr(5, 5, 16, 32)
    x3 = arr(b, hw // 4, hw // 4, 32)
    wfc = arr(32 * (hw // 4) ** 2, 10)

    conv = functools.partial(
        jax.lax.conv_general_dilated, window_strides=(1, 1), padding="SAME")

    def nhwc(x, w):
        return conv(x, w, dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def nchw(x, w):
        return conv(x, w, dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def bn_relu_pool(y):
        mean = jnp.mean(y.astype(f32), axis=(0, 1, 2))
        var = jnp.var(y.astype(f32), axis=(0, 1, 2))
        yn = (y.astype(f32) - mean) * jax.lax.rsqrt(var + 1e-5)
        return jax.lax.reduce_window(
            jax.nn.relu(yn).astype(y.dtype), jnp.array(-jnp.inf, y.dtype),
            jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")

    # spatial-minor matmul conv: activations [B, C, H, W] with W in lanes
    # (no channel padding); k5 conv = 25 shift-slices contracted over C via
    # dot_general with H*W as the lane-major free dim
    def conv_spatial_minor(x_chw, w_oihw):
        bb, ci, hh, ww = x_chw.shape
        co = w_oihw.shape[0]
        xp = jnp.pad(x_chw, ((0, 0), (0, 0), (2, 2), (2, 2)))
        out = jnp.zeros((bb, co, hh, ww), f32)
        for dx in range(5):
            for dy in range(5):
                sl = jax.lax.dynamic_slice(
                    xp, (0, 0, dx, dy), (bb, ci, hh, ww))
                # [co, ci] @ [b, ci, h, w] -> [b, co, h, w]
                out = out + jax.lax.dot_general(
                    w_oihw[:, :, dx, dy], sl,
                    (((1,), (1,)), ((), ())),
                    preferred_element_type=f32,
                ).transpose(1, 0, 2, 3)
        return out.astype(x_chw.dtype)

    stages = {
        "resize": (lambda x: jax.image.resize(
            x, (b, hw, hw, 1), "bilinear"), x_raw),
        "conv1_nhwc": (lambda x: nhwc(x, w1), x_big),
        "conv1_nchw": (lambda x: nchw(
            x, jnp.transpose(w1, (3, 2, 0, 1))), jnp.transpose(x_big, (0, 3, 1, 2))),
        "conv1_spatial_minor": (lambda x: conv_spatial_minor(
            x, jnp.transpose(w1, (3, 2, 0, 1))), jnp.transpose(x_big, (0, 3, 1, 2))),
        "bn_relu_pool1": (bn_relu_pool, y1),
        "conv2_nhwc": (lambda x: nhwc(x, w2), x2),
        "conv2_nchw": (lambda x: nchw(
            x, jnp.transpose(w2, (3, 2, 0, 1))), jnp.transpose(x2, (0, 3, 1, 2))),
        "conv2_spatial_minor": (lambda x: conv_spatial_minor(
            x, jnp.transpose(w2, (3, 2, 0, 1))), jnp.transpose(x2, (0, 3, 1, 2))),
        "head_matmul": (lambda x: x.reshape(b, -1) @ wfc, x3),
        "fwd_conv1_grad": (lambda x: jax.grad(
            lambda xx: nhwc(xx, w1).astype(f32).sum())(x), x_big),
    }

    # the space-to-depth plan's two convs (models/convnet_s2d.py): k3 on a
    # 4x-coarser grid with fat channels — the lane-friendly replacements
    from tpu_sandbox.models.convnet_s2d import scatter_kernel
    x1s = arr(b, hw // 4, hw // 4, 16)
    w1s = scatter_kernel(w1, 4)                       # [3,3,16,256]
    x2s = arr(b, hw // 4, hw // 4, 64)
    w2s = scatter_kernel(w2, 2)                       # [3,3,64,128]
    stages.update({
        "conv1_s2d": (lambda x: nhwc(x, w1s), x1s),
        "conv2_s2d": (lambda x: nhwc(x, w2s), x2s),
        "conv1_s2d_grad": (lambda x: jax.grad(
            lambda xx: nhwc(xx, w1s).astype(f32).sum())(x), x1s),
        "conv2_s2d_grad": (lambda x: jax.grad(
            lambda xx: nhwc(xx, w2s).astype(f32).sum())(x), x2s),
    })

    # the r03 production kernels (ops/pallas_conv.py, ops/pallas_bn_tail.py):
    # per-stage times for the exact ops the fused plan runs, fwd and VJP —
    # measured against the XLA rows above, these attribute any gap between
    # the AOT traffic/compute floors and the whole-step headline
    from tpu_sandbox.ops.pallas_bn_tail import fused_bn_relu_pool
    from tpu_sandbox.ops.pallas_conv import conv3x3, conv3x3_stats

    b1s = arr(256, dtype=bf16)
    b2s = arr(128, dtype=bf16)
    gam1 = jnp.ones(16, f32)
    bet1 = jnp.zeros(16, f32)
    y1s = arr(b, hw // 4, hw // 4, 256)

    stages.update({
        "conv1_pallas": (lambda x: conv3x3(x, w1s.astype(bf16), b1s), x1s),
        "conv1_pallas_stats": (
            lambda x: conv3x3_stats(x, w1s.astype(bf16), b1s)[0], x1s),
        "conv2_pallas": (lambda x: conv3x3(x, w2s.astype(bf16), b2s), x2s),
        "conv1_pallas_vjp": (lambda x: jax.grad(
            lambda xx: conv3x3(xx, w1s.astype(bf16), b1s)
            .astype(f32).sum())(x), x1s),
        "conv2_pallas_vjp": (lambda x: jax.grad(
            lambda xx: conv3x3(xx, w2s.astype(bf16), b2s)
            .astype(f32).sum())(x), x2s),
        "tail1_pallas": (
            lambda y: fused_bn_relu_pool(y, gam1, bet1, 16, 4)[0], y1s),
        "tail1_pallas_vjp": (lambda y: jax.grad(
            lambda yy: fused_bn_relu_pool(yy, gam1, bet1, 16, 4)[0]
            .astype(f32).sum())(y), y1s),
    })

    for name, (f, x0) in stages.items():
        if only and name not in only:
            continue
        try:
            t = chained(f, x0, n)
            print(json.dumps({"stage": name,
                              "sec": round(t["sec_per_step"], 6),
                              "t_n": round(t["t_n_sec"], 4),
                              "t_2n": round(t["t_2n_sec"], 4)}), flush=True)
        except Exception as e:
            print(json.dumps({"stage": name,
                              "error": f"{type(e).__name__}: {e}"[:300]}),
                  flush=True)


if __name__ == "__main__":
    main()
