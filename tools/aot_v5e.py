"""Chipless v5e AOT analysis: compile the real train step for TPU without
a TPU and read XLA's own numbers.

The axon tunnel can be down for hours; this tool keeps the optimization
loop running anyway. jax.experimental.topologies + the local libtpu build
a compile-only v5e device (`chips_per_host_bounds=[1,1,1]` unlocks the
1x1x1 topology), and `jit(...).lower().compile()` then yields:

- ``memory_analysis()``: argument/output/temp bytes — peak-HBM estimates
  (the chipless twin of the capacity experiment);
- ``cost_analysis()``: executed FLOPs and bytes accessed — the traffic
  model that predicts step time on the 819 GB/s HBM.

Usage:
  python tools/aot_v5e.py --plan s2d --batch 5            # one config
  python tools/aot_v5e.py --plan plain --batch 5
  python tools/aot_v5e.py --capacity --plan s2d           # bisect max batch

Numbers printed here are COMPILER estimates, labeled as such — the bench
still owns the measured truth once the chip answers.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# runnable as `python tools/aot_v5e.py` from anywhere (sys.path[0] is
# tools/, which does not see the tpu_sandbox package at the repo root)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

HBM_BYTES = 16 * 1024**3  # v5e: 16 GiB HBM per chip
HBM_BW = 819e9            # v5e HBM bandwidth, bytes/sec


def unwrap_cost(compiled) -> dict:
    """compiled.cost_analysis() across jax versions (list vs dict)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    return ca


def make_topology(topology_name: str = "v5e:1x1x1",
                  chips_per_host_bounds=(1, 1, 1)):
    """Compile-only TPU topology. The 1x1x1 default is the single-chip
    memory/FLOPs twin; multi-chip bounds (e.g. ``"v5e:2x2x1"``,
    ``(2, 2, 1)``) give tools that need real cross-chip collectives in the
    compiled HLO — the schedule receipt in tools/hlo_schedule.py — a mesh
    to compile against."""
    # env setup lives HERE, not at module import: importing this module
    # (e.g. tests importing hlo_traffic for its classifier) must not
    # flip the whole process into forced-compiled-kernel mode — that
    # poisoned a full pytest run once (interpret-mode CPU tests started
    # lowering real Mosaic kernels and died)
    os.environ.setdefault("TPU_SKIP_MDS_QUERY", "1")
    # lower the REAL Mosaic kernels, not the interpreter (see
    # pallas_common): this process only compiles, never executes
    os.environ.setdefault("TPU_SANDBOX_FORCE_COMPILED_KERNELS", "1")

    import jax

    jax.config.update("jax_platforms", "cpu")
    from jax.experimental import topologies

    return topologies.get_topology_desc(
        platform="tpu", topology_name=topology_name,
        chips_per_host_bounds=list(chips_per_host_bounds),
    )


def compile_step(topo, plan: str, batch: int, image_size: int = 3000,
                 dtype_name: str = "bf16", remat: bool = False):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_sandbox.models import pick_convnet
    from tpu_sandbox.train import TrainState, make_train_step

    mesh = Mesh(np.array(topo.devices), ("data",))
    sh = NamedSharding(mesh, P())
    dtype = jnp.bfloat16 if dtype_name == "bf16" else jnp.float32
    model = pick_convnet(image_size, plan=plan, dtype=dtype)
    tx = optax.sgd(1e-4)
    state = jax.eval_shape(lambda: TrainState.create(
        model, jax.random.key(0),
        jnp.zeros((1, image_size, image_size, 1), dtype), tx,
    ))
    state = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh), state
    )
    imgs = jax.ShapeDtypeStruct((batch, 28, 28, 1), jnp.float32, sharding=sh)
    labs = jax.ShapeDtypeStruct((batch,), jnp.int32, sharding=sh)
    step = make_train_step(model, tx, image_size=(image_size, image_size),
                           donate=True, remat=remat)
    return step.trace(state, imgs, labs).lower().compile()


def analyze(compiled, plan: str, batch: int, remat: bool = False) -> dict:
    ma = compiled.memory_analysis()
    ca = unwrap_cost(compiled)
    # donated args alias outputs; live peak ~ args + temps
    peak = ma.argument_size_in_bytes + ma.temp_size_in_bytes
    return {
        "plan": plan,
        "remat": remat,
        "batch": batch,
        "flops": ca["flops"],
        "bytes_accessed": ca.get("bytes accessed"),
        "argument_bytes": ma.argument_size_in_bytes,
        "temp_bytes": ma.temp_size_in_bytes,
        "est_peak_bytes": peak,
        "est_peak_gb": round(peak / 1024**3, 2),
        "fits_16g_hbm": peak < HBM_BYTES * 0.98,
        "est_step_ms_bw_bound": (
            round(ca["bytes accessed"] / HBM_BW * 1e3, 1)
            if ca.get("bytes accessed") else None
        ),
        "source": "chipless v5e AOT compile (XLA estimates, not measurements)",
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--plan", choices=["s2dt", "s2d", "plain"],
                   default="s2dt")
    p.add_argument("--batch", type=int, default=5)
    p.add_argument("--image-size", type=int, default=3000)
    p.add_argument("--dtype", choices=["bf16", "fp32"], default="bf16")
    p.add_argument("--remat", action="store_true",
                   help="recompute-forward backward (jax.checkpoint over "
                        "the loss) — the capacity lever")
    p.add_argument("--capacity", action="store_true",
                   help="bisect the largest batch whose est peak fits HBM")
    args = p.parse_args()
    topo = make_topology()

    if not args.capacity:
        compiled = compile_step(topo, args.plan, args.batch, args.image_size,
                                args.dtype, remat=args.remat)
        print(json.dumps(analyze(compiled, args.plan, args.batch, args.remat)))
        return

    def fits(bs: int) -> bool:
        try:
            c = compile_step(topo, args.plan, bs, args.image_size, args.dtype,
                             remat=args.remat)
        except Exception as e:  # compiler OOM = does not fit
            if "exceed" in str(e).lower() or "memory" in str(e).lower():
                return False
            raise
        r = analyze(c, args.plan, bs, args.remat)
        print(json.dumps(r), flush=True)
        return r["fits_16g_hbm"]

    lo, hi, bs = 0, None, 1
    while bs <= 512:
        if fits(bs):
            lo = bs
            bs *= 2
        else:
            hi = bs
            break
    if hi is None:
        hi = 513
    while hi - lo > 1:
        mid = (lo + hi) // 2
        lo, hi = (mid, hi) if fits(mid) else (lo, mid)
    print(json.dumps({
        "metric": "aot_est_max_batch", "plan": args.plan,
        "remat": args.remat, "value": lo,
        "first_over": hi if hi <= 512 else None,
        "source": "chipless v5e AOT compile (XLA estimates)",
    }))


if __name__ == "__main__":
    main()
