#!/usr/bin/env python3
"""tracediff — gate a run's critical-path profile against a baseline.

Compares two runs segment-by-segment (gateway route, queue wait,
prefill, decode, publish, …) and exits nonzero when a segment regressed
significantly — the perf gate a bench or CI job puts after its workload.

    python tools/tracediff.py BASELINE CANDIDATE
        Each argument is either a critpath profile JSON (written by
        ``tracecat --critpath FILE`` or the bench archive hook) or a raw
        trace directory, which is analyzed on the fly.

    python tools/tracediff.py A B --threshold 0.10 --min-ms 0.5
        A segment REGRESSES when its quantile-paired median-of-ratios
        exceeds 1 + threshold AND its median grew by at least --min-ms
        AND it carries at least --min-share of either run's wall. The
        median of ratios — not a ratio of means — is the point: one
        straggler request cannot fail the build, a distribution-wide 20%
        decode slowdown will.

Exit status: 0 clean, 1 regression(s), 2 bad input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_sandbox.obs import critpath  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tracediff", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("baseline", help="profile JSON or trace dir (the "
                                     "run to compare against)")
    ap.add_argument("candidate", help="profile JSON or trace dir (the "
                                      "run under test)")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="relative growth that counts as a regression "
                         "(default 0.10 = +10%%)")
    ap.add_argument("--min-ms", type=float, default=0.5,
                    help="noise floor: ignore segments whose median "
                         "grew less than this many ms (default 0.5)")
    ap.add_argument("--min-share", type=float, default=0.01,
                    help="noise floor: ignore segments carrying less "
                         "than this share of wall (default 0.01)")
    ap.add_argument("--json", action="store_true",
                    help="emit the comparison as JSON instead of a table")
    args = ap.parse_args(argv)

    try:
        base = critpath.load_profile(args.baseline)
        cand = critpath.load_profile(args.candidate)
    except (OSError, ValueError) as e:
        print(f"tracediff: {e}", file=sys.stderr)
        return 2

    cmp = critpath.compare_profiles(
        base, cand, threshold=args.threshold,
        min_ms=args.min_ms, min_share=args.min_share)
    if args.json:
        print(json.dumps(cmp, sort_keys=True))
    else:
        print(critpath.format_compare(cmp))
    return 1 if cmp["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
