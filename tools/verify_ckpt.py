#!/usr/bin/env python
"""Offline integrity audit of a sharded checkpoint directory.

Walks every ``step-XXXXXXXX/`` under the given directory, re-hashes each
shard against its manifest's SHA-256, and prints one line per step:

    step 00000012  sealed    2 shard(s), 1.3 MiB
    step 00000016  torn      no manifest (commit never completed)
    step 00000020  CORRUPT   shard-00001.npz: sha256 mismatch

Exit status: 0 when every sealed step verifies (torn steps are expected
debris of a kill inside the commit window and do NOT fail the audit —
restore skips them by design), 1 when any sealed step is corrupt, 2 on
usage errors. ``--strict`` also fails on torn steps, for post-run checks
where the job is known to have finished cleanly.

HostCheckpoint npz files (``step-*.npz``) sitting in the same directory
are audited automatically: re-hashed against their ``.sha256`` sidecar
when one exists, then parse-checked with ``np.load``. Pre-integrity
files without a sidecar get the parse check only and are noted, not
failed — a missing sidecar is a provenance gap, not corruption.

    npz  step-00000016.npz  ok        sha256 verified, 0.1 MiB
    npz  step-00000008.npz  ok        no sidecar (unverified), loads
    npz  step-00000012.npz  CORRUPT   sha256 mismatch — ...

With ``--kv-port`` the audit switches to the **deploy registry**: it
connects to the cluster KV store, walks every fleet's model registry
(``deploy/models/<fleet>/<ver>``), re-verifies each registered artifact's
seal, and reports lifecycle status per version:

    fleet default: target v3, 4 registered
      v1  superseded   sealed     gc-able   /ckpts/step-00000100
      v2  rolled_back  sealed     gc-able   /ckpts/step-00000200
      v3  current      sealed               /ckpts/step-00000300
      v4  candidate    CORRUPT              /ckpts/step-00000400

Exit 1 when any registered artifact is dangling (record points at a
directory that no longer exists) or corrupt; rejected versions with
recorded problems are expected history, not failures.

Runs from a repo checkout without installation:
    python tools/verify_ckpt.py /path/to/ckpt-dir
    python tools/verify_ckpt.py --kv-port 5999 [--fleet default]
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np


def _ensure_import_path() -> None:
    root = Path(__file__).resolve().parent.parent
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))


def _dir_bytes(step_dir: Path) -> int:
    return sum(p.stat().st_size for p in step_dir.iterdir() if p.is_file())


def audit_deploy_registry(host: str, port: int,
                          fleet: str | None = None) -> int:
    """Registry-audit mode: lifecycle + seal status of every registered
    version, straight from the KV store (pure read, never deletes)."""
    from tpu_sandbox.deploy.registry import audit_registry, audited_fleets
    from tpu_sandbox.runtime.kvstore import KVClient

    kv = KVClient(host, port)
    fleets = [fleet] if fleet is not None else audited_fleets(kv)
    if not fleets:
        print("no deploy registry state in this store")
        return 0
    bad = 0
    for fl in fleets:
        report = audit_registry(kv, "" if fl == "default" else fl)
        print(f"fleet {report['fleet']}: target v{report['target']}, "
              f"{len(report['versions'])} registered"
              + (f", {len(report['missing_records'])} allocated but "
                 f"unrecorded" if report["missing_records"] else ""))
        for row in report["versions"]:
            if row["dangling"]:
                seal = "DANGLING"
            elif row["sealed"]:
                seal = "sealed"
            elif all(p.startswith("torn:") for p in row["problems"]):
                seal = "torn"
            else:
                seal = "CORRUPT"
            # a rejected version's bad artifact is recorded history; a
            # bad artifact anywhere else is a live integrity problem
            if seal in ("DANGLING", "CORRUPT") \
                    and row["status"] != "rejected":
                bad += 1
            print(f"  v{row['ver']}  {row['status']:<12} {seal:<9} "
                  f"{'gc-able  ' if row['gc_able'] else '         '}"
                  f"{row['step_dir']}")
            for p in row["problems"][:4]:
                print(f"      {p}")
    return 1 if bad else 0


def main(argv=None) -> int:
    _ensure_import_path()
    from tpu_sandbox.train.checkpoint import (
        _parse_step_dir,
        verify_npz_sidecar,
        verify_step_dir,
    )

    ap = argparse.ArgumentParser(
        description="re-hash sharded checkpoint steps against their "
                    "manifests; exit 1 on corruption"
    )
    ap.add_argument("directory", nargs="?",
                    help="checkpoint directory to audit")
    ap.add_argument("--strict", action="store_true",
                    help="fail on torn (unsealed) steps too, not just "
                         "corrupt ones")
    ap.add_argument("--host-npz", action="store_true",
                    help="(kept for compatibility; host npz files are now "
                         "always audited when present)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="only print problems and the summary line")
    ap.add_argument("--kv-port", type=int, default=None,
                    help="audit the deploy model registry in the KV store "
                         "at this port instead of a local directory")
    ap.add_argument("--host", default="127.0.0.1",
                    help="KV store host for --kv-port (default 127.0.0.1)")
    ap.add_argument("--fleet", default=None,
                    help="restrict the registry audit to one fleet label")
    args = ap.parse_args(argv)

    if args.kv_port is not None:
        return audit_deploy_registry(args.host, args.kv_port, args.fleet)
    if args.directory is None:
        print("error: a checkpoint directory (or --kv-port) is required",
              file=sys.stderr)
        return 2

    root = Path(args.directory)
    if not root.is_dir():
        print(f"error: {root} is not a directory", file=sys.stderr)
        return 2

    step_dirs = sorted(
        (p for p in root.iterdir() if _parse_step_dir(p) is not None),
        key=lambda p: _parse_step_dir(p),
    )
    sealed = torn = corrupt = 0
    for sd in step_dirs:
        step = _parse_step_dir(sd)
        problems = verify_step_dir(sd)
        if not problems:
            sealed += 1
            if not args.quiet:
                shards = len(list(sd.glob("shard-*.npz")))
                mib = _dir_bytes(sd) / (1 << 20)
                print(f"step {step:08d}  sealed    "
                      f"{shards} shard(s), {mib:.1f} MiB")
            continue
        if all(p.startswith("torn:") for p in problems):
            torn += 1
            print(f"step {step:08d}  torn      "
                  + "; ".join(p.split(": ", 1)[-1] for p in problems))
        else:
            corrupt += 1
            print(f"step {step:08d}  CORRUPT   "
                  + "; ".join(p.split(": ", 1)[-1] for p in problems))

    npz_total = npz_bad = npz_unverified = 0
    for f in sorted(root.glob("step-*.npz")):
        tail = f.stem.split("-", 1)[1]
        if not tail.isdigit():
            continue
        npz_total += 1
        problem = verify_npz_sidecar(f)
        if problem is not None:
            npz_bad += 1
            print(f"npz  {f.name}  CORRUPT   {problem}")
            continue
        has_sidecar = Path(str(f) + ".sha256").exists()
        try:
            with np.load(f, allow_pickle=False) as z:
                z["__meta__"]
        except Exception as e:
            npz_bad += 1
            print(f"npz  {f.name}  CORRUPT   does not load ({e!r})")
            continue
        if not has_sidecar:
            npz_unverified += 1
            print(f"npz  {f.name}  ok        no sidecar (unverified), loads")
        elif not args.quiet:
            mib = f.stat().st_size / (1 << 20)
            print(f"npz  {f.name}  ok        sha256 verified, {mib:.1f} MiB")

    quarantine = root.parent / (root.name + ".quarantine")
    quarantined = (
        len([p for p in quarantine.iterdir() if p.is_dir()])
        if quarantine.is_dir() else 0
    )
    quarantined += len(list(root.glob("step-*.npz.corrupt")))

    print(f"{len(step_dirs)} step(s): {sealed} sealed, {torn} torn, "
          f"{corrupt} corrupt"
          + (f"; {npz_total} host npz ({npz_bad} corrupt, "
             f"{npz_unverified} unverified)" if npz_total else "")
          + (f"; {quarantined} previously quarantined" if quarantined else ""))
    if corrupt or npz_bad:
        return 1
    if args.strict and torn:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
