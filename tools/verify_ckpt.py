#!/usr/bin/env python
"""Offline integrity audit of a sharded checkpoint directory.

Walks every ``step-XXXXXXXX/`` under the given directory, re-hashes each
shard against its manifest's SHA-256, and prints one line per step:

    step 00000012  sealed    2 shard(s), 1.3 MiB
    step 00000016  torn      no manifest (commit never completed)
    step 00000020  CORRUPT   shard-00001.npz: sha256 mismatch

Exit status: 0 when every sealed step verifies (torn steps are expected
debris of a kill inside the commit window and do NOT fail the audit —
restore skips them by design), 1 when any sealed step is corrupt, 2 on
usage errors. ``--strict`` also fails on torn steps, for post-run checks
where the job is known to have finished cleanly.

HostCheckpoint npz files (``step-*.npz``) sitting in the same directory
are audited automatically: re-hashed against their ``.sha256`` sidecar
when one exists, then parse-checked with ``np.load``. Pre-integrity
files without a sidecar get the parse check only and are noted, not
failed — a missing sidecar is a provenance gap, not corruption.

    npz  step-00000016.npz  ok        sha256 verified, 0.1 MiB
    npz  step-00000008.npz  ok        no sidecar (unverified), loads
    npz  step-00000012.npz  CORRUPT   sha256 mismatch — ...

Runs from a repo checkout without installation:
    python tools/verify_ckpt.py /path/to/ckpt-dir
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np


def _ensure_import_path() -> None:
    root = Path(__file__).resolve().parent.parent
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))


def _dir_bytes(step_dir: Path) -> int:
    return sum(p.stat().st_size for p in step_dir.iterdir() if p.is_file())


def main(argv=None) -> int:
    _ensure_import_path()
    from tpu_sandbox.train.checkpoint import (
        _parse_step_dir,
        verify_npz_sidecar,
        verify_step_dir,
    )

    ap = argparse.ArgumentParser(
        description="re-hash sharded checkpoint steps against their "
                    "manifests; exit 1 on corruption"
    )
    ap.add_argument("directory", help="checkpoint directory to audit")
    ap.add_argument("--strict", action="store_true",
                    help="fail on torn (unsealed) steps too, not just "
                         "corrupt ones")
    ap.add_argument("--host-npz", action="store_true",
                    help="(kept for compatibility; host npz files are now "
                         "always audited when present)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="only print problems and the summary line")
    args = ap.parse_args(argv)

    root = Path(args.directory)
    if not root.is_dir():
        print(f"error: {root} is not a directory", file=sys.stderr)
        return 2

    step_dirs = sorted(
        (p for p in root.iterdir() if _parse_step_dir(p) is not None),
        key=lambda p: _parse_step_dir(p),
    )
    sealed = torn = corrupt = 0
    for sd in step_dirs:
        step = _parse_step_dir(sd)
        problems = verify_step_dir(sd)
        if not problems:
            sealed += 1
            if not args.quiet:
                shards = len(list(sd.glob("shard-*.npz")))
                mib = _dir_bytes(sd) / (1 << 20)
                print(f"step {step:08d}  sealed    "
                      f"{shards} shard(s), {mib:.1f} MiB")
            continue
        if all(p.startswith("torn:") for p in problems):
            torn += 1
            print(f"step {step:08d}  torn      "
                  + "; ".join(p.split(": ", 1)[-1] for p in problems))
        else:
            corrupt += 1
            print(f"step {step:08d}  CORRUPT   "
                  + "; ".join(p.split(": ", 1)[-1] for p in problems))

    npz_total = npz_bad = npz_unverified = 0
    for f in sorted(root.glob("step-*.npz")):
        tail = f.stem.split("-", 1)[1]
        if not tail.isdigit():
            continue
        npz_total += 1
        problem = verify_npz_sidecar(f)
        if problem is not None:
            npz_bad += 1
            print(f"npz  {f.name}  CORRUPT   {problem}")
            continue
        has_sidecar = Path(str(f) + ".sha256").exists()
        try:
            with np.load(f, allow_pickle=False) as z:
                z["__meta__"]
        except Exception as e:
            npz_bad += 1
            print(f"npz  {f.name}  CORRUPT   does not load ({e!r})")
            continue
        if not has_sidecar:
            npz_unverified += 1
            print(f"npz  {f.name}  ok        no sidecar (unverified), loads")
        elif not args.quiet:
            mib = f.stat().st_size / (1 << 20)
            print(f"npz  {f.name}  ok        sha256 verified, {mib:.1f} MiB")

    quarantine = root.parent / (root.name + ".quarantine")
    quarantined = (
        len([p for p in quarantine.iterdir() if p.is_dir()])
        if quarantine.is_dir() else 0
    )
    quarantined += len(list(root.glob("step-*.npz.corrupt")))

    print(f"{len(step_dirs)} step(s): {sealed} sealed, {torn} torn, "
          f"{corrupt} corrupt"
          + (f"; {npz_total} host npz ({npz_bad} corrupt, "
             f"{npz_unverified} unverified)" if npz_total else "")
          + (f"; {quarantined} previously quarantined" if quarantined else ""))
    if corrupt or npz_bad:
        return 1
    if args.strict and torn:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
