#!/usr/bin/env python
"""Offline integrity audit of a sharded checkpoint directory.

Walks every ``step-XXXXXXXX/`` under the given directory, re-hashes each
shard against its manifest's SHA-256, and prints one line per step:

    step 00000012  sealed    2 shard(s), 1.3 MiB
    step 00000016  torn      no manifest (commit never completed)
    step 00000020  CORRUPT   shard-00001.npz: sha256 mismatch

Exit status: 0 when every sealed step verifies (torn steps are expected
debris of a kill inside the commit window and do NOT fail the audit —
restore skips them by design), 1 when any sealed step is corrupt, 2 on
usage errors. ``--strict`` also fails on torn steps, for post-run checks
where the job is known to have finished cleanly.

HostCheckpoint npz files (``step-*.npz``) sitting in the same directory
are checked for basic loadability with ``--host-npz`` (they carry no
checksums — presence of a readable zip is the best available signal).

Runs from a repo checkout without installation:
    python tools/verify_ckpt.py /path/to/ckpt-dir
"""

from __future__ import annotations

import argparse
import sys
import zipfile
from pathlib import Path


def _ensure_import_path() -> None:
    root = Path(__file__).resolve().parent.parent
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))


def _dir_bytes(step_dir: Path) -> int:
    return sum(p.stat().st_size for p in step_dir.iterdir() if p.is_file())


def main(argv=None) -> int:
    _ensure_import_path()
    from tpu_sandbox.train.checkpoint import _parse_step_dir, verify_step_dir

    ap = argparse.ArgumentParser(
        description="re-hash sharded checkpoint steps against their "
                    "manifests; exit 1 on corruption"
    )
    ap.add_argument("directory", help="checkpoint directory to audit")
    ap.add_argument("--strict", action="store_true",
                    help="fail on torn (unsealed) steps too, not just "
                         "corrupt ones")
    ap.add_argument("--host-npz", action="store_true",
                    help="also check HostCheckpoint step-*.npz files for "
                         "loadability (no checksums exist for those)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="only print problems and the summary line")
    args = ap.parse_args(argv)

    root = Path(args.directory)
    if not root.is_dir():
        print(f"error: {root} is not a directory", file=sys.stderr)
        return 2

    step_dirs = sorted(
        (p for p in root.iterdir() if _parse_step_dir(p) is not None),
        key=lambda p: _parse_step_dir(p),
    )
    sealed = torn = corrupt = 0
    for sd in step_dirs:
        step = _parse_step_dir(sd)
        problems = verify_step_dir(sd)
        if not problems:
            sealed += 1
            if not args.quiet:
                shards = len(list(sd.glob("shard-*.npz")))
                mib = _dir_bytes(sd) / (1 << 20)
                print(f"step {step:08d}  sealed    "
                      f"{shards} shard(s), {mib:.1f} MiB")
            continue
        if all(p.startswith("torn:") for p in problems):
            torn += 1
            print(f"step {step:08d}  torn      "
                  + "; ".join(p.split(": ", 1)[-1] for p in problems))
        else:
            corrupt += 1
            print(f"step {step:08d}  CORRUPT   "
                  + "; ".join(p.split(": ", 1)[-1] for p in problems))

    npz_bad = 0
    if args.host_npz:
        for f in sorted(root.glob("step-*.npz")):
            tail = f.stem.split("-", 1)[1]
            if not tail.isdigit():
                continue
            ok = zipfile.is_zipfile(f)
            if not ok:
                npz_bad += 1
                print(f"npz  {f.name}  UNREADABLE (not a zip archive)")
            elif not args.quiet:
                print(f"npz  {f.name}  readable")

    quarantine = root.parent / (root.name + ".quarantine")
    quarantined = (
        len([p for p in quarantine.iterdir() if p.is_dir()])
        if quarantine.is_dir() else 0
    )

    print(f"{len(step_dirs)} step(s): {sealed} sealed, {torn} torn, "
          f"{corrupt} corrupt"
          + (f"; {npz_bad} unreadable npz" if args.host_npz else "")
          + (f"; {quarantined} previously quarantined" if quarantined else ""))
    if corrupt or npz_bad:
        return 1
    if args.strict and torn:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
