"""Chipless per-op attribution of the LM train step (the ConvNet trick,
applied to the second benchmark family — VERDICT r04 next-6 prep).

AOT-compiles bench_lm's EXACT headline step (12L d1024 ff4096 v32k
s2048 bf16, dots-remat, flash attention, fused Pallas CE, AdamW) for a
v5e via jax.experimental.topologies, then ranks the non-Pallas entry
ops by XLA's ``estimated_cycles`` and by padded operand/output bytes —
the same attribution that located the ConvNet's ~95 ms of layout glue
(memory: hlo-cycle-attribution). Pallas custom calls carry no estimate,
so this ranks exactly the "unattributed residue" between measured step
time and kernel time.

Usage: python tools/aot_lm_cycles.py [--batch 16] [--dump-hlo PATH]
One JSON doc to stdout. Estimates, not measurements.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)
sys.path.insert(0, os.path.dirname(_HERE))

from aot_v5e import HBM_BW, make_topology, unwrap_cost  # noqa: E402


def compile_lm_step(topo, batch: int, seq: int = 2048):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_sandbox.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )
    from tpu_sandbox.ops.losses import cross_entropy_loss
    from tpu_sandbox.ops.pallas_attention import flash_attention_fn
    from tpu_sandbox.train import TrainState

    cfg = TransformerConfig(vocab_size=32768, d_model=1024, n_heads=8,
                            n_layers=12, d_ff=4096, max_len=seq,
                            dtype=jnp.bfloat16, remat=True,
                            remat_policy="dots", fp32_logits=False)
    model = TransformerLM(cfg, attention_fn=flash_attention_fn())
    tx = optax.adamw(3e-4)
    mesh = Mesh(np.array(topo.devices), ("data",))
    sh = NamedSharding(mesh, P())
    state = jax.eval_shape(lambda: TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, seq), jnp.int32), tx))
    state = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state)

    def loss_fn(params, tokens, targets):
        logits = model.apply({"params": params}, tokens)
        return cross_entropy_loss(
            logits.reshape(-1, logits.shape[-1]), targets.reshape(-1))

    def step(state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, tokens, targets)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        return state.replace(
            step=state.step + 1,
            params=optax.apply_updates(state.params, updates),
            opt_state=new_opt,
        ), loss

    toks = jax.ShapeDtypeStruct((batch, seq), jnp.int32, sharding=sh)
    return jax.jit(step, donate_argnums=(0,)).trace(
        state, toks, toks).lower().compile()


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--top", type=int, default=20)
    p.add_argument("--dump-hlo", default=None)
    args = p.parse_args()

    topo = make_topology()
    compiled = compile_lm_step(topo, args.batch, args.seq)
    txt = compiled.as_text()
    if args.dump_hlo:
        with open(args.dump_hlo, "w") as f:
            f.write(txt)
    entry = txt[txt.index("ENTRY "):]

    rows = []
    for m in re.finditer(
            r'^\s+%?([\w.\-]+) = .*?estimated_cycles":"(\d+)"', entry, re.M):
        op = re.search(r'op_name="([^"]*)"', m.group(0))
        rows.append((int(m.group(2)) / 940e3, m.group(1),
                     (op.group(1) if op else "")))
    rows.sort(reverse=True)

    ca = unwrap_cost(compiled)
    doc = {
        "what": ("per-op estimated_cycles (940 MHz -> ms) of the"
                 " non-Pallas entry ops in the AOT-compiled LM train"
                 " step - chipless estimate, not a measurement. The"
                 " total EXCLUDES the Pallas flash-attention and"
                 " fused-CE kernels (custom calls carry no estimate)"),
        "config": f"12L d1024 ff4096 v32k s{args.seq} bf16 dots-remat "
                  f"flash fused-CE adamw b{args.batch}",
        "bytes_accessed_gb": round(ca.get("bytes accessed", 0) / 1e9, 1),
        "bw_floor_ms": round(ca.get("bytes accessed", 0) / HBM_BW * 1e3, 1),
        "non_kernel_est_ms_total": round(sum(r[0] for r in rows), 1),
        "n_ops_with_estimates": len(rows),
        "top": [
            {"est_ms": round(ms, 2), "op": name, "op_name": op[:110]}
            for ms, name, op in rows[:args.top]
        ],
        "source": "chipless v5e AOT compile (tools/aot_lm_cycles.py)",
    }
    print(json.dumps(doc, indent=1))


if __name__ == "__main__":
    main()
