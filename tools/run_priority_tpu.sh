#!/bin/bash
# Chip-return runbook: highest-value measurements first, bounded wall-clock.
# Run the moment a probe (bench.py::accelerator_usable in a SUBPROCESS with
# a timeout — never bare jax.devices(), a wedged tunnel hangs it forever)
# answers true. Each step appends to measured/run_log.txt; every bench mode
# prints one JSON line and self-degrades rather than crashing (the
# images_per_sec mode also ladders down the fused-kernel plans on compile
# failure — grep the output for "plan_fallback").
cd "$(dirname "$0")/.." || exit 1
log() { echo "=== $1 $(date +%T) ===" >> measured/run_log.txt; }

log "P1 images_per_sec (s2d + pallas conv/tail, bs=5 reference shape)"
timeout 1800 python bench.py > measured/images_per_sec_r03.json 2> measured/images_per_sec_r03.err
log "P1 exit $?"

log "P1b images_per_sec bs=16 (AOT-sized best batch)"
timeout 1800 python bench.py --batch-per-device 16 > measured/images_per_sec_b16_r03.json 2> measured/images_per_sec_b16_r03.err
log "P1b exit $?"

log "P2 pallas kernel checks (flash, CE, bn-tail, conv) + TFLOPs"
timeout 1800 python bench.py --metric pallas > measured/pallas_r03.json 2> measured/pallas_r03.err
log "P2 exit $?"

log "P3 lm (dots remat, b16 — the chipless-sized config)"
timeout 2400 python bench.py --metric lm > measured/lm_dots_b16_r03.json 2> measured/lm_dots_b16_r03.err
log "P3 exit $?"

log "P4 capacity (the reference's OOM experiment, measured)"
timeout 2400 python bench.py --metric capacity > measured/capacity_r03.json 2> measured/capacity_r03.err
log "P4 exit $?"

log "P5 sweep (batch x dtype ladder)"
timeout 3600 python bench.py --metric sweep --steps 5 > measured/sweep_r03.json 2> measured/sweep_r03.err
log "P5 exit $?"

log "P6 seq_scaling (ring vs flash-ring vs ulysses)"
timeout 3600 python bench.py --metric seq_scaling > measured/seq_scaling_r03.json 2> measured/seq_scaling_r03.err
log "P6 exit $?"

log "ALL DONE — update BASELINE.md measured tables from measured/*_r03.json"
