#!/bin/bash
# Chip-return runbook, manual entry point. The actual rung list lives in
# the CURRENT round's ladder (tools/ladder_r05.sh) — this wrapper exists
# so "run the priority measurements by hand" has one stable name across
# rounds. Probe first (bench.py::accelerator_usable in a SUBPROCESS with
# a timeout — never bare jax.devices(); a wedged tunnel hangs it
# forever), then exec the ladder. ONE chip process at a time.
cd "$(dirname "$0")/.." || exit 1
if ! python -c "import bench,sys; sys.exit(0 if bench.accelerator_usable() else 1)"; then
  echo "chip not answering — arm tools/rerun_on_recovery.sh instead" >&2
  exit 1
fi
exec bash tools/ladder_r05.sh
