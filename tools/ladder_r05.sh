#!/bin/bash
# ROUND-5 measurement ladder — run sequentially the moment the chip
# answers (exec'd by tools/rerun_on_recovery.sh so edits to THIS file
# are picked up at recovery time, not at arm time). ONE chip process at
# a time — nothing else may touch the chip while this runs.
#
# Order (VERDICT r04 next-1/2/4/5/6): the two headline step measurements
# first (two rounds of chipless surgery are stacked behind them), then
# the kernel race that decides the r05 wgrad-restage and sparse-conv1
# defaults, then the never-measured experiments (convergence curve,
# capacity/OOM, lm), then the wider tables.
cd "$(dirname "$0")/.." || exit 1
log() { echo "=== $1 $(date +%T) ===" >> measured/run_log.txt; }

# Stop LAUNCHING rungs 3.5h after recovery so the chip is free for the
# driver's end-of-round bench.
DEADLINE=$(( $(date +%s) + 12600 ))
rung_ok() {
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    log "DEADLINE reached - leaving the chip for the driver bench"
    exit 0
  fi
}

log "r05 ladder starting"

log "R0 images_per_sec bs=16 (r04 step + r05 gt-wgrad; preflight gate live)"
timeout 2400 python bench.py --batch-per-device 16 --steps 15 > measured/images_per_sec_s2dt_b16_r05.json 2> measured/images_per_sec_s2dt_b16_r05.err
log "R0 exit $?"

rung_ok
log "R1 images_per_sec bs=5 (the reference parity batch)"
timeout 2400 python bench.py --batch-per-device 5 --steps 15 > measured/images_per_sec_s2dt_b5_r05.json 2> measured/images_per_sec_s2dt_b5_r05.err
log "R1 exit $?"

rung_ok
log "R2 conv_micro repeats=3 (gt-vs-auto wgrad race + sparse conv1 race)"
timeout 3600 python tools/conv_micro.py --batch 16 > measured/conv_micro_r05.jsonl 2> measured/conv_micro_r05.err
log "R2 exit $?"

rung_ok
log "R3 convergence (tamed-lr loss curve at 3000^2 — VERDICT next-4)"
timeout 2400 python bench.py --metric convergence > measured/convergence_r05.json 2> measured/convergence_r05.err
log "R3 exit $?"

rung_ok
log "R4 capacity (the reference's OOM experiment, measured at last)"
timeout 3600 python bench.py --metric capacity > measured/capacity_r05.json 2> measured/capacity_r05.err
log "R4 exit $?"

rung_ok
log "R5 lm (dots remat, b16)"
timeout 2400 python bench.py --metric lm > measured/lm_r05.json 2> measured/lm_r05.err
log "R5 exit $?"

rung_ok
log "R6 pallas kernel checks + TFLOPs"
timeout 2400 python bench.py --metric pallas > measured/pallas_r05.json 2> measured/pallas_r05.err
log "R6 exit $?"

rung_ok
log "R7 sweep (batch ladder + plan race: s2dt vs scat-conv1 vs nhwc vs xla)"
timeout 5400 python bench.py --metric sweep --steps 8 > measured/sweep_r05.json 2> measured/sweep_r05.err
log "R7 exit $?"

rung_ok
log "R8 seq_scaling"
timeout 3600 python bench.py --metric seq_scaling > measured/seq_scaling_r05.json 2> measured/seq_scaling_r05.err
log "R8 exit $?"

log "R05 LADDER DONE - update BASELINE.md from measured/*_r05.*"
