"""Per-kernel conv micro-benchmark at the s2d plan's REAL shapes.

Round-3 motive: the first on-chip run of the fused-conv plan measured
254 ms/step at bs=16 against a 33 ms AOT traffic floor and a ~48 ms
compute floor (BASELINE.md "The 10x target, argued") — the Pallas convs
are executing near ~21 TF/s where the shape analysis predicted ~110.
This tool separates WHICH kernel (conv1/conv2 x fwd/bwd, Pallas vs the
XLA lax.conv it replaced) eats the step, with the same fetch-synced
differential timing as bench.py, so the optimization targets the
measured hot spot instead of the estimate.

Usage (chip): python tools/conv_micro.py [--batch 16] [--ops conv1_fwd,...]
Writes one JSON line per timed op to stdout.

Shapes (models/convnet_s2d.py, 3000^2 input):
  conv1: x [B,750,750,16]  w [3,3,16,256]   (r=4 scatter of 5x5 1->16)
  conv2: x [B,750,750,64]  w [3,3,64,128]   (r=2 scatter of 5x5 16->32)
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--iters", type=int, default=8)
    p.add_argument("--repeats", type=int, default=3,
                   help="timing repeats per op; rows publish the min and "
                        "the full sample list (run-to-run spread)")
    p.add_argument("--ops", type=str, default="")
    p.add_argument("--hw", type=int, default=750)
    p.add_argument("--force-cpu", action="store_true",
                   help="smoke-test the tool off-chip (interpret-mode "
                        "kernels; timings are not TPU claims). NEVER run "
                        "this tool on the chip while another bench holds "
                        "it — a mid-compile kill wedges the tunnel.")
    p.add_argument("--trace", type=str, default="",
                   help="directory for a jax.profiler trace of each timed "
                        "op (best-effort: the tunneled TPU may not "
                        "support device tracing; the timing numbers above "
                        "are the source of truth either way)")
    args = p.parse_args()

    if args.force_cpu:
        from tpu_sandbox.utils.cli import ensure_devices
        ensure_devices(1, force_cpu=True)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpu_sandbox.ops.pallas_conv import (
        _flip_transpose,
        conv3x3,
        conv3x3_reference,
        conv3x3_stats,
    )
    from tpu_sandbox.ops.pallas_conv_t import (
        conv3x3_t,
        conv3x3_t_stats,
        conv3x3_t_wgrad,
    )
    from tpu_sandbox.utils.profiling import (
        host_sync,
        measure_per_step_repeated,
        trace as profiling_trace,
    )

    b, hw = args.batch, args.hw
    rng = np.random.default_rng(0)
    dev = jax.devices()[0]

    def mk(shape, dt=jnp.bfloat16):
        # standard_normal(dtype=f32): rng.normal would stage a float64
        # host transient (~4.6 GB for conv2 at bs=16) next to a live chip
        return jnp.asarray(
            rng.standard_normal(size=shape, dtype=np.float32) * 0.1, dt)

    shapes = {
        "conv1": dict(x=(b, hw, hw, 16), w=(3, 3, 16, 256)),
        "conv2": dict(x=(b, hw, hw, 64), w=(3, 3, 64, 128)),
    }

    def fwd_flops(x, w):
        bb, h, wd, c = x
        return 2 * bb * h * wd * 9 * c * w[-1]

    def time_op(name, step_fn, flops, traffic_bytes, *ops):
        """step_fn(acc, *ops)->scalar must data-depend on acc. The
        operands are REAL jit arguments, not closure captures: captured
        arrays bake into the HLO as constants, and the tunnel's
        remote-compile HTTP request then ships them (288 MB at bs=16 ->
        HTTP 413 'length limit exceeded', observed on-chip)."""
        jstep = jax.jit(step_fn)

        def run_steps(k):
            acc = jnp.float32(0.0)
            for _ in range(k):
                acc = jstep(acc, *ops)
            return acc

        t = measure_per_step_repeated(run_steps, args.iters,
                                      repeats=args.repeats)
        spc = t["sec_per_step"]
        if args.trace:
            try:
                with profiling_trace(os.path.join(args.trace, name)):
                    host_sync(run_steps(2))
            except Exception as e:  # tracing is best-effort diagnostics
                print(json.dumps({"op": name,
                                  "trace_failed": f"{type(e).__name__}: "
                                                  f"{str(e)[:200]}"}),
                      flush=True)
        rec = {
            "op": name, "batch": b, "sec_per_call": round(spc, 6),
            "tflops": round(flops / spc / 1e12, 2) if spc > 0 else None,
            "hbm_gbps": round(traffic_bytes / spc / 1e9, 1)
            if spc > 0 else None,
            "flops": flops, "traffic_bytes_min": traffic_bytes,
            "device_kind": str(dev.device_kind),
            "timing_method": t["timing_method"],
            "repeats": t.get("repeats", 1),
            "sec_per_call_samples": t.get("sec_per_step_samples"),
            "spread_frac": t.get("spread_frac"),
        }
        if spc <= 0:
            # same rule as bench.py: a non-positive differential is timing
            # jitter, not a measurement — never rank kernels by this row
            rec["degraded"] = "non-positive differential; noise, not a time"
        print(json.dumps(rec), flush=True)

    want = set(filter(None, args.ops.split(",")))

    for cname, sh in shapes.items():
        x = mk(sh["x"])
        w = mk(sh["w"])
        bias = mk((sh["w"][-1],))
        fl = fwd_flops(sh["x"], sh["w"])
        nbytes = lambda s: int(np.prod(s)) * 2
        io_fwd = nbytes(sh["x"]) + nbytes(sh["x"][:3] + (sh["w"][-1],))

        # -------- forward: pallas (stats variant = production), pallas
        # plain, and the XLA conv it replaced --------
        # The timed scalar must be a FULL reduction of every computed
        # array: an element slice like y[0,0,0,0] lets XLA push the slice
        # through the conv and compute a handful of pixels — observed
        # on-chip as conv1_bwd_xla "321 TF/s" (> the 197 peak). The sum
        # adds one fused output pass to both sides identically.
        def red(a):
            return jnp.sum(a.astype(jnp.float32)) * 1e-9

        if not want or f"{cname}_fwd" in want:
            def s_pallas(acc, x, w, bias):
                y, s, ss = conv3x3_stats(x + acc.astype(x.dtype), w, bias)
                return red(y)
            time_op(f"{cname}_fwd_pallas_stats", s_pallas, fl, io_fwd,
                    x, w, bias)

            def s_plain(acc, x, w, bias):
                y = conv3x3(x + acc.astype(x.dtype), w, bias)
                return red(y)
            time_op(f"{cname}_fwd_pallas", s_plain, fl, io_fwd, x, w, bias)

            def s_xla(acc, x, w, bias):
                y = conv3x3_reference(x + acc.astype(x.dtype), w, bias)
                return red(y)
            time_op(f"{cname}_fwd_xla", s_xla, fl, io_fwd, x, w, bias)

        # -------- backward (dx+dw+db together, via vjp), pallas vs XLA ----
        if not want or f"{cname}_bwd" in want:
            g = mk(sh["x"][:3] + (sh["w"][-1],))

            def s_bwd(acc, x, w, bias, g):
                _, vjp = jax.vjp(
                    lambda xx, ww, bb: conv3x3(xx, ww, bb),
                    x + acc.astype(x.dtype), w, bias)
                dx, dw, db = vjp(g)
                return red(dx) + red(dw) + red(db)
            time_op(f"{cname}_bwd_pallas", s_bwd, 2 * fl,
                    2 * nbytes(sh["x"]) + 2 * nbytes(g.shape),
                    x, w, bias, g)

            def s_bwd_xla(acc, x, w, bias, g):
                _, vjp = jax.vjp(
                    lambda xx, ww, bb: conv3x3_reference(xx, ww, bb),
                    x + acc.astype(x.dtype), w, bias)
                dx, dw, db = vjp(g)
                return red(dx) + red(dw) + red(db)
            time_op(f"{cname}_bwd_xla", s_bwd_xla, 2 * fl,
                    2 * nbytes(sh["x"]) + 2 * nbytes(g.shape),
                    x, w, bias, g)

        # -------- transposed-layout kernels (pallas_conv_t): x [B,H,C,W]
        # — the round-3 rework; same math, channels on sublanes. The
        # big device arrays are shared across sections and dropped per
        # conv: per-section fresh 4.6 GB cotangents accumulated across
        # sections OOM'd the 16 GB chip on the first run --------
        t_ops = {f"{cname}_{o}" for o in
                 ("fwd_t", "bwd_t", "wgrad_t", "dgrad_t")}
        if cname == "conv1":
            t_ops.add("conv1_sparse")
        g_ops = t_ops - {f"{cname}_fwd_t"}
        if not want or (want & t_ops):
            xt = mk((sh["x"][0], sh["x"][1], sh["x"][3], sh["x"][2]))
        if not want or (want & g_ops):
            # only when a backward op needs it: at conv1 bs=16 this is a
            # 4.6 GB array on a 16 GB chip
            gt = mk((sh["x"][0], sh["x"][1], sh["w"][-1], sh["x"][2]))

        if not want or f"{cname}_fwd_t" in want:
            def s_t(acc, xt, w, bias):
                y = conv3x3_t(xt + acc.astype(xt.dtype), w, bias)
                return red(y)
            time_op(f"{cname}_fwd_pallas_t", s_t, fl, io_fwd, xt, w, bias)

            def s_t_stats(acc, xt, w, bias):
                y, s, ss = conv3x3_t_stats(xt + acc.astype(xt.dtype),
                                           w, bias)
                return red(y)
            time_op(f"{cname}_fwd_pallas_t_stats", s_t_stats, fl, io_fwd,
                    xt, w, bias)

        if not want or f"{cname}_bwd_t" in want:
            def s_bwd_t(acc, xt, w, bias, gt):
                _, vjp = jax.vjp(
                    lambda xx, ww, bb: conv3x3_t(xx, ww, bb),
                    xt + acc.astype(xt.dtype), w, bias)
                dx, dw, db = vjp(gt)
                return red(dx) + red(dw) + red(db)
            time_op(f"{cname}_bwd_pallas_t", s_bwd_t, 2 * fl,
                    2 * nbytes(sh["x"]) + 2 * nbytes(gt.shape),
                    xt, w, bias, gt)

        # wgrad alone (the isolated fused dw+db pass — what conv1's
        # backward pays in the real step, where dx is DCE'd) and dgrad
        # alone (fwd kernel on flipped weights)
        if not want or f"{cname}_wgrad_t" in want:
            # r05 restage race: explicit-gT native dot vs Mosaic's own
            # lane-lane handling (VERDICT r04 next-2, the named wgrad
            # per-row-transpose bottleneck). Same math (equality-tested);
            # sec_per_call decides the production default.
            for restage in ("gt", "auto"):
                def s_wgrad_t(acc, xt, gt, _r=restage):
                    dwt, db = conv3x3_t_wgrad(xt + acc.astype(xt.dtype),
                                              gt, restage=_r)
                    return red(dwt) + red(db)
                time_op(f"{cname}_wgrad_pallas_t[{restage}]", s_wgrad_t,
                        fl, nbytes(sh["x"]) + nbytes(gt.shape), xt, gt)

        if not want or f"{cname}_dgrad_t" in want:
            wf = _flip_transpose(w)
            zb = jnp.zeros((sh["x"][-1],), gt.dtype)

            def s_dgrad_t(acc, gt, wf, zb):
                y = conv3x3_t(gt + acc.astype(gt.dtype), wf, zb)
                return red(y)
            time_op(f"{cname}_dgrad_pallas_t", s_dgrad_t,
                    fwd_flops((sh["x"][0], sh["x"][1], sh["x"][2],
                               sh["w"][-1]), wf.shape),
                    nbytes(gt.shape) + nbytes(sh["x"]),
                    gt, wf, zb)

        # -------- the r04 sparse-tap conv1 (union tap tile, K=64):
        # race it against the scattered-3x3 rows above. Executed-flop
        # basis differs by design (64 vs 144 K-rows) — compare
        # sec_per_call, not tflops, across kernels --------
        if cname == "conv1" and (not want or "conv1_sparse" in want):
            from tpu_sandbox.ops.pallas_conv5_t import (
                conv1_s2d_t,
                conv1_s2d_t_stats,
                conv1_s2d_t_wgrad,
            )

            fl_sp = 2 * b * hw * hw * 64 * 256
            k5 = mk((5, 5, 1, 16))
            b16 = mk((16,))

            def s_sparse(acc, xt, k5, b16):
                y = conv1_s2d_t(xt + acc.astype(xt.dtype), k5, b16)
                return red(y)
            time_op("conv1_fwd_sparse", s_sparse, fl_sp, io_fwd,
                    xt, k5, b16)

            def s_sparse_stats(acc, xt, k5, b16):
                y, s, ss = conv1_s2d_t_stats(xt + acc.astype(xt.dtype),
                                             k5, b16)
                return red(y)
            time_op("conv1_fwd_sparse_stats", s_sparse_stats, fl_sp,
                    io_fwd, xt, k5, b16)

            for restage in ("gt", "auto"):
                def s_sparse_wgrad(acc, xt, gt, _r=restage):
                    dw1, db = conv1_s2d_t_wgrad(
                        xt + acc.astype(xt.dtype), gt, restage=_r)
                    return red(dw1) + red(db)
                time_op(f"conv1_wgrad_sparse[{restage}]", s_sparse_wgrad,
                        fl_sp, nbytes(sh["x"]) + nbytes(gt.shape), xt, gt)

        if not want or (want & t_ops):
            del xt
        if not want or (want & g_ops):
            del gt

        # -------- dgrad alone (fwd kernel, flipped weights) --------
        if not want or f"{cname}_dgrad" in want:
            g = mk(sh["x"][:3] + (sh["w"][-1],))
            wf = _flip_transpose(w)
            zb = jnp.zeros((sh["x"][-1],), g.dtype)

            def s_dgrad(acc, g, wf, zb):
                y = conv3x3(g + acc.astype(g.dtype), wf, zb)
                return red(y)
            time_op(f"{cname}_dgrad_pallas", s_dgrad,
                    fwd_flops(g.shape, wf.shape),
                    nbytes(g.shape) + nbytes(sh["x"]),
                    g, wf, zb)

    print(json.dumps({"note": "pair tflops against the shape's MXU "
                              "ceiling and hbm_gbps against ~819 GB/s "
                              "(v5e) to see which wall each kernel hits"}),
          flush=True)


if __name__ == "__main__":
    main()
