"""Chipless AOT receipt for the serve decode step: cache donation + cost.

Compiles ``serve/decode.py``'s single-token decode step for a v5e (no TPU
needed — jax.experimental.topologies) and reads XLA's own numbers:

- ``alias_size_in_bytes`` must cover both KV page buffers — the proof that
  the per-step cache update is in-place (donated), not a copy of the whole
  cache every token;
- argument/output/temp bytes and FLOPs — the decode step's HBM working
  set, which is what bounds tokens/sec on a real chip (decode is
  bandwidth-bound: the cache read dominates).

Usage:
  python tools/aot_serve.py                       # default geometry
  python tools/aot_serve.py --num-blocks 512 --block-size 16 --max-batch 8
  python tools/aot_serve.py --cache-dtype bf16    # half the cache traffic
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.aot_v5e import make_topology, unwrap_cost  # noqa: E402


def compile_decode(topo, *, num_blocks: int, block_size: int,
                   max_blocks_per_seq: int, max_batch: int,
                   cache_dtype_name: str):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
    from tpu_sandbox.serve.cache import CacheConfig
    from tpu_sandbox.serve.decode import make_decode_fn, page_shapes

    mesh = Mesh(np.array(topo.devices), ("data",))
    sh = NamedSharding(mesh, P())

    model_cfg = TransformerConfig()
    cache_cfg = CacheConfig(num_blocks=num_blocks, block_size=block_size,
                            max_blocks_per_seq=max_blocks_per_seq)
    cache_dtype = jnp.bfloat16 if cache_dtype_name == "bf16" else jnp.float32

    def sharded(s):
        return jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh)

    params = jax.eval_shape(
        lambda: TransformerLM(model_cfg).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"])
    params = jax.tree.map(sharded, params)
    kd, vd = (sharded(s) for s in page_shapes(model_cfg, cache_cfg,
                                              cache_dtype))
    fn = make_decode_fn(model_cfg, cache_cfg, max_batch, cache_dtype)
    compiled = fn.lower(
        params, kd, vd,
        sharded(jax.ShapeDtypeStruct((max_batch, 1), jnp.int32)),
        sharded(jax.ShapeDtypeStruct((max_batch,), jnp.int32)),
        sharded(jax.ShapeDtypeStruct(
            (max_batch, cache_cfg.max_blocks_per_seq), jnp.int32)),
    ).compile()
    cache_bytes = 2 * kd.size * kd.dtype.itemsize
    return compiled, cache_bytes, model_cfg, cache_cfg


def analyze(compiled, cache_bytes: int, args) -> dict:
    ma = compiled.memory_analysis()
    ca = unwrap_cost(compiled)
    alias = ma.alias_size_in_bytes
    return {
        "metric": "serve_aot_donation",
        "geometry": {
            "num_blocks": args.num_blocks, "block_size": args.block_size,
            "max_blocks_per_seq": args.max_blocks_per_seq,
            "max_batch": args.max_batch, "cache_dtype": args.cache_dtype,
        },
        "kv_cache_bytes": cache_bytes,
        "alias_bytes": alias,
        # the decode step donates both page buffers: XLA must alias at
        # least the full cache input->output (anything less means a
        # fresh cache copy per generated token)
        "donation_verified": alias >= cache_bytes,
        "argument_bytes": ma.argument_size_in_bytes,
        "output_bytes": ma.output_size_in_bytes,
        "temp_bytes": ma.temp_size_in_bytes,
        "flops_per_step": ca.get("flops"),
        "bytes_accessed_per_step": ca.get("bytes accessed"),
        "source": "chipless v5e AOT compile (XLA estimates, not "
                  "measurements)",
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--num-blocks", type=int, default=64)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--max-blocks-per-seq", type=int, default=16)
    p.add_argument("--max-batch", type=int, default=4)
    p.add_argument("--cache-dtype", choices=["fp32", "bf16"], default="fp32")
    args = p.parse_args()
    topo = make_topology()
    compiled, cache_bytes, _, _ = compile_decode(
        topo, num_blocks=args.num_blocks, block_size=args.block_size,
        max_blocks_per_seq=args.max_blocks_per_seq,
        max_batch=args.max_batch, cache_dtype_name=args.cache_dtype)
    print(json.dumps(analyze(compiled, cache_bytes, args)))


if __name__ == "__main__":
    main()
