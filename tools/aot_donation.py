"""Chipless donation receipt: AOT-compile the DP and ZeRO train steps with
donation on and off and read the peak-memory delta from XLA's own
memory analysis.

Donation aliases the old ``TrainState`` buffers into the new state's
outputs; without it both generations are live across the step and the
outputs need their own allocation on top of arguments + temps. The CPU
backend does not implement donation (aliasing always 0 there), so this is
strictly a TPU-topology tool — ``bench.py --metric donation`` shells out
here and degrades gracefully off-toolchain.

Single-process like every AOT tool (libtpu init + forced compiled
kernels): do not run two at once, never import into a pytest process.

Usage: python tools/aot_donation.py [--topology v5e:2x2x1]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def measure(topo, *, zero: bool, donate: bool, batch_per_rank: int = 8) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from tpu_sandbox.models import ConvNet
    from tpu_sandbox.parallel import DataParallel
    from tpu_sandbox.train import TrainState

    devices = np.array(topo.devices)
    world = devices.size
    mesh = Mesh(devices, ("data",))
    model = ConvNet(use_bn=False)
    tx = optax.sgd(1e-2, momentum=0.9)
    state = jax.eval_shape(lambda: TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx,
    ))
    imgs = jax.ShapeDtypeStruct(
        (world * batch_per_rank, 28, 28, 1), jnp.float32)
    labs = jax.ShapeDtypeStruct((world * batch_per_rank,), jnp.int32)
    dp = DataParallel(model, tx, mesh, zero=zero, donate=donate)
    ma = dp.lower_step(state, imgs, labs).compile().memory_analysis()
    out = {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
    }
    # donated outputs alias arguments; undonated outputs are a second live
    # copy of the state on top of args + temps
    unaliased_out = out["output_bytes"] - out["alias_bytes"]
    out["est_peak_bytes"] = (
        out["argument_bytes"] + out["temp_bytes"] + unaliased_out)
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--topology", default="v5e:2x2x1")
    p.add_argument("--chips-per-host", default="2,2,1")
    p.add_argument("--batch-per-rank", type=int, default=8)
    args = p.parse_args()

    from aot_v5e import make_topology

    topo = make_topology(
        args.topology, tuple(int(x) for x in args.chips_per_host.split(",")))
    result: dict = {
        "metric": "donation",
        "topology": args.topology,
        "source": "chipless v5e AOT memory analysis "
                  "(XLA estimates, not measurements)",
    }
    for label, zero in (("dp", False), ("zero", True)):
        on = measure(topo, zero=zero, donate=True,
                     batch_per_rank=args.batch_per_rank)
        off = measure(topo, zero=zero, donate=False,
                      batch_per_rank=args.batch_per_rank)
        result[label] = {
            "donate_on": on,
            "donate_off": off,
            "peak_delta_bytes": off["est_peak_bytes"] - on["est_peak_bytes"],
            "donation_verified": on["alias_bytes"] > 0,
        }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
