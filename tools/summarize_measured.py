"""One-screen summary of the measured/ story for a round.

Reads every ``measured/*_r{N}*.json[l]`` artifact plus the current-round
err files and prints a compact table: headline images/sec lines (with
plan, loss flag, fallbacks), capacity, kernel micro rows (min + spread),
lm/seq rows, and which rungs never produced output. Run after the
recovery ladder (tools/rerun_on_recovery.sh) finishes — or any time, to
see what is still missing.

Usage: python tools/summarize_measured.py [--round 4]
"""

from __future__ import annotations

import argparse
import glob
import json
import os


def _rows(path):
    text = open(path).read()
    try:  # whole-file JSON (indented artifacts like hlo_cycles_*)
        doc = json.loads(text)
        if isinstance(doc, dict):
            return [doc]
        if isinstance(doc, list):
            return [d for d in doc if isinstance(d, dict)]
        return []  # scalar JSON (a partial write): report as empty
    except json.JSONDecodeError:
        pass
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            d = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(d, dict):  # bare strings inside indented JSON
            out.append(d)
    return out


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--round", type=int, default=4)
    args = p.parse_args()
    tag = f"_r{args.round:02d}"
    base = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "measured")

    files = sorted(glob.glob(os.path.join(base, f"*{tag}*")))
    if not files:
        print(f"no measured/*{tag}* artifacts yet")
    for path in files:
        name = os.path.basename(path)
        if name.endswith(".err"):
            size = os.path.getsize(path)
            if size:
                tail = open(path, errors="replace").read()[-300:]
                print(f"-- {name}: {size} B of stderr; tail: ...{tail!r}")
            continue
        if not name.endswith((".json", ".jsonl")):
            # plain-text artifacts (probe transcripts etc.): present, not
            # a dead rung — show the first line instead of crying EMPTY
            first = open(path, errors="replace").readline().strip()
            print(f"-- {name}: text artifact ({os.path.getsize(path)} B): "
                  f"{first[:100]}")
            continue
        rows = _rows(path)
        if not rows:
            print(f"-- {name}: EMPTY (rung died before its JSON line)")
            continue
        print(f"-- {name}")
        shown = 0
        for r in rows:
            if "metric" in r:
                bits = [f"{r['metric']}={r.get('value')}",
                        f"unit={r.get('unit')}"]
                for k in ("execution_plan", "kernel_plan", "global_batch",
                          "sec_per_step", "mfu", "final_loss", "loss_flag",
                          "plan_fallback", "degraded", "spread_frac"):
                    if r.get(k) is not None:
                        bits.append(f"{k}={r[k]}")
                print("   " + "  ".join(str(b) for b in bits))
            elif "sec_per_call" in r:  # conv_micro kernel rows
                print(f"   {r.get('op')}: {r['sec_per_call']}s  "
                      f"tflops={r.get('tflops')}  "
                      f"spread={r.get('spread_frac')}"
                      + ("  INVALID" if r.get("invalid")
                         or r.get("degraded") else ""))
            elif "bytes_accessed" in r:  # AOT compile rows
                print(f"   plan={r.get('plan')} batch={r.get('batch')} "
                      f"bytes={r.get('bytes_accessed')} "
                      f"peak_gb={r.get('est_peak_gb')} "
                      f"fits={r.get('fits_16g_hbm')}")
            else:
                continue  # per-op traffic / breakdown rows: skip detail
            shown += 1
        if not shown:
            print(f"   ({len(rows)} rows, no summary-known shape)")


if __name__ == "__main__":
    main()
