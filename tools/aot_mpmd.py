"""Chipless AOT receipt for the MPMD pipeline: per-stage executables.

The SPMD pipeline compiles ONE program that every pipe rank executes.
The MPMD claim is the opposite — each stage gang compiles ONLY its own
program — and this tool is the receipt: it AOT-compiles every stage's
train programs for a v5e topology (no TPU needed,
jax.experimental.topologies) and reads XLA's own numbers per stage:

- stage 0's executables carry the embedding table and no LM head; the
  last stage's the reverse; interior stages carry neither — visible in
  per-stage ``param_bytes`` and the has_embedding/has_head flags;
- per-program argument/output/temp bytes and FLOPs, which is what a
  per-stage mesh actually holds and executes (the whole point of MPMD:
  no stage pays memory or compile time for another stage's layers).

Usage:
  python tools/aot_mpmd.py                        # default geometry
  python tools/aot_mpmd.py --n-stages 8 --n-layers 32
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.aot_v5e import make_topology, unwrap_cost  # noqa: E402


def mpmd_aot_report(*, n_stages: int = 4, microbatches: int = 4,
                    vocab_size: int = 8192, d_model: int = 256,
                    n_layers: int = 8, n_heads: int = 8, d_ff: int = 1024,
                    batch: int = 32, seqlen: int = 128,
                    layer_split: list[int] | None = None,
                    zb: bool = False) -> dict:
    """Compile every stage's programs chiplessly; returns the receipt.

    ``layer_split`` compiles an uneven pipeline (per-stage layer counts);
    ``zb`` lowers the ZB-H1 split backward (bwd_input / bwd_weight as
    separate executables) instead of the fused one, so the receipt shows
    what each half actually costs — the numbers ``schedule.autotune_plan``
    trades against."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
    from tpu_sandbox.mpmd.program import (
        StageProgram,
        check_layer_split,
        stage_params,
    )
    from tpu_sandbox.mpmd.schedule import bubble_fraction

    topo = make_topology()
    cfg = TransformerConfig(vocab_size=vocab_size, d_model=d_model,
                            n_heads=n_heads, n_layers=n_layers, d_ff=d_ff,
                            max_len=max(seqlen, 128))
    # a real (tiny, CPU) init supplies the per-stage param trees; only
    # shapes reach the chipless compile below
    flat = jax.tree.map(
        np.asarray,
        TransformerLM(cfg).init(jax.random.key(0),
                                jnp.zeros((1, seqlen), jnp.int32))["params"])
    tx = optax.sgd(0.1)
    mb_rows = max(1, batch // microbatches)
    # one single-chip mesh PER STAGE — the chipless twin of one mesh per
    # stage gang; every stage's programs are compiled against its own
    mesh = Mesh(np.array(topo.devices), ("stage",))
    sh = NamedSharding(mesh, P())

    def sharded_like(x):
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype,
                                    sharding=sh)

    split = check_layer_split(n_layers, n_stages, layer_split)
    stages = []
    for s in range(n_stages):
        prog = StageProgram(cfg, tx, s, n_stages, microbatches,
                            layer_split=layer_split)
        sp = stage_params(flat, s, n_stages, layer_split=layer_split)
        absp = jax.tree.map(sharded_like, sp)
        if prog.is_first:
            x = jax.ShapeDtypeStruct((mb_rows, seqlen), jnp.int32,
                                     sharding=sh)
        else:
            x = jax.ShapeDtypeStruct((mb_rows, seqlen, d_model), cfg.dtype,
                                     sharding=sh)
        targets = jax.ShapeDtypeStruct((mb_rows, seqlen), jnp.int32,
                                       sharding=sh)
        lowered = prog.lower_train_programs(
            absp, x, targets if prog.is_last else None, zb=zb)
        programs = {}
        for name, low in lowered.items():
            compiled = low.compile()
            ma = compiled.memory_analysis()
            ca = unwrap_cost(compiled)
            programs[name] = {
                "argument_bytes": ma.argument_size_in_bytes,
                "output_bytes": ma.output_size_in_bytes,
                "temp_bytes": ma.temp_size_in_bytes,
                "flops": ca.get("flops"),
            }
        param_bytes = sum(
            int(np.asarray(leaf).nbytes) for leaf in jax.tree.leaves(sp))
        stages.append({
            "stage": s,
            "layers_local": split[s],
            "param_bytes": param_bytes,
            "has_embedding": "pre" in sp,
            "has_head": "post" in sp,
            "programs": programs,
        })

    return {
        "metric": "mpmd_aot_stages",
        "geometry": {
            "n_stages": n_stages, "microbatches": microbatches,
            "vocab_size": vocab_size, "d_model": d_model,
            "n_layers": n_layers, "n_heads": n_heads, "d_ff": d_ff,
            "batch": batch, "seqlen": seqlen,
            "layer_split": split, "zb": zb,
        },
        "bubble_fraction": bubble_fraction(n_stages, microbatches),
        "stages": stages,
        # the MPMD claim, checked from XLA's own accounting: embedding
        # weight lives in stage 0's executable only, the head in the
        # last stage's only — no stage compiles another stage's program
        "only_first_stage_has_embedding": all(
            r["has_embedding"] == (r["stage"] == 0) for r in stages),
        "only_last_stage_has_head": all(
            r["has_head"] == (r["stage"] == n_stages - 1) for r in stages),
        "source": "chipless v5e AOT compile of each stage's own programs "
                  "(XLA estimates, not measurements)",
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--n-stages", type=int, default=4)
    p.add_argument("--microbatches", type=int, default=4)
    p.add_argument("--vocab-size", type=int, default=8192)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-layers", type=int, default=8)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--d-ff", type=int, default=1024)
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--seqlen", type=int, default=128)
    p.add_argument("--layer-split", default="",
                   help="json list of per-stage layer counts, e.g. [3,3,2]")
    p.add_argument("--zb", action="store_true",
                   help="lower the ZB-H1 split backward "
                   "(bwd_input/bwd_weight) instead of the fused one")
    args = p.parse_args()
    print(json.dumps(mpmd_aot_report(
        n_stages=args.n_stages, microbatches=args.microbatches,
        vocab_size=args.vocab_size, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads, d_ff=args.d_ff,
        batch=args.batch, seqlen=args.seqlen,
        layer_split=(json.loads(args.layer_split)
                     if args.layer_split else None),
        zb=args.zb)))


if __name__ == "__main__":
    main()
