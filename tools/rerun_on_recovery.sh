#!/bin/bash
# Probe the wedged tunnel every 4 min (subprocess probe, never bare
# jax.devices()); when it answers, EXEC the round's measurement ladder.
# The ladder lives in its own file (tools/ladder_r05.sh) precisely so it
# can be edited while this watcher is armed: bash reads scripts
# incrementally, so editing a RUNNING script corrupts it, but exec
# reads the ladder fresh at recovery time (see memory:
# tpu-chip-discipline).
cd "$(dirname "$0")/.." || exit 1
echo "=== RECOVERY WATCH (r05) started $(date +%T) ===" >> measured/run_log.txt
while true; do
  if python -c "import bench,sys; sys.exit(0 if bench.accelerator_usable() else 1)" 2>/dev/null; then
    break
  fi
  sleep 240
done
echo "=== chip recovered $(date +%T) ===" >> measured/run_log.txt
exec bash tools/ladder_r05.sh
