#!/bin/bash
# Probe the wedged tunnel every 4 min (subprocess probe, never bare
# jax.devices()); when it answers, run the round-3 rerun ladder
# sequentially. ONE chip process at a time — nothing else may touch the
# chip while this runs (see memory: tpu-chip-discipline).
cd "$(dirname "$0")/.." || exit 1
log() { echo "=== $1 $(date +%T) ===" >> measured/run_log.txt; }

log "RECOVERY WATCH started"
while true; do
  if python -c "import bench,sys; sys.exit(0 if bench.accelerator_usable() else 1)" 2>/dev/null; then
    break
  fi
  sleep 240
done
log "chip recovered; rerun ladder starting"

log "R0 conv_micro (per-kernel diagnosis, bs=16)"
timeout 3000 python tools/conv_micro.py --batch 16 > measured/conv_micro_r03.jsonl 2> measured/conv_micro_r03.err
log "R0 exit $?"

log "R1 pallas (fixed f32 tol)"
timeout 1800 python bench.py --metric pallas > measured/pallas_r03.json 2> measured/pallas_r03.err
log "R1 exit $?"

log "R2 lm (dots remat, b16)"
timeout 2400 python bench.py --metric lm > measured/lm_dots_b16_r03.json 2> measured/lm_dots_b16_r03.err
log "R2 exit $?"

log "R3 capacity"
timeout 2400 python bench.py --metric capacity > measured/capacity_r03.json 2> measured/capacity_r03.err
log "R3 exit $?"

log "R4 sweep"
timeout 3600 python bench.py --metric sweep --steps 5 > measured/sweep_r03.json 2> measured/sweep_r03.err
log "R4 exit $?"

log "R5 seq_scaling"
timeout 3600 python bench.py --metric seq_scaling > measured/seq_scaling_r03.json 2> measured/seq_scaling_r03.err
log "R5 exit $?"

log "RERUN LADDER DONE"
