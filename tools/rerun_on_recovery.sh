#!/bin/bash
# Probe the wedged tunnel every 4 min (subprocess probe, never bare
# jax.devices()); when it answers, run the round-3 rerun ladder
# sequentially. ONE chip process at a time — nothing else may touch the
# chip while this runs (see memory: tpu-chip-discipline).
#
# r03 status before arming: bs=16 s2dt measured 80.36 img/s (1.07x
# baseline, measured/images_per_sec_s2dt_b16.json); the tunnel wedged
# before the bs=5 run. The ladder finishes the measured story: parity
# batch, capacity, the plan race, LM dots-remat, kernel checks,
# seq scaling.
cd "$(dirname "$0")/.." || exit 1
log() { echo "=== $1 $(date +%T) ===" >> measured/run_log.txt; }

log "RECOVERY WATCH started"
while true; do
  if python -c "import bench,sys; sys.exit(0 if bench.accelerator_usable() else 1)" 2>/dev/null; then
    break
  fi
  sleep 240
done
log "chip recovered; rerun ladder starting"

log "R0 images_per_sec bs=5 (s2dt, the reference parity batch)"
timeout 2400 python bench.py --batch-per-device 5 --steps 15 > measured/images_per_sec_s2dt_b5.json 2> measured/images_per_sec_s2dt_b5.err
log "R0 exit $?"

log "R1 capacity (s2dt: AOT says bs=16 at 11.8 GB -> headroom above 16)"
timeout 3600 python bench.py --metric capacity > measured/capacity_r03.json 2> measured/capacity_r03.err
log "R1 exit $?"

log "R2 sweep (batch ladder + plan race: s2dt vs nhwc vs xla)"
timeout 5400 python bench.py --metric sweep --steps 8 > measured/sweep_r03.json 2> measured/sweep_r03.err
log "R2 exit $?"

log "R3 lm (dots remat, b16)"
timeout 2400 python bench.py --metric lm > measured/lm_dots_b16_r03.json 2> measured/lm_dots_b16_r03.err
log "R3 exit $?"

log "R4 pallas (now incl. transposed kernels)"
timeout 2400 python bench.py --metric pallas > measured/pallas_r03.json 2> measured/pallas_r03.err
log "R4 exit $?"

log "R5 seq_scaling"
timeout 3600 python bench.py --metric seq_scaling > measured/seq_scaling_r03.json 2> measured/seq_scaling_r03.err
log "R5 exit $?"

log "RERUN LADDER DONE"
