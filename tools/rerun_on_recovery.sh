#!/bin/bash
# Probe the wedged tunnel every 4 min (subprocess probe, never bare
# jax.devices()); when it answers, run the ROUND-4 measurement ladder
# sequentially. ONE chip process at a time — nothing else may touch the
# chip while this runs (see memory: tpu-chip-discipline).
#
# r04 status before arming: the s2dt step lost its ~95ms of layout glue
# chiplessly (fused input stage + in-layout fc; AOT non-kernel cycles
# 141.7 -> 65.3 ms, measured/hlo_cycles_s2dt_b16_r04.json). The ladder
# measures the new step first at both batch sizes (VERDICT r03 next-1/2:
# bs=16 headline target >=150 img/s; bs=5 is the reference parity batch),
# then the three never-measured experiments (capacity, lm, seq_scaling)
# and the repeat-aware kernel micro (next-7: classify the r03 bwd
# discrepancy as noise or state).
cd "$(dirname "$0")/.." || exit 1
log() { echo "=== $1 $(date +%T) ===" >> measured/run_log.txt; }

# Global deadline: stop LAUNCHING rungs 3.5h after the chip recovers so
# the chip is free for the driver's end-of-round bench (worst-case rung
# timeouts sum to ~7h — holding the chip that long would collide with
# the one run that produces BENCH_r04.json). R0-R3 are the critical
# measurements and land well inside the window.
rung_ok() {
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    log "DEADLINE reached - leaving the chip for the driver bench"
    exit 0
  fi
}

log "RECOVERY WATCH (r04) started"
while true; do
  if python -c "import bench,sys; sys.exit(0 if bench.accelerator_usable() else 1)" 2>/dev/null; then
    break
  fi
  sleep 240
done
log "chip recovered; r04 ladder starting"
DEADLINE=$(( $(date +%s) + 12600 ))

log "R0 images_per_sec bs=16 (new step: fused input + in-layout fc)"
timeout 2400 python bench.py --batch-per-device 16 --steps 15 > measured/images_per_sec_s2dt_b16_r04.json 2> measured/images_per_sec_s2dt_b16_r04.err
log "R0 exit $?"

rung_ok
log "R1 images_per_sec bs=5 (the reference parity batch)"
timeout 2400 python bench.py --batch-per-device 5 --steps 15 > measured/images_per_sec_s2dt_b5_r04.json 2> measured/images_per_sec_s2dt_b5_r04.err
log "R1 exit $?"

rung_ok
log "R2 capacity (the reference's OOM experiment, measured at last)"
timeout 3600 python bench.py --metric capacity > measured/capacity_r04.json 2> measured/capacity_r04.err
log "R2 exit $?"

rung_ok
log "R3 conv_micro repeats=3 (spread protocol; bwd discrepancy reclass)"
timeout 3600 python tools/conv_micro.py --batch 16 > measured/conv_micro_r04.jsonl 2> measured/conv_micro_r04.err
log "R3 exit $?"

rung_ok
log "R4 lm (dots remat, b16)"
timeout 2400 python bench.py --metric lm > measured/lm_dots_b16_r04.json 2> measured/lm_dots_b16_r04.err
log "R4 exit $?"

rung_ok
log "R5 pallas kernel checks (incl. transposed kernels) + TFLOPs"
timeout 2400 python bench.py --metric pallas > measured/pallas_r04.json 2> measured/pallas_r04.err
log "R5 exit $?"

rung_ok
log "R6 sweep (batch ladder + plan race: s2dt vs nhwc vs xla)"
timeout 5400 python bench.py --metric sweep --steps 8 > measured/sweep_r04.json 2> measured/sweep_r04.err
log "R6 exit $?"

rung_ok
log "R7 seq_scaling"
timeout 3600 python bench.py --metric seq_scaling > measured/seq_scaling_r04.json 2> measured/seq_scaling_r04.err
log "R7 exit $?"

log "R04 RERUN LADDER DONE — update BASELINE.md from measured/*_r04.*"
