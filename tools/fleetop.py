#!/usr/bin/env python3
"""fleetop — live ops console for the health plane.

Connects to the cluster KV store and renders, from durable state alone
(no process needs to cooperate):

- fleet health gauges from the tsdb ring (queue depth, replica count,
  goodput, recorder drops) and the per-series producer list;
- the where-time-goes panel: the live critical-path segment breakdown
  published by ``obs/critpath.publish_profile`` and the per-stage MPMD
  ``mpmd.bubble_fraction`` gauges, when either is present;
- per-replica occupancy and SLO burn: the TTL'd load reports next to
  each replica's shed/done burn rate over the recent window, with
  replicas currently excluded from routing (active ``replica_burn``)
  flagged;
- the deployment panel: per-replica serving version, each fleet's
  rollout phase, live canary shares, and the last verdict/rollback from
  the durable decision log;
- active alerts (the TTL'd condition flags control planes act on) and
  the most recent durable alert records;
- postmortem pointers: the ``tracecat`` invocation that reconstructs
  the causal timeline around each recent alert.

    python tools/fleetop.py --port 5999
        One shot: render and exit.

    python tools/fleetop.py --port 5999 --watch 2
        Clear-screen refresh every 2 s until interrupted.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_sandbox.obs import health, tsdb  # noqa: E402
from tpu_sandbox.runtime.kvstore import KVClient  # noqa: E402
from tpu_sandbox.serve.replica import read_load_reports  # noqa: E402

#: fleet gauges worth a headline line when any process publishes them
FLEET_GAUGES = (
    "sched.queue.depth", "sched.running", "autoscale.replicas",
    "serve.goodput", "obs.recorder.dropped",
)

#: trailing window for the burn columns, in fine buckets
BURN_BUCKETS = 12


def _fmt_num(v) -> str:
    if v is None:
        return "-"
    f = float(v)
    return str(int(f)) if f == int(f) else f"{f:.3f}"


def _burn_by_proc(kv) -> dict[str, tuple[float, float, float | None]]:
    """proc -> (shed, done, burn rate) over the trailing window."""
    shed_rows = tsdb.read_series(kv, "engine.shed")
    done_rows = tsdb.read_series(kv, "engine.done")
    newest = max((r["bucket"] for r in shed_rows + done_rows), default=0)
    since = newest - BURN_BUCKETS + 1
    shed = tsdb.window_sum(shed_rows, since_bucket=since, per_proc=True)
    done = tsdb.window_sum(done_rows, since_bucket=since, per_proc=True)
    out = {}
    for proc in sorted(set(shed) | set(done)):
        s, d = shed.get(proc, 0.0), done.get(proc, 0.0)
        rate = s / (s + d) if s + d > 0 else None
        out[proc] = (s, d, rate)
    return out


def _series_labels(series: str) -> dict[str, str]:
    """``name{seg=route,proc=x}`` -> {"seg": "route", ...}."""
    if "{" not in series or not series.endswith("}"):
        return {}
    body = series[series.index("{") + 1:-1]
    out = {}
    for pair in body.split(","):
        if "=" in pair:
            k, v = pair.split("=", 1)
            out[k] = v
    return out


def _latest_by_label(rows: list[dict], label: str) -> dict[str, float]:
    """Newest gauge value per distinct value of ``label`` across all
    producers (fleet-wide view: last writer wins per label value)."""
    best: dict[str, dict] = {}
    for r in rows:
        if r["kind"] == "counter":
            continue
        key = _series_labels(r["series"]).get(label, "?")
        cur = best.get(key)
        if cur is None or r["bucket"] >= cur["bucket"]:
            best[key] = r
    return {k: float(v["v"]) for k, v in best.items()}


def _critpath_panel(kv, lines) -> None:
    """Where time goes: the live segment breakdown published by
    ``obs/critpath.publish_profile`` plus the per-stage pipeline bubble
    the MPMD stage workers publish online."""
    shares = _latest_by_label(
        tsdb.read_series(kv, "critpath.segment.share"), "seg")
    bubbles = _latest_by_label(
        tsdb.read_series(kv, "mpmd.bubble_fraction"), "stage")
    if not shares and not bubbles:
        return
    lines.append("")
    lines.append("where time goes:")
    if shares:
        ms = _latest_by_label(
            tsdb.read_series(kv, "critpath.segment.ms"), "seg")
        for seg, share in sorted(shares.items(), key=lambda kv_: -kv_[1]):
            med = ms.get(seg)
            lines.append(
                f"  {seg:<14} {share:>6.1%} of request wall"
                + ("" if med is None else f"   median {med:.3f}ms"))
        cov = tsdb.latest_value(tsdb.read_series(kv, "critpath.coverage"))
        if cov is not None:
            lines.append(f"  attribution coverage {float(cov):.1%}")
    if bubbles:
        lines.append("  mpmd bubble: " + "  ".join(
            f"stage{stage}={frac:.3f}"
            for stage, frac in sorted(bubbles.items())))


def _deploy_panel(kv, reports, lines, now) -> None:
    """Continuous-deployment state, reconstructed from the registry alone:
    per-fleet target version, the active rollout's phase, live canary
    shares, the latest canary verdict, and the most recent rollback."""
    from tpu_sandbox.deploy.registry import (  # noqa: E402
        audited_fleets, current_target, deploy_events, fleet_label,
        read_shares, registry_versions, rollout_phase,
    )

    fleets = audited_fleets(kv)
    lines.append("")
    lines.append("deployment:")
    if not fleets:
        lines.append("  no registry state")
        return
    events = deploy_events(kv)
    for fleet in fleets:
        target = current_target(kv, fleet)
        versions = registry_versions(kv, fleet)
        active = None
        for seq in sorted(versions, reverse=True):
            ph = rollout_phase(kv, fleet, seq)
            if ph["rec"] is not None and ph["done"] is None \
                    and ph["reject"] is None:
                active = ph
                break
        if active is None:
            phase_desc = "idle"
        else:
            verdict = active["verdict"]
            if verdict is None:
                phase_desc = f"v{active['ver']} canary"
            else:
                phase_desc = (f"v{active['ver']} converging "
                              f"(canary {verdict.get('outcome', '?')})")
        lines.append(f"  fleet {fleet}: target v{target}, "
                     f"{len(versions)} registered, rollout {phase_desc}")
        shares = read_shares(kv, fleet)
        if shares:
            lines.append("    canary shares: " + ", ".join(
                f"v{v}={s:.0%}" for v, s in sorted(shares.items())))
        label = fleet_label(fleet)
        last_verdict = next(
            (e for e in reversed(events)
             if e.get("fleet") == label
             and e.get("action") in ("canary_fail", "promoted")), None)
        if last_verdict is not None:
            age = now - float(last_verdict.get("wall", now))
            lines.append(f"    last canary verdict: "
                         f"{last_verdict['action']} v"
                         f"{last_verdict.get('ver', '?')} "
                         f"({age:.0f}s ago)")
        last_rb = next(
            (e for e in reversed(events)
             if e.get("fleet") == label
             and e.get("action") == "rolled_back"), None)
        if last_rb is not None:
            age = now - float(last_rb.get("wall", now))
            lines.append(f"    last rollback: v{last_rb.get('ver', '?')} "
                         f"-> v{last_rb.get('target', '?')} "
                         f"({age:.0f}s ago)")


def render(kv, *, now: float | None = None, max_alerts: int = 8) -> str:
    """The whole console as one string — pure so tests can assert on it
    and ``--watch`` can diff it."""
    now = time.time() if now is None else now
    lines = [f"fleetop @ {time.strftime('%H:%M:%S', time.localtime(now))}"]

    # -- fleet gauges --------------------------------------------------------
    lines.append("")
    lines.append("fleet:")
    shown = 0
    for name in FLEET_GAUGES:
        rows = tsdb.read_series(kv, name)
        if not rows:
            continue
        val = tsdb.latest_value(rows)
        procs = sorted({r["proc"] for r in rows})
        lines.append(f"  {name:<24} {_fmt_num(val):>10}   "
                     f"({len(procs)} producer"
                     f"{'s' if len(procs) != 1 else ''})")
        shown += 1
    series = tsdb.list_series(kv)
    lines.append(f"  {len(series)} live series from "
                 f"{len({p for p, _ in series})} processes"
                 if series else "  no time series published yet")

    # -- per-replica occupancy + burn ---------------------------------------
    reports = read_load_reports(kv)
    burns = _burn_by_proc(kv)
    excluded = health.active_subjects(kv, "replica_burn")
    lines.append("")
    lines.append("replicas:")
    tags = sorted(set(reports) | set(burns))
    if not tags:
        lines.append("  none reporting")
    else:
        lines.append(f"  {'tag':<16} {'ver':>5} {'queue':>6} {'active':>7} "
                     f"{'shed':>6} {'done':>6} {'burn':>7}  routing")
        for tag in tags:
            rep = reports.get(tag, {})
            # load reports key on the raw tag; the tsdb proc name is the
            # same tag with '/' flattened (see ReplicaWorker)
            s, d, rate = burns.get(
                tag, burns.get(tag.replace("/", "-"), (0.0, 0.0, None)))
            routing = "EXCLUDED" if (
                tag in excluded or tag.replace("/", "-") in excluded
            ) else "ok"
            lines.append(
                f"  {tag:<16} {_fmt_num(rep.get('ver')):>5} "
                f"{_fmt_num(rep.get('queue_depth')):>6} "
                f"{_fmt_num(rep.get('active')):>7} {_fmt_num(s):>6} "
                f"{_fmt_num(d):>6} "
                f"{('-' if rate is None else f'{rate:.1%}'):>7}  {routing}")

    _critpath_panel(kv, lines)

    _deploy_panel(kv, reports, lines, now)

    # -- alerts --------------------------------------------------------------
    active = health.active_alerts(kv)
    lines.append("")
    lines.append(f"active alerts ({len(active)}):")
    for a in active:
        lines.append(f"  [{a.get('rule', '?')}] {a.get('subject', '?')} "
                     f"window={a.get('window_idx', '?')}")
    if not active:
        lines.append("  none")

    recent = health.alerts(kv)[-max_alerts:]
    lines.append("")
    lines.append(f"recent alert records (last {len(recent)}):")
    for a in recent:
        age = now - float(a.get("wall", now))
        lines.append(f"  {age:7.1f}s ago  [{a.get('rule', '?')}] "
                     f"{a.get('subject', '?')}")
    if not recent:
        lines.append("  none")

    # -- postmortem pointers -------------------------------------------------
    trace_dir = os.environ.get("TPU_SANDBOX_TRACE_DIR", "")
    if recent:
        lines.append("")
        if trace_dir:
            oldest = now - float(recent[0].get("wall", now)) + 5.0
            lines.append("postmortem: python tools/tracecat.py "
                         f"{trace_dir} --last {max(oldest, 5.0):.0f}s")
        else:
            lines.append("postmortem: set TPU_SANDBOX_TRACE_DIR and rerun "
                         "with tracing to get causal timelines")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleetop", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--host", default="127.0.0.1",
                    help="KV store host (default 127.0.0.1)")
    ap.add_argument("--port", type=int, required=True,
                    help="KV store port")
    ap.add_argument("--watch", type=float, metavar="SECONDS", default=None,
                    help="refresh every N seconds until interrupted")
    args = ap.parse_args(argv)

    kv = KVClient(args.host, args.port)
    if args.watch is None:
        print(render(kv))
        return 0
    try:
        while True:
            out = render(kv)
            sys.stdout.write("\x1b[2J\x1b[H" + out + "\n")
            sys.stdout.flush()
            time.sleep(args.watch)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
