"""graftlint CLI: run the three static-analysis passes over the repo.

Usage:
  python tools/graftlint.py                      # passes 1+3 (AST, fast)
  python tools/graftlint.py --pass hlo           # pass 2 only (compiles!)
  python tools/graftlint.py --all                # everything
  python tools/graftlint.py --all --no-aot       # pass 2 w/o AOT compiles
  python tools/graftlint.py --json               # machine-readable
  python tools/graftlint.py --update-baseline    # accept current findings
  python tools/graftlint.py --no-baseline        # raw findings, no ratchet

Exit codes: 0 clean (after baseline), 1 findings, 2 usage/internal error.

Pass 2 AOT-compiles the real step functions against a chipless v5e
topology. That path mutates process env (forced compiled Pallas kernels)
and is single-process like the other AOT tools — run it via this CLI
(the tier-1 gate shells out here), never import-and-run inside a pytest
process, and never run two AOT tools at once.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
sys.path.insert(0, _ROOT)

# Pass 2 traces engines on 8 virtual CPU devices; both knobs must land
# before jax is imported (safe no-ops for the AST-only passes).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

from tpu_sandbox.analysis import (  # noqa: E402
    apply_baseline,
    load_baseline,
    render_baseline,
    run_collective_pass,
    run_control_pass,
)

BASELINE_PATH = os.path.join(_ROOT, "tpu_sandbox", "analysis",
                             "baseline.toml")
PASSES = ("collective", "hlo", "control")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--pass", dest="passes", action="append",
                   choices=PASSES, default=None,
                   help="pass to run (repeatable); default: collective + "
                        "control (the AST passes)")
    p.add_argument("--all", action="store_true",
                   help="run all three passes (hlo compiles the engines)")
    p.add_argument("--root", default=_ROOT, help="repo root to scan")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one JSON object instead of text")
    p.add_argument("--baseline", default=BASELINE_PATH,
                   help="baseline file (default: analysis/baseline.toml)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report raw findings, ignoring the baseline")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline to accept current findings")
    p.add_argument("--no-aot", action="store_true",
                   help="pass 2 without the chipless AOT compiles "
                        "(donation reported as skipped)")
    p.add_argument("--steps",
                   default="dp,zero,pjit,pipeline,dp-int8,dp-overlap,"
                           "sp,decode,prefill,fsdp,tp,ep,mpmd",
                   help="pass 2 step functions to trace")
    args = p.parse_args(argv)

    passes = tuple(args.passes or ())
    if args.all:
        passes = PASSES
    elif not passes:
        passes = ("collective", "control")

    findings = []
    report: dict = {"passes": list(passes)}
    if "collective" in passes:
        findings.extend(run_collective_pass(args.root))
    if "control" in passes:
        findings.extend(run_control_pass(args.root))
    if "hlo" in passes:
        from tpu_sandbox.analysis.hlo_pass import run_hlo_pass

        hlo_findings, hlo_report = run_hlo_pass(
            steps=tuple(s for s in args.steps.split(",") if s),
            aot=not args.no_aot,
        )
        findings.extend(hlo_findings)
        report["hlo"] = hlo_report

    findings.sort(key=lambda f: (f.file, f.line, f.rule))

    if args.update_baseline:
        with open(args.baseline, "w", encoding="utf-8") as f:
            f.write(render_baseline(findings))
        print(f"baseline rewritten with {len(findings)} finding(s): "
              f"{args.baseline}")
        return 0

    suppressions = [] if args.no_baseline else load_baseline(args.baseline)
    kept, suppressed, unused = apply_baseline(findings, suppressions)
    report.update({
        "findings": len(kept),
        "suppressed": len(suppressed),
        "unused_suppressions": len(unused),
    })

    if args.as_json:
        report["details"] = [f.__dict__ for f in kept]
        report["unused"] = [s.__dict__ for s in unused]
        print(json.dumps(report))
    else:
        for f in kept:
            print(f.format())
        for s in unused:
            print(f"note: unused baseline entry rule={s.rule} file={s.file} "
                  f"match={s.match!r} — delete it")
        if "hlo" in passes:
            print("pass 2 report: "
                  + json.dumps(report.get("hlo", {}), default=str))
        print(f"graftlint: {len(kept)} finding(s), "
              f"{len(suppressed)} suppressed, "
              f"{len(unused)} unused suppression(s)")
    return 1 if kept else 0


if __name__ == "__main__":
    sys.exit(main())
