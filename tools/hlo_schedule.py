"""Did the gradient sync actually overlap? Read XLA's schedule and see.

The overlapped step pipeline (parallel/buckets.py) only earns its keep if
XLA really interleaves the per-bucket collectives with the remaining
backward compute. This tool parses an optimized-HLO dump — instruction
order in a scheduled module (``is_scheduled=true``) IS the schedule — and
reports, per collective:

- async ``-start``/``-done`` pairs (the GPU-style spelling): how many
  compute ops (dot / convolution / fusion / custom-call) sit strictly
  between start and done — >=1 means the latency hides under compute;
- synchronous collectives (the TPU spelling: this libtpu never splits
  collectives into HLO async pairs — overlap happens below HLO, in the
  TensorCore emitter, when ``xla_tpu_overlap_compute_collective_tc`` is
  on): whether the op is SCHEDULED before the last backward compute op
  (metadata ``op_name=".../transpose(..."`` marks backprop). A collective
  issued while backward work remains is an interleaved issue point — the
  monolithic sync can only ever sit after the last gradient;
- exposed vs overlapped communication bytes, and the receipt the bucketing
  exists to produce: ``all_reduce_issues_before_last_bwd_compute >= 1``.

Chipless: the driver builds a REAL multi-chip v5e topology
(``v5e:2x2x1``, 4 devices — the 1x1x1 twin has no cross-chip collectives
to schedule) via jax.experimental.topologies, AOT-compiles the bucketed
DataParallel step, and analyzes the result. Single-process like the other
AOT tools: do not run two at once. Estimates of schedule structure, not
measured step time; the bench owns measured truth.

Usage:
  python tools/hlo_schedule.py                       # compile + analyze
  python tools/hlo_schedule.py --no-overlap          # monolithic baseline
  python tools/hlo_schedule.py --hlo-file dump.txt   # re-analyze a dump
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _HERE)            # import aot_v5e / hlo_traffic as siblings
sys.path.insert(0, os.path.dirname(_HERE))  # import tpu_sandbox from the repo

from hlo_traffic import _COLLECTIVES, shape_bytes  # noqa: E402

# aot_v5e (and with it libtpu topologies) stays lazy in the driver below:
# schedule_report() must be importable on CPU-only boxes — the tier-1
# fixture test and bench.py run it against text.

#: Opcodes that count as "compute a collective can hide under". Fusions
#: cover the elementwise/reduce bulk XLA packs around the dots; dots and
#: convolutions are the backward work itself; custom-call catches Mosaic.
_COMPUTE = ("dot", "convolution", "fusion", "custom-call")

_INST = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.+?)\s+([a-z][\w\-]*)\((.*)$"
)
_BWD = re.compile(r'op_name="[^"]*transpose\(')

#: Latency-hiding / async-collective knobs for TPU AOT compiles (the
#: compile-option spelling of MaxText's LIBTPU_INIT_ARGS). Verified to
#: exist on the local libtpu; the TC-overlap flag is what makes the
#: sync-form collectives below actually run concurrently with compute.
TPU_OVERLAP_COMPILER_OPTIONS = {
    "xla_tpu_enable_latency_hiding_scheduler": "true",
    "xla_tpu_overlap_compute_collective_tc": "true",
    "xla_tpu_enable_async_collective_fusion": "true",
    "xla_enable_async_all_reduce": "true",
}


def _operand_region(rest: str) -> str:
    """The operand list of one instruction: everything up to the first ')'
    that is outside layout braces and balanced parens. TPU layouts carry
    parens INSIDE braces (``{0:T(8,128)S(1)}``), so a bare split on ')'
    truncates mid-layout; tuple-shaped operands open parens of their own.
    """
    brace = paren = 0
    for i, ch in enumerate(rest):
        if ch == "{":
            brace += 1
        elif ch == "}":
            brace -= 1
        elif brace == 0 and ch == "(":
            paren += 1
        elif brace == 0 and ch == ")":
            if paren == 0:
                return rest[:i]
            paren -= 1
    return rest


def _operand_tokens(rest: str) -> list[str]:
    """Candidate operand names, '%' sigil optional (dumps come both ways).
    Shape/dtype tokens ride along; callers filter by known names."""
    return re.findall(r"%?([\w.\-]+)", _operand_region(rest))


def schedule_report(hlo_text: str) -> dict:
    """Schedule-structure report of an optimized (scheduled) HLO module.

    Pure text analysis — no jax import. Processes every computation
    independently (shard_map bodies compile to nested computations);
    instruction order within a computation is taken as the schedule, which
    holds for modules printed after scheduling (``is_scheduled=true``).
    """
    collectives = []    # per-collective detail rows, all computations
    issue_count = 0     # all-reduce issue points before last bwd compute
    last_bwd_op = None

    def flush(ops):
        """Process one computation's ordered instruction list."""
        nonlocal issue_count, last_bwd_op
        if not ops:
            return
        compute_idx = [
            i for i, (_, opcode, _, _line) in enumerate(ops)
            if opcode in _COMPUTE
        ]
        bwd_idx = [i for i in compute_idx if _BWD.search(ops[i][3])]
        last_bwd = bwd_idx[-1] if bwd_idx else None
        if last_bwd is not None:
            last_bwd_op = ops[last_bwd][0]
        starts = {}  # name -> (index, opcode base, bytes)
        for i, (name, opcode, rest, _line) in enumerate(ops):
            base = opcode
            for suf in ("-start", "-done"):
                if opcode.endswith(suf):
                    base = opcode[: -len(suf)]
            if base not in _COLLECTIVES:
                continue
            before_bwd = last_bwd is not None and i < last_bwd
            nbytes = shape_bytes(_operand_region(rest))
            if opcode.endswith("-start"):
                starts[name] = (i, base, nbytes)
                if base == "all-reduce" and before_bwd:
                    issue_count += 1
            elif opcode.endswith("-done"):
                for tok in _operand_tokens(rest):
                    if tok in starts:
                        s_i, s_base, s_bytes = starts.pop(tok)
                        between = sum(1 for c in compute_idx if s_i < c < i)
                        collectives.append({
                            "op": tok, "opcode": s_base, "form": "async",
                            "bytes": s_bytes,
                            "compute_ops_between": between,
                            "overlapped": between >= 1,
                            "before_last_bwd_compute": (
                                last_bwd is not None and s_i < last_bwd
                            ),
                        })
                        break
            else:
                # sync-form collective: its schedule position is the issue
                # point; scheduled before the last backward compute op
                # means there is compute left for the TC to hide it under
                if base == "all-reduce" and before_bwd:
                    issue_count += 1
                collectives.append({
                    "op": name, "opcode": base, "form": "sync",
                    "bytes": nbytes,
                    "compute_ops_between": sum(
                        1 for c in compute_idx if c > i
                    ) if before_bwd else 0,
                    "overlapped": before_bwd,
                    "before_last_bwd_compute": before_bwd,
                })
        # a -start whose -done never showed up (shouldn't happen in valid
        # scheduled HLO): count it exposed rather than dropping bytes
        for name, (s_i, s_base, s_bytes) in starts.items():
            collectives.append({
                "op": name, "opcode": s_base, "form": "async",
                "bytes": s_bytes, "compute_ops_between": 0,
                "overlapped": False, "before_last_bwd_compute": False,
            })

    ops: list[tuple[str, str, str, str]] = []
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{") and "->" in stripped:
            flush(ops)       # new computation header
            ops = []
            continue
        m = _INST.match(line)
        if m:
            name, _shape, opcode, rest = m.groups()
            ops.append((name, opcode, rest, line))
    flush(ops)

    overlapped_b = sum(c["bytes"] for c in collectives if c["overlapped"])
    exposed_b = sum(c["bytes"] for c in collectives if not c["overlapped"])
    total_b = overlapped_b + exposed_b
    n_async = sum(1 for c in collectives if c["form"] == "async")
    return {
        "collective_count": len(collectives),
        "async_pairs": n_async,
        "sync_collectives": len(collectives) - n_async,
        "overlapped_collectives": sum(
            1 for c in collectives if c["overlapped"]
        ),
        "comm_bytes_total": total_b,
        "comm_bytes_overlapped": overlapped_b,
        "comm_bytes_exposed": exposed_b,
        "exposed_comm_fraction": (
            round(exposed_b / total_b, 4) if total_b else None
        ),
        "all_reduce_issues_before_last_bwd_compute": issue_count,
        "last_bwd_compute_op": last_bwd_op,
        "collectives": collectives,
    }


def build_overlapped_hlo(devices, *, batch_per_rank: int = 8,
                         bucket_mb: float = 0.02,
                         grad_compress: str = "none",
                         overlap: bool = True,
                         compiler_options: dict | None = None) -> str:
    """AOT-compile the DataParallel MNIST step on ``devices`` (topology or
    real) and return the optimized HLO text. The tiny bucket_mb default is
    sized to the ~116 KB ConvNet gradient so the step splits into several
    buckets — the schedule structure under test, not a tuning suggestion
    (real models keep the 25 MB default)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from tpu_sandbox.models import ConvNet
    from tpu_sandbox.parallel import DataParallel
    from tpu_sandbox.train import TrainState

    devices = np.array(devices)
    mesh = Mesh(devices, ("data",))
    world = devices.size
    # BN-free so the bucketed grad sync is the ONLY collective in the step
    model = ConvNet(use_bn=False)
    tx = optax.sgd(1e-2, momentum=0.9)
    state = jax.eval_shape(lambda: TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx,
    ))
    dp = DataParallel(
        model, tx, mesh, grad_compress=grad_compress,
        overlap_grad_sync=overlap, bucket_mb=bucket_mb, donate=False,
    )
    if dp.compress.needs_residual:
        state = state.replace(grad_residual=jax.tree.map(
            lambda p: jax.ShapeDtypeStruct((world, *p.shape), jnp.float32),
            state.params,
        ))
    imgs = jax.ShapeDtypeStruct(
        (world * batch_per_rank, 28, 28, 1), jnp.float32
    )
    labs = jax.ShapeDtypeStruct((world * batch_per_rank,), jnp.int32)
    lowered = dp.lower_step(state, imgs, labs)
    try:
        return lowered.compile(
            compiler_options=compiler_options or TPU_OVERLAP_COMPILER_OPTIONS
        ).as_text()
    except Exception:
        if compiler_options is not None:
            raise
        # non-TPU backends (the CPU fallback) reject TPU-only options
        return lowered.compile().as_text()


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--topology", default="v5e:2x2x1",
                   help="compile-only TPU topology (needs >1 chip for "
                        "cross-chip collectives to exist)")
    p.add_argument("--chips-per-host", default="2,2,1")
    p.add_argument("--batch-per-rank", type=int, default=8)
    p.add_argument("--bucket-mb", type=float, default=0.02)
    p.add_argument("--grad-compress", choices=["none", "bf16", "int8"],
                   default="none")
    p.add_argument("--no-overlap", action="store_true",
                   help="monolithic single-all-reduce baseline")
    p.add_argument("--hlo-file", default=None,
                   help="re-analyze an existing optimized-HLO dump instead "
                        "of recompiling")
    p.add_argument("--dump-hlo", default=None,
                   help="also write the optimized HLO text here")
    p.add_argument("--detail", action="store_true",
                   help="include the per-collective detail list")
    args = p.parse_args()

    if args.hlo_file:
        text = open(args.hlo_file).read()
        source = f"hlo file {args.hlo_file}"
    else:
        from aot_v5e import make_topology

        topo = make_topology(
            args.topology,
            tuple(int(x) for x in args.chips_per_host.split(",")),
        )
        text = build_overlapped_hlo(
            topo.devices, batch_per_rank=args.batch_per_rank,
            bucket_mb=args.bucket_mb, grad_compress=args.grad_compress,
            overlap=not args.no_overlap,
        )
        source = (
            f"chipless {args.topology} AOT compile "
            "(schedule structure, not measured time)"
        )
        if args.dump_hlo:
            open(args.dump_hlo, "w").write(text)

    report = schedule_report(text)
    if not args.detail:
        report.pop("collectives")
    report["overlap"] = not args.no_overlap
    report["bucket_mb"] = args.bucket_mb
    report["grad_compress"] = args.grad_compress
    report["source"] = source
    print(json.dumps(report))


if __name__ == "__main__":
    main()
