#!/usr/bin/env python3
"""tracecat — merge flight-recorder logs and render them.

Reads a directory of per-process recorder JSONL files (written wherever
``TPU_SANDBOX_TRACE_DIR`` pointed), merges them onto one clock via the
KV-sequencer calibration, and renders one of:

    python tools/tracecat.py LOGDIR --out trace.json
        Chrome/Perfetto trace-event JSON. Open at https://ui.perfetto.dev
        (or chrome://tracing): one track per process, spans nested,
        fault injections as instant events.

    python tools/tracecat.py LOGDIR --rid r0007
        Per-request waterfall: every span of that request's trace,
        ordered and indented by causal depth.

    python tools/tracecat.py LOGDIR --last 10s
        Postmortem: causally-ordered text timeline of the final N
        seconds before the logs went quiet — kills, lease expiries,
        scavenge requeues, in order, across every process.

With no mode flag it prints a summary: processes, record counts, trace
chains and their integrity.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_sandbox.obs import collect  # noqa: E402


def _parse_seconds(text: str) -> float:
    text = text.strip().lower()
    if text.endswith("s"):
        text = text[:-1]
    return float(text)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tracecat", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("logdir", help="directory of recorder *.jsonl files")
    ap.add_argument("--out", metavar="FILE",
                    help="write merged Chrome trace-event JSON here")
    ap.add_argument("--rid", metavar="RID",
                    help="print the waterfall for one request id")
    ap.add_argument("--trace", metavar="TRACE_ID",
                    help="print the waterfall for one trace id")
    ap.add_argument("--last", metavar="DUR",
                    help="print the postmortem timeline of the final "
                         "window, e.g. --last 10s")
    args = ap.parse_args(argv)

    logs = collect.load_dir(args.logdir)
    if not logs:
        print(f"no recorder logs under {args.logdir}", file=sys.stderr)
        return 1
    offsets = collect.clock_offsets(logs)
    merged = collect.merge(logs, offsets)

    did_something = False
    if args.out:
        trace = collect.to_chrome_trace(merged)
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(trace, fh)
        print(f"wrote {len(trace['traceEvents'])} events to {args.out} "
              f"(open at https://ui.perfetto.dev)")
        did_something = True
    if args.rid or args.trace:
        rows = collect.request_waterfall(merged, rid=args.rid,
                                         trace=args.trace)
        if not rows:
            print("no matching trace", file=sys.stderr)
            return 1
        print(collect.format_waterfall(rows))
        did_something = True
    if args.last:
        window = collect.last_window(merged, _parse_seconds(args.last))
        print(collect.format_timeline(window))
        did_something = True

    if not did_something:
        print(f"{len(logs)} process logs, {len(merged)} records")
        for key in sorted(logs):
            print(f"  {key}: {len(logs[key])} records "
                  f"(offset {offsets.get(key, 0.0):+.6f}s)")
        chains = collect.trace_chains(merged)
        ok = sum(1 for recs in chains.values()
                 if collect.chain_check(recs)["connected"])
        print(f"{len(chains)} traces, {ok} fully connected")
    return 0


if __name__ == "__main__":
    sys.exit(main())
