#!/usr/bin/env python3
"""tracecat — merge flight-recorder logs and render them.

Reads a directory of per-process recorder JSONL files (written wherever
``TPU_SANDBOX_TRACE_DIR`` pointed), merges them onto one clock via the
KV-sequencer calibration, and renders one of:

    python tools/tracecat.py LOGDIR --out trace.json
        Chrome/Perfetto trace-event JSON. Open at https://ui.perfetto.dev
        (or chrome://tracing): one track per process, spans nested,
        fault injections as instant events.

    python tools/tracecat.py LOGDIR --rid r0007
        Per-request waterfall: every span of that request's trace,
        ordered and indented by causal depth. Spans on the request's
        critical path are marked ``*``; spans whose parent never landed
        (leaked span, torn log) carry an ``[orphan]`` tag. A where-did-
        the-time-go segment line follows the waterfall.

    python tools/tracecat.py LOGDIR --critpath [FILE]
        Run-level critical-path profile: where the run's request time
        went, segment by segment (obs/critpath.py). With FILE, also
        write the profile JSON — the input ``tools/tracediff.py`` gates
        on.

    python tools/tracecat.py LOGDIR --last 10s
        Postmortem: causally-ordered text timeline of the final N
        seconds before the logs went quiet — kills, lease expiries,
        scavenge requeues, in order, across every process.

With no mode flag it prints a summary: processes, record counts,
dropped (torn/corrupt) lines, trace chains and their integrity.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_sandbox.obs import collect, critpath  # noqa: E402


def _parse_seconds(text: str) -> float:
    text = text.strip().lower()
    if text.endswith("s"):
        text = text[:-1]
    return float(text)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tracecat", description=__doc__,
                                 formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("logdir", help="directory of recorder *.jsonl files")
    ap.add_argument("--out", metavar="FILE",
                    help="write merged Chrome trace-event JSON here")
    ap.add_argument("--rid", metavar="RID",
                    help="print the waterfall for one request id")
    ap.add_argument("--trace", metavar="TRACE_ID",
                    help="print the waterfall for one trace id")
    ap.add_argument("--critpath", metavar="FILE", nargs="?", const="-",
                    help="print the run's critical-path profile; with "
                         "FILE, also write the profile JSON for "
                         "tracediff")
    ap.add_argument("--last", metavar="DUR",
                    help="print the postmortem timeline of the final "
                         "window, e.g. --last 10s")
    args = ap.parse_args(argv)

    stats: dict = {}
    logs = collect.load_dir(args.logdir, stats)
    if not logs:
        print(f"no recorder logs under {args.logdir}", file=sys.stderr)
        return 1
    offsets = collect.clock_offsets(logs)
    merged = collect.merge(logs, offsets)

    did_something = False
    if args.out:
        trace = collect.to_chrome_trace(merged)
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(trace, fh)
        print(f"wrote {len(trace['traceEvents'])} events to {args.out} "
              f"(open at https://ui.perfetto.dev)")
        did_something = True
    if args.rid or args.trace:
        rows = collect.request_waterfall(merged, rid=args.rid,
                                         trace=args.trace)
        if not rows:
            print("no matching trace", file=sys.stderr)
            return 1
        trace_id = rows[0]["trace"]
        records = [r for r in merged if r.get("trace") == trace_id]
        crit = {r.get("span") for r in critpath.critical_path(records)
                if r.get("span")}
        print(collect.format_waterfall(rows, crit=crit))
        stalls = [r for r in merged if r.get("ph") == "X"
                  and r.get("name", "").startswith("swap:")]
        req = critpath.attribute_request(records, stalls)
        if req is not None:
            segs = sorted(req["segments"].items(), key=lambda kv: -kv[1])
            print(f"  critical path ({req['outcome']}, "
                  f"wall {req['wall_s'] * 1e3:.3f}ms, coverage "
                  f"{req['coverage']:.1%}): " + ", ".join(
                      f"{seg}={s * 1e3:.3f}ms" for seg, s in segs))
            if req["outcome"] != "ok" and req.get("blame"):
                print(f"  blame: {req['blame']}")
        did_something = True
    if args.critpath:
        result = critpath.analyze(merged)
        print(critpath.format_profile(result["profile"]))
        if args.critpath != "-":
            critpath.save_profile(result["profile"], args.critpath)
            print(f"wrote profile to {args.critpath}")
        did_something = True
    if args.last:
        window = collect.last_window(merged, _parse_seconds(args.last))
        print(collect.format_timeline(window))
        did_something = True

    if not did_something:
        print(f"{len(logs)} process logs, {len(merged)} records, "
              f"{stats.get('dropped_records', 0)} dropped lines")
        for key in sorted(logs):
            print(f"  {key}: {len(logs[key])} records "
                  f"(offset {offsets.get(key, 0.0):+.6f}s)")
        chains = collect.trace_chains(merged)
        ok = sum(1 for recs in chains.values()
                 if collect.chain_check(recs)["connected"])
        print(f"{len(chains)} traces, {ok} fully connected")
    return 0


if __name__ == "__main__":
    sys.exit(main())
