"""Does the REFERENCE training recipe itself diverge at 3000^2?

Root-cause probe for the r03 bench's final_loss=10.1 (VERDICT r03
next-3). Torch-CPU replica of the reference stack
(/root/reference/mnist_onegpu.py:11-31: ConvNet 5x5/16 + BN + pool,
5x5/32 + BN + pool, LazyLinear(10); SGD(1e-4); CE) on bench.py's pixel
distribution (synthetic MNIST, normalized, 25% label flips).

Measured result (r05, this machine, bilinear upsampling matching both
the reference's transforms.Resize and the JAX bench — ADVICE r04): loss
2.2628 -> 110.54 -> 421.10 -> 107.99 -> 77.20 -> 0.0000 over six bs=2
steps, logit |max| growing to ~670
(measured/reference_dynamics_probe_r05.txt; the earlier mode="nearest"
run gave 2.2840 -> 150.66 -> 406.26 — same mechanism, different input
distribution). Mechanism: with ~18M post-pool features, one SGD update
moves the next logits by lr * g * ||f||^2 = O(100-1000) — the recipe is
chaotic at this scale in ANY framework. The JAX bench's 10.1 nats after 135 steps
is the same dynamics (tamer, if anything). Numerics of the s2dt plan are
separately pinned against the plain plan at production row width in
tests/test_convnet_s2d_t.py::test_equality_at_production_row_width_bf16.

Run: PYTHONPATH=. python tools/reference_dynamics_probe.py  (CPU, ~3 min)
"""
import sys, os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import torch
import torch.nn as nn

from tpu_sandbox.data import synthetic_mnist
from tpu_sandbox.data.mnist import normalize

IMG = 3000
BS = 2
torch.manual_seed(0)
torch.set_num_threads(8)


class ConvNet(nn.Module):
    def __init__(self, num_classes=10):
        super().__init__()
        self.layer1 = nn.Sequential(
            nn.Conv2d(1, 16, kernel_size=5, stride=1, padding=2),
            nn.BatchNorm2d(16), nn.ReLU(),
            nn.MaxPool2d(kernel_size=2, stride=2))
        self.layer2 = nn.Sequential(
            nn.Conv2d(16, 32, kernel_size=5, stride=1, padding=2),
            nn.BatchNorm2d(32), nn.ReLU(),
            nn.MaxPool2d(kernel_size=2, stride=2))
        self.fc = nn.LazyLinear(num_classes)

    def forward(self, x):
        out = self.layer1(x)
        out = self.layer2(out)
        out = out.reshape(out.size(0), -1)
        return self.fc(out)


images, labels = synthetic_mnist(n=64, seed=0)
images = normalize(images)
rng = np.random.default_rng(1)
flip = rng.random(len(labels)) < 0.25
labels = np.where(flip, rng.integers(0, 10, size=len(labels)), labels)

model = ConvNet()
model(torch.zeros(1, 1, IMG, IMG))  # init lazy fc
crit = nn.CrossEntropyLoss()
opt = torch.optim.SGD(model.parameters(), 1e-4)

import torch.nn.functional as F
sel_rng = np.random.default_rng(0)
for step in range(6):
    sel = sel_rng.integers(0, len(images), size=BS)
    xb = torch.from_numpy(np.asarray(images[sel]).reshape(BS, 28, 28))
    xb = xb.float().unsqueeze(1)  # [B,1,28,28]
    # bilinear to match BOTH pipelines (ADVICE r04: the reference's
    # transforms.Resize is PIL bilinear, the JAX bench resizes bilinear;
    # the earlier mode="nearest" probed a different input distribution)
    xb = F.interpolate(xb, size=(IMG, IMG), mode="bilinear",
                       align_corners=False)
    yb = torch.from_numpy(labels[sel].astype(np.int64))
    out = model(xb)
    loss = crit(out, yb)
    opt.zero_grad(); loss.backward(); opt.step()
    print(f"step {step}: loss {loss.item():.4f} "
          f"logit|max| {out.abs().max().item():.1f} "
          f"fc|w|max {model.fc.weight.abs().max().item():.2e}")
