"""End-to-end elastic training over real processes (CPU, world size 2):
kill a rank mid-epoch → supervisor restarts the generation → workers
resume from the newest checkpoint with exact data order → the final model
matches an uninterrupted same-seed run. Plus the preemption variant
(SIGTERM → save → exit 75 → restart NOT charged).

Each case spawns 2 jax.distributed processes per generation, so these are
marked slow and stay out of tier-1; the same machinery is covered fast and
single-process in test_supervisor.py / test_resumable.py.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "mnist_distributed.py"

# 64 synthetic samples / (bs 4 x 2 ranks) = 8 steps per epoch, 16 total
COMMON = [
    "--elastic", "-g", "2", "--epochs", "2", "--batch-size", "4",
    "--image-size", "28", "--synthetic-n", "64", "--limit-steps", "8",
    "--dtype", "fp32", "--plan", "plain", "--log-every", "1000",
    "--ckpt-every", "2",
]
TOTAL_STEPS = 16


def run_elastic(ckpt_dir, fault_plan=None, timeout=600):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["TPU_SANDBOX_BACKOFF"] = "0.1"
    env["TPU_SANDBOX_TERM_TIMEOUT"] = "10"
    if fault_plan is not None:
        env["TPU_SANDBOX_FAULT_PLAN"] = json.dumps(fault_plan)
    cmd = [sys.executable, str(SCRIPT), *COMMON, "--ckpt-dir", str(ckpt_dir)]
    return subprocess.run(
        cmd, env=env, cwd=REPO, capture_output=True, text=True,
        timeout=timeout,
    )


def final_params(ckpt_dir):
    f = Path(ckpt_dir) / f"step-{TOTAL_STEPS:08d}.npz"
    assert f.exists(), f"missing final checkpoint {f}"
    with np.load(f, allow_pickle=False) as z:
        return {k: z[k].copy() for k in z.files if k.startswith("leaf:")}


def assert_same_model(a, b):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=0, atol=1e-6, err_msg=k)


def test_kill_rank_midepoch_restart_resume_loss_parity(tmp_path):
    ref_dir = tmp_path / "ref"
    r = run_elastic(ref_dir)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "1 generation(s)" in r.stdout

    # rank 1 is SIGKILLed right after optimizer step 5 (mid-epoch 1)
    crash_dir = tmp_path / "crash"
    r = run_elastic(
        crash_dir, fault_plan=[{"rank": 1, "step": 5, "action": "kill"}]
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert "gen1:failure" in out and "gen2:ok" in out, out
    assert "1 restart(s) charged" in out, out
    # kill hit step 5; the last committed checkpoint is step 4 — generation
    # 2 must resume exactly there, not start over
    assert "resumed from step 4" in out, out

    assert_same_model(final_params(ref_dir), final_params(crash_dir))


def test_sigterm_preemption_saves_and_is_not_charged(tmp_path):
    ref_dir = tmp_path / "ref"
    r = run_elastic(ref_dir)
    assert r.returncode == 0, r.stdout + r.stderr

    pre_dir = tmp_path / "preempt"
    r = run_elastic(
        pre_dir, fault_plan=[{"rank": 0, "step": 5, "action": "sigterm"}]
    )
    assert r.returncode == 0, r.stdout + r.stderr
    out = r.stdout
    assert "gen1:preemption" in out and "gen2:ok" in out, out
    assert "0 restart(s) charged" in out, out
    assert "1 preemption(s)" in out, out
    # the preempted generation saved at the signal boundary (step 5, an odd
    # step ckpt_every=2 alone would never have written) and generation 2
    # resumed from exactly there
    assert "resumed from step 5" in out, out

    assert_same_model(final_params(ref_dir), final_params(pre_dir))
