"""The Pallas fc head (ops/pallas_fc_t.py) == the plain einsum path it
wraps — forward, input-grad (the Pallas kernel), weight/bias grads (the
unchanged XLA dots) — in interpret mode; Mosaic lowering at production
geometry is pinned in tests/test_mosaic_lowering.py."""

import jax
import jax.numpy as jnp
import numpy as np

from tpu_sandbox.ops.pallas_fc_t import fc_dgrad_t, fc_t


def _case(n=3, h=8, c=16, w=32, k=10, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    y = jnp.asarray(rng.standard_normal((n, h, c, w)), dtype)
    kernel = jnp.asarray(
        0.01 * rng.standard_normal((h * c * w, k)), jnp.float32)
    bias = jnp.asarray(rng.standard_normal(k), jnp.float32)
    return y, kernel, bias


def _einsum_ref(y, kernel, bias, dtype):
    n, h, c, w = y.shape
    k4 = kernel.astype(dtype).reshape(h, c, w, kernel.shape[-1])
    return jnp.einsum("nhcw,hcwk->nk", y, k4) + bias.astype(dtype)


def test_forward_matches_einsum():
    y, kernel, bias = _case()
    np.testing.assert_allclose(
        np.asarray(fc_t(y, kernel, bias, jnp.float32)),
        np.asarray(_einsum_ref(y, kernel, bias, jnp.float32)),
        rtol=1e-6, atol=1e-6)


def test_grads_match_einsum_autodiff():
    """All three cotangents (dy via the Pallas kernel, dkernel/dbias via
    the same XLA dots autodiff builds) must match the plain path."""
    y, kernel, bias = _case(seed=1)

    def loss_pallas(y, kernel, bias):
        return jnp.sum(fc_t(y, kernel, bias, jnp.float32) ** 2)

    def loss_ref(y, kernel, bias):
        return jnp.sum(_einsum_ref(y, kernel, bias, jnp.float32) ** 2)

    gp = jax.grad(loss_pallas, argnums=(0, 1, 2))(y, kernel, bias)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(y, kernel, bias)
    for a, b, nm in zip(gp, gr, ("dy", "dkernel", "dbias")):
        scale = float(np.max(np.abs(np.asarray(b)))) or 1.0
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0, atol=2e-5 * scale, err_msg=nm)


def test_dgrad_kernel_alone():
    """fc_dgrad_t == the broadcast-sum it replaces, incl. bf16 output
    rounding and a non-divisible-looking H that exercises block picking."""
    rng = np.random.default_rng(2)
    n, k, h, c, w = 4, 10, 6, 8, 16
    g = jnp.asarray(rng.standard_normal((n, k)), jnp.float32)
    wt = jnp.asarray(rng.standard_normal((k, h, c, w)), jnp.bfloat16)
    dy = fc_dgrad_t(g, wt, jnp.bfloat16)
    ref = jnp.einsum("nk,khcw->nhcw", g,
                     wt.astype(jnp.float32)).astype(jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(dy, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=1e-2)


def test_bf16_compute_path():
    """bf16 y (the production compute dtype): fc_t tracks the einsum
    path within bf16 rounding."""
    y, kernel, bias = _case(dtype=jnp.bfloat16, seed=3)

    def loss_pallas(kernel):
        return jnp.sum(fc_t(y, kernel, bias, jnp.bfloat16) ** 2)

    def loss_ref(kernel):
        return jnp.sum(_einsum_ref(y, kernel, bias, jnp.bfloat16) ** 2)

    gp = jax.grad(loss_pallas)(kernel)
    gr = jax.grad(loss_ref)(kernel)
    scale = float(np.max(np.abs(np.asarray(gr)))) or 1.0
    assert float(np.max(np.abs(np.asarray(gp - gr)))) / scale < 5e-3


def test_kill_switch_einsum_path(monkeypatch):
    """TPU_SANDBOX_NO_PALLAS_FC=1 must keep working (the emergency
    fallback if the fc kernel fails on the runtime at hand): the model's
    einsum branch matches the Pallas-path logits and grads to
    tolerance."""
    import flax.linen as fnn

    from tpu_sandbox.models.convnet_s2d_t import _DenseT

    rng = np.random.default_rng(4)
    y = jnp.asarray(rng.standard_normal((2, 8, 16, 32)), jnp.float32)

    def run(env):
        if env:
            monkeypatch.setenv("TPU_SANDBOX_NO_PALLAS_FC", "1")
        else:
            monkeypatch.delenv("TPU_SANDBOX_NO_PALLAS_FC", raising=False)
        m = _DenseT(10, jnp.float32)
        v = m.init(jax.random.key(0), y)

        def f(p):
            return jnp.sum(m.apply({"params": p}, y) ** 2)

        return m.apply(v, y), jax.grad(f)(v["params"])

    out_p, g_p = run(env=False)
    out_e, g_e = run(env=True)
    np.testing.assert_allclose(np.asarray(out_p), np.asarray(out_e),
                               rtol=1e-6, atol=1e-6)
    for key in ("kernel", "bias"):
        np.testing.assert_allclose(
            np.asarray(g_p[key], np.float32),
            np.asarray(g_e[key], np.float32), rtol=1e-5, atol=1e-5,
            err_msg=key)
