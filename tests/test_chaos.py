"""Chaos-campaign harness, fast and in-process (tier-1).

One quick seeded campaign runs here so the harness itself is
regression-gated: 2 gateways + 2 stub-engine replicas, a tiny replayed
trace, a shed_storm and a gateway kill mid-load — then the full audit
(zero lost, exactly-one verdict per rid, alert claims, byte-identical
audit across two same-seed runs). The full fault matrix (every action
family, multiple seeds, prefix probes) lives slow-marked in
test_chaos_integration.py; the real-process version is
``bench.py --metric chaos``.
"""

import contextlib
import threading
import time

import numpy as np
import pytest

from tpu_sandbox.gateway.client import GatewayClient
from tpu_sandbox.gateway.fleet import FleetSpec
from tpu_sandbox.gateway.server import Gateway
from tpu_sandbox.models.transformer import TransformerConfig
from tpu_sandbox.obs import workload
from tpu_sandbox.runtime.chaos import (CHAOS_ACTIONS, ChaosCampaign,
                                       ChaosFault, build_schedule,
                                       check_alert_claims)
from tpu_sandbox.serve.cache import CacheConfig
from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig

MCFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_len=128)
CCFG = CacheConfig(num_blocks=24, block_size=4, max_blocks_per_seq=8)
BLOCK = CCFG.block_size


class _StubStep:
    """DecodeStep stand-in: next token = (last + 1) % vocab, no jax."""

    def __init__(self, buckets=(8, 16), vocab=64):
        self.buckets = tuple(buckets)
        self.vocab = vocab
        self.prefill = {b: self._prefill for b in self.buckets}

    def pick_bucket(self, plen):
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(f"prompt of {plen} exceeds buckets {self.buckets}")

    def _prefill(self, params, k, v, toks, dest, last):
        toks = np.asarray(toks)
        logits = np.zeros((self.vocab,), np.float32)
        logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
        return logits, k, v

    def decode(self, params, k, v, tokens, lengths, tables):
        tokens = np.asarray(tokens)
        logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
        for i in range(tokens.shape[0]):
            logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
        return logits, k, v


def _engine():
    cfg = ServeConfig(model=MCFG, cache=CCFG, max_batch=2, buckets=(8, 16))
    return ContinuousEngine(None, cfg, step=_StubStep(), clock=time.monotonic)


def _worker(kv, tag):
    from tpu_sandbox.serve.replica import ReplicaWorker

    return ReplicaWorker(kv, _engine(), tag=tag, lease_ttl=1.0,
                         load_interval=0.02)


@contextlib.contextmanager
def _pumping(*workers):
    stop = threading.Event()

    def run():
        while not stop.is_set():
            for w in workers:
                w.tick()
            time.sleep(0.001)

    t = threading.Thread(target=run, name="chaos-pump", daemon=True)
    t.start()
    try:
        yield stop
    finally:
        stop.set()
        t.join(timeout=10.0)


@pytest.fixture
def kv_pair():
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    server = KVServer()
    kv = KVClient(port=server.port)
    clones = []

    def clone():
        c = kv.clone()
        clones.append(c)
        return c

    yield server, kv, clone
    for c in clones:
        c.close()
    kv.close()
    server.stop()


# -- schedule expansion: pure + seeded ----------------------------------------


def test_build_schedule_same_seed_same_faults():
    targets = {"kill_gateway": ["gw0", "gw1"], "shed_storm": ["w0"],
               "stall_replica": ["w0:0.1", "w1:0.2"]}
    a = build_schedule(7, duration_s=2.0, targets=targets, n_faults=6)
    b = build_schedule(7, duration_s=2.0, targets=targets, n_faults=6)
    assert a == b
    assert len(a) == 6
    assert all(f.action in CHAOS_ACTIONS for f in a)
    assert [f.at_s for f in a] == sorted(f.at_s for f in a)
    c = build_schedule(8, duration_s=2.0, targets=targets, n_faults=6)
    assert a != c  # a different seed draws a different campaign


def test_build_schedule_rejects_bad_inputs():
    with pytest.raises(ValueError, match="no action"):
        build_schedule(1, duration_s=1.0, targets={})
    with pytest.raises(ValueError, match="unknown chaos actions"):
        build_schedule(1, duration_s=1.0,
                       targets={"kill_everything": ["x"]})


def test_campaign_refuses_hookless_actions(kv_pair):
    _, kv, _ = kv_pair
    trace = workload.synthesize(3, 1)
    sched = [ChaosFault(at_s=0.1, action="kill_gateway", target="gw0")]
    with pytest.raises(ValueError, match="has no hook"):
        ChaosCampaign(kv, trace, lambda *a: True, seed=3, schedule=sched)


# -- the tier-1 smoke campaign ------------------------------------------------

SMOKE_SEED = 1013


def _run_smoke_campaign(kv, clone):
    """One seeded campaign: 2 gateways, 2 stub replicas, 10 requests,
    a replica shed_storm then a gateway SIGKILL stand-in mid-load."""
    trace = workload.synthesize(SMOKE_SEED, 10, duration_s=0.5,
                                prompt_tokens=(4, 10),
                                decode_tokens=(2, 4))
    schedule = [
        ChaosFault(at_s=0.18, action="shed_storm", target="w0"),
        ChaosFault(at_s=0.30, action="kill_gateway", target="gw0"),
    ]
    fleets = [FleetSpec(block_size=BLOCK)]
    gws = {
        gid: Gateway(kv, fleets, gateway_id=gid, hb_ttl=0.5,
                     refresh_min_s=0.005).start()
        for gid in ("gw0", "gw1")
    }
    w0, w1 = _worker(clone(), "w0"), _worker(clone(), "w1")
    client = None
    try:
        with _pumping(w0, w1):
            client = GatewayClient(
                endpoints=[("127.0.0.1", gws["gw0"].port),
                           ("127.0.0.1", gws["gw1"].port)],
                backoff_base=0.01)
            campaign = ChaosCampaign(
                clone(), trace, client.submit, seed=SMOKE_SEED,
                schedule=schedule,
                hooks={"kill_gateway": lambda gid: gws[gid].kill()},
                block_size=BLOCK, verdict_timeout=60.0)
            res = campaign.run()
        alert_failures = check_alert_claims(kv)
    finally:
        if client is not None:
            client.close()
        for g in gws.values():
            g.close()
    return res, alert_failures


def test_smoke_campaign_zero_loss_exactly_once(kv_pair):
    _, kv, clone = kv_pair
    res, alert_failures = _run_smoke_campaign(kv, clone)
    assert res.ok, res.failures
    assert res.lost == []
    assert res.submitted == 10
    # every rid converged to a terminal "ok" verdict with real tokens —
    # the shed_storm cost retries, never answers
    assert len(res.verdicts) == 10
    assert all(v["verdict"] == "ok" and v["tokens"]
               for v in res.verdicts.values())
    assert [f["action"] for f in res.fired] == ["shed_storm",
                                                "kill_gateway"]
    assert alert_failures == []


@pytest.mark.slow
def test_smoke_campaign_audit_bytes_identical_across_fleets():
    """Same seed, two fresh fleets -> byte-identical claim audit. The
    wall-clock interleavings differ; the audit must not notice."""
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    audits = []
    for _ in range(2):
        server = KVServer()
        kv = KVClient(port=server.port)
        clones = []

        def clone():
            c = kv.clone()
            clones.append(c)
            return c

        try:
            res, alert_failures = _run_smoke_campaign(kv, clone)
            assert res.ok, res.failures
            assert alert_failures == []
            audits.append(res.audit_bytes())
        finally:
            for c in clones:
                c.close()
            kv.close()
            server.stop()
    assert audits[0] == audits[1]
