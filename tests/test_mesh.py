"""Mesh / device-group tests — parity with dist group creation
(reference: allreduce_toy.py:27, mnist_distributed.py:100)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpu_sandbox.runtime import mesh as meshlib


def test_default_mesh_is_1d_data(devices):
    m = meshlib.make_mesh()
    assert m.axis_names == ("data",)
    assert m.shape["data"] == 8


def test_multi_axis_mesh(devices):
    m = meshlib.make_mesh({"data": 2, "model": 4})
    assert m.shape == {"data": 2, "model": 4}


def test_wildcard_axis(devices):
    m = meshlib.make_mesh({"data": -1, "model": 2})
    assert m.shape == {"data": 4, "model": 2}


def test_bad_sizes_raise(devices):
    with pytest.raises(ValueError):
        meshlib.make_mesh({"data": 3})
    with pytest.raises(ValueError):
        meshlib.make_mesh({"data": -1, "model": -1})
    with pytest.raises(ValueError):
        meshlib.make_mesh({"data": -1, "model": 3})


def test_submesh_fixes_other_axes(devices):
    m = meshlib.make_mesh({"data": 2, "model": 4})
    sub = meshlib.submesh(m, ["model"])
    assert sub.axis_names == ("model",)
    assert sub.shape == {"model": 4}
    # devices are row 0 of the full grid
    assert list(sub.devices.ravel()) == list(m.devices[0])


def test_shardings(devices):
    m = meshlib.make_mesh({"data": 8})
    x = jax.device_put(np.arange(16.0).reshape(8, 2), meshlib.batch_sharding(m))
    assert x.sharding.spec == P("data")
    assert len(x.addressable_shards) == 8
    r = jax.device_put(np.ones(3), meshlib.replicated(m))
    assert r.sharding.is_fully_replicated
