"""Runtime/bootstrap tests — capability parity with reference test_init.py.

The reference smoke test spawns 4 processes that rendezvous and exit
(test_init.py:112-117). Here: init() on the 8-virtual-device CPU backend,
topology introspection, serial sentinel, cleanup idempotence.
"""

import jax

from tpu_sandbox.runtime import bootstrap


def test_find_free_port_is_string_and_bindable():
    import socket

    port = bootstrap.find_free_port()
    assert isinstance(port, str)  # string: it feeds an env var
    with socket.socket() as s:
        s.bind(("127.0.0.1", int(port)))  # genuinely free


def test_coordinator_address_honors_env(monkeypatch):
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.7")
    monkeypatch.setenv("MASTER_PORT", "29500")
    assert bootstrap.coordinator_address() == "10.0.0.7:29500"


def test_coordinator_address_defaults_to_loopback(monkeypatch):
    monkeypatch.delenv("MASTER_ADDR", raising=False)
    monkeypatch.delenv("MASTER_PORT", raising=False)
    host, port = bootstrap.coordinator_address().split(":")
    assert host == "127.0.0.1"
    assert 1024 <= int(port) <= 65535


def test_init_single_process_topology():
    topo = bootstrap.init()
    assert bootstrap.is_initialized()
    assert topo.process_id == 0
    assert topo.num_processes == 1
    assert topo.global_devices == 8
    assert topo.backend == "cpu"
    assert "process 0/1" in topo.summary()
    bootstrap.cleanup()
    assert not bootstrap.is_initialized()


def test_serial_sentinel_skips_init():
    # reference rank==-1 semantics (test_init.py:73): serial mode, no group.
    topo = bootstrap.init(process_id=bootstrap.SERIAL_RANK)
    assert bootstrap.is_initialized()
    assert topo.num_processes == 1
    bootstrap.cleanup()


def test_cleanup_idempotent():
    bootstrap.cleanup()
    bootstrap.cleanup()
    assert not bootstrap.is_initialized()


def test_backend_name_matches_jax():
    assert bootstrap.backend_name() == jax.default_backend()


def test_multiprocess_init_requires_shared_coordinator(monkeypatch):
    import pytest

    monkeypatch.delenv("MASTER_PORT", raising=False)
    monkeypatch.delenv("PROCESS_ID", raising=False)
    with pytest.raises(ValueError, match="shared coordinator"):
        bootstrap.init(num_processes=2, process_id=0)
    with pytest.raises(ValueError, match="process_id"):
        bootstrap.init(coordinator="127.0.0.1:1234", num_processes=2)


def test_init_twice_is_idempotent():
    bootstrap.init()
    topo = bootstrap.init()
    assert topo.num_processes == 1
    bootstrap.cleanup()
