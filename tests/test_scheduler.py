"""Cluster scheduler control plane, fast (fake agents, no jax): the
durable queue API, per-job KV namespacing, gang admission, priority
preemption, admission timeouts with namespace sweeps, and scheduler-death
adoption (satellite: random kill orders must leave the surviving job
undamaged and un-double-charged). The full two-job fault matrix with real
training runs slow in test_cluster_integration.py."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from tpu_sandbox.runtime.kvstore import (
    KVClient,
    KVServer,
    NamespacedKV,
    for_job,
    job_namespace,
)
from tpu_sandbox.runtime.scheduler import (
    ClusterScheduler,
    JobSpec,
    cancel_job,
    job_events,
    k_state,
    k_verdict,
    list_jobs,
    submit_job,
)

PY = sys.executable
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {"PYTHONPATH": ROOT}


# -- per-job namespacing (kvstore layer) -----------------------------------


def test_job_namespace_spelling():
    assert job_namespace("") == ""
    assert job_namespace("default") == ""  # bare-prefix default-job alias
    assert job_namespace("alpha") == "job/alpha/"
    for bad in ("a/b", "a b", "a\tb", "a\nb"):
        with pytest.raises(ValueError):
            job_namespace(bad)


def test_for_job_default_is_identity_and_jobs_are_isolated():
    with KVServer() as srv:
        kv = KVClient(port=srv.port)
        assert for_job(kv, "") is kv
        assert for_job(kv, "default") is kv
        a = for_job(kv, "a")
        b = for_job(kv, "b")
        assert isinstance(a, NamespacedKV)
        a.set("leader/term", b"3")
        b.set("leader/term", b"7")
        kv.set("leader/term", b"1")  # the default job's view
        # three elections, three stores-within-the-store
        assert a.get("leader/term") == b"3"
        assert b.get("leader/term") == b"7"
        assert kv.get("leader/term") == b"1"
        assert kv.get("job/a/leader/term") == b"3"  # where it really lives
        # keys() is namespace-relative; the sweep is namespace-bounded
        assert a.keys("leader/") == ["leader/term"]
        a.set("budget/restarts", b"1")
        assert a.delete_prefix("") == 2  # whole-job sweep, nobody else's
        assert kv.get("job/b/leader/term") == b"7"
        assert kv.get("leader/term") == b"1"
        # nesting two job prefixes is always a bug
        with pytest.raises(ValueError, match="nest"):
            for_job(a, "c")
        kv.close()


def test_namespaced_add_and_barrier():
    with KVServer() as srv:
        kv = KVClient(port=srv.port)
        a = for_job(kv, "a")
        assert a.add("budget/claim/1", 1) == 1
        assert a.add("budget/claim/1", 1) == 2
        assert kv.add("budget/claim/1", 1) == 1  # default job unaffected
        a.barrier(1, key="sync")  # single-member barrier completes
        kv.close()


# -- JobSpec validation ----------------------------------------------------


def test_job_spec_validation():
    ok = dict(hosts=1, world_size=1, agent_argv=["true"])
    JobSpec(job_id="fine", **ok)
    with pytest.raises(ValueError, match="real job id"):
        JobSpec(job_id="", **ok)
    with pytest.raises(ValueError, match="real job id"):
        JobSpec(job_id="default", **ok)
    with pytest.raises(ValueError):
        JobSpec(job_id="has/slash", **ok)
    with pytest.raises(ValueError, match="hosts"):
        JobSpec(job_id="j", hosts=0, world_size=1, agent_argv=["true"])
    # gang shape: every host must own at least one rank
    with pytest.raises(ValueError, match="at least one rank"):
        JobSpec(job_id="j", hosts=3, world_size=2, agent_argv=["true"])
    # template placeholders are validated at submit time, not spawn time
    with pytest.raises(ValueError, match="template"):
        JobSpec(job_id="j", hosts=1, world_size=1,
                agent_argv=["run", "--x", "{unknown_placeholder}"])
    spec = JobSpec(job_id="j", hosts=2, world_size=3,
                   agent_argv=["run", "{agent_id}", "{kv_port}", "{job_id}",
                               "{num_agents}", "{world_size}"])
    assert spec.format_argv(agent_id=1, kv_port=99) == \
        ["run", "1", "99", "j", "2", "3"]
    assert JobSpec.from_json(spec.to_json()) == spec


# -- durable queue API -----------------------------------------------------


def test_submit_list_cancel_roundtrip():
    with KVServer() as srv:
        kv = KVClient(port=srv.port)
        s1 = submit_job(kv, JobSpec(job_id="a", hosts=1, world_size=1,
                                    agent_argv=["true"], priority=2))
        s2 = submit_job(kv, JobSpec(job_id="b", hosts=2, world_size=2,
                                    agent_argv=["true"]))
        assert s2 == s1 + 1
        jobs = list_jobs(kv)
        assert [j["job_id"] for j in jobs] == ["a", "b"]
        assert jobs[0] == {"job_id": "a", "state": "queued", "seq": s1,
                           "priority": 2, "hosts": 1, "world_size": 1,
                           "tenant": "", "share": 1.0, "cogroup": ""}
        with pytest.raises(ValueError, match="already exists"):
            submit_job(kv, JobSpec(job_id="a", hosts=1, world_size=1,
                                   agent_argv=["true"]))
        assert "submitted" in job_events(kv, "a")
        cancel_job(kv, "a")
        assert kv.try_get("sched/jobs/a/cancel") == b"1"
        kv.close()


# -- fake agents -----------------------------------------------------------
#
# Each agent is a real subprocess speaking the job-namespaced protocol the
# scheduler watches: heartbeat under agent_hb/<id>, verdict to job/done.
# Mirrors test_host_agent's _FAKE_AGENT idiom, one level up the stack.

_AGENT = """
import importlib.util, json, os, signal, sys, time
# load kvstore.py directly: the package __init__ drags in jax, which is
# ~0.5s of startup tax on each of the ~16 agents this suite spawns
_spec = importlib.util.spec_from_file_location(
    "_kv", os.path.join({root!r}, "tpu_sandbox", "runtime", "kvstore.py"))
_kv = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_kv)
KVClient, for_job = _kv.KVClient, _kv.for_job
aid = int(sys.argv[1]); port = int(sys.argv[2]); job = sys.argv[3]
mode = sys.argv[4]; arg = float(sys.argv[5]) if len(sys.argv) > 5 else 0.0
kv = for_job(KVClient(port=port), job)
stop = []
signal.signal(signal.SIGTERM, lambda s, f: stop.append(1))
# published only after the handler is in place: tests that wait on this
# key may then SIGTERM us without racing the default (kill) disposition
kv.set(f"test/ran/{{aid}}", str(os.getpid()))

def beat():
    kv.set_ttl(f"agent_hb/{{aid}}", repr(time.time()), 5.0)

def done(ok, preempted=False):
    if aid == 0:
        kv.set("job/done", json.dumps(
            {{"ok": ok, "preempted": preempted, "reason": "fake agent",
              "summary": "", "restarts": int(kv.try_get("budget/restarts")
                                             or 0),
              "preemptions": 0, "generations": 1}}))

if mode == "work":        # heartbeat for `arg` seconds, then succeed
    t0 = time.monotonic()
    while time.monotonic() - t0 < arg and not stop:
        beat(); time.sleep(0.03)
    if stop:
        done(False, preempted=True); sys.exit(75)
    done(True); time.sleep(0.1); sys.exit(0)
elif mode == "mortal":      # first life runs long; respawned lives crash
    lives = kv.add(f"test/lives/{{aid}}", 1)
    if lives >= 2:
        sys.exit(9)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 60 and not stop:
        beat(); time.sleep(0.03)
    sys.exit(75 if stop else 0)
elif mode == "preemptible":
    # first life: run until SIGTERM, checkpoint-through-preemption;
    # second life: note the resume and finish clean, uncharged.
    # lives are PER AGENT: a gang's ranks must not count each other
    lives = kv.add(f"test/lives/{{aid}}", 1)
    if lives >= 2:
        kv.set("test/resumed", b"1")
        done(True); time.sleep(0.1); sys.exit(0)
    t0 = time.monotonic()
    while time.monotonic() - t0 < 60 and not stop:
        beat(); time.sleep(0.03)
    done(False, preempted=True)
    sys.exit(75)
"""


def _agent_argv(script, mode, arg=0.0):
    return [PY, str(script), "{agent_id}", "{kv_port}", "{job_id}",
            mode, str(arg)]


@pytest.fixture()
def agent_script(tmp_path):
    script = tmp_path / "fake_sched_agent.py"
    script.write_text(_AGENT.format(root=ROOT))
    return script


# -- gang admission --------------------------------------------------------


def test_gang_is_all_or_nothing(agent_script):
    """Pool of 3, two 2-host jobs: the second must not launch ANY agent
    (not even for the one free slot) until the first gang's slots free."""
    with ClusterScheduler(3, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        sched.submit(JobSpec(job_id="first", hosts=2, world_size=3,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.5)))
        sched.submit(JobSpec(job_id="second", hosts=2, world_size=2,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.1)))
        saw_partial = []
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            states = {j["job_id"]: j["state"] for j in list_jobs(sched.kv)}
            second_agents = sched.kv.keys("job/second/test/ran/")
            if states.get("first") == "running" \
                    and states.get("second") == "queued" \
                    and second_agents:
                saw_partial.append(second_agents)
            if states.get("second") != "queued":
                break
            sched._tick()
            time.sleep(0.02)
        states = sched.serve(timeout=60)
        assert saw_partial == [], "gang launched while still queued"
        assert states == {"first": "done", "second": "done"}, states
        # both gangs eventually ran with their FULL host set
        ev = job_events(sched.kv, "second")
        assert ev["admitted"] >= ev["submitted"]


def test_heterogeneous_world_sizes_share_the_pool(agent_script):
    """3 ranks on 2 hosts next to 1 rank on 1 host: world % hosts != 0 is
    admissible (the launch record carries the rank table — unit-proven in
    test_host_agent.test_assign_ranks_heterogeneous)."""
    with ClusterScheduler(3, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        sched.submit(JobSpec(job_id="train", hosts=2, world_size=3,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.3)))
        sched.submit(JobSpec(job_id="bench", hosts=1, world_size=1,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.3)))
        states = sched.serve(timeout=60)
        assert states == {"train": "done", "bench": "done"}, states
        # both gangs' namespaces were swept on completion
        assert sched.kv.keys("job/train/") == []
        assert sched.kv.keys("job/bench/") == []


# -- MPMD co-gangs: cogroup all-or-nothing admission ------------------------


def test_cogroup_admitted_all_or_nothing(agent_script):
    """Pool 3, a 2-host occupant running: a 2-member cogroup (1 host each)
    must NOT take the single free slot piecemeal — stage 1 without stage 0
    would just block on the transport. Both members admit together once
    the occupant drains."""
    with ClusterScheduler(3, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        sched.submit(JobSpec(job_id="occupant", hosts=2, world_size=2,
                             priority=5,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.6)))
        assert _tick_until(sched, lambda: (
            sched.kv.try_get(k_state("occupant")) == b"running"))
        for s in (0, 1):
            sched.submit(JobSpec(job_id=f"stage{s}", hosts=1, world_size=1,
                                 priority=5, cogroup="pipe0",
                                 agent_argv=_agent_argv(agent_script, "work",
                                                        0.2)))
        # 1 slot free, group needs 2: neither member may launch — a bare
        # 1-host head WOULD fit, so any launch here is the cogroup bug
        for _ in range(10):
            sched._tick()
            time.sleep(0.02)
        assert sched.kv.try_get(k_state("stage0")) == b"queued"
        assert sched.kv.try_get(k_state("stage1")) == b"queued"
        assert sched.kv.keys("job/stage0/test/ran/") == []
        assert sched.kv.keys("job/stage1/test/ran/") == []
        states = sched.serve(timeout=60)
        assert states == {"occupant": "done", "stage0": "done",
                          "stage1": "done"}, states
        # co-admission: both members admitted in the same scheduling tick
        a0 = job_events(sched.kv, "stage0")["admitted"]
        a1 = job_events(sched.kv, "stage1")["admitted"]
        assert abs(a0 - a1) < 0.5, (a0, a1)


def test_cogroup_preempts_room_for_whole_group(agent_script):
    """A high-priority co-gang must carve out its TOTAL host need: the
    1-host head alone would fit beside the low-priority occupant, but
    victims are picked for the group's sum (2), so the occupant is
    preempted and both stages run."""
    with ClusterScheduler(2, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        sched.submit(JobSpec(
            job_id="occupant", hosts=2, world_size=2, priority=0,
            agent_argv=_agent_argv(agent_script, "preemptible")))
        assert _tick_until(sched, lambda: (
            sched.kv.try_get(k_state("occupant")) == b"running"
            and sched.kv.keys("job/occupant/test/ran/")))
        for s in (0, 1):
            sched.submit(JobSpec(job_id=f"stage{s}", hosts=1, world_size=1,
                                 priority=5, cogroup="pipe0",
                                 agent_argv=_agent_argv(agent_script, "work",
                                                        0.2)))
        states = sched.serve(timeout=120)
        assert states == {"occupant": "done", "stage0": "done",
                          "stage1": "done"}, states
        ev = job_events(sched.kv, "occupant")
        assert "preempt_sent" in ev and "readmitted" in ev
        # both stages were up while the occupant waited its turn back
        assert job_events(sched.kv, "stage0")["admitted"] \
            >= ev["preempt_sent"]


def test_cogroup_never_backfills_its_own_members(agent_script):
    """Backfill must not slip ONE member of the head's own co-gang into a
    free slot while the group as a whole is blocked — that is exactly the
    piecemeal admission cogroups exist to prevent."""
    with ClusterScheduler(3, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        sched.submit(JobSpec(job_id="occupant", hosts=2, world_size=2,
                             priority=5,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.6)))
        assert _tick_until(sched, lambda: (
            sched.kv.try_get(k_state("occupant")) == b"running"))
        # head of the queue: the blocked co-gang (needs 2, only 1 free);
        # a LOWER-priority member of the same gang sits behind it and
        # would pass the plain backfill fit test
        sched.submit(JobSpec(job_id="stage0", hosts=1, world_size=1,
                             priority=5, cogroup="pipe0",
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.2)))
        sched.submit(JobSpec(job_id="stage1", hosts=1, world_size=1,
                             priority=0, cogroup="pipe0",
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.2)))
        for _ in range(10):
            sched._tick()
            time.sleep(0.02)
        assert sched.kv.try_get(k_state("stage1")) == b"queued"
        assert "backfilled" not in job_events(sched.kv, "stage1")
        states = sched.serve(timeout=60)
        assert all(s == "done" for s in states.values()), states


# -- priority preemption ---------------------------------------------------


def test_priority_preemption_checkpoints_and_resumes(agent_script):
    """Full pool, high-priority arrival: the low-priority job is SIGTERMed,
    posts a preempted (uncharged) verdict, re-queues at its original seq,
    and resumes after the high-priority job drains."""
    with ClusterScheduler(1, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        low_seq = sched.submit(
            JobSpec(job_id="low", hosts=1, world_size=1, priority=0,
                    agent_argv=_agent_argv(agent_script, "preemptible")))
        # wait until low is actually running before outranking it
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            sched._tick()
            state = (sched.kv.try_get(k_state("low")) or b"").decode()
            if state == "running" and sched.kv.keys("job/low/test/ran/"):
                break
            time.sleep(0.02)
        sched.submit(
            JobSpec(job_id="high", hosts=1, world_size=1, priority=5,
                    agent_argv=_agent_argv(agent_script, "work", 0.3)))
        states = sched.serve(timeout=120)
        assert states == {"low": "done", "high": "done"}, states
        # the victim kept its place in line (seq unchanged through requeue)
        jobs = {j["job_id"]: j for j in list_jobs(sched.kv)}
        assert jobs["low"]["seq"] == low_seq
        ev_low = job_events(sched.kv, "low")
        ev_high = job_events(sched.kv, "high")
        # the bench.py receipts, in causal order on the scheduler's clock
        assert ev_low["admitted"] <= ev_low["preempt_sent"] \
            <= ev_low["preempted"] <= ev_low["readmitted"]
        assert ev_high["admitted"] >= ev_low["preempt_sent"]
        # preemption was free: the resumed verdict charges no restarts
        verdict = json.loads(sched.kv.get(k_verdict("low")))
        assert verdict["ok"] and verdict["restarts"] == 0


# -- backfill --------------------------------------------------------------


def _tick_until(sched, pred, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        sched._tick()
        if pred():
            return True
        time.sleep(0.02)
    return False


def test_backfill_runs_small_job_behind_blocked_head(agent_script):
    """Pool 3: an equal-priority 2-host head can't preempt the 2-host
    occupant and can't fit the 1 free slot — a strictly-lower-priority
    1-host job may start behind it (and everything still finishes)."""
    with ClusterScheduler(3, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        sched.submit(JobSpec(job_id="occupant", hosts=2, world_size=2,
                             priority=5,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.6)))
        assert _tick_until(sched, lambda: (
            sched.kv.try_get(k_state("occupant")) == b"running"))
        sched.submit(JobSpec(job_id="head", hosts=2, world_size=2,
                             priority=5,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.2)))
        sched.submit(JobSpec(job_id="small", hosts=1, world_size=1,
                             priority=0,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.2)))
        assert _tick_until(sched, lambda: (
            sched.kv.try_get(k_state("small")) == b"running"))
        # the head is still blocked and queued — small jumped it, safely
        assert sched.kv.try_get(k_state("head")) == b"queued"
        assert "backfilled" in job_events(sched.kv, "small")
        states = sched.serve(timeout=60)
        assert states == {"occupant": "done", "head": "done",
                          "small": "done"}, states


@pytest.mark.slow  # ~4s of real agent work; tier-1 keeps the positive case
def test_backfill_never_admits_equal_priority(agent_script):
    """An equal-priority candidate could starve the head (the head can't
    preempt it back out), so it must wait in line even when it fits."""
    with ClusterScheduler(3, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        sched.submit(JobSpec(job_id="occupant", hosts=2, world_size=2,
                             priority=5,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.6)))
        assert _tick_until(sched, lambda: (
            sched.kv.try_get(k_state("occupant")) == b"running"))
        sched.submit(JobSpec(job_id="head", hosts=2, world_size=2,
                             priority=5,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.2)))
        sched.submit(JobSpec(job_id="peer", hosts=1, world_size=1,
                             priority=5,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.2)))
        for _ in range(10):
            sched._tick()
            time.sleep(0.02)
        assert sched.kv.try_get(k_state("peer")) == b"queued"
        assert sched.kv.keys("job/peer/test/ran/") == []
        assert "backfilled" not in job_events(sched.kv, "peer")
        states = sched.serve(timeout=60)
        assert states == {"occupant": "done", "head": "done",
                          "peer": "done"}, states
        # FIFO held: the head went first once the occupant's slots freed
        assert job_events(sched.kv, "head")["admitted"] <= \
            job_events(sched.kv, "peer")["admitted"]


@pytest.mark.slow  # ~4s of real agent work; tier-1 keeps the positive case
def test_backfill_starvation_guard_near_head_deadline(agent_script):
    """Once the head has burned half its admission window, backfill stops
    — the remaining window is reserved for making room."""
    with ClusterScheduler(3, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        sched.submit(JobSpec(job_id="occupant", hosts=2, world_size=2,
                             priority=5,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.6)))
        assert _tick_until(sched, lambda: (
            sched.kv.try_get(k_state("occupant")) == b"running"))
        sched.submit(JobSpec(job_id="head", hosts=2, world_size=2,
                             priority=5,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.2)))
        sched._tick()  # registers the head's admission deadline
        # simulate the head having consumed ~75% of its 120s window
        sched._queue_deadline["head"] = time.monotonic() + 30.0
        sched.submit(JobSpec(job_id="late", hosts=1, world_size=1,
                             priority=0,
                             agent_argv=_agent_argv(agent_script, "work",
                                                    0.2)))
        for _ in range(10):
            sched._tick()
            time.sleep(0.02)
        assert sched.kv.try_get(k_state("late")) == b"queued"
        assert "backfilled" not in job_events(sched.kv, "late")
        states = sched.serve(timeout=60)
        assert states == {"occupant": "done", "head": "done",
                          "late": "done"}, states


# -- admission deadline + sweep --------------------------------------------


def test_unsatisfiable_job_times_out_with_clean_namespace(agent_script):
    with ClusterScheduler(1, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        sched.start()
        # leaked-looking state from a previous life of the same id: the
        # sweep must take it out with the timeout
        ghost = for_job(sched.kv, "huge")
        ghost.set("leader/term", b"9")
        ghost.set("budget/claim/3", b"2")
        sched.submit(JobSpec(job_id="huge", hosts=4, world_size=4,
                             agent_argv=_agent_argv(agent_script, "work"),
                             admission_timeout=0.3))
        states = sched.serve(timeout=30)
        assert states == {"huge": "timeout"}, states
        # THE namespace-sweep assertion: no leaked claims anywhere
        assert sched.kv.keys(job_namespace("huge")) == []
        assert "timeout" in job_events(sched.kv, "huge")


# -- weighted fair share ---------------------------------------------------


def test_weighted_fair_share_converges_to_tenant_weights(agent_script):
    """Two equal-priority tenants on a pool of 1, shares 2:1.  Jobs are
    submitted interleaved (so raw seq order favours neither) and all have
    the same duration; the admission order must track virtual time, i.e.
    at every decision point the normalised service |served_a/2 - served_b|
    stays within one job of balanced.  Plain FIFO would drift to 1.5.
    Jobs are short — the property is about admission ORDER, and vtime
    normalises by duration, so only equality of durations matters."""
    alpha = [f"a{i}" for i in range(6)]
    beta = [f"b{i}" for i in range(3)]
    with ClusterScheduler(1, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        # interleave submissions: a0 b0 a1 b1 a2 b2 a3 a4 a5
        order = [j for pair in zip(alpha, beta) for j in pair] + alpha[3:]
        for jid in order:
            tenant = "alpha" if jid.startswith("a") else "beta"
            share = 2.0 if tenant == "alpha" else 1.0
            sched.submit(JobSpec(
                job_id=jid, hosts=1, world_size=1, tenant=tenant,
                share=share,
                agent_argv=_agent_argv(agent_script, "work", 0.2)))
        states = sched.serve(timeout=120)
        assert all(s == "done" for s in states.values()), states
        admitted = sorted(
            alpha + beta, key=lambda j: job_events(sched.kv, j)["admitted"])
        na = nb = 0
        for jid in admitted:
            if jid.startswith("a"):
                na += 1
            else:
                nb += 1
            assert abs(na / 2.0 - nb / 1.0) <= 1.0, \
                f"service drifted from 2:1 weights at {admitted}"
        # both tenants were charged virtual time, normalised by share:
        # 6 jobs at share 2 and 3 jobs at share 1 accrue about equally.
        va, vb = sched.tenant_vtime("alpha"), sched.tenant_vtime("beta")
        assert va > 0 and vb > 0
        assert 0.4 < va / vb < 2.5, (va, vb)


@pytest.mark.slow  # ~12s of subprocess scheduler work; tier-1 keeps the
# in-process convergence test above plus both death-adoption kill orders
def test_vtime_ledger_survives_scheduler_death(agent_script):
    """Satellite: kill the scheduler mid-run; the successor must restore
    the per-tenant virtual-time ledger from sched/vtime/<tenant> and keep
    the 2:1 weighted convergence across the whole admission sequence — a
    successor that reset the ledger would restart both tenants at zero
    service and owe alpha nothing for what it already consumed."""
    alpha = [f"a{i}" for i in range(6)]
    beta = [f"b{i}" for i in range(3)]
    with KVServer() as srv:
        kv = KVClient(port=srv.port)
        order = [j for pair in zip(alpha, beta) for j in pair] + alpha[3:]
        for jid in order:
            tenant = "alpha" if jid.startswith("a") else "beta"
            submit_job(kv, JobSpec(
                job_id=jid, hosts=1, world_size=1, tenant=tenant,
                share=2.0 if tenant == "alpha" else 1.0,
                agent_argv=_agent_argv(agent_script, "work", 0.4)))
        sched1 = _spawn_scheduler_proc(srv.port, pool=1)
        try:
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                done = [j for j in list_jobs(kv) if j["state"] == "done"]
                if len(done) >= 3:
                    break
                time.sleep(0.05)
            else:
                pytest.fail("first scheduler never finished 3 jobs")
            sched1.kill()
            sched1.wait()
            # the ledger the dead scheduler persisted job-by-job
            persisted = {t: float(kv.get(f"sched/vtime/{t}"))
                         for t in ("alpha", "beta")}
            assert persisted["alpha"] > 0 and persisted["beta"] > 0
            with ClusterScheduler(1, kv_port=srv.port, poll=0.02,
                                  adopt_timeout=2.0, extra_env=ENV,
                                  verbose=False) as s2:
                s2.start()
                # restored BEFORE any new charge, not recomputed from zero
                assert s2.tenant_vtime("alpha") == persisted["alpha"]
                assert s2.tenant_vtime("beta") == persisted["beta"]
                states = s2.serve(timeout=120)
            assert all(s == "done" for s in states.values()), states
            admitted = sorted(
                alpha + beta, key=lambda j: job_events(kv, j)["admitted"])
            na = nb = 0
            for jid in admitted:
                if jid.startswith("a"):
                    na += 1
                else:
                    nb += 1
                assert abs(na / 2.0 - nb / 1.0) <= 1.0, \
                    f"2:1 convergence broken across restart: {admitted}"
        finally:
            if sched1.poll() is None:
                sched1.kill()
                sched1.wait()
            kv.close()


# -- serve/train colocation (autoscaler drives the scheduler) --------------


def test_autoscaler_preempts_training_and_returns_slots(agent_script):
    """End-to-end colocation story against a live scheduler: a queue-depth
    spike makes the autoscaler grow the serve gang at high priority, which
    preempts the low-priority 2-host training gang (checkpoint-out via
    SIGTERM, uncharged requeue); once load subsides the gang shrinks
    newest-first and training resumes on the returned slots and finishes
    clean.  The whole episode must be reconstructable from job_events +
    autoscale_events alone.  (Bitwise resume parity is proven by
    test_priority_preemption_checkpoints_and_resumes and the checkpoint
    suite; replica drain zero-loss by the serve SLO/chaos tests — here the
    stub agents prove the slot choreography.)"""
    from tpu_sandbox.serve.autoscale import (AutoscaleConfig,
                                             ReplicaAutoscaler,
                                             autoscale_events)
    from tpu_sandbox.serve.replica import k_load

    with ClusterScheduler(2, poll=0.02, extra_env=ENV,
                          verbose=False) as sched:
        sched.submit(JobSpec(
            job_id="train", hosts=2, world_size=2, priority=0,
            tenant="train",
            agent_argv=_agent_argv(agent_script, "preemptible")))
        assert _tick_until(sched, lambda: (
            sched.kv.try_get(k_state("train")) == b"running"
            and sched.kv.keys("job/train/test/ran/")))
        asc = ReplicaAutoscaler(
            sched.kv, _agent_argv(agent_script, "work", 60.0),
            cfg=AutoscaleConfig(min_replicas=0, max_replicas=2,
                                scale_up_depth=4.0, scale_down_depth=0.5,
                                hysteresis_ticks=1, cooldown_s=0.0,
                                priority=10))

        def report(depth):
            sched.kv.set_ttl(k_load("stub"),
                             json.dumps({"queue_depth": depth}), 60.0)

        # overload: the replica engines report deep queues
        report(9.0)
        up1 = asc.tick()
        assert up1 and up1["action"] == "scale_up" \
            and up1["reason"] == "queue_depth"
        rep1, rep2 = up1["job_id"], None
        # the 1-host serve job outranks the 2-host training gang: training
        # is SIGTERMed, checkpoints out, and requeues at its original seq
        assert _tick_until(sched, lambda: (
            sched.kv.keys(f"job/{rep1}/test/ran/")
            and sched.kv.try_get(k_state("train")) == b"queued"))
        up2 = asc.tick()
        assert up2 and up2["action"] == "scale_up" and up2["n_after"] == 2
        rep2 = up2["job_id"]
        # wait for the replica agents themselves (not just the admission
        # record) so the scale-down SIGTERM can't race their startup
        assert _tick_until(sched, lambda: (
            sched.kv.keys(f"job/{rep2}/test/ran/")))
        # training needs 2 hosts and 0 are free: it must stay queued, NOT
        # half-launch (gang admission is all-or-nothing)
        assert sched.kv.try_get(k_state("train")) == b"queued"

        # load subsides: shrink newest-first, handing slots back
        report(0.0)
        down1 = asc.tick()
        assert down1 and down1["action"] == "scale_down" \
            and down1["job_id"] == rep2
        assert _tick_until(sched, lambda: (
            sched.kv.try_get(k_state(rep2)) == b"cancelled"))
        # 1 free host is still not enough for the 2-host training gang
        assert sched.kv.try_get(k_state("train")) == b"queued"
        down2 = asc.tick()
        assert down2 and down2["action"] == "scale_down" \
            and down2["job_id"] == rep1

        states = sched.serve(timeout=120)
        assert states["train"] == "done", states
        assert states[rep1] == "cancelled" and states[rep2] == "cancelled"
        # the resumed verdict is the second stub life's, uncharged
        verdict = json.loads(sched.kv.get(k_verdict("train")))
        assert verdict["ok"] and verdict["restarts"] == 0
        # the timeline: preempted before the re-admission that finished it
        ev = job_events(sched.kv, "train")
        assert ev["admitted"] <= ev["preempt_sent"] <= ev["preempted"] \
            <= ev["readmitted"]
        # and the autoscaler's own event log tells the same story
        actions = [(e["action"], e["job_id"])
                   for e in autoscale_events(sched.kv)]
        assert actions == [("scale_up", rep1), ("scale_up", rep2),
                           ("scale_down", rep2), ("scale_down", rep1)]


# -- scheduler death / adoption (satellite: random kill orders) ------------


def _spawn_scheduler_proc(port, pool):
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "from tpu_sandbox.runtime.scheduler import ClusterScheduler\n"
        "ClusterScheduler(%d, kv_port=%d, poll=0.02,\n"
        "                 verbose=False).serve(timeout=120)\n"
        % (ROOT, pool, port)
    )
    return subprocess.Popen([PY, "-c", code],
                            env={**os.environ, "PYTHONPATH": ROOT})


@pytest.mark.parametrize("kill_order", [
    ("scheduler", "victim_agent"),
    ("victim_agent", "scheduler"),
])
def test_scheduler_death_leaves_survivor_unharmed(agent_script, kill_order):
    """Kill the scheduler process and one job's agent in both orders: the
    OTHER job must finish clean (no deadlock) with zero restarts charged
    (no double-charge), reaped by a successor scheduler that adopts what
    the dead one left running."""
    with KVServer() as srv:
        kv = KVClient(port=srv.port)
        submit_job(kv, JobSpec(
            job_id="victim", hosts=1, world_size=1,
            agent_argv=_agent_argv(agent_script, "mortal")))
        submit_job(kv, JobSpec(
            job_id="survivor", hosts=1, world_size=1,
            agent_argv=_agent_argv(agent_script, "work", 2.0)))
        sched1 = _spawn_scheduler_proc(srv.port, pool=2)
        try:
            # wait for both gangs to be up (agents registered their pids)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if kv.keys("job/victim/test/ran/") \
                        and kv.keys("job/survivor/test/ran/"):
                    break
                time.sleep(0.05)
            else:
                pytest.fail("jobs never started under the scheduler")
            victim_pid = int(kv.get("job/victim/test/ran/0"))
            for target in kill_order:
                if target == "scheduler":
                    sched1.kill()
                    sched1.wait()
                else:
                    os.kill(victim_pid, signal.SIGKILL)
                time.sleep(0.1)
            # the survivor's agent is parented to the dead scheduler but
            # keeps running — its verdict lands without any scheduler
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if kv.try_get("job/survivor/job/done") is not None:
                    break
                time.sleep(0.05)
            else:
                pytest.fail("survivor deadlocked after the kills")
            # a successor adopts the wreckage: survivor reaped as done,
            # the victim's dead gang detected by silence and failed
            with ClusterScheduler(2, kv_port=srv.port, poll=0.02,
                                  adopt_timeout=1.0, verbose=False) as s2:
                states = s2.serve(timeout=120)
            assert states["survivor"] == "done", states
            assert states["victim"] == "failed", states
            verdict = json.loads(kv.get(k_verdict("survivor")))
            assert verdict["ok"] and verdict["restarts"] == 0
            # both namespaces swept; neither job can leak into a third
            assert kv.keys("job/survivor/") == []
            assert kv.keys("job/victim/") == []
        finally:
            if sched1.poll() is None:
                sched1.kill()
                sched1.wait()
            kv.close()
