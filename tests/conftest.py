"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference's test strategy (SURVEY.md §4) simulates multi-node with
multi-process + gloo on localhost. The TPU-native analogue is JAX's CPU
backend with XLA_FORCE_HOST_PLATFORM_DEVICE_COUNT fake devices — single
process, 8 devices, real mesh/collective semantics.

Must run before any `import jax` in test modules, hence conftest-level env.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# jax may already be imported (site customization registers a TPU plugin and
# sets JAX_PLATFORMS before conftest runs); backend init is lazy, so flipping
# the config here still forces CPU as long as no backend has initialized.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5): no such option; the XLA_FLAGS env set above is the
    # only way to size the host platform, and it already asks for 8
    pass
jax.config.update("jax_threefry_partitionable", True)

import gc  # noqa: E402
import sys  # noqa: E402
import threading  # noqa: E402
import time  # noqa: E402

import pytest  # noqa: E402

# A 400+-test session grows jax's jit caches monotonically (gigabytes of
# live objects), and CPython's cyclic GC walks the entire live set on every
# full collection. Trace-time allocation churn trips the default thresholds
# constantly, so by the later test files each collection costs seconds and
# the suite visibly crawls (same tests run 1.5-2x faster in isolation).
# Tracing produces garbage, not leaks — collect far less often, and keep
# the live set the collector walks bounded by dropping the compile caches
# at module boundaries (modules don't share jitted functions, so the only
# cost is re-tracing the handful of library-level jits like
# resize_on_device).
gc.set_threshold(50_000, 20, 20)
gc.freeze()  # startup world (jax, numpy, flax) is permanent: never scan it


@pytest.fixture(autouse=True, scope="module")
def _bounded_jit_caches():
    yield
    jax.clear_caches()
    gc.collect()
    # whatever survived the module's teardown is long-lived by definition
    # (session fixtures, module caches jax keeps internally) — exempt it
    # from every future collection instead of rescanning it per module
    gc.freeze()


@pytest.fixture(autouse=True)
def _no_resource_leaks():
    """Fail any test that leaks a live KVServer or a new non-daemon thread.

    A leaked server holds its port for the rest of the session and turns
    later find_free_port races into one-in-N flakes that reproduce only in
    full runs; a leaked non-daemon thread hangs interpreter shutdown. Both
    were historically found by CI timeouts instead of by the guilty test —
    this pins the blame at the source. Daemon threads get a pass (wedged
    Heartbeat threads are abandoned by design), and stragglers get a short
    join grace first so tests that are merely slow to wind down don't trip.

    Serve engines count too: an engine still holding admitted or queued
    requests after a test means the test abandoned in-flight work (the
    replica drain/requeue paths exist precisely so nothing is ever
    abandoned), so it fails the same way a leaked server does.

    Gateways count the same way a KVServer does: a live one holds its
    listening port and a cloned KV connection for the rest of the session.
    """
    from tpu_sandbox.runtime import kvstore

    threads_before = set(threading.enumerate())
    servers_before = set(kvstore.live_servers())
    gateways_before = set()
    if "tpu_sandbox.gateway.server" in sys.modules:
        from tpu_sandbox.gateway.server import live_gateways

        gateways_before = set(live_gateways())
    yield
    me = threading.current_thread()

    def stragglers():
        return [t for t in threading.enumerate()
                if t not in threads_before and t is not me
                and not t.daemon and t.is_alive()]

    deadline = time.monotonic() + 2.0
    leaked_threads = stragglers()
    while leaked_threads and time.monotonic() < deadline:
        for t in leaked_threads:
            t.join(timeout=0.2)
        leaked_threads = stragglers()

    leaked_servers = [s for s in kvstore.live_servers()
                      if s not in servers_before]
    problems = []
    if "tpu_sandbox.serve.engine" in sys.modules:
        from tpu_sandbox.serve.engine import live_engines

        busy = live_engines()
        if busy:
            loads = [(e.active_requests, len(e.waiting)) for e in busy]
            for e in busy:  # unwedge the rest of the session
                e.drain_to_requests()
            problems.append(
                f"{len(busy)} serve engine(s) abandoned with in-flight "
                f"work (active, waiting): {loads}"
            )
    if "tpu_sandbox.gateway.server" in sys.modules:
        from tpu_sandbox.gateway.server import live_gateways

        open_gateways = [g for g in live_gateways()
                         if g not in gateways_before]
        if open_gateways:
            gw_ports = [g.port for g in open_gateways]
            for g in open_gateways:  # free ports/threads for the session
                g.close()
            problems.append(
                f"{len(gw_ports)} gateway(s) left running on port(s) "
                f"{gw_ports}"
            )
    if leaked_servers:
        ports = [s.port for s in leaked_servers]
        for s in leaked_servers:  # free the ports for the rest of the run
            s.stop()
        problems.append(
            f"{len(ports)} KVServer(s) left running on port(s) {ports}"
        )
    if leaked_threads:
        names = ", ".join(repr(t.name) for t in leaked_threads)
        problems.append(f"non-daemon thread(s) still alive: {names}")
    if problems:
        pytest.fail("resource leak: " + "; ".join(problems), pytrace=False)


def pytest_collection_modifyitems(config, items):
    """Safety net: any ``*_integration`` test module is slow by construction
    (it spawns real worker processes and waits on supervisors/timeouts), so
    mark the whole module rather than trusting each test to remember the
    decorator. Tier-1 (`-m 'not slow'`) stays fast unit tests only."""
    slow = pytest.mark.slow
    for item in items:
        mod = item.module.__name__ if item.module else ""
        if mod.endswith("_integration"):
            item.add_marker(slow)


@pytest.fixture(scope="session")
def devices():
    assert jax.device_count() == 8, "expected 8 virtual CPU devices"
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8(devices):
    from tpu_sandbox.runtime.mesh import make_mesh

    return make_mesh({"data": 8})
