"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference's test strategy (SURVEY.md §4) simulates multi-node with
multi-process + gloo on localhost. The TPU-native analogue is JAX's CPU
backend with XLA_FORCE_HOST_PLATFORM_DEVICE_COUNT fake devices — single
process, 8 devices, real mesh/collective semantics.

Must run before any `import jax` in test modules, hence conftest-level env.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# jax may already be imported (site customization registers a TPU plugin and
# sets JAX_PLATFORMS before conftest runs); backend init is lazy, so flipping
# the config here still forces CPU as long as no backend has initialized.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5): no such option; the XLA_FLAGS env set above is the
    # only way to size the host platform, and it already asks for 8
    pass
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Safety net: any ``*_integration`` test module is slow by construction
    (it spawns real worker processes and waits on supervisors/timeouts), so
    mark the whole module rather than trusting each test to remember the
    decorator. Tier-1 (`-m 'not slow'`) stays fast unit tests only."""
    slow = pytest.mark.slow
    for item in items:
        mod = item.module.__name__ if item.module else ""
        if mod.endswith("_integration"):
            item.add_marker(slow)


@pytest.fixture(scope="session")
def devices():
    assert jax.device_count() == 8, "expected 8 virtual CPU devices"
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8(devices):
    from tpu_sandbox.runtime.mesh import make_mesh

    return make_mesh({"data": 8})
