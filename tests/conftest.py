"""Test harness: run everything on a virtual 8-device CPU mesh.

The reference's test strategy (SURVEY.md §4) simulates multi-node with
multi-process + gloo on localhost. The TPU-native analogue is JAX's CPU
backend with XLA_FORCE_HOST_PLATFORM_DEVICE_COUNT fake devices — single
process, 8 devices, real mesh/collective semantics.

Must run before any `import jax` in test modules, hence conftest-level env.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# jax may already be imported (site customization registers a TPU plugin and
# sets JAX_PLATFORMS before conftest runs); backend init is lazy, so flipping
# the config here still forces CPU as long as no backend has initialized.
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jax (< 0.5): no such option; the XLA_FLAGS env set above is the
    # only way to size the host platform, and it already asks for 8
    pass
jax.config.update("jax_threefry_partitionable", True)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    assert jax.device_count() == 8, "expected 8 virtual CPU devices"
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8(devices):
    from tpu_sandbox.runtime.mesh import make_mesh

    return make_mesh({"data": 8})
