"""Trace analytics plane: critical-path attribution, profile compare,
bubble accounting, and the canonical workload trace.

Most tests run over the committed fixture trace dirs
(``tests/fixtures/trace_small`` and its 30%-slower-decode twin
``trace_slow`` — regenerate with ``tests/fixtures/make_trace_fixtures.py``)
whose timestamps are hand-placed, so segment math is asserted exactly.
"""

import json
import os

import pytest

from tpu_sandbox.obs import critpath, workload
from tpu_sandbox.obs.collect import load_merged

from tests.test_gateway import kv_pair  # noqa: F401 (fixture)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
TRACE_SMALL = os.path.join(FIXTURES, "trace_small")
TRACE_SLOW = os.path.join(FIXTURES, "trace_slow")


@pytest.fixture(scope="module")
def small_merged():
    return load_merged(TRACE_SMALL)


@pytest.fixture(scope="module")
def small_analysis(small_merged):
    return critpath.analyze(small_merged)


# -- critical path walk -------------------------------------------------------


def test_critical_path_is_root_first_causal_chain(small_merged):
    from tpu_sandbox.obs.collect import trace_chains

    recs = trace_chains(small_merged)["t00"]
    names = [r["name"] for r in critpath.critical_path(recs)]
    assert names == ["submit", "route", "enqueue", "claim", "admit",
                     "decode", "publish", "verdict"]
    # prefill refines admit but is not on the causal spine
    assert "prefill" not in names


def test_terminal_prefers_verdict_over_later_noise():
    recs = [
        {"ph": "X", "name": "decode", "uts": 0.0, "dur": 0.01,
         "span": "a.1", "parent": None, "trace": "t", "pkey": "p/1"},
        {"ph": "i", "name": "verdict", "uts": 0.011, "span": "a.2",
         "parent": "a.1", "trace": "t", "pkey": "p/1",
         "args": {"verdict": "ok"}},
        # a scavenger instant landing after the verdict must not steal
        # the terminal slot
        {"ph": "i", "name": "lease:expired", "uts": 0.02, "span": "a.3",
         "parent": None, "trace": "t", "pkey": "p/1"},
    ]
    assert critpath._terminal(recs)["name"] == "verdict"


# -- attribution --------------------------------------------------------------


def test_attribution_exact_segments_on_fixture(small_analysis):
    req = next(r for r in small_analysis["requests"] if r["rid"] == "r00")
    assert req["outcome"] == "ok"
    assert req["coverage"] == pytest.approx(1.0)
    ms = {k: v * 1e3 for k, v in req["segments"].items()}
    # hand-placed fixture timestamps -> exact segment durations
    assert ms["submit"] == pytest.approx(0.2, abs=1e-6)
    assert ms["route"] == pytest.approx(0.8, abs=1e-6)
    assert ms["enqueue"] == pytest.approx(0.2, abs=1e-6)
    assert ms["queue_wait"] == pytest.approx(1.8, abs=1e-6)
    assert ms["claim"] == pytest.approx(0.5, abs=1e-6)
    assert ms["engine_queue"] == pytest.approx(0.1, abs=1e-6)
    assert ms["prefill"] == pytest.approx(3.8, abs=1e-6)
    assert ms["decode"] == pytest.approx(20.0, abs=1e-6)
    assert ms["publish"] == pytest.approx(0.6, abs=1e-6)
    assert ms["publish_wait"] == pytest.approx(0.3, abs=1e-6)
    # attribution sums to the wall exactly
    assert sum(req["segments"].values()) == pytest.approx(req["wall_s"])


def test_blame_names_the_segment_that_ate_the_shed_request(small_analysis):
    shed = next(r for r in small_analysis["requests"] if r["rid"] == "r06")
    assert shed["outcome"] == "shed:capacity"
    assert shed["blame"] == "queue_wait"
    prof = small_analysis["profile"]
    assert prof["blame"] == {"queue_wait": 1}
    assert prof["requests"] == 7 and prof["ok"] == 6
    assert prof["coverage_min"] == pytest.approx(1.0)


def test_swap_stall_carved_out_of_queue_gap():
    recs = [
        {"ph": "X", "name": "submit", "uts": 0.0, "dur": 0.001,
         "span": "a.1", "parent": None, "trace": "t", "pkey": "client/1",
         "args": {"rid": "r0"}},
        {"ph": "X", "name": "enqueue", "uts": 0.001, "dur": 0.0002,
         "span": "a.2", "parent": "a.1", "trace": "t", "pkey": "gw/1"},
        {"ph": "X", "name": "claim", "uts": 0.010, "dur": 0.0005,
         "span": "b.1", "parent": "a.2", "trace": "t", "pkey": "serve/1"},
        {"ph": "i", "name": "verdict", "uts": 0.0105, "span": "b.2",
         "parent": "b.1", "trace": "t", "pkey": "serve/1",
         "args": {"verdict": "ok"}},
    ]
    stall = {"ph": "X", "name": "swap:pause", "uts": 0.002, "dur": 0.004,
             "span": "b.9", "parent": None, "trace": None, "pkey": "serve/1"}

    req = critpath.attribute_request(recs, [stall])
    ms = {k: v * 1e3 for k, v in req["segments"].items()}
    # the 8.8ms enqueue->claim gap: 4ms explained by the overlapping
    # weight swap, the 0.8ms before + 4ms after stay queue_wait
    assert ms["swap_pause"] == pytest.approx(4.0, abs=1e-6)
    assert ms["queue_wait"] == pytest.approx(4.8, abs=1e-6)
    assert req["coverage"] == pytest.approx(1.0)
    assert sum(req["segments"].values()) == pytest.approx(req["wall_s"])

    # a swap on some other engine does not explain this request's wait
    other = dict(stall, pkey="serve/other")
    req2 = critpath.attribute_request(recs, [other])
    assert "swap_pause" not in req2["segments"]
    assert req2["segments"]["queue_wait"] * 1e3 == pytest.approx(8.8,
                                                                 abs=1e-6)


def test_aggregate_shape_and_samples(small_analysis):
    prof = small_analysis["profile"]
    assert prof["schema"] == critpath.PROFILE_SCHEMA
    dec = prof["segments"]["decode"]
    assert dec["n"] == 6
    assert dec["samples"] == sorted(dec["samples"])
    assert dec["median_s"] == pytest.approx(0.021, abs=1e-6)
    shares = sum(s["share"] for s in prof["segments"].values())
    assert shares == pytest.approx(1.0, abs=1e-3)
    # the serving replica carries the request segments in the proc view
    assert any(p.startswith("serve-rep0") for p in prof["by_proc"])


# -- compare / tracediff engine -----------------------------------------------


def test_compare_flags_decode_slowdown_and_only_decode(small_analysis):
    prof_a = small_analysis["profile"]
    prof_b = critpath.analyze(load_merged(TRACE_SLOW))["profile"]
    cmp = critpath.compare_profiles(prof_a, prof_b)
    assert cmp["regressions"] == ["decode"]
    dec = next(r for r in cmp["segments"] if r["segment"] == "decode")
    assert dec["ratio"] == pytest.approx(1.3, abs=0.01)


def test_compare_identical_profiles_is_clean(small_analysis):
    prof = small_analysis["profile"]
    cmp = critpath.compare_profiles(prof, prof)
    assert cmp["regressions"] == []
    assert cmp["wall_ratio"] == pytest.approx(1.0)


def test_profile_save_load_roundtrip_and_schema_gate(small_analysis,
                                                     tmp_path):
    prof = small_analysis["profile"]
    path = str(tmp_path / "prof.json")
    critpath.save_profile(prof, path)
    assert critpath.load_profile(path) == prof
    # a trace dir analyzes on the fly to the same profile
    assert critpath.load_profile(TRACE_SMALL) == prof
    bad = dict(prof, schema="tpu-sandbox.critpath/999")
    critpath.save_profile(bad, path)
    with pytest.raises(ValueError, match="schema"):
        critpath.load_profile(path)


# -- MPMD bubble accounting ---------------------------------------------------


def test_bubble_fractions_from_stage_spans():
    def rec(name, dur, stage, step):
        return {"ph": "X", "name": name, "uts": 0.0, "dur": dur,
                "span": None, "parent": None,
                "args": {"stage": stage, "step": step}}

    merged = [
        rec("stage:step", 0.010, 0, 0),
        rec("stage:op", 0.004, 0, 0), rec("stage:op", 0.004, 0, 0),
        rec("stage:step", 0.010, 1, 0),
        rec("stage:op", 0.010, 1, 0),
    ]
    out = critpath.bubble_fractions(merged)
    assert out["per_stage"] == {0: pytest.approx(0.2), 1: pytest.approx(0.0)}
    assert out["mean"] == pytest.approx(0.1)
    assert {(r["stage"], r["step"]) for r in out["per_step"]} == {(0, 0),
                                                                  (1, 0)}


# -- tsdb publication (the fleetop feed) --------------------------------------


def test_publish_profile_lands_in_tsdb(small_analysis, kv_pair):
    from tpu_sandbox.obs import tsdb

    _, kv, _ = kv_pair
    wrote = critpath.publish_profile(kv, small_analysis["profile"])
    assert wrote > 0
    shares = tsdb.read_series(kv, "critpath.segment.share")
    segs = {row["series"].split("seg=")[1].rstrip("}") for row in shares}
    assert "decode" in segs and "queue_wait" in segs
    cov = tsdb.latest_value(tsdb.read_series(kv, "critpath.coverage"))
    assert cov == pytest.approx(small_analysis["profile"]["coverage_mean"])


# -- workload trace -----------------------------------------------------------


def test_workload_from_trace_fields(small_merged):
    wl = workload.from_trace(small_merged, source="fixture")
    assert wl["schema"] == workload.SCHEMA
    rows = {r["rid"]: r for r in wl["requests"]}
    assert len(rows) == 7
    assert rows["r00"]["t_s"] == 0.0
    assert rows["r03"]["t_s"] == pytest.approx(0.150)
    assert rows["r02"]["prompt_tokens"] == 22
    assert rows["r02"]["decode_tokens"] == 10
    assert rows["r02"]["chain"] == "aa11"
    assert rows["r05"]["outcome"] == "ok"
    assert rows["r06"]["outcome"] == "shed:capacity"
    assert rows["r06"]["decode_tokens"] == 0
    # replay order is arrival order
    assert [r["rid"] for r in workload.replay_order(wl)] == \
        [f"r{i:02d}" for i in range(7)]


def test_workload_roundtrip_byte_identical(small_merged, tmp_path):
    wl = workload.from_trace(small_merged, source="fixture")
    text = workload.dumps(wl)
    assert text.endswith("\n")
    assert workload.dumps(workload.loads(text)) == text
    path = str(tmp_path / "wl.json")
    workload.save(wl, path)
    with open(path, "r", encoding="utf-8") as fh:
        assert fh.read() == text
    assert workload.load(path) == wl


def test_workload_validation_rejects_bad_traces(small_merged):
    wl = workload.from_trace(small_merged)
    with pytest.raises(ValueError, match="schema"):
        workload.loads(json.dumps(dict(wl, schema="workload/0")))
    broken = json.loads(workload.dumps(wl))
    del broken["requests"][0]["chain"]
    with pytest.raises(ValueError, match="missing fields"):
        workload.loads(json.dumps(broken))
    neg = json.loads(workload.dumps(wl))
    neg["requests"][0]["t_s"] = -1.0
    with pytest.raises(ValueError, match="bad arrival"):
        workload.loads(json.dumps(neg))
