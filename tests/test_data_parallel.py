"""Data-parallel engine tests on the 8-virtual-device mesh.

The reference's correctness story was eyeballed loss curves; here it's
asserted: DP over 8 shards must match single-device training on the same
effective batch exactly (BN-free model — bitwise-level agreement up to fp
reassociation), per-replica BN stats must actually diverge per rank (DDP
does not sync BN), and the sharded loader must reproduce DistributedSampler
rank shards."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_sandbox.data import ShardedBatchLoader, synthetic_mnist
from tpu_sandbox.data.mnist import normalize
from tpu_sandbox.models import ConvNet
from tpu_sandbox.parallel import DataParallel
from tpu_sandbox.runtime.mesh import make_mesh
from tpu_sandbox.train import TrainState, make_train_step


def setup(use_bn, lr=0.05):
    model = ConvNet(use_bn=use_bn)
    tx = optax.sgd(lr)
    state = TrainState.create(model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx)
    return model, tx, state


def test_dp_matches_single_device_without_bn(mesh8):
    """Same params, same effective batch 16: one DP step over 8 shards ==
    one single-device step (pmean of shard grads == full-batch grad)."""
    model, tx, state = setup(use_bn=False)
    images, labels = synthetic_mnist(n=16, seed=0)
    images, labels = normalize(images), labels.astype("int32")

    single_step = make_train_step(model, tx, donate=False)
    ref_state, ref_loss = single_step(state, jnp.asarray(images), jnp.asarray(labels))

    dp = DataParallel(model, tx, mesh8, donate=False)
    dstate = dp.shard_state(state)
    di, dl = dp.shard_batch(images, labels)
    new_state, losses = dp.train_step(dstate, di, dl)

    assert losses.shape == (8,)
    # global mean loss == mean of shard losses (equal shard sizes)
    np.testing.assert_allclose(float(jnp.mean(losses)), float(ref_loss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-6
        ),
        new_state.params,
        ref_state.params,
    )


def test_dp_params_stay_replicated(mesh8):
    model, tx, state = setup(use_bn=True)
    dp = DataParallel(model, tx, mesh8, donate=False)
    dstate = dp.shard_state(state)
    images, labels = synthetic_mnist(n=16, seed=0)
    new_state, _ = dp.train_step(*((dstate,) + dp.shard_batch(normalize(images), labels.astype("int32"))))
    # every device must hold identical params after the step
    kernel = new_state.params["conv1"]["kernel"]
    shards = [np.asarray(s.data) for s in kernel.addressable_shards]
    for s in shards[1:]:
        np.testing.assert_array_equal(shards[0], s)


def test_bn_stats_are_per_replica(mesh8):
    """Feed rank-dependent data: BN means must differ per rank (DDP parity:
    no cross-replica BN sync)."""
    model, tx, state = setup(use_bn=True)
    dp = DataParallel(model, tx, mesh8, donate=False)
    dstate = dp.shard_state(state)
    # biased batches: rank i sees images scaled by i/8
    images = np.concatenate(
        [normalize(synthetic_mnist(n=2, seed=0)[0]) * (i / 8) for i in range(8)]
    )
    labels = np.zeros(16, np.int32)
    new_state, _ = dp.train_step(dstate, *dp.shard_batch(images, labels))
    means = np.asarray(new_state.batch_stats["bn1"]["mean"])  # [8, 16]
    assert means.shape[0] == 8
    assert not np.allclose(means[0], means[7])
    # and unshard_state picks one rank's stats
    local = dp.unshard_state(new_state, rank=3)
    np.testing.assert_array_equal(
        np.asarray(local.batch_stats["bn1"]["mean"]), means[3]
    )


def test_dp_loss_vector_is_rank_local(mesh8):
    model, tx, state = setup(use_bn=False)
    dp = DataParallel(model, tx, mesh8, donate=False)
    dp_avg = DataParallel(model, tx, mesh8, donate=False, average_loss=True)
    images, labels = synthetic_mnist(n=16, seed=0)
    batch = (normalize(images), labels.astype("int32"))
    _, local = dp.train_step(dp.shard_state(state), *dp.shard_batch(*batch))
    _, avg = dp_avg.train_step(dp_avg.shard_state(state), *dp_avg.shard_batch(*batch))
    assert not np.allclose(np.asarray(local), np.asarray(local)[0])  # ranks differ
    np.testing.assert_allclose(np.asarray(avg), np.mean(np.asarray(local)), rtol=1e-6)


def test_dp_validates_axis(mesh8):
    model, tx, _ = setup(use_bn=False)
    with pytest.raises(ValueError, match="axis"):
        DataParallel(model, tx, mesh8, axis="model")


def test_sharded_loader_reproduces_rank_shards():
    images, labels = synthetic_mnist(n=64, seed=0)
    loader = ShardedBatchLoader(images, labels, batch_size=4, num_replicas=8)
    batch_i, batch_l = next(iter(loader))
    assert batch_i.shape == (32, 28, 28)
    # device r's slice must equal what rank r's own sampler yields
    from tpu_sandbox.data import DistributedSampler

    for r in [0, 3, 7]:
        idx = DistributedSampler(64, 8, r).indices(0)[:4]
        np.testing.assert_array_equal(batch_l[r * 4 : (r + 1) * 4], labels[idx])


def test_sharded_loader_epochs_and_len():
    images, labels = synthetic_mnist(n=30, seed=0)
    loader = ShardedBatchLoader(images, labels, batch_size=4, num_replicas=4)
    # ceil(30/4)=8 per rank -> ceil(8/4)=2 steps
    assert len(loader) == 2
    steps = list(loader)
    assert steps[0][0].shape[0] == 16
    assert steps[1][0].shape[0] == 16  # padded equal shards even at the tail


def test_dp_training_loss_decreases(mesh8):
    from tpu_sandbox.train import Trainer

    model, tx, state = setup(use_bn=True)
    dp = DataParallel(model, tx, mesh8)
    images, labels = synthetic_mnist(n=128, seed=0)
    loader = ShardedBatchLoader(
        normalize(images), labels.astype("int32"), batch_size=2, num_replicas=8
    )

    def step(s, i, l):
        return dp.train_step(s, *dp.shard_batch(i, l))

    trainer = Trainer(step, log_every=1, verbose=False)
    final = trainer.fit(dp.shard_state(state), loader, epochs=4)
    assert np.mean(trainer.losses[-4:]) < np.mean(trainer.losses[:4]) * 0.9
    assert int(final.step) == 4 * len(loader)


def test_zero1_matches_plain_dp(mesh8):
    """ZeRO-1 (sharded optimizer state) is the same math as plain DP: with
    AdamW (stateful, elementwise) the losses and final params agree to
    float tolerance over several steps, while the big dim-0-divisible
    optimizer moments actually live sharded across the axis."""
    model = ConvNet(use_bn=False)
    tx = optax.adamw(1e-3)
    state0 = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx
    )
    images, labels = synthetic_mnist(n=16, seed=0)
    images, labels = normalize(images), labels.astype("int32")

    def run(zero):
        dp = DataParallel(model, tx, mesh8, zero=zero, donate=False)
        st = dp.shard_state(state0)
        losses = []
        for _ in range(3):
            st, loss = dp.train_step(st, *dp.shard_batch(images, labels))
            losses.append(np.asarray(loss))
        return st, losses

    st_plain, losses_plain = run(zero=False)
    st_zero, losses_zero = run(zero=True)
    np.testing.assert_allclose(
        np.stack(losses_zero), np.stack(losses_plain), rtol=1e-5
    )
    for (kp, p), (_, z) in zip(
        jax.tree_util.tree_leaves_with_path(st_plain.params),
        jax.tree_util.tree_leaves_with_path(st_zero.params),
    ):
        np.testing.assert_allclose(
            np.asarray(z), np.asarray(p), atol=1e-6,
            err_msg=jax.tree_util.keystr(kp),
        )

    # the fc kernel's Adam moments (dim0 = flattened features, divisible by
    # 8) must be sharded over the data axis; conv kernels (dim0=5) must not
    mu = st_zero.opt_state[0].mu
    fc_mu = mu["fc"]["kernel"]
    conv_mu = mu["conv1"]["kernel"]
    fc_spec = fc_mu.sharding.spec
    assert fc_spec and fc_spec[0] == "data", fc_spec
    conv_spec = conv_mu.sharding.spec
    assert not conv_spec or conv_spec[0] is None, conv_spec


def test_dp_s2dt_fused_input_matches_plain_resize(mesh8):
    """The full r04 production input path under DataParallel — raw 28x28
    batch -> fused resize+s2d -> ConvNetS2DT (sparse-tap conv1, fused
    tails) — computes the same step as the plain ConvNet with
    resize_on_device, on an 8-shard mesh (fp32, 64x64 target)."""
    import optax

    from tpu_sandbox.models import ConvNet
    from tpu_sandbox.models.convnet_s2d_t import ConvNetS2DT
    from tpu_sandbox.train import TrainState

    tx = optax.sgd(1e-2)
    plain = ConvNet(dtype=jnp.float32)
    s2dt = ConvNetS2DT(dtype=jnp.float32, fused_tail=True)
    state = TrainState.create(
        plain, jax.random.key(0), jnp.zeros((1, 64, 64, 1)), tx)

    images, labels = synthetic_mnist(n=16, seed=3)
    images, labels = normalize(images), labels.astype("int32")

    results = {}
    for name, model in (("plain", plain), ("s2dt", s2dt)):
        dp = DataParallel(model, tx, mesh8, donate=False,
                          image_size=(64, 64))
        dstate = dp.shard_state(state)
        di, dl = dp.shard_batch(images, labels)
        new_state, losses = dp.train_step(dstate, di, dl)
        results[name] = (float(jnp.mean(losses)), new_state.params)

    np.testing.assert_allclose(results["s2dt"][0], results["plain"][0],
                               rtol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5),
        results["s2dt"][1], results["plain"][1],
    )


def test_shard_state_local_refuses_single_controller(mesh8):
    """The partial-restore placement is only sound when each process owns
    exactly its own mesh slot; a single-controller 8-device process must
    be pushed to the full restore + shard_state path."""
    model, tx, state = setup(use_bn=True)
    dp = DataParallel(model, tx, mesh8, donate=False)
    with pytest.raises(ValueError, match="one process per mesh slot"):
        dp.shard_state_local(state, state)


def test_shard_state_local_places_rank_blocks(mesh8, monkeypatch):
    """Single-controller simulation of the multi-controller contract:
    with process_count==world and one local device, restore_partial's
    rank-local view (rep leaves global, shard0 leaves this rank's block)
    lands on the mesh with the same specs, shapes, and dtypes the full
    shard_state path produces — and the block itself bitwise."""
    model = ConvNet(use_bn=True)
    tx = optax.sgd(0.05, momentum=0.9)  # momentum: ZeRO-eligible opt state
    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx)
    dp = DataParallel(model, tx, mesh8, donate=False, zero=True)
    full = dp.shard_state(state)  # reference placement
    # rank 0's restore_partial view: device 0's addressable shard of every
    # leaf (full array for replicated leaves, the rank-0 block for sharded)
    local = jax.tree.map(
        lambda x: np.asarray(x.addressable_shards[0].data), full)

    monkeypatch.setattr(jax, "process_count", lambda: 8)
    monkeypatch.setattr(jax, "local_device_count", lambda: 1)
    placed = dp.shard_state_local(local, dp.checkpoint_template(state))

    def check(p, f):
        assert p.shape == f.shape and p.dtype == f.dtype
        assert p.sharding == f.sharding
        # device 0 holds rank 0's block (the only shard this simulated
        # process is authoritative for) — bitwise what the view held
        np.testing.assert_array_equal(
            np.asarray(p.addressable_shards[0].data),
            np.asarray(f.addressable_shards[0].data))
    jax.tree.map(check, placed, full)

    # a wrong-shaped block fails loudly instead of silently misplacing
    bad = local.replace(
        opt_state=jax.tree.map(
            lambda x: x[:1] if x.ndim >= 1 and x.shape[0] > 1 else x,
            local.opt_state))
    with pytest.raises(ValueError, match="local block|replicated leaf"):
        dp.shard_state_local(bad, dp.checkpoint_template(state))
