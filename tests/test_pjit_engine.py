"""PjitEngine tests: compiler-driven DP and TP on the virtual 8-device mesh.

The correctness bar mirrors test_data_parallel: sharded training must equal
single-device training on the same effective batch (BN-free model), and the
tensor-sharded head must actually be sharded (not silently replicated)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpu_sandbox.data import synthetic_mnist
from tpu_sandbox.data.mnist import normalize
from tpu_sandbox.models import ConvNet
from tpu_sandbox.parallel import PjitEngine
from tpu_sandbox.parallel.pjit_engine import param_specs
from tpu_sandbox.runtime.mesh import make_mesh
from tpu_sandbox.train import TrainState, make_train_step


def setup(lr=0.05, use_bn=False):
    model = ConvNet(use_bn=use_bn)
    tx = optax.sgd(lr)
    state = TrainState.create(model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx)
    images, labels = synthetic_mnist(n=16, seed=0)
    return model, tx, state, normalize(images), labels.astype("int32")


def test_param_specs_rules():
    model, _, state, _, _ = setup()
    specs = param_specs(state.params, [("fc/kernel", P(None, "model"))])
    assert specs["fc"]["kernel"] == P(None, "model")
    assert specs["fc"]["bias"] == P()
    assert specs["conv1"]["kernel"] == P()


def test_pjit_dp_matches_single_device(mesh8):
    model, tx, state, images, labels = setup()
    ref_state, ref_loss = make_train_step(model, tx, donate=False)(
        state, jnp.asarray(images), jnp.asarray(labels)
    )
    eng = PjitEngine(model, tx, mesh8, donate=False)
    sstate = eng.shard_state(state)
    new_state, loss = eng.train_step(sstate, *eng.shard_batch(images, labels))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6),
        new_state.params, ref_state.params,
    )


def test_pjit_tp_column_sharded_head():
    # column parallel: output dim (10) split over a 2-way model axis
    mesh = make_mesh({"data": 4, "model": 2})
    model, tx, state, images, labels = setup()
    eng = PjitEngine(
        model, tx, mesh, rules=[("fc/kernel", P(None, "model"))], donate=False
    )
    sstate = eng.shard_state(state)
    kernel = sstate.params["fc"]["kernel"]
    assert kernel.sharding.spec == P(None, "model")
    shard_shapes = {s.data.shape for s in kernel.addressable_shards}
    assert shard_shapes == {(1568, 5)}

    new_state, loss = eng.train_step(sstate, *eng.shard_batch(images, labels))
    assert np.isfinite(float(loss))
    assert new_state.params["fc"]["kernel"].sharding.spec == P(None, "model")


def test_pjit_tp_row_sharded_head_matches_single_device():
    """Row-parallel head (18M-dim analogue): kernel sharded on its input dim;
    XLA inserts the psum. Results must match the unsharded run."""
    mesh = make_mesh({"data": 2, "model": 4})
    model, tx, state, images, labels = setup()
    ref_state, ref_loss = make_train_step(model, tx, donate=False)(
        state, jnp.asarray(images), jnp.asarray(labels)
    )
    eng = PjitEngine(
        model, tx, mesh, rules=[("fc/kernel", P("model", None))], donate=False
    )
    sstate = eng.shard_state(state)
    shard_shapes = {s.data.shape for s in sstate.params["fc"]["kernel"].addressable_shards}
    assert shard_shapes == {(392, 10)}  # 1568/4 rows per model shard
    new_state, loss = eng.train_step(sstate, *eng.shard_batch(images, labels))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(new_state.params["fc"]["kernel"]),
        np.asarray(ref_state.params["fc"]["kernel"]),
        atol=1e-6,
    )


def test_pjit_spatial_sharding_matches_single_device():
    """The CNN's sequence-parallel analog: the image height dim sharded over
    a 'spatial' axis (XLA inserts conv halo exchanges). Must match the
    unsharded step."""
    mesh = make_mesh({"data": 2, "spatial": 4})
    model, tx, state, images, labels = setup()
    ref_state, ref_loss = make_train_step(model, tx, donate=False)(
        state, jnp.asarray(images), jnp.asarray(labels)
    )
    eng = PjitEngine(
        model, tx, mesh, input_spec=P("data", "spatial"), donate=False
    )
    sstate = eng.shard_state(state)
    si, sl = eng.shard_batch(images, labels)
    assert si.sharding.spec == P("data", "spatial")
    new_state, loss = eng.train_step(sstate, si, sl)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(new_state.params["conv1"]["kernel"]),
        np.asarray(ref_state.params["conv1"]["kernel"]),
        atol=1e-6,
    )


def test_pjit_with_bn_trains(mesh8):
    model, tx, state, images, labels = setup(use_bn=True)
    eng = PjitEngine(model, tx, mesh8, donate=False)
    sstate = eng.shard_state(state)
    s1, l1 = eng.train_step(sstate, *eng.shard_batch(images, labels))
    s2, l2 = eng.train_step(s1, *eng.shard_batch(images, labels))
    assert float(l2) < float(l1)  # SyncBN path trains


def test_pjit_validates_batch_axis(mesh8):
    model, tx, state, *_ = setup()
    with pytest.raises(ValueError, match="batch axis"):
        PjitEngine(model, tx, mesh8, batch_axis="model")


def _train_adamw(mesh8, n_steps=3, **engine_kw):
    """Shared harness for the ZeRO/FSDP exactness tests: AdamW ConvNet,
    3 engine steps from a fixed init; returns (final state, losses)."""
    import optax

    from tpu_sandbox.data import synthetic_mnist
    from tpu_sandbox.data.mnist import normalize
    from tpu_sandbox.models import ConvNet

    model = ConvNet(use_bn=False)
    tx = optax.adamw(1e-3)
    state0 = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 28, 28, 1)), tx
    )
    images, labels = synthetic_mnist(n=16, seed=0)
    images, labels = normalize(images), labels.astype("int32")
    eng = PjitEngine(model, tx, mesh8, donate=False, **engine_kw)
    st = eng.shard_state(state0)
    losses = []
    for _ in range(n_steps):
        st, loss = eng.train_step(st, *eng.shard_batch(images, labels))
        losses.append(float(loss))
    return st, losses


def _assert_params_equal(a, b):
    for (kp, x), (_, y) in zip(
        jax.tree_util.tree_leaves_with_path(a),
        jax.tree_util.tree_leaves_with_path(b),
    ):
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(x), atol=1e-6,
            err_msg=jax.tree_util.keystr(kp),
        )


def test_zero_axis_shards_opt_state(mesh8):
    """Compiler-driven ZeRO-1: PjitEngine(zero_axis='data') trains the same
    losses as the replicated engine while AdamW moments of otherwise
    replicated params live sharded on the data axis."""
    st_rep, losses_rep = _train_adamw(mesh8)
    st_zero, losses_zero = _train_adamw(mesh8, zero_axis="data")
    np.testing.assert_allclose(losses_zero, losses_rep, rtol=1e-5)
    mu = st_zero.opt_state[0].mu
    fc_spec = mu["fc"]["kernel"].sharding.spec
    assert fc_spec and fc_spec[0] == "data", fc_spec
    conv_spec = mu["conv1"]["kernel"].sharding.spec
    assert not conv_spec or conv_spec[0] is None, conv_spec
    _assert_params_equal(st_rep.params, st_zero.params)


def test_fsdp_axis_shards_params(mesh8):
    """FSDP (ZeRO-3) as specs: params themselves live sharded on the data
    axis, GSPMD all-gathers at use; training matches the replicated engine
    and both params and AdamW moments carry the dim-0 'data' sharding."""
    st_rep, losses_rep = _train_adamw(mesh8)
    st_fsdp, losses_fsdp = _train_adamw(mesh8, fsdp_axis="data")
    np.testing.assert_allclose(losses_fsdp, losses_rep, rtol=1e-5)
    fc = st_fsdp.params["fc"]["kernel"]
    assert fc.sharding.spec and fc.sharding.spec[0] == "data", fc.sharding
    mu = st_fsdp.opt_state[0].mu["fc"]["kernel"]
    assert mu.sharding.spec and mu.sharding.spec[0] == "data", mu.sharding
    # conv kernels (dim0=5, not divisible by 8) stay replicated
    ck = st_fsdp.params["conv1"]["kernel"].sharding.spec
    assert not ck or ck[0] is None, ck
    _assert_params_equal(st_rep.params, st_fsdp.params)
