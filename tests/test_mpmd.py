"""MPMD pipeline vs the SPMD baseline: bitwise parity, fault recovery,
and the disaggregated prefill/decode handoff.

The parity contract (see mpmd/program.py): trained *parameters* are
bitwise identical to PipelineParallel on a ``{'data': 1, 'pipe': S}``
mesh over >= 20 steps; the reported *loss* may differ by ~1 ulp on some
steps (XLA may regroup the CE-mean reduction across the two
compilations), so losses are compared to 1e-6. Recovery must land on the
SAME bits as the unfaulted run with every slot claimed exactly once per
generation — a microbatch applied twice or dropped shows up here, not in
a flaky convergence plot.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from tpu_sandbox.models.transformer import TransformerConfig
from tpu_sandbox.mpmd import MPMDPipeline
from tpu_sandbox.parallel.pipeline import PipelineParallel
from tpu_sandbox.runtime.mesh import make_mesh

CFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=4,
                        d_ff=64, max_len=64)
M = 4
STEPS = 21


def _batch():
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, size=(8, 16)).astype(np.int32)
    return tokens, ((tokens + 7) % 64).astype(np.int32)


def _assert_trees_bitwise(ref, got):
    bad = []

    def cmp(path, a, b):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            bad.append(jax.tree_util.keystr(path))

    jax.tree_util.tree_map_with_path(cmp, ref, got)
    assert not bad, f"{len(bad)} leaves differ, e.g. {bad[:4]}"


@pytest.fixture(scope="module")
def spmd_ref():
    """The SPMD pipeline baseline: initial flat params, trained params,
    per-step losses. Computed once; every parity test compares to it."""
    tokens, targets = _batch()
    tx = optax.adam(1e-2)
    mesh = make_mesh({"data": 1, "pipe": 2}, devices=jax.devices()[:2])
    pp = PipelineParallel(CFG, tx, mesh, microbatches=M, donate=False)
    state = pp.init_state(jax.random.key(0), jnp.asarray(tokens))
    flat = pp.merged_params(state)
    sstate = pp.shard_state(state)
    batch = pp.shard_batch(tokens, targets)
    losses = []
    for _ in range(STEPS):
        sstate, loss = pp.train_step(sstate, *batch)
        losses.append(float(loss))
    return {"flat": flat, "params": pp.merged_params(sstate),
            "losses": losses}


def test_mpmd_bitwise_parity_with_spmd(spmd_ref):
    """Two separate per-stage programs on two single-device meshes,
    activations/grads over the transport — same bits as the fused SPMD
    program after 21 adam steps."""
    tokens, targets = _batch()
    pipe = MPMDPipeline(CFG, optax.adam(1e-2), n_stages=2, microbatches=M,
                        devices=jax.devices()[2:4])
    pipe.init_from_flat(spmd_ref["flat"])
    losses = pipe.train(STEPS, tokens, targets)
    _assert_trees_bitwise(spmd_ref["params"], pipe.merged_params())
    np.testing.assert_allclose(losses, spmd_ref["losses"], rtol=0, atol=1e-6)
    # each stage ran its own program: the wire actually carried payloads
    s = pipe.transport.stats
    assert s.puts == s.gets > 0 and s.bytes_out == s.bytes_in > 0
    assert 0.0 < pipe.bubble_fraction() < 1.0
    # clean run: every slot claimed exactly once, all in generation 0
    claims = pipe.transport.audit()["claims"]
    assert claims and all(v == 1 for v in claims.values())


def test_mpmd_stage_kill_recovers_bitwise(spmd_ref, tmp_path):
    """Stage 1 dies mid-step (between two transport ops); the driver
    respawns it at generation 1, it restores its own checkpoint and
    replays from durable slots. End state: bitwise the unfaulted params,
    no microbatch lost or double-applied."""
    tokens, targets = _batch()
    pipe = MPMDPipeline(CFG, optax.adam(1e-2), n_stages=2, microbatches=M,
                        devices=jax.devices()[4:6], ckpt_root=str(tmp_path),
                        get_timeout=30.0)
    pipe.init_from_flat(spmd_ref["flat"])
    pipe.workers[1].fail_at = (7, 3)  # step 7, mid-schedule op
    losses = pipe.train(STEPS, tokens, targets, recover=True)
    _assert_trees_bitwise(spmd_ref["params"], pipe.merged_params())
    assert len(losses) == STEPS
    np.testing.assert_allclose(losses, spmd_ref["losses"], rtol=0, atol=1e-6)
    # the relaunch actually happened and replayed under a new generation
    assert pipe.workers[1].generation == 1
    # zero duplicate deliveries across BOTH generations
    claims = pipe.transport.audit()["claims"]
    dup = {k: v for k, v in claims.items() if v != 1}
    assert not dup, f"duplicate claims: {dup}"
    # every microbatch of every step applied exactly once per stage
    for w in pipe.workers:
        assert sorted(set(w.applied_steps)) == sorted(w.applied_steps)


def test_mpmd_leader_gc_releases_applied_slots(spmd_ref, tmp_path):
    """With checkpoints on, the driver advances a release watermark:
    slots for fully-applied steps are dropped from the wire."""
    tokens, targets = _batch()
    pipe = MPMDPipeline(CFG, optax.adam(1e-2), n_stages=2, microbatches=M,
                        devices=jax.devices()[6:8], ckpt_root=str(tmp_path))
    pipe.init_from_flat(spmd_ref["flat"])
    pipe.train(6, tokens, targets)
    assert pipe._released_through >= 0
    for step in range(pipe._released_through + 1):
        for mb in range(M):
            assert not pipe.transport.poll("act0", step, mb)
            assert not pipe.transport.poll("grad0", step, mb)


# -- disaggregated prefill/decode over the same transport ---------------------


@pytest.mark.parametrize("temperature,seed", [(0.0, 0), (0.8, 42)])
def test_disagg_tokens_identical_to_single_replica(temperature, seed):
    """Prefill on one replica, KV pages shipped over the stage transport,
    decode on another: the generated tokens are identical to a
    single-replica ContinuousEngine serving the same request."""
    from tpu_sandbox.mpmd.transport import LocalTransport
    from tpu_sandbox.serve.cache import CacheConfig
    from tpu_sandbox.serve.decode import build_decode_step
    from tpu_sandbox.serve.disagg import (DecodeReplica, DisaggRequest,
                                          PrefillReplica,
                                          serve_disaggregated)
    from tpu_sandbox.serve.engine import ContinuousEngine, Request, ServeConfig
    from tpu_sandbox.models.transformer import TransformerLM

    mcfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                             d_ff=64, max_len=128, dtype=jnp.float32)
    ccfg = CacheConfig(num_blocks=24, block_size=4, max_blocks_per_seq=8)
    params = TransformerLM(mcfg).init(jax.random.key(0),
                                      jnp.zeros((1, 8), jnp.int32))["params"]
    step = build_decode_step(mcfg, ccfg, max_batch=3, buckets=(8, 16))
    prompt = [5, 9, 3, 7, 11, 2]

    eng = ContinuousEngine(params, ServeConfig(model=mcfg, cache=ccfg,
                                               max_batch=3, buckets=(8, 16)),
                           step=step)
    eng.submit(Request(rid="a", prompt=list(prompt), max_new_tokens=9,
                       temperature=temperature, seed=seed))
    eng.run_until_idle()
    ref = eng.results["a"].tokens

    tr = LocalTransport()
    prefill = PrefillReplica(params, mcfg, ccfg, tr, step=step)
    decode = DecodeReplica(params, mcfg, ccfg, tr, step=step)
    req = DisaggRequest(rid="a", prompt=list(prompt), max_new_tokens=9,
                        temperature=temperature, seed=seed)
    out = serve_disaggregated(prefill, decode, req)
    assert out == ref
    assert tr.stats.bytes_out == tr.stats.bytes_in > 0
    # handoff is claim-once: a second decode of the same request in the
    # same generation is refused, a new generation (relaunched decode
    # replica) may replay it
    with pytest.raises(RuntimeError, match="already decoded"):
        decode.decode_from_handoff(req)
    prefill2 = PrefillReplica(params, mcfg, ccfg, tr, step=step)
    prefill2.prefill_and_ship(req)  # idempotent replay put
    decode2 = DecodeReplica(params, mcfg, ccfg, tr, step=step, generation=1)
    assert decode2.decode_from_handoff(req) == ref
