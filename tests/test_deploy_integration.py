"""Slow-tier deployment integration: real weights, real faults.

Everything test_deploy.py proves with stub engines is re-proven here with
real transformer weights flowing through the full artifact path — export,
seal, registry, controller verify, replica checksum-verified load — under
the two worst faults at once:

- the deploy controller is killed mid-rollout (after the begin record,
  lease left to lapse) and a successor completes the promotion with
  exactly one event per decision;
- the serving replica is killed mid-swap (command in the mailbox, never
  applied) with claimed work in flight; its respawn lands on the target
  version while the orphaned requests are scavenged and replayed
  **bitwise** on the version they pinned — compared against a one-shot
  forward reference, not against another engine run.

Plus the first closed-loop workload: generate -> train -> publish ->
promote, two generations, the distillation objective strictly improving
and each generation's requests served on that generation's weights.

Module name ends in _integration: conftest marks everything here slow.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from tpu_sandbox.deploy.controller import DeployConfig, DeployController
from tpu_sandbox.deploy.registry import (current_target, deploy_events,
                                         registry_versions)
from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
from tpu_sandbox.serve.cache import CacheConfig
from tpu_sandbox.serve.decode import build_decode_step
from tpu_sandbox.serve.engine import ContinuousEngine, ServeConfig
from tpu_sandbox.serve.replica import (ReplicaWorker, k_cmd, k_pin,
                                       read_load_reports, read_result,
                                       submit_request)
from tpu_sandbox.train.trainer import publish_checkpoint

MCFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_len=128, dtype=jnp.float32)
CCFG = CacheConfig(num_blocks=24, block_size=4, max_blocks_per_seq=8)
MAX_CTX = CCFG.max_context


@pytest.fixture(scope="module")
def model():
    return TransformerLM(MCFG)


@pytest.fixture(scope="module")
def step():
    return build_decode_step(MCFG, CCFG, max_batch=2, buckets=(8, 16))


@pytest.fixture(scope="module")
def fwd(model):
    return jax.jit(lambda params, toks: model.apply({"params": params}, toks))


@pytest.fixture
def kv_pair():
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    server = KVServer()
    kv = KVClient(port=server.port)
    clones = []

    def clone():
        c = kv.clone()
        clones.append(c)
        return c

    yield server, kv, clone
    for c in clones:
        c.close()
    kv.close()
    server.stop()


def _params(seed):
    return TransformerLM(MCFG).init(
        jax.random.key(seed), jnp.zeros((1, 8), jnp.int32))["params"]


def _engine(params, step):
    return ContinuousEngine(params, ServeConfig(
        model=MCFG, cache=CCFG, max_batch=2, buckets=(8, 16)), step=step)


def _worker(kv, params, step, **over):
    over.setdefault("lease_ttl", 0.4)
    over.setdefault("load_interval", 0.02)
    over.setdefault("publish_ts", False)
    # swap_loader stays None: swaps go through the real artifact path
    # (controller verify, then the replica's checksum-verified load)
    return ReplicaWorker(kv, _engine(params, step), tag="w0", **over)


def _controller(kv, member_id):
    return DeployController(
        kv, member_id=member_id, election_ttl=0.6,
        cfg=DeployConfig(swap_resend_s=0.05))


def _greedy(fwd, params, prompt, max_new):
    """One-shot-forward greedy continuation: the bitwise reference the
    paged serve path must reproduce exactly (test_serve.py's parity
    oracle, here used across a weight swap and a replica death)."""
    toks = list(prompt)
    out = []
    for _ in range(max_new):
        padded = np.zeros((1, MAX_CTX), np.int32)
        padded[0, :len(toks)] = toks
        logits = np.asarray(fwd(params, jnp.asarray(padded)))[0, len(toks) - 1]
        out.append(int(logits.argmax()))
        toks.append(out[-1])
    return out


def _drive(until, *actors, timeout=90.0, poll=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for a in actors:
            a.tick()
        if until():
            return
        time.sleep(poll)
    raise AssertionError("drive condition not reached in time")


def _actions(kv):
    return [e["action"] for e in deploy_events(kv)]


def test_rollout_survives_controller_and_replica_kills_bitwise(
        kv_pair, tmp_path, model, step, fwd):
    _, kv, clone = kv_pair
    params_v0 = _params(0)
    prompts = {f"r{i}": [1 + i, 2, 3, 4, 5] for i in range(3)}

    # the doomed replica claims real work on the boot weights (pins v0)
    dead = _worker(clone(), params_v0, step)
    for rid, prompt in prompts.items():
        submit_request(kv, rid, prompt, 4)
    _drive(lambda: dead.stats.claimed == 3, dead, timeout=60.0)
    assert all(kv.get(k_pin(r)) == b"0" for r in prompts)

    # a new version is published; controller A begins the rollout and
    # lands the swap command in the mailbox...
    params_v1 = _params(1)
    ver = publish_checkpoint(kv, params_v1, export_dir=tmp_path, step=1)
    a = _controller(clone(), "a")
    _drive(lambda: kv.try_get(k_cmd("w0")) is not None, a, timeout=60.0)
    assert _actions(kv) == ["published", "promote_begin"]
    # ...then BOTH die: A's lease lapses unreleased, the replica never
    # applies the command. Leases and the load report expire.
    del a
    time.sleep(0.8)
    assert read_load_reports(kv) == {}

    # successor controller + respawned replica finish the rollout
    respawn = _worker(clone(), _params(0), step)
    b = _controller(clone(), "b")
    _drive(lambda: current_target(kv) == ver
           and all(kv.try_get(f"serve/result/{r}") is not None
                   for r in prompts),
           respawn, b, timeout=120.0)

    # exactly-once: one begin, one verdict, one done — across two
    # controllers and a replica death
    assert _actions(kv) == ["published", "promote_begin", "canary_pass",
                            "promoted"]
    assert respawn.engine.version == ver
    # the orphaned requests replayed BITWISE on their pinned version:
    # token-identical to the v0 one-shot-forward reference, even though
    # the serving engine promoted to v1 mid-replay
    for rid, prompt in prompts.items():
        got = read_result(kv, rid)
        assert got["verdict"] == "ok" and got["ver"] == 0
        assert got["tokens"] == _greedy(fwd, params_v0, prompt, 4)
    # fresh traffic decodes on the promoted artifact, bitwise v1: the
    # round trip export -> seal -> verify -> load lost nothing
    submit_request(kv, "fresh", [9, 8, 7], 4)
    _drive(lambda: kv.try_get("serve/result/fresh") is not None,
           respawn, b, timeout=60.0)
    got = read_result(kv, "fresh")
    assert got["ver"] == ver
    assert got["tokens"] == _greedy(fwd, params_v1, [9, 8, 7], 4)
    b.resign()
    dead.engine.drain_to_requests()  # release the killed replica's engine


def test_generate_train_promote_improves_across_generations(
        kv_pair, tmp_path, model, step, fwd):
    """The closed loop: a teacher generates data, the student trains on
    it, the checkpoint publishes, the controller promotes, and the NEXT
    generation's data is served by the freshly promoted weights. The
    distillation objective must strictly improve across generations."""
    _, kv, clone = kv_pair
    teacher = _params(7)
    student = _params(0)
    opt = optax.adam(3e-3)
    opt_state = opt.init(student)
    rng = np.random.default_rng(0)
    eval_toks = jnp.asarray(rng.integers(0, MCFG.vocab_size, (8, 16)),
                            jnp.int32)

    @jax.jit
    def distill_loss(params, toks):
        t_logits = model.apply({"params": teacher}, toks)
        s_logits = model.apply({"params": params}, toks)
        t_prob = jax.nn.softmax(t_logits, -1)
        return -jnp.mean(jnp.sum(
            t_prob * jax.nn.log_softmax(s_logits, -1), -1))

    grad_fn = jax.jit(jax.value_and_grad(distill_loss))

    worker = _worker(clone(), _params(0), step)
    ctrl = _controller(clone(), "loop")
    losses = [float(distill_loss(student, eval_toks))]
    served_vers = []
    try:
        for gen in range(2):
            # generate -> train: fresh batches each generation
            for _ in range(30):
                batch = jnp.asarray(
                    rng.integers(0, MCFG.vocab_size, (8, 16)), jnp.int32)
                _, grads = grad_fn(student, batch)
                updates, opt_state = opt.update(grads, opt_state)
                student = optax.apply_updates(student, updates)
            losses.append(float(distill_loss(student, eval_toks)))
            # publish -> promote: the real rolling-update machinery
            ver = publish_checkpoint(kv, student, export_dir=tmp_path,
                                     step=gen + 1)
            _drive(lambda: current_target(kv) == ver, worker, ctrl,
                   timeout=120.0)
            # serve on the promoted weights, bitwise: the loop is closed
            rid = f"gen{gen}"
            submit_request(kv, rid, [3, 1, 4, 1, 5], 3)
            _drive(lambda: kv.try_get(f"serve/result/{rid}") is not None,
                   worker, ctrl, timeout=60.0)
            got = read_result(kv, rid)
            served_vers.append(got["ver"])
            assert got["ver"] == ver
            loaded = registry_versions(kv)[ver]
            assert got["tokens"] == _greedy(
                fwd, worker.engine._params_by_ver[ver], [3, 1, 4, 1, 5], 3)
            assert loaded["step"] == gen + 1
    finally:
        ctrl.resign()
    assert served_vers == [1, 2]
    # the objective strictly improves generation over generation
    assert losses[2] < losses[1] < losses[0]
