"""Checkpoint-restore hardening (orbax path): broken step directories are
quarantined and restore falls back to the newest *valid* step — the
on-disk damage an elastic supervisor's mid-save kills (or fault
injection's ``corrupt_ckpt``) leave behind."""

import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_sandbox.runtime.faults import corrupt_step_dir
from tpu_sandbox.train import TrainState
from tpu_sandbox.train import checkpoint as ckpt


def tiny_state(v: float = 0.0) -> TrainState:
    tx = optax.sgd(0.1)
    params = {"w": jnp.full((2, 3), v, jnp.float32)}
    return TrainState(
        step=jnp.asarray(0, jnp.int32),
        params=params,
        batch_stats={},
        opt_state=tx.init(params),
    )


def test_latest_step_survives_junk_entries(tmp_path):
    ckpt.save(tmp_path, tiny_state(), step=1)
    (tmp_path / "notes.txt").write_text("stray junk a killed worker left")
    (tmp_path / "tmp_dir").mkdir()
    assert ckpt.latest_step(tmp_path) == 1


def test_restore_quarantines_corrupt_step_and_falls_back(tmp_path):
    ckpt.save(tmp_path, tiny_state(1.0), step=1)
    ckpt.save(tmp_path, tiny_state(2.0), step=2)
    corrupt_step_dir(tmp_path / "2")

    restored = ckpt.restore(tmp_path, tiny_state())
    np.testing.assert_array_equal(
        np.asarray(restored.params["w"]), np.full((2, 3), 1.0, np.float32)
    )
    qdir = tmp_path.parent / (tmp_path.name + ".quarantine")
    assert (qdir / "2").exists(), "broken step must be moved aside, not lost"
    # the fallback is durable: a fresh restore now lands on step 1 directly
    assert ckpt.latest_step(tmp_path) == 1


def test_restore_raises_when_every_step_is_broken(tmp_path):
    ckpt.save(tmp_path, tiny_state(), step=1)
    corrupt_step_dir(tmp_path / "1")
    with pytest.raises(FileNotFoundError, match=r"no \*valid\* checkpoints"):
        ckpt.restore(tmp_path, tiny_state())


def test_restore_explicit_step_stays_strict(tmp_path):
    """Asking for a specific step must fail loud on corruption — silent
    fallback is only for the 'give me the newest' elastic-resume path."""
    ckpt.save(tmp_path, tiny_state(1.0), step=1)
    ckpt.save(tmp_path, tiny_state(2.0), step=2)
    corrupt_step_dir(tmp_path / "2")
    with pytest.raises(Exception):
        ckpt.restore(tmp_path, tiny_state(), step=2)
    # strict mode quarantined nothing
    assert not (tmp_path.parent / (tmp_path.name + ".quarantine")).exists()


def test_quarantine_step_is_race_tolerant(tmp_path):
    (tmp_path / "ck").mkdir()
    (tmp_path / "ck" / "5").mkdir()
    first = ckpt.quarantine_step(tmp_path / "ck", 5)
    assert first is not None and first.exists()
    # second quarantiner (another rank) lost the rename race: clean None
    assert ckpt.quarantine_step(tmp_path / "ck", 5) is None


def test_data_state_sidecar_roundtrip(tmp_path):
    ckpt.save_data_state(tmp_path, 7, epoch=1, offset=3, extra={"note": "x"})
    got = ckpt.load_data_state(tmp_path, 7)
    assert got == {"step": 7, "epoch": 1, "offset": 3, "note": "x"}
    assert ckpt.load_data_state(tmp_path, 99) is None  # missing: None
    # corrupt sidecar: None, caller derives the order from the step count
    (tmp_path / "data_state-7.json").write_text("{not json")
    assert ckpt.load_data_state(tmp_path, 7) is None


def test_sidecars_do_not_break_orbax_discovery(tmp_path):
    """Sidecar *files* must be invisible to orbax's step discovery and the
    layout guard — that's why they are files, not directories."""
    ckpt.save(tmp_path, tiny_state(1.0), step=1)
    ckpt.save_data_state(tmp_path, 1, epoch=0, offset=4)
    assert ckpt.latest_step(tmp_path) == 1
    restored = ckpt.restore(tmp_path, tiny_state())
    np.testing.assert_array_equal(
        np.asarray(restored.params["w"]), np.full((2, 3), 1.0, np.float32)
    )
