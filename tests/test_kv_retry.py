"""KV client edge cases around the elastic restart path: the server coming
up LATE (every relaunched worker races rank 0's listen()), the server dying
mid-conversation (rank 0 crashed while peers still hold connections), and
the TTL/prefix hygiene ops the sharded-checkpoint commit leans on."""

import threading
import time

import pytest

from tpu_sandbox.runtime.bootstrap import find_free_port
from tpu_sandbox.runtime.kvstore import KVClient, KVServer, _backoff_delays


# -- the backoff schedule itself -------------------------------------------


def test_backoff_delays_grow_exponentially_with_jitter():
    # list() never sleeps, so the generator busy-yields for the whole
    # wall-clock window — keep it short
    delays = list(_backoff_delays(0.2, base=0.02, cap=10.0))
    assert delays, "deadline should allow at least one retry"
    # every delay is its exponential envelope scaled by a factor in
    # [0.5, 1.5): never zero (no busy-spin), never a lockstep constant
    for i, d in enumerate(delays[:5]):
        envelope = 0.02 * (2 ** i)
        assert 0.5 * envelope <= d < 1.5 * envelope or d <= envelope, (
            i, d, envelope)
    assert all(d > 0 for d in delays)
    # jitter: a second schedule should not replay the first exactly
    again = list(_backoff_delays(0.2, base=0.02, cap=10.0))
    assert delays[:3] != again[:3]


def test_backoff_delays_respect_cap_and_deadline():
    t0 = time.monotonic()
    total = 0.0
    for d in _backoff_delays(0.4, base=0.05, cap=0.1):
        assert d <= 0.1 * 1.5 + 1e-9  # capped envelope x max jitter factor
        assert d <= 0.4 + 1e-9  # no single sleep overshoots the deadline
        total += d
        time.sleep(d)
    # the generator exhausts AT the deadline: the loop above slept through
    # ~the whole window and not multiples of it
    elapsed = time.monotonic() - t0
    assert 0.3 <= elapsed < 2.0, elapsed


def test_backoff_delays_zero_timeout_gives_up_immediately():
    assert list(_backoff_delays(0.0)) == []
    assert list(_backoff_delays(-1.0)) == []


def test_connect_retries_until_server_appears():
    port = int(find_free_port())
    started = {}

    def late_start():
        time.sleep(0.4)  # client spins on ECONNREFUSED meanwhile
        started["server"] = KVServer(port=port)

    t = threading.Thread(target=late_start)
    t.start()
    try:
        kv = KVClient(port=port, connect_timeout=10.0)
        kv.set("hello", b"world")
        assert kv.try_get("hello") == b"world"
        kv.close()
    finally:
        t.join()
        started["server"].stop()


def test_connect_timeout_is_bounded():
    port = int(find_free_port())  # nothing ever listens here
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="retried for"):
        KVClient(port=port, connect_timeout=0.5)
    # bounded: gave up near the deadline, not after hanging minutes
    assert time.monotonic() - t0 < 5.0


def test_server_death_mid_claim_raises_not_hangs():
    server = KVServer()
    kv = KVClient(port=server.port)
    kv.set("ckpt/g1/5/shard_done/1", b"claimed")
    server.stop()
    # the next request on the dead connection must fail loud (the caller —
    # a rank mid-commit — turns this into its own crash and the supervisor
    # restarts the generation); a silent hang would wedge the commit window
    with pytest.raises(RuntimeError):
        for _ in range(3):  # first call can still ride the closing socket
            kv.set("ckpt/g1/5/shard_done/0", b"claimed")
            time.sleep(0.05)
    kv.close()


def test_ttl_key_expires_and_plain_set_clears_ttl():
    with KVServer() as server:
        kv = KVClient(port=server.port)
        kv.set_ttl("claim/a", b"x", ttl=0.2)
        kv.set_ttl("claim/b", b"y", ttl=0.2)
        assert kv.try_get("claim/a") == b"x"
        kv.set("claim/b", b"y2")  # plain set = permanent: TTL dropped
        time.sleep(0.35)
        assert kv.try_get("claim/a") is None      # reaped
        assert kv.keys("claim/") == ["claim/b"]   # survivor
        assert kv.try_get("claim/b") == b"y2"
        with pytest.raises(ValueError):
            kv.set_ttl("claim/c", b"z", ttl=0)
        kv.close()


def test_keys_and_delete_prefix():
    with KVServer() as server:
        kv = KVClient(port=server.port)
        for k in ("ckpt/g1/5/shard_done/0", "ckpt/g1/5/shard_done/1",
                  "ckpt/g2/5/shard_done/0", "fault/0/claimed"):
            kv.set(k, b"1")
        assert kv.keys("ckpt/g1/") == [
            "ckpt/g1/5/shard_done/0", "ckpt/g1/5/shard_done/1",
        ]
        assert kv.delete_prefix("ckpt/g1/") == 2
        assert kv.keys("ckpt/") == ["ckpt/g2/5/shard_done/0"]
        assert kv.try_get("fault/0/claimed") == b"1"  # untouched namespace
        with pytest.raises(ValueError):
            kv.delete_prefix("")  # whole-store wipe must not be a typo away
        kv.close()

# -- read-retry (host-agent control plane) ---------------------------------
#
# Reads (get/try_get/keys) are idempotent, so the client retries them with
# jittered backoff and a fresh connection — an agent polling `elastic/
# generation` across a KV hiccup should see a blip, not a crash. Writes
# stay single-shot: a retried add() could double-claim a charge budget.


def test_read_survives_server_restart_on_same_port():
    port = int(find_free_port())
    first = KVServer(port=port)
    kv = KVClient(port=port)
    kv.set("elastic/generation", b"3")
    first.stop()  # connection now dead; next read must redial, not raise

    second = {}

    def restart():
        time.sleep(0.3)
        second["srv"] = KVServer(port=port)
        c = KVClient(port=port)
        c.set("elastic/generation", b"4")  # restarted store, new contents
        c.close()

    t = threading.Thread(target=restart)
    t.start()
    try:
        # the property under test: the read redials instead of raising.
        # The redial may legitimately land in the gap after the restarted
        # server is listening but before the helper's set() — poll through
        # that window rather than flake on scheduler timing.
        deadline = time.monotonic() + 10.0
        got = kv.try_get("elastic/generation")
        while got != b"4" and time.monotonic() < deadline:
            time.sleep(0.05)
            got = kv.try_get("elastic/generation")
        assert got == b"4"
        assert kv.keys("elastic/") == ["elastic/generation"]
    finally:
        t.join()
        second["srv"].stop()
        kv.close()


def test_read_retry_is_bounded_when_server_stays_dead():
    server = KVServer()
    kv = KVClient(port=server.port, connect_timeout=0.3)
    kv.set("k", b"v")
    server.stop()
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="kv"):
        for _ in range(3):  # first read can still drain the closing socket
            kv.try_get("k")
            time.sleep(0.05)
    # five attempts x short backoff x bounded reconnect — seconds, not forever
    assert time.monotonic() - t0 < 30.0
    kv.close()


def test_writes_do_not_retry_across_server_death():
    """add() is the election/charge primitive — replaying it after a
    reconnect could hand two agents the same claim. It must fail loud on
    the very path where reads quietly recover."""
    server = KVServer()
    kv = KVClient(port=server.port, connect_timeout=0.3)
    kv.set("budget/claim/1", b"0")
    server.stop()
    with pytest.raises(RuntimeError):
        for _ in range(3):
            kv.add("budget/claim/1", 1)
            time.sleep(0.05)
    kv.close()
