"""End-to-end smoke for the trace tooling over the committed fixture
trace dirs: tracecat (summary, per-request waterfall with critical-path
marks, profile export), tracediff gating, torn-log-tail resilience in
the collector, and the fleetop where-time-goes panel.

The CLI tests shell out with ``sys.executable`` — the tools are
scripts, not modules, and the test must exercise their argv surface and
exit codes exactly as a user would.
"""

import importlib.util
import json
import os
import shutil
import subprocess
import sys

import pytest

from tests.test_gateway import kv_pair  # noqa: F401 (fixture)
from tpu_sandbox.obs import critpath
from tpu_sandbox.obs.collect import (chain_check, load_dir, load_merged,
                                     read_log, request_waterfall)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures")
TRACE_SMALL = os.path.join(FIXTURES, "trace_small")
TRACE_SLOW = os.path.join(FIXTURES, "trace_slow")


def _run(tool, *argv):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", tool), *argv],
        capture_output=True, text=True, timeout=120)


# -- tracecat -----------------------------------------------------------------


def test_tracecat_summary():
    out = _run("tracecat.py", TRACE_SMALL)
    assert out.returncode == 0, out.stderr
    assert "3 process logs" in out.stdout
    assert "0 dropped lines" in out.stdout
    assert "7 traces, 7 fully connected" in out.stdout


def test_tracecat_waterfall_marks_critical_path():
    out = _run("tracecat.py", TRACE_SMALL, "--rid", "r01")
    assert out.returncode == 0, out.stderr
    lines = out.stdout.splitlines()
    decode = next(ln for ln in lines if " decode " in ln or
                  ln.rstrip().endswith("decode  [serve-rep0/300]"))
    prefill = next(ln for ln in lines if "prefill" in ln)
    assert "*" in decode
    assert "*" not in prefill  # refines admit, not on the causal spine
    crit = next(ln for ln in lines if "critical path (ok" in ln)
    assert "decode=" in crit and "coverage 100" in crit


def test_tracecat_waterfall_blames_shed_request():
    out = _run("tracecat.py", TRACE_SMALL, "--rid", "r06")
    assert out.returncode == 0, out.stderr
    assert "critical path (shed:capacity" in out.stdout
    assert "blame: queue_wait" in out.stdout


def test_tracecat_unknown_rid_exits_nonzero():
    out = _run("tracecat.py", TRACE_SMALL, "--rid", "nope")
    assert out.returncode == 1


def test_tracecat_critpath_profile_export(tmp_path):
    prof_path = str(tmp_path / "prof.json")
    out = _run("tracecat.py", TRACE_SMALL, "--critpath", prof_path)
    assert out.returncode == 0, out.stderr
    assert "critpath profile: 7 requests (6 ok)" in out.stdout
    prof = critpath.load_profile(prof_path)
    assert prof["schema"] == critpath.PROFILE_SCHEMA


# -- tracediff ----------------------------------------------------------------


def test_tracediff_gates_decode_slowdown():
    out = _run("tracediff.py", TRACE_SMALL, TRACE_SLOW)
    assert out.returncode == 1, out.stdout
    assert "REGRESSED" in out.stdout
    assert "1 regression(s): decode" in out.stdout


def test_tracediff_identical_run_is_clean():
    out = _run("tracediff.py", TRACE_SMALL, TRACE_SMALL)
    assert out.returncode == 0, out.stdout
    assert "0 regression(s)" in out.stdout


def test_tracediff_json_mode():
    out = _run("tracediff.py", TRACE_SMALL, TRACE_SLOW, "--json")
    assert out.returncode == 1
    cmp = json.loads(out.stdout)
    assert cmp["regressions"] == ["decode"]


def test_tracediff_bad_input_exits_2(tmp_path):
    missing = str(tmp_path / "nope.json")
    out = _run("tracediff.py", TRACE_SMALL, missing)
    assert out.returncode == 2
    bad = tmp_path / "bad_schema.json"
    bad.write_text('{"schema": "not-a-profile"}\n', encoding="utf-8")
    out = _run("tracediff.py", TRACE_SMALL, str(bad))
    assert out.returncode == 2
    assert "schema" in out.stderr


# -- torn log tails -----------------------------------------------------------


def _torn_copy(tmp_path, victim="gateway-200.jsonl", keep_lines=None,
               tear_at=None):
    """Copy the fixture dir, then truncate ``victim`` mid-way through a
    record line — what a SIGKILL'd process leaves behind."""
    torn = tmp_path / "torn"
    shutil.copytree(TRACE_SMALL, torn)
    path = torn / victim
    lines = path.read_text(encoding="utf-8").splitlines(keepends=True)
    if keep_lines is None:
        keep_lines = len(lines) - 1
    partial = lines[keep_lines][:len(lines[keep_lines]) // 2]
    path.write_text("".join(lines[:keep_lines]) + partial,
                    encoding="utf-8")
    return str(torn)


def test_read_log_counts_torn_tail_as_dropped(tmp_path):
    torn = _torn_copy(tmp_path, victim="serve-rep0-300.jsonl")
    stats = {}
    path = os.path.join(torn, "serve-rep0-300.jsonl")
    full = os.path.join(TRACE_SMALL, "serve-rep0-300.jsonl")
    recs = read_log(path, stats)
    assert stats["dropped_records"] == 1
    assert len(recs) == len(read_log(full, {})) - 1


def test_torn_gateway_tail_leaves_dangling_chain_without_crash(tmp_path):
    # tear the gateway log inside r06's route record: r06 keeps its
    # client submit and replica claim/shed, but claim's parent (the
    # enqueue span) never made it to disk
    torn = _torn_copy(tmp_path, victim="gateway-200.jsonl", keep_lines=13)
    stats = {}
    merged = load_merged(torn, stats)
    assert stats["dropped_records"] == 1
    from tpu_sandbox.obs.collect import trace_chains
    chains = trace_chains(merged)
    check = chain_check(chains["t06"])
    assert not check["connected"]
    assert check["dangling"] >= 1
    # attribution still works on the torn chain (truncated walk), and
    # the waterfall says WHY the row floated free
    req = critpath.attribute_request(chains["t06"])
    assert req is not None and req["outcome"] == "shed:capacity"
    rows = request_waterfall(merged, rid="r06")
    assert any(r["orphan"] for r in rows)
    out = _run("tracecat.py", torn, "--rid", "r06")
    assert out.returncode == 0, out.stderr
    assert "[orphan]" in out.stdout


def test_load_dir_stats_shape(tmp_path):
    stats = {}
    logs = load_dir(TRACE_SMALL, stats)
    assert stats["files"] == 3
    assert stats.get("dropped_records", 0) == 0
    assert set(logs) == {"client/100", "gateway/200", "serve-rep0/300"}


# -- fleetop panel ------------------------------------------------------------


def _load_fleetop():
    spec = importlib.util.spec_from_file_location(
        "fleetop_under_test", os.path.join(REPO, "tools", "fleetop.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_fleetop_where_time_goes_panel(kv_pair):
    from tpu_sandbox.obs.metrics import MetricsRegistry
    from tpu_sandbox.obs.record import Recorder
    from tpu_sandbox.obs.tsdb import TimeSeriesFlusher

    _, kv, _ = kv_pair
    fleetop = _load_fleetop()
    # nothing published yet -> no panel
    assert "where time goes:" not in fleetop.render(kv)

    prof = critpath.analyze(load_merged(TRACE_SMALL))["profile"]
    critpath.publish_profile(kv, prof)
    reg = MetricsRegistry()
    reg.gauge("mpmd.bubble_fraction", labels={"stage": "0"}).set(0.21)
    reg.gauge("mpmd.bubble_fraction", labels={"stage": "1"}).set(0.19)
    TimeSeriesFlusher(kv, proc="mpmd-test", registry=reg,
                      recorder=Recorder(None)).flush()

    out = fleetop.render(kv)
    assert "where time goes:" in out
    assert "decode" in out
    assert "attribution coverage 100.0%" in out
    assert "mpmd bubble: stage0=0.210  stage1=0.190" in out
