"""train_resumable: exact-data-order resume, preemption (SIGTERM →
save → Preempted), the non-finite guard, and the HostCheckpoint backend —
all single-process, all tier-1 fast.

The state here is a toy linear model (pure pytree), not the ConvNet: every
property under test lives in the loop/checkpoint machinery, and the toy
keeps each case sub-second.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tpu_sandbox.runtime.faults import FaultInjector, FaultPlan
from tpu_sandbox.runtime.kvstore import KVClient, KVServer
from tpu_sandbox.train.checkpoint import HostCheckpoint
from tpu_sandbox.train.trainer import (
    AbortOnAnomaly,
    Preempted,
    PreemptionHandler,
    train_resumable,
)


# -- toy model: w <- w - lr * grad(mse(w.x, y)) -----------------------------

def make_batches(n_batches=8, bs=4, dim=3, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(dim,)).astype(np.float32)
    batches = []
    for _ in range(n_batches):
        x = rng.normal(size=(bs, dim)).astype(np.float32)
        y = (x @ w_true).astype(np.float32)
        batches.append((x, y))
    return batches


class Loader:
    """Deterministic loader that records what it hands out, so tests can
    assert the exact global consumption order across crash+resume."""

    def __init__(self, batches, log=None):
        self.batches = batches
        self.log = log if log is not None else []

    def __len__(self):
        return len(self.batches)

    def __iter__(self):
        for i, b in enumerate(self.batches):
            self.log.append(i)
            yield b


@jax.jit
def sgd_step(state, x, y):
    def loss_fn(w):
        return jnp.mean((x @ w - y) ** 2)

    loss, g = jax.value_and_grad(loss_fn)(state["w"])
    return {"w": state["w"] - 0.05 * g}, loss


def fresh_state():
    return {"w": jnp.zeros(3, jnp.float32)}


def hc_fns(tmp_path):
    hc = HostCheckpoint(tmp_path)
    template = jax.tree.map(np.asarray, fresh_state())

    def save_fn(state, step, epoch, offset):
        hc.save(jax.tree.map(np.asarray, state), step,
                epoch=epoch, offset=offset)

    def restore_fn():
        res = hc.restore(template)
        if res is None:
            return None
        state, meta = res
        return jax.tree.map(jnp.asarray, state), meta

    return hc, save_fn, restore_fn


class PreemptAt:
    """Injector stub: flip the (programmatic) preemption flag at a step."""

    def __init__(self, handler, step):
        self.handler = handler
        self.step = step

    def maybe_fire(self, step):
        if step == self.step:
            self.handler.preempt_now()


def test_uninterrupted_run_applies_every_batch():
    batches = make_batches()
    state, report = train_resumable(
        sgd_step, fresh_state(), Loader(batches), 2, verbose=False
    )
    assert report.steps_applied == 2 * len(batches)
    assert report.final_step == 2 * len(batches)
    assert report.resumed_step is None and report.skipped_nonfinite == 0


def recording_step(batches, seq):
    """Wrap sgd_step to append the *applied* batch's index — the loader may
    fetch-and-skip during resume; only batches that reach the step count."""
    ids = {id(x): i for i, (x, _) in enumerate(batches)}

    def step(state, x, y):
        seq.append(ids[id(x)])
        return sgd_step(state, x, y)

    return step


@pytest.mark.parametrize("preempt_step", [3, 8, 11])
def test_preempt_resume_parity(tmp_path, preempt_step):
    """Kill-and-resume must equal the uninterrupted run: same final
    weights, every batch stepped exactly once, in the same order."""
    batches = make_batches()
    ref_seq = []
    ref_state, _ = train_resumable(
        recording_step(batches, ref_seq), fresh_state(), Loader(batches), 2,
        verbose=False,
    )

    _, save_fn, restore_fn = hc_fns(tmp_path)
    seq = []
    handler = PreemptionHandler()
    with pytest.raises(Preempted) as exc:
        train_resumable(
            recording_step(batches, seq), fresh_state(), Loader(batches), 2,
            save_fn=save_fn, restore_fn=restore_fn, ckpt_every=2,
            preemption=handler, injector=PreemptAt(handler, preempt_step),
            verbose=False,
        )
    assert exc.value.step == preempt_step
    assert len(seq) == preempt_step  # nothing stepped past the boundary

    # "restarted process": fresh loop, restore from disk
    state, report = train_resumable(
        recording_step(batches, seq), fresh_state(), Loader(batches), 2,
        save_fn=save_fn, restore_fn=restore_fn, ckpt_every=2,
        preemption=PreemptionHandler(), verbose=False,
    )
    assert report.resumed_step == preempt_step
    assert report.final_step == 2 * len(batches)
    assert report.steps_applied == 2 * len(batches) - preempt_step
    np.testing.assert_array_equal(
        np.asarray(state["w"]), np.asarray(ref_state["w"])
    )
    # no batch replayed, none skipped: crash+resume sequence == reference
    assert seq == ref_seq


def test_sigterm_via_fault_injector_saves_and_preempts(tmp_path):
    """The real signal path: a planned SIGTERM at step 3 → the handler
    flags it, the in-flight step finishes, the state is saved, Preempted
    escapes — and the checkpoint on disk is step 3's."""
    batches = make_batches()
    hc, save_fn, restore_fn = hc_fns(tmp_path)
    handler = PreemptionHandler().install()
    try:
        injector = FaultInjector(FaultPlan().add(0, 3, "sigterm"), 0)
        with pytest.raises(Preempted):
            train_resumable(
                sgd_step, fresh_state(), Loader(batches), 2,
                save_fn=save_fn, restore_fn=restore_fn, ckpt_every=100,
                preemption=handler, injector=injector, verbose=False,
            )
    finally:
        handler.uninstall()
    assert hc.latest_step() == 3
    _, meta = hc.restore(jax.tree.map(np.asarray, fresh_state()))
    assert (meta["step"], meta["epoch"], meta["offset"]) == (3, 0, 3)


def test_nonfinite_step_is_skipped_keeping_state():
    batches = make_batches(n_batches=6)
    poisoned = list(batches)
    x, y = poisoned[2]
    poisoned[2] = (x, np.full_like(y, np.nan))  # loss -> nan

    state, report = train_resumable(
        sgd_step, fresh_state(), Loader(poisoned), 1,
        max_bad_steps=3, verbose=False,
    )
    assert report.skipped_nonfinite == 1
    assert report.steps_applied == 5
    assert report.final_step == 5

    # the skipped batch must not have moved the weights: replaying only the
    # good batches reproduces the final state exactly
    clean_state, _ = train_resumable(
        sgd_step, fresh_state(),
        Loader([b for i, b in enumerate(poisoned) if i != 2]), 1,
        verbose=False,
    )
    np.testing.assert_array_equal(
        np.asarray(state["w"]), np.asarray(clean_state["w"])
    )


def test_nonfinite_streak_aborts():
    batches = make_batches(n_batches=6)
    bad = [(x, np.full_like(y, np.nan)) for x, y in batches]
    with pytest.raises(AbortOnAnomaly, match="3 consecutive"):
        train_resumable(
            sgd_step, fresh_state(), Loader(bad), 1,
            max_bad_steps=3, verbose=False,
        )


def test_preemption_propagates_through_kv():
    """Rank A receives the signal; rank B (never signaled) learns about it
    from the store and stops at the same boundary."""
    with KVServer() as srv:
        a = PreemptionHandler(KVClient(port=srv.port))
        b = PreemptionHandler(KVClient(port=srv.port))
        assert not b.requested()
        a.preempt_now()
        assert a.requested()  # announces to the store as a side effect
        assert b.requested()
        a.kv.close()
        b.kv.close()


def test_preemption_handler_signal_sets_flag_only():
    import os
    import signal

    handler = PreemptionHandler().install()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert handler.requested()
    finally:
        handler.uninstall()


# -- HostCheckpoint backend -------------------------------------------------

def _tree(v):
    return {
        "w": np.full((3, 2), v, np.float32),
        "nested": {"b": np.arange(4, dtype=np.int32) + int(v)},
    }


def test_host_checkpoint_roundtrip_and_prune(tmp_path):
    hc = HostCheckpoint(tmp_path, keep=2)
    for step in (1, 2, 3):
        hc.save(_tree(step), step, epoch=0, offset=step)
    assert hc.steps() == [2, 3]  # keep=2 pruned step 1
    state, meta = hc.restore(_tree(0))
    assert meta == {"step": 3, "epoch": 0, "offset": 3, "dtypes": {}}
    np.testing.assert_array_equal(state["w"], _tree(3)["w"])
    np.testing.assert_array_equal(state["nested"]["b"], _tree(3)["nested"]["b"])


def test_host_checkpoint_bf16_exact_roundtrip(tmp_path):
    hc = HostCheckpoint(tmp_path)
    state = {"p": np.asarray(jnp.arange(8, dtype=jnp.bfloat16) / 3)}
    hc.save(state, 1, epoch=0, offset=1)
    restored, meta = hc.restore({"p": np.zeros(8, state["p"].dtype)})
    assert restored["p"].dtype == state["p"].dtype
    np.testing.assert_array_equal(
        restored["p"].astype(np.float32), state["p"].astype(np.float32)
    )
    assert meta["dtypes"] == {"p": "bfloat16"}


def test_host_checkpoint_corrupt_falls_back(tmp_path, capsys):
    hc = HostCheckpoint(tmp_path)
    hc.save(_tree(1), 1, epoch=0, offset=1)
    hc.save(_tree(2), 2, epoch=0, offset=2)
    # scribble over the newest file (fault injection does exactly this)
    newest = sorted(tmp_path.glob("step-*.npz"))[-1]
    newest.write_bytes(b"\xde\xad not a zipfile")
    state, meta = hc.restore(_tree(0))
    assert meta["step"] == 1
    np.testing.assert_array_equal(state["w"], _tree(1)["w"])
    # broken file quarantined aside, not deleted
    assert list(tmp_path.glob("*.corrupt")), "corrupt file must be kept aside"
    assert "quarantined" in capsys.readouterr().out


def test_host_checkpoint_writes_sha256_sidecar(tmp_path):
    from tpu_sandbox.train.checkpoint import verify_npz_sidecar

    hc = HostCheckpoint(tmp_path, keep=2)
    for step in (1, 2, 3):
        hc.save(_tree(step), step, epoch=0, offset=step)
    # every kept step has a matching sidecar; pruned steps lost theirs
    assert sorted(p.name for p in tmp_path.glob("*.sha256")) == [
        "step-00000002.npz.sha256", "step-00000003.npz.sha256",
    ]
    for step in (2, 3):
        assert verify_npz_sidecar(tmp_path / f"step-{step:08d}.npz") is None


def test_host_checkpoint_sidecar_catches_valid_but_wrong_npz(tmp_path, capsys):
    """The nasty case 'does the zipfile parse' cannot see: the newest file
    is replaced by a perfectly LOADABLE npz with wrong content. The hash
    check must quarantine it (sidecar moved along) and fall back."""
    hc = HostCheckpoint(tmp_path)
    hc.save(_tree(1), 1, epoch=0, offset=1)
    hc.save(_tree(2), 2, epoch=0, offset=2)
    # forge step 2: valid npz, right schema, wrong params
    forged = _tree(99)
    hc_forge = HostCheckpoint(tmp_path / "forge")
    src = hc_forge.save(forged, 2, epoch=0, offset=2)
    (tmp_path / "step-00000002.npz").write_bytes(src.read_bytes())
    state, meta = hc.restore(_tree(0))
    assert meta["step"] == 1                       # fell back past the forgery
    np.testing.assert_array_equal(state["w"], _tree(1)["w"])
    assert "sha256 mismatch" in capsys.readouterr().out
    names = sorted(p.name for p in tmp_path.glob("*.corrupt"))
    assert names == ["step-00000002.npz.corrupt",
                     "step-00000002.npz.sha256.corrupt"]


def test_host_checkpoint_legacy_file_without_sidecar_restores(tmp_path):
    hc = HostCheckpoint(tmp_path)
    hc.save(_tree(5), 5, epoch=0, offset=5)
    (tmp_path / "step-00000005.npz.sha256").unlink()  # pre-integrity file
    state, meta = hc.restore(_tree(0))
    assert meta["step"] == 5
    np.testing.assert_array_equal(state["w"], _tree(5)["w"])


def test_host_checkpoint_empty_and_shape_mismatch(tmp_path):
    hc = HostCheckpoint(tmp_path)
    assert hc.restore(_tree(0)) is None  # fresh start
    hc.save(_tree(1), 1, epoch=0, offset=1)
    bad_template = {"w": np.zeros((9, 9), np.float32),
                    "nested": {"b": np.zeros(4, np.int32)}}
    # explicit step: strict fail-loud
    with pytest.raises(ValueError, match="shape"):
        hc.restore(bad_template, step=1)
