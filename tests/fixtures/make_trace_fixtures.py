"""Regenerate the committed trace-dir fixtures.

``trace_small/`` is a deterministic three-process recorder log set —
client, gateway, one replica — carrying six served requests plus one
queue-shed, every timestamp hand-placed so tests can assert exact
segment math. ``trace_slow/`` is its twin with decode modeled 30%
slower: the pair is the tracediff smoke fixture (small vs slow must
gate, small vs small must not).

    python tests/fixtures/make_trace_fixtures.py

Writes both directories next to this file. Commit the output; tests
read the files, they never run this.
"""

import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))

PROCS = {
    "client": 100,
    "gateway": 200,
    "serve-rep0": 300,
}

#: wall - mono offset all three processes share (one box, one clock)
WALL = 1000.0


def _build(decode_scale: float):
    logs = {proc: [{"ph": "P", "mono": 0.0, "wall": WALL,
                    "proc": proc, "pid": pid}]
            for proc, pid in PROCS.items()}
    counters = {proc: 0 for proc in PROCS}

    def emit(proc, ph, name, ts, dur, trace, parent, args):
        counters[proc] += 1
        span = f"{PROCS[proc]:x}.{counters[proc]}"
        rec = {"ph": ph, "name": name, "ts": round(ts, 6), "trace": trace,
               "span": span, "parent": parent, "args": args,
               "pid": PROCS[proc], "proc": proc, "tid": 0}
        if ph == "X":
            rec["dur"] = round(dur, 6)
        logs[proc].append(rec)
        return span

    for i in range(6):
        t0 = 0.050 * i
        rid = f"r{i:02d}"
        trace = f"t{i:02d}"
        # per-request deterministic jitter keeps the segment samples
        # distinct without disturbing the medians tests assert on
        j = 0.0002 * i
        decode_dur = (0.020 + 0.0004 * i) * decode_scale
        sub = emit("client", "X", "submit", t0, 0.0010, trace, None,
                   {"rid": rid})
        rt = emit("gateway", "X", "route", t0 + 0.0002, 0.0008, trace, sub,
                  {"rid": rid, "plen": 20 + i, "chain": "aa11",
                   "fleet": "default"})
        enq = emit("gateway", "X", "enqueue", t0 + 0.0010, 0.0002, trace, rt,
                   {"rid": rid})
        clm = emit("serve-rep0", "X", "claim", t0 + 0.0030 + j, 0.0005,
                   trace, enq, {"rid": rid})
        adm = emit("serve-rep0", "X", "admit", t0 + 0.0036 + j, 0.0040,
                   trace, clm, {"rid": rid})
        emit("serve-rep0", "X", "prefill", t0 + 0.0037 + j, 0.0038, trace,
             adm, {"rid": rid, "plen": 20 + i})
        t_dec = t0 + 0.0076 + j
        dec = emit("serve-rep0", "X", "decode", t_dec, decode_dur, trace,
                   adm, {"rid": rid, "tokens": 8 + i})
        t_pub = t_dec + decode_dur + 0.0002
        pub = emit("serve-rep0", "X", "publish", t_pub, 0.0006, trace, dec,
                   {"rid": rid})
        emit("serve-rep0", "i", "verdict", t_pub + 0.0007, 0.0, trace, pub,
             {"rid": rid, "verdict": "ok"})

    # one queue-shed: claimed late off a deep queue, shed at the engine
    # door — blame must land on queue_wait, the segment that ate it
    t0, rid, trace = 0.35, "r06", "t06"
    sub = emit("client", "X", "submit", t0, 0.0010, trace, None,
               {"rid": rid})
    rt = emit("gateway", "X", "route", t0 + 0.0002, 0.0008, trace, sub,
              {"rid": rid, "plen": 20, "chain": "aa11", "fleet": "default"})
    enq = emit("gateway", "X", "enqueue", t0 + 0.0010, 0.0002, trace, rt,
               {"rid": rid})
    clm = emit("serve-rep0", "X", "claim", t0 + 0.0210, 0.0005, trace, enq,
               {"rid": rid})
    emit("serve-rep0", "i", "shed:capacity", t0 + 0.0216, 0.0, trace, clm,
         {"rid": rid, "verdict": "SHED"})
    return logs


def write(dirname: str, decode_scale: float) -> None:
    out = os.path.join(HERE, dirname)
    os.makedirs(out, exist_ok=True)
    for proc, records in _build(decode_scale).items():
        path = os.path.join(out, f"{proc}-{PROCS[proc]}.jsonl")
        with open(path, "w", encoding="utf-8") as fh:
            for rec in records:
                fh.write(json.dumps(rec, sort_keys=True) + "\n")


if __name__ == "__main__":
    write("trace_small", 1.0)
    write("trace_slow", 1.3)
    print("wrote trace_small/ and trace_slow/")
