"""Seeded GL-R3xx violations — every pattern here must be FLAGGED.

Mirrors the control-plane idioms of ``runtime/`` with each guard removed.
Never imported; fed to ``analysis.control_pass.lint_source`` as text
(so the jax import below is only ever parsed, never executed).
"""

import threading
import time

import jax
from jax import lax


@jax.jit
def _sync_grads(g):
    return lax.psum(g, "data")  # collective: every dispatch is a rendezvous


def drain_microbatches(batches):  # GL-R305: per-iteration dispatch storm
    out = []
    for b in batches:
        out.append(_sync_grads(b))
    return out


def k_static_claim():
    return "budget/claim"  # helper with NO per-round discriminator


class BadAgent:
    def __init__(self, kv):
        self.kv = kv
        self.timeout = 10.0

    def charge_once(self):  # GL-R301: constant key claim
        return self.kv.add("budget/restart_claim", 1) == 1

    def charge_via_helper(self):  # GL-R301: unscoped key helper
        return self.kv.add(k_static_claim(), 1) == 1

    def peer_is_alive(self, rank):  # GL-R302: remote stamp vs local clock
        stamp = float(self.kv.get(f"hb/{rank}").decode())
        age = time.time() - stamp  # cross-host skew corrupts this
        return age < self.timeout

    def start_worker(self):  # GL-R303: non-daemon thread
        t = threading.Thread(target=self._run)
        t.start()
        return t

    def _run(self):
        pass

    def _leader_tick(self):
        self._resolve()

    def _resolve(self):  # GL-R304: blocking read in a leader section
        verdict = self.kv.get("gen/teardown")
        return verdict


class BadFrontend:
    def __init__(self):
        self.waiting = []

    def submit(self, request):  # GL-R306: no capacity check, no shed path
        self.waiting.append(request)
        return True


class _BaseResolver:
    """The blocking read lives on the base class; only the subclass's
    ``_leader*`` root makes it leader-reachable."""

    def __init__(self, kv):
        self.kv = kv

    def _lookup(self, key):
        return self.kv.get(key)  # blocking — lethal once a leader calls it


class BadLeaderSub(_BaseResolver):
    def _leader_sync(self):  # GL-R304: blocking read one base class away
        return self._lookup("gen/teardown")
