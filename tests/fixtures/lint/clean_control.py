"""Clean control-plane patterns — nothing here may be flagged.

The guarded twins of ``bad_control.py``: generation-scoped claims, the
skew-free change-token watchdog idiom, daemon threads, and non-blocking
reads inside leader sections.
"""

import threading
import time

import jax
import jax.numpy as jnp
from jax import lax


@jax.jit
def _sync_grads_scanned(gs):
    # the whole drain is ONE program: the loop lives inside the jit, so
    # there is a single dispatch (and a single rendezvous schedule)
    return lax.scan(lambda c, g: (c, lax.psum(g, "data")), None, gs)[1]


def drain_microbatches(batches):
    return _sync_grads_scanned(batches)


_local_norm = jax.jit(jnp.sum)  # single-device jit: loops over it are fine


def accumulate_norms(chunks):
    total = 0.0
    for c in chunks:  # no collective in the dispatched program
        total += float(_local_norm(c))
    return total


def k_gen_claim(gen):
    return f"budget/claim/{gen}"  # per-generation discriminator


class GoodAgent:
    def __init__(self, kv):
        self.kv = kv
        self.timeout = 10.0
        self._observed = {}

    def charge_once(self, gen):  # scoped literal key
        return self.kv.add(f"budget/claim/{gen}", 1) == 1

    def charge_via_helper(self, gen):  # scoped key helper
        return self.kv.add(k_gen_claim(gen), 1) == 1

    def peer_is_alive(self, rank):
        # skew-free: the remote stamp is an opaque change token; only the
        # LOCAL time since we saw it change is compared to the timeout
        now = time.time()
        raw = self.kv.try_get(f"hb/{rank}")
        if raw is None:
            return False
        prev = self._observed.get(rank)
        if prev is None or prev[0] != raw:
            self._observed[rank] = (raw, now)
            return True
        return (now - prev[1]) < self.timeout

    def start_worker(self):
        t = threading.Thread(target=self._run, daemon=True)
        t.start()
        return t

    def start_worker_late_daemon(self):
        t = threading.Thread(target=self._run)
        t.daemon = True  # set before start(): also accepted
        t.start()
        return t

    def _run(self):
        pass

    def _leader_tick(self):
        self._resolve()

    def _resolve(self):  # non-blocking read: re-observe next tick
        verdict = self.kv.try_get("gen/teardown")
        if verdict is None:
            return None
        return verdict

    def follower_wait(self):
        # blocking get() is FINE outside leader-reachable methods —
        # followers have no lease to lose
        return self.kv.get("gen/launch")


class BoundedFrontend:
    def __init__(self, limit):
        self.limit = limit
        self.waiting = []
        self.shed_log = []

    def submit(self, request):
        # bounded admission: capacity comparison + an explicit shed path,
        # so overload produces verdicts instead of memory growth
        if len(self.waiting) >= self.limit:
            self._record_shed(request)
            return False
        self.waiting.append(request)
        return True

    def submit_dropping_oldest(self, request):
        # the other clean spelling: no len() compare in this function,
        # but the drop call marks it as overload-aware
        self.drop_expired()
        self.waiting.append(request)

    def drop_expired(self):
        del self.waiting[: max(0, len(self.waiting) - self.limit)]

    def _record_shed(self, request):
        self.shed_log.append(request)

    def requeue(self, request):
        # appendleft is exempt: requeueing already-admitted work adds
        # nothing the bounded queue has not already accepted
        self.waiting.appendleft(request)


class _PatientBase:
    """Blocking ``_lookup`` is fine here: the leader subclass overrides
    it, and nothing leader-reachable ever calls THIS definition."""

    def __init__(self, kv):
        self.kv = kv

    def _lookup(self, key):
        return self.kv.get(key)

    def follower_fetch(self, key):
        return self._lookup(key)  # followers have no lease to lose


class GoodLeaderSub(_PatientBase):
    def _lookup(self, key):  # override wins: non-blocking under the lease
        return self.kv.try_get(key)

    def _leader_sync(self):
        return self._lookup("gen/teardown")
