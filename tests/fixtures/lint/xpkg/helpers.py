"""Collective-bearing helpers behind a PACKAGE path — the fixtures call
these through multi-dotted receivers (``xpkg.helpers.sync_all()``),
which CrossIndex resolves by longest import-alias prefix.

Clean on its own: every collective here runs unconditionally."""

from jax import lax


def sync_all(tree, axis):
    return lax.pmean(tree, axis)


def sync_step(tree, axis):
    # depth-2 chain: bearing must propagate locally before the dotted
    # receiver crosses the import edge
    return sync_all(tree, axis)


def plain_scale(tree, factor):
    # no collective anywhere below this: calls to it must never flag
    return {k: v * factor for k, v in tree.items()}
