"""Package shell for the multi-dotted-receiver fixtures: gives
``xpkg.helpers`` its dotted module name under the fixture root. Never
imported by the tests; only ever parsed."""
