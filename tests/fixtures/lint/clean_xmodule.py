"""The guarded twin of ``bad_xmodule.py`` — same imports, same helpers,
zero findings. Every rank collapses onto the same imported collective
sequence, and the jitted dispatch happens once, not per iteration.
"""

import jax

import xmodule_helper
from xmodule_helper import plain_scale, sync_all, sync_step


def all_ranks_sync(tree, rank, axis):
    tree = sync_all(tree, axis)  # unconditional: every rank participates
    if rank == 0:
        tree = plain_scale(tree, 1.0)  # rank-guarded but collective-free
    return tree


def all_ranks_module_attr(tree, axis):
    return xmodule_helper.sync_all(tree, axis)


def batched_imported_sync(batch, axis):
    stepper = jax.jit(sync_step)
    return stepper(batch, axis)  # one dispatch; the loop lives in-program
