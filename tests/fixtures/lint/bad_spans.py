"""Seeded GL-O403 violations — span names minted at runtime.

Parsed by the lint tests, never imported. Every function below
fragments trace aggregation: the critical-path analyzer, waterfalls,
and tracediff all key on the span name, and each of these mints one
name per request/value.
"""

from tpu_sandbox.obs import get_recorder


def fstring_no_family(rid):
    # one span name PER REQUEST — the rid belongs in args=, and an
    # f-string is only sanctioned with a static "family:" prefix
    with get_recorder().span(f"request_{rid}"):
        pass


def percent_minted(stage, t0):
    rec = get_recorder()
    rec.complete("stage_%d" % stage, t0)


def variable_name(event_name):
    recorder = get_recorder()
    recorder.instant(event_name, args={"src": "mailbox"})
