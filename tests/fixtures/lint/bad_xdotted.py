"""Seeded multi-dotted-receiver violations — every pattern here must be
FLAGGED when linted TOGETHER with ``xpkg/helpers.py``. Before the
longest-prefix receiver resolution, ``pkg.mod.fn()`` receivers were
opaque to CrossIndex and this whole file read clean — that asymmetry is
the regression this fixture pins.
"""

import xpkg.helpers
import xpkg as xp


def rank_branch_dotted_attr(tree, rank, axis):  # GL-C103
    if rank == 0:
        tree = xpkg.helpers.sync_all(tree, axis)  # pmean behind pkg.mod
    return tree


def rank_branch_alias_sub(tree, process_index, axis):  # GL-C103
    if process_index == 0:
        tree = xp.helpers.sync_all(tree, axis)  # alias + submodule hop
    return tree


def rank_exit_then_dotted_chain(tree, rank, axis):  # GL-C102
    if rank != 0:
        return tree  # other ranks bail...
    return xpkg.helpers.sync_step(tree, axis)  # ...depth-2 + dotted edge
