"""Seeded GL-C1xx violations — every pattern here must be FLAGGED.

Not imported anywhere; the lint fixture tests feed this file's source to
``analysis.collective_pass.lint_source`` and assert each rule fires.
"""

from jax import lax


def rank_branch_collective(grads, rank, axis):  # GL-C101
    if rank == 0:
        grads = lax.pmean(grads, axis)  # only rank 0 issues the pmean
    return grads


def rank_early_exit(grads, process_index, axis):  # GL-C102
    if process_index != 0:
        return grads  # other ranks bail before the collective below
    return lax.psum(grads, axis)


def _helper_syncs(tree, group):
    return group.all_reduce(tree)


def rank_branch_calls_helper(tree, group, coords):  # GL-C103
    if coords[0] == 0:
        tree = _helper_syncs(tree, group)  # helper bears the collective
    return tree


def rank_cond_lambda(x, axis):  # GL-C101 via lax.cond branches
    idx = lax.axis_index(axis)
    return lax.cond(
        idx == 0,
        lambda: lax.all_gather(x, axis),  # one branch gathers...
        lambda: x,                        # ...the other doesn't
    )


def rank_while_collective(x, local_rank, axis):  # GL-C101 (while form)
    while local_rank > 0:
        x = lax.ppermute(x, axis, [(0, 1)])
        local_rank -= 1
    return x


class _ShardSyncA:
    """Same-named methods as ShardSyncB below: the old bare-name table
    let this class's collective-free ``_sync`` answer for B's, hiding
    both of B's violations one method away."""

    def _sync(self, tree):
        return tree  # no collective in A's spelling

    def gated(self, tree, rank):
        return tree


class ShardSyncB:
    def _sync(self, tree):
        return lax.psum(tree, "data")  # B's _sync DOES bear a collective

    def maybe_sync(self, tree, rank):  # GL-C103: self-call one method away
        if rank == 0:
            tree = self._sync(tree)  # must resolve to ShardSyncB._sync
        return tree

    def gated(self, tree, rank):  # GL-C101 inside a name-shadowed method
        if rank == 0:
            tree = lax.pmean(tree, "data")
        return tree
