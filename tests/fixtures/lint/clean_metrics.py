"""Clean twins for GL-O402 — sanctioned metric-name shapes.

Static ``snake.dotted`` literals; bounded dimensions ride ``labels=``
instead of being baked into the name.
"""

from tpu_sandbox.obs import get_registry


def static_names(tenant):
    reg = get_registry()
    reg.counter("sched.admissions", labels={"kind": "admitted"}).inc()
    reg.gauge("sched.tenant.queued", labels={"tenant": tenant}).set(3)
    reg.histogram("engine.ttft").observe(0.12)


def keyword_name():
    get_registry().counter(name="gateway.shed.door").inc()


def non_registry_receiver(index):
    # instrument-shaped calls on non-registry objects are out of scope
    index.counter(f"dynamic.{index}").inc()
