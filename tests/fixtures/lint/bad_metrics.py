"""Seeded GL-O402 violations — dynamic / malformed metric names.

Parsed by the lint tests, never imported. Each function mints series
cardinality at runtime: the exact incident class the rule exists for
(one dashboard per tenant id, one alert rule that matches nothing).
"""

from tpu_sandbox.obs import get_registry


def fstring_name(tenant):
    # one counter series per tenant id — unbounded cardinality
    get_registry().counter(f"sched.tenant.{tenant}.queued").inc()


def concatenated_name(stage):
    reg = get_registry()
    reg.gauge("pipeline." + stage).set(1.0)


def undotted_name():
    registry = get_registry()
    # a static literal, but flat: no component prefix for rules to key on
    registry.histogram("latency").observe(0.5)
