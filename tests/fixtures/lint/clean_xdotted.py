"""The guarded twin of ``bad_xdotted.py`` — same imports, same dotted
receivers, zero findings. Every rank collapses onto the same dotted
collective, and the rank-guarded call reaches only a collective-free
function through the very same ``pkg.mod.fn`` shape.
"""

import xpkg.helpers
import xpkg as xp


def all_ranks_dotted_sync(tree, rank, axis):
    tree = xpkg.helpers.sync_all(tree, axis)  # unconditional
    if rank == 0:
        # rank-guarded but collective-free, through the dotted receiver:
        # resolution must prove absence too, not just presence
        tree = xpkg.helpers.plain_scale(tree, 1.0)
    return tree


def all_ranks_alias_sub(tree, axis):
    return xp.helpers.sync_step(tree, axis)
