"""Guarded twins for GL-O401: every sanctioned span shape, none of
which may trip the rule. Parsed by the linter, never imported."""

from tpu_sandbox.obs import get_recorder


def route_one(rid):
    pass


def with_block(rid):
    # the preferred spelling: closes on every path by construction
    rec = get_recorder()
    with rec.span("route", args={"rid": rid}) as sp:
        route_one(rid)
        return sp.ctx


def explicit_try_finally(rid):
    # begin_span is allowed when the try/finally follows immediately
    rec = get_recorder()
    sp = rec.begin_span("claim", args={"rid": rid})
    try:
        route_one(rid)
    finally:
        sp.close()


def retrospective(rid, t0):
    # complete() emits in one shot — it cannot leak
    rec = get_recorder()
    return rec.complete("decode", t0, args={"rid": rid})


def point_event(rid):
    rec = get_recorder()
    return rec.instant("verdict", args={"rid": rid})
