"""Clean twins for GL-O403 — sanctioned span-name shapes.

Static literals (colon families included), the sanctioned
``f"family:{value}"`` dynamic shape, and same-named methods on
non-recorder receivers, none of which may trip the rule.
"""

from tpu_sandbox.obs import get_recorder


def static_names(rid, t0):
    rec = get_recorder()
    with rec.span("route", args={"rid": rid}):
        pass
    rec.complete("swap:pause", t0, args={"ver": 3})
    rec.instant("lease:expired", args={"rid": rid})


def family_prefixed(reason, rid):
    # the one sanctioned dynamic shape: a static family prefix ending
    # in ':' — aggregation keys on "door", the reason set is bounded
    with get_recorder().span(f"door:{reason}", args={"rid": rid}):
        pass


def keyword_name(rid):
    get_recorder().instant(name="verdict", args={"rid": rid})


def non_recorder_receiver(checkpoint, step):
    # complete()-shaped calls on non-recorder objects are out of scope
    checkpoint.complete(f"step-{step}", step)
