"""Seeded cross-module violations — every pattern here must be FLAGGED
when linted TOGETHER with ``xmodule_helper.py`` (run_collective_pass /
run_control_pass over both files). Linted alone, the import targets are
unknowable and the file reads clean — that asymmetry is the regression
this fixture pins.
"""

import jax

import xmodule_helper
from xmodule_helper import sync_all, sync_step


def rank_branch_from_import(tree, rank, axis):  # GL-C103
    if rank == 0:
        tree = sync_all(tree, axis)  # pmean lives one import away
    return tree


def rank_branch_module_attr(tree, process_index, axis):  # GL-C103
    if process_index == 0:
        tree = xmodule_helper.sync_all(tree, axis)
    return tree


def rank_exit_then_imported_chain(tree, rank, axis):  # GL-C102
    if rank != 0:
        return tree  # other ranks bail...
    return sync_step(tree, axis)  # ...before a depth-2 imported collective


def drain_with_imported_sync(batches, axis):  # GL-R305
    stepper = jax.jit(sync_step)  # multi-device: body pmean is imported
    out = []
    for b in batches:
        out.append(stepper(b, axis))  # dispatch storm per iteration
    return out
