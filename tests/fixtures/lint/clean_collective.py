"""Clean collective patterns — nothing here may be flagged.

The fixture tests assert ``lint_source`` returns zero findings for this
file: every rank-conditional is collective-free and every collective is
reached by all ranks.
"""

from jax import lax


def unconditional_sync(grads, axis):
    return lax.pmean(grads, axis)  # every rank reaches this


def rank_branch_logging_only(loss, rank):
    if rank == 0:
        print(f"loss={loss}")  # side effects only; no collectives
    return loss


def collective_then_rank_branch(grads, rank, axis):
    grads = lax.psum(grads, axis)  # sync FIRST, uniformly
    if rank == 0:
        grads = grads * 1.0
    return grads


def rank_cond_no_collectives(x, axis):
    idx = lax.axis_index(axis)
    # branches diverge in VALUES, not in collective sequence — fine
    return lax.cond(idx == 0, lambda: x * 2.0, lambda: x)


def data_cond_collective(x, flag, axis):
    # the predicate is data-derived, not rank-derived: all ranks take the
    # same branch, so the gather stays collective-consistent
    if flag:
        x = lax.all_gather(x, axis)
    return x


def early_exit_before_any_collective(x, rank):
    if rank != 0:
        return x  # fine: no collective AFTER the divergent exit
    return x * 2.0


class _LoudSync:
    """Bearing ``_sync``, called unconditionally — clean. Defined FIRST
    so a bare-name any-match would wrongly answer for _QuietSync below."""

    def _sync(self, tree):
        return lax.psum(tree, "data")

    def sync(self, tree):
        return self._sync(tree)  # every rank reaches the psum


class _QuietSync:
    """Collective-free ``_sync``: the rank gate below must stay clean
    even though _LoudSync owns a bearing method of the same name."""

    def _sync(self, tree):
        return tree

    def maybe_sync(self, tree, rank):
        if rank == 0:
            tree = self._sync(tree)  # resolves to OUR _sync: no finding
        return tree
