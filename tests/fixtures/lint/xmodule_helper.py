"""Collective-bearing helpers the cross-module fixtures import.

Clean on its own: every collective here runs unconditionally. The
violations live in ``bad_xmodule.py``, which hides these calls behind an
``import`` — the hole xmodule.CrossIndex closes. Never imported by the
tests; only ever parsed.
"""

from jax import lax


def sync_all(tree, axis):
    return lax.pmean(tree, axis)


def sync_step(tree, axis):
    # depth-2 chain: bearing must propagate THROUGH this module before
    # crossing the import edge
    return sync_all(tree, axis)


def plain_scale(tree, factor):
    # no collective anywhere below this: calls to it must never flag
    return {k: v * factor for k, v in tree.items()}
