"""Seeded GL-O401 violations: spans begun outside the sanctioned
shapes. Each leaks its record from the merged timeline on some path.
Parsed by the linter, never imported."""

from tpu_sandbox.obs import get_recorder


def route_one(rid):
    pass


def happy_path_only(rid):
    # close() is reached only when route_one does not raise — the span
    # leaks on every error path
    rec = get_recorder()
    sp = rec.begin_span("route", args={"rid": rid})
    route_one(rid)
    sp.close()


def handle_discarded(rid):
    # nothing holds the span, so nothing can ever close it
    rec = get_recorder()
    rec.begin_span("enqueue", args={"rid": rid})
    route_one(rid)


def work_before_the_try(rid):
    # the try/finally is there, but route_one sits between the begin
    # and the try — an exception in it leaks the span
    rec = get_recorder()
    sp = rec.begin_span("claim", args={"rid": rid})
    route_one(rid)
    try:
        route_one(rid)
    finally:
        sp.close()
