#!/bin/sh
# Regenerate the committed TLS test fixtures. Long validity on purpose:
# these are test-only keys for 127.0.0.1, never trusted outside the suite.
set -e
cd "$(dirname "$0")"
days=36500
subj_ca="/CN=tpu-sandbox test CA"
subj_alt="/CN=tpu-sandbox WRONG CA"
ext="subjectAltName=DNS:localhost,IP:127.0.0.1"

openssl req -x509 -newkey rsa:2048 -nodes -keyout ca.key -out ca.pem \
    -days "$days" -subj "$subj_ca"
openssl req -newkey rsa:2048 -nodes -keyout server.key -out server.csr \
    -subj "/CN=localhost"
openssl x509 -req -in server.csr -CA ca.pem -CAkey ca.key \
    -CAcreateserial -out server.pem -days "$days" -extfile <(echo "$ext")

# a second, unrelated CA: the wrong-trust-root client test
openssl req -x509 -newkey rsa:2048 -nodes -keyout wrong_ca.key \
    -out wrong_ca.pem -days "$days" -subj "$subj_alt"
rm -f server.csr ca.srl
