"""ShardedCheckpoint unit tests — manifest, checksums, two-phase commit,
quarantine fall-through, pruning, and the verifier. All single-process and
fast (tier-1): the multi-rank protocol is exercised by constructing one
ShardedCheckpoint object per simulated rank against a shared directory,
which is exactly the on-disk/KV contract the real per-process ranks see.

Ordering rule for the single-threaded simulations: non-zero ranks save
FIRST (their save returns right after phase 1), rank 0 saves LAST — its
save blocks awaiting the others' claims before sealing.
"""

import json
import os

import numpy as np
import pytest

from tpu_sandbox.runtime.faults import corrupt_latest_shard
from tpu_sandbox.train.checkpoint import (
    MANIFEST_NAME,
    CheckpointVerifier,
    ShardedCheckpoint,
    _sha256_file,
    fold_per_replica,
    verify_step_dir,
)

WORLD = 2


def _tree(seed: int, world: int = WORLD):
    """(per-rank local trees, spec, global template) for a toy state:
    one replicated leaf, one ZeRO-style dim-0-sharded leaf, one
    per-replica BN-style leaf (leading axis 1 per rank)."""
    rng = np.random.default_rng(seed)
    w = rng.standard_normal((3, 4)).astype(np.float32)       # rep
    mom = rng.standard_normal((world * 2, 4)).astype(np.float32)  # shard0
    bn = rng.standard_normal((world, 5)).astype(np.float32)  # per-replica
    locals_ = [
        {"w": w, "mom": mom[r * 2:(r + 1) * 2], "bn": bn[r:r + 1]}
        for r in range(world)
    ]
    spec = {"w": "rep", "mom": "shard0", "bn": "shard0"}
    template = {"w": w, "mom": mom, "bn": bn[0]}  # unsharded, one replica
    return locals_, spec, template, {"w": w, "mom": mom, "bn": bn}


def _ckpts(directory, world: int = WORLD, **kw):
    kw.setdefault("commit_timeout", 5.0)
    return [
        ShardedCheckpoint(directory, rank=r, world_size=world,
                          verbose=False, **kw)
        for r in range(world)
    ]


def _save_all(cks, locals_, spec, step, *, epoch=0, offset=0):
    oks = []
    for ck, lt in list(zip(cks, locals_))[::-1]:  # rank 0 last: it seals
        oks.append(ck.save(lt, spec, step, epoch=epoch, offset=offset))
    return oks[::-1]


def test_round_trip_bitwise(tmp_path):
    locals_, spec, template, full = _tree(0)
    cks = _ckpts(tmp_path / "ck")
    oks = _save_all(cks, locals_, spec, 8, epoch=1, offset=3)
    assert oks == [True, True]
    for ck in cks:  # every rank restores the same bytes
        tree, meta = ck.restore(template)
        np.testing.assert_array_equal(tree["w"], full["w"])
        np.testing.assert_array_equal(tree["mom"], full["mom"])
        # per-replica leaf comes back EXPANDED (world, 5) for exact
        # per-rank placement at unchanged world size
        np.testing.assert_array_equal(tree["bn"], full["bn"])
        assert (meta["step"], meta["epoch"], meta["offset"]) == (8, 1, 3)
        assert meta["world_size"] == WORLD


def test_manifest_contents_and_checksums(tmp_path):
    locals_, spec, template, _ = _tree(1)
    cks = _ckpts(tmp_path / "ck")
    _save_all(cks, locals_, spec, 2)
    sd = cks[0].step_dir(2)
    manifest = json.loads((sd / MANIFEST_NAME).read_text())
    assert manifest["format"].startswith("tpu-sandbox-sharded-ckpt")
    assert manifest["world_size"] == WORLD
    assert [s["rank"] for s in manifest["shards"]] == [0, 1]
    for sh in manifest["shards"]:
        f = sd / sh["file"]
        assert _sha256_file(f) == sh["sha256"]
        assert f.stat().st_size == sh["bytes"]
    assert verify_step_dir(sd) == []
    # replicated leaves live in rank 0's shard only
    with np.load(sd / "shard-00001.npz") as z:
        assert "leaf:w" not in z.files and "leaf:mom" in z.files


def test_torn_step_falls_back_and_quarantines(tmp_path):
    locals_, spec, template, full = _tree(2)
    cks = _ckpts(tmp_path / "ck")
    _save_all(cks, locals_, spec, 1)
    # newer step: shards written, manifest never sealed (kill in the window)
    for ck, lt in list(zip(cks, locals_))[1:]:
        ck.save(lt, spec, 5, epoch=0, offset=0)
    sd5 = cks[0].step_dir(5)
    (sd5 / "shard-00000.npz").write_bytes(b"half a shard")
    assert not (sd5 / MANIFEST_NAME).exists()
    tree, meta = cks[1].restore(template)
    assert meta["step"] == 1
    np.testing.assert_array_equal(tree["mom"], full["mom"])
    assert not sd5.exists()  # quarantined out of the fallback chain
    q = tmp_path / "ck.quarantine"
    assert (q / sd5.name).is_dir()


def test_corrupt_shard_detected_by_checksum(tmp_path):
    locals_, spec, template, full = _tree(3)
    cks = _ckpts(tmp_path / "ck")
    _save_all(cks, locals_, spec, 1)
    _save_all(cks, locals_, spec, 2)
    hit = corrupt_latest_shard(tmp_path / "ck", rank=1)
    assert hit is not None and hit.name == "shard-00001.npz"
    sd2 = cks[0].step_dir(2)
    assert (sd2 / MANIFEST_NAME).exists()  # still LOOKS sealed
    assert any(p.startswith("corrupt:") for p in verify_step_dir(sd2))
    tree, meta = cks[0].restore(template)
    assert meta["step"] == 1  # fell back past the lying step
    np.testing.assert_array_equal(tree["w"], full["w"])
    assert not sd2.exists()


def test_explicit_step_is_strict(tmp_path):
    locals_, spec, template, _ = _tree(4)
    cks = _ckpts(tmp_path / "ck")
    _save_all(cks, locals_, spec, 1)
    corrupt_latest_shard(tmp_path / "ck", rank=0)
    with pytest.raises(ValueError, match="failed verification"):
        cks[0].restore(template, step=1)
    # strict mode quarantines nothing — the evidence stays in place
    assert cks[0].step_dir(1).exists()


def test_commit_timeout_leaves_step_unsealed(tmp_path):
    locals_, spec, _, _ = _tree(5)
    ck0 = ShardedCheckpoint(tmp_path / "ck", rank=0, world_size=WORLD,
                            commit_timeout=0.3, verbose=False)
    ok = ck0.save(locals_[0], spec, 7, epoch=0, offset=0)  # rank 1 never shows
    assert ok is False
    assert not (ck0.step_dir(7) / MANIFEST_NAME).exists()
    assert ck0.latest_sealed_step() is None


def test_commit_hook_phases(tmp_path):
    locals_, spec, _, _ = _tree(6)
    cks = _ckpts(tmp_path / "ck")
    seen = {0: [], 1: []}
    cks[1].save(locals_[1], spec, 3, epoch=0, offset=0,
                commit_hook=seen[1].append)
    cks[0].save(locals_[0], spec, 3, epoch=0, offset=0,
                commit_hook=seen[0].append)
    assert seen[1] == ["claimed"]          # non-zero ranks never seal
    assert seen[0] == ["claimed", "sealing"]


def test_prune_keeps_sealed_window_quarantines_old_torn(tmp_path):
    locals_, spec, _, _ = _tree(7)
    cks = _ckpts(tmp_path / "ck", keep=2)
    _save_all(cks, locals_, spec, 1)
    # an old torn step between sealed ones: must survive as evidence
    torn = cks[0].step_dir(2)
    torn.mkdir()
    (torn / "shard-00000.npz").write_bytes(b"debris")
    _save_all(cks, locals_, spec, 3)
    _save_all(cks, locals_, spec, 4)  # prune triggers: sealed {1,3,4}, keep 2
    assert cks[0].sealed_steps() == [3, 4]
    assert not cks[0].step_dir(1).exists()      # old sealed: deleted
    assert not torn.exists()                    # old torn: moved, not deleted
    assert (tmp_path / "ck.quarantine" / torn.name).is_dir()


def test_fold_per_replica_and_reshard(tmp_path):
    world = 4
    locals_, spec, template, full = _tree(8, world=world)
    cks = _ckpts(tmp_path / "ck", world=world)
    _save_all(cks, locals_, spec, 1)
    tree, meta = cks[0].restore(template)
    assert tree["bn"].shape == (world, 5)   # expanded per-replica
    folded = fold_per_replica(tree, template)
    np.testing.assert_array_equal(folded["bn"], full["bn"][0])
    assert folded["mom"].shape == template["mom"].shape
    # the reassembled tree is the full GLOBAL value — a new world size just
    # re-slices it downstream; nothing in the file format is world-bound
    np.testing.assert_array_equal(
        np.concatenate([locals_[r]["mom"] for r in range(world)], 0),
        tree["mom"],
    )


def test_restore_partial_bitwise_slice_of_full(tmp_path):
    """Partial restore (this rank's shard + rank 0's) returns bitwise the
    same values a full reassembly would slice out for this rank."""
    world = 4
    locals_, spec, template, _ = _tree(20, world=world)
    cks = _ckpts(tmp_path / "ck", world=world)
    _save_all(cks, locals_, spec, 5, epoch=2, offset=9)
    ftree, _ = cks[0].restore(template)
    for r, ck in enumerate(cks):
        tree, meta = ck.restore_partial(template)
        np.testing.assert_array_equal(tree["w"], ftree["w"])
        # sharded leaves come back as THIS RANK's block, i.e. the rank-r
        # slice of the full reassembly — and bitwise what rank r saved
        np.testing.assert_array_equal(
            tree["mom"], ftree["mom"][r * 2:(r + 1) * 2])
        np.testing.assert_array_equal(tree["mom"], locals_[r]["mom"])
        np.testing.assert_array_equal(tree["bn"], ftree["bn"][r:r + 1])
        assert (meta["step"], meta["epoch"], meta["offset"]) == (5, 2, 9)
        assert meta["world_size"] == world


def test_restore_partial_world_change_and_strictness(tmp_path):
    locals_, spec, template, _ = _tree(21)
    cks = _ckpts(tmp_path / "ck")
    _save_all(cks, locals_, spec, 3)
    # a changed world size must refuse: resharding is restore()'s job
    grown = ShardedCheckpoint(tmp_path / "ck", rank=0,
                              world_size=WORLD + 1, verbose=False)
    with pytest.raises(ValueError, match="unchanged world size"):
        grown.restore_partial(template, step=3)
    # only the shards actually read are hashed: rank 0 never touches
    # rank 1's rotten file, rank 1 fails loud on it
    corrupt_latest_shard(tmp_path / "ck", rank=1)
    tree, meta = cks[0].restore_partial(template)
    assert meta["step"] == 3
    np.testing.assert_array_equal(tree["mom"], locals_[0]["mom"])
    with pytest.raises(ValueError, match="shard 1"):
        cks[1].restore_partial(template)
    assert cks[1].step_dir(3).exists()  # strict: nothing quarantined
    empty = ShardedCheckpoint(tmp_path / "none", rank=0,
                              world_size=WORLD, verbose=False)
    assert empty.restore_partial(template) is None


def test_verifier_scan_quarantines_bitrot(tmp_path):
    locals_, spec, template, _ = _tree(9)
    cks = _ckpts(tmp_path / "ck")
    _save_all(cks, locals_, spec, 1)
    _save_all(cks, locals_, spec, 2)
    v = CheckpointVerifier(cks[0], interval=3600)
    assert v.scan_once() == []              # clean sweep
    corrupt_latest_shard(tmp_path / "ck", rank=0)
    assert v.scan_once() == [2]             # rotted step pulled from chain
    assert v.corrupt_found == [2]
    assert cks[0].sealed_steps() == [1]


def test_kv_backed_claims_and_cleanup(tmp_path):
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    locals_, spec, template, full = _tree(10)
    with KVServer() as server:
        kv = KVClient(port=server.port)
        cks = _ckpts(tmp_path / "ck", kv=kv, generation=2)
        cks[1].save(locals_[1], spec, 6, epoch=0, offset=0)
        assert kv.keys("ckpt/g2/6/shard_done/") == ["ckpt/g2/6/shard_done/1"]
        cks[0].save(locals_[0], spec, 6, epoch=0, offset=0)
        # sealed: claim keys for the step are swept, not left to the TTL
        assert kv.keys("ckpt/g2/") == []
        tree, meta = cks[0].restore(template)
        assert meta["step"] == 6
        np.testing.assert_array_equal(tree["mom"], full["mom"])


def test_unknown_spec_kind_rejected(tmp_path):
    ck = ShardedCheckpoint(tmp_path / "ck", rank=0, world_size=1,
                           verbose=False)
    with pytest.raises(ValueError, match="unknown spec kind"):
        ck.save({"w": np.zeros(2)}, {"w": "diagonal"}, 0, epoch=0, offset=0)


def test_host_npz_coexists_with_step_dirs(tmp_path):
    """HostCheckpoint npz files and sharded step dirs in one directory must
    not confuse each other's discovery (files vs dirs)."""
    from tpu_sandbox.train.checkpoint import HostCheckpoint

    locals_, spec, template, _ = _tree(11)
    hc = HostCheckpoint(tmp_path / "ck")
    hc.save(template, 4, epoch=0, offset=0)
    cks = _ckpts(tmp_path / "ck")
    _save_all(cks, locals_, spec, 9)
    assert cks[0].sealed_steps() == [9]
    assert hc.steps() == [4]


def test_verify_ckpt_cli(tmp_path, capsys):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    from tools.verify_ckpt import main

    locals_, spec, _, _ = _tree(12)
    cks = _ckpts(tmp_path / "ck")
    _save_all(cks, locals_, spec, 1)
    _save_all(cks, locals_, spec, 2)
    assert main([str(tmp_path / "ck")]) == 0
    out = capsys.readouterr().out
    assert "2 sealed" in out and "0 corrupt" in out

    # torn step: reported, but only --strict fails on it
    torn = cks[0].step_dir(3)
    torn.mkdir()
    (torn / "shard-00000.npz").write_bytes(b"debris")
    assert main([str(tmp_path / "ck")]) == 0
    assert main([str(tmp_path / "ck"), "--strict"]) == 1

    corrupt_latest_shard(tmp_path / "ck", rank=1)
    assert main([str(tmp_path / "ck")]) == 1
    assert "CORRUPT" in capsys.readouterr().out
    assert main([str(tmp_path / "missing")]) == 2


def test_verify_ckpt_cli_audits_host_npz(tmp_path, capsys):
    """HostCheckpoint npz files in the directory are audited too: sidecar
    hash first, then an actual load; no sidecar is a note, not a failure."""
    import sys

    from tpu_sandbox.train.checkpoint import HostCheckpoint

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir))
    from tools.verify_ckpt import main

    hc = HostCheckpoint(tmp_path / "ck", keep=3)
    hc.save({"w": np.arange(6.0)}, 4, epoch=0, offset=4)
    hc.save({"w": np.arange(6.0)}, 8, epoch=0, offset=8)
    assert main([str(tmp_path / "ck")]) == 0
    assert "sha256 verified" in capsys.readouterr().out

    # legacy file (no sidecar): noted, still exit 0
    (tmp_path / "ck" / "step-00000004.npz.sha256").unlink()
    assert main([str(tmp_path / "ck")]) == 0
    assert "no sidecar (unverified)" in capsys.readouterr().out

    # loadable forgery: only the hash can tell -> exit 1
    np.savez(tmp_path / "ck" / "step-00000008.npz",
             **{"__meta__": np.array("{}")})
    assert main([str(tmp_path / "ck")]) == 1
    assert "sha256 mismatch" in capsys.readouterr().out

    # truncated legacy file: the load check catches it -> exit 1
    (tmp_path / "ck" / "step-00000004.npz").write_bytes(b"debris")
    assert main([str(tmp_path / "ck"), "-q"]) == 1
    assert "does not load" in capsys.readouterr().out


def test_compressed_shards_round_trip(tmp_path):
    """compress=True writes zlib-deflated npz shards: restore is bitwise
    (np.load inflates transparently; checksums are over the bytes on
    disk either way), and the manifest records both sizes so operators
    can see the ratio. Compressible data (zeros-heavy) must actually
    shrink on disk."""
    rng = np.random.default_rng(7)
    w = np.zeros((64, 256), np.float32)
    w[::8] = rng.standard_normal((8, 256))  # 1/8 dense: deflate wins big
    mom = np.zeros((WORLD * 32, 16), np.float32)
    locals_ = [{"w": w, "mom": mom[r * 32:(r + 1) * 32]}
               for r in range(WORLD)]
    spec = {"w": "rep", "mom": "shard0"}
    template = {"w": w, "mom": mom}

    cks = _ckpts(tmp_path / "ck", compress=True)
    assert _save_all(cks, locals_, spec, 4) == [True, True]
    tree, meta = cks[1].restore(template)
    np.testing.assert_array_equal(tree["w"], w)
    np.testing.assert_array_equal(tree["mom"], mom)

    manifest = json.loads(
        (cks[0].step_dir(4) / MANIFEST_NAME).read_text())
    for sh in manifest["shards"]:
        assert sh["compressed"] is True
        assert sh["bytes"] < sh["raw_bytes"], sh
        # the checksum covers the COMPRESSED bytes on disk
        assert _sha256_file(cks[0].step_dir(4) / sh["file"]) == sh["sha256"]
    assert verify_step_dir(cks[0].step_dir(4)) == []

    # uncompressed shards record compressed=False and bytes ~ raw_bytes
    cks_plain = _ckpts(tmp_path / "ck_plain")
    _save_all(cks_plain, locals_, spec, 4)
    plain = json.loads(
        (cks_plain[0].step_dir(4) / MANIFEST_NAME).read_text())
    for sh in plain["shards"]:
        assert sh["compressed"] is False
        assert sh["bytes"] >= sh["raw_bytes"]  # npz container overhead
