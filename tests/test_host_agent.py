"""Fast cross-host elastic tests: HostAgents as in-process threads over one
KVServer, ranks as tiny ``python -c`` subprocesses. Covers the control
plane end to end (election → launch → report → resolve → relaunch →
verdict) without the jax-importing workers of the slow
test_multihost_elastic_integration module."""

import json
import subprocess
import sys
import threading
import time

import pytest

from tpu_sandbox.runtime.faults import (
    Fault,
    FaultInjector,
    FaultPlan,
    agent_cmd_key,
)
from tpu_sandbox.runtime.host_agent import (
    AgentConfig,
    AgentLauncher,
    HostAgent,
    K_GENERATION,
    K_JOB_DONE,
    K_PREEMPTIONS,
    K_RESTARTS,
    assign_ranks,
    ranks_for_agent,
)
from tpu_sandbox.runtime.kvstore import KVClient, KVServer
from tpu_sandbox.runtime.supervisor import PREEMPTED_EXIT_CODE, RankGroup

PY = sys.executable


# -- pure helpers ----------------------------------------------------------

def test_ranks_for_agent_contiguous_blocks():
    assert ranks_for_agent(0, 2, 4) == [0, 1]
    assert ranks_for_agent(1, 2, 4) == [2, 3]
    assert ranks_for_agent(2, 3, 3) == [2]


def test_assign_ranks_heterogeneous():
    # uneven worlds split into balanced contiguous blocks, extras first
    assert assign_ranks(3, 2) == [[0, 1], [2]]
    assert assign_ranks(7, 3) == [[0, 1, 2], [3, 4], [5, 6]]
    assert assign_ranks(4, 4) == [[0], [1], [2], [3]]
    # every world size covers exactly ranks 0..world-1, in order
    for world in range(1, 12):
        for agents in range(1, world + 1):
            flat = [r for b in assign_ranks(world, agents) for r in b]
            assert flat == list(range(world))
    # an over-provisioned gang is an admission-time error, never idle hosts
    with pytest.raises(ValueError, match="at least one rank"):
        assign_ranks(2, 3)
    with pytest.raises(ValueError, match="num_agents"):
        assign_ranks(4, 0)


# -- RankGroup -------------------------------------------------------------

def test_rank_group_spawn_poll_teardown():
    g = RankGroup(term_timeout=5.0)
    g.spawn([[PY, "-c", "import sys; sys.exit(3)"],
             [PY, "-c", "import sys; sys.exit(0)"]], None)
    assert len(g) == 2
    deadline = time.monotonic() + 10
    while g.running and time.monotonic() < deadline:
        time.sleep(0.02)
    assert g.poll() == [3, 0]
    assert g.teardown() == [3, 0]  # idempotent on dead groups


def test_rank_group_refuses_overlapping_spawn():
    g = RankGroup(term_timeout=5.0)
    g.spawn([[PY, "-c", "import time; time.sleep(60)"]], None)
    with pytest.raises(RuntimeError, match="previous group"):
        g.spawn([[PY, "-c", "pass"]], None)
    codes = g.teardown()
    assert codes[0] is not None  # SIGTERM'd, not still running
    g.spawn([[PY, "-c", "pass"]], None)  # after teardown: allowed
    g.teardown()


# -- fault routing to the agent mailbox ------------------------------------

def test_agent_fault_posts_to_mailbox():
    with KVServer() as srv:
        kv = KVClient(port=srv.port)
        plan = FaultPlan([Fault(rank=1, step=2, action="kill_agent")])
        inj = FaultInjector(plan, rank=1, kv=kv, agent_id=7)
        assert inj.maybe_fire(step=1) == []
        fired = inj.maybe_fire(step=2)
        assert [f.action for f in fired] == ["kill_agent"]
        cmd = json.loads(kv.get(agent_cmd_key(7)))
        assert cmd == {"action": "kill_agent", "arg": None}
        # claimed globally: a relaunched rank replaying step 2 won't re-fire
        inj2 = FaultInjector(plan, rank=1, kv=kv, agent_id=7)
        assert inj2.maybe_fire(step=2) == []
        kv.close()


def test_agent_fault_without_agent_context_fails_loud():
    plan = FaultPlan([Fault(rank=0, step=1, action="partition_host",
                            target="2.5")])
    inj = FaultInjector(plan, rank=0, kv=None, agent_id=None)
    with pytest.raises(RuntimeError, match="agent-mode"):
        inj.maybe_fire(step=1)


def test_partition_duration_validated():
    with pytest.raises(ValueError, match="duration"):
        Fault(rank=0, step=1, action="partition_host", target="soon")


# -- the agent/leader state machine (threads + subprocess ranks) -----------

def _cfg(aid, *, num_agents=2, world=2, port=0, **kw):
    kw.setdefault("heartbeat_interval", 0.1)
    kw.setdefault("agent_timeout", 3.0)
    kw.setdefault("grace", 20.0)
    kw.setdefault("lease_ttl", 0.8)
    kw.setdefault("poll", 0.02)
    kw.setdefault("term_timeout", 5.0)
    kw.setdefault("ack_timeout", 10.0)
    kw.setdefault("agent_wait", 20.0)
    kw.setdefault("backoff", 0.05)
    return AgentConfig(agent_id=aid, num_agents=num_agents,
                       world_size=world, kv_port=port, **kw)


def _run_agents(srv, rank_cmd, *, num_agents=2, world=2, timeout=40.0,
                cfg_kw=None):
    """Run one HostAgent per simulated host in threads; return exit codes."""
    results = {}

    def one(aid):
        cfg = _cfg(aid, num_agents=num_agents, world=world, port=srv.port,
                   **(cfg_kw or {}))
        results[aid] = HostAgent(cfg, rank_cmd).run()

    threads = [threading.Thread(target=one, args=(a,))
               for a in range(num_agents)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert not any(t.is_alive() for t in threads), "agents never terminated"
    return [results[a] for a in range(num_agents)]


def test_clean_generation_reaches_ok_verdict():
    with KVServer() as srv:
        codes = _run_agents(
            srv, lambda gen, rank, port: [PY, "-c", "import sys; sys.exit(0)"]
        )
        assert codes == [0, 0]
        kv = KVClient(port=srv.port)
        verdict = json.loads(kv.get(K_JOB_DONE))
        assert verdict["ok"] and verdict["generations"] == 1
        assert verdict["restarts"] == 0
        kv.close()


def test_failure_charges_one_restart_then_recovers():
    """Gen 1 has a crashing rank; the leader tears the world down, charges
    exactly one restart (across two agents racing to resolve), and gen 2
    completes."""
    def rank_cmd(gen, rank, port):
        code = 1 if (gen == 1 and rank == 1) else 0
        return [PY, "-c", f"import sys; sys.exit({code})"]

    with KVServer() as srv:
        codes = _run_agents(srv, rank_cmd)
        assert codes == [0, 0]
        kv = KVClient(port=srv.port)
        verdict = json.loads(kv.get(K_JOB_DONE))
        assert verdict["ok"]
        assert int(kv.get(K_RESTARTS)) == 1
        assert int(kv.get(K_GENERATION)) == 2
        assert int(kv.try_get(K_PREEMPTIONS) or 0) == 0
        kv.close()


def test_preemption_is_not_charged_as_restart():
    def rank_cmd(gen, rank, port):
        code = PREEMPTED_EXIT_CODE if (gen == 1 and rank == 0) else 0
        return [PY, "-c", f"import sys; sys.exit({code})"]

    with KVServer() as srv:
        codes = _run_agents(srv, rank_cmd)
        assert codes == [0, 0]
        kv = KVClient(port=srv.port)
        verdict = json.loads(kv.get(K_JOB_DONE))
        assert verdict["ok"]
        assert int(kv.get(K_PREEMPTIONS)) == 1
        assert int(kv.try_get(K_RESTARTS) or 0) == 0
        kv.close()


def test_restart_budget_exhaustion_fails_the_job():
    with KVServer() as srv:
        codes = _run_agents(
            srv,
            lambda gen, rank, port: [PY, "-c", "import sys; sys.exit(1)"],
            cfg_kw={"max_restarts": 1},
        )
        assert codes == [1, 1]
        kv = KVClient(port=srv.port)
        verdict = json.loads(kv.get(K_JOB_DONE))
        assert not verdict["ok"] and not verdict["preempted"]
        assert "budget" in verdict["reason"]
        assert int(kv.get(K_RESTARTS)) == 2  # gen1 charge + gen2 over-budget
        kv.close()


# -- AgentLauncher (the scheduler stand-in) --------------------------------

_FAKE_AGENT = """
import json, sys
sys.path.insert(0, {root!r})
from tpu_sandbox.runtime.kvstore import KVClient
kv = KVClient(port=int(sys.argv[1]))
incarnation = kv.add("test/incarnation", 1)
if incarnation == 1:
    sys.exit(9)  # first life dies before any verdict
kv.set("job/done", json.dumps(
    {{"ok": True, "reason": "fake agent finished", "summary": "",
      "restarts": 0, "preemptions": 0, "generations": 1}}))
kv.close()
sys.exit(0)
"""


def test_launcher_respawns_dead_agent_until_verdict(tmp_path):
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "fake_agent.py"
    script.write_text(_FAKE_AGENT.format(root=root))
    with KVServer() as srv:
        launcher = AgentLauncher(
            1, lambda aid, port: [PY, str(script), str(port)],
            kv_server=srv, poll=0.05, drain_timeout=10,
        )
        assert launcher.run() == 0
        assert launcher.respawns == 1


def test_launcher_respawn_limit_bounds_crash_loops(tmp_path):
    script = tmp_path / "dying_agent.py"
    script.write_text("import sys; sys.exit(9)\n")
    with KVServer() as srv:
        launcher = AgentLauncher(
            1, lambda aid, port: [PY, str(script), str(port)],
            kv_server=srv, respawn_limit=2, poll=0.05, drain_timeout=5,
        )
        assert launcher.run() == 1
        assert launcher.respawns == 3  # 2 allowed + the one over the limit
