"""ConvNet parity tests — shapes, lazy head sizing, and a numerical
cross-check against a torch replica of the reference architecture
(torch-cpu is in the image; the reference model is mnist_onegpu.py:11-31)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.models import ConvNet
from tpu_sandbox.ops import cross_entropy_loss


def init_model(h=32, w=32):
    model = ConvNet()
    variables = model.init(jax.random.key(0), jnp.zeros((1, h, w, 1)), train=False)
    return model, variables


def test_forward_shapes_and_lazy_head():
    model, variables = init_model(32, 32)
    # 32x32 -> pool -> 16 -> pool -> 8; flatten = 32*8*8 = 2048
    assert variables["params"]["fc"]["kernel"].shape == (2048, 10)
    logits = model.apply(variables, jnp.ones((3, 32, 32, 1)), train=False)
    assert logits.shape == (3, 10)
    assert logits.dtype == jnp.float32

    # lazy semantics: a different input size gives a different head
    _, v2 = init_model(64, 64)
    assert v2["params"]["fc"]["kernel"].shape == (32 * 16 * 16, 10)


def test_param_count_matches_reference_at_3000():
    # At 3000x3000 the head must be 18M x 10 (SURVEY §2.1 C11).
    model = ConvNet()
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.zeros((1, 3000, 3000, 1)), train=False)
    )
    assert shapes["params"]["fc"]["kernel"].shape == (32 * 750 * 750, 10)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(shapes["params"]))
    assert n_params > 180_000_000  # the ~180M-param OOM-demo matmul


def test_batch_stats_update_in_train_mode():
    model, variables = init_model()
    x = jax.random.normal(jax.random.key(1), (4, 32, 32, 1)) * 3 + 1
    _, mutated = model.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    new_mean = mutated["batch_stats"]["bn1"]["mean"]
    assert not np.allclose(np.asarray(new_mean), 0.0)  # moved toward batch mean


def test_cross_entropy_matches_analytic():
    logits = jnp.log(jnp.array([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1]]))
    labels = jnp.array([0, 1])
    expected = -(np.log(0.7) + np.log(0.8)) / 2
    np.testing.assert_allclose(float(cross_entropy_loss(logits, labels)), expected, rtol=1e-6)


def test_numerical_parity_with_torch_reference():
    """Copy weights into a torch replica of the reference stack and compare
    eval-mode forward outputs — verifies conv padding, BN eps, pool, and
    flatten-order semantics match the architecture the reference trains."""
    torch = pytest.importorskip("torch")
    from tpu_sandbox.utils.parity import torch_twin

    model, variables = init_model(16, 16)
    tm = torch_twin(torch, variables["params"], hw=4).eval()

    x = np.random.default_rng(0).normal(size=(2, 16, 16, 1)).astype(np.float32)
    jax_out = np.asarray(model.apply(variables, jnp.asarray(x), train=False))
    with torch.no_grad():
        torch_out = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(jax_out, torch_out, atol=1e-4)


def test_training_loss_curve_parity_with_torch():
    """SURVEY §7 hard-part 3: same init, same data, same SGD — the per-step
    *training* losses must track the torch reference step for step (train
    mode exercises conv/BN/pool/matmul gradients and the BN batch-stat
    path; SGD(lr, no momentum) is linear so drift would compound and show)."""
    torch = pytest.importorskip("torch")
    import optax

    from tpu_sandbox.train import TrainState, make_train_step
    from tpu_sandbox.utils.parity import torch_twin

    lr, steps, bs = 0.05, 8, 8
    model, variables = init_model(16, 16)
    tm = torch_twin(torch, variables["params"], hw=4)

    rng = np.random.default_rng(42)
    batches = [
        (rng.normal(size=(bs, 16, 16, 1)).astype(np.float32),
         rng.integers(0, 10, size=bs).astype(np.int64))
        for _ in range(steps)
    ]

    tx = optax.sgd(lr)
    state = TrainState.create(model, jax.random.key(0), jnp.zeros((1, 16, 16, 1)), tx)
    state = state.replace(params=variables["params"],
                          batch_stats=variables["batch_stats"])
    step = make_train_step(model, tx, donate=False)
    jax_losses = []
    for x, y in batches:
        state, loss = step(state, jnp.asarray(x), jnp.asarray(y.astype(np.int32)))
        jax_losses.append(float(loss))

    tm.train()
    opt = torch.optim.SGD(tm.parameters(), lr=lr)
    crit = torch.nn.CrossEntropyLoss()
    torch_losses = []
    for x, y in batches:
        opt.zero_grad()
        out = tm(torch.from_numpy(x.transpose(0, 3, 1, 2)))
        loss = crit(out, torch.from_numpy(y))
        loss.backward()
        opt.step()
        torch_losses.append(float(loss))

    np.testing.assert_allclose(jax_losses, torch_losses, rtol=2e-3, atol=2e-3)
