"""MoE / expert-parallel tests: routing math, capacity overflow, training,
and expert-sharded execution matching the unsharded run."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
from tpu_sandbox.parallel.expert import MoeMlp
from tpu_sandbox.parallel.pjit_engine import PjitEngine
from tpu_sandbox.runtime.mesh import make_mesh

CFG = TransformerConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_len=64,
    n_experts=4, capacity_factor=2.0,
)


def test_moe_forward_shape_and_aux_loss():
    layer = MoeMlp(CFG)
    x = jax.random.normal(jax.random.key(0), (2, 16, 32))
    variables = layer.init(jax.random.key(1), x)
    y, aux = layer.apply(
        {"params": variables["params"]}, x, mutable=["aux_loss"]
    )
    assert y.shape == x.shape
    (aux_val,) = aux["aux_loss"]["load_balance"]
    # perfectly balanced top-1 routing gives aux ~= 1; any routing >= 1
    assert float(aux_val) >= 0.99


def test_moe_top1_math_with_ample_capacity():
    """With capacity >= S every token is kept: output must equal
    gate_prob * expert_ffn(token) computed by hand."""
    cfg = TransformerConfig(d_model=8, d_ff=16, n_experts=2, capacity_factor=4.0)
    layer = MoeMlp(cfg)
    x = jax.random.normal(jax.random.key(2), (1, 6, 8))
    variables = layer.init(jax.random.key(3), x)
    y = layer.apply(variables, x)

    p = variables["params"]
    logits = x @ p["router"]["kernel"] + p["router"]["bias"]
    probs = jax.nn.softmax(logits, -1)
    idx = jnp.argmax(probs, -1)[0]
    gate = jnp.max(probs, -1)[0]
    import flax.linen as nn

    for t in range(6):
        e = int(idx[t])
        expected = float(gate[t]) * (
            nn.gelu(x[0, t] @ p["w_up"][e]) @ p["w_down"][e]
        )
        np.testing.assert_allclose(np.asarray(y[0, t]), np.asarray(expected), atol=1e-5)


def test_moe_top2_math_with_ample_capacity():
    """GShard-style top-2: output must equal the normalized-gate mix of the
    two chosen experts' FFNs, computed by hand."""
    cfg = TransformerConfig(d_model=8, d_ff=16, n_experts=4,
                            capacity_factor=8.0, router_top_k=2)
    layer = MoeMlp(cfg)
    x = jax.random.normal(jax.random.key(7), (1, 6, 8))
    variables = layer.init(jax.random.key(8), x)
    y = layer.apply(variables, x)

    p = variables["params"]
    logits = x @ p["router"]["kernel"] + p["router"]["bias"]
    probs = jax.nn.softmax(logits, -1)[0]  # [S, E]
    import flax.linen as nn

    for t in range(6):
        vals, idx = jax.lax.top_k(probs[t], 2)
        gates = vals / vals.sum()
        expected = sum(
            float(gates[j]) * (
                nn.gelu(x[0, t] @ p["w_up"][int(idx[j])]) @ p["w_down"][int(idx[j])]
            )
            for j in range(2)
        )
        np.testing.assert_allclose(
            np.asarray(y[0, t]), np.asarray(expected), atol=1e-5
        )


def test_moe_top2_first_choices_have_priority():
    """Choice-major capacity: with capacity for half the tokens, every
    token's FIRST choice gets a slot before any second choice does — so
    second-choice dispatch only appears in experts with spare capacity."""
    cfg = TransformerConfig(d_model=8, d_ff=16, n_experts=2,
                            capacity_factor=1.0, router_top_k=2)
    layer = MoeMlp(cfg)
    x = jax.random.normal(jax.random.key(9), (1, 8, 8))
    variables = layer.init(jax.random.key(10), x)
    # E=2, K=2: every token picks both experts; capacity = 1.0*8/2 = 4 per
    # expert, demand = 8 firsts + 8 seconds over 2*4=8 slots. All slots
    # must go to first choices.
    p = variables["params"]
    logits = x @ p["router"]["kernel"] + p["router"]["bias"]
    first = np.asarray(jnp.argmax(jax.nn.softmax(logits, -1), -1))[0]  # [S]
    n_first_e0 = int((first == 0).sum())
    if n_first_e0 in (0, 8):
        pytest.skip("degenerate routing draw; all firsts on one expert")
    # run and check: each token kept iff its first choice had a free slot
    # (first-come within the sequence), never via its second choice when
    # that expert was already full of firsts... simplest sufficient check:
    # total kept (nonzero outputs) == total capacity filled by firsts when
    # firsts saturate an expert
    y = np.asarray(layer.apply(variables, x))
    kept = (np.abs(y[0]).sum(-1) > 1e-7)
    # every token whose first choice queue position < 4 must be kept
    pos = {0: 0, 1: 0}
    for t in range(8):
        if pos[first[t]] < 4:
            assert kept[t], f"token {t} (first choice {first[t]}) dropped"
        pos[first[t]] += 1


def test_moe_capacity_overflow_drops_tokens():
    """capacity_factor small: tokens past capacity get zero output (they
    ride the residual in a Block)."""
    cfg = TransformerConfig(d_model=8, d_ff=16, n_experts=1, capacity_factor=0.5)
    layer = MoeMlp(cfg)
    x = jax.random.normal(jax.random.key(4), (1, 8, 8))
    variables = layer.init(jax.random.key(5), x)
    y = np.asarray(layer.apply(variables, x))
    # n_experts=1: all tokens route to expert 0, capacity = 4 -> tokens 4..7 dropped
    assert not np.allclose(y[0, :4], 0.0)
    np.testing.assert_allclose(y[0, 4:], 0.0, atol=1e-7)


def moe_model_ctor():
    return TransformerLM(CFG, mlp_cls=MoeMlp)


def lm_batch(b=8, s=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, CFG.vocab_size, size=(b, s)).astype(np.int32)
    targets = ((tokens + 7) % CFG.vocab_size).astype(np.int32)
    return tokens, targets


def test_moe_transformer_trains():
    from tpu_sandbox.ops.losses import cross_entropy_loss

    model = moe_model_ctor()
    tokens, targets = lm_batch()
    variables = model.init(jax.random.key(0), jnp.asarray(tokens))
    tx = optax.adam(1e-2)
    opt_state = tx.init(variables["params"])

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            logits = model.apply({"params": p}, jnp.asarray(tokens))
            return cross_entropy_loss(
                logits.reshape(-1, logits.shape[-1]), jnp.asarray(targets).reshape(-1)
            )

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    params = variables["params"]
    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.6, (losses[0], losses[-1])


def test_aux_loss_wired_into_engine():
    """VERDICT r01 weak #8: the sown load-balance loss must actually reach
    the training objective. With lr=0 the step loss is pure objective, so
    loss(aux_weight=w) - loss(aux_weight=0) == w * aux (aux >= ~1)."""
    from tpu_sandbox.train import TrainState

    mesh = make_mesh({"data": 8})
    model = moe_model_ctor()
    tx = optax.sgd(0.0)
    tokens, targets = lm_batch()
    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx
    )
    losses = {}
    for w in (0.0, 0.5):
        eng = PjitEngine(model, tx, mesh, task="lm", aux_weight=w, donate=False)
        _, loss = eng.train_step(
            eng.shard_state(state), *eng.shard_batch(tokens, targets)
        )
        losses[w] = float(loss)
    # aux >= 0.99 (test_moe_forward_shape_and_aux_loss) => gap >= 0.5*0.99
    assert losses[0.5] - losses[0.0] >= 0.49, losses


def test_aux_loss_keeps_routing_balanced():
    """Train a few hundred steps with the Switch alpha and assert top-1
    routing does not collapse: balanced routing keeps aux ~= 1, collapse
    onto one of E=4 experts drives it toward 4."""
    from tpu_sandbox.train import TrainState

    mesh = make_mesh({"data": 8})
    model = moe_model_ctor()
    tx = optax.adam(3e-3)
    tokens, targets = lm_batch(b=8, s=16)
    state = TrainState.create(
        model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx
    )
    eng = PjitEngine(model, tx, mesh, task="lm", aux_weight=0.01, donate=False)
    state = eng.shard_state(state)
    batch = eng.shard_batch(tokens, targets)
    first = None
    for i in range(200):
        state, loss = eng.train_step(state, *batch)
        if first is None:
            first = float(loss)
        elif i % 20 == 0:
            float(loss)  # sync: cap the async dispatch queue

    _, sown = model.apply(
        {"params": jax.device_get(state.params)}, jnp.asarray(tokens),
        mutable=["aux_loss"],
    )
    aux = float(jax.tree.leaves(sown["aux_loss"])[0])
    assert aux < 1.8, f"routing collapsing: aux={aux}"
    assert float(loss) < first, (first, float(loss))


def test_expert_parallel_sharding_matches_unsharded():
    """dp x ep mesh: expert weights sharded on 'expert'; the jit'd step must
    produce the same loss and params as the unsharded single-device step."""
    from tpu_sandbox.train import TrainState

    mesh = make_mesh({"data": 2, "expert": 4})
    model = moe_model_ctor()
    tx = optax.sgd(0.1)
    tokens, targets = lm_batch()

    state = TrainState.create(model, jax.random.key(0), jnp.zeros((1, 16), jnp.int32), tx)

    # unsharded reference
    ref_eng = PjitEngine(model, tx, mesh, task="lm", donate=False)
    ref_state, ref_loss = ref_eng.train_step(
        ref_eng.shard_state(state), *ref_eng.shard_batch(tokens, targets)
    )

    eng = PjitEngine(
        model, tx, mesh, task="lm",
        rules=[(r"w_(up|down)", P("expert", None, None))],
        donate=False,
    )
    sstate = eng.shard_state(state)
    w_up = sstate.params["block0"]["mlp"]["w_up"]
    assert w_up.sharding.spec == P("expert", None, None)
    assert {s.data.shape for s in w_up.addressable_shards} == {(1, 32, 64)}

    new_state, loss = eng.train_step(sstate, *eng.shard_batch(tokens, targets))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(new_state.params["block0"]["mlp"]["w_up"]),
        np.asarray(ref_state.params["block0"]["mlp"]["w_up"]),
        atol=1e-5,
    )
