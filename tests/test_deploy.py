"""Continuous deployment, fast and in-process (tier-1).

Stub-engine fault matrix for the train->serve deployment loop: the
durable model registry, the leader-elected :class:`DeployController`,
rolling swaps through the replica mailbox, canary analysis against the
tsdb, and the hard contracts the ISSUE pins:

- every in-flight request finishes on the weights it started with, or is
  replayed bitwise on them (version pin survives requeue/scavenge);
- promotion/rollback decisions are exactly-once through controller death
  (killed between record and claim -> the successor completes, one event);
- a corrupt or unsealed artifact is rejected before ANY replica is told
  about it — no swap command ever exists for a rejected version;
- a replica killed mid-swap respawns onto the target version (re-sent
  mailbox command) while its orphaned work replays on the pinned version.

Real subprocess fleets + jax weights live in the slow-marked
test_deploy_integration.py; everything here uses the _StubStep pattern
(next token = last + 1 mod vocab) so the file stays inside tier-1.
"""

import json
import time
from pathlib import Path

import numpy as np
import pytest

from tpu_sandbox.deploy.controller import DeployConfig, DeployController
from tpu_sandbox.deploy.registry import (current_target, deploy_events,
                                         k_ro, load_step_params,
                                         read_shares, registry_versions,
                                         rollout_phase, audit_registry)
from tpu_sandbox.gateway.fleet import FleetSpec
from tpu_sandbox.gateway.server import Gateway
from tpu_sandbox.gateway.client import GatewayClient, RetriesExhausted
from tpu_sandbox.models.transformer import TransformerConfig
from tpu_sandbox.obs.health import active_subjects
from tpu_sandbox.serve.cache import CacheConfig
from tpu_sandbox.serve.engine import ContinuousEngine, Request, ServeConfig
from tpu_sandbox.serve.replica import (ReplicaWorker, k_cmd, k_done, k_load,
                                       k_pin, k_result, read_load_reports,
                                       read_result, submit_request)
from tpu_sandbox.train.checkpoint import export_params, verify_step_dir
from tpu_sandbox.train.trainer import publish_checkpoint

MCFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_len=128)
CCFG = CacheConfig(num_blocks=24, block_size=4, max_blocks_per_seq=8)
BLOCK = CCFG.block_size


class _StubStep:
    """DecodeStep stand-in: next token = (last + 1) % vocab, no jax."""

    def __init__(self, buckets=(8, 16), vocab=64):
        self.buckets = tuple(buckets)
        self.vocab = vocab
        self.prefill = {b: self._prefill for b in self.buckets}

    def pick_bucket(self, plen):
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(f"prompt of {plen} exceeds buckets {self.buckets}")

    def _prefill(self, params, k, v, toks, dest, last):
        toks = np.asarray(toks)
        logits = np.zeros((self.vocab,), np.float32)
        logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
        return logits, k, v

    def decode(self, params, k, v, tokens, lengths, tables):
        tokens = np.asarray(tokens)
        logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
        for i in range(tokens.shape[0]):
            logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
        return logits, k, v


def _engine(**over):
    cfg = ServeConfig(model=MCFG, cache=CCFG, max_batch=2, buckets=(8, 16),
                      **over)
    return ContinuousEngine(None, cfg, step=_StubStep(), clock=time.monotonic)


@pytest.fixture
def kv_pair():
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    server = KVServer()
    kv = KVClient(port=server.port)
    clones = []

    def clone():
        c = kv.clone()
        clones.append(c)
        return c

    yield server, kv, clone
    for c in clones:
        c.close()
    kv.close()
    server.stop()


_SENTINEL_LOADER = object()


def _worker(kv, tag, **over):
    over.setdefault("lease_ttl", 0.3)
    over.setdefault("load_interval", 0.02)
    over.setdefault("publish_ts", False)
    # stub weights for swap commands: any version loads instantly (tests
    # that want the artifact path pass swap_loader=None explicitly)
    if over.get("swap_loader", _SENTINEL_LOADER) is _SENTINEL_LOADER:
        over["swap_loader"] = lambda cmd: ("stub", int(cmd["ver"]))
    return ReplicaWorker(kv, _engine(), tag=tag, **over)


def _controller(kv, **over):
    over.setdefault("cfg", DeployConfig(swap_resend_s=0.05, canary_evals=2))
    over.setdefault("election_ttl", 1.0)
    return DeployController(kv, **over)


def _publish(kv, directory, *, step=100, params=None, **kw):
    params = params if params is not None \
        else {"w": np.arange(8, dtype=np.float32)}
    return publish_checkpoint(kv, params, export_dir=directory, step=step,
                              **kw)


def _corrupt(step_dir):
    """Flip trailing bytes of one shard: size unchanged, digest broken."""
    shard = next(Path(step_dir).glob("shard-*.npz"))
    data = shard.read_bytes()
    shard.write_bytes(data[:-4] + b"XXXX")


def _drive(until, *actors, timeout=20.0, poll=0.01):
    """Tick every actor (workers + controllers) until the condition
    holds. Single-threaded on purpose: every interleaving is explicit."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for a in actors:
            a.tick()
        if until():
            return
        time.sleep(poll)
    raise AssertionError("drive condition not reached in time")


def _actions(kv):
    return [e["action"] for e in deploy_events(kv)]


def _seed_hist(kv, proc, series, *, p99=0.0, mean=0.0):
    """One synthetic tsdb histogram point — the deterministic stand-in
    for a TimeSeriesFlusher flush (the global metrics registry is shared
    in-process, so real flushes can't separate canary from baseline)."""
    bucket = int(time.time())
    kv.set_ttl(f"obs/ts/{proc}/{series}/{bucket % 120}", json.dumps(
        {"kind": "histogram",
         "v": {"count": 1, "p50": p99, "p90": p99, "p99": p99, "mean": mean},
         "bucket": bucket, "wall": time.time()}), 60.0)


# -- registry / trainer handoff ----------------------------------------------


def test_publish_checkpoint_round_trip(kv_pair, tmp_path):
    _, kv, _ = kv_pair
    params = {"w": np.arange(6, dtype=np.float32),
              "b": np.ones((2, 3), np.float32)}
    ver = publish_checkpoint(kv, params, export_dir=tmp_path, step=42,
                             extra={"note": "gen1"})
    assert ver == 1
    rec = registry_versions(kv)[1]
    assert rec["step"] == 42 and rec["note"] == "gen1"
    assert verify_step_dir(rec["step_dir"]) == []  # sealed on disk
    got = load_step_params(rec["step_dir"], params)
    np.testing.assert_array_equal(got["w"], params["w"])
    np.testing.assert_array_equal(got["b"], params["b"])
    # publication is registration, never promotion
    assert current_target(kv) == 0
    assert _actions(kv) == ["published"]
    assert publish_checkpoint(kv, params, export_dir=tmp_path, step=43) == 2


# -- engine: versioned weights, pins, grouped decode -------------------------


def test_engine_swap_keeps_inflight_on_pinned_version():
    eng = _engine()
    eng.submit(Request(rid="a", prompt=[1, 2, 3], max_new_tokens=4))
    eng.step()  # admit "a" on the boot version
    assert eng.active_requests == 1
    eng.swap_params(("stub", 1), 1)
    eng.submit(Request(rid="b", prompt=[1, 2, 3], max_new_tokens=4))
    eng.run_until_idle()
    # both decode the same tokens; each carries the version it pinned
    assert eng.results["a"].tokens == eng.results["b"].tokens == [4, 5, 6, 7]
    assert eng.results["a"].ver == 0 and eng.results["b"].ver == 1
    assert eng.has_version(0)  # boot weights retained as rollback target


def test_engine_swap_flushes_prefix_cache():
    eng = _engine()
    eng.submit(Request(rid="r", prompt=list(range(1, 9)), max_new_tokens=2))
    eng.run_until_idle()
    assert eng.load_report()["prefix_digest"]
    assert eng.swap_params(("stub", 1), 1) >= 1
    # the resident prefix K/V was computed under the old weights: gone
    assert eng.load_report()["prefix_digest"] == []


def test_engine_stale_pin_sheds_explicitly():
    eng = _engine()
    eng.submit(Request(rid="r", prompt=[1, 2, 3], max_new_tokens=2, ver=7))
    eng.step()
    # pinned weights not resident and no loader: an explicit verdict, so
    # the client restarts a fresh lifecycle — never a silent re-pin
    assert eng.shed["r"].reason == "stale_version"
    loaded = _engine()
    loaded.loader = lambda ver: ("stub", ver) if ver == 7 else None
    loaded.submit(Request(rid="r", prompt=[1, 2, 3], max_new_tokens=2, ver=7))
    loaded.run_until_idle()
    assert loaded.results["r"].ver == 7


# -- replica: the swap mailbox ------------------------------------------------


def test_replica_swap_acks_and_is_idempotent(kv_pair):
    _, kv, clone = kv_pair
    w = _worker(clone(), "w0")
    kv.set(k_cmd("w0"), json.dumps({"action": "swap", "ver": 2}))
    w.tick()
    assert w.engine.version == 2 and w.stats.swaps == 1
    assert json.loads(kv.get(k_load("w0")))["ver"] == 2  # the ack
    # a re-sent command for the version already running is consumed, not
    # re-applied (the controller re-sends until the ack lands)
    kv.set(k_cmd("w0"), json.dumps({"action": "swap", "ver": 2}))
    w.tick()
    assert w.stats.swaps == 1 and kv.try_get(k_cmd("w0")) is None


def test_replica_swap_verifies_before_touching_engine(kv_pair, tmp_path):
    _, kv, clone = kv_pair
    w = _worker(clone(), "w0")
    step_dir = export_params(tmp_path, {"w": np.arange(4.)}, 1)
    _corrupt(step_dir)
    kv.set(k_cmd("w0"), json.dumps(
        {"action": "swap", "ver": 3, "step_dir": str(step_dir)}))
    w.tick()
    # verify-before-touch: the engine is exactly as it was, and the load
    # report carries the error the controller rolls back on
    assert w.engine.version == 0 and w.stats.swap_errors == 1
    rep = json.loads(kv.get(k_load("w0")))
    assert rep["ver"] == 0
    assert rep["swap_error"]["ver"] == 3
    assert rep["swap_error"]["error"] == "verify"
    assert rep["swap_error"]["problems"]


# -- controller: the fault matrix ---------------------------------------------


def test_corrupt_artifact_rejected_before_any_swap_command(kv_pair, tmp_path):
    _, kv, clone = kv_pair
    ver = _publish(kv, tmp_path)
    _corrupt(registry_versions(kv)[ver]["step_dir"])
    ctrl = _controller(clone())
    _drive(lambda: rollout_phase(kv, "", ver)["reject"] is not None, ctrl)
    phase = rollout_phase(kv, "", ver)
    assert phase["reject_claimed"]
    assert phase["reject"]["problems"]
    assert phase["rec"] is None  # never began
    # the hard gate: no replica was ever told about this version
    assert kv.keys("serve/cmd/") == []
    assert _actions(kv) == ["published", "rejected"]
    # rejected forever: further ticks re-decide nothing
    for _ in range(5):
        assert ctrl.tick() is None
    assert _actions(kv) == ["published", "rejected"]
    row = audit_registry(kv)["versions"][0]
    assert row["status"] == "rejected" and not row["sealed"]
    ctrl.resign()


def test_unsealed_artifact_rejected(kv_pair, tmp_path):
    _, kv, clone = kv_pair
    ver = _publish(kv, tmp_path)
    # simulate a kill inside the export commit window: manifest gone
    step_dir = Path(registry_versions(kv)[ver]["step_dir"])
    (step_dir / "MANIFEST.json").unlink()
    ctrl = _controller(clone())
    _drive(lambda: rollout_phase(kv, "", ver)["reject"] is not None, ctrl)
    assert "torn" in rollout_phase(kv, "", ver)["reject"]["problems"][0]
    assert kv.keys("serve/cmd/") == []
    ctrl.resign()


def test_single_replica_rollout_promotes_without_baseline(kv_pair, tmp_path):
    _, kv, clone = kv_pair
    w = _worker(clone(), "w0")
    ctrl = _controller(clone())
    ver = _publish(kv, tmp_path)
    _drive(lambda: current_target(kv) == ver, w, ctrl)
    assert json.loads(kv.get(k_load("w0")))["ver"] == ver
    assert _actions(kv) == ["published", "promote_begin", "canary_pass",
                            "promoted"]
    verdict = rollout_phase(kv, "", ver)["verdict"]
    assert verdict["reason"] == "no_baseline"
    assert read_shares(kv) is None  # no split ever went up for one replica
    assert audit_registry(kv)["versions"][0]["status"] == "current"
    ctrl.resign()


def test_controller_killed_between_record_and_claim_exactly_once(
        kv_pair, tmp_path):
    _, kv, clone = kv_pair
    ver = _publish(kv, tmp_path)
    step_dir = registry_versions(kv)[ver]["step_dir"]
    # the predecessor died between the rec record and its claim: the
    # record exists, the claim does not, no event was ever emitted
    kv.set(k_ro("", ver, "rec"), json.dumps(
        {"ver": ver, "step_dir": step_dir, "prev": 0, "wall": time.time()}))
    assert _actions(kv) == ["published"]
    w = _worker(clone(), "w0")
    a, b = _controller(clone(), member_id="a"), \
        _controller(clone(), member_id="b")
    _drive(lambda: current_target(kv) == ver, w, a, b)
    # two candidate controllers raced the whole rollout; the claim-once
    # phase records kept every decision single
    acts = _actions(kv)
    assert acts == ["published", "promote_begin", "canary_pass", "promoted"]
    # a fresh successor reconstructs "nothing to do" from the store alone
    a.resign()
    b.resign()
    c = _controller(clone(), member_id="c")
    for _ in range(5):
        c.tick()
    assert _actions(kv) == acts
    c.resign()


def test_canary_regression_rolls_back_and_alerts(kv_pair, tmp_path):
    _, kv, clone = kv_pair
    w0, w1 = _worker(clone(), "w0"), _worker(clone(), "w1")
    ctrl = _controller(clone())
    ver = _publish(kv, tmp_path)
    # phase 1: the canary (first tag) swaps and the traffic split goes up
    _drive(lambda: read_shares(kv) is not None, w0, w1, ctrl)
    assert json.loads(kv.get(k_load("w0")))["ver"] == ver
    assert json.loads(kv.get(k_load("w1")))["ver"] == 0
    assert read_shares(kv) == {ver: 0.25, 0: 0.75}
    # phase 2: the canary's p99 TTFT degrades 10x against the incumbent —
    # the BaselineDeltaRule fires regress_streak consecutive evaluations
    _seed_hist(kv, "w0", "engine.ttft", p99=10.0)
    _seed_hist(kv, "w1", "engine.ttft", p99=1.0)
    _drive(lambda: current_target(kv) == 0
           and rollout_phase(kv, "", ver)["done"] is not None, w0, w1, ctrl)
    phase = rollout_phase(kv, "", ver)
    assert phase["verdict"]["outcome"] == "fail"
    assert phase["verdict"]["evidence"][0]["series"] == "engine.ttft"
    assert phase["done"]["outcome"] == "rolled_back"
    # both replicas converged back; the split is gone; target never moved
    assert json.loads(kv.get(k_load("w0")))["ver"] == 0
    assert read_shares(kv) is None
    assert _actions(kv) == ["published", "promote_begin", "canary_fail",
                            "rolled_back"]
    # the regression is a first-class health alert while the TTL lasts
    assert "default" in active_subjects(kv, "canary_regression")
    assert audit_registry(kv)["versions"][0]["status"] == "rolled_back"
    ctrl.resign()


def test_artifact_rotting_after_verify_rolls_back(kv_pair, tmp_path):
    """The race the replica-side re-verify exists for: the artifact was
    sealed when the controller checked it, and rots before the replica
    loads it. The failed swap is evidence; the rollout fails closed."""
    _, kv, clone = kv_pair
    w = _worker(clone(), "w0", swap_loader=None)  # real artifact path
    ctrl = _controller(clone())
    ver = _publish(kv, tmp_path)
    _drive(lambda: "promote_begin" in _actions(kv), ctrl)
    _corrupt(registry_versions(kv)[ver]["step_dir"])
    _drive(lambda: rollout_phase(kv, "", ver)["done"] is not None, w, ctrl)
    phase = rollout_phase(kv, "", ver)
    assert phase["verdict"]["outcome"] == "fail"
    assert phase["verdict"]["evidence"][0]["swap_error"]["error"] == "verify"
    assert phase["done"]["outcome"] == "rolled_back"
    assert w.engine.version == 0 and current_target(kv) == 0
    ctrl.resign()


def test_replica_killed_mid_swap_respawns_and_replays_bitwise(
        kv_pair, tmp_path):
    _, kv, clone = kv_pair
    dead = _worker(clone(), "w0")
    ctrl = _controller(clone())
    # the replica claims a request on the boot version (pin = 0)...
    submit_request(kv, "r0", [1, 2, 3], 3)
    dead.tick()
    assert dead.stats.claimed == 1 and kv.get(k_pin("r0")) == b"0"
    # ...then a rollout starts and the swap command lands in its mailbox
    ver = _publish(kv, tmp_path)
    _drive(lambda: kv.try_get(k_cmd("w0")) is not None, ctrl)
    # SIGKILL mid-swap: the worker never ticks again. Its load report and
    # leases expire; the mailbox still holds the unconsumed command.
    time.sleep(0.45)
    assert read_load_reports(kv) == {}
    respawn = _worker(clone(), "w0")
    _drive(lambda: current_target(kv) == ver
           and kv.try_get(k_result("r0")) is not None,
           respawn, ctrl)
    # the respawn landed on the target version (mailbox command, then the
    # controller's re-send patience covers a consumed-but-unapplied one)
    assert respawn.engine.version == ver
    assert json.loads(kv.get(k_load("w0")))["ver"] == ver
    # the orphaned request was scavenged, re-claimed, and replayed on its
    # PINNED version — bitwise the tokens of the unfaulted run
    got = read_result(kv, "r0")
    assert got["verdict"] == "ok" and got["tokens"] == [4, 5, 6]
    assert got["ver"] == 0 and kv.get(k_pin("r0")) == b"0"
    assert respawn.stats.scavenged == 1
    # exactly-once held through the replica fault too
    assert _actions(kv) == ["published", "promote_begin", "canary_pass",
                            "promoted"]
    ctrl.resign()
    dead.engine.drain_to_requests()  # release the abandoned engine


# -- gateway door: dead-fleet fast-fail (satellite wire test) -----------------


def test_door_no_replicas_fast_fail_over_wire(kv_pair):
    _, kv, _ = kv_pair
    gw = Gateway(kv, [FleetSpec(block_size=BLOCK)],
                 refresh_min_s=0.005).start()
    try:
        with GatewayClient(gw.port, deadline_s=1.0, max_retries=0) as client:
            # zero fresh load reports + a deadline: fast-fail at the door
            # instead of letting the rid rot against a dead fleet
            assert client.submit("r0", [1, 2, 3], 2) is False
            with pytest.raises(RetriesExhausted) as ei:
                client.result("r0", timeout=10.0)
    finally:
        gw.close()
    assert ei.value.last_reason == "door:no_replicas"
    got = ei.value.verdict
    assert got["verdict"] == "SHED" and got["reason"] == "door:no_replicas"
    assert got["replica"] == "gateway"
    # same claim-once verdict slot as door:infeasible
    assert kv.get(k_done("r0")) is not None
    assert json.loads(kv.get(k_result("r0")))["reason"] == "door:no_replicas"
    assert gw.stats.shed_door == 1 and gw.stats.admitted == 0


# -- canary choice: least-loaded, pinned across ticks and failover ------------


def test_pick_canary_least_loaded_persisted_across_failover(kv_pair):
    _, kv, _ = kv_pair
    ctrl = _controller(kv)
    reports = {"a": {"queue_depth": 3, "active": 1},
               "b": {"queue_depth": 0, "active": 1},
               "c": {"queue_depth": 1, "active": 0}}
    tags = ["a", "b", "c"]
    # least queued+active work wins; the b/c tie (load 1) breaks on tag
    assert ctrl._pick_canary(7, reports, tags) == "b"
    # persisted: the choice must not flap as load shifts between ticks
    reports["b"]["queue_depth"] = 9
    assert ctrl._pick_canary(7, reports, tags) == "b"
    # a successor controller (leader failover mid-canary) swaps and
    # measures the SAME replica it inherited
    ctrl2 = _controller(kv)
    assert ctrl2._pick_canary(7, reports, tags) == "b"
    # the persisted canary died (report gone): re-chosen least-loaded
    del reports["b"]
    assert ctrl._pick_canary(7, reports, ["a", "c"]) == "c"
    assert ctrl2._pick_canary(7, reports, ["a", "c"]) == "c"
    # a different rollout seq is a fresh choice, not the inherited one
    assert ctrl._pick_canary(8, {"a": {"queue_depth": 0, "active": 0},
                                 "c": reports["c"]}, ["a", "c"]) == "a"
