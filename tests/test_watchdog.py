"""Failure-detection subsystem: heartbeats, watchdog, bounded rendezvous,
and the numerical sanitizers.

All single-process with threads — the store is real TCP either way, and
the multiprocess path of the same store is covered by test_native.py.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.runtime.kvstore import KVClient, KVServer
from tpu_sandbox.runtime.watchdog import (
    DeadRankError,
    Heartbeat,
    RendezvousTimeout,
    Watchdog,
    wait_for_world,
)


@pytest.fixture()
def store():
    with KVServer() as srv:
        clients = []

        def connect():
            c = KVClient(port=srv.port)
            clients.append(c)
            return c

        yield connect
        for c in clients:
            c.close()


def test_try_get(store):
    c = store()
    assert c.try_get("nope") is None
    c.set("yes", b"v")
    assert c.try_get("yes") == b"v"


def test_heartbeat_and_watchdog_alive(store):
    hbs = [Heartbeat(store(), r, interval=0.05).start() for r in range(3)]
    wd = Watchdog(store(), world_size=3, timeout=1.0)
    assert wd.dead_ranks() == []
    wd.assert_all_alive()
    for hb in hbs:
        hb.stop()


def test_watchdog_detects_dead_rank(store):
    hb0 = Heartbeat(store(), 0, interval=0.05).start()
    hb1 = Heartbeat(store(), 1, interval=0.05).start()
    wd = Watchdog(store(), world_size=2, timeout=0.3)
    wd.assert_all_alive()

    hb1.stop()  # rank 1 "crashes"
    wd.check()  # absorb rank 1's final stamp (liveness = stamp *changes*)
    time.sleep(0.5)
    assert wd.dead_ranks() == [1]
    with pytest.raises(DeadRankError, match=r"\[1\]"):
        wd.assert_all_alive()
    # health report carries the age of the stale stamp
    h1 = wd.check()[1]
    assert h1.last_seen is not None and h1.age > 0.3
    hb0.stop()


def test_watchdog_never_started_rank(store):
    Heartbeat(store(), 0, interval=0.05).start()
    wd = Watchdog(store(), world_size=2, timeout=10.0, grace=0.2)
    assert wd.dead_ranks() == []  # within grace
    time.sleep(0.3)
    assert wd.dead_ranks() == [1]  # grace expired, rank 1 never appeared


def test_watch_thread_flags_failure(store):
    hb = Heartbeat(store(), 0, interval=0.05).start()
    wd = Watchdog(store(), world_size=2, timeout=0.2, grace=0.2)
    wd.watch(poll=0.05)
    time.sleep(0.6)
    wd.stop_watching()
    assert isinstance(wd.failure, DeadRankError)
    hb.stop()


def test_wait_for_world_success(store):
    import threading

    errs = []

    def rank(r):
        try:
            wait_for_world(store(), 3, r, timeout=5.0)
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [threading.Thread(target=rank, args=(r,)) for r in range(3)]
    [t.start() for t in ts]
    [t.join(timeout=10) for t in ts]
    assert not errs


def test_wait_for_world_timeout_names_missing(store):
    with pytest.raises(RendezvousTimeout, match=r"missing ranks: \[1, 2\]"):
        wait_for_world(store(), 3, 0, timeout=0.3)


def test_wait_for_world_regeneration_waits_again(store):
    """After a successful round, a second round must NOT be satisfied by the
    first round's keys (elastic-restart scenario)."""
    import threading

    clients = [store() for _ in range(2)]
    ts = [
        threading.Thread(target=wait_for_world, args=(clients[r], 2, r),
                         kwargs={"timeout": 5.0})
        for r in range(2)
    ]
    [t.start() for t in ts]
    [t.join(timeout=10) for t in ts]

    # generation 2: only rank 0 shows up -> must time out, not pass
    with pytest.raises(RendezvousTimeout, match=r"missing ranks: \[1\]"):
        wait_for_world(clients[0], 2, 0, timeout=0.3)


# --- sanitizers ----------------------------------------------------------

def test_assert_finite_names_bad_leaves():
    from tpu_sandbox.utils.debugging import NonFiniteError, assert_finite

    good = {"a": jnp.ones(3), "b": {"c": jnp.zeros(2)}}
    assert_finite(good, "good")

    bad = {"a": jnp.ones(3), "b": {"c": jnp.array([1.0, np.nan])}}
    with pytest.raises(NonFiniteError, match="b/c"):
        assert_finite(bad, "bad")

    # bf16 (numpy kind 'V') must not slip past the dtype filter — it is the
    # framework's default compute dtype
    bad16 = {"p": jnp.array([1.0, np.nan], jnp.bfloat16)}
    with pytest.raises(NonFiniteError, match="p"):
        assert_finite(bad16, "bad16")
    assert_finite({"p": jnp.ones(3, jnp.bfloat16)}, "good16")


def test_heartbeat_restart(store):
    hb = Heartbeat(store(), 0, interval=0.05)
    hb.start()
    hb.stop()
    hb.start()  # must beat again, not exit instantly on the stale stop event
    wd = Watchdog(store(), world_size=1, timeout=0.4)
    wd.check()
    time.sleep(0.2)
    assert wd.dead_ranks() == []
    hb.stop()


def test_watchdog_rank_appearing_late_but_within_grace(store):
    """A slow-starting rank must not be declared dead: no stamp at all is
    tolerated for the full grace window, and the first beat clears it."""
    wd = Watchdog(store(), world_size=1, timeout=5.0, grace=0.6)
    assert wd.dead_ranks() == []  # nothing yet: inside grace
    time.sleep(0.2)
    hb = Heartbeat(store(), 0, interval=0.05).start()  # appears late
    time.sleep(0.6)  # well past the grace deadline
    assert wd.dead_ranks() == []  # but it's beating now
    hb.stop()


def test_watchdog_rank_appearing_after_grace(store):
    """Grace expiry without a stamp = dead; a rank that then *does* appear
    flips back to alive (elastic rejoin), with death re-judged from its
    stamp ages, not the stale grace verdict."""
    wd = Watchdog(store(), world_size=1, timeout=5.0, grace=0.2)
    time.sleep(0.3)
    assert wd.dead_ranks() == [0]  # never appeared, grace spent
    hb = Heartbeat(store(), 0, interval=0.05).start()
    assert wd.dead_ranks() == []  # late joiner is alive again
    hb.stop()


def test_heartbeat_deregister_races_watchdog_check(store):
    """Heartbeat.stop(deregister=True) deletes the hb key while a watchdog
    may be mid-check: both sides must stay exception-free, and the final
    verdict must be 'gone' (key deleted reads as never-appeared)."""
    import threading

    wd = Watchdog(store(), world_size=1, timeout=5.0, grace=0.0)
    errs = []
    stop = threading.Event()

    def hammer():
        try:
            while not stop.is_set():
                wd.check()
        except Exception as e:  # pragma: no cover
            errs.append(e)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for r in range(5):  # repeated register/deregister under fire
            hb = Heartbeat(store(), 0, interval=0.02).start()
            time.sleep(0.05)
            hb.stop(deregister=True)
    finally:
        stop.set()
        t.join(timeout=5)
    assert not errs
    # grace=0.0 and the key deleted: the rank reads as dead, not lingering
    assert wd.dead_ranks() == [0]


# --- KV client connect retry ----------------------------------------------

def test_kvclient_retries_until_server_appears():
    """Worker processes race rank 0's server startup; the client must
    retry-connect inside its timeout instead of dying on the first RST."""
    import threading

    from tpu_sandbox.runtime.bootstrap import find_free_port

    port = int(find_free_port())
    box = {}

    def late_server():
        time.sleep(0.4)
        box["srv"] = KVServer(port=port)

    t = threading.Thread(target=late_server)
    t.start()
    try:
        c = KVClient(port=port, connect_timeout=10.0)  # server not up yet
        c.set("k", b"v")
        assert c.try_get("k") == b"v"
        c.close()
    finally:
        t.join(timeout=5)
        if "srv" in box:
            box["srv"].stop()


def test_kvclient_connect_timeout_exhausted_raises():
    from tpu_sandbox.runtime.bootstrap import find_free_port

    port = int(find_free_port())  # nothing will ever listen here
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="retried"):
        KVClient(port=port, connect_timeout=0.4)
    assert time.monotonic() - t0 < 5.0  # bounded, not hanging


def test_kvclient_single_attempt_mode():
    from tpu_sandbox.runtime.bootstrap import find_free_port

    port = int(find_free_port())
    t0 = time.monotonic()
    with pytest.raises(ConnectionError):
        KVClient(port=port, connect_timeout=0)
    assert time.monotonic() - t0 < 1.0  # no retry loop at all


def test_guarded_step_catches_blowup():
    from tpu_sandbox.utils.debugging import NonFiniteError, guarded_step

    def step(state, x):
        state = state + x
        return state, jnp.sum(state)

    g = guarded_step(jax.jit(step))
    s = jnp.zeros(2)
    s, loss = g(s, jnp.ones(2))
    assert float(loss) == 2.0
    with pytest.raises(NonFiniteError, match="step 1"):
        g(s, jnp.array([np.inf, 0.0]))
