"""ConvNetS2DT == ConvNet: the transposed s2d plan is the same function.

Mirror of test_convnet_s2d.py for the [N,H,C,W]-layout plan
(models/convnet_s2d_t.py): identical parameter tree, forward, gradients,
and batch-stats evolution as the reference-parity ConvNet, with and
without the fused tail pair."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_sandbox.models import ConvNet
from tpu_sandbox.models.convnet_s2d_t import ConvNetS2DT
from tpu_sandbox.ops.losses import cross_entropy_loss


def _models(use_bn=True, dtype=jnp.float32, **kw):
    return (ConvNet(use_bn=use_bn, dtype=dtype),
            ConvNetS2DT(use_bn=use_bn, dtype=dtype, **kw))


def _data(n=3, hw=48, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n, hw, hw, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(n,)), jnp.int32)
    return x, y


def test_param_trees_compatible():
    ref, t = _models()
    x, _ = _data()
    vr = ref.init(jax.random.key(0), x)
    vt = t.init(jax.random.key(0), x)
    assert jax.tree.map(jnp.shape, vr) == jax.tree.map(jnp.shape, vt)


def test_space_to_depth_t_is_transposed_space_to_depth():
    from tpu_sandbox.models.convnet_s2d import space_to_depth
    from tpu_sandbox.models.convnet_s2d_t import space_to_depth_t

    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 16, 12)),
                    jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(space_to_depth_t(x, 4)),
        np.asarray(space_to_depth(x, 4).transpose(0, 1, 3, 2)),
    )


def test_block_max_pool_t_is_transposed_block_max_pool():
    from tpu_sandbox.models.convnet_s2d import block_max_pool
    from tpu_sandbox.models.convnet_s2d_t import block_max_pool_t

    y = jnp.asarray(np.random.default_rng(1).standard_normal((2, 6, 5, 48)),
                    jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(block_max_pool_t(y.transpose(0, 1, 3, 2), 4, 3)),
        np.asarray(block_max_pool(y, 4, 3).transpose(0, 1, 3, 2)),
    )


@pytest.mark.parametrize("use_bn", [True, False])
def test_forward_matches_convnet(use_bn):
    ref, t = _models(use_bn)
    x, _ = _data()
    variables = ref.init(jax.random.key(0), x)
    if use_bn:
        lr = ref.apply(variables, x, train=True, mutable=["batch_stats"])
        lt = t.apply(variables, x, train=True, mutable=["batch_stats"])
        out_r, out_t = lr[0], lt[0]
    else:
        out_r = ref.apply(variables, x, train=True)
        out_t = t.apply(variables, x, train=True)
    np.testing.assert_allclose(np.asarray(out_t), np.asarray(out_r),
                               atol=2e-4)
    if use_bn:
        for k in ("bn1", "bn2"):
            for stat in ("mean", "var"):
                np.testing.assert_allclose(
                    np.asarray(lt[1]["batch_stats"][k][stat]),
                    np.asarray(lr[1]["batch_stats"][k][stat]),
                    atol=1e-5, err_msg=f"{k}/{stat}")


def test_eval_mode_uses_running_stats():
    ref, t = _models()
    x, _ = _data()
    variables = ref.init(jax.random.key(0), x)
    np.testing.assert_allclose(
        np.asarray(t.apply(variables, x, train=False)),
        np.asarray(ref.apply(variables, x, train=False)), atol=2e-4)


def test_gradients_match_convnet():
    ref, t = _models()
    x, y = _data()
    variables = ref.init(jax.random.key(0), x)
    params, stats = variables["params"], variables["batch_stats"]

    def loss_fn(model):
        def f(p):
            logits, _ = model.apply(
                {"params": p, "batch_stats": stats}, x, train=True,
                mutable=["batch_stats"],
            )
            return cross_entropy_loss(logits, y)
        return f

    lr, gr = jax.value_and_grad(loss_fn(ref))(params)
    lt, gt = jax.value_and_grad(loss_fn(t))(params)
    np.testing.assert_allclose(lt, lr, atol=1e-5)
    flat_r = jax.tree_util.tree_leaves_with_path(gr)
    flat_t = {jax.tree_util.keystr(k): v
              for k, v in jax.tree_util.tree_leaves_with_path(gt)}
    for k, v in flat_r:
        np.testing.assert_allclose(
            np.asarray(flat_t[jax.tree_util.keystr(k)]), np.asarray(v),
            atol=5e-4, err_msg=jax.tree_util.keystr(k))


def test_fused_tail_matches_unfused_model():
    """ConvNetS2DT(fused_tail=True) == ConvNetS2DT: logits, grads, BN
    running stats with shared init (the production fused chain: conv
    stats fused in-kernel + fused tail pair)."""
    x, y = _data(n=2, hw=32, seed=5)
    plain = ConvNetS2DT()
    fused = ConvNetS2DT(fused_tail=True)
    variables = plain.init(jax.random.key(0), x)
    params, stats = variables["params"], variables["batch_stats"]

    def step(model):
        def f(p):
            logits, upd = model.apply(
                {"params": p, "batch_stats": stats}, x, train=True,
                mutable=["batch_stats"],
            )
            return cross_entropy_loss(logits, y), upd
        (loss, upd), g = jax.value_and_grad(f, has_aux=True)(params)
        return loss, g, upd["batch_stats"]

    lp, gp, sp = step(plain)
    lf, gf, sf = step(fused)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lp), atol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=5e-4), gf, gp)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5), sf, sp)


def test_short_training_runs_stay_together():
    """5 SGD steps from shared init: losses track to float tolerance."""
    ref, t = _models()
    x, y = _data(n=4, hw=32)
    tx = optax.sgd(1e-2)
    variables = ref.init(jax.random.key(0), x)

    def run(model):
        params, stats = variables["params"], variables["batch_stats"]
        opt = tx.init(params)
        losses = []
        for _ in range(5):
            def f(p):
                logits, upd = model.apply(
                    {"params": p, "batch_stats": stats}, x, train=True,
                    mutable=["batch_stats"],
                )
                return cross_entropy_loss(logits, y), upd
            (loss, upd), g = jax.value_and_grad(f, has_aux=True)(params)
            stats = upd["batch_stats"]
            updates, opt = tx.update(g, opt, params)
            params = optax.apply_updates(params, updates)
            losses.append(float(loss))
        return losses

    np.testing.assert_allclose(run(t), run(ref), rtol=1e-4)
