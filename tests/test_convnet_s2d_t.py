"""ConvNetS2DT == ConvNet: the transposed s2d plan is the same function.

Mirror of test_convnet_s2d.py for the [N,H,C,W]-layout plan
(models/convnet_s2d_t.py): identical parameter tree, forward, gradients,
and batch-stats evolution as the reference-parity ConvNet, with and
without the fused tail pair."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tpu_sandbox.models import ConvNet
from tpu_sandbox.models.convnet_s2d_t import ConvNetS2DT
from tpu_sandbox.ops.losses import cross_entropy_loss


def _models(use_bn=True, dtype=jnp.float32, **kw):
    return (ConvNet(use_bn=use_bn, dtype=dtype),
            ConvNetS2DT(use_bn=use_bn, dtype=dtype, **kw))


def _data(n=3, hw=48, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n, hw, hw, 1)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, size=(n,)), jnp.int32)
    return x, y


def test_param_trees_compatible():
    ref, t = _models()
    x, _ = _data()
    vr = ref.init(jax.random.key(0), x)
    vt = t.init(jax.random.key(0), x)
    assert jax.tree.map(jnp.shape, vr) == jax.tree.map(jnp.shape, vt)


def test_space_to_depth_t_is_transposed_space_to_depth():
    from tpu_sandbox.models.convnet_s2d import space_to_depth
    from tpu_sandbox.models.convnet_s2d_t import space_to_depth_t

    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 16, 12)),
                    jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(space_to_depth_t(x, 4)),
        np.asarray(space_to_depth(x, 4).transpose(0, 1, 3, 2)),
    )


def test_block_max_pool_t_is_transposed_block_max_pool():
    from tpu_sandbox.models.convnet_s2d import block_max_pool
    from tpu_sandbox.models.convnet_s2d_t import block_max_pool_t

    y = jnp.asarray(np.random.default_rng(1).standard_normal((2, 6, 5, 48)),
                    jnp.float32)
    np.testing.assert_array_equal(
        np.asarray(block_max_pool_t(y.transpose(0, 1, 3, 2), 4, 3)),
        np.asarray(block_max_pool(y, 4, 3).transpose(0, 1, 3, 2)),
    )


@pytest.mark.parametrize("use_bn", [True, False])
def test_forward_matches_convnet(use_bn):
    ref, t = _models(use_bn)
    x, _ = _data()
    variables = ref.init(jax.random.key(0), x)
    if use_bn:
        lr = ref.apply(variables, x, train=True, mutable=["batch_stats"])
        lt = t.apply(variables, x, train=True, mutable=["batch_stats"])
        out_r, out_t = lr[0], lt[0]
    else:
        out_r = ref.apply(variables, x, train=True)
        out_t = t.apply(variables, x, train=True)
    np.testing.assert_allclose(np.asarray(out_t), np.asarray(out_r),
                               atol=2e-4)
    if use_bn:
        for k in ("bn1", "bn2"):
            for stat in ("mean", "var"):
                np.testing.assert_allclose(
                    np.asarray(lt[1]["batch_stats"][k][stat]),
                    np.asarray(lr[1]["batch_stats"][k][stat]),
                    atol=1e-5, err_msg=f"{k}/{stat}")


def test_eval_mode_uses_running_stats():
    ref, t = _models()
    x, _ = _data()
    variables = ref.init(jax.random.key(0), x)
    np.testing.assert_allclose(
        np.asarray(t.apply(variables, x, train=False)),
        np.asarray(ref.apply(variables, x, train=False)), atol=2e-4)


@pytest.mark.slow  # tier-1 keeps the test_convnet_s2d.py twin
def test_gradients_match_convnet():
    ref, t = _models()
    x, y = _data()
    variables = ref.init(jax.random.key(0), x)
    params, stats = variables["params"], variables["batch_stats"]

    def loss_fn(model):
        def f(p):
            logits, _ = model.apply(
                {"params": p, "batch_stats": stats}, x, train=True,
                mutable=["batch_stats"],
            )
            return cross_entropy_loss(logits, y)
        return f

    lr, gr = jax.value_and_grad(loss_fn(ref))(params)
    lt, gt = jax.value_and_grad(loss_fn(t))(params)
    np.testing.assert_allclose(lt, lr, atol=1e-5)
    flat_r = jax.tree_util.tree_leaves_with_path(gr)
    flat_t = {jax.tree_util.keystr(k): v
              for k, v in jax.tree_util.tree_leaves_with_path(gt)}
    for k, v in flat_r:
        np.testing.assert_allclose(
            np.asarray(flat_t[jax.tree_util.keystr(k)]), np.asarray(v),
            atol=5e-4, err_msg=jax.tree_util.keystr(k))


@pytest.mark.slow  # tier-1 keeps the test_convnet_s2d.py twin
def test_fused_tail_matches_unfused_model():
    """ConvNetS2DT(fused_tail=True) == ConvNetS2DT: logits, grads, BN
    running stats with shared init (the production fused chain: conv
    stats fused in-kernel + fused tail pair)."""
    x, y = _data(n=2, hw=32, seed=5)
    plain = ConvNetS2DT()
    fused = ConvNetS2DT(fused_tail=True)
    variables = plain.init(jax.random.key(0), x)
    params, stats = variables["params"], variables["batch_stats"]

    def step(model):
        # jit'd like the production trainer; also ~2x faster than eager
        # op-by-op dispatch on the CPU backend
        @jax.jit
        def go(p):
            def f(p):
                logits, upd = model.apply(
                    {"params": p, "batch_stats": stats}, x, train=True,
                    mutable=["batch_stats"],
                )
                return cross_entropy_loss(logits, y), upd
            (loss, upd), g = jax.value_and_grad(f, has_aux=True)(p)
            return loss, g, upd["batch_stats"]
        return go(params)

    lp, gp, sp = step(plain)
    lf, gf, sf = step(fused)
    np.testing.assert_allclose(np.asarray(lf), np.asarray(lp), atol=1e-5)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=5e-4), gf, gp)
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5), sf, sp)


@pytest.mark.slow  # tier-1 keeps the test_convnet_s2d.py twin
def test_short_training_runs_stay_together():
    """5 SGD steps from shared init: losses track to float tolerance."""
    ref, t = _models()
    x, y = _data(n=4, hw=32)
    tx = optax.sgd(1e-2)
    variables = ref.init(jax.random.key(0), x)

    def run(model):
        # one jit'd SGD step, like the production trainer (and ~5x faster
        # than eager op-by-op dispatch on the CPU backend)
        @jax.jit
        def one(params, stats, opt):
            def f(p):
                logits, upd = model.apply(
                    {"params": p, "batch_stats": stats}, x, train=True,
                    mutable=["batch_stats"],
                )
                return cross_entropy_loss(logits, y), upd
            (loss, upd), g = jax.value_and_grad(f, has_aux=True)(params)
            updates, opt = tx.update(g, opt, params)
            return (optax.apply_updates(params, updates),
                    upd["batch_stats"], opt, loss)

        params, stats = variables["params"], variables["batch_stats"]
        opt = tx.init(params)
        losses = []
        for _ in range(5):
            params, stats, opt, loss = one(params, stats, opt)
            losses.append(float(loss))
        return losses

    # Five compounding SGD steps amplify the one-ULP conv/reduction
    # differences between the two plans; CPU XLA's conv reassociation makes
    # the drift land near 1e-4 (observed max ~1.09e-4 eager, ~3.3e-6 under
    # jit, ROADMAP "known flake"). Keep the tight bound on TPU, where both
    # plans lower to the same MXU convs.
    rtol = 1e-4 if jax.default_backend() == "tpu" else 1e-3
    np.testing.assert_allclose(run(t), run(ref), rtol=rtol)


@pytest.mark.slow  # tier-1 keeps test_data_parallel's fused-input parity
def test_fused_input_stage_matches_resize_plus_s2d():
    """fused_input_stage == resize_on_device + space_to_depth_t exactly
    (same bilinear weights via the resize-of-identity matrix): the
    production input path must be THE resize the other plans run, not an
    approximation of it."""
    from tpu_sandbox.models.convnet_s2d_t import space_to_depth_t
    from tpu_sandbox.train import resize_on_device

    rng = np.random.default_rng(0)
    x28 = jnp.asarray(rng.standard_normal((3, 28, 28, 1)), jnp.float32)
    m = ConvNetS2DT(dtype=jnp.float32)
    fused = m.fused_input_stage(x28, (96, 96))
    ref = space_to_depth_t(resize_on_device(x28, (96, 96))[..., 0], 4)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref),
                               atol=2e-5)
    # and the model consumes the pre-s2d tensor identically
    variables = m.init(jax.random.key(0), resize_on_device(x28, (96, 96)))
    out_full = m.apply(variables, resize_on_device(x28, (96, 96)),
                       train=False)
    out_pre = m.apply(variables, fused, train=False)
    np.testing.assert_allclose(np.asarray(out_pre), np.asarray(out_full),
                               atol=2e-4)


def test_prepare_inputs_dispatch():
    """prepare_inputs: fused stage for models that declare one (pre-s2d
    output shape), plain resize for everything else."""
    from tpu_sandbox.train import prepare_inputs

    x28 = jnp.zeros((2, 28, 28, 1), jnp.float32)
    assert prepare_inputs(ConvNetS2DT(), x28, (64, 64)).shape == (2, 16, 16, 16)
    assert prepare_inputs(ConvNet(), x28, (64, 64)).shape == (2, 64, 64, 1)


def test_checkpoint_refuses_pre_canonical_layout(tmp_path):
    """Checkpoints carry the fc row-order stamp; a directory without it
    (or with a different one) is refused loudly — same-shaped fc kernels
    with permuted rows must not restore silently."""
    import optax

    from tpu_sandbox.train import TrainState, checkpoint

    model = ConvNet()
    x = jnp.zeros((1, 16, 16, 1), jnp.float32)
    state = TrainState.create(model, jax.random.key(0), x, optax.sgd(0.1))
    d = tmp_path / "ck"
    checkpoint.save(d, state, 0)
    assert (d / "LAYOUT").read_text().strip() == "fc-row-order=hcw"
    restored = checkpoint.restore(d, state)
    np.testing.assert_array_equal(
        np.asarray(restored.params["fc"]["kernel"]),
        np.asarray(state.params["fc"]["kernel"]))
    (d / "LAYOUT").unlink()  # simulate a pre-stamp checkpoint
    with pytest.raises(ValueError, match="layout mismatch"):
        checkpoint.restore(d, state)


@pytest.mark.slow  # wide-row rerun of the equality tier-1 still pins
def test_equality_at_production_row_width_bf16():
    """VERDICT r03 weak-3: the 48x48 fp32 equality proves nothing about
    750-wide rows in bf16 (the production geometry at image 3000). This
    pins s2dt == plain at H=16, W=3000 — the exact 750-lane row width —
    in bf16, with tolerances ~3x the measured deviation (logits rel
    2.2e-3, loss 2.5e-3; fp32 at this width measures 4.4e-7 — pure bf16
    rounding, not a layout defect)."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((2, 16, 3000, 1)), jnp.bfloat16)
    yl = jnp.asarray(rng.integers(0, 10, size=(2,)), jnp.int32)
    ref = ConvNet(dtype=jnp.bfloat16)
    t = ConvNetS2DT(dtype=jnp.bfloat16, fused_tail=True)
    variables = ref.init(jax.random.key(0), x)
    params, stats = variables["params"], variables["batch_stats"]

    def run(model):
        def f(p):
            logits, _ = model.apply(
                {"params": p, "batch_stats": stats}, x, train=True,
                mutable=["batch_stats"])
            return cross_entropy_loss(logits, yl), logits
        (loss, logits), g = jax.value_and_grad(f, has_aux=True)(params)
        return loss, logits, g

    l_r, lo_r, g_r = run(ref)
    l_t, lo_t, g_t = run(t)
    assert abs(float(l_r) - float(l_t)) < 8e-3
    scale = float(np.max(np.abs(np.asarray(lo_r, np.float32))))
    dev = float(np.max(np.abs(np.asarray(lo_r, np.float32)
                              - np.asarray(lo_t, np.float32))))
    assert dev / scale < 8e-3, (dev, scale)
    # fc grads carry ~all the signal at this depth; conv-bias grads are
    # near-zero under BN so only relative-to-scale checks make sense
    fr = np.asarray(g_r["fc"]["kernel"], np.float32)
    ft = np.asarray(g_t["fc"]["kernel"], np.float32)
    assert np.max(np.abs(fr - ft)) / (np.max(np.abs(fr)) or 1.0) < 0.05


@pytest.mark.slow  # grads stay pinned by test_pallas_conv1_tail_t tier-1
def test_fused_conv1_bwd_matches_unfused_model():
    """r05 backward fusion A/B at the model level: ConvNetS2DT with
    fused_conv1_bwd True vs False — same loss, same grads (the fused
    path never materializes conv1's cotangent; dcbias excluded from
    tight comparison, it is analytically ~0 under BN and pure
    summation noise in both paths)."""
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((2, 32, 32, 1)), jnp.float32)
    yl = jnp.asarray(rng.integers(0, 10, size=(2,)), jnp.int32)
    ref = ConvNetS2DT(features=(8, 8), fused_tail=True,
                      fused_conv1_bwd=False)
    fused = ConvNetS2DT(features=(8, 8), fused_tail=True)
    variables = ref.init(jax.random.key(0), x)
    params, stats = variables["params"], variables["batch_stats"]

    def run(model):
        # jit'd like the production trainer; also ~2x faster than eager
        # op-by-op dispatch on the CPU backend
        @jax.jit
        def go(p):
            def f(p):
                logits, mut = model.apply(
                    {"params": p, "batch_stats": stats}, x, train=True,
                    mutable=["batch_stats"])
                return cross_entropy_loss(logits, yl), mut["batch_stats"]
            (loss, new_stats), g = jax.value_and_grad(f, has_aux=True)(p)
            return loss, new_stats, g
        return go(params)

    l_r, st_r, g_r = run(ref)
    l_f, st_f, g_f = run(fused)
    assert abs(float(l_r) - float(l_f)) < 1e-5
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), atol=1e-5), st_r, st_f)
    for path in (("conv1", "kernel"), ("bn1", "scale"), ("bn1", "bias"),
                 ("conv2", "kernel"), ("fc", "kernel")):
        a = np.asarray(g_f[path[0]][path[1]], np.float32)
        b = np.asarray(g_r[path[0]][path[1]], np.float32)
        scale = float(np.max(np.abs(b))) or 1.0
        assert float(np.max(np.abs(a - b))) / scale < 3e-5, path
