"""Gateway front door, fast and in-process (tier-1).

Everything here runs the real gateway/replica/client code paths over real
localhost sockets with a *stub* decode step (next token = last + 1 mod
vocab, the test_serve_slo.py pattern) — no jax compiles, so the whole
file stays inside the tier-1 budget. Four layers get covered:

- routing + admission as pure functions (no sockets, hand-built views);
- the resident-prefix digest satellite at the allocator level (digest
  shrinks the moment eviction drops an entry — no stale advertisements);
- the wire protocol, adversarially: truncated/oversized/malformed frames
  and auth failures close the one connection without wedging the accept
  loop or leaking a request;
- the gateway end to end: prefix routing, door sheds with claim-once
  verdicts, retry/hedge through the socket, multi-fleet isolation, and
  the targeted-queue ownership rules (including the tail-bump/set race:
  an owner never skips a not-yet-visible entry).

Real subprocess replicas and the full bench CLI live in the slow-marked
test_gateway_integration.py.
"""

import contextlib
import json
import os
import socket
import struct
import threading
import time

import pytest

from tpu_sandbox.gateway import routing, wire
from tpu_sandbox.gateway.fleet import (FleetSpec, fleet_kv, fleet_namespace)
from tpu_sandbox.gateway.server import (Gateway, k_gateway_hb,
                                        live_gateway_endpoints,
                                        live_gateways)
from tpu_sandbox.gateway.client import (GatewayAuthError, GatewayClient,
                                        GatewayError, RetriesExhausted)
from tpu_sandbox.models.transformer import TransformerConfig
from tpu_sandbox.serve.cache import (CacheConfig, PagedKVCache, chain_digest)
from tpu_sandbox.serve.engine import ContinuousEngine, Request, ServeConfig

import numpy as np

MCFG = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                         d_ff=64, max_len=128)
CCFG = CacheConfig(num_blocks=24, block_size=4, max_blocks_per_seq=8)
BLOCK = CCFG.block_size


class _StubStep:
    """DecodeStep stand-in: next token = (last + 1) % vocab, no jax."""

    def __init__(self, buckets=(8, 16), vocab=64):
        self.buckets = tuple(buckets)
        self.vocab = vocab
        self.prefill = {b: self._prefill for b in self.buckets}

    def pick_bucket(self, plen):
        for b in self.buckets:
            if plen <= b:
                return b
        raise ValueError(f"prompt of {plen} exceeds buckets {self.buckets}")

    def _prefill(self, params, k, v, toks, dest, last):
        toks = np.asarray(toks)
        logits = np.zeros((self.vocab,), np.float32)
        logits[(int(toks[0, int(last)]) + 1) % self.vocab] = 1.0
        return logits, k, v

    def decode(self, params, k, v, tokens, lengths, tables):
        tokens = np.asarray(tokens)
        logits = np.zeros((tokens.shape[0], self.vocab), np.float32)
        for i in range(tokens.shape[0]):
            logits[i, (int(tokens[i, 0]) + 1) % self.vocab] = 1.0
        return logits, k, v


def _view(tag, *, depth_chain=(), **kw):
    kw.setdefault("digest", frozenset(depth_chain))
    return routing.ReplicaView(tag=tag, **kw)


# -- routing + admission: pure functions --------------------------------------


def test_match_depth_deepest_hash_alone_decides():
    chain = chain_digest(list(range(1, 13)), BLOCK)  # 3 full blocks
    assert len(chain) == 3
    assert routing.match_depth(chain, _view("a", depth_chain=chain)) == 3
    # deepest member decides even when shallower links were evicted
    assert routing.match_depth(chain, _view("a", depth_chain=[chain[2]])) == 3
    assert routing.match_depth(chain, _view("a", depth_chain=[chain[0]])) == 1
    assert routing.match_depth(chain, _view("a")) == 0
    assert routing.match_depth([], _view("a", depth_chain=chain)) == 0


def test_choose_prefers_depth_then_load_then_tag():
    chain = chain_digest(list(range(1, 13)), BLOCK)
    shallow_idle = _view("a", depth_chain=[chain[0]], queue_depth=0)
    deep_busy = _view("b", depth_chain=chain, queue_depth=5)
    v, d = routing.choose(chain, [shallow_idle, deep_busy])
    assert (v.tag, d) == ("b", 3)  # depth beats load
    # equal depth: less load wins
    deep_idle = _view("c", depth_chain=chain, queue_depth=1)
    v, d = routing.choose(chain, [deep_busy, deep_idle])
    assert (v.tag, d) == ("c", 3)
    # no residency anywhere: least-loaded fallback at depth 0
    v, d = routing.choose(chain, [_view("x", queue_depth=3),
                                  _view("y", queue_depth=1)])
    assert (v.tag, d) == ("y", 0)
    # exclusion removes the winner (the hedge path's contract)
    v, d = routing.choose(chain, [shallow_idle, deep_busy],
                          exclude=frozenset({"b"}))
    assert (v.tag, d) == ("a", 1)
    assert routing.choose(chain, [deep_busy],
                          exclude=frozenset({"b"})) is None
    assert routing.choose(chain, []) is None


def test_fresh_drops_stale_reports():
    views = [_view("a", age_s=0.1), _view("b", age_s=9.0)]
    assert [v.tag for v in routing.fresh(views, 5.0)] == ["a"]
    assert routing.fresh(views, 0.01) == []


def test_admission_modes():
    v = _view("a", queue_depth=4, active=1, pending_local=2)  # load 7
    # feasible: (load+1)/rate vs deadline
    ok, reason, est = routing.admit(v, mode="feasible", service_rate_rps=2.0,
                                    deadline_s=10.0, occupancy_bound=8)
    assert ok and reason == "" and est == pytest.approx(4.0)
    ok, reason, _ = routing.admit(v, mode="feasible", service_rate_rps=2.0,
                                  deadline_s=1.0, occupancy_bound=8)
    assert not ok and reason == "infeasible"
    # no deadline: nothing to miss
    ok, _, _ = routing.admit(v, mode="feasible", service_rate_rps=0.001,
                             deadline_s=None, occupancy_bound=8)
    assert ok
    # occupancy: queue_depth + pending_local vs bound, deadline ignored
    ok, reason, _ = routing.admit(v, mode="occupancy", service_rate_rps=2.0,
                                  deadline_s=0.0, occupancy_bound=7)
    assert ok
    ok, reason, _ = routing.admit(v, mode="occupancy", service_rate_rps=2.0,
                                  deadline_s=None, occupancy_bound=6)
    assert not ok and reason == "queue_full"
    ok, _, _ = routing.admit(v, mode="none", service_rate_rps=2.0,
                             deadline_s=-1.0, occupancy_bound=0)
    assert ok
    with pytest.raises(ValueError):
        routing.admit(v, mode="lottery", service_rate_rps=2.0,
                      deadline_s=None, occupancy_bound=8)
    with pytest.raises(ValueError):
        routing.estimate_completion_s(v, 0.0)


def test_parse_report_degrades_missing_fields():
    v = routing.parse_report("w0", {}, age_s=1.5)
    assert v.tag == "w0" and v.load == 0 and v.digest == frozenset()
    assert v.age_s == 1.5 and v.max_batch == 1
    full = routing.parse_report(
        "w1", {"queue_depth": 2, "active": 1, "prefix_digest": ["ab", "cd"]},
        age_s=0.0, pending_local=3)
    assert full.load == 6 and full.digest == frozenset({"ab", "cd"})


# -- resident-prefix digest under eviction (satellite) ------------------------


def test_resident_digest_drops_with_eviction_and_stays_bounded():
    cache = PagedKVCache(CacheConfig(num_blocks=6, block_size=4,
                                     max_blocks_per_seq=4))
    old = list(range(1, 9))       # 2 full blocks
    a = cache.alloc(old, 0)
    cache.free(a, cache_prefix=True)
    assert cache.resident_prefix_digest() == chain_digest(old, 4)
    # allocating a disjoint prompt under block pressure evicts FIFO: the
    # old chain's entries leave the digest the moment they leave the cache
    new = list(range(100, 108))
    b = cache.alloc(new, 8)       # needs 4 blocks; only 3 remain free
    cache.free(b, cache_prefix=True)
    evicted = cache.stats["evicted_cache_blocks"]
    assert evicted >= 1
    resident = cache.resident_prefix_digest()
    assert len(resident) == len(cache._prefix)
    gone = [h for h in chain_digest(old, 4) if h not in resident]
    assert len(gone) == evicted  # no stale advertisements
    # bounded: top_k keeps the NEWEST entries (the ones surviving longest)
    top1 = cache.resident_prefix_digest(top_k=1)
    assert len(top1) == 1 and top1[0] == resident[-1]


def test_engine_load_report_carries_digest():
    eng = ContinuousEngine(
        None, ServeConfig(model=MCFG, cache=CCFG, max_batch=2,
                          buckets=(8, 16)),
        step=_StubStep(), clock=time.monotonic)
    prompt = list(range(1, 9))
    eng.submit(Request(rid="r0", prompt=prompt, max_new_tokens=2))
    eng.run_until_idle()
    rep = eng.load_report()
    assert set(chain_digest(prompt, BLOCK)) <= set(rep["prefix_digest"])


# -- wire protocol units ------------------------------------------------------


def test_frame_roundtrip_and_hostile_lengths():
    frame = wire.pack_frame(wire.OP_SUBMIT, wire.encode_body({"rid": "r"}))
    op, length = wire.parse_header(frame[:5])
    assert op == wire.OP_SUBMIT and length == len(frame) - 5
    assert wire.decode_body(frame[5:]) == {"rid": "r"}
    with pytest.raises(wire.ProtocolError):
        wire.pack_frame(wire.OP_SUBMIT, b"x" * (wire.MAX_FRAME + 1))
    # a hostile 4 GB length prefix dies at the header, before allocation
    with pytest.raises(wire.ProtocolError):
        wire.parse_header(struct.pack("!BI", wire.OP_SUBMIT, 1 << 31))
    with pytest.raises(wire.ProtocolError):
        wire.parse_header(b"\x01\x02")  # short header
    with pytest.raises(wire.ProtocolError):
        wire.decode_body(b"not json")
    with pytest.raises(wire.ProtocolError):
        wire.decode_body(b"[1, 2]")  # JSON but not an object


# -- gateway end to end (stub replicas, real sockets) -------------------------


def _engine(**over):
    cfg = ServeConfig(model=MCFG, cache=CCFG, max_batch=2, buckets=(8, 16),
                      **over)
    return ContinuousEngine(None, cfg, step=_StubStep(), clock=time.monotonic)


@pytest.fixture
def kv_pair():
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    server = KVServer()
    kv = KVClient(port=server.port)
    clones = []

    def clone():
        c = kv.clone()
        clones.append(c)
        return c

    yield server, kv, clone
    for c in clones:
        c.close()
    kv.close()
    server.stop()


def _worker(kv, **over):
    from tpu_sandbox.serve.replica import ReplicaWorker

    over.setdefault("lease_ttl", 1.0)
    over.setdefault("load_interval", 0.02)
    return ReplicaWorker(kv, _engine(), **over)


@contextlib.contextmanager
def _pumping(*workers):
    """Tick workers from one background thread (each worker was built on
    its own KV clone, so the main thread's client stays unshared)."""
    stop = threading.Event()

    def run():
        while not stop.is_set():
            for w in workers:
                w.tick()
            time.sleep(0.001)

    t = threading.Thread(target=run, name="pump", daemon=True)
    t.start()
    try:
        yield stop
    finally:
        stop.set()
        t.join(timeout=10.0)


def _gateway(kv, **over):
    over.setdefault("fleets", [FleetSpec(block_size=BLOCK)])
    over.setdefault("refresh_min_s", 0.005)
    return Gateway(kv, over.pop("fleets"), **over).start()


def _fake_report(kv, tag, *, digest=(), queue_depth=0, ttl=30.0):
    from tpu_sandbox.serve.replica import k_load

    kv.set_ttl(k_load(tag), json.dumps({
        "queue_depth": queue_depth, "active": 0, "max_batch": 2,
        "free_block_frac": 1.0, "prefix_digest": list(digest)}), ttl)


def _wait_for_report(kv, tag, timeout=10.0):
    from tpu_sandbox.serve.replica import read_load_reports

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if tag in read_load_reports(kv):
            return
        time.sleep(0.005)
    raise AssertionError(f"no load report from {tag}")


def test_gateway_serves_end_to_end_over_socket(kv_pair):
    _, kv, clone = kv_pair
    w = _worker(clone(), tag="w0")
    with _gateway(kv) as gw, _pumping(w):
        _wait_for_report(kv, "w0")
        with GatewayClient(gw.port) as client:
            assert client.submit("r0", [1, 2, 3], 3) is True
            got = client.result("r0", timeout=30.0)
            assert got["verdict"] == "ok" and got["tokens"] == [4, 5, 6]
            assert client.try_result("r0")["tokens"] == [4, 5, 6]
            stats = client.gateway_stats()
    assert stats["stats"]["requests"] == 1
    assert stats["stats"]["admitted"] == 1
    assert gw.stats.shed_door == 0
    assert client.stats.completed == 1


def test_routes_to_deepest_prefix_replica(kv_pair):
    from tpu_sandbox.serve.replica import k_tq

    _, kv, _ = kv_pair
    prompt = list(range(1, 13))
    chain = chain_digest(prompt, BLOCK)
    _fake_report(kv, "shallow", digest=chain[:1])
    _fake_report(kv, "deep", digest=[chain[2]], queue_depth=3)
    with _gateway(kv) as gw:
        s = socket.create_connection(("127.0.0.1", gw.port), timeout=5)
        try:
            wire.send_frame(s, wire.OP_SUBMIT, {
                "rid": "r0", "prompt": prompt, "max_new_tokens": 2})
            status, resp = wire.recv_response(s)
            assert status == wire.ST_OK
            # busier but deeper wins; the targeted queue got the entry
            assert resp == {"admitted": True, "replica": "deep", "depth": 3,
                            "estimate_s": resp["estimate_s"],
                            "routed": "prefix"}
            assert kv.get(k_tq("deep", 0)) == b"r0"
            # nothing resident: least-loaded fallback ("shallow" is idle)
            wire.send_frame(s, wire.OP_SUBMIT, {
                "rid": "r1", "prompt": [50, 51, 52, 53, 54],
                "max_new_tokens": 2})
            status, resp = wire.recv_response(s)
            assert resp["replica"] == "shallow" and resp["routed"] == "balance"
        finally:
            s.close()
        assert gw.stats.routed_prefix == 1 and gw.stats.routed_balance == 1


def test_door_shed_writes_claim_once_verdict(kv_pair):
    from tpu_sandbox.serve.replica import k_done, k_result

    _, kv, _ = kv_pair
    # a fleet calibrated at 1 rps with 100 queued: ~101 s to completion
    _fake_report(kv, "busy", queue_depth=100)
    fleets = [FleetSpec(block_size=BLOCK, service_rate_rps=1.0)]
    with _gateway(kv, fleets=fleets) as gw:
        with GatewayClient(gw.port, deadline_s=1.0, max_retries=0) as client:
            assert client.submit("r0", [1, 2, 3], 2) is False
            with pytest.raises(RetriesExhausted) as ei:
                client.result("r0", timeout=10.0)
    got = ei.value.verdict
    assert got["verdict"] == "SHED" and got["reason"] == "door:infeasible"
    assert got["replica"] == "gateway"
    assert ei.value.last_reason == "door:infeasible"
    assert len(ei.value.attempts) == 1
    assert ei.value.attempts[0]["shed_reason"] == "door:infeasible"
    assert kv.get(k_done("r0")) is not None
    assert json.loads(kv.get(k_result("r0")))["verdict"] == "SHED"
    assert gw.stats.shed_door == 1 and gw.stats.admitted == 0
    assert client.stats.shed == 1


def test_no_fresh_reports_falls_back_to_shared_queue(kv_pair):
    from tpu_sandbox.serve.replica import k_queue

    _, kv, clone = kv_pair
    with _gateway(kv) as gw:
        with GatewayClient(gw.port) as client:
            # fleet warming up: nobody has reported, yet the door admits
            assert client.submit("r0", [1, 2, 3], 3) is True
            assert kv.get(k_queue(0)) == b"r0"
            assert gw.stats.routed_shared == 1
            w = _worker(clone(), tag="late")
            with _pumping(w):
                got = client.result("r0", timeout=30.0)
    assert got["verdict"] == "ok" and got["tokens"] == [4, 5, 6]


def test_client_retries_shed_through_gateway(kv_pair):
    _, kv, clone = kv_pair
    w = _worker(clone(), tag="w0")
    storm = _worker(clone(), tag="storm")
    # a deadline-carrying submit against zero fresh reports would now
    # fast-fail at the door (door:no_replicas); this test is about the
    # retry path, so give routing a live view of w0 up front
    _fake_report(kv, "w0")
    with _gateway(kv) as gw:
        with GatewayClient(gw.port, deadline_s=30.0,
                           max_retries=2) as client:
            assert client.submit("r0", [1, 2, 3], 3) is True
            # one replica sheds it; the retry reroutes and succeeds
            storm._publish_verdict("r0", {
                "rid": "r0", "verdict": "SHED", "reason": "fault:shed_storm",
                "replica": "storm"})
            with _pumping(w):
                got = client.result("r0", timeout=30.0)
    assert got["verdict"] == "ok" and got["tokens"] == [4, 5, 6]
    assert client.stats.retries == 1
    assert gw.stats.clears == 1
    storm.engine.drain_to_requests()


def test_hedge_reroutes_away_from_first_replica(kv_pair):
    _, kv, clone = kv_pair
    prompt = list(range(1, 9))
    chain = chain_digest(prompt, BLOCK)
    # "ghost" advertises the whole chain but will never claim anything
    _fake_report(kv, "ghost", digest=chain)
    w = _worker(clone(), tag="w1")
    with _gateway(kv) as gw, _pumping(w):
        _wait_for_report(kv, "w1")
        with GatewayClient(gw.port, hedge_after=0.05) as client:
            assert client.submit("r0", prompt, 3) is True
            got = client.result("r0", timeout=30.0)
    assert got["verdict"] == "ok" and got["replica"] == "w1"
    assert client.stats.hedges == 1
    assert gw.stats.hedges == 1


def test_multi_fleet_isolation(kv_pair):
    _, kv, clone = kv_pair
    fleets = [FleetSpec(name="chat", block_size=BLOCK),
              FleetSpec(name="code", block_size=BLOCK)]
    wa = _worker(fleet_kv(clone(), "chat"), tag="wa")
    wb = _worker(fleet_kv(clone(), "code"), tag="wb")
    with _gateway(kv, fleets=fleets) as gw, _pumping(wa, wb):
        _wait_for_report(fleet_kv(kv, "chat"), "wa")
        _wait_for_report(fleet_kv(kv, "code"), "wb")
        with GatewayClient(gw.port, fleet="chat") as ca, \
                GatewayClient(gw.port, fleet="code") as cb:
            # the SAME rid lives independently in each fleet's namespace
            assert ca.submit("r0", [1, 2, 3], 2)
            assert cb.submit("r0", [1, 2, 3], 4)
            got_a = ca.result("r0", timeout=30.0)
            got_b = cb.result("r0", timeout=30.0)
            with pytest.raises(GatewayError, match="unknown fleet"), \
                    GatewayClient(gw.port, fleet="nope") as cx:
                cx.submit("r0", [1], 1)
    assert got_a["tokens"] == [4, 5]
    assert got_b["tokens"] == [4, 5, 6, 7]
    assert kv.try_get("fleet/chat/serve/result/r0") is not None
    assert kv.try_get("fleet/code/serve/result/r0") is not None
    assert kv.try_get("serve/result/r0") is None  # nothing leaked to bare


def test_fleet_namespace_and_spec_validation(kv_pair):
    from tpu_sandbox.runtime.kvstore import NamespacedKV

    _, kv, _ = kv_pair
    assert fleet_namespace("") == ""
    assert fleet_namespace("chat") == "fleet/chat/"
    for bad in ("a/b", "a b", "a\tb", "a\nb"):
        with pytest.raises(ValueError):
            fleet_namespace(bad)
    assert fleet_kv(kv, "") is kv
    with pytest.raises(ValueError, match="nest"):
        fleet_kv(fleet_kv(kv, "a"), "b")
    with pytest.raises(ValueError):
        FleetSpec(name="a/b")
    specs = FleetSpec(name="chat", share=2.0, priority=1,
                      replica_args=["--config", "cfg.json"]).replica_job_specs(
        replicas=2, base_priority=10)
    assert [s.job_id for s in specs] == ["serve-chat-0", "serve-chat-1"]
    assert all(s.tenant == "fleet-chat" and s.share == 2.0 and
               s.priority == 11 and
               s.env["TPU_SANDBOX_FLEET"] == "chat" for s in specs)
    with pytest.raises(ValueError, match="duplicate fleet"):
        Gateway(kv, [FleetSpec(name="x"), FleetSpec(name="x")])
    with pytest.raises(ValueError, match="admission"):
        Gateway(kv, None, admission="vibes")


# -- adversarial wire behavior against a live gateway -------------------------


@pytest.fixture
def gw(kv_pair):
    _, kv, _ = kv_pair
    g = _gateway(kv)
    yield g
    g.close()


def _raw(gw_):
    return socket.create_connection(("127.0.0.1", gw_.port), timeout=5)


def _closed_by_peer(s, timeout=5.0):
    s.settimeout(timeout)
    try:
        return s.recv(1) == b""
    except (ConnectionError, OSError):
        return True


def _wait_stat(gw_, attr, want, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if getattr(gw_.stats, attr) >= want:
            return
        time.sleep(0.005)
    raise AssertionError(f"{attr} stuck at {getattr(gw_.stats, attr)}")


def test_oversized_length_prefix_closes_connection(gw):
    s = _raw(gw)
    s.sendall(struct.pack("!BI", wire.OP_SUBMIT, 1 << 31))
    assert _closed_by_peer(s)
    s.close()
    _wait_stat(gw, "protocol_errors", 1)
    # the accept loop survived: a well-behaved client still gets served
    with GatewayClient(gw.port) as c:
        assert c.gateway_stats()["stats"]["protocol_errors"] == 1


def test_truncated_frame_is_protocol_error_not_wedge(gw):
    s = _raw(gw)
    s.sendall(struct.pack("!BI", wire.OP_SUBMIT, 100) + b"x" * 10)
    s.shutdown(socket.SHUT_WR)  # EOF mid-frame
    assert _closed_by_peer(s)
    s.close()
    _wait_stat(gw, "protocol_errors", 1)
    with GatewayClient(gw.port) as c:
        assert c.gateway_stats()["stats"]["connections"] >= 2


def test_malformed_json_and_unknown_op_close_connection(gw):
    s = _raw(gw)
    s.sendall(wire.pack_frame(wire.OP_SUBMIT, b"not json"))
    assert _closed_by_peer(s)
    s.close()
    s = _raw(gw)
    s.sendall(wire.pack_frame(ord("Z"), wire.encode_body({})))
    assert _closed_by_peer(s)
    s.close()
    _wait_stat(gw, "protocol_errors", 2)


def test_clean_eof_between_frames_is_not_an_error(gw):
    s = _raw(gw)
    wire.send_frame(s, wire.OP_STATS, {})
    status, _ = wire.recv_response(s)
    assert status == wire.ST_OK
    s.close()  # mid-conversation hangup, but between frames
    _wait_stat(gw, "connections", 1)
    deadline = time.monotonic() + 1.0
    while time.monotonic() < deadline and gw.stats.protocol_errors == 0:
        time.sleep(0.01)
    assert gw.stats.protocol_errors == 0


def test_malformed_body_fails_request_not_connection(gw):
    s = _raw(gw)
    wire.send_frame(s, wire.OP_SUBMIT, {"prompt": [1]})  # no rid
    status, resp = wire.recv_response(s)
    assert status == wire.ST_ERR and "KeyError" in resp["error"]
    # the framing was fine, so the conversation continues
    wire.send_frame(s, wire.OP_STATS, {})
    status, _ = wire.recv_response(s)
    assert status == wire.ST_OK
    s.close()


def test_auth_gate(kv_pair):
    _, kv, _ = kv_pair
    with _gateway(kv, token="sesame") as g:
        with GatewayClient(g.port, token="sesame") as c:
            assert c.gateway_stats()["stats"]["auth_failures"] == 0
        with pytest.raises(GatewayAuthError):
            GatewayClient(g.port, token="wrong")
        # any op before hello is an auth failure, even a well-formed one
        s = _raw(g)
        wire.send_frame(s, wire.OP_STATS, {})
        status, _ = wire.recv_response(s)
        assert status == wire.ST_AUTH
        assert _closed_by_peer(s)
        s.close()
        assert g.stats.auth_failures == 2


def test_mid_request_disconnect_strands_nothing(kv_pair):
    _, kv, clone = kv_pair
    w = _worker(clone(), tag="w0")
    with _gateway(kv) as gw, _pumping(w):
        _wait_for_report(kv, "w0")
        c = GatewayClient(gw.port)
        assert c.submit("r0", [1, 2, 3], 3) is True
        c.close()  # caller dies right after the door admitted
        # the request still runs to a verdict; a new caller can fetch it
        with GatewayClient(gw.port) as c2:
            got = c2.result("r0", timeout=30.0)
    assert got["verdict"] == "ok" and got["tokens"] == [4, 5, 6]


def test_live_gateways_tracks_open_and_closed(kv_pair):
    _, kv, _ = kv_pair
    before = set(live_gateways())
    g = _gateway(kv)
    assert g in live_gateways()
    g.close()
    g.close()  # idempotent
    assert g not in live_gateways() and set(live_gateways()) == before


# -- targeted queues: ownership and the tail-bump/set race --------------------


def test_targeted_entry_claimed_by_owner_only(kv_pair):
    from tpu_sandbox.serve import replica as R

    _, kv, clone = kv_pair
    owner = _worker(clone(), tag="owner")
    other = _worker(clone(), tag="other", scavenge_interval=60.0)
    R.write_request(kv, "r0", [1, 2, 3], 2)
    R.enqueue_to(kv, "owner", "r0")
    _fake_report(kv, "owner")  # owner is alive: peers keep hands off
    for _ in range(20):
        other.tick()
    assert other.stats.claimed == 0
    deadline = time.monotonic() + 10.0
    while kv.try_get(R.k_result("r0")) is None:
        assert time.monotonic() < deadline
        owner.tick()
    assert owner.stats.claimed == 1
    assert json.loads(kv.get(R.k_result("r0")))["replica"] == "owner"


def test_targeted_entry_visible_late_is_not_lost(kv_pair):
    """The tail-bump/set race: the producer bumps serve/tq/<tag>/tail and
    THEN writes the entry body. An owner whose scan lands in that window
    must hold its cursor and retry — skipping would strand the request
    forever (peers defer to a live owner)."""
    from tpu_sandbox.serve import replica as R

    _, kv, clone = kv_pair
    w = _worker(clone(), tag="w0")
    R.write_request(kv, "r0", [1, 2, 3], 2)
    kv.add(R.k_tq_tail("w0"))  # tail bumped, body not yet visible
    for _ in range(5):
        w.tick()
    assert w._tq_scanned == 0 and w.stats.claimed == 0  # cursor held
    kv.set(R.k_tq("w0", 0), "r0")  # the producer's write lands
    deadline = time.monotonic() + 10.0
    while kv.try_get(R.k_result("r0")) is None:
        assert time.monotonic() < deadline
        w.tick()
    assert w.stats.claimed == 1
    assert json.loads(kv.get(R.k_result("r0")))["verdict"] == "ok"


def test_targeted_permanent_hole_advances_after_patience(kv_pair):
    """A producer that died between bump and set leaves a hole with no
    entry behind it: after lease_ttl of patience the cursor moves on, and
    later entries still get claimed."""
    from tpu_sandbox.serve import replica as R

    _, kv, clone = kv_pair
    w = _worker(clone(), tag="w0", lease_ttl=0.05)
    kv.add(R.k_tq_tail("w0"))  # permanent hole at slot 0
    w.tick()
    time.sleep(0.1)
    w.tick()
    w.tick()
    assert w._tq_scanned == 1  # gave up on the hole, nothing was lost
    R.write_request(kv, "r1", [1, 2, 3], 2)
    R.enqueue_to(kv, "w0", "r1")
    deadline = time.monotonic() + 10.0
    while kv.try_get(R.k_result("r1")) is None:
        assert time.monotonic() < deadline
        w.tick()
    assert json.loads(kv.get(R.k_result("r1")))["verdict"] == "ok"


def test_dead_owner_targeted_entry_scavenged_to_shared(kv_pair):
    """A request routed to a replica that died before claiming it: the
    owner's load report expires, a peer's scavenge moves the entry to the
    shared queue (marking it, so a drain can't double-requeue), and the
    peer serves it — routing is a hint, never a trap."""
    from tpu_sandbox.serve import replica as R

    _, kv, clone = kv_pair
    R.write_request(kv, "r0", [1, 2, 3], 3)
    R.enqueue_to(kv, "ghost", "r0")  # no such worker, no load report
    w = _worker(clone(), tag="w1", scavenge_interval=0.05, lease_ttl=0.2)
    deadline = time.monotonic() + 15.0
    while kv.try_get(R.k_result("r0")) is None:
        assert time.monotonic() < deadline
        w.tick()
        time.sleep(0.002)
    got = json.loads(kv.get(R.k_result("r0")))
    assert got["verdict"] == "ok" and got["replica"] == "w1"
    assert kv.try_get(R.k_tq_scavenged("ghost", 0)) is not None


# -- bench smoke (tier-1) -----------------------------------------------------


def test_bench_gateway_quick_smoke():
    """`bench_gateway(quick=True)` in-process: the full socket bench at
    toy scale. Quick mode is too small for the perf claims to be
    meaningful, so only the accounting invariants are asserted here;
    BENCH_r08.json holds a committed full run."""
    from bench import bench_gateway

    out = bench_gateway(quick=True)
    assert out["metric"] == "gateway"
    assert out["every_request_verdicted"] is True
    for arm in ("routing_prefix", "routing_random",
                "admission_feasible", "admission_occupancy"):
        run = out[arm]
        assert run["verdict_audit_ok"] is True
        assert run["admitted"] + run["door_shed"] == run["submitted"]
        assert run["completed_ok"] + run["engine_shed"] == run["admitted"]
    assert "prefix_beats_random_p99" in out
    assert "feasible_goodput_holds" in out


# -- HA front door: heartbeat leases, failover, TLS ---------------------------

TLSDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "fixtures", "tls")


def _server_ctx():
    return wire.make_server_ssl_context(
        os.path.join(TLSDIR, "server.pem"), os.path.join(TLSDIR, "server.key"))


def _client_ctx(ca="ca.pem"):
    return wire.make_client_ssl_context(os.path.join(TLSDIR, ca))


def _wait_endpoints(kv, want_ids, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ids = [e[0] for e in live_gateway_endpoints(kv)]
        if ids == want_ids:
            return ids
        time.sleep(0.02)
    raise AssertionError(
        f"endpoints {[e[0] for e in live_gateway_endpoints(kv)]}, "
        f"wanted {want_ids}")


def test_hb_lease_discovery_close_vs_kill(kv_pair):
    """Every gateway registers a TTL'd gateway/hb/<id> lease. A clean
    close retires it immediately; a kill() (the SIGKILL stand-in) leaves
    it to lapse — discovery degrades on its own, nothing cleans up."""
    _, kv, _ = kv_pair
    g0 = _gateway(kv, gateway_id="gw0", hb_ttl=0.4)
    g1 = _gateway(kv, gateway_id="gw1", hb_ttl=0.4)
    try:
        eps = _wait_endpoints(kv, ["gw0", "gw1"])
        by_id = {e[0]: e for e in live_gateway_endpoints(kv)}
        assert by_id["gw0"][2] == g0.port and by_id["gw1"][2] == g1.port
        g1.close()  # clean: lease deleted right now
        assert [e[0] for e in live_gateway_endpoints(kv)] == ["gw0"]
        g0.kill()   # abrupt: lease still there until the TTL lapses
        assert kv.try_get(k_gateway_hb("gw0")) is not None
        _wait_endpoints(kv, [], timeout=3.0)
    finally:
        g0.close()
        g1.close()


def test_client_fails_over_when_gateway_killed(kv_pair):
    """Kill the connected gateway mid-session: the next op fails over to
    the survivor and completes against the same store state."""
    _, kv, clone = kv_pair
    w = _worker(clone(), tag="w0")
    g0 = _gateway(kv, gateway_id="gw0", hb_ttl=0.4)
    g1 = _gateway(kv, gateway_id="gw1", hb_ttl=0.4)
    client = None
    try:
        with _pumping(w):
            _wait_for_report(kv, "w0")
            client = GatewayClient(
                endpoints=[("127.0.0.1", g0.port), ("127.0.0.1", g1.port)],
                backoff_base=0.01)
            assert client.submit("r0", [1, 2, 3], 3) is True
            assert client.result("r0", timeout=30.0)["verdict"] == "ok"
            assert client.stats.failovers == 0
            g0.kill()
            assert client.submit("r1", [1, 2, 3], 3) is True
            got = client.result("r1", timeout=30.0)
            assert got["verdict"] == "ok" and got["tokens"] == [4, 5, 6]
            assert client.stats.failovers >= 1
            assert client.endpoint == ("127.0.0.1", g1.port)
    finally:
        if client is not None:
            client.close()
        g0.close()
        g1.close()


def test_failover_submit_repolls_verdict_never_reexecutes(kv_pair):
    """The one op that is not blindly reissued: a submit that dies with
    the gateway first polls the verdict slot on the survivor — a request
    whose verdict already landed is returned, never re-enqueued."""
    from tpu_sandbox.serve.replica import k_done, k_req, k_result

    _, kv, _ = kv_pair
    g0 = _gateway(kv, gateway_id="gw0", hb_ttl=0.4)
    g1 = _gateway(kv, gateway_id="gw1", hb_ttl=0.4)
    client = None
    try:
        client = GatewayClient(
            endpoints=[("127.0.0.1", g0.port), ("127.0.0.1", g1.port)],
            backoff_base=0.01)
        # the request's verdict landed (elsewhere) before this submit
        kv.set(k_result("done-rid"), json.dumps(
            {"rid": "done-rid", "verdict": "ok", "tokens": [9, 9],
             "replica": "w9"}))
        assert kv.add(k_done("done-rid")) == 1
        g0.kill()  # the client's socket dies with it
        assert client.submit("done-rid", [1, 2, 3], 2) is True
        assert client.stats.failovers >= 1
        got = client.result("done-rid", timeout=10.0)
        assert got["tokens"] == [9, 9] and got["replica"] == "w9"
        # never re-executed: no request body, no queue entry was written
        assert kv.try_get(k_req("done-rid")) is None
    finally:
        if client is not None:
            client.close()
        g0.close()
        g1.close()


def test_tls_round_trip_and_plaintext_refused(kv_pair):
    """With a TLS listener every byte on the external wire is encrypted:
    a TLS client (hello inside the channel) round-trips; a plaintext
    frame dies in the handshake — counted, connection closed, accept
    loop untouched."""
    _, kv, clone = kv_pair
    w = _worker(clone(), tag="w0")
    with _gateway(kv, tls=_server_ctx(), token="s3cr3t") as g, _pumping(w):
        _wait_for_report(kv, "w0")
        with GatewayClient(g.port, token="s3cr3t",
                           tls=_client_ctx()) as client:
            assert client.submit("r0", [1, 2, 3], 3) is True
            assert client.result("r0", timeout=30.0)["tokens"] == [4, 5, 6]
        # plaintext probe: the gateway refuses during the handshake
        s = _raw(g)
        with contextlib.suppress(ConnectionError, OSError):
            wire.send_frame(s, wire.OP_STATS, {})
            s.settimeout(5.0)
            assert s.recv(1) == b""  # closed, never answered
        s.close()
        _wait_stat(g, "tls_handshake_failures", 1)
        # the accept loop survived: TLS clients still served
        with GatewayClient(g.port, token="s3cr3t",
                           tls=_client_ctx()) as client:
            assert client.gateway_stats()["stats"]["connections"] >= 1


def test_tls_wrong_ca_never_connects(kv_pair):
    _, kv, _ = kv_pair
    with _gateway(kv, tls=_server_ctx(), token="s3cr3t") as g:
        with pytest.raises(GatewayError):
            GatewayClient(g.port, token="s3cr3t",
                          tls=_client_ctx("wrong_ca.pem"),
                          failover_cycles=1, backoff_base=0.0)
        _wait_stat(g, "tls_handshake_failures", 1)
        with GatewayClient(g.port, token="s3cr3t",
                           tls=_client_ctx()) as client:
            assert client.gateway_stats()["stats"]["requests"] == 0


def test_tls_mid_handshake_disconnect_and_oversized_frame(kv_pair):
    """Two adversaries inside the TLS path: a peer that connects and
    hangs up mid-handshake, and an authenticated TLS peer that sends an
    oversized length prefix inside the encrypted channel. Both end their
    own connection; neither wedges the accept loop."""
    _, kv, _ = kv_pair
    with _gateway(kv, tls=_server_ctx()) as g:
        # mid-handshake disconnect: TCP connect, then silence and close
        s = _raw(g)
        s.close()
        # oversized frame INSIDE an established TLS channel
        tls = _client_ctx().wrap_socket(_raw(g), server_hostname="127.0.0.1")
        tls.sendall(struct.pack("!BI", wire.OP_SUBMIT, 1 << 31))
        assert _closed_by_peer(tls)
        tls.close()
        _wait_stat(g, "protocol_errors", 1)
        with GatewayClient(g.port, tls=_client_ctx()) as client:
            assert client.gateway_stats()["stats"]["protocol_errors"] == 1


def test_requests_stamped_with_gateway_id(kv_pair):
    """Every routed request carries the gateway's id; replicas count
    claims per gateway in their load report — the chaos claim audit's
    attribution evidence."""
    from tpu_sandbox.serve.replica import read_load_reports

    _, kv, clone = kv_pair
    w = _worker(clone(), tag="w0")
    with _gateway(kv, gateway_id="gw-stamp") as g, _pumping(w):
        _wait_for_report(kv, "w0")
        with GatewayClient(g.port) as client:
            assert client.submit("r0", [1, 2, 3], 3) is True
            assert client.result("r0", timeout=30.0)["verdict"] == "ok"
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            rep = read_load_reports(kv).get("w0", {})
            if rep.get("gw_claims"):
                break
            time.sleep(0.02)
        assert rep["gw_claims"] == {"gw-stamp": 1}
