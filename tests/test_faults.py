"""Fault-injection harness: plan round-trips, exactly-once claims, and the
checkpoint corruptors the recovery tests rely on."""

import signal

import pytest

from tpu_sandbox.runtime.faults import (
    ENV_PLAN,
    Fault,
    FaultInjector,
    FaultPlan,
    corrupt_latest_shard,
    corrupt_latest_step,
    corrupt_step_dir,
)
from tpu_sandbox.runtime.kvstore import KVClient, KVServer


def test_fault_validation():
    with pytest.raises(ValueError, match="unknown fault action"):
        Fault(0, 1, "explode")
    with pytest.raises(ValueError, match="needs target"):
        Fault(0, 1, "corrupt_ckpt")
    with pytest.raises(ValueError, match="needs target"):
        Fault(0, 1, "corrupt_shard")
    Fault(0, 1, "corrupt_ckpt", target="/tmp/ck")  # ok with target
    Fault(0, 1, "corrupt_shard", target="/tmp/ck")
    Fault(1, 4, "kill_during_commit")  # no target needed


def test_plan_json_and_env_round_trip():
    plan = (FaultPlan()
            .add(1, 7, "kill")
            .add(0, 4, "sigterm")
            .add(0, 9, "corrupt_ckpt", target="/tmp/ck"))
    again = FaultPlan.from_json(plan.to_json())
    assert again.faults == plan.faults

    env = plan.to_env({})
    assert ENV_PLAN in env
    assert FaultPlan.from_env(env).faults == plan.faults
    # unset env var -> empty plan, not an error
    assert FaultPlan.from_env({}).faults == []


def test_injector_fires_only_matching_rank_and_step():
    fired = []
    plan = FaultPlan().add(1, 3, "hang_heartbeat")
    inj = FaultInjector(plan, rank=1, on_hang_heartbeat=lambda: fired.append(1))
    assert inj.maybe_fire(2) == []
    other = FaultInjector(plan, rank=0, on_hang_heartbeat=lambda: fired.append(0))
    assert other.maybe_fire(3) == []  # wrong rank
    assert [f.action for f in inj.maybe_fire(3)] == ["hang_heartbeat"]
    assert fired == [1]
    assert inj.maybe_fire(3) == []  # local claim: never twice


def test_claim_is_exactly_once_across_injectors():
    """Two injectors sharing the store model a worker before and after an
    elastic restart replaying the same step: the fault fires once."""
    plan = FaultPlan().add(0, 5, "hang_heartbeat")
    fired = []
    with KVServer() as srv:
        kv = KVClient(port=srv.port)
        first = FaultInjector(plan, 0, kv,
                              on_hang_heartbeat=lambda: fired.append("a"))
        second = FaultInjector(plan, 0, kv,
                               on_hang_heartbeat=lambda: fired.append("b"))
        assert len(first.maybe_fire(5)) == 1
        assert second.maybe_fire(5) == []  # claimed in the store
        assert fired == ["a"]
        kv.close()


def test_sigterm_fault_delivers_signal():
    plan = FaultPlan().add(0, 1, "sigterm")
    got = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: got.append(s))
    try:
        FaultInjector(plan, 0).maybe_fire(1)
        assert got == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_corrupt_step_dir_keeps_layout(tmp_path):
    d = tmp_path / "7" / "params"
    d.mkdir(parents=True)
    f = d / "data.bin"
    f.write_bytes(b"precious")
    touched = corrupt_step_dir(tmp_path / "7")
    assert touched == [f]
    assert f.exists() and f.read_bytes() != b"precious"


def test_corrupt_latest_step_orbax_layout(tmp_path):
    (tmp_path / "3").mkdir()
    (tmp_path / "10").mkdir()
    (tmp_path / "10" / "x.bin").write_bytes(b"good")
    assert corrupt_latest_step(tmp_path) == tmp_path / "10"
    assert (tmp_path / "10" / "x.bin").read_bytes() != b"good"


def test_commit_faults_fire_only_in_the_commit_window():
    """kill_during_commit must never fire at a step boundary (maybe_fire)
    and step-boundary faults must never fire from the commit hook — the two
    channels are disjoint by action, and the KV claim still makes the
    commit-window fault exactly-once across a restart replay."""
    plan = (FaultPlan()
            .add(0, 5, "kill_during_commit")
            .add(0, 5, "hang_heartbeat"))
    with KVServer() as srv:
        kv = KVClient(port=srv.port)
        hung = []
        inj = FaultInjector(plan, 0, kv,
                            on_hang_heartbeat=lambda: hung.append(1))
        # step boundary: only the hang fires; the kill stays for the window
        assert [f.action for f in inj.maybe_fire(5)] == ["hang_heartbeat"]
        assert hung == [1]
        # wrong step inside the window: nothing
        replay = FaultInjector(plan, 0, kv)
        assert replay.maybe_fire_commit(4) == []
        # cannot SIGKILL this test process to observe the real fire; claim
        # it through a second injector instead and verify the first (the
        # restarted generation replaying step 5) then sees it as spent
        assert kv.add("fault/0/claimed", 1) == 1
        assert replay.maybe_fire_commit(5) == []
        kv.close()


def _sealed_step(tmp_path, step, ranks=2):
    sd = tmp_path / f"step-{step:08d}"
    sd.mkdir(parents=True)
    for r in range(ranks):
        (sd / f"shard-{r:05d}.npz").write_bytes(b"shardbytes%d" % r)
    (sd / "MANIFEST.json").write_text("{}")
    return sd


def test_corrupt_latest_shard_targets_one_shard_of_newest_sealed(tmp_path):
    assert corrupt_latest_shard(tmp_path / "missing") is None
    old = _sealed_step(tmp_path, 3)
    new = _sealed_step(tmp_path, 9)
    torn = tmp_path / "step-00000012"  # newer but unsealed: not a target
    torn.mkdir()
    (torn / "shard-00000.npz").write_bytes(b"debris")
    hit = corrupt_latest_shard(tmp_path, rank=1)
    assert hit == new / "shard-00001.npz"
    assert hit.read_bytes() != b"shardbytes1"
    assert (new / "shard-00000.npz").read_bytes() == b"shardbytes0"
    assert (new / "MANIFEST.json").exists()  # still LOOKS sealed
    assert (old / "shard-00001.npz").read_bytes() == b"shardbytes1"
    # missing rank falls back to the first shard present
    assert corrupt_latest_shard(tmp_path, rank=7) == new / "shard-00000.npz"


def test_corrupt_latest_step_sharded_layout(tmp_path):
    sd = _sealed_step(tmp_path, 4)
    assert corrupt_latest_step(tmp_path) == sd
    assert (sd / "shard-00000.npz").read_bytes() != b"shardbytes0"


def test_corrupt_latest_step_npz_layout(tmp_path):
    (tmp_path / "step-2.npz").write_bytes(b"aaaa")
    (tmp_path / "step-10.npz").write_bytes(b"bbbb")
    # numeric order: step-10 is the newest, despite sorting after step-2
    # lexicographically
    assert corrupt_latest_step(tmp_path) == tmp_path / "step-10.npz"
    assert (tmp_path / "step-2.npz").read_bytes() == b"aaaa"
    assert corrupt_latest_step(tmp_path / "missing") is None
    empty = tmp_path / "empty"
    empty.mkdir()
    assert corrupt_latest_step(empty) is None
