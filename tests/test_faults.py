"""Fault-injection harness: plan round-trips, exactly-once claims, and the
checkpoint corruptors the recovery tests rely on."""

import signal

import pytest

from tpu_sandbox.runtime.faults import (
    ENV_PLAN,
    Fault,
    FaultInjector,
    FaultPlan,
    corrupt_latest_step,
    corrupt_step_dir,
)
from tpu_sandbox.runtime.kvstore import KVClient, KVServer


def test_fault_validation():
    with pytest.raises(ValueError, match="unknown fault action"):
        Fault(0, 1, "explode")
    with pytest.raises(ValueError, match="needs target"):
        Fault(0, 1, "corrupt_ckpt")
    Fault(0, 1, "corrupt_ckpt", target="/tmp/ck")  # ok with target


def test_plan_json_and_env_round_trip():
    plan = (FaultPlan()
            .add(1, 7, "kill")
            .add(0, 4, "sigterm")
            .add(0, 9, "corrupt_ckpt", target="/tmp/ck"))
    again = FaultPlan.from_json(plan.to_json())
    assert again.faults == plan.faults

    env = plan.to_env({})
    assert ENV_PLAN in env
    assert FaultPlan.from_env(env).faults == plan.faults
    # unset env var -> empty plan, not an error
    assert FaultPlan.from_env({}).faults == []


def test_injector_fires_only_matching_rank_and_step():
    fired = []
    plan = FaultPlan().add(1, 3, "hang_heartbeat")
    inj = FaultInjector(plan, rank=1, on_hang_heartbeat=lambda: fired.append(1))
    assert inj.maybe_fire(2) == []
    other = FaultInjector(plan, rank=0, on_hang_heartbeat=lambda: fired.append(0))
    assert other.maybe_fire(3) == []  # wrong rank
    assert [f.action for f in inj.maybe_fire(3)] == ["hang_heartbeat"]
    assert fired == [1]
    assert inj.maybe_fire(3) == []  # local claim: never twice


def test_claim_is_exactly_once_across_injectors():
    """Two injectors sharing the store model a worker before and after an
    elastic restart replaying the same step: the fault fires once."""
    plan = FaultPlan().add(0, 5, "hang_heartbeat")
    fired = []
    with KVServer() as srv:
        kv = KVClient(port=srv.port)
        first = FaultInjector(plan, 0, kv,
                              on_hang_heartbeat=lambda: fired.append("a"))
        second = FaultInjector(plan, 0, kv,
                               on_hang_heartbeat=lambda: fired.append("b"))
        assert len(first.maybe_fire(5)) == 1
        assert second.maybe_fire(5) == []  # claimed in the store
        assert fired == ["a"]
        kv.close()


def test_sigterm_fault_delivers_signal():
    plan = FaultPlan().add(0, 1, "sigterm")
    got = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: got.append(s))
    try:
        FaultInjector(plan, 0).maybe_fire(1)
        assert got == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_corrupt_step_dir_keeps_layout(tmp_path):
    d = tmp_path / "7" / "params"
    d.mkdir(parents=True)
    f = d / "data.bin"
    f.write_bytes(b"precious")
    touched = corrupt_step_dir(tmp_path / "7")
    assert touched == [f]
    assert f.exists() and f.read_bytes() != b"precious"


def test_corrupt_latest_step_orbax_layout(tmp_path):
    (tmp_path / "3").mkdir()
    (tmp_path / "10").mkdir()
    (tmp_path / "10" / "x.bin").write_bytes(b"good")
    assert corrupt_latest_step(tmp_path) == tmp_path / "10"
    assert (tmp_path / "10" / "x.bin").read_bytes() != b"good"


def test_corrupt_latest_step_npz_layout(tmp_path):
    (tmp_path / "step-2.npz").write_bytes(b"aaaa")
    (tmp_path / "step-10.npz").write_bytes(b"bbbb")
    # numeric order: step-10 is the newest, despite sorting after step-2
    # lexicographically
    assert corrupt_latest_step(tmp_path) == tmp_path / "step-10.npz"
    assert (tmp_path / "step-2.npz").read_bytes() == b"aaaa"
    assert corrupt_latest_step(tmp_path / "missing") is None
    empty = tmp_path / "empty"
    empty.mkdir()
    assert corrupt_latest_step(empty) is None
