"""Stage-transport contract tests (tier-1, stub + KV wire, no jax).

Both implementations must honor the same delivery discipline — the
driver and worker are written against the interface, so every property
here is parametrized over LocalTransport and KVTransport: produce-once
(idempotent replay puts), claim-once per generation, blocking get with
timeout, release_step GC, and the byte-exact pack/unpack framing the
bitwise replay parity rests on.
"""

import numpy as np
import pytest

from tpu_sandbox.mpmd.schedule import (
    bubble_fraction,
    fetch_plan,
    max_in_flight,
    one_f_one_b,
    publish_plan,
)
from tpu_sandbox.mpmd.transport import (
    EdgeNames,
    KVTransport,
    LocalTransport,
    pack_arrays,
    unpack_arrays,
)


@pytest.fixture
def kv_pair():
    from tpu_sandbox.runtime.kvstore import KVClient, KVServer

    server = KVServer()
    kv = KVClient(port=server.port)
    yield server, kv
    kv.close()
    server.stop()


@pytest.fixture(params=["local", "kv"])
def transport(request, kv_pair):
    if request.param == "local":
        return LocalTransport()
    _, kv = kv_pair
    return KVTransport(kv, prefix="mpmd/pipe0")


def _arrays(seed=0):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=(3, 5)).astype(np.float32),
            rng.integers(0, 100, size=(7,)).astype(np.int32)]


# -- framing ------------------------------------------------------------------


def test_pack_unpack_roundtrip_bitwise():
    arrays = _arrays()
    meta, payload = pack_arrays(arrays)
    out = unpack_arrays(meta, payload)
    assert len(out) == len(arrays)
    for a, b in zip(arrays, out):
        assert a.dtype == b.dtype and a.shape == b.shape
        assert a.tobytes() == b.tobytes()


def test_unpack_rejects_corrupt_payload():
    meta, payload = pack_arrays(_arrays())
    with pytest.raises(ValueError, match="meta describes"):
        unpack_arrays(meta, payload + b"\x00")  # trailing garbage
    with pytest.raises(ValueError):
        unpack_arrays(meta, payload[:-1])  # truncated mid-array


# -- delivery discipline (both wires) -----------------------------------------


def test_put_get_roundtrip_and_stats(transport):
    arrays = _arrays()
    assert transport.put("act0", 3, 1, arrays) is True
    got = transport.get("act0", 3, 1, timeout=5.0)
    for a, b in zip(arrays, got):
        assert a.tobytes() == b.tobytes() and a.dtype == b.dtype
    s = transport.stats
    assert s.puts == 1 and s.gets == 1
    assert s.bytes_out == s.bytes_in > 0


def test_put_is_produce_once(transport):
    arrays = _arrays()
    assert transport.put("act0", 0, 0, arrays) is True
    # a replaying producer re-puts the same slot and loses the claim
    assert transport.put("act0", 0, 0, arrays) is False
    got = transport.get("act0", 0, 0, timeout=5.0)
    assert got[0].tobytes() == arrays[0].tobytes()
    assert transport.audit()["commits"].popitem()[1] == 2


def test_get_times_out_on_absent_slot(transport):
    with pytest.raises(TimeoutError):
        transport.get("act0", 9, 9, timeout=0.05)


def test_poll_and_release_step(transport):
    transport.put("act0", 4, 0, _arrays())
    transport.put("act0", 4, 1, _arrays(1))
    transport.put("act0", 5, 0, _arrays(2))
    transport.put("grad0", 4, 0, _arrays(3))
    assert transport.poll("act0", 4, 0) and transport.poll("act0", 4, 1)
    transport.release_step("act0", 4)
    assert not transport.poll("act0", 4, 0)
    assert not transport.poll("act0", 4, 1)
    # other steps and other edges survive the GC
    assert transport.poll("act0", 5, 0)
    assert transport.poll("grad0", 4, 0)


def test_claim_once_per_generation(transport):
    # within one generation a slot feeds exactly one consumer op
    assert transport.claim("act0", 2, 0, generation=0) is True
    assert transport.claim("act0", 2, 0, generation=0) is False
    # a relaunched generation legitimately re-claims for replay
    assert transport.claim("act0", 2, 0, generation=1) is True
    assert transport.claim("act0", 2, 1, generation=0) is True
    claims = transport.audit()["claims"]
    dup = {k: v for k, v in claims.items() if v != 1}
    assert list(dup.values()) == [2]  # exactly the double-claim we made


# -- KV wire specifics --------------------------------------------------------


def test_kv_transport_chunks_large_payload(kv_pair):
    _, kv = kv_pair
    tr = KVTransport(kv, prefix="mpmd/pipe0", chunk_bytes=1024)
    big = np.arange(5000, dtype=np.float64)  # 40000 bytes -> 40 chunks
    assert tr.put("act0", 0, 0, [big]) is True
    (got,) = tr.get("act0", 0, 0, timeout=5.0)
    assert got.tobytes() == big.tobytes()
    import json
    meta = json.loads(kv.get("mpmd/pipe0/mpmd/slot/act0/0/0/meta"))
    assert meta["nchunks"] == 40 and meta["bytes"] == 40000


def test_kv_transport_finishes_dead_writers_slot(kv_pair):
    """Commit claimed, meta never landed (writer died mid-put): the
    replayer loses the claim but completes the slot with its own
    deterministic bytes."""
    _, kv = kv_pair
    tr = KVTransport(kv, prefix="mpmd/pipe0")
    kv.add("mpmd/pipe0/mpmd/slot/act0/0/0/commit", 1)  # the dead writer
    arrays = _arrays()
    assert tr.put("act0", 0, 0, arrays) is False  # lost claim, finished slot
    (a, b) = tr.get("act0", 0, 0, timeout=5.0)
    assert a.tobytes() == arrays[0].tobytes()


def test_kv_transport_prefix_isolation(kv_pair):
    _, kv = kv_pair
    t0 = KVTransport(kv, prefix="mpmd/pipe0")
    t1 = KVTransport(kv, prefix="mpmd/pipe1")
    t0.put("act0", 0, 0, _arrays())
    assert not t1.poll("act0", 0, 0)
    assert t1.claim("act0", 0, 0, generation=0) is True  # own claim plane


def test_kv_transport_rejects_oversized_chunks(kv_pair):
    _, kv = kv_pair
    with pytest.raises(ValueError, match="read cap"):
        KVTransport(kv, chunk_bytes=1 << 20)


# -- schedule properties ------------------------------------------------------


def test_one_f_one_b_op_counts_and_order():
    S, M = 4, 8
    for s in range(S):
        ops = one_f_one_b(s, S, M)
        fs = [m for op, m in ops if op == "F"]
        bs = [m for op, m in ops if op == "B"]
        # every microbatch forwarded and backwarded exactly once, in order
        assert fs == list(range(M)) and bs == list(range(M))
        # a microbatch's B never precedes its F
        seen_f = set()
        for op, m in ops:
            if op == "F":
                seen_f.add(m)
            else:
                assert m in seen_f


def test_one_f_one_b_stash_bound():
    S = 4
    for M in (1, 2, 4, 16):
        for s in range(S):
            # the 1F1B point: in-flight bounded by S - stage, however
            # large M grows (GPipe would stash M)
            assert max_in_flight(one_f_one_b(s, S, M)) == min(M, S - s)


def test_one_f_one_b_validates_args():
    with pytest.raises(ValueError):
        one_f_one_b(4, 4, 2)
    with pytest.raises(ValueError):
        one_f_one_b(0, 2, 0)


def test_bubble_fraction_formula():
    assert bubble_fraction(1, 4) == 0.0
    assert bubble_fraction(4, 4) == pytest.approx(3 / 7)
    # more microbatches amortize the bubble
    assert bubble_fraction(4, 32) < bubble_fraction(4, 4)


def test_publish_fetch_plan_roundtrip(kv_pair):
    _, kv = kv_pair
    publish_plan(kv, n_stages=2, microbatches=4, steps=10, seed=7,
                 prefix="mpmd/pipe0", extra={"model": {"d_model": 32}})
    plan = fetch_plan(kv, prefix="mpmd/pipe0")
    assert plan["steps"] == 10 and plan["seed"] == 7
    assert plan["model"] == {"d_model": 32}
    assert plan["ops"][0] == one_f_one_b(0, 2, 4)
    assert plan["ops"][1] == one_f_one_b(1, 2, 4)
    with pytest.raises(TimeoutError):
        fetch_plan(kv, prefix="mpmd/other", timeout=0.05)


def test_edge_names():
    e = EdgeNames(2)
    assert e.act == "act2" and e.grad == "grad2"
