"""Two-tenant cluster mode end-to-end (CPU, real training jobs): a
ClusterScheduler pool runs two mnist jobs side by side and one of them is
killed every way runtime/faults.py knows how to kill a tenant, while the
other must not notice:

- agent death  (kill_agent on the noisy job's leader host) — the noisy
  job restarts a generation, charged once, inside its own namespace
- preemption   (sigterm to a noisy rank) — checkpoint-through-preemption
  inside the job, uncharged
- partition    (partition_host) — the noisy job's leadership moves hosts

In every variant the quiet job's final checkpoint must be bitwise equal
to a solo run of the same seed, and its budget counters untouched — the
fault-isolation contract of the multi-tenant scheduler.

The second half is the priority-preemption acceptance path: a
high-priority job lands on a full pool, the low-priority training job is
SIGTERMed by the scheduler, checkpoints through the preemption vote, exits
uncharged, and later resumes to bitwise parity with an uninterrupted run.

Real subprocesses + jax.distributed per generation: slow-marked, out of
tier-1. The scheduler control plane is covered fast in test_scheduler.py.
"""

import json
import os
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from tpu_sandbox.runtime.scheduler import (
    ClusterScheduler,
    JobSpec,
    job_events,
    k_state,
    k_verdict,
)

pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent
SCRIPT = REPO / "mnist_distributed.py"

# 64 synthetic samples / (bs 4 x 2 ranks) = 8 steps per epoch, 16 total
CFG = [
    "-g", "2", "--epochs", "2", "--batch-size", "4", "--image-size", "28",
    "--synthetic-n", "64", "--limit-steps", "8", "--dtype", "fp32",
    "--plan", "plain", "--log-every", "1000", "--ckpt-every", "2",
]
TOTAL_STEPS = 16

# a short filler job for the high-priority arrival (2 steps and done;
# world 2 because the gloo-backed CPU collectives need a real process
# group — single-rank elastic worlds are not a supported topology)
CFG_QUICK = [
    "-g", "2", "--epochs", "1", "--batch-size", "4", "--image-size", "28",
    "--synthetic-n", "16", "--limit-steps", "2", "--dtype", "fp32",
    "--plan", "plain", "--log-every", "1000",
]

KNOBS = {
    "PYTHONPATH": str(REPO) + os.pathsep + os.environ.get("PYTHONPATH", ""),
    "JAX_PLATFORMS": "cpu",
    "TPU_SANDBOX_BACKOFF": "0.1",
    "TPU_SANDBOX_TERM_TIMEOUT": "10",
    "TPU_SANDBOX_LEASE_TTL": "2",
    "TPU_SANDBOX_AGENT_TIMEOUT": "4",
}


def training_job(job_id, ckpt_dir, *, hosts=1, world=2, priority=0,
                 cfg=CFG, fault_plan=None):
    """A real elastic mnist job as a scheduler tenant — the exact argv
    shape mnist_distributed.run_cluster_pool submits for itself."""
    argv = [sys.executable, str(SCRIPT), "--elastic",
            "--agents", str(hosts), "--agent-id", "{agent_id}",
            "--kv-port", "{kv_port}", "--job-id", "{job_id}",
            "--max-restarts", "4", *cfg]
    if ckpt_dir is not None:
        argv += ["--ckpt-dir", str(ckpt_dir)]
    env = {}
    if fault_plan is not None:
        env["TPU_SANDBOX_FAULT_PLAN"] = json.dumps(fault_plan)
    return JobSpec(job_id=job_id, hosts=hosts, world_size=world,
                   agent_argv=argv, priority=priority,
                   admission_timeout=600.0, env=env)


def final_params(ckpt_dir):
    f = Path(ckpt_dir) / f"step-{TOTAL_STEPS:08d}.npz"
    assert f.exists(), f"missing final checkpoint {f}"
    with np.load(f, allow_pickle=False) as z:
        return {k: z[k].copy() for k in z.files if k.startswith("leaf:")}


def assert_same_model(a, b):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=0, atol=1e-6, err_msg=k)


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """One solo, unfaulted, un-scheduled run of the shared config — the
    parity target for both the quiet tenant and the resumed victim (the
    cluster path must not perturb the math of either)."""
    ref_dir = tmp_path_factory.mktemp("cluster") / "ref"
    import subprocess
    env = {**os.environ, **KNOBS}
    r = subprocess.run(
        [sys.executable, str(SCRIPT), "--elastic", "--agents", "1", *CFG,
         "--ckpt-dir", str(ref_dir)],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    return final_params(ref_dir)


# -- the two-job fault matrix ----------------------------------------------


@pytest.mark.parametrize("fault_name,fault_plan", [
    ("agent_death",
     [{"rank": 0, "step": 5, "action": "kill_agent"}]),
    ("preemption",
     [{"rank": 0, "step": 5, "action": "sigterm"}]),
    ("partition",
     [{"rank": 0, "step": 5, "action": "partition_host", "target": "8"}]),
])
def test_faulted_neighbor_never_touches_quiet_job(
        reference, tmp_path, fault_name, fault_plan):
    """Jobs 'noisy' (2 hosts) and 'quiet' (1 host) share a 3-slot pool.
    The fault fires only inside noisy's namespace; noisy recovers through
    its own elastic machinery and quiet must come out bitwise identical
    to the solo reference with zero charges."""
    noisy_dir = tmp_path / "noisy"
    quiet_dir = tmp_path / "quiet"
    with ClusterScheduler(3, poll=0.05, extra_env=KNOBS,
                          verbose=False) as sched:
        sched.submit(training_job("noisy", noisy_dir, hosts=2,
                                  fault_plan=fault_plan))
        sched.submit(training_job("quiet", quiet_dir, hosts=1))
        states = sched.serve(timeout=900)
        assert states == {"noisy": "done", "quiet": "done"}, states

        # the isolation contract: quiet's budgets are untouched and the
        # scheduler never so much as sent it a signal
        verdict = json.loads(sched.kv.get(k_verdict("quiet")))
        assert verdict["ok"], verdict
        assert verdict["restarts"] == 0, verdict
        assert verdict["preemptions"] == 0, verdict
        assert "preempt_sent" not in job_events(sched.kv, "quiet")

        # the fault really fired — noisy paid for it, in its own ledger
        noisy = json.loads(sched.kv.get(k_verdict("noisy")))
        assert noisy["ok"], noisy
        if fault_name == "preemption":
            assert noisy["preemptions"] >= 1, noisy
            assert noisy["restarts"] == 0, noisy
        else:
            assert noisy["restarts"] >= 1, noisy

        # both namespaces swept: nothing leaks into the next tenant
        assert sched.kv.keys("job/noisy/") == []
        assert sched.kv.keys("job/quiet/") == []

    assert_same_model(reference, final_params(quiet_dir))


# -- priority preemption to bitwise parity ---------------------------------


def test_preempted_job_resumes_to_bitwise_parity(reference, tmp_path):
    """Low-priority training fills the pool; a high-priority job arrives
    mid-epoch. The scheduler SIGTERMs the gang, the ranks checkpoint
    through the preemption vote and exit uncharged, the high-priority job
    runs, and the victim resumes to the same final parameters as a run
    nobody ever interrupted."""
    low_dir = tmp_path / "low"
    with ClusterScheduler(1, poll=0.05, extra_env=KNOBS,
                          verbose=False) as sched:
        sched.submit(training_job("low", low_dir, hosts=1, priority=0))
        # outrank it only once it is demonstrably mid-training: running,
        # with at least one checkpoint on disk to resume from
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            sched._tick()
            state = (sched.kv.try_get(k_state("low")) or b"").decode()
            if state == "running" and list(low_dir.glob("step-*.npz")):
                break
            time.sleep(0.1)
        else:
            pytest.fail("low-priority job never reached a checkpoint")
        sched.submit(training_job("high", None, hosts=1, world=2,
                                  priority=5, cfg=CFG_QUICK))
        states = sched.serve(timeout=900)
        assert states == {"low": "done", "high": "done"}, states

        # the acceptance receipts, in causal order on the scheduler clock
        ev = job_events(sched.kv, "low")
        assert ev["admitted"] <= ev["preempt_sent"] \
            <= ev["preempted"] <= ev["readmitted"]
        assert job_events(sched.kv, "high")["admitted"] \
            >= ev["preempt_sent"]

        # preemption was free: the victim's verdict charges no restarts
        verdict = json.loads(sched.kv.get(k_verdict("low")))
        assert verdict["ok"], verdict
        assert verdict["restarts"] == 0, verdict
        assert verdict["preemptions"] >= 1, verdict

    assert_same_model(reference, final_params(low_dir))
