"""The fused conv1+tail backward (ops/pallas_conv1_tail_t.py) == the
unfused composition it replaces — forward outputs, batch stats, and ALL
gradients (dk5, conv bias, dgamma, dbeta) — in interpret mode; Mosaic
lowering at production geometry is pinned in tests/test_mosaic_lowering.
The fused backward's dy never exists in HBM, so equality here is the
whole correctness argument for the ~9.4 GB/step traffic cut."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_sandbox.models.convnet_s2d_t import space_to_depth_t
from tpu_sandbox.ops.pallas_conv1_tail_t import (
    conv1_tail_t,
    conv1_tail_t_reference,
)


def _case(n=2, hw=32, f1=8, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    img = jnp.asarray(rng.standard_normal((n, hw, hw)), dtype)
    x = space_to_depth_t(img, 4)
    k5 = jnp.asarray(0.3 * rng.standard_normal((5, 5, 1, f1)), dtype)
    cb = jnp.asarray(0.1 * rng.standard_normal(f1), dtype)
    gamma = jnp.asarray(1.0 + 0.1 * rng.standard_normal(f1), jnp.float32)
    beta = jnp.asarray(0.1 * rng.standard_normal(f1), jnp.float32)
    return x, k5, cb, gamma, beta


@pytest.mark.parametrize("hw", [32, 4])  # 4: one-block image, all halos
def test_forward_and_stats_match_unfused(hw):
    x, k5, cb, gamma, beta = _case(hw=hw)
    f1 = k5.shape[-1]
    out, mu, var = conv1_tail_t(x, k5, cb, gamma, beta, f1, 4)
    ref, mu_r, var_r = conv1_tail_t_reference(x, k5, cb, gamma, beta, f1, 4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(mu), np.asarray(mu_r), atol=1e-5)
    np.testing.assert_allclose(np.asarray(var), np.asarray(var_r), atol=1e-5)


def test_all_grads_match_unfused():
    x, k5, cb, gamma, beta = _case(seed=1)
    f1 = k5.shape[-1]

    def loss(fn):
        def f(k5, cb, gamma, beta):
            out, _, _ = fn(x, k5, cb, gamma, beta, f1, 4)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return f

    gf = jax.grad(loss(conv1_tail_t), argnums=(0, 1, 2, 3))(
        k5, cb, gamma, beta)
    gr = jax.grad(loss(conv1_tail_t_reference), argnums=(0, 1, 2, 3))(
        k5, cb, gamma, beta)
    for a, b, nm in zip(gf, gr, ("dk5", "dcbias", "dgamma", "dbeta")):
        if nm == "dcbias":
            # dL/dcbias = sum(dy) is ANALYTICALLY ~0 under BN (the
            # backward's c1/c2 correction zeroes each channel's dy sum);
            # both paths produce f32 summation-order noise ~1e-5 around
            # it (per-channel |dy| mass is O(10) here — verified: a
            # third summation order gives yet another ~1e-5 value).
            # Assert both are tiny, not bit-close to each other.
            for v, src in ((a, "fused"), (b, "unfused")):
                assert float(np.max(np.abs(np.asarray(v)))) < 1e-3, src
            continue
        scale = float(np.max(np.abs(np.asarray(b)))) or 1.0
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0, atol=3e-5 * scale, err_msg=nm)


def test_bf16_grads_track_unfused():
    """Production compute dtype: the in-kernel dy is rounded to bf16
    exactly as the HBM tensor would have been, so even in bf16 the two
    paths agree tightly (same rounding points)."""
    x, k5, cb, gamma, beta = _case(seed=2, dtype=jnp.bfloat16)
    f1 = k5.shape[-1]

    def loss(fn):
        def f(k5):
            out, _, _ = fn(x, k5, cb, gamma, beta, f1, 4)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return f

    gf = jax.grad(loss(conv1_tail_t))(k5)
    gr = jax.grad(loss(conv1_tail_t_reference))(k5)
    scale = float(np.max(np.abs(np.asarray(gr, np.float32)))) or 1.0
    dev = float(np.max(np.abs(np.asarray(gf, np.float32)
                              - np.asarray(gr, np.float32))))
    assert dev / scale < 2e-2, (dev, scale)


def test_differentiated_input_raises():
    """The composite keeps conv1's data-only input contract: a
    differentiated x raises (AD-rule guard), including across jit."""
    x, k5, cb, gamma, beta = _case()
    f1 = k5.shape[-1]

    def loss(s):
        out, _, _ = conv1_tail_t(x * s, k5, cb, gamma, beta, f1, 4)
        return jnp.sum(out)

    with pytest.raises(ValueError, match="ZERO input cotangent"):
        jax.grad(loss)(jnp.float32(1.0))
    with pytest.raises(ValueError, match="ZERO input cotangent"):
        jax.grad(jax.jit(loss))(jnp.float32(1.0))


def test_wgrad_restage_variants_agree(monkeypatch):
    """The fused tail wgrad honors TPU_SANDBOX_WGRAD_RESTAGE like every
    other wgrad kernel (it was hardcoded to 'gt' before): both variants
    produce the same gradients, and the unset default is the 'gt'
    native-dot form bitwise. jax.grad re-traces per call, so the
    trace-time env read sees each monkeypatched value."""
    x, k5, cb, gamma, beta = _case(seed=3)
    f1 = k5.shape[-1]

    def grads():
        def f(k5, cb, gamma, beta):
            out, _, _ = conv1_tail_t(x, k5, cb, gamma, beta, f1, 4)
            return jnp.sum(out.astype(jnp.float32) ** 2)
        return jax.grad(f, argnums=(0, 1, 2, 3))(k5, cb, gamma, beta)

    monkeypatch.setenv("TPU_SANDBOX_WGRAD_RESTAGE", "gt")
    g_gt = grads()
    monkeypatch.setenv("TPU_SANDBOX_WGRAD_RESTAGE", "auto")
    g_auto = grads()
    monkeypatch.delenv("TPU_SANDBOX_WGRAD_RESTAGE")
    g_default = grads()
    for a, b, nm in zip(g_gt, g_auto, ("dk5", "dcbias", "dgamma", "dbeta")):
        scale = float(np.max(np.abs(np.asarray(a, np.float32)))) or 1.0
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0, atol=1e-5 * scale, err_msg=nm)
    for a, b in zip(g_gt, g_default):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
