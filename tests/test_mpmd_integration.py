"""Two-stage MPMD pipeline as real scheduler jobs (slow): per-stage
worker processes co-admitted as a cogroup, activations/grads over the
KV-store transport, faults landing MID-SHIPMENT.

The receipts: after a stage host is SIGKILLed halfway through a step's
op list (half its slots shipped), the scheduler respawns the worker,
which restores its own HostCheckpoint, bumps the claim generation, and
replays from durable slots — final params BITWISE identical to an
unfaulted in-process run, per-step losses identical, and the claim-once
audit shows zero duplicate deliveries in any generation. A network
partition (paused heartbeats, stalled shipments) must heal with no
relaunch at all.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax

from tpu_sandbox.runtime.faults import FaultPlan
from tpu_sandbox.runtime.scheduler import ClusterScheduler, JobSpec

PY = sys.executable
ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {"PYTHONPATH": ROOT}

MODEL = {"vocab_size": 64, "d_model": 32, "n_heads": 2, "n_layers": 4,
         "d_ff": 64, "max_len": 64}
OPTIMIZER = {"name": "adam", "lr": 0.01}
BATCH = [8, 16]
STEPS = 8
M = 4
SEED = 0


@pytest.fixture(scope="module")
def reference():
    """Unfaulted in-process 2-stage run with the exact init derivation the
    workers use (plan-seeded rng for data, seeded TransformerLM init)."""
    import optax

    from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
    from tpu_sandbox.mpmd import MPMDPipeline

    cfg = TransformerConfig(**MODEL)
    rng = np.random.default_rng(SEED)
    tokens = rng.integers(0, cfg.vocab_size, size=tuple(BATCH)).astype(
        np.int32)
    targets = ((tokens + 7) % cfg.vocab_size).astype(np.int32)
    flat = jax.tree.map(
        np.asarray,
        TransformerLM(cfg).init(jax.random.key(SEED), tokens)["params"])
    pipe = MPMDPipeline(cfg, optax.adam(OPTIMIZER["lr"]), n_stages=2,
                        microbatches=M)
    pipe.init_from_flat(flat)
    losses = pipe.train(STEPS, tokens, targets)
    return {
        "losses": losses,
        "stage_leaves": {
            s: [np.asarray(x) for x in
                jax.tree.leaves(pipe.workers[s].host_state()["params"])]
            for s in (0, 1)
        },
    }


def _json_arg(obj):
    # agent_argv elements are str.format templates: JSON braces must be
    # doubled so they survive placeholder substitution
    return json.dumps(obj).replace("{", "{{").replace("}", "}}")


def _stage_argv(stage, ckpt_root):
    argv = [PY, "-m", "tpu_sandbox.mpmd.worker",
            "{agent_id}", "{kv_port}", "{job_id}",
            "--stage", str(stage), "--ckpt-root", str(ckpt_root),
            "--get-timeout", "120"]
    if stage == 0:  # the leader publishes the plan everyone else fetches
        argv += ["--steps", str(STEPS), "--n-stages", "2",
                 "--microbatches", str(M), "--seed", str(SEED),
                 "--model", _json_arg(MODEL),
                 "--optimizer", _json_arg(OPTIMIZER),
                 "--batch", _json_arg(BATCH)]
    return argv


def _run_pipeline(tmp_path, fault_env_stage1):
    with ClusterScheduler(2, poll=0.05, extra_env=ENV,
                          verbose=False) as sched:
        for s in (0, 1):
            sched.submit(JobSpec(
                job_id=f"stage{s}", hosts=1, world_size=1, cogroup="pipe0",
                agent_argv=_stage_argv(s, tmp_path / "ckpt"),
                admission_timeout=120.0,
                env=fault_env_stage1 if s == 1 else {}))
        states = sched.serve(timeout=300)
        assert states == {"stage0": "done", "stage1": "done"}, states

        from tpu_sandbox.mpmd.transport import KVTransport

        tr = KVTransport(sched.kv, prefix="mpmd/pipe0/")
        finals = {s: tr.get("final", 0, s, timeout=10.0) for s in (0, 1)}
        losses = json.loads(sched.kv.get("mpmd/pipe0/losses"))
        audit = tr.audit()
        generations = {s: int(sched.kv.get(f"mpmd/pipe0/gen/{s}"))
                       for s in (0, 1)}
        # the job-done verdicts survive in the raw store summary only via
        # states above; namespaces are swept — transport plane must not be
        assert sched.kv.keys("job/stage0/") == []
    return finals, losses, audit, generations


def _assert_bitwise(reference, finals):
    for s in (0, 1):
        ref, got = reference["stage_leaves"][s], finals[s]
        assert len(ref) == len(got)
        for i, (a, b) in enumerate(zip(ref, got)):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert a.tobytes() == b.tobytes(), \
                f"stage {s} leaf {i} differs from unfaulted run"


def test_two_stage_pipeline_clean_run_matches_inprocess(tmp_path, reference):
    finals, losses, audit, gens = _run_pipeline(tmp_path, {})
    _assert_bitwise(reference, finals)
    np.testing.assert_allclose(losses, reference["losses"], rtol=0,
                               atol=1e-6)
    assert gens == {0: 1, 1: 1}
    dup = {k: v for k, v in audit["claims"].items() if v != 1}
    assert not dup, f"duplicate deliveries: {dup}"


def test_two_stage_pipeline_stage_kill_recovers_bitwise(tmp_path, reference):
    """kill_agent fires mid-schedule on stage 1 at step 3: half the step's
    slots are shipped when the host dies. The scheduler respawn + durable
    slots + claim-generation bump must land bitwise with zero duplicate
    and zero lost microbatches."""
    plan = FaultPlan().add(rank=1, step=3, action="kill_agent")
    finals, losses, audit, gens = _run_pipeline(
        tmp_path, {"TPU_SANDBOX_FAULT_PLAN": plan.to_json()})
    _assert_bitwise(reference, finals)
    np.testing.assert_allclose(losses, reference["losses"], rtol=0,
                               atol=1e-6)
    # the kill really happened: stage 1 is on its second claim generation
    assert gens == {0: 1, 1: 2}
    dup = {k: v for k, v in audit["claims"].items() if v != 1}
    assert not dup, f"duplicate deliveries: {dup}"
    # replay actually re-claimed under the new generation
    assert any(k.startswith("2/") for k in audit["claims"])


# ---------------------------------------------------------------------------
# 3 stages, uneven layer split, ZB-H1 split backward (the fast-fabric
# tentpole at process level): same fault matrix, same receipts
# ---------------------------------------------------------------------------

N3 = 3
LAYER_SPLIT = [2, 1, 1]  # uneven on purpose: stage 0 carries half the net
KIND3 = "zb_h1"


@pytest.fixture(scope="module")
def reference3():
    """Unfaulted in-process 3-stage ZB-H1 twin over the same uneven
    split — the bitwise target for the process-level runs below."""
    import optax

    from tpu_sandbox.models.transformer import TransformerConfig, TransformerLM
    from tpu_sandbox.mpmd import MPMDPipeline

    cfg = TransformerConfig(**MODEL)
    rng = np.random.default_rng(SEED)
    tokens = rng.integers(0, cfg.vocab_size, size=tuple(BATCH)).astype(
        np.int32)
    targets = ((tokens + 7) % cfg.vocab_size).astype(np.int32)
    flat = jax.tree.map(
        np.asarray,
        TransformerLM(cfg).init(jax.random.key(SEED), tokens)["params"])
    pipe = MPMDPipeline(cfg, optax.adam(OPTIMIZER["lr"]), n_stages=N3,
                        microbatches=M, kind=KIND3, layer_split=LAYER_SPLIT)
    pipe.init_from_flat(flat)
    losses = pipe.train(STEPS, tokens, targets)
    return {
        "losses": losses,
        "stage_leaves": {
            s: [np.asarray(x) for x in
                jax.tree.leaves(pipe.workers[s].host_state()["params"])]
            for s in range(N3)
        },
    }


def _stage_argv3(stage, ckpt_root):
    argv = [PY, "-m", "tpu_sandbox.mpmd.worker",
            "{agent_id}", "{kv_port}", "{job_id}",
            "--stage", str(stage), "--ckpt-root", str(ckpt_root),
            "--get-timeout", "120"]
    if stage == 0:
        argv += ["--steps", str(STEPS), "--n-stages", str(N3),
                 "--microbatches", str(M), "--seed", str(SEED),
                 "--schedule-kind", KIND3,
                 "--layer-split", _json_arg(LAYER_SPLIT),
                 "--model", _json_arg(MODEL),
                 "--optimizer", _json_arg(OPTIMIZER),
                 "--batch", _json_arg(BATCH)]
    return argv


def _run_pipeline3(tmp_path, fault_env, fault_stage):
    with ClusterScheduler(N3, poll=0.05, extra_env=ENV,
                          verbose=False) as sched:
        for s in range(N3):
            sched.submit(JobSpec(
                job_id=f"stage{s}", hosts=1, world_size=1, cogroup="pipe0",
                agent_argv=_stage_argv3(s, tmp_path / "ckpt"),
                admission_timeout=120.0,
                env=fault_env if s == fault_stage else {}))
        states = sched.serve(timeout=300)
        assert states == {f"stage{s}": "done" for s in range(N3)}, states

        from tpu_sandbox.mpmd.transport import KVTransport

        tr = KVTransport(sched.kv, prefix="mpmd/pipe0/")
        finals = {s: tr.get("final", 0, s, timeout=10.0) for s in range(N3)}
        losses = json.loads(sched.kv.get("mpmd/pipe0/losses"))
        audit = tr.audit()
        generations = {s: int(sched.kv.get(f"mpmd/pipe0/gen/{s}"))
                       for s in range(N3)}
    return finals, losses, audit, generations


def _assert_bitwise3(reference3, finals):
    for s in range(N3):
        ref, got = reference3["stage_leaves"][s], finals[s]
        assert len(ref) == len(got)
        for i, (a, b) in enumerate(zip(ref, got)):
            assert a.dtype == b.dtype and a.shape == b.shape
            assert a.tobytes() == b.tobytes(), \
                f"stage {s} leaf {i} differs from unfaulted run"


def test_three_stage_zb_uneven_kill_midstream_recovers_bitwise(
        tmp_path, reference3):
    """The tentpole at process level: 3 stages on an uneven [2,1,1]
    split under the ZB-H1 schedule, with the MIDDLE stage SIGKILLed
    mid-schedule — half its B cotangents shipped, its deferred W reserve
    un-run. Respawn + durable slots + generation bump must land bitwise
    with zero duplicate claims."""
    plan = FaultPlan().add(rank=1, step=3, action="kill_agent")
    finals, losses, audit, gens = _run_pipeline3(
        tmp_path, {"TPU_SANDBOX_FAULT_PLAN": plan.to_json()}, fault_stage=1)
    _assert_bitwise3(reference3, finals)
    np.testing.assert_allclose(losses, reference3["losses"], rtol=0,
                               atol=1e-6)
    assert gens == {0: 1, 1: 2, 2: 1}
    dup = {k: v for k, v in audit["claims"].items() if v != 1}
    assert not dup, f"duplicate deliveries: {dup}"
    assert any(k.startswith("2/") for k in audit["claims"])


def test_three_stage_zb_uneven_partition_heals_in_place(tmp_path,
                                                        reference3):
    plan = FaultPlan().add(rank=2, step=2, action="partition_host",
                           target="1.5")
    finals, losses, audit, gens = _run_pipeline3(
        tmp_path, {"TPU_SANDBOX_FAULT_PLAN": plan.to_json()}, fault_stage=2)
    _assert_bitwise3(reference3, finals)
    np.testing.assert_allclose(losses, reference3["losses"], rtol=0,
                               atol=1e-6)
    assert gens == {0: 1, 1: 1, 2: 1}  # healed with no relaunch
    dup = {k: v for k, v in audit["claims"].items() if v != 1}
    assert not dup, f"duplicate deliveries: {dup}"


def test_two_stage_pipeline_partition_heals_without_relaunch(tmp_path,
                                                             reference):
    """partition_host silences stage 1's heartbeats and stalls it for
    1.5s mid-shipment; peers block on the transport and the schedule
    simply resumes — no respawn, no new generation, same bits."""
    plan = FaultPlan().add(rank=1, step=2, action="partition_host",
                           target="1.5")
    finals, losses, audit, gens = _run_pipeline(
        tmp_path, {"TPU_SANDBOX_FAULT_PLAN": plan.to_json()})
    _assert_bitwise(reference, finals)
    np.testing.assert_allclose(losses, reference["losses"], rtol=0,
                               atol=1e-6)
    assert gens == {0: 1, 1: 1}  # the partition healed in place
    dup = {k: v for k, v in audit["claims"].items() if v != 1}
    assert not dup, f"duplicate deliveries: {dup}"
